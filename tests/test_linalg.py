"""Cholesky linalg + masking tests — coverage the reference lacks entirely
(its logDetAndInv is tested only transitively, SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.ops.linalg import (
    NotPositiveDefiniteException,
    check_pd_status,
    chol_logdet,
    chol_solve,
    cholesky,
    is_pd,
    masked_kernel_matrix,
    posdef_inverse,
)


def _random_spd(n, rng, jitter=1e-3):
    a = rng.normal(size=(n, n))
    return a @ a.T + jitter * np.eye(n)


def test_logdet_matches_numpy(rng):
    mat = _random_spd(20, rng)
    chol_l = cholesky(jnp.asarray(mat))
    sign, logdet = np.linalg.slogdet(mat)
    assert sign > 0
    np.testing.assert_allclose(float(chol_logdet(chol_l)), logdet, rtol=1e-10)


def test_chol_solve_matches_numpy(rng):
    mat = _random_spd(20, rng)
    b = rng.normal(size=20)
    chol_l = cholesky(jnp.asarray(mat))
    np.testing.assert_allclose(
        np.asarray(chol_solve(chol_l, jnp.asarray(b))),
        np.linalg.solve(mat, b),
        rtol=1e-8,
    )


def test_posdef_inverse(rng):
    mat = _random_spd(15, rng)
    inv, ok = posdef_inverse(jnp.asarray(mat))
    assert bool(ok)
    np.testing.assert_allclose(np.asarray(inv), np.linalg.inv(mat), rtol=1e-7)


def test_non_pd_detected():
    mat = jnp.asarray(np.array([[1.0, 2.0], [2.0, 1.0]]))  # indefinite
    chol_l = cholesky(mat)
    assert not bool(is_pd(chol_l))
    with pytest.raises(NotPositiveDefiniteException):
        check_pd_status(is_pd(chol_l))


def test_masked_kernel_matrix_identity_padding(rng):
    mat = _random_spd(6, rng)
    mask = np.array([1.0, 1.0, 1.0, 1.0, 0.0, 0.0])
    masked = np.asarray(masked_kernel_matrix(jnp.asarray(mat), jnp.asarray(mask)))
    np.testing.assert_allclose(masked[:4, :4], mat[:4, :4])
    np.testing.assert_allclose(masked[4:, :4], 0.0)
    np.testing.assert_allclose(masked[:4, 4:], 0.0)
    np.testing.assert_allclose(masked[4:, 4:], np.eye(2))


def test_masked_logdet_equals_submatrix(rng):
    """Padded embedding must not change logdet or solves — SURVEY.md §7
    hard-part 5."""
    mat = _random_spd(6, rng)
    mask = np.array([1.0] * 4 + [0.0] * 2)
    masked = masked_kernel_matrix(jnp.asarray(mat), jnp.asarray(mask))
    chol_full = cholesky(masked)
    chol_sub = cholesky(jnp.asarray(mat[:4, :4]))
    np.testing.assert_allclose(
        float(chol_logdet(chol_full)), float(chol_logdet(chol_sub)), rtol=1e-10
    )
    b = rng.normal(size=6)
    bm = b * mask
    sol = np.asarray(chol_solve(chol_full, jnp.asarray(bm)))
    sol_sub = np.asarray(chol_solve(chol_sub, jnp.asarray(b[:4])))
    np.testing.assert_allclose(sol[:4], sol_sub, rtol=1e-8)
    np.testing.assert_allclose(sol[4:], 0.0, atol=1e-12)
