"""Native multiclass (softmax Laplace) classifier tests.

Oracle strategy (the repo's standard): the batched Algorithm-3.3
implementation is checked against a brute-force dense f64 implementation
of the SAME mathematics on the full ``[n*C]`` system — generic Newton with
``numpy.linalg.solve``, log Z with ``slogdet`` — plus central finite
differences for the hyperparameter gradient (which exercises the
one-differentiable-Newton-step implicit-gradient trick end to end).
"""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.special import logsumexp, softmax

from spark_gp_tpu.kernels.base import Const, EyeKernel
from spark_gp_tpu.kernels.rbf import RBFKernel
from spark_gp_tpu.models.laplace_mc import (
    _gram_stack,
    batched_neg_logz_mc,
    laplace_mc_mode,
)


def _problem(rng, n=14, n_classes=3, p=2):
    x = rng.normal(size=(n, p))
    y = rng.integers(0, n_classes, size=n)
    return x, y, np.eye(n_classes)[y]


def _oracle_mode_and_logz(kmat, y1h, iters=200):
    """Dense full-system softmax Laplace in f64: generic Newton on the
    stacked [n*C] system, log Z via slogdet — no Algorithm 3.3 structure
    shared with the implementation under test."""
    n, n_classes = y1h.shape
    kb = np.kron(np.eye(n_classes), kmat)  # class-major blocks
    big_f = np.zeros(n * n_classes)
    for _ in range(iters):
        f = big_f.reshape(n_classes, n).T
        pi = softmax(f, axis=1)
        d_mat = np.diag(pi.T.reshape(-1))
        stack = np.vstack([np.diag(pi[:, c]) for c in range(n_classes)])
        w_mat = d_mat - stack @ stack.T
        grad = (y1h - pi).T.reshape(-1)
        b = w_mat @ big_f + grad
        a = np.linalg.solve(np.eye(n * n_classes) + w_mat @ kb, b)
        f_new = kb @ a
        done = np.max(np.abs(f_new - big_f)) < 1e-12
        big_f = f_new
        if done:
            break
    f = big_f.reshape(n_classes, n).T
    pi = softmax(f, axis=1)
    d_mat = np.diag(pi.T.reshape(-1))
    stack = np.vstack([np.diag(pi[:, c]) for c in range(n_classes)])
    w_mat = d_mat - stack @ stack.T
    a = np.linalg.solve(kb, big_f)
    psi = -0.5 * a @ big_f + np.sum(
        np.sum(y1h * f, axis=1) - logsumexp(f, axis=1)
    )
    _, logdet = np.linalg.slogdet(np.eye(n * n_classes) + kb @ w_mat)
    return f, psi - 0.5 * logdet


@pytest.fixture
def mc_fixture(rng):
    x, y, y1h = _problem(rng)
    kernel = RBFKernel(0.8) + Const(1e-3) * EyeKernel()
    theta = jnp.asarray(np.array([0.8]))
    kmat = _gram_stack(
        kernel, theta, jnp.asarray(x[None]), jnp.ones((1, x.shape[0]))
    )
    return kernel, theta, x, y1h, kmat


def test_mode_matches_dense_oracle(mc_fixture):
    kernel, theta, x, y1h, kmat = mc_fixture
    n = x.shape[0]
    f_hat, _ = laplace_mc_mode(
        kmat, jnp.asarray(y1h[None]), jnp.ones((1, n)),
        jnp.zeros((1, n, y1h.shape[1])), 1e-10,
    )
    f_oracle, _ = _oracle_mode_and_logz(np.asarray(kmat[0]), y1h)
    np.testing.assert_allclose(np.asarray(f_hat[0]), f_oracle, atol=1e-10)


def test_logz_matches_dense_oracle(mc_fixture):
    kernel, theta, x, y1h, kmat = mc_fixture
    n = x.shape[0]
    value, _, _ = batched_neg_logz_mc(
        kernel, 1e-10, theta, jnp.asarray(x[None]), jnp.asarray(y1h[None]),
        jnp.ones((1, n)), jnp.zeros((1, n, y1h.shape[1])),
    )
    _, logz_oracle = _oracle_mode_and_logz(np.asarray(kmat[0]), y1h)
    np.testing.assert_allclose(-float(value), logz_oracle, rtol=1e-12)


def test_gradient_matches_finite_difference(rng):
    """The one-differentiable-Newton-step implicit gradient vs central FD
    — the end-to-end check that the stop_gradient mode + single step
    reproduces the full dlogZ/dtheta (incl. the determinant's implicit
    f-dependence, the binary path's s2/s3 analogue)."""
    x, y, y1h = _problem(rng, n=12)
    kernel = RBFKernel(0.7) + Const(1e-2) * EyeKernel()
    n = x.shape[0]

    def nll(theta_val):
        value, grad, _ = batched_neg_logz_mc(
            kernel, 1e-12, jnp.asarray(np.array([theta_val])),
            jnp.asarray(x[None]), jnp.asarray(y1h[None]), jnp.ones((1, n)),
            jnp.zeros((1, n, y1h.shape[1])),
        )
        return float(value), float(grad[0])

    _, grad = nll(0.7)
    h = 1e-6
    fd = (nll(0.7 + h)[0] - nll(0.7 - h)[0]) / (2 * h)
    np.testing.assert_allclose(grad, fd, rtol=1e-6)


def test_padding_is_inert(rng):
    """An expert stack padded with masked rows must produce the same nll,
    gradient and (real-row) modes as the unpadded stack."""
    x, y, y1h = _problem(rng, n=10)
    kernel = RBFKernel(0.8) + Const(1e-3) * EyeKernel()
    theta = jnp.asarray(np.array([0.8]))
    n, n_classes = y1h.shape

    v0, g0, f0 = batched_neg_logz_mc(
        kernel, 1e-10, theta, jnp.asarray(x[None]), jnp.asarray(y1h[None]),
        jnp.ones((1, n)), jnp.zeros((1, n, n_classes)),
    )
    pad = 3
    xp = np.concatenate([x, np.broadcast_to(x[:1], (pad, x.shape[1]))])
    y1hp = np.concatenate([y1h, np.zeros((pad, n_classes))])
    maskp = np.concatenate([np.ones(n), np.zeros(pad)])
    v1, g1, f1 = batched_neg_logz_mc(
        kernel, 1e-10, theta, jnp.asarray(xp[None]), jnp.asarray(y1hp[None]),
        jnp.asarray(maskp[None]), jnp.zeros((1, n + pad, n_classes)),
    )
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-10)
    np.testing.assert_allclose(
        np.asarray(f1[0, :n]), np.asarray(f0[0]), atol=1e-10
    )


def _blobs(rng, n_per=60, n_classes=3):
    centers = np.array([[0.0, 0.0], [3.0, 0.0], [0.0, 3.0]])[:n_classes]
    x = np.concatenate(
        [rng.normal(size=(n_per, 2)) * 0.6 + c for c in centers]
    )
    y = np.repeat(np.arange(n_classes), n_per)
    perm = rng.permutation(len(y))
    return x[perm], y[perm]


@pytest.mark.parametrize("optimizer", ["host", "device"])
def test_estimator_end_to_end_blobs(rng, optimizer):
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    x, y = _blobs(rng)
    model = (
        GaussianProcessMulticlassClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(45)
        .setActiveSetSize(40)
        .setMaxIter(20)
        .setOptimizer(optimizer)
        .fit(x, y)
    )
    pred = model.predict(x)
    acc = float(np.mean(pred == y))
    assert acc > 0.95, acc
    proba = model.predict_proba(x)
    assert proba.shape == (x.shape[0], 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    # averaged probabilities use the shared latent variance
    proba_avg = model.predict_proba(x[:10], averaged=True, mc_samples=64)
    np.testing.assert_allclose(proba_avg.sum(axis=1), 1.0, rtol=1e-6)
    assert model.num_classes == 3


def test_estimator_sharded_objective(rng, eight_device_mesh):
    """Host optimizer over the shard_map'd multiclass objective on the
    8-device mesh: same quality as single-device."""
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    x, y = _blobs(rng)
    model = (
        GaussianProcessMulticlassClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(24)
        .setActiveSetSize(40)
        .setMaxIter(15)
        .setOptimizer("host")
        .setMesh(eight_device_mesh)
        .fit(x, y)
    )
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.95, acc


def test_save_load_roundtrip(rng, tmp_path):
    from spark_gp_tpu import (
        GaussianProcessMulticlassClassifier,
        GaussianProcessMulticlassModel,
    )

    x, y = _blobs(rng, n_per=40)
    model = (
        GaussianProcessMulticlassClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(30)
        .setMaxIter(10)
        .fit(x, y)
    )
    path = str(tmp_path / "mc_model")
    model.save(path)
    loaded = GaussianProcessMulticlassModel.load(path)
    np.testing.assert_allclose(
        loaded.predict_raw(x[:20]), model.predict_raw(x[:20]), rtol=1e-12
    )


def test_label_validation():
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    x = np.zeros((10, 2))
    with pytest.raises(ValueError, match="integers"):
        GaussianProcessMulticlassClassifier().fit(x, np.full(10, 0.5))
    with pytest.raises(ValueError, match="integers"):
        GaussianProcessMulticlassClassifier().fit(x, np.full(10, -1))
    with pytest.raises(ValueError, match="at least 2"):
        GaussianProcessMulticlassClassifier().fit(x, np.zeros(10))


def test_iris_beats_bar(rng):
    """Iris through the NATIVE multiclass path (the reference needs
    OneVsRest + 3 fits for this, Iris.scala:26-27): 5-fold CV accuracy
    above 0.9 with one model per fold."""
    from spark_gp_tpu import GaussianProcessMulticlassClassifier
    from spark_gp_tpu.data import load_iris
    from spark_gp_tpu.utils.validation import accuracy, kfold_indices

    x, y = load_iris()
    scores = []
    for train_idx, test_idx in kfold_indices(x.shape[0], 5, seed=13):
        model = (
            GaussianProcessMulticlassClassifier()
            .setDatasetSizeForExpert(20)
            .setActiveSetSize(30)
            .setMaxIter(20)
            .fit(x[train_idx], y[train_idx])
        )
        scores.append(accuracy(y[test_idx], model.predict(x[test_idx])))
    assert float(np.mean(scores)) > 0.9, scores


def test_greedy_provider_multiclass(rng):
    """The uses_fit_outputs provider branch: greedy Seeger selection over
    the max-class latent margin (heuristic scalarization, documented in
    _projected_process_multi)."""
    from spark_gp_tpu import (
        GaussianProcessMulticlassClassifier,
        GreedilyOptimizingActiveSetProvider,
    )

    x, y = _blobs(rng, n_per=40)
    model = (
        GaussianProcessMulticlassClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(24)
        .setMaxIter(10)
        .setActiveSetProvider(GreedilyOptimizingActiveSetProvider())
        .fit(x, y)
    )
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.9, acc


def test_device_sharded_fit(rng, eight_device_mesh):
    """fit_gpc_mc_device_sharded: the whole multiclass optimizer inside one
    shard_map over the 8-device mesh."""
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    x, y = _blobs(rng)
    model = (
        GaussianProcessMulticlassClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(24)
        .setActiveSetSize(40)
        .setMaxIter(15)
        .setOptimizer("device")
        .setMesh(eight_device_mesh)
        .fit(x, y)
    )
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.95, acc


def test_device_checkpointed_fit_and_resume(rng, tmp_path):
    """Segmented device fit persists L-BFGS state; an identical refit
    resumes from the finished checkpoint without re-optimizing."""
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    x, y = _blobs(rng, n_per=40)

    def make():
        return (
            GaussianProcessMulticlassClassifier()
            .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
            .setDatasetSizeForExpert(40)
            .setActiveSetSize(30)
            .setMaxIter(12)
            .setOptimizer("device")
            .setCheckpointDir(str(tmp_path))
            .setCheckpointInterval(4)
        )

    m1 = make().fit(x, y)
    files = list(tmp_path.iterdir())
    assert files, "no checkpoint was written"
    m2 = make().fit(x, y)  # resumes the finished state
    np.testing.assert_allclose(
        m2.predict_raw(x[:20]), m1.predict_raw(x[:20]), rtol=1e-5, atol=1e-8
    )


def test_fit_distributed_multiclass(rng, eight_device_mesh):
    """Pre-sharded global stack entry: quality parity with plain fit, the
    n_classes device inference, and the label-domain check on the stack."""
    from spark_gp_tpu import GaussianProcessMulticlassClassifier
    from spark_gp_tpu.parallel import distributed as dist

    x, y = _blobs(rng)
    gdata = dist.distribute_global_experts(
        x, y.astype(np.float64), 24, eight_device_mesh
    )

    def make():
        return (
            GaussianProcessMulticlassClassifier()
            .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
            .setDatasetSizeForExpert(24)
            .setActiveSetSize(40)
            .setMaxIter(15)
            .setMesh(eight_device_mesh)
        )

    model = make().fit_distributed(gdata)
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.95, acc
    assert model.num_classes == 3

    bad = dist.distribute_global_experts(
        x, y.astype(np.float64) + 0.5, 24, eight_device_mesh
    )
    with pytest.raises(ValueError, match="integers"):
        make().fit_distributed(bad)


def test_mean_only_multiclass_rejects_averaged_proba(rng):
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    x, y = _blobs(rng, n_per=30)
    model = (
        GaussianProcessMulticlassClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(45)
        .setActiveSetSize(20)
        .setMaxIter(5)
        .setPredictiveVariance(False)
        .fit(x, y)
    )
    # MAP probabilities still work on a mean-only model
    proba = model.predict_proba(x[:10])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    with pytest.raises(ValueError, match="setPredictiveVariance"):
        model.predict_proba(x[:10], averaged=True)
