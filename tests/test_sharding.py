"""Multi-device sharding parity — the test the reference approximates with
``master("local[10]")`` (SURVEY.md §4): sharded computations on the forced
8-device CPU mesh must agree with the single-device path up to reduction
order.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels import Const, EyeKernel, RBFKernel
from spark_gp_tpu.models import ppa
from spark_gp_tpu.models.laplace import (
    make_laplace_objective,
    make_sharded_laplace_objective,
)
from spark_gp_tpu.models.likelihood import (
    make_sharded_value_and_grad,
    make_value_and_grad,
)
from spark_gp_tpu.parallel.experts import group_for_experts
from spark_gp_tpu.parallel.mesh import shard_experts


@pytest.fixture
def problem(rng):
    n, p = 220, 3
    x = rng.normal(size=(n, p))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    kernel = RBFKernel(1.0) + Const(1e-2) * EyeKernel()
    return x, y, kernel


def test_sharded_nll_matches_single_device(problem, eight_device_mesh):
    x, y, kernel = problem
    data = group_for_experts(x, y, dataset_size_for_expert=20)  # E = 11
    theta = jnp.asarray(kernel.init_theta())

    v1, g1 = make_value_and_grad(kernel, data)(theta)

    sharded_data = shard_experts(data, eight_device_mesh)
    assert sharded_data.num_experts % 8 == 0
    v2, g2 = make_sharded_value_and_grad(kernel, sharded_data, eight_device_mesh)(theta)

    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-9)


def test_sharded_kmn_stats_match(problem, eight_device_mesh, rng):
    x, y, kernel = problem
    data = group_for_experts(x, y, dataset_size_for_expert=20)
    theta = jnp.asarray(kernel.init_theta())
    active = jnp.asarray(x[rng.choice(x.shape[0], 16, replace=False)])

    u1a, u2a = ppa.kmn_stats(kernel, theta, active, data)

    sharded_data = shard_experts(data, eight_device_mesh)
    stats_fn = ppa.make_sharded_kmn_stats(kernel, eight_device_mesh)
    u1b, u2b = stats_fn(theta, active, sharded_data)

    np.testing.assert_allclose(np.asarray(u1a), np.asarray(u1b), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(u2a), np.asarray(u2b), rtol=1e-9)


def test_sharded_laplace_matches_single_device(eight_device_mesh, rng):
    n, p = 120, 2
    x = rng.normal(size=(n, p))
    y = (x.sum(axis=1) > 0).astype(np.float64)
    kernel = RBFKernel(1.0) + Const(1e-3) * EyeKernel()
    data = group_for_experts(x, y, dataset_size_for_expert=20)
    theta = jnp.asarray(kernel.init_theta())
    f0 = jnp.zeros_like(data.y)

    v1, g1, f1 = make_laplace_objective(kernel, data, 1e-6)(theta, f0)

    sharded = shard_experts(data, eight_device_mesh)
    f0s = jnp.zeros_like(sharded.y)
    v2, g2, f2 = make_sharded_laplace_objective(kernel, sharded, 1e-6, eight_device_mesh)(
        theta, f0s
    )

    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-8)
    np.testing.assert_allclose(
        np.asarray(f1), np.asarray(f2)[: data.num_experts], rtol=1e-9
    )


def test_estimator_with_mesh_end_to_end(eight_device_mesh):
    """Full fit with setMesh: same model quality as single-device."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel as RBF
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import rmse

    x, y = make_synthetics(n=500)

    def make():
        return (
            GaussianProcessRegression()
            .setKernel(lambda: 1.0 * RBF(0.1, 1e-6, 10))
            .setDatasetSizeForExpert(50)
            .setActiveSetSize(50)
            .setSigma2(1e-3)
            .setSeed(13)
        )

    m_single = make().fit(x, y)
    m_sharded = make().setMesh(eight_device_mesh).fit(x, y)
    r1 = rmse(y, m_single.predict(x))
    r2 = rmse(y, m_sharded.predict(x))
    assert r2 < 0.11
    np.testing.assert_allclose(r1, r2, atol=5e-3)
