"""Unit tests for the TPU window watcher's capture plumbing
(benchmarks/tpu_window_watcher.py).

The watcher is the round's only collector of hardware evidence when the
device tunnel revives outside an interactive session, so its envelope
logic — platform extraction and the never-clobber-good-evidence guard —
must not rot untested.  The probe/capture loop itself needs a live
tunnel and is exercised operationally.
"""

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from benchmarks import tpu_window_watcher as watcher  # noqa: E402


def _bench_line(platform, value=123.0):
    return json.dumps(
        {"metric": "m", "value": value, "unit": "u",
         "detail": {"platform": platform}}
    )


def test_captured_platform_reads_last_json_line():
    env = {"stdout_tail": "noise\n" + _bench_line("tpu") + "\n"}
    assert watcher._captured_platform(env) == "tpu"
    env = {"stdout_tail": _bench_line("tpu") + "\n" + _bench_line("cpu")}
    assert watcher._captured_platform(env) == "cpu"  # LAST line wins
    assert watcher._captured_platform({"stdout_tail": "no json"}) is None
    assert watcher._captured_platform({}) is None
    # bare payloads without detail fall back to a top-level platform key
    assert (
        watcher._captured_platform({"stdout_tail": '{"platform": "tpu"}'})
        == "tpu"
    )


def test_run_never_clobbers_good_evidence(tmp_path, monkeypatch):
    """A failed or chip-less re-capture must park itself in a .failed file
    next to prior good evidence, not overwrite it."""
    monkeypatch.setattr(watcher, "ROOT", str(tmp_path))
    monkeypatch.setattr(watcher, "ART_DIR", str(tmp_path))

    # first capture: clean exit, on-chip payload
    watcher._run(
        [sys.executable, "-c", f"print('{_bench_line('tpu')}')"],
        "ART.json", 30,
    )
    prior = json.load(open(tmp_path / "ART.json"))
    assert prior["returncode"] == 0
    assert watcher._captured_platform(prior) == "tpu"

    # failing re-capture: must park, prior artifact untouched
    watcher._run([sys.executable, "-c", "raise SystemExit(3)"], "ART.json", 30)
    assert json.load(open(tmp_path / "ART.json")) == prior
    parked = json.load(open(tmp_path / "ART.json.failed"))
    assert parked["returncode"] == 3

    # clean exit but the chip was lost (CPU fallback): also parked
    watcher._run(
        [sys.executable, "-c", f"print('{_bench_line('cpu')}')"],
        "ART.json", 30,
    )
    assert json.load(open(tmp_path / "ART.json")) == prior
    assert watcher._captured_platform(
        json.load(open(tmp_path / "ART.json.failed"))
    ) == "cpu"

    # a BETTER capture (clean, on-chip) does replace the artifact
    watcher._run(
        [sys.executable, "-c", f"print('{_bench_line('tpu', 999.0)}')"],
        "ART.json", 30,
    )
    updated = json.load(open(tmp_path / "ART.json"))
    assert updated != prior
    assert watcher._captured_platform(updated) == "tpu"


def test_run_timeout_records_both_streams(tmp_path, monkeypatch):
    # fence must exceed interpreter startup (~4s on this image: the site
    # hook imports jax into every python process) so the child actually
    # prints before the kill
    monkeypatch.setattr(watcher, "ROOT", str(tmp_path))
    monkeypatch.setattr(watcher, "ART_DIR", str(tmp_path))
    watcher._run(
        [sys.executable, "-c",
         "import sys, time; print('partial'); sys.stdout.flush(); "
         "print('diag', file=sys.stderr); sys.stderr.flush(); time.sleep(120)"],
        "SLOW.json", 10,
    )
    envelope = json.load(open(tmp_path / "SLOW.json"))
    assert envelope["timed_out_after_s"] == 10
    assert "partial" in envelope["stdout_tail"]
    assert "diag" in envelope["stderr_tail"]


def test_capture_window_bails_when_tunnel_dies(monkeypatch):
    """A tunnel that dies mid-window must abandon the remaining lanes
    (instead of serially burning each one's full timeout against a dead
    device) — and a healthy tunnel must run all five lanes in priority
    order, bench first."""
    ran, notes = [], []
    monkeypatch.setattr(
        watcher, "_run", lambda cmd, out, t, env=None: ran.append(out)
    )

    # healthy: every lane runs, bench first; completed -> True (main then
    # takes the long post-capture sleep)
    monkeypatch.setattr(watcher, "_probe_tpu", lambda *a, **k: True)
    assert watcher.capture_window(notes.append) is True
    assert ran[0] == "TPU_WINDOW_BENCH.json"
    assert len(ran) == 5

    # tunnel dies after the first lane: bail with a log line
    ran.clear()
    notes.clear()
    monkeypatch.setattr(watcher, "_probe_tpu", lambda *a, **k: False)
    # bailed -> False (main then drops to the 3-min down-tunnel cadence)
    assert watcher.capture_window(notes.append) is False
    assert ran == ["TPU_WINDOW_BENCH.json"]
    assert any("abandoning" in n for n in notes)


def test_rehearsal_artifact_every_lane_valid():
    """Watcher dress rehearsal (VERDICT next #1): the committed
    WATCHER_REHEARSAL.json was produced by an env-forced tiny-config CPU
    run of the FULL five-lane window sequence through capture_window
    itself (``python benchmarks/tpu_window_watcher.py --rehearse``).
    Every lane must have emitted a valid envelope with a clean exit —
    the capture plumbing is proven BEFORE the next real tunnel window.
    The salvage path (.failed parking) and the bail path are exercised
    live by test_run_never_clobbers_good_evidence and
    test_capture_window_bails_when_tunnel_dies above."""
    path = os.path.join(ROOT, "WATCHER_REHEARSAL.json")
    assert os.path.exists(path), (
        "no committed rehearsal artifact — run "
        "python benchmarks/tpu_window_watcher.py --rehearse and commit "
        "WATCHER_REHEARSAL.json"
    )
    with open(path) as fh:
        summary = json.load(fh)
    assert summary["format"] == "spark_gp_tpu.watcher_rehearsal/v1"
    assert summary["completed_window"] is True
    assert set(summary["lanes"]) == {
        "BENCH", "TESTS", "MATCHED", "LARGE_M", "PALLAS"
    }
    for name, lane in summary["lanes"].items():
        assert lane["present"], name
        assert lane["valid_envelope"], (name, lane)
        assert lane["returncode"] == 0, (name, lane)
        assert lane["timed_out"] is False, (name, lane)
    # the bench lane actually measured (CPU platform recorded)
    assert summary["lanes"]["BENCH"]["platform"] == "cpu"
    # the pallas lane carried the fused gram·vector streaming rows
    # (ISSUE 20): sweep_matvec ran inside the same subprocess
    assert summary["lanes"]["PALLAS"]["matvec_rows"] is True
    assert summary["env"]["PALLAS_SWEEP_MATVEC_SIZES"] == "32,64"
    # the rehearsal env is the CPU tiny-config contract
    assert summary["env"]["JAX_PLATFORMS"] == "cpu"
    assert summary["env"]["GP_WATCHER_REHEARSAL"] == "1"
    assert any("window capture finished" in n for n in summary["notes"])


def test_rehearse_writes_artifacts_outside_real_evidence(tmp_path, monkeypatch):
    """rehearse() must point every lane artifact at its own directory —
    a rehearsal may never clobber real TPU_WINDOW_* evidence — and must
    restore ART_DIR and the staged env afterwards."""
    ran = []
    monkeypatch.setattr(
        watcher, "_run", lambda cmd, out, t, env=None: ran.append(
            (out, watcher.ART_DIR, env.get("JAX_PLATFORMS"),
             env.get("GP_TEST_PLATFORM"))
        )
    )
    art_before = watcher.ART_DIR
    env_before = os.environ.get("GP_WATCHER_REHEARSAL")
    summary = watcher.rehearse(str(tmp_path), note=lambda m: None)
    assert watcher.ART_DIR == art_before
    assert os.environ.get("GP_WATCHER_REHEARSAL") == env_before
    assert len(ran) == 5
    # every lane targeted the rehearsal dir and the CPU backend
    for out, art_dir, jax_platforms, test_platform in ran:
        assert art_dir == str(tmp_path)
        assert jax_platforms == "cpu"
        assert test_platform in (None, "cpu")
    # lanes were stubbed, so no envelopes landed — the summary says so
    assert all(not lane["present"] for lane in summary["lanes"].values())
    assert os.path.exists(tmp_path / "WATCHER_REHEARSAL.json")


def test_bench_fence_sized_from_constituent_knobs(monkeypatch):
    """The bench lane's fence must follow the timeout knobs bench.py
    honors (attempts x preflight + backoff + 2 x worker + roofline +
    margin) instead of a hardcoded zero-slack constant: raising
    BENCH_WORKER_TIMEOUT must raise the fence past the new worker
    budget, never let the watcher kill a healthy bench."""
    for var in (
        "BENCH_PREFLIGHT_TIMEOUT", "BENCH_PREFLIGHT_ATTEMPTS",
        "BENCH_WORKER_TIMEOUT", "BENCH_ROOFLINE_TIMEOUT",
    ):
        monkeypatch.delenv(var, raising=False)
    default = watcher._bench_fence_s()
    # defaults: (4 default + 1 cpu-fallback attempt)*150 + 90 backoff
    # + 2*2400 workers + 1500 roofline + 300 margin
    assert default == 5 * 150 + 90 + 2 * 2400 + 1500 + 300
    # the fence covers both worker plans plus the roofline, with slack
    assert default > 2 * 2400 + 1500
    monkeypatch.setenv("BENCH_WORKER_TIMEOUT", "4000")
    assert watcher._bench_fence_s() >= default + 2 * (4000 - 2400)
    monkeypatch.setenv("BENCH_PREFLIGHT_ATTEMPTS", "1")
    # 1 default attempt + 1 fallback attempt, no backoff sleeps
    assert watcher._bench_fence_s() == 2 * 150 + 2 * 4000 + 1500 + 300
