"""The forensics plane (ISSUE 10): flight recorder, incident bundles,
cross-process trace stitching, XLA cost attribution, gpctl.

Acceptance proofs, all tier-1:

* chaos-injected terminal failures — an OOM-exhausted fit, a dead-host
  coordination timeout, a hung serve batch — each produce EXACTLY ONE
  schema-valid incident bundle carrying the failing span tree, the
  last-N recorder events, and the degradation-rung history;
* a 2-(logical-)process ``fit_distributed`` yields run journals sharing
  ONE stitched trace id (minted on process 0, adopted over the KV
  plane);
* ``gpctl diff`` of two run journals runs clean; list/show/merge work;
* measured ``gp_xla_flops_total`` is non-null for all four estimator
  families' fits and for PPA predict (``GP_XLA_COST=1``).
"""

import glob
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessClassifier,
    GaussianProcessMulticlassClassifier,
    GaussianProcessPoissonRegression,
    GaussianProcessRegression,
    RBFKernel,
)
from spark_gp_tpu.obs import cost as obs_cost
from spark_gp_tpu.obs import recorder as obs_recorder
from spark_gp_tpu.obs import runtime as obs_runtime
from spark_gp_tpu.obs import trace as obs_trace
from spark_gp_tpu.resilience import chaos, fallback

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the run-journal golden schema: every journal must carry these keys
JOURNAL_REQUIRED_KEYS = (
    "format", "name", "created_unix", "trace_id", "pid", "build_info",
    "precision_lane", "mesh", "timings", "metrics", "degradations",
    "quarantine", "compiles", "compiles_by_entry", "memory", "span_count",
    "spans", "xla_cost", "path",
)


def _tiny_xy(seed=0, n=120):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    return x, np.sin(x.sum(axis=1))


def _tiny_gp(optimizer="host", max_iter=3):
    return (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(30)
        .setSigma2(1e-3)
        .setMaxIter(max_iter)
        .setSeed(3)
        .setOptimizer(optimizer)
    )


def _bundles(directory):
    return sorted(glob.glob(os.path.join(directory, "incident_*.json")))


def _tree_nodes(nodes):
    for node in nodes:
        yield node
        yield from _tree_nodes(node.get("children") or [])


# -- flight recorder basics --------------------------------------------------


def test_recorder_ring_bounds_and_gating():
    ring = obs_recorder.FlightRecorder(capacity=4)
    for i in range(7):
        ring.record("fit.retry", attempt=i)
    events = ring.snapshot()
    assert len(events) == 4 and ring.dropped == 3
    # oldest evicted, newest retained, seq monotonic
    attempts = [e["attempt"] for e in events]
    assert attempts == [3, 4, 5, 6]
    assert [e["seq"] for e in events] == sorted(e["seq"] for e in events)
    assert ring.snapshot(last=2)[0]["attempt"] == 5
    # gating: set_recording(False) makes record a no-op
    obs_recorder.set_recording(False)
    try:
        ring.record("fit.retry", attempt=99)
        assert len(ring.snapshot()) == 4
    finally:
        obs_recorder.set_recording(None)


def test_recorder_fed_by_span_events_and_metric_watchlist():
    obs_recorder.RECORDER.clear()
    # span events relay even WITHOUT an open span
    assert not obs_trace.add_event("breaker.open", model="m1")
    # erroring spans leave an event
    with pytest.raises(ValueError):
        with obs_trace.span("doomed.unit"):
            raise ValueError("boom")
    # serve metric watchlist: shed keys relay, request counters do not
    from spark_gp_tpu.serve.metrics import ServingMetrics

    m = ServingMetrics(name="rectest")
    m.inc("requests", 5)          # not watchlisted
    m.inc("shed.breaker")         # watchlisted
    names = [e["name"] for e in obs_recorder.RECORDER.snapshot()]
    assert "breaker.open" in names
    assert "error" in names
    assert "metric.shed.breaker" in names
    assert "metric.requests" not in names


# -- run journal golden schema ----------------------------------------------


def test_run_journal_golden_schema(tmp_path, monkeypatch):
    monkeypatch.setenv("GP_RUN_JOURNAL_DIR", str(tmp_path))
    x, y = _tiny_xy()
    model = _tiny_gp().fit(x, y)
    journal = model.run_journal
    for key in JOURNAL_REQUIRED_KEYS:
        assert key in journal, f"journal missing {key!r}"
    # trace-id consistency: a non-null stitched id, identical on disk
    assert isinstance(journal["trace_id"], str) and journal["trace_id"]
    with open(journal["path"]) as fh:
        on_disk = json.load(fh)
    assert on_disk["trace_id"] == journal["trace_id"]
    assert on_disk["build_info"]["backend"] == "cpu"
    assert on_disk["pid"] == os.getpid()
    # clean fit: no degradations, no incident bundle anywhere in the dir
    assert journal["degradations"] == []
    assert _bundles(str(tmp_path)) == []


# -- incident bundles: the three chaos acceptance proofs --------------------


def test_oom_exhausted_fit_dumps_exactly_one_schema_valid_bundle(
    tmp_path, monkeypatch
):
    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))
    x, y = _tiny_xy()
    gp = _tiny_gp(optimizer="device")
    with chaos.oom_after_calls(0):  # every rung's dispatch OOMs
        with pytest.raises(fallback.DegradationExhaustedError) as exc:
            gp.fit(x, y)
    assert exc.value.failure_class == fallback.OOM
    bundles = _bundles(str(tmp_path))
    assert len(bundles) == 1, bundles
    with open(bundles[0]) as fh:
        bundle = json.load(fh)
    assert obs_recorder.validate_bundle(bundle) == []
    assert bundle["failure_class"] == "oom"
    assert bundle["reason"] == "fit.GaussianProcessRegression"
    # the failing span tree: rooted at the fit's own root span
    names = {n["name"] for n in _tree_nodes(bundle["spans"])}
    assert "fit.GaussianProcessRegression" in names
    # the rung history as the ladder ran (ISSUE 14: the oom class tries
    # the iterative solver rung first)
    rungs = [(d["from"], d["to"]) for d in bundle["degradations"]]
    assert rungs == [
        ("native", "iterative"),
        ("iterative", "matfree"),
        ("matfree", "segmented"),
        ("segmented", "host_f64"),
    ]
    # the last-N recorder events include the classified-failure sequence
    event_names = [e["name"] for e in bundle["events"]]
    assert "fallback.failure" in event_names
    # exactly ONE incident.bundle event per incident (the add_event relay
    # is the single emission — no recorder double-log)
    assert event_names.count("incident.bundle") <= 1
    # chaos repro recipe rides along
    assert isinstance(bundle["chaos"], dict)
    assert bundle["trace_id"].startswith("t-")


def test_bundle_survives_span_ring_eviction(tmp_path, monkeypatch):
    """A bundle written AFTER the span ring evicted the fit's spans must
    still contain the failure's own span path: the tree is sourced from
    the root span's trace_spans collection, not the ring."""
    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))
    monkeypatch.setattr(obs_trace, "RING", obs_trace.SpanRing(2))
    x, y = _tiny_xy()
    # pad the ring with unrelated spans DURING the failing fit via a
    # competing thread? unnecessary: capacity 2 already evicts the fit's
    # phase spans as later spans close — the root is never ring-resident
    with chaos.oom_after_calls(0):
        with pytest.raises(fallback.DegradationExhaustedError):
            _tiny_gp(optimizer="device").fit(x, y)
    # prove the eviction premise: the ring holds almost nothing
    assert len(obs_trace.RING.snapshot()) <= 2
    with open(_bundles(str(tmp_path))[0]) as fh:
        bundle = json.load(fh)
    names = {n["name"] for n in _tree_nodes(bundle["spans"])}
    assert "fit.GaussianProcessRegression" in names
    assert "group_experts" in names, names


def test_dead_host_coord_timeout_dumps_one_bundle(tmp_path, monkeypatch):
    """Two logical hosts over the in-process KV store; host 1 dies mid-fit.
    Host 0's CoordinationTimeoutError is a terminal classified failure ->
    exactly one bundle (class coord_timeout); host 1's simulated death is
    UNKNOWN -> no bundle."""
    from spark_gp_tpu.parallel import coord
    from spark_gp_tpu.parallel.coord import (
        CoordinationTimeoutError,
        DcnContext,
        InProcessCoordClient,
        InProcessCoordStore,
    )
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import expert_mesh, shard_experts
    from spark_gp_tpu.resilience.chaos import SimulatedPreemption

    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))

    class DyingCtx(DcnContext):
        def __init__(self, client, timeout_s, die_after):
            super().__init__(client, timeout_s=timeout_s)
            self.die_after = die_after
            self._vag_rounds = 0

        def allgather_bytes(self, name, payload):
            if name == "vag":
                self._vag_rounds += 1
                if self._vag_rounds > self.die_after:
                    raise SimulatedPreemption("chaos: host died mid-fit")
            return super().allgather_bytes(name, payload)

    store = InProcessCoordStore()
    ctxs = [
        DcnContext(
            InProcessCoordClient(store, 0, 2, clock=time.monotonic),
            timeout_s=3.0,
        ),
        DyingCtx(
            InProcessCoordClient(store, 1, 2, clock=time.monotonic),
            timeout_s=3.0, die_after=3,
        ),
    ]
    results = {}

    def host(pid):
        coord.set_dcn_context_for_testing(ctxs[pid])
        try:
            rng = np.random.default_rng(100 + pid)
            n = 144 if pid == 0 else 112
            x = rng.normal(size=(n, 2))
            y = np.sin(x.sum(axis=1))
            # disjoint device halves per logical host: concurrent
            # collective programs over a SHARED mesh can deadlock XLA's
            # rendezvous on small hosts (see tests/test_coord._host_mesh)
            import jax

            devs = jax.devices()
            half = max(1, len(devs) // 2)
            mesh = expert_mesh(devs[pid * half:(pid + 1) * half])
            data = shard_experts(group_for_experts(x, y, 16), mesh)
            results[pid] = (
                _tiny_gp(max_iter=30).setMesh(mesh).fit_distributed(data)
            )
        except BaseException as exc:  # noqa: BLE001 — collected for asserts
            results[pid] = exc
        finally:
            coord.set_dcn_context_for_testing(None)

    threads = [threading.Thread(target=host, args=(pid,)) for pid in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert isinstance(results[0], CoordinationTimeoutError), results[0]
    assert isinstance(results[1], SimulatedPreemption), results[1]
    bundles = _bundles(str(tmp_path))
    assert len(bundles) == 1, bundles
    with open(bundles[0]) as fh:
        bundle = json.load(fh)
    assert obs_recorder.validate_bundle(bundle) == []
    assert bundle["failure_class"] == "coord_timeout"
    assert "1" in bundle["error"]  # the missing pid is NAMED


def test_hung_serve_batch_dumps_one_bundle(tmp_path, monkeypatch):
    from spark_gp_tpu.resilience.chaos import hang_model
    from spark_gp_tpu.serve import GPServeServer
    from spark_gp_tpu.serve.lifecycle import ExecHungError

    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))
    x, y = _tiny_xy(seed=1)
    model = _tiny_gp().fit(x, y)
    path = str(tmp_path / "hang_model.npz")
    model.save(path)
    server = GPServeServer(
        max_batch=16, min_bucket=8, max_wait_ms=1.0,
        hang_timeout_s=0.25, breaker_reset_s=30.0, request_timeout_ms=None,
    )
    server.register("hang", path)
    server.start()
    hanging = hang_model(server, "hang", hang_forever=True, max_block_s=30.0)
    try:
        fut = server.submit("hang", x[:4], request_id="req-incident-7")
        with pytest.raises(ExecHungError):
            fut.result(timeout=5.0)
    finally:
        hanging.release()
        server.stop()
    bundles = _bundles(str(tmp_path))
    assert len(bundles) == 1, bundles
    with open(bundles[0]) as fh:
        bundle = json.load(fh)
    assert obs_recorder.validate_bundle(bundle) == []
    assert bundle["reason"] == "exec.hung"
    assert bundle["failure_class"] == "exec.hung"
    assert bundle["model"] == "hang"
    # the client's correlation id made it into the forensics artifact
    assert bundle["request_ids"] == ["req-incident-7"]
    # the wedged dispatch's own (still-open) span is rendered verbatim
    assert bundle["hung_span"]["name"] == "serve.predict"
    assert bundle["hung_span"]["attrs"]["request_ids"] == ["req-incident-7"]
    # the recorder's event log carries the watchdog/breaker sequence
    names = [e["name"] for e in bundle["events"]]
    assert "metric.exec.hung" in names or "metric.lifecycle.watchdog_trips" in names


def test_bundle_still_dumped_with_tracing_off(tmp_path, monkeypatch):
    """GP_TRACING=0 is the SPAN layer's kill switch, not the forensics
    plane's (that is GP_RECORDER=0): a terminal classified failure must
    still bundle — just without a span tree."""
    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))
    obs_trace.set_tracing(False)
    try:
        x, y = _tiny_xy()
        with chaos.oom_after_calls(0):
            with pytest.raises(fallback.DegradationExhaustedError):
                _tiny_gp(optimizer="device").fit(x, y)
    finally:
        obs_trace.set_tracing(None)
    bundles = _bundles(str(tmp_path))
    assert len(bundles) == 1, bundles
    with open(bundles[0]) as fh:
        bundle = json.load(fh)
    assert obs_recorder.validate_bundle(bundle) == []
    assert bundle["failure_class"] == "oom"
    assert bundle["spans"] == []  # no tracer, no tree — by design
    assert [d["to"] for d in bundle["degradations"]] == [
        "iterative", "matfree", "segmented", "host_f64",
    ]


def test_mixed_program_fit_keeps_per_program_cost_rows():
    """A fit that executes DISTINCT compiled programs under one trace
    root (a degraded re-execution) must journal one cost row per
    program, not multiply one program's flops by the other's calls."""
    cap = obs_runtime.FitCapture("mixtest")
    cap.note_xla_cost("fit.X", {"flops": 100.0, "bytes": 10.0})
    cap.note_xla_cost("fit.X", {"flops": 100.0, "bytes": 10.0})
    cap.note_xla_cost("fit.X", {"flops": 7.0, "bytes": 3.0})  # other program
    assert cap.xla_costs["fit.X"]["executions"] == 2.0
    assert cap.xla_costs["fit.X#2"]["flops_per_execution"] == 7.0
    assert cap.xla_costs["fit.X#2"]["executions"] == 1.0


def test_clean_fit_and_degraded_fit_write_no_bundle(tmp_path, monkeypatch):
    """Successfully-degraded work journals its rung history but does NOT
    bundle: bundles are terminal-failure artifacts only."""
    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))
    x, y = _tiny_xy()
    with chaos.oom_after_calls(0, op="one_dispatch") as fired:
        model = _tiny_gp(optimizer="device").fit(x, y)
    assert fired[0], "fault never fired"
    assert model.degradations, "ladder never engaged"
    assert _bundles(str(tmp_path)) == []


# -- cross-process trace stitching ------------------------------------------


def test_two_process_fit_shares_one_stitched_trace_id(tmp_path, monkeypatch):
    from spark_gp_tpu.parallel import coord
    from spark_gp_tpu.parallel.coord import (
        DcnContext,
        InProcessCoordClient,
        InProcessCoordStore,
    )
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import expert_mesh, shard_experts

    monkeypatch.setenv("GP_RUN_JOURNAL_DIR", str(tmp_path))
    store = InProcessCoordStore()
    ctxs = [
        DcnContext(
            InProcessCoordClient(store, pid, 2, clock=time.monotonic),
            timeout_s=30.0,
        )
        for pid in range(2)
    ]
    results = {}

    def host(pid):
        coord.set_dcn_context_for_testing(ctxs[pid])
        try:
            rng = np.random.default_rng(100 + pid)
            n = 144 if pid == 0 else 112
            x = rng.normal(size=(n, 2))
            y = np.sin(x.sum(axis=1))
            # disjoint device halves per logical host: concurrent
            # collective programs over a SHARED mesh can deadlock XLA's
            # rendezvous on small hosts (see tests/test_coord._host_mesh)
            import jax

            devs = jax.devices()
            half = max(1, len(devs) // 2)
            mesh = expert_mesh(devs[pid * half:(pid + 1) * half])
            data = shard_experts(group_for_experts(x, y, 16), mesh)
            results[pid] = (
                _tiny_gp(max_iter=8).setMesh(mesh).fit_distributed(data)
            )
        except BaseException as exc:  # noqa: BLE001 — collected for asserts
            results[pid] = exc
        finally:
            coord.set_dcn_context_for_testing(None)

    threads = [threading.Thread(target=host, args=(pid,)) for pid in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for pid in range(2):
        assert not isinstance(results[pid], BaseException), results[pid]
    traces = {
        pid: results[pid].run_journal["trace_id"] for pid in range(2)
    }
    assert traces[0] == traces[1], traces
    assert traces[0].startswith("t-")
    # and the persisted journals agree with the in-memory ones
    on_disk = sorted(glob.glob(os.path.join(str(tmp_path), "run_journal_*")))
    assert len(on_disk) == 2
    disk_traces = {json.load(open(p))["trace_id"] for p in on_disk}
    assert disk_traces == {traces[0]}


def test_serve_stream_echoes_request_id(tmp_path):
    import io

    from spark_gp_tpu.serve.__main__ import _serve_stream
    from spark_gp_tpu.serve.server import GPServeServer

    x, y = _tiny_xy(seed=2)
    model = _tiny_gp().fit(x, y)
    path = str(tmp_path / "echo.npz")
    model.save(path)
    server = GPServeServer(max_batch=8, min_bucket=4, request_timeout_ms=None)
    server.register("tiny", path)
    server.start()
    try:
        out = io.StringIO()
        lines = [
            json.dumps({"id": 1, "model": "tiny", "x": x[:2].tolist(),
                        "request_id": "client-trace-42"}),
            json.dumps({"id": 2, "model": "nope", "x": x[:2].tolist(),
                        "request_id": "client-trace-43"}),
            json.dumps({"cmd": "shutdown"}),
        ]
        assert _serve_stream(server, lines, out, threading.Lock())
    finally:
        server.stop()
    replies = [json.loads(line) for line in out.getvalue().splitlines()]
    assert replies[0]["id"] == 1
    assert replies[0]["request_id"] == "client-trace-42"  # echoed on success
    assert replies[1]["request_id"] == "client-trace-43"  # echoed on error
    assert "error" in replies[1]


# -- XLA cost attribution ----------------------------------------------------


def test_measured_flops_non_null_for_all_families_and_ppa_predict():
    obs_cost.set_cost_metering(True)
    try:
        x, y = _tiny_xy()
        labels = (x.sum(axis=1) > 0).astype(np.float64)
        multi = (np.digitize(x.sum(axis=1), [-1.0, 1.0])).astype(np.float64)
        counts = np.floor(np.abs(x.sum(axis=1))).astype(np.float64)

        def config(est):
            return (
                est.setKernel(lambda: RBFKernel(1.0))
                .setDatasetSizeForExpert(30).setActiveSetSize(20)
                .setSigma2(1e-2).setMaxIter(2).setSeed(3)
            )

        fits = {
            "gpr": config(GaussianProcessRegression()).fit(x, y),
            "gpc": config(GaussianProcessClassifier()).fit(x, labels),
            "gpc_mc": config(
                GaussianProcessMulticlassClassifier()
            ).fit(x, multi),
            "gp_poisson": config(
                GaussianProcessPoissonRegression()
            ).fit(x, counts),
        }
        for name, model in fits.items():
            xla = model.run_journal["xla_cost"]
            assert xla is not None, f"{name}: no xla_cost in journal"
            assert xla["flops_total"] > 0, (name, xla)
            mfu = xla["measured_mfu_optimize"]
            assert mfu is not None and mfu["mfu"] > 0, (name, mfu)
        # PPA predict attribution (entry fallback label predict.ppa)
        before = obs_cost.measured_flops("predict.ppa")
        fits["gpr"].predict(x[:16])
        assert obs_cost.measured_flops("predict.ppa") > before
        # the exposition renders the series as gp_xla_flops_total{entry=}
        from spark_gp_tpu.obs.expo import render_openmetrics
        from spark_gp_tpu.obs.runtime import telemetry
        from spark_gp_tpu.serve.metrics import ServingMetrics

        page = render_openmetrics(ServingMetrics(), telemetry.snapshot())
        assert 'gp_xla_flops_total{entry="predict.ppa"}' in page
    finally:
        obs_cost.set_cost_metering(None)


def test_cost_metering_off_by_default_and_cache_hits():
    obs_cost.clear_cache()
    assert obs_cost.cost_metering_enabled() is False  # GP_XLA_COST unset
    import jax
    import jax.numpy as jnp

    probe = jax.jit(lambda a: (a @ a).sum())
    operand = jnp.ones((16, 16))
    obs_cost.set_cost_metering(True)
    try:
        first = obs_cost.measure(probe, (operand,))
        assert first is not None and first["flops"] > 0
        # second call: answered from the signature cache (same object)
        assert obs_cost.measure(probe, (operand,)) is first
    finally:
        obs_cost.set_cost_metering(None)


# -- build info + chrome metadata -------------------------------------------


def test_build_info_in_exposition_and_journal(tmp_path, monkeypatch):
    info = obs_runtime.build_info()
    assert info["backend"] == "cpu"
    assert info["version"]
    from spark_gp_tpu.obs.expo import render_openmetrics
    from spark_gp_tpu.serve.metrics import ServingMetrics

    page = render_openmetrics(ServingMetrics(name="buildtest"))
    line = next(l for l in page.splitlines() if l.startswith("gp_build_info{"))
    assert 'backend="cpu"' in line and line.endswith(" 1")
    assert "# TYPE gp_build info" in page


def test_chrome_trace_emits_named_lanes():
    with obs_trace.span("lane.test"):
        pass
    doc = obs_trace.chrome_trace()
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert any(
        e["name"] == "process_name"
        and e["args"]["name"] == f"spark_gp_tpu p{os.getpid()}"
        for e in meta
    )
    thread_names = {
        e["args"]["name"] for e in meta if e["name"] == "thread_name"
    }
    assert threading.current_thread().name in thread_names
    # metadata precedes the complete events (renders in every viewer)
    kinds = [e["ph"] for e in doc["traceEvents"]]
    assert kinds.index("M") < kinds.index("X")


# -- gpctl -------------------------------------------------------------------


@pytest.fixture(scope="module")
def two_journals(tmp_path_factory):
    journal_dir = tmp_path_factory.mktemp("gpctl_journals")
    prev = os.environ.get("GP_RUN_JOURNAL_DIR")
    os.environ["GP_RUN_JOURNAL_DIR"] = str(journal_dir)
    try:
        x, y = _tiny_xy()
        a = _tiny_gp(max_iter=2).fit(x, y)
        b = _tiny_gp(max_iter=3).fit(x, y)
    finally:
        if prev is None:
            os.environ.pop("GP_RUN_JOURNAL_DIR", None)
        else:
            os.environ["GP_RUN_JOURNAL_DIR"] = prev
    return str(journal_dir), a.run_journal["path"], b.run_journal["path"]


def _gpctl(*args, timeout=120):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "tools.gpctl", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=ROOT,
    )


def test_gpctl_list_show_and_diff_run_clean(two_journals):
    journal_dir, path_a, path_b = two_journals
    listed = _gpctl("list", journal_dir)
    assert listed.returncode == 0, listed.stderr
    rows = [l for l in listed.stdout.splitlines() if l.startswith("journal")]
    assert len(rows) == 2, listed.stdout
    assert "GaussianProcessRegression" in listed.stdout

    shown = _gpctl("show", path_a)
    assert shown.returncode == 0, shown.stderr
    assert "span tree:" in shown.stdout
    assert "fit.GaussianProcessRegression" in shown.stdout
    assert "phase optimize_hypers" in shown.stdout

    # the acceptance criterion: diff of two run journals runs clean
    diffed = _gpctl("diff", path_a, path_b)
    assert diffed.returncode == 0, diffed.stderr
    assert "phase timings" in diffed.stdout
    assert "compiles" in diffed.stdout


def test_gpctl_merge_groups_by_trace_id(two_journals, tmp_path):
    journal_dir, path_a, path_b = two_journals
    out_path = str(tmp_path / "merged.json")
    merged = _gpctl("merge", journal_dir, "--out", out_path)
    assert merged.returncode == 0, merged.stderr
    with open(out_path) as fh:
        doc = json.load(fh)
    assert doc["format"] == "spark_gp_tpu.gpctl_merge/v1"
    # two independent fits -> two distinct traces, one journal each
    assert len(doc["traces"]) == 2
    for group in doc["traces"].values():
        assert len(group["journals"]) == 1
        assert group["bundles"] == []


def test_gpctl_show_validates_bundle_schema(tmp_path, monkeypatch):
    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))
    x, y = _tiny_xy()
    with chaos.oom_after_calls(0):
        with pytest.raises(fallback.DegradationExhaustedError):
            _tiny_gp(optimizer="device").fit(x, y)
    bundle_path = _bundles(str(tmp_path))[0]
    shown = _gpctl("show", bundle_path)
    assert shown.returncode == 0, shown.stderr + shown.stdout
    assert "failure_class: oom" in shown.stdout
    assert "degradation:" in shown.stdout
    # a corrupted bundle fails validation with exit 1
    with open(bundle_path) as fh:
        doc = json.load(fh)
    del doc["degradations"]
    broken = str(tmp_path / "incident_broken.json")
    with open(broken, "w") as fh:
        json.dump(doc, fh)
    shown_broken = _gpctl("show", broken)
    assert shown_broken.returncode == 1
    assert "SCHEMA" in shown_broken.stderr
