"""End-to-end classification acceptance — Iris (the reference's example,
Iris.scala) with asserted thresholds the reference only prints."""

import numpy as np

from spark_gp_tpu import GaussianProcessClassifier
from spark_gp_tpu.data import load_iris
from spark_gp_tpu.utils.validation import OneVsRest, accuracy, kfold_indices


def _gpc():
    return GaussianProcessClassifier().setDatasetSizeForExpert(20).setActiveSetSize(30)


def test_binary_setosa_accuracy():
    x, y = load_iris()
    y_bin = (y == 1.0).astype(np.float64)  # setosa is linearly separable
    model = _gpc().fit(x, y_bin)
    assert accuracy(y_bin, model.predict(x)) > 0.98


def test_predict_raw_and_proba_shapes():
    x, y = load_iris()
    y_bin = (y == 2.0).astype(np.float64)
    model = _gpc().fit(x, y_bin)
    raw = model.predict_raw(x[:7])
    assert raw.shape == (7, 2)
    np.testing.assert_allclose(raw[:, 0], -raw[:, 1])  # (-f, f), GPClf.scala:155
    proba = model.predict_proba(x[:7])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)
    assert np.all((proba >= 0) & (proba <= 1))
    # averaged (Gauss-Hermite) probabilities are also valid and shrink towards
    # 0.5 relative to the MAP sigmoid (variance widens the link)
    av = model.predict_proba(x[:7], averaged=True)
    np.testing.assert_allclose(av.sum(axis=1), 1.0, rtol=1e-6)
    assert np.all(np.abs(av[:, 1] - 0.5) <= np.abs(proba[:, 1] - 0.5) + 1e-6)


def test_iris_ovr_cv_accuracy():
    """3-class OvR 3-fold accuracy; the reference prints ~0.95 with CV 10."""
    x, y = load_iris()
    scores = []
    for train_idx, test_idx in kfold_indices(x.shape[0], 3, seed=13):
        ovr = OneVsRest(_gpc).fit(x[train_idx], y[train_idx])
        scores.append(accuracy(y[test_idx], ovr.predict(x[test_idx])))
    assert float(np.mean(scores)) > 0.9


def test_classifier_save_load(tmp_path):
    x, y = load_iris()
    y_bin = (y == 1.0).astype(np.float64)
    model = _gpc().fit(x, y_bin)
    path = str(tmp_path / "clf")
    model.save(path)
    from spark_gp_tpu import GaussianProcessClassificationModel

    restored = GaussianProcessClassificationModel.load(path)
    np.testing.assert_allclose(
        restored.predict_proba(x[:9]), model.predict_proba(x[:9]), rtol=1e-12
    )
