"""Contract guards for the round artifacts' producers (bench.py, quality.py).

The round driver consumes these scripts' stdout directly (BENCH_r*.json /
QUALITY_r*.json); a regression that breaks their output contract would
otherwise surface only in the driver's end-of-round artifacts.  Tiny
configs keep the guards to ~30 s on the CPU harness.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, env_extra, args=(), timeout=900):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the artifact producers manage their own subprocesses; drop the
    # harness's forced 8-device flag so their workers start cleanly, and
    # drop every contract-bearing knob a developer shell might have
    # exported (an inherited GP_SYNC_PHASES=0 would fail the phase
    # attribution assertion on a perfectly healthy bench.py).  GP_SYNC_PHASES
    # is dropped rather than pinned so the bench's own platform-default
    # branch (CPU primaries run synced) is what the assertion exercises.
    env.pop("XLA_FLAGS", None)
    env.pop("GP_SYNC_PHASES", None)
    # an exported solver-lane pin (or knob refinement) would flip the
    # exact-lane primaries and the solver_lanes section's comparisons
    for var in [v for v in env if v.startswith("GP_SOLVER_")]:
        env.pop(var)
    # an exported lane/precision pin would fail the strict-lane and
    # guard-shape assertions on a healthy bench.py
    env.pop("GP_PRECISION_LANE", None)
    env.pop("GP_MATMUL_PRECISION", None)
    env.pop("GP_PRECISION_GRAM", None)
    # an exported tracer override (GP_TRACING=0) would fail the
    # observability section's spans-recorded assertion; a profiler or
    # journal dir would write artifacts into a developer's directories
    env.pop("GP_TRACING", None)
    env.pop("GP_TRACE_DIR", None)
    env.pop("GP_RUN_JOURNAL_DIR", None)
    # forensics/cost knobs: a forced recorder-off would null the recorder
    # overhead measurement; an incident dir would litter a developer's
    # directory if a bench sub-measurement ever fails classified
    env.pop("GP_RECORDER", None)
    env.pop("GP_XLA_COST", None)
    env.pop("GP_INCIDENT_DIR", None)
    # a disabled quality plane / fit telemetry would null the quality
    # overhead measurement on a healthy bench.py
    env.pop("GP_SERVE_QUALITY", None)
    env.pop("GP_EXPERT_TELEMETRY", None)
    env.pop("GP_COVARIATE_SUMMARY", None)
    # an exported GP_MEMPLAN=0 (or a stray margin/limit) would fail the
    # memory_plan section on a healthy bench.py
    env.pop("GP_MEMPLAN", None)
    env.pop("GP_MEMPLAN_MARGIN", None)
    env.pop("GP_MEMPLAN_LIMIT_BYTES", None)
    # an exported aggregation policy would flip the poe-default primary
    # fit (and the policy comparison rows); an exported selection knob
    # would break the aggregation section's selection-off baseline
    for var in [v for v in env if v.startswith("GP_AGG_")]:
        env.pop(var)
    for var in list(env):
        # GP_CHAOS_*: a staged fault (dead host / kill counter / staged
        # corruption) from a chaos shell would kill the bench worker
        # mid-measurement; GP_COORD_*: a shrunken deadline would fail
        # healthy coordination; GP_INTEGRITY*: a kill-switched plane (or
        # a forced 100% serve-verify fraction) would null or inflate the
        # integrity overhead measurement on a healthy bench.py
        if var.startswith(
            ("BENCH_", "QUALITY_", "GP_CHAOS_", "GP_COORD_", "GP_INTEGRITY")
        ):
            env.pop(var)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, script), *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=ROOT,
    )


@pytest.mark.slow
def test_bench_emits_one_parseable_result_line():
    out = _run(
        "bench.py",
        {
            "BENCH_N": "1500",
            "BENCH_EXPERT": "50",
            "BENCH_MXU_EXPERT": "64",
            "BENCH_MAXITER": "3",
            "BENCH_PREFLIGHT_TIMEOUT": "120",
            "BENCH_PREFLIGHT_ATTEMPTS": "1",
            # the solver-lane bar is pinned at s=2048 (the acceptance
            # size); two experts per stack (the batched regime the lane
            # is built for — single-matrix LAPACK vs batched einsums is
            # not the production shape) and few reps keep the probe
            # inside the contract-run budget
            "BENCH_SOLVER_SIZES": "256,2048",
            "BENCH_SOLVER_EXPERTS": "2",
            "BENCH_SOLVER_REPS": "2",
        },
    )
    assert out.returncode == 0, out.stderr[-500:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    # the driver's contract: metric/value/unit/vs_baseline always present
    assert result["metric"] == "gpr_train_points_per_sec_per_chip"
    assert result["value"] and result["value"] > 0
    assert result["unit"] == "points/s/chip"
    assert result["vs_baseline"] and result["vs_baseline"] > 0
    detail = result["detail"]
    # the final line is the FULL result, not the early partial emit
    assert "partial" not in detail
    assert detail["platform"] == "cpu"
    # phase attribution: under the bench's own CPU default (GP_SYNC_PHASES
    # unset -> synced primary; TPU primaries run async with a fenced synced
    # breakdown fit instead) the optimizer phase must carry its own
    # wall-clock, not hide in the final fetch
    phases = detail["fit_phase_seconds"]
    assert phases["optimize_hypers"] > phases.get("sync_fetch", 0.0)
    # the MXU-aligned secondary config rode along
    assert detail["mxu_config"]["expert_size"] == 64
    assert detail["mxu_config"]["fit_seconds"] > 0
    # the serving path entered the trajectory: p50/p99 latency and
    # throughput through the micro-batcher, with a compile-free hot path
    serve = detail["serve_predict"]
    assert "error" not in serve, serve
    assert serve["points_per_sec"] > 0
    assert 0 < serve["latency_p50_ms"] <= serve["latency_p99_ms"]
    assert all(c == 1 for c in serve["compiles_per_bucket"].values())
    # the resilience section rode along: one NaN-poisoned expert is
    # quarantined and the faulted fit completes at a sane overhead
    res = detail["resilience"]
    assert "error" not in res, res
    assert res["experts_quarantined"] == 1
    assert res["faulted_fit_seconds"] > 0
    assert np.isfinite(res["faulted_final_nll_renormalized"])
    # the degradation ladder rode along (ISSUE 9, resilience/fallback.py;
    # ISSUE 14 gave the oom class an iterative-first rung): a
    # chaos-injected RESOURCE_EXHAUSTED on the one-dispatch device fit
    # completes through the iterative solver rung within 3x the clean
    # wall-clock, theta within the lane's documented stochastic bar
    deg = detail["degraded_fit"]
    assert "error" not in deg, deg
    assert deg["engaged"] is True, deg
    assert deg["injected_failures"] >= 1
    assert "iterative" in deg["rungs"], deg
    assert deg["failure_classes"] == ["oom"], deg
    assert deg["wallclock_ratio"] < 3.0, deg
    assert deg["nll_rel_delta"] <= 1e-2, deg
    # the predictive memory planner (ISSUE 11, resilience/memplan.py):
    # the same workload under a chaos-staged device budget completes with
    # ZERO injected OOMs and zero reactive rung transitions — the plan
    # sizes the dispatch down BEFORE execution instead of crashing into
    # the ladder, and the decision is provenance-stamped
    mp = detail["memory_plan"]
    assert "error" not in mp, mp
    assert "skipped" not in mp, mp
    assert mp["injected_ooms"] == 0, mp
    assert mp["oom_failures"] == 0, mp
    assert mp["rung_transitions"] == 0, mp
    # the pre-sized choice under pressure is now the iterative solver
    # rung (ISSUE 14: skinny CG workspace preferred over halving
    # segments); theta parity at the lane's stochastic bar
    assert mp["planned"] is True and mp["chosen"] == "iterative", mp
    row = mp["plan_rows"][0]
    assert row["fits"] is True
    assert row["predicted_bytes"] >= row["raw_bytes"]
    assert row["predicted_bytes"] <= mp["budget_bytes"]
    assert mp["nll_rel_delta"] <= 1e-2, mp
    # the mixed-precision lane contract: the lane the primary fit ran at
    # is recorded, the MFU estimate is non-null (the peak table carries a
    # CPU-proxy entry precisely so this plumbing is exercised off-TPU),
    # and the precision_lanes section has all three lanes with gram rates,
    # end-to-end fits, and fit-time guard deltas on the non-strict lanes.
    # The >= 1.5x mixed-vs-strict gram bar is TPU-only (on CPU the
    # compensated path is strictly extra work) — here only the shape.
    assert detail["precision_lane"] == "strict"
    assert detail["est_mfu_vs_bf16_peak"] is not None
    assert detail["mxu_config"]["est_mfu_vs_bf16_peak"] is not None
    lanes_section = detail["precision_lanes"]
    assert "error" not in lanes_section, lanes_section
    assert lanes_section["gram_probe"]["flops_per_call"] > 0
    lanes = lanes_section["lanes"]
    assert set(lanes) == {"strict", "mixed", "fast"}
    for row in lanes.values():
        assert row["gram_build_gflops_per_sec"] > 0
        assert row["fit_seconds"] > 0
        assert row["train_points_per_sec"] > 0
    assert lanes["strict"]["source"] == "primary measurement"
    for lane_name in ("mixed", "fast"):
        assert lanes[lane_name]["gram_speedup_vs_strict"] > 0
        guard = lanes[lane_name]["guard"]
        for leg in ("delta_nll_rel", "delta_grad_rel", "delta_predict_rel"):
            assert np.isfinite(guard[leg])
    # no-breach is only pinned for the production-intended mixed lane
    # (fast is a documented loose tripwire, not an accuracy contract)
    assert lanes["mixed"]["guard"]["breach"] == 0.0, lanes["mixed"]["guard"]
    # the theta-invariant precompute plane (ISSUE 8, kernels/base.py):
    # cached isotropic evaluations must beat the per-eval gram rebuild by
    # >= 1.3x on the distance-dominated CPU probe, the cache must actually
    # have engaged, and toggling the plane (GP_GRAM_CACHE) must not move
    # any family's fitted hyperparameters beyond float noise
    hot = detail["fit_hot_loop"]
    assert "error" not in hot, hot
    assert hot["cache_engaged"] is True
    evals = hot["nll_evals_per_sec"]
    assert evals["cached"] > 0 and evals["uncached"] > 0
    assert evals["speedup"] >= 1.3, evals
    assert set(hot["families"]) == {"gpr", "gpc", "gp_poisson"}
    for name, fam in hot["families"].items():
        assert fam["cached_cache_engaged"] == 1.0, (name, fam)
        assert fam["uncached_cache_engaged"] == 0.0, (name, fam)
        assert fam["theta_max_abs_delta"] <= 1e-6, (name, fam)
    # the solver lanes (ISSUE 14, ops/iterative.py): the iterative
    # CG/Lanczos lane must beat the exact batched Cholesky by >= 1.3x
    # nll_evals/sec at the largest probed expert size (s = 2048 here, a
    # size whose exact native dispatch the memory model prices over the
    # demo budget while the iterative rung fits), with fitted-theta
    # parity within the lane's documented 5e-2 stochastic bar and the
    # engaged-lane provenance stamped on the iterative fit
    sl = detail["solver_lanes"]
    assert "error" not in sl, sl
    assert sl["largest_s"] == 2048, sl
    assert sl["speedup_at_largest"] >= 1.3, sl
    largest = sl["sizes"][str(sl["largest_s"])]
    assert largest["nll_evals_per_sec"]["iterative"] > 0
    demo = largest["memory_budget_demo"]
    assert demo["iterative_fits"] is True and demo["exact_fits"] is False, sl
    assert sl["fitted_theta"]["rel_delta"] <= 5e-2, sl
    assert sl["solver_metrics"].get("solver_lane") == "iterative", sl
    assert sl["solver_metrics"].get("solver.residual", 1.0) <= 1e-2, sl
    # the matfree column (ISSUE 20, ops/pallas_matvec.py): the gram-less
    # streaming lane runs the same CG/SLQ program, so it must produce a
    # live eval rate, a modeled peak strictly under the iterative gram
    # stack at s = 2048, admission under a tight budget the iterative
    # rung exceeds (the O(E*s^2) ceiling the lane breaks), theta parity
    # within the same stochastic bar, and its own engaged provenance
    assert largest["nll_evals_per_sec"]["matfree"] > 0, sl
    from spark_gp_tpu.resilience import memplan as _memplan
    big = largest["modeled_fit_bytes"]
    assert _memplan.predicted_bytes(big["matfree"]) < (
        _memplan.predicted_bytes(big["iterative"])
    ), big
    tight = largest["matfree_budget_demo"]
    assert tight["matfree_fits"] is True, sl
    assert tight["iterative_fits"] is False, sl
    assert sl["fitted_theta"]["rel_delta_matfree"] <= 5e-2, sl
    assert sl["solver_metrics_matfree"].get("solver_lane") == "matfree", sl
    assert sl["solver_metrics_matfree"].get(
        "solver.matfree_engaged"
    ) == 1.0, sl
    assert sl["solver_metrics_matfree"].get(
        "solver.residual", 1.0
    ) <= 1e-2, sl
    # the expert aggregation plane (ISSUE 16, models/aggregation.py): on
    # the clustered stand-in at E = 64 the healed product beats plain PoE
    # on held-out NLPD and lands 90% coverage near-calibrated while PoE's
    # overconfidence is demonstrated outside the band; correlation-aware
    # selection drops >= 25% of the pairwise-duplicated experts, speeds
    # the objective evaluation >= 1.5x, and costs <= 1% held-out NLPD
    ag = detail["aggregation"]
    assert "error" not in ag, ag
    assert ag["num_experts"] >= 64, ag
    pol = ag["policies"]
    assert pol["healed"]["nlpd"] < pol["poe"]["nlpd"], pol
    assert 0.84 <= pol["healed"]["coverage90"] <= 0.97, pol
    assert pol["poe"]["coverage90"] < 0.80, pol
    sel = ag["selection"]
    assert sel["dropped_fraction"] >= 0.25, sel
    assert sel["eval_speedup"] >= 1.5, sel
    # signed: positive = degradation; selection may legitimately IMPROVE
    # held-out NLPD (the deduplicated objective is better conditioned)
    assert sel["nlpd_rel_delta"] <= 1e-2, sel
    # the observability contract: the span/journal/telemetry layer stays
    # out of the hot path — <2% on fit and serve_predict (min-of-reps,
    # interleaved; obs/trace.py) — while provably ON (spans recorded)
    obs = detail["observability"]
    assert "error" not in obs, obs
    assert obs["fit"]["spans_per_fit"] >= 3, obs["fit"]
    assert obs["fit"]["overhead_pct"] < 2.0, obs["fit"]
    assert obs["serve_predict"]["overhead_pct"] < 2.0, obs["serve_predict"]
    # the flight recorder (ISSUE 10, obs/recorder.py) rides the same bar:
    # recorder-on vs recorder-off stays under 2% on both paths
    rec = obs["recorder"]
    assert rec["record_seconds"] > 0 and rec["note_metric_seconds"] > 0
    assert rec["fit_overhead_pct"] < 2.0, rec
    assert rec["serve_overhead_pct"] < 2.0, rec
    # the statistical health plane (ISSUE 13, obs/quality.py) rides the
    # same bar: the monitor's BATCHER-side work (one note_predictions
    # handoff per dispatch; puts/scoring run on the drainer thread)
    # stays under 2% of the burst, with zero batches dropped
    quality = obs["quality"]
    assert quality["note_seconds"] > 0, quality
    assert quality["pending_put_seconds"] > 0, quality
    assert quality["drift_score_seconds"] > 0, quality
    assert quality["dropped_batches"] == 0, quality
    assert quality["overhead_pct"] < 2.0, quality
    assert quality["monitor_on_points_per_sec_max"] > 0, quality
    # measured XLA cost attribution (obs/cost.py): the metered fit's
    # journal carries non-null flops and a measured optimize-phase MFU
    xla = obs["xla_cost"]
    assert xla is not None and xla["flops_total"] > 0, xla
    assert xla["measured_mfu_optimize"] is not None, xla
    assert xla["measured_mfu_optimize"]["mfu"] > 0, xla
    # the multi-host coordination contract (parallel/coord.py): barrier and
    # per-evaluation allreduce round-trips are measured, and a coordinated
    # checkpoint save (barrier + writer election + digest cross-check)
    # completed against the plain atomic writer baseline
    mh = detail["multihost_resilience"]
    assert "error" not in mh, mh
    assert mh["barrier_roundtrip_us"] > 0
    assert mh["allreduce_roundtrip_us"] > 0
    assert mh["checkpoint_save_us"]["uncoordinated"] > 0
    assert mh["checkpoint_save_us"]["coordinated_2host"] > 0
    assert np.isfinite(mh["coordinated_ckpt_overhead_ratio"])
    # the serve lifecycle contract (serve/lifecycle.py): a canary rollout
    # under a closed-loop client is a ZERO-downtime swap (no failed
    # requests, auto-promoted), and a drain answers the whole queued
    # burst before stopping
    lc = detail["lifecycle"]
    assert "error" not in lc, lc
    assert lc["rollout_promoted"] is True, lc
    assert lc["rollout_failed_requests"] == 0, lc
    assert lc["rollout_requests_ok"] > 0
    assert lc["canary_shadow_scores"] >= 5
    assert lc["drain_seconds"] > 0
    assert lc["drained_clean"] is True, lc
    assert lc["drain_burst_answered"] == lc["drain_burst_requests"], lc
    # the fleet contract (ISSUE 12, serve/fleet.py + serve/router.py): a
    # closed-loop client over a 3-replica consistent-hash fleet with the
    # bucket owner SIGKILLed mid-burst answers EVERY request — zero
    # failed requests, at least one failover re-route, sane p50 <= p99
    fl = detail["fleet"]
    assert "error" not in fl, fl
    assert fl["replicas"] == 3
    assert fl["failover_failed_requests"] == 0, fl
    assert fl["requests_ok"] == fl["requests"], fl
    assert fl["failovers"] >= 1, fl
    assert 0 < fl["latency_p50_ms"] <= fl["latency_p99_ms"], fl
    # the numerical-integrity contract (ISSUE 17, resilience/integrity.py):
    # the SDC defenses — attested collectives on every DCN round, sampled
    # cross-replica answer verification on serve — cost under 2% of the
    # clean paths they guard.  overhead_pct is the directly-measured
    # integrity work divided by the path wall-clock (the interleaved
    # measured_delta_pct is informational: thread-rendezvous noise on
    # these sub-100ms paths swamps the true cost in either direction).
    ig = detail["integrity"]
    assert "error" not in ig, ig
    assert ig["allreduce_attested_us_min"] > 0
    assert ig["fit"]["vag_rounds"] >= 1, ig["fit"]
    assert ig["fit"]["attest_round_us"] > 0
    assert ig["fit"]["overhead_pct"] < 2.0, ig["fit"]
    assert ig["serve"]["verify_fraction"] == 0.01, ig["serve"]
    assert ig["serve"]["overhead_pct"] < 2.0, ig["serve"]


@pytest.mark.slow
def test_bench_forced_extras_run_on_cpu():
    """BENCH_FORCE_EXTRAS exercises the TPU-gated extras' code paths on CPU
    (tiny shapes) so new extras never execute for the first time on real
    tunnel-uptime.  Pallas/airfoil stay off (Mosaic needs a chip; airfoil
    has no small config); the N-scaling curve and the synced phase
    breakdown run for real."""
    out = _run(
        "bench.py",
        {
            "BENCH_N": "1500",
            "BENCH_EXPERT": "50",
            "BENCH_MXU_EXPERT": "64",
            "BENCH_MAXITER": "3",
            "BENCH_PREFLIGHT_TIMEOUT": "120",
            "BENCH_PREFLIGHT_ATTEMPTS": "1",
            "BENCH_FORCE_EXTRAS": "1",
            "BENCH_PALLAS_SWEEP": "0",
            "BENCH_AIRFOIL": "0",
            "BENCH_SCALING_SIZES": "800,1500",
        },
    )
    assert out.returncode == 0, out.stderr[-500:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    detail = result["detail"]
    rows = detail["scaling_n"]["rows"]
    assert [r["n_points"] for r in rows] == [800, 1500]
    assert all(r["points_per_sec"] > 0 for r in rows)
    # the size matching the primary N reuses the primary fit, not a re-run
    assert rows[1]["source"] == "primary measurement"
    assert rows[1]["fit_seconds"] == round(detail["fit_seconds"], 4)
    # the synced-breakdown extra replaced the phases and said so
    assert detail["fit_phase_seconds_synced"]["status"].startswith("ok")
    assert "separate synced fit" in detail["phase_timing_note"]
    assert detail["fit_phase_seconds"]["optimize_hypers"] > 0
    # un-selected extras stayed off
    assert "pallas_sweep" not in detail
    assert "airfoil_10fold" not in detail


@pytest.mark.slow
def test_quality_single_part_report_contract():
    out = _run("quality.py", {}, args=("--parts", "greedy_vs_random"))
    # surface the real cause on a crash instead of an opaque JSON error
    assert out.returncode in (0, 1), out.stderr[-500:]
    report = json.loads(out.stdout)
    part = report["parts"]["greedy_vs_random"]
    assert "error" not in part, part
    assert isinstance(part["passed"], bool)
    assert report["failed_bars"] == ([] if part["passed"] else ["greedy_vs_random"])
    # bars gate the exit code
    assert out.returncode == (0 if not report["failed_bars"] else 1)


def test_parse_bench_payload_shapes():
    """_parse_bench_payload must read all four artifact shapes: raw emit,
    builder side artifact, watcher envelope, driver capture."""
    sys.path.insert(0, ROOT)
    import bench

    raw = {"metric": "m", "value": 1.0, "unit": "u", "detail": {"platform": "tpu"}}
    assert bench._parse_bench_payload(raw) == raw
    assert bench._parse_bench_payload({"parsed": raw}) == raw
    line = json.dumps(raw)
    assert bench._parse_bench_payload(
        {"stdout_tail": "noise\n" + line + "\n"}
    ) == raw
    assert bench._parse_bench_payload({"tail": line + "\n"}) == raw
    assert bench._parse_bench_payload({"tail": "no json here"}) is None
    assert bench._parse_bench_payload("not a dict") is None


def test_freshest_hardware_evidence_finds_committed_artifact():
    """The repo carries at least one on-TPU side artifact (BENCH_r04_tpu.json);
    the evidence scanner must surface a pointer with the driver-readable
    fields (VERDICT r4 #6)."""
    sys.path.insert(0, ROOT)
    import bench

    ev = bench._freshest_hardware_evidence()
    assert ev is not None, "no TPU evidence found despite committed artifacts"
    for key in ("file", "metric", "value", "unit", "captured"):
        assert key in ev, key
    assert ev["value"] and ev["value"] > 0


@pytest.mark.slow
def test_bench_fallback_embeds_hardware_evidence_pointer():
    """When the default plan fails preflight, the CPU-fallback artifact must
    carry detail.freshest_hardware_evidence so it can never masquerade as
    the round's hardware number."""
    out = _run(
        "bench.py",
        {
            # an unloadable platform makes the default plan fail FAST and
            # deterministically; the cpu-fallback plan then measures
            "JAX_PLATFORMS": "no_such_platform",
            "BENCH_N": "1500",
            "BENCH_EXPERT": "50",
            "BENCH_MXU_EXPERT": "64",
            "BENCH_MAXITER": "3",
            "BENCH_PREFLIGHT_TIMEOUT": "120",
            "BENCH_PREFLIGHT_ATTEMPTS": "1",
        },
    )
    assert out.returncode == 0, out.stderr[-500:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    detail = result["detail"]
    assert "fallback" in detail
    ev = detail["freshest_hardware_evidence"]
    assert isinstance(ev, dict), ev  # this checkout has committed evidence
    assert ev["value"] > 0
    assert "file" in ev and "captured" in ev


def test_freshest_hardware_evidence_prefers_stamped_artifacts():
    """A capture-stamped TPU artifact must outrank unstamped ones even
    when the unstamped file's mtime is newer (fresh-clone mtimes are all
    checkout time): the evidence pointer must name the newest STAMPED
    on-chip number, not whichever file git wrote last."""
    sys.path.insert(0, ROOT)
    import bench

    ev = bench._freshest_hardware_evidence()
    assert ev is not None
    # BENCH_r04_tpu.json is the only committed artifact carrying a capture
    # stamp with platform=tpu; BENCH_r02.json (also tpu) is unstamped and
    # its checkout mtime is newer — the stamp must win
    assert ev["captured"] is not None


@pytest.mark.slow
def test_matched_config_lane_contract():
    """benchmarks/matched_config.py must emit one JSON line with both
    timing modes, RTT measurements, and the r2-comparison summary."""
    out = _run(
        "benchmarks/matched_config.py",
        {"MATCHED_N": "2000", "MATCHED_EXPERT": "50", "MATCHED_MAXITER": "3"},
    )
    assert out.returncode == 0, out.stderr[-500:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    for mode in ("async", "sync_phases"):
        row = result["rows"][mode]
        assert row["train_points_per_sec"] > 0
        assert row["phase_seconds"]
    assert result["rtt_before"]["median_ms"] >= 0
    assert result["rtt_after"]["median_ms"] >= 0
    assert result["summary"]["r2_reference_pts_per_sec"] == 247124.8
    assert result["summary"]["async_vs_sync_ratio"] is not None


@pytest.mark.slow
def test_large_m_lane_contract():
    """benchmarks/large_m.py must engage the device magic-solve dispatch
    (m >= _DEVICE_SOLVE_MIN_M), pass both RMSE bars, and carry phase
    timings that show where the m^3 work ran."""
    out = _run(
        "benchmarks/large_m.py",
        {"LARGE_M": "2048", "LARGE_M_N": "12000", "LARGE_M_MAXITER": "2"},
        timeout=900,
    )
    assert out.returncode == 0, out.stderr[-500:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    lane = result["m4096_synthetic"]
    assert lane["m"] >= result["device_solve_min_m"]
    assert lane["passed"], lane
    assert lane["phase_seconds"]["magic_solve"] > 0
    assert result["airfoil_m1000"]["passed"], result["airfoil_m1000"]
    assert result["passed"]


@pytest.mark.slow
def test_roofline_lane_contract():
    """benchmarks/roofline.py must emit one JSON line with both precision
    lanes (run as separate child processes), per-op rows carrying the
    achieved-rate fields, and the mixed-precision quality guard."""
    out = _run(
        "benchmarks/roofline.py",
        {
            "ROOFLINE_TOTAL": "2048",
            "ROOFLINE_SIZES": "64",
            "ROOFLINE_REPEATS": "1",
            "ROOFLINE_CHILD_TIMEOUT": "420",
        },
        timeout=900,
    )
    assert out.returncode == 0, out.stderr[-500:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    for lane in ("highest", "high"):
        assert lane in result, result.keys()
        rows = result[lane]["rows"]
        ops = [r["op"] for r in rows]
        assert any(o.startswith("gram_build") for o in ops)
        assert any(o.startswith("spd_inv_logdet_fwd") for o in ops)
        assert any(o.startswith("objective_value_and_grad") for o in ops)
        assert all(r["achieved_tflops_per_sec"] > 0 for r in rows)
        assert "calibration_matmul_4096" in result[lane]
    guard = result["mixed_precision_guard"]
    assert guard["both_under_bar"], guard
    assert "verdict" in guard
