"""Generic-likelihood Laplace + GP Poisson regression tests.

Oracle strategy mirrors tests/test_multiclass.py: dense f64 full-system
Newton + slogdet for the mode and log Z, central finite differences for
the hyperparameter gradient, plus a check that the autodiff-derived
grad/Hessian of the Likelihood base equals the Poisson closed forms.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels.base import Const, EyeKernel
from spark_gp_tpu.kernels.rbf import RBFKernel
from spark_gp_tpu.models.laplace_generic import (
    Likelihood,
    PoissonLikelihood,
    _gram_stack,
    batched_neg_logz_generic,
    laplace_generic_mode,
)


def _problem(rng, n=15, p=2):
    x = rng.normal(size=(n, p))
    f_true = 1.0 + np.sin(x.sum(axis=1))
    y = rng.poisson(np.exp(f_true)).astype(np.float64)
    return x, y


def _oracle(kmat, y, iters=300):
    """Dense f64 Newton on the full system + direct log Z (no structure
    shared with the implementation under test)."""
    n = len(y)
    f = np.zeros(n)
    for _ in range(iters):
        w = np.exp(f)
        grad = y - w
        h = np.diag(w)
        f_new = kmat @ np.linalg.solve(np.eye(n) + h @ kmat, h @ f + grad)
        done = np.max(np.abs(f_new - f)) < 1e-12
        f = f_new
        if done:
            break
    w = np.exp(f)
    a = np.linalg.solve(kmat, f)
    psi = -0.5 * a @ f + np.sum(y * f - np.exp(f))
    _, logdet = np.linalg.slogdet(np.eye(n) + kmat @ np.diag(w))
    return f, psi - 0.5 * logdet


def test_autodiff_grad_hess_matches_closed_form(rng):
    """The Likelihood base derives (grad, W) by vmapped autodiff; Poisson
    overrides with closed forms — they must agree."""
    f = jnp.asarray(rng.normal(size=(2, 7)))
    y = jnp.asarray(rng.poisson(2.0, size=(2, 7)).astype(np.float64))
    lik = PoissonLikelihood()
    g_c, w_c = lik.grad_hess(f, y)
    g_a, w_a = Likelihood.grad_hess(lik, f, y)
    np.testing.assert_allclose(np.asarray(g_a), np.asarray(g_c), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(w_a), np.asarray(w_c), rtol=1e-12)


@pytest.fixture
def poisson_fixture(rng):
    x, y = _problem(rng)
    kernel = RBFKernel(0.9) + Const(1e-2) * EyeKernel()
    theta = jnp.asarray(np.array([0.9]))
    kmat = _gram_stack(
        kernel, theta, jnp.asarray(x[None]), jnp.ones((1, x.shape[0]))
    )
    return kernel, theta, x, y, kmat


def test_mode_matches_dense_oracle(poisson_fixture):
    kernel, theta, x, y, kmat = poisson_fixture
    n = len(y)
    f_hat, _ = laplace_generic_mode(
        PoissonLikelihood(), kmat, jnp.asarray(y[None]), jnp.ones((1, n)),
        jnp.zeros((1, n)), 1e-12,
    )
    f_oracle, _ = _oracle(np.asarray(kmat[0]), y)
    np.testing.assert_allclose(np.asarray(f_hat[0]), f_oracle, atol=1e-9)


def test_logz_matches_dense_oracle(poisson_fixture):
    kernel, theta, x, y, kmat = poisson_fixture
    n = len(y)
    value, _, _ = batched_neg_logz_generic(
        PoissonLikelihood(), kernel, 1e-12, theta, jnp.asarray(x[None]),
        jnp.asarray(y[None]), jnp.ones((1, n)), jnp.zeros((1, n)),
    )
    _, logz_oracle = _oracle(np.asarray(kmat[0]), y)
    np.testing.assert_allclose(-float(value), logz_oracle, rtol=1e-10)


def test_gradient_matches_finite_difference(rng):
    x, y = _problem(rng, n=12)
    kernel = RBFKernel(0.8) + Const(1e-2) * EyeKernel()
    n = len(y)

    def nll(t):
        value, grad, _ = batched_neg_logz_generic(
            PoissonLikelihood(), kernel, 1e-12, jnp.asarray(np.array([t])),
            jnp.asarray(x[None]), jnp.asarray(y[None]), jnp.ones((1, n)),
            jnp.zeros((1, n)),
        )
        return float(value), float(grad[0])

    _, grad = nll(0.8)
    h = 1e-6
    fd = (nll(0.8 + h)[0] - nll(0.8 - h)[0]) / (2 * h)
    np.testing.assert_allclose(grad, fd, rtol=1e-6)


def test_padding_is_inert(rng):
    x, y = _problem(rng, n=10)
    kernel = RBFKernel(0.9) + Const(1e-2) * EyeKernel()
    theta = jnp.asarray(np.array([0.9]))
    n = len(y)
    v0, g0, f0 = batched_neg_logz_generic(
        PoissonLikelihood(), kernel, 1e-12, theta, jnp.asarray(x[None]),
        jnp.asarray(y[None]), jnp.ones((1, n)), jnp.zeros((1, n)),
    )
    pad = 3
    xp = np.concatenate([x, np.broadcast_to(x[:1], (pad, x.shape[1]))])
    yp = np.concatenate([y, np.zeros(pad)])
    maskp = np.concatenate([np.ones(n), np.zeros(pad)])
    v1, g1, f1 = batched_neg_logz_generic(
        PoissonLikelihood(), kernel, 1e-12, theta, jnp.asarray(xp[None]),
        jnp.asarray(yp[None]), jnp.asarray(maskp[None]),
        jnp.zeros((1, n + pad)),
    )
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-9)
    np.testing.assert_allclose(
        np.asarray(f1[0, :n]), np.asarray(f0[0]), atol=1e-10
    )


def _count_problem(rng, n=400):
    x = np.linspace(0, 4, n)[:, None]
    rate = np.exp(1.0 + np.sin(2 * x[:, 0]))
    y = rng.poisson(rate).astype(np.float64)
    return x, y, rate


@pytest.mark.parametrize("optimizer", ["host", "device"])
def test_estimator_end_to_end(rng, optimizer):
    from spark_gp_tpu import GaussianProcessPoissonRegression

    x, y, rate = _count_problem(rng)
    model = (
        GaussianProcessPoissonRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setActiveSetSize(60)
        .setMaxIter(20)
        .setOptimizer(optimizer)
        .fit(x, y)
    )
    pred = model.predict_rate(x)
    rel = np.mean(np.abs(pred - rate) / rate)
    assert rel < 0.25, rel
    mean, var = model.predict_latent(x[:10])
    assert var is not None and np.all(var >= 0)


def test_estimator_sharded_objective(rng, eight_device_mesh):
    from spark_gp_tpu import GaussianProcessPoissonRegression

    x, y, rate = _count_problem(rng)
    model = (
        GaussianProcessPoissonRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setDatasetSizeForExpert(50)
        .setActiveSetSize(60)
        .setMaxIter(15)
        .setOptimizer("host")
        .setMesh(eight_device_mesh)
        .fit(x, y)
    )
    rel = np.mean(np.abs(model.predict_rate(x) - rate) / rate)
    assert rel < 0.25, rel


def test_save_load_and_validation(rng, tmp_path):
    from spark_gp_tpu import (
        GaussianProcessPoissonModel,
        GaussianProcessPoissonRegression,
    )

    x, y, _ = _count_problem(rng, n=200)
    model = (
        GaussianProcessPoissonRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setActiveSetSize(40)
        .setMaxIter(10)
        .fit(x, y)
    )
    path = str(tmp_path / "poisson")
    model.save(path)
    loaded = GaussianProcessPoissonModel.load(path)
    np.testing.assert_allclose(
        loaded.predict_rate(x[:20]), model.predict_rate(x[:20]), rtol=1e-12
    )
    with pytest.raises(ValueError, match="counts"):
        GaussianProcessPoissonRegression().fit(x, y - 0.5)
    with pytest.raises(ValueError, match="counts"):
        GaussianProcessPoissonRegression().fit(x, -y - 1)


def test_bernoulli_generic_matches_hand_coded_binary(rng):
    """Cross-validation of two independent implementations: the generic
    autodiff Laplace (Newton-fixed-point gradient) and the hand-assembled
    Algorithm 5.1 of models/laplace.py must agree on the objective AND the
    hyperparameter gradient for the sigmoid likelihood — each certifies
    the other."""
    from spark_gp_tpu.models.laplace import expert_neg_logz_and_grad
    from spark_gp_tpu.models.laplace_generic import BernoulliLikelihood

    n = 18
    x = rng.normal(size=(n, 2))
    y = (x.sum(axis=1) > 0).astype(np.float64)
    kernel = RBFKernel(0.9) + Const(1e-2) * EyeKernel()
    theta = jnp.asarray(np.array([0.9]))

    v_hand, g_hand, f_hand = expert_neg_logz_and_grad(
        kernel, 1e-12, theta, jnp.asarray(x), jnp.asarray(y),
        jnp.ones(n), jnp.zeros(n),
    )
    v_gen, g_gen, f_gen = batched_neg_logz_generic(
        BernoulliLikelihood(), kernel, 1e-12, theta, jnp.asarray(x[None]),
        jnp.asarray(y[None]), jnp.ones((1, n)), jnp.zeros((1, n)),
    )
    np.testing.assert_allclose(float(v_gen), float(v_hand), rtol=1e-10)
    np.testing.assert_allclose(
        np.asarray(g_gen), np.asarray(g_hand), rtol=1e-8
    )
    np.testing.assert_allclose(
        np.asarray(f_gen[0]), np.asarray(f_hand), atol=1e-9
    )


def test_bernoulli_autodiff_grad_hess_matches_closed_form(rng):
    from spark_gp_tpu.models.laplace_generic import BernoulliLikelihood

    f = jnp.asarray(rng.normal(size=(2, 6)))
    y = jnp.asarray((rng.normal(size=(2, 6)) > 0).astype(np.float64))
    lik = BernoulliLikelihood()
    g_c, w_c = lik.grad_hess(f, y)
    g_a, w_a = Likelihood.grad_hess(lik, f, y)
    np.testing.assert_allclose(np.asarray(g_a), np.asarray(g_c), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(w_a), np.asarray(w_c), rtol=1e-10)


def test_binomial_likelihood(rng):
    """Closed-form grad/W vs the base autodiff derivation, plus mode
    recovery on aggregated binary data (20 trials per point)."""
    from spark_gp_tpu.models.laplace_generic import BinomialLikelihood

    trials = 20
    lik = BinomialLikelihood(trials)
    f = jnp.asarray(rng.normal(size=(2, 6)))
    y = jnp.asarray(rng.integers(0, trials + 1, size=(2, 6)).astype(np.float64))
    g_c, w_c = lik.grad_hess(f, y)
    g_a, w_a = Likelihood.grad_hess(lik, f, y)
    np.testing.assert_allclose(np.asarray(g_a), np.asarray(g_c), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(w_a), np.asarray(w_c), rtol=1e-10)
    with pytest.raises(ValueError, match="positive"):
        BinomialLikelihood(0)

    # mode recovery: n points with known success probability
    n = 200
    x = np.linspace(0, 4, n)[:, None]
    p_true = 1.0 / (1.0 + np.exp(-np.sin(2 * x[:, 0])))
    y_counts = rng.binomial(trials, p_true).astype(np.float64)
    kernel = RBFKernel(0.5, 0.5, 0.5) + Const(1e-2) * EyeKernel()
    theta = jnp.asarray(np.array([0.5]))
    kmat = _gram_stack(kernel, theta, jnp.asarray(x[None]), jnp.ones((1, n)))
    f_hat, _ = laplace_generic_mode(
        lik, kmat, jnp.asarray(y_counts[None]), jnp.ones((1, n)),
        jnp.zeros((1, n)), 1e-10,
    )
    p_hat = 1.0 / (1.0 + np.exp(-np.asarray(f_hat[0])))
    assert np.mean(np.abs(p_hat - p_true)) < 0.05


def test_fit_distributed_poisson(rng, eight_device_mesh):
    from spark_gp_tpu import GaussianProcessPoissonRegression
    from spark_gp_tpu.parallel import distributed as dist

    x, y, rate = _count_problem(rng)
    gdata = dist.distribute_global_experts(x, y, 50, eight_device_mesh)

    def make():
        return (
            GaussianProcessPoissonRegression()
            .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
            .setDatasetSizeForExpert(50)
            .setActiveSetSize(60)
            .setMaxIter(15)
            .setMesh(eight_device_mesh)
        )

    model = make().fit_distributed(gdata)
    rel = np.mean(np.abs(model.predict_rate(x) - rate) / rate)
    assert rel < 0.25, rel

    bad = dist.distribute_global_experts(x, y + 0.5, 50, eight_device_mesh)
    with pytest.raises(ValueError, match="counts"):
        make().fit_distributed(bad)


def test_mean_only_poisson_uses_map_rate(rng):
    """setPredictiveVariance(False): predict_rate falls back to exp(mu)
    (no lognormal correction) instead of failing."""
    from spark_gp_tpu import GaussianProcessPoissonRegression

    x, y, _ = _count_problem(rng, n=200)
    model = (
        GaussianProcessPoissonRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setActiveSetSize(40)
        .setMaxIter(8)
        .setPredictiveVariance(False)
        .fit(x, y)
    )
    mean, var = model.predict_latent(x[:20])
    assert var is None
    rate = model.predict_rate(x[:20])
    np.testing.assert_allclose(rate, np.exp(mean), rtol=1e-12)


def test_poisson_device_sharded_matches_single_device(rng, eight_device_mesh):
    """The one-dispatch sharded generic-Laplace fit (VERDICT r3 item 3):
    same theta as the single-device device fit, up to reduction order."""
    from spark_gp_tpu import GaussianProcessPoissonRegression

    x, y, rate = _count_problem(rng)

    def make(mesh=None):
        gp = (
            GaussianProcessPoissonRegression()
            .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
            .setDatasetSizeForExpert(50)
            .setActiveSetSize(60)
            .setMaxIter(15)
            .setOptimizer("device")
        )
        if mesh is not None:
            gp.setMesh(mesh)
        return gp

    m_plain = make().fit(x, y)
    m_sharded = make(eight_device_mesh).fit(x, y)
    # rtol 1e-3, not 1e-5: the two fits differ only in psum reduction
    # order, but the LBFGSB Cauchy-point path takes DISCRETE branch
    # decisions (segment hit vs advance), so ulp-level value differences
    # can legitimately fork the iterate path; both end within tol of the
    # same optimum.
    np.testing.assert_allclose(
        m_sharded.raw_predictor.theta, m_plain.raw_predictor.theta, rtol=1e-3
    )
    rel = np.mean(np.abs(m_sharded.predict_rate(x) - rate) / rate)
    assert rel < 0.25, rel


def test_poisson_device_checkpointed_resume(rng, tmp_path):
    """Segmented device fit with checkpointing: a run killed mid-way resumes
    from the persisted L-BFGS state (incl. latent warm-start stack) and
    reaches the one-shot theta (VERDICT r3 item 3)."""
    from spark_gp_tpu import GaussianProcessPoissonRegression

    x, y, _ = _count_problem(rng, n=300)

    def gp(d):
        return (
            GaussianProcessPoissonRegression()
            .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
            .setDatasetSizeForExpert(50)
            .setActiveSetSize(50)
            .setMaxIter(15)
            .setOptimizer("device")
            .setCheckpointDir(str(d))
            .setCheckpointInterval(4)
        )

    theta_full = gp(tmp_path / "a").fit(x, y).raw_predictor.theta
    gp(tmp_path / "b").setMaxIter(3).fit(x, y)  # "killed" after 3 iters
    resumed = gp(tmp_path / "b").fit(x, y)
    np.testing.assert_allclose(
        resumed.raw_predictor.theta, theta_full, rtol=1e-4
    )


def test_poisson_device_sharded_checkpointed(rng, tmp_path, eight_device_mesh):
    """Segmented checkpointing composes with the sharded generic loop."""
    from spark_gp_tpu import GaussianProcessPoissonRegression

    x, y, _ = _count_problem(rng, n=300)

    def gp(ck=None):
        g = (
            GaussianProcessPoissonRegression()
            .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
            .setDatasetSizeForExpert(50)
            .setActiveSetSize(50)
            .setMaxIter(12)
            .setOptimizer("device")
            .setMesh(eight_device_mesh)
        )
        if ck is not None:
            g.setCheckpointDir(str(ck)).setCheckpointInterval(5)
        return g

    theta_ck = gp(tmp_path).fit(x, y).raw_predictor.theta
    theta_plain = gp().fit(x, y).raw_predictor.theta
    np.testing.assert_allclose(theta_ck, theta_plain, rtol=1e-5)


def test_negative_binomial_closed_forms_and_poisson_limit(rng):
    """NB closed-form grad/W vs the base autodiff derivation, and the
    r -> inf limit recovering the Poisson likelihood (both objective and
    derivatives)."""
    from spark_gp_tpu.models.laplace_generic import NegativeBinomialLikelihood

    f = jnp.asarray(rng.normal(size=(2, 6)))
    y = jnp.asarray(rng.integers(0, 9, size=(2, 6)).astype(np.float64))
    lik = NegativeBinomialLikelihood(3.5)
    g_c, w_c = lik.grad_hess(f, y)
    g_a, w_a = Likelihood.grad_hess(lik, f, y)
    np.testing.assert_allclose(np.asarray(g_a), np.asarray(g_c), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(w_a), np.asarray(w_c), rtol=1e-10)
    assert np.all(np.asarray(w_c) > 0)  # log-concave
    with pytest.raises(ValueError, match="positive"):
        NegativeBinomialLikelihood(0.0)

    # Poisson limit: r -> inf
    big = NegativeBinomialLikelihood(1e8)
    pois = PoissonLikelihood()
    g_b, w_b = big.grad_hess(f, y)
    g_p, w_p = pois.grad_hess(f, y)
    np.testing.assert_allclose(np.asarray(g_b), np.asarray(g_p), atol=1e-6)
    np.testing.assert_allclose(np.asarray(w_b), np.asarray(w_p), rtol=1e-6)


def test_negative_binomial_mode_matches_dense_oracle(rng):
    """Laplace mode under the NB likelihood vs a dense f64 Newton oracle
    written directly from the NB derivatives (no shared structure)."""
    from spark_gp_tpu.models.laplace_generic import NegativeBinomialLikelihood

    n, r = 14, 2.0
    x, y = _problem(rng, n=n)
    kernel = RBFKernel(0.9) + Const(1e-2) * EyeKernel()
    theta = jnp.asarray(np.array([0.9]))
    kmat = _gram_stack(kernel, theta, jnp.asarray(x[None]), jnp.ones((1, n)))
    f_hat, _ = laplace_generic_mode(
        NegativeBinomialLikelihood(r), kmat, jnp.asarray(y[None]),
        jnp.ones((1, n)), jnp.zeros((1, n)), 1e-12,
    )

    k = np.asarray(kmat[0])
    f = np.zeros(n)
    for _ in range(500):
        s = 1.0 / (1.0 + np.exp(-(f - np.log(r))))
        grad = y - (y + r) * s
        w = (y + r) * s * (1.0 - s)
        f_new = k @ np.linalg.solve(
            np.eye(n) + np.diag(w) @ k, w * f + grad
        )
        if np.max(np.abs(f_new - f)) < 1e-13:
            f = f_new
            break
        f = f_new
    np.testing.assert_allclose(np.asarray(f_hat[0]), f, atol=1e-9)


def test_negative_binomial_estimator_on_overdispersed_counts(rng):
    """End-to-end on gamma-Poisson (= NB) data with heavy overdispersion:
    the NB estimator must recover the latent rate; its Poisson-limit
    sibling on the same data is the baseline it should not lose to."""
    from spark_gp_tpu import GaussianProcessNegativeBinomialRegression

    n, r = 600, 2.0
    x = np.linspace(0, 4, n)[:, None]
    rate = np.exp(1.0 + np.sin(2 * x[:, 0]))
    # NB sampling as a gamma-Poisson mixture with shape r
    lam = rate * rng.gamma(shape=r, scale=1.0 / r, size=n)
    y = rng.poisson(lam).astype(np.float64)

    model = (
        GaussianProcessNegativeBinomialRegression(dispersion=r)
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setActiveSetSize(60)
        .setMaxIter(20)
        .fit(x, y)
    )
    assert model.instr is not None
    rel = np.mean(np.abs(model.predict_rate(x) - rate) / rate)
    assert rel < 0.3, rel
    assert (
        GaussianProcessNegativeBinomialRegression()
        .setDispersion(5.0)
        .getDispersion()
        == 5.0
    )
