"""Theta-invariant precompute plane (ISSUE 8): cached-vs-recomputed parity.

The contract under test (kernels/base.py): for every kernel declaring
``prepare``, ``gram_from_cache(theta, prepare(x))`` must reproduce
``gram(theta, x)`` — and every fit objective fed a cache must produce the
same NLL/gradient/optimum as the per-evaluation rebuild, while never
touching ``kernel.gram`` inside the differentiated hot loop.  Kernels
without an invariant (ARD, custom) must keep today's programs untouched.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spark_gp_tpu.kernels.base import (
    Const,
    EyeKernel,
    ThetaOverrideKernel,
    masked_gram_stack,
    prepare_gram_cache,
    supports_gram_cache,
)
from spark_gp_tpu.kernels.families import (
    DotProductKernel,
    PeriodicKernel,
    PolynomialKernel,
    RationalQuadraticKernel,
    SpectralMixtureKernel,
)
from spark_gp_tpu.kernels.matern import (
    ARDMatern32Kernel,
    Matern12Kernel,
    Matern32Kernel,
    Matern52Kernel,
)
from spark_gp_tpu.kernels.rbf import ARDRBFKernel, RBFKernel
from spark_gp_tpu.models.likelihood import batched_nll, make_value_and_grad
from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts

P_DIM = 3

# every shipped kernel family with a theta-invariant structure, plus the
# composition algebra around them (noise-augmented sums, trainable scale,
# products, theta overrides)
CACHED_KERNELS = {
    "rbf": lambda: RBFKernel(0.6, 1e-6, 10.0),
    "matern12": lambda: Matern12Kernel(0.8),
    "matern32": lambda: Matern32Kernel(0.8),
    "matern52": lambda: Matern52Kernel(0.8),
    "rq": lambda: RationalQuadraticKernel(0.7, 1.3),
    "dot": lambda: DotProductKernel(0.5),
    "poly": lambda: PolynomialKernel(2, 0.8),
    "sum_noise": lambda: 1.0 * RBFKernel(0.6, 1e-6, 10.0)
    + Const(1e-2) * EyeKernel(),
    "product": lambda: RBFKernel(0.9) * Matern32Kernel(1.1),
    "scaled_sum": lambda: Const(0.5) * (
        Matern52Kernel(0.7) + 2.0 * RationalQuadraticKernel(1.0, 2.0)
    ),
    "override": lambda: ThetaOverrideKernel(
        1.0 * RBFKernel(0.6, 1e-6, 10.0) + Const(1e-2) * EyeKernel(),
        [1.7, 0.45],
    ),
}

# kernels that must DECLINE the plane (theta-dependent distances / maps)
UNCACHED_KERNELS = {
    "ard_rbf": lambda: ARDRBFKernel(P_DIM),
    "ard_matern": lambda: ARDMatern32Kernel(P_DIM),
    "periodic": lambda: PeriodicKernel(1.0, 1.0),
    "spectral": lambda: SpectralMixtureKernel(P_DIM, q=2),
    "mixed_sum": lambda: RBFKernel(0.6) + 1.0 * ARDRBFKernel(P_DIM),
}


def _stack(n=160, s=40, dtype=np.float64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, P_DIM))
    y = np.sin(x.sum(axis=1))
    return group_for_experts(x, y, s, dtype=dtype)


def _theta(kernel, dtype):
    t = np.asarray(kernel.init_theta(), dtype=np.float64)
    # nudge off the init point so scale coefficients are not exactly 1
    t = t * (1.0 + 0.1 * np.arange(1, t.shape[0] + 1))
    return jnp.asarray(t, dtype=dtype)


@pytest.mark.parametrize("name", sorted(CACHED_KERNELS))
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_cached_gram_nll_grad_parity(name, dtype):
    """gram / NLL / gradient from the cache match the rebuild: to float
    noise in f32 (<= 1e-6 relative) and exactly in f64 — the cached path
    runs the same arithmetic minus the re-contraction."""
    kernel = CACHED_KERNELS[name]()
    assert supports_gram_cache(kernel)
    ctx = jax.enable_x64() if dtype == np.float64 else _nullcontext()
    with ctx:
        data = _stack(dtype=dtype)
        theta = _theta(kernel, data.x.dtype)
        cache = prepare_gram_cache(kernel, data.x)
        assert cache is not None

        g_cached = masked_gram_stack(kernel, theta, data.x, data.mask, cache)
        g_plain = masked_gram_stack(kernel, theta, data.x, data.mask)
        tol = 0.0 if dtype == np.float64 else 1e-6
        np.testing.assert_allclose(
            np.asarray(g_cached), np.asarray(g_plain), rtol=tol, atol=tol
        )

        # the model kernel may lack a ridge (pure RBF/Matérn grams are
        # singular-ish at coincident-free data they are fine) — add noise
        noisy = kernel + Const(1e-2) * EyeKernel()
        theta_n = jnp.asarray(theta, dtype=data.x.dtype)
        cache_n = prepare_gram_cache(noisy, data.x)
        v_c, g_c = make_value_and_grad(noisy, data, cache=cache_n)(theta_n)
        v_u, g_u = make_value_and_grad(noisy, data)(theta_n)
        rtol = 0.0 if dtype == np.float64 else 1e-6
        np.testing.assert_allclose(float(v_c), float(v_u), rtol=max(rtol, 0))
        np.testing.assert_allclose(
            np.asarray(g_c), np.asarray(g_u), rtol=rtol, atol=rtol
        )


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


@pytest.mark.parametrize("name", sorted(UNCACHED_KERNELS))
def test_prepare_none_fallback(name):
    """Kernels without an invariant decline the plane: prepare is None,
    prepare_gram_cache returns None, and the uncached objective runs."""
    kernel = UNCACHED_KERNELS[name]()
    assert kernel.prepare is None
    assert not supports_gram_cache(kernel)
    data = _stack()
    assert prepare_gram_cache(kernel, data.x) is None
    theta = _theta(kernel, data.x.dtype)
    noisy = kernel + Const(1e-2) * EyeKernel()
    assert noisy.prepare is None  # composites inherit the opt-out
    v, g = make_value_and_grad(noisy, data)(
        jnp.asarray(np.asarray(noisy.init_theta()), dtype=data.x.dtype)
    )
    assert np.isfinite(float(v))
    assert np.all(np.isfinite(np.asarray(g)))


def test_gram_cache_kill_switch(monkeypatch):
    """GP_GRAM_CACHE=0 disables the plane process-wide."""
    kernel = 1.0 * RBFKernel(0.6, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    data = _stack()
    monkeypatch.setenv("GP_GRAM_CACHE", "0")
    assert not supports_gram_cache(kernel)
    assert prepare_gram_cache(kernel, data.x) is None
    monkeypatch.delenv("GP_GRAM_CACHE")
    assert supports_gram_cache(kernel)


class _GramForbiddenRBF(RBFKernel):
    """RBF whose ``gram`` refuses to trace: proves the cached objective
    never routes through the raw gram build.  ``prepare``/``cross``/
    ``gram_from_cache`` are inherited untouched."""

    def gram(self, theta, x):
        raise AssertionError(
            "kernel.gram was called inside a cached fit objective"
        )


def test_no_gram_call_inside_cached_objective():
    """The lint-style contract of the ISSUE: with a cache available, no
    fit entry point evaluates ``kernel.gram`` inside the differentiated
    objective — asserted by tracing the cached programs with a kernel
    whose ``gram`` raises."""
    from spark_gp_tpu.models.laplace import batched_neg_logz
    from spark_gp_tpu.models.loo import batched_loo_nll

    kernel = (
        1.0 * _GramForbiddenRBF(0.6, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    )
    data = _stack()
    theta = jnp.asarray(
        np.asarray(kernel.init_theta()), dtype=data.x.dtype
    )
    cache = prepare_gram_cache(kernel, data.x)
    assert cache is not None
    # marginal NLL + gradient (the GPR hot loop)
    v, g = make_value_and_grad(kernel, data, cache=cache)(theta)
    assert np.isfinite(float(v))
    # LOO objective
    v_loo = jax.jit(
        lambda t: batched_loo_nll(kernel, t, data, cache=cache),
        static_argnums=(),
    )(theta)
    assert np.isfinite(float(v_loo))
    # Laplace objective (gram stack + dK/dtheta jacobian both cached)
    y01 = (np.asarray(data.y) > 0).astype(np.float64)
    data_b = ExpertData(
        x=data.x, y=jnp.asarray(y01, data.x.dtype), mask=data.mask
    )
    nll, grad, _ = batched_neg_logz(
        kernel, 1e-6, theta, data_b, jnp.zeros_like(data_b.y), cache
    )
    assert np.isfinite(float(nll))
    assert np.all(np.isfinite(np.asarray(grad)))
    # and WITHOUT a cache the guard actually bites (the test tests itself)
    with pytest.raises(AssertionError, match="cached fit objective"):
        make_value_and_grad(kernel, data)(theta)


def test_jitter_operand_rides_cached_objective():
    """The resilience layer's adaptive-jitter retries re-dispatch the SAME
    cached program with a traced jitter operand: values must match the
    uncached jittered objective, and the cache is reused verbatim."""
    kernel = 1.0 * RBFKernel(0.6, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    data = _stack()
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=data.x.dtype)
    cache = prepare_gram_cache(kernel, data.x)
    jitter = jnp.full((data.x.shape[0],), 1e-4, data.x.dtype)
    v_c = batched_nll(kernel, theta, data, jitter, cache=cache)
    v_u = batched_nll(kernel, theta, data, jitter)
    np.testing.assert_allclose(float(v_c), float(v_u), rtol=1e-6)


def _gpr(optimizer, restarts=1, **kw):
    from spark_gp_tpu import GaussianProcessRegression

    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.5, 1e-6, 10.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(24)
        .setSigma2(1e-3)
        .setSeed(3)
        .setMaxIter(12)
        .setOptimizer(optimizer)
    )
    if restarts > 1:
        gp = gp.setNumRestarts(restarts)
    return gp


def _fit_theta(gp, x, y, enabled):
    prev = os.environ.get("GP_GRAM_CACHE")
    os.environ["GP_GRAM_CACHE"] = "1" if enabled else "0"
    try:
        model = gp.fit(x, y)
    finally:
        if prev is None:
            os.environ.pop("GP_GRAM_CACHE", None)
        else:
            os.environ["GP_GRAM_CACHE"] = prev
    assert model.instr.metrics.get("gram_cache_engaged") == float(enabled)
    return np.asarray(model.raw_predictor.theta)


@pytest.mark.parametrize("optimizer", ["host", "device"])
def test_fit_theta_parity_cached_vs_uncached(optimizer):
    """End-to-end: the fitted optimum is identical (<= 1e-6) with the
    plane on vs off, on both optimizer paths."""
    rng = np.random.default_rng(5)
    x = rng.normal(size=(200, P_DIM))
    y = np.sin(x.sum(axis=1))
    t_on = _fit_theta(_gpr(optimizer), x, y, True)
    t_off = _fit_theta(_gpr(optimizer), x, y, False)
    np.testing.assert_allclose(t_on, t_off, atol=1e-6)


def test_multistart_shares_one_cache():
    """The batched device multi-start broadcasts ONE cache across the R
    vmapped lanes (it is closed over, not vmapped) and lands on the same
    winner as the uncached run."""
    rng = np.random.default_rng(7)
    x = rng.normal(size=(200, P_DIM))
    y = np.sin(x.sum(axis=1))
    t_on = _fit_theta(_gpr("device", restarts=3), x, y, True)
    t_off = _fit_theta(_gpr("device", restarts=3), x, y, False)
    np.testing.assert_allclose(t_on, t_off, atol=1e-6)


def test_quarantine_retry_rebuilds_cache():
    """A poisoned expert fit completes on the cached path: the pre-fit
    screen (or the recovery driver) quarantines it, the cache tracks the
    repaired stack, and the result matches the uncached recovery."""
    from spark_gp_tpu.resilience.chaos import poison_expert
    from spark_gp_tpu.parallel.experts import num_experts_for

    rng = np.random.default_rng(11)
    x = rng.normal(size=(200, P_DIM))
    y = np.sin(x.sum(axis=1))
    e = num_experts_for(x.shape[0], 40)
    xq, yq = poison_expert(x, y, expert=1, num_experts=e, kind="nan", seed=0)
    t_on = _fit_theta(_gpr("host"), xq, yq, True)
    t_off = _fit_theta(_gpr("host"), xq, yq, False)
    np.testing.assert_allclose(t_on, t_off, atol=1e-6)


def test_ard_program_identity_unchanged():
    """ARD (prepare=None) fits hand the SAME jitted program a ``None``
    cache whether the plane is enabled or not: toggling GP_GRAM_CACHE
    must not add a compile cache entry (the acceptance criterion's
    byte-identical-programs / no-compile-regression check)."""
    from spark_gp_tpu.models.likelihood import _vag_impl

    kernel = 1.0 * ARDRBFKernel(P_DIM) + Const(1e-2) * EyeKernel()
    data = _stack(seed=13)
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=data.x.dtype)
    before = _vag_impl._cache_size()
    v1, _ = make_value_and_grad(kernel, data)(theta)
    after_first = _vag_impl._cache_size()
    prev = os.environ.get("GP_GRAM_CACHE")
    os.environ["GP_GRAM_CACHE"] = "0"
    try:
        cache = prepare_gram_cache(kernel, data.x)
        assert cache is None
        v2, _ = make_value_and_grad(kernel, data, cache=cache)(theta)
    finally:
        if prev is None:
            os.environ.pop("GP_GRAM_CACHE", None)
        else:
            os.environ["GP_GRAM_CACHE"] = prev
    # second call re-used the first call's executable: no new entry
    assert _vag_impl._cache_size() == after_first
    assert after_first >= before
    np.testing.assert_allclose(float(v1), float(v2), rtol=0, atol=0)


def test_cache_memory_is_one_distance_stack():
    """The documented memory cost: for the noise-augmented isotropic model
    kernel the cache is one [E, s, s] block plus a zero-byte Eye carrier
    (docs/ROOFLINE.md)."""
    kernel = 1.0 * RBFKernel(0.6, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    data = _stack()
    cache = prepare_gram_cache(kernel, data.x)
    leaves = jax.tree.leaves(cache)
    e, s = data.x.shape[0], data.x.shape[1]
    sizes = sorted(leaf.size for leaf in leaves)
    assert sizes == [0, e * s * s]
