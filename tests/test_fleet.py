"""Multi-replica serving fleet (spark_gp_tpu/serve/fleet.py + router.py):
consistent-hash ring, generation-stamped membership + heartbeat verdicts,
per-request failover with bounded jittered retry, hedged re-dispatch,
drain-aware rebalancing, fleet-wide canary, and router restart recovery.

Router logic is proven against scripted stub transports (no jax, no real
waiting: clock and sleep are injectable); the end-to-end legs run real
:class:`GPServeServer` replicas over an in-process KV store — the same
rig the chaos soak (``tools/soak.py`` fleet_* scenarios) and bench's
``fleet`` section drive.
"""

import threading
import time

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.parallel.coord import (
    InProcessCoordClient,
    InProcessCoordStore,
)
from spark_gp_tpu.resilience import chaos
from spark_gp_tpu.serve import GPServeServer
from spark_gp_tpu.serve.fleet import (
    FleetCanary,
    FleetMembership,
    HashRing,
    LocalReplica,
)
from spark_gp_tpu.serve.lifecycle import DrainingError
from spark_gp_tpu.serve.queue import ServeFuture
from spark_gp_tpu.serve.router import (
    FailoverExhaustedError,
    FleetRouter,
    NoReplicasError,
    ReplicaUnreachableError,
    RouterDeadlineError,
    failover_eligible,
)

pytestmark = pytest.mark.chaos


class FakeClock:
    """Injectable clock whose sleep advances time (the coord-test idiom):
    deadlines and hedge timers resolve without real waiting."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


class StubTransport:
    """Scripted replica transport: ``script`` maps call index -> one of
    ``"ok"`` (answer), an exception instance (raised at submit), a
    future-side exception wrapped in ``("error", exc)``, or ``"hang"``
    (a future that never completes)."""

    def __init__(self, replica_id, script=None, health=None):
        self.replica_id = replica_id
        self.script = script or {}
        self.default = self.script.pop("default", "ok")
        self._health = health or {"queue": {"pressure": 0.1}, "lifecycle": {}}
        self.calls = []

    def submit(self, model, x, timeout_ms=None, request_id=None,
               priority=0, version=None, observable=True):
        action = self.script.get(len(self.calls), self.default)
        self.calls.append((model, request_id))
        if isinstance(action, BaseException):
            raise action
        future = ServeFuture()
        if action == "hang":
            return future
        if isinstance(action, tuple) and action[0] == "error":
            future.set_error(action[1])
            return future
        rows = np.asarray(x).shape[0]
        future.set_result((np.full(rows, 7.0), np.full(rows, 0.5)))
        return future

    def health(self):
        return self._health

    def close(self):
        pass


def _membership(store=None, **kw):
    defaults = dict(
        fleet="t", interval_s=0.05, straggler_after_s=0.15,
        dead_after_s=0.35,
    )
    defaults.update(kw)
    return FleetMembership(
        InProcessCoordClient(store or InProcessCoordStore(), 0, 1),
        **defaults,
    )


def _router(membership, transports, clock=None, **kw):
    defaults = dict(
        max_batch=16, min_bucket=8, default_timeout_ms=2_000.0,
        poll_interval_s=0.0, backoff_s=0.001,
    )
    defaults.update(kw)
    if clock is not None:
        defaults.update(clock=clock, sleep=clock.sleep)
    return FleetRouter(membership, transports, **defaults)


# -- hash ring -------------------------------------------------------------


def test_ring_is_deterministic_and_orders_distinct_owners():
    nodes = ["r0", "r1", "r2", "r3"]
    a, b = HashRing(nodes), HashRing(list(reversed(nodes)))
    for key in ("m/8", "m/16", "other/8"):
        order = a.owners(key)
        assert order == b.owners(key)  # stable across constructions
        assert sorted(order) == sorted(nodes)  # all distinct replicas
    assert a.owners("m/8", count=2) == a.owners("m/8")[:2]


def test_ring_removal_moves_only_the_removed_nodes_keys():
    nodes = ["r0", "r1", "r2", "r3"]
    full = HashRing(nodes)
    keys = [f"m/{b}" for b in (8, 16, 32, 64)] + [
        f"model{i}/8" for i in range(40)
    ]
    gone = "r1"
    reduced = HashRing([n for n in nodes if n != gone])
    for key in keys:
        before = full.owners(key)[0]
        after = reduced.owners(key)[0]
        if before != gone:
            # consistent hashing: keys not owned by the removed node
            # keep their owner
            assert after == before, key
        else:
            # the removed node's keys land on its successor
            assert after == full.owners(key)[1], key


# -- membership ------------------------------------------------------------


def test_membership_register_generation_and_view():
    m = _membership()
    g1 = m.register("r0", address="127.0.0.1:9000")
    g2 = m.register("r1")
    assert g2 == g1 + 1
    view = m.poll()
    assert view["generation"] == g2
    assert view["live"] == ["r0", "r1"]
    assert view["members"]["r0"]["address"] == "127.0.0.1:9000"
    assert view["members"]["r0"]["pid"] > 0
    m.set_state("r0", "draining")
    view = m.poll()
    assert view["live"] == ["r1"]
    assert view["draining"] == ["r0"]
    m.deregister("r0")
    assert "r0" not in m.poll()["members"]


def test_membership_dead_verdict_and_recovery_fake_clock():
    clock = FakeClock()
    store = InProcessCoordStore()
    client = InProcessCoordClient(store, 0, 1, clock=clock, sleep=clock.sleep)
    m = FleetMembership(
        client, fleet="t", interval_s=1.0,
        straggler_after_s=3.0, dead_after_s=10.0,
    )
    m.register("r0")
    m.register("r1")
    m.poll()
    clock.t += 5.0  # r1 goes quiet past the straggler threshold
    m.heartbeat("r0")
    view = m.poll()
    assert view["stragglers"] == ["r1"]
    assert view["dead"] == []
    clock.t += 7.0  # now past the dead threshold
    m.heartbeat("r0")
    view = m.poll()
    assert view["dead"] == ["r1"]
    assert "r1" not in view["live"]
    m.heartbeat("r1")  # the stamp resumes: recovery
    view = m.poll()
    assert view["dead"] == [] and view["stragglers"] == []
    assert view["live"] == ["r0", "r1"]


def test_deregistered_member_is_not_flagged_dead_by_other_ledgers():
    """A replica that politely deregisters must not age into a false
    dead verdict in ANOTHER process's membership ledger (the router's),
    and churn must not grow that ledger forever."""
    clock = FakeClock()
    store = InProcessCoordStore()

    def view_of():
        return FleetMembership(
            InProcessCoordClient(store, 0, 1, clock=clock,
                                 sleep=clock.sleep),
            fleet="t", interval_s=1.0, straggler_after_s=3.0,
            dead_after_s=10.0,
        )

    writer, router_view = view_of(), view_of()
    writer.register("r0")
    writer.register("r1")
    router_view.poll()
    writer.deregister("r1")  # polite exit — in the WRITER's process
    clock.t += 60.0  # far past the dead threshold
    writer.heartbeat("r0")
    view = router_view.poll()
    assert view["dead"] == []  # no false corpse
    assert router_view.snapshot()["dead"] == []
    assert "r1" not in router_view._ledger.last_seen()  # ledger pruned


def test_generation_bumps_from_concurrent_writers_never_collide():
    """Two replica processes registering 'simultaneously' (each through
    its own membership client) must BOTH advance the generation: the
    marker-count scheme has no lost update to race on."""
    store = InProcessCoordStore()
    a = _membership(store)
    b = _membership(store)
    g1 = a.register("r0")
    g2 = b.register("r1")
    g3 = a.register("r2")
    assert (g1, g2, g3) == (1, 2, 3)
    assert a.poll()["generation"] == 3
    assert b.poll()["generation"] == 3


def test_router_redials_a_dead_transport_through_its_factory():
    """A transport that died must not shadow a restarted replica: the
    re-dial sweep drops the unusable instance and builds a fresh one
    from the member record."""
    m = _membership()
    _registered(m, ["r0"])

    class DyingStub(StubTransport):
        def __init__(self, rid):
            super().__init__(rid)
            self.unusable = False
            self.closed = False

        def close(self):
            self.closed = True

    dialed = []

    def factory(rid, record):
        transport = DyingStub(rid)
        dialed.append(transport)
        return transport

    router = _router(m, {}, transport_factory=factory)
    router.predict("m", np.zeros((2, 3)))
    assert len(dialed) == 1
    dialed[0].unusable = True  # the replica 'dies' and restarts
    router.predict("m", np.zeros((2, 3)))
    assert len(dialed) == 2  # re-dialed a fresh transport
    assert dialed[0].closed and dialed[1].calls


# -- router: failover / hedging / deadline ---------------------------------


def _registered(membership, rids):
    for rid in rids:
        membership.register(rid)


def test_router_fails_over_on_unreachable_owner():
    m = _membership()
    _registered(m, ["r0", "r1", "r2"])
    order = _router(m, {r: StubTransport(r) for r in ["r0", "r1", "r2"]})
    order = order.route("m", 4)
    owner, successor = order[0], order[1]
    transports = {
        rid: StubTransport(
            rid,
            script=(
                {"default": ReplicaUnreachableError(rid)}
                if rid == owner else None
            ),
        )
        for rid in ["r0", "r1", "r2"]
    }
    router = _router(m, transports)
    mean, var = router.predict("m", np.zeros((4, 3)))
    assert mean.shape == (4,)
    assert transports[owner].calls and transports[successor].calls
    assert router.metrics.counter("router.failovers") == 1
    assert router.metrics.counter("router.failed") == 0
    # the re-dispatch reuses the SAME request_id: one logical request
    assert transports[owner].calls[0][1] == transports[successor].calls[0][1]


def test_router_fails_over_on_draining_and_breaker_codes():
    m = _membership()
    _registered(m, ["r0", "r1"])
    probe = _router(m, {r: StubTransport(r) for r in ["r0", "r1"]})
    owner = probe.route("m", 4)[0]
    other = [r for r in ["r0", "r1"] if r != owner][0]
    transports = {
        owner: StubTransport(owner, script={"default": DrainingError()}),
        other: StubTransport(other),
    }
    router = _router(m, transports)
    mean, _ = router.predict("m", np.zeros((2, 3)))
    assert float(mean[0]) == 7.0
    assert router.metrics.counter("router.failovers") == 1


def test_router_does_not_retry_client_errors():
    m = _membership()
    _registered(m, ["r0", "r1"])
    transports = {
        rid: StubTransport(rid, script={"default": ValueError("bad shape")})
        for rid in ["r0", "r1"]
    }
    router = _router(m, transports)
    with pytest.raises(ValueError):
        router.predict("m", np.zeros((2, 3)))
    # no replica beyond the owner was burned on an unretryable error
    assert sum(len(t.calls) for t in transports.values()) == 1
    assert router.metrics.counter("router.failovers") == 0


def test_router_failover_budget_is_bounded():
    m = _membership()
    _registered(m, ["r0", "r1", "r2", "r3"])
    transports = {
        rid: StubTransport(
            rid, script={"default": ReplicaUnreachableError(rid)}
        )
        for rid in ["r0", "r1", "r2", "r3"]
    }
    router = _router(m, transports, failover_attempts=1)
    with pytest.raises(FailoverExhaustedError) as err:
        router.predict("m", np.zeros((2, 3)))
    # 1 + failover_attempts dispatches, not the whole ring
    assert sum(len(t.calls) for t in transports.values()) == 2
    assert len(err.value.attempts) == 2
    assert err.value.code == "router.failover_exhausted"
    assert router.metrics.counter("router.failed") == 1


def test_router_deadline_is_terminal_fake_clock():
    clock = FakeClock()
    store = InProcessCoordStore()
    m = FleetMembership(
        InProcessCoordClient(store, 0, 1, clock=clock, sleep=clock.sleep),
        fleet="t", interval_s=1.0,
    )
    m.register("r0")
    transports = {"r0": StubTransport("r0", script={"default": "hang"})}
    started = time.monotonic()
    router = _router(m, transports, clock=clock, default_timeout_ms=500.0)
    with pytest.raises(RouterDeadlineError) as err:
        router.predict("m", np.zeros((2, 3)))
    assert err.value.code == "router.deadline"
    assert time.monotonic() - started < 5.0  # fake clock: no real wait


def test_router_hedges_around_a_straggler_fake_clock():
    clock = FakeClock()
    store = InProcessCoordStore()
    m = FleetMembership(
        InProcessCoordClient(store, 0, 1, clock=clock, sleep=clock.sleep),
        fleet="t", interval_s=1.0,
    )
    for rid in ("r0", "r1", "r2"):
        m.register(rid)
    probe = _router(
        m, {r: StubTransport(r) for r in ["r0", "r1", "r2"]}, clock=clock
    )
    order = probe.route("m", 4)
    transports = {
        rid: StubTransport(
            rid,
            script={"default": "hang"} if rid == order[0] else None,
        )
        for rid in ["r0", "r1", "r2"]
    }
    router = _router(
        m, transports, clock=clock, hedge_after_s=0.1,
        default_timeout_ms=5_000.0,
    )
    mean, _ = router.predict("m", np.zeros((4, 3)))
    assert float(mean[0]) == 7.0
    assert router.metrics.counter("router.hedges") == 1
    assert router.metrics.counter("router.hedge_wins") == 1
    assert transports[order[0]].calls and transports[order[1]].calls
    # the hedge reused the primary's request_id (one logical request)
    assert transports[order[0]].calls[0][1] == transports[order[1]].calls[0][1]


def test_router_no_replicas_is_classified():
    m = _membership()
    router = _router(m, {})
    with pytest.raises(NoReplicasError) as err:
        router.predict("m", np.zeros((2, 3)))
    assert err.value.code == "router.no_replicas"


def test_failover_eligibility_vocabulary():
    assert failover_eligible(ReplicaUnreachableError("r0"))
    assert failover_eligible(DrainingError())
    assert failover_eligible(RuntimeError("server shut down"))
    assert not failover_eligible(ValueError("bad shape"))
    assert not failover_eligible(KeyError("no model"))


# -- drain-aware rebalancing ----------------------------------------------


def test_draining_replica_leaves_the_ring_before_it_exits():
    m = _membership()
    _registered(m, ["r0", "r1", "r2"])
    transports = {r: StubTransport(r) for r in ["r0", "r1", "r2"]}
    router = _router(m, transports)
    owner = router.route("m", 4)[0]
    m.set_state(owner, "draining")
    assert owner not in router.route("m", 4)  # keys migrated...
    mean, _ = router.predict("m", np.zeros((4, 3)))  # ...and traffic flows
    assert float(mean[0]) == 7.0
    assert not transports[owner].calls
    view = router.snapshot()["view"]
    assert view["draining"] == [owner]


# -- fleet metrics page ----------------------------------------------------


def test_fleet_page_aggregates_scaling_signals():
    m = _membership()
    _registered(m, ["r0", "r1"])
    transports = {
        "r0": StubTransport("r0", health={
            "queue": {"pressure": 0.95},
            "lifecycle": {"memory": {"shedding": False}},
        }),
        "r1": StubTransport("r1", health={
            "queue": {"pressure": 0.9},
            "lifecycle": {"memory": {"shedding": True}},
        }),
    }
    router = _router(m, transports)
    sample = router.sample_fleet()
    assert sample["scale_up"] is True
    assert sample["queue_pressure"]["r0"] == pytest.approx(0.95)
    page = router.openmetrics()
    assert 'gp_fleet_queue_pressure{replica="r0"} 0.95' in page
    assert 'gp_fleet_memory_shedding{replica="r1"} 1' in page
    assert "gp_fleet_scale_up 1" in page
    assert "gp_fleet_replicas_live 2" in page
    assert "gp_router_rebuilds_total" in page
    assert page.rstrip().endswith("# EOF")


# -- router restart --------------------------------------------------------


def test_router_restart_recovers_membership_from_kv():
    store = InProcessCoordStore()
    m = _membership(store)
    _registered(m, ["r0", "r1", "r2"])
    transports = {r: StubTransport(r) for r in ["r0", "r1", "r2"]}
    first = _router(m, transports)
    first.predict("m", np.zeros((4, 3)))
    gen = m.last_known_generation
    # a BRAND-NEW router over the same store, transports re-dialed lazily
    built = []
    second = _router(
        _membership(store), {},
        transport_factory=lambda rid, record: (
            built.append(rid) or transports[rid]
        ),
    )
    view = second.snapshot()["view"]
    assert sorted(view["members"]) == ["r0", "r1", "r2"]
    assert view["generation"] == gen
    assert sorted(built) == ["r0", "r1", "r2"]
    assert second.metrics.counter("router.rebuilds") >= 1
    mean, _ = second.predict("m", np.zeros((4, 3)))
    assert float(mean[0]) == 7.0
    # identical ring: both routers agree on every key's owner
    for bucket in (8, 16):
        assert first.route("m", bucket) == second.route("m", bucket)


# -- end-to-end over real serve replicas -----------------------------------


@pytest.fixture(scope="module")
def fleet_model(tmp_path_factory):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(120, 3))
    y = np.sin(x.sum(axis=1))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(30).setActiveSetSize(30)
        .setMaxIter(5).setSeed(3).fit(x, y)
    )
    path = str(tmp_path_factory.mktemp("fleet") / "fleet.npz")
    model.save(path)
    return path, model, x


def _real_fleet(path, n=3, **server_kw):
    membership = _membership()
    replicas = []
    for i in range(n):
        defaults = dict(
            max_batch=16, min_bucket=8, max_wait_ms=1.0,
            request_timeout_ms=5_000.0, hang_timeout_s=None,
            replica_id=f"r{i}",
        )
        defaults.update(server_kw)
        server = GPServeServer(**defaults)
        server.register("m", path)
        server.start()
        replica = LocalReplica(server, f"r{i}", membership)
        replica.register()
        replicas.append(replica)
    router = FleetRouter(
        membership,
        transports={r.replica_id: r.transport for r in replicas},
        max_batch=16, min_bucket=8, default_timeout_ms=5_000.0,
        poll_interval_s=0.0,
    )
    return membership, replicas, router


def test_fleet_end_to_end_kill_failover_zero_lost(fleet_model):
    path, model, x = fleet_model
    membership, replicas, router = _real_fleet(path)
    by_id = {r.replica_id: r for r in replicas}
    try:
        local = model.predict(x[:4])
        for _ in range(3):
            for r in replicas:
                r.heartbeat()
            mean, _ = router.predict("m", x[:4])
            np.testing.assert_allclose(mean, local, rtol=1e-5, atol=1e-6)
        owner = router.route("m", 4)[0]
        chaos.kill_replica(by_id[owner])
        for _ in range(4):  # mid-burst kill: every request re-routes
            mean, _ = router.predict("m", x[:4])
            np.testing.assert_allclose(mean, local, rtol=1e-5, atol=1e-6)
        assert router.metrics.counter("router.failovers") >= 1
        assert router.metrics.counter("router.failed") == 0
        # the heartbeat verdict evicts the corpse from the ring
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            for r in replicas:
                r.heartbeat()
            if owner in router.rebuild()["dead"]:
                break
            time.sleep(0.05)
        assert owner in router.snapshot()["view"]["dead"]
        assert owner not in router.route("m", 4)
    finally:
        router.close()
        for r in replicas:
            r.stop()


def test_fleet_canary_promotes_only_when_all_replicas_clear(fleet_model):
    path, model, x = fleet_model
    membership, replicas, router = _real_fleet(path)
    servers = {r.replica_id: r.server for r in replicas}
    try:
        canary = FleetCanary(
            membership.client, fleet="t", promote_after=2
        )
        canary.start(servers, "m", path, fraction=1.0)
        # replica identity rode into health for verdict attribution
        health = replicas[0].server.health()
        assert health["replica"]["replica_id"] == "r0"
        assert health["replica"]["fleet"] == "t"
        assert health["replica"]["coord_era"] >= 1
        verdict = None
        for _ in range(6):
            for server in servers.values():
                for i in range(3):
                    server.predict("m", x[i: i + 4], timeout_ms=5_000.0)
            verdict = canary.pump("m", servers)
            if verdict is not None:
                break
        assert verdict == "promote"
        for rid, server in servers.items():
            assert server.registry.get("m").version == 2, rid
            assert server.canaries.active("m") is None, rid
            assert server.metrics.counter("canary.promotions") == 1, rid
        # one replica still scoring would have held the fleet back: the
        # verdict needed EVERY replica above the bar (promote_after=2,
        # so >= 2 clean scores per replica were required and recorded)
        for server in servers.values():
            assert server.metrics.counter("canary.shadow_scores") >= 2
    finally:
        router.close()
        for r in replicas:
            r.stop()


def test_fleet_canary_local_promotion_is_disabled(fleet_model):
    """Under fleet control a replica must never promote on its own: the
    local policy's promote_after is effectively infinite."""
    path, _, x = fleet_model
    membership, replicas, router = _real_fleet(path, n=1)
    servers = {r.replica_id: r.server for r in replicas}
    try:
        canary = FleetCanary(membership.client, fleet="t", promote_after=50)
        canary.start(servers, "m", path, fraction=1.0)
        server = replicas[0].server
        for i in range(8):
            server.predict("m", x[i: i + 4], timeout_ms=5_000.0)
        # plenty of clean scores, yet no local promotion happened
        assert server.canaries.active("m") is not None
        assert server.registry.get("m").version == 1
        assert canary.pump("m", servers) is None  # fleet bar not met either
    finally:
        router.close()
        for r in replicas:
            r.stop()


def test_plain_server_health_carries_replica_identity():
    server = GPServeServer(replica_id="solo-1")
    health = server.health()
    assert health["replica"]["replica_id"] == "solo-1"
    assert health["replica"]["pid"] > 0
    assert "backend" in health["replica"]["build_info"]
    assert health["replica"]["coord_era"] is None  # not fleet-bound


def test_router_is_thread_safe_under_concurrent_clients():
    m = _membership()
    _registered(m, ["r0", "r1", "r2"])
    transports = {r: StubTransport(r) for r in ["r0", "r1", "r2"]}
    router = _router(m, transports)
    errors = []

    def client():
        try:
            for _ in range(20):
                router.predict("m", np.zeros((4, 3)))
        except Exception as exc:  # noqa: BLE001 — collected for assert
            errors.append(exc)

    threads = [threading.Thread(target=client) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not errors
    assert router.metrics.counter("router.requests") == 80
