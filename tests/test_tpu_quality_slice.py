"""On-chip quality slice (VERDICT r4 weak #3 / task #2).

Every recorded TPU bench number rides the Mosaic ``spd_inv_logdet`` kernel
(``ops/pallas_linalg.py:_use_pallas`` routes every f32 fit with s <= 512
through it), so the chip must also carry an ASSERTED quality bar — not just
throughput.  These tests run only under ``GP_TEST_PLATFORM=tpu`` (conftest
skips everything unmarked in tpu mode and fails fast if no chip): the
window watcher (benchmarks/tpu_window_watcher.py) executes them inside
every captured TPU window, writing the pytest tail to
``TPU_WINDOW_TESTS.json``.

Bars mirror the examples' own assertions: synthetics 10-fold CV RMSE
< 0.11 (Synthetics.scala:33, run here at 3 folds for window budget — the
bar is per-fold-mean and fold-count-insensitive on this easy problem),
iris accuracy >= 0.9 (Iris.scala:35-38), and the Poisson (generic
Laplace) rate-recovery relative error < 0.1 (examples/poisson.py).
"""

import jax
import numpy as np
import pytest

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        jax.default_backend() != "tpu",
        reason="on-chip quality bar (f32 hardware path); CPU f64 bars live "
        "in the e2e tests and quality.py",
    ),
]


def test_synthetics_rmse_bar_on_chip():
    from examples.synthetics import make_gp
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import cross_validate, rmse

    x, y = make_synthetics()
    score = cross_validate(make_gp(), x, y, num_folds=3, metric=rmse, seed=13)
    assert np.isfinite(score)
    assert score < 0.11, f"on-chip synthetics RMSE {score} breaches the 0.11 bar"


def test_iris_accuracy_bar_on_chip():
    from examples.iris import make_gpc
    from spark_gp_tpu.data import load_iris
    from spark_gp_tpu.utils.validation import OneVsRest, accuracy, train_validation_split

    x, y = load_iris()
    score = train_validation_split(
        OneVsRest(make_gpc), x, y, train_ratio=0.8, metric=accuracy, seed=5,
    )
    assert score >= 0.9, f"on-chip iris OvR accuracy {score} below the 0.9 bar"


def test_airfoil_rmse_bar_on_chip():
    """The reference's HEADLINE quality contract on hardware (VERDICT
    next #6): airfoil 5-feature ARD config (Airfoil.scala:9-33, the
    examples/airfoil.py setup verbatim) must hold its RMSE < 2.1 bar on
    the f32 chip path — 3 folds instead of the example's 10 for window
    budget (the bar is a per-fold-mean; the CPU f64 10-fold twin lives in
    bench.py's airfoil extra and examples/airfoil.py)."""
    from spark_gp_tpu import (
        ARDRBFKernel,
        Const,
        EyeKernel,
        GaussianProcessRegression,
    )
    from spark_gp_tpu.data import load_airfoil
    from spark_gp_tpu.ops.scaling import scale
    from spark_gp_tpu.utils.validation import cross_validate, rmse

    x, y = load_airfoil()
    x = np.asarray(scale(x))
    gp = (
        GaussianProcessRegression()
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(1000)
        .setSigma2(1e-4)
        .setKernel(lambda: 1.0 * ARDRBFKernel(5) + Const(1.0) * EyeKernel())
        .setSeed(13)
    )
    score = cross_validate(gp, x, y, num_folds=3, metric=rmse, seed=13)
    assert np.isfinite(score)
    assert score < 2.1, f"on-chip airfoil RMSE {score} breaches the 2.1 bar"


def test_poisson_rate_recovery_on_chip():
    """Generic-likelihood Laplace on hardware: the Poisson regressor must
    recover a known rate surface within the example's own 0.1 bar
    (examples/poisson.py config via its shared factory; smaller n for
    window budget)."""
    from examples.poisson import make_poisson_gp

    rng = np.random.default_rng(42)
    n = 800
    x = np.linspace(0, 4, n)[:, None]
    rate = np.exp(1.0 + np.sin(2 * x[:, 0]))
    y = rng.poisson(rate).astype(np.float64)
    model = make_poisson_gp().fit(x, y)
    rel = float(np.mean(np.abs(model.predict_rate(x) - rate) / rate))
    assert rel < 0.1, f"on-chip poisson rate error {rel} breaches the 0.1 bar"
