"""Projected Process Approximation vs a dense oracle.

The reference never unit-tests this algebra (SURVEY.md §4); here every piece
is checked against a straight dense-numpy transcription of
ProjectedGaussianProcessHelper.scala / R&W ch. 8.3.4 formulas.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels import Const, EyeKernel, RBFKernel
from spark_gp_tpu.models import ppa
from spark_gp_tpu.models.ppa import ProjectedProcessRawPredictor
from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException
from spark_gp_tpu.parallel.experts import group_for_experts


@pytest.fixture
def setup(rng):
    n, p, m = 80, 2, 12
    x = rng.normal(size=(n, p))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    active = x[rng.choice(n, m, replace=False)]
    sigma2 = 1e-2
    kernel = RBFKernel(1.0) + Const(sigma2) * EyeKernel()
    theta = kernel.init_theta()
    return x, y, active, kernel, theta, sigma2


def _dense_cross(kernel, theta, a, x):
    return np.asarray(kernel.cross(jnp.asarray(theta), jnp.asarray(a), jnp.asarray(x)))


def test_kmn_stats_match_dense(setup):
    x, y, active, kernel, theta, _ = setup
    data = group_for_experts(x, y, dataset_size_for_expert=17)
    u1, u2 = ppa.kmn_stats(
        kernel, jnp.asarray(theta), jnp.asarray(active), data
    )
    kmn = _dense_cross(kernel, theta, active, x)  # [m, n]
    np.testing.assert_allclose(np.asarray(u1), kmn @ kmn.T, rtol=1e-9)
    np.testing.assert_allclose(np.asarray(u2), kmn @ y, rtol=1e-9)


def test_magic_solve_matches_dense_formulas(setup):
    x, y, active, kernel, theta, sigma2 = setup
    kmn = _dense_cross(kernel, theta, active, x)
    u1, u2 = kmn @ kmn.T, kmn @ y
    magic_vector, magic_matrix = ppa.magic_solve(kernel, theta, active, u1, u2)

    # dense oracle — PGPH.scala:49-60 with the noise-augmented K_mm
    kmm = np.asarray(kernel.gram(jnp.asarray(theta), jnp.asarray(active)))
    sn2 = float(np.asarray(kernel.white_noise_var(jnp.asarray(theta))))
    assert sn2 == pytest.approx(sigma2)
    pd = sn2 * kmm + u1
    np.testing.assert_allclose(magic_vector, np.linalg.solve(pd, u2), rtol=1e-8)
    np.testing.assert_allclose(
        magic_matrix,
        sn2 * np.linalg.inv(pd) - np.linalg.inv(kmm),
        rtol=1e-7,
        atol=1e-10,
    )


def test_predictor_mean_var_match_dense(setup):
    x, y, active, kernel, theta, _ = setup
    kmn = _dense_cross(kernel, theta, active, x)
    magic_vector, magic_matrix = ppa.magic_solve(
        kernel, theta, active, kmn @ kmn.T, kmn @ y
    )
    raw = ProjectedProcessRawPredictor(
        kernel=kernel,
        theta=theta,
        active=active,
        magic_vector=magic_vector,
        magic_matrix=magic_matrix,
    )
    x_test = x[:7]
    mean, var = raw(x_test)
    cross = _dense_cross(kernel, theta, x_test, active)  # [t, m]
    self_k = np.asarray(
        kernel.self_diag(jnp.asarray(theta), jnp.asarray(x_test))
    )
    np.testing.assert_allclose(np.asarray(mean), cross @ magic_vector, rtol=1e-8)
    np.testing.assert_allclose(
        np.asarray(var),
        self_k + np.einsum("tm,mk,tk->t", cross, magic_matrix, cross),
        rtol=1e-7,
    )


def test_ppa_approaches_exact_gp_as_active_grows(rng):
    """With the active set = the full training set, the PPA posterior mean
    approaches the exact GP posterior mean.  Not exactly: the reference (and
    we, for parity) use the noise-augmented K_mm in the normal equations
    (PGPH.scala:54-55), which perturbs the system by O(sigma^4) relative to
    R&W 8.3.4 — hence the loose tolerance."""
    n, p = 40, 1
    x = np.linspace(0, 1, n).reshape(n, 1)
    y = np.sin(3 * x[:, 0]) + 0.01 * rng.normal(size=n)
    sigma2 = 1e-2
    kernel = RBFKernel(0.3) + Const(sigma2) * EyeKernel()
    theta = kernel.init_theta()

    kmn = _dense_cross(kernel, theta, x, x)
    magic_vector, _ = ppa.magic_solve(kernel, theta, x, kmn @ kmn.T, kmn @ y)
    raw = ProjectedProcessRawPredictor(
        kernel=kernel, theta=theta, active=x,
        magic_vector=magic_vector, magic_matrix=np.zeros((n, n)),
    )
    mean, _ = raw(x)

    # exact GP: K_noisy^-1 y against the *noiseless* cross kernel
    k_noisy = np.asarray(kernel.gram(jnp.asarray(theta), jnp.asarray(x)))
    cross_nf = _dense_cross(kernel, theta, x, x)  # Eye contributes 0 cross
    exact_mean = cross_nf @ np.linalg.solve(k_noisy, y)
    np.testing.assert_allclose(np.asarray(mean), exact_mean, rtol=5e-3, atol=5e-3)


def test_non_pd_raises_with_advice(setup):
    x, y, active, kernel, theta, _ = setup
    u1 = -np.eye(active.shape[0])  # force a non-PD system
    with pytest.raises(NotPositiveDefiniteException, match="sigma2"):
        ppa.magic_solve(kernel, theta, active, u1, np.zeros(active.shape[0]))


def test_magic_solve_device_matches_host(rng):
    """The device f64 solver (large-m path) must agree with the host numpy
    solver to f64 round-off."""
    m = 300
    kernel = RBFKernel(1.5) + Const(1e-3) * EyeKernel()
    theta = kernel.init_theta()
    active = rng.normal(size=(m, 3))
    b = rng.normal(size=(m, m)) / np.sqrt(m)
    u1 = b @ b.T * m * 0.01
    u2 = rng.normal(size=m)

    mv_host, mm_host = ppa.magic_solve(kernel, theta, active, u1, u2)
    mv_dev, mm_dev = ppa.magic_solve_device(kernel, theta, active, u1, u2)
    np.testing.assert_allclose(mv_dev, mv_host, rtol=1e-9, atol=1e-11)
    np.testing.assert_allclose(mm_dev, mm_host, rtol=1e-7, atol=1e-9)


def test_magic_solve_device_non_pd_raises(rng):
    """Jitter escalation exhausts -> the reference's advice-bearing error
    (PGPH.scala:9-11) from the device path too."""
    m = 64
    kernel = RBFKernel(1.5) + Const(1e-3) * EyeKernel()
    active = rng.normal(size=(m, 3))
    u1 = -1e6 * np.eye(m)  # violently indefinite PD matrix
    with pytest.raises(NotPositiveDefiniteException):
        ppa.magic_solve_device(kernel, kernel.init_theta(), active, u1, np.zeros(m))


def test_large_m_ppa_on_virtual_mesh(rng, eight_device_mesh):
    """m=4096 end-to-end PPA stage on the 8-device mesh: sharded (U1, u2)
    assembly feeding the device magic solve (the m >= _DEVICE_SOLVE_MIN_M
    dispatch), finite predictions out (SURVEY §2.3 TP row; VERDICT r2
    missing #3)."""
    from spark_gp_tpu.parallel.mesh import shard_experts

    m, n, p = 4096, 4608, 3
    x = rng.normal(size=(n, p))
    y = np.sin(x.sum(axis=1))
    kernel = RBFKernel(1.5) + Const(1e-2) * EyeKernel()
    theta = jnp.asarray(kernel.init_theta())
    data = shard_experts(group_for_experts(x, y, 64), eight_device_mesh)
    active = x[rng.choice(n, size=m, replace=False)]

    import jax

    with jax.enable_x64():
        stats = ppa.make_sharded_kmn_stats(kernel, eight_device_mesh)
        u1, u2 = stats(theta, jnp.asarray(active), data)
        u1, u2 = np.asarray(u1), np.asarray(u2)
    assert u1.shape == (m, m)

    assert m >= ppa._DEVICE_SOLVE_MIN_M  # exercises the large-m dispatch
    # (no mesh -> replicated device solver; the mesh-sharded solver is
    # parity-tested in test_sharded_magic_solve_matches_host — running it
    # at m=4096 on the CPU-emulated mesh costs ~4 min for no extra signal)
    mv, mm = ppa.magic_solve(kernel, kernel.init_theta(), active, u1, u2)
    raw = ProjectedProcessRawPredictor(
        kernel=kernel,
        theta=np.asarray(kernel.init_theta(), dtype=np.float64),
        active=np.asarray(active, dtype=np.float64),
        magic_vector=mv,
        magic_matrix=mm,
    )
    mean, var = raw(x[:128])
    mean, var = np.asarray(mean), np.asarray(var)
    assert np.all(np.isfinite(mean)) and np.all(np.isfinite(var))
    # the m-point projection of a 4.6k-row smooth function should interpolate
    assert float(np.sqrt(np.mean((mean - y[:128]) ** 2))) < 0.15


def test_sharded_magic_solve_matches_host(rng, eight_device_mesh):
    """The mesh-sharded large-m solver (distributed blocked Cholesky) must
    agree with the host numpy solver to f64 round-off, including the
    identity-padding slice-back."""
    m = 300
    kernel = RBFKernel(1.5) + Const(1e-3) * EyeKernel()
    theta = kernel.init_theta()
    active = rng.normal(size=(m, 3))
    b = rng.normal(size=(m, m)) / np.sqrt(m)
    u1 = b @ b.T * m * 0.01
    u2 = rng.normal(size=m)

    mv_host, mm_host = ppa.magic_solve(kernel, theta, active, u1, u2)
    mv_sh, mm_sh = ppa.sharded_magic_solve(
        kernel, np.asarray(theta, dtype=np.float64), active, u1, u2,
        eight_device_mesh, block=16,
    )
    np.testing.assert_allclose(mv_sh, mv_host, rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(mm_sh, mm_host, rtol=1e-6, atol=1e-8)


def test_chunked_prediction_matches_unchunked(rng):
    """The streaming (chunked) predict path must agree with a
    single-dispatch predict to floating-point round-off (not byte-identical:
    different chunk shapes may compile to different tilings)."""
    m = 40
    kernel = RBFKernel(1.0) + Const(1e-3) * EyeKernel()
    raw = ProjectedProcessRawPredictor(
        kernel=kernel,
        theta=np.asarray(kernel.init_theta(), dtype=np.float64),
        active=rng.normal(size=(m, 2)),
        magic_vector=rng.normal(size=m),
        magic_matrix=rng.normal(size=(m, m)),
    )
    x_test = rng.normal(size=(517, 2))  # not a multiple of any chunk size
    mean_one, var_one = (np.asarray(a) for a in raw(x_test))
    old = ProjectedProcessRawPredictor._PREDICT_CHUNK_ELEMS
    try:
        # force tiny chunks (100 elems / m=40 -> chunk of 2 rows)
        ProjectedProcessRawPredictor._PREDICT_CHUNK_ELEMS = 100
        mean_ch, var_ch = (np.asarray(a) for a in raw(x_test))
    finally:
        ProjectedProcessRawPredictor._PREDICT_CHUNK_ELEMS = old
    # not byte-identical as a claim: different chunk shapes may compile to
    # different tilings/reduction orders on accelerator backends
    np.testing.assert_allclose(mean_ch, mean_one, rtol=1e-12, atol=1e-13)
    np.testing.assert_allclose(var_ch, var_one, rtol=1e-12, atol=1e-13)


def test_mean_only_model(rng, tmp_path):
    """setPredictiveVariance(False): mean identical to the full model, no
    [m, m] operator built, informative errors on variance access, and
    save/load round-trips the mean-only form (all three magic-solve
    branches honor with_variance — host checked here, device/sharded via
    their parity tests plus the dispatch flag)."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.models.gpr import GaussianProcessRegressionModel

    x = rng.normal(size=(300, 2))
    y = np.sin(x.sum(axis=1))

    def gp(variance):
        return (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(1.0))
            .setActiveSetSize(60)
            .setMaxIter(10)
            .setSeed(5)
            .setPredictiveVariance(variance)
        )

    full = gp(True).fit(x, y)
    mean_only = gp(False).fit(x, y)
    assert mean_only.raw_predictor.magic_matrix is None
    np.testing.assert_allclose(
        mean_only.predict(x), full.predict(x), rtol=1e-10, atol=1e-12
    )
    with pytest.raises(ValueError, match="setPredictiveVariance"):
        mean_only.predict_with_var(x)

    path = str(tmp_path / "mean_only.npz")
    mean_only.save(path)
    loaded = GaussianProcessRegressionModel.load(path)
    assert loaded.raw_predictor.magic_matrix is None
    np.testing.assert_allclose(loaded.predict(x), mean_only.predict(x))


def test_mean_only_device_and_sharded_solvers(rng, eight_device_mesh):
    """with_variance=False on the device and mesh-sharded branches returns
    the same magic vector as the full solve, and None for the matrix."""
    m = 300
    kernel = RBFKernel(1.5) + Const(1e-3) * EyeKernel()
    theta = np.asarray(kernel.init_theta(), dtype=np.float64)
    active = rng.normal(size=(m, 3))
    b = rng.normal(size=(m, m)) / np.sqrt(m)
    u1 = b @ b.T * m * 0.01
    u2 = rng.normal(size=m)

    mv_full, _ = ppa.magic_solve(kernel, theta, active, u1, u2)
    mv_dev, mm_dev = ppa.magic_solve_device(
        kernel, theta, active, u1, u2, with_variance=False
    )
    assert mm_dev is None
    np.testing.assert_allclose(mv_dev, mv_full, rtol=1e-9, atol=1e-11)

    mv_sh, mm_sh = ppa.sharded_magic_solve(
        kernel, theta, active, u1, u2, eight_device_mesh, block=16,
        with_variance=False,
    )
    assert mm_sh is None
    np.testing.assert_allclose(mv_sh, mv_full, rtol=1e-8, atol=1e-10)


# --- joint predictive covariance + posterior sampling ---------------------


def test_predict_with_cov_diag_equals_var(rng):
    """diag(cov) == var exactly (the Eye noise component is diagonal)."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    x = rng.normal(size=(300, 2))
    y = np.sin(x.sum(axis=1))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setActiveSetSize(60)
        .setMaxIter(15)
        .fit(x, y)
    )
    t = x[:40]
    mean_v, var = model.predict_with_var(t)
    mean_c, cov = model.predict_with_cov(t)
    np.testing.assert_allclose(mean_c, mean_v, rtol=1e-12)
    np.testing.assert_allclose(np.diag(cov), var, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(cov, cov.T, rtol=1e-9, atol=1e-12)


def test_predict_cov_matches_dense_ppa_oracle(rng):
    """Joint covariance against an independent dense f64 recomputation of
    the full PPA chain (PGPH.scala:49-60 conventions + the R&W eq. 8.27
    operator applied off-diagonally): same active set, same statistics,
    numpy-only algebra.  Validates the cross/gram conventions and the
    solve wiring, independent of the PPA approximation quality."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    n, m, sigma2, ls = 60, 20, 1e-2, 1.2
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1))

    model = (
        GaussianProcessRegression()
        # pinned hyperparameters: the oracle must use the same kernel
        .setKernel(lambda: RBFKernel(ls, ls, ls))
        .setSigma2(sigma2)
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(m)
        .setMaxIter(1)
        .fit(x, y)
    )
    a = np.asarray(model.raw_predictor.active)
    t = rng.normal(size=(12, 2))
    mean, cov = model.predict_with_cov(t)

    def k(p, q):
        d2 = ((p[:, None, :] - q[None, :, :]) ** 2).sum(-1)
        return np.exp(-d2 / (2 * ls**2))

    kmm = k(a, a) + sigma2 * np.eye(m)  # noise-augmented K_mm
    kan = k(a, x)  # cross kernel has no Eye component
    pd = sigma2 * kmm + kan @ kan.T
    mv = np.linalg.solve(pd, kan @ y)
    mm = sigma2 * np.linalg.inv(pd) - np.linalg.inv(kmm)
    kta = k(t, a)
    mean_oracle = kta @ mv
    cov_oracle = k(t, t) + sigma2 * np.eye(len(t)) + kta @ mm @ kta.T
    np.testing.assert_allclose(mean, mean_oracle, rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(cov, cov_oracle, rtol=1e-6, atol=1e-9)


def test_sample_posterior_statistics(rng):
    """Seeded determinism; empirical mean/covariance of many draws match
    the analytic posterior (loose MC tolerances)."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    x = rng.normal(size=(200, 1))
    y = np.sin(x[:, 0])
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setActiveSetSize(50)
        .setMaxIter(15)
        .fit(x, y)
    )
    t = np.linspace(-1.5, 1.5, 10)[:, None]
    s1 = model.sample_posterior(t, n_samples=4, seed=7)
    s2 = model.sample_posterior(t, n_samples=4, seed=7)
    np.testing.assert_allclose(s1, s2, rtol=1e-15)
    assert s1.shape == (4, 10)

    mean, cov = model.predict_with_cov(t)
    draws = model.sample_posterior(t, n_samples=20000, seed=1)
    np.testing.assert_allclose(
        draws.mean(axis=0), mean, atol=4 * np.sqrt(np.diag(cov).max() / 20000) + 1e-3
    )
    emp_cov = np.cov(draws.T)
    np.testing.assert_allclose(emp_cov, cov, atol=0.05 * max(1.0, np.abs(cov).max()))


def test_mean_only_model_rejects_cov(rng):
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    x = rng.normal(size=(120, 2))
    y = np.sin(x.sum(axis=1))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setActiveSetSize(40)
        .setMaxIter(5)
        .setPredictiveVariance(False)
        .fit(x, y)
    )
    with pytest.raises(ValueError, match="covariance"):
        model.predict_with_cov(x[:5])


def test_predict_rejects_feature_mismatch(rng):
    """A wrong feature count at predict time fails with a readable message
    naming the expected dimensionality, not a jit broadcast error."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    x = rng.normal(size=(80, 3))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setActiveSetSize(20)
        .setMaxIter(3)
        .fit(x, np.sin(x.sum(1)))
    )
    with pytest.raises(ValueError, match=r"\[t, 3\]"):
        model.predict(rng.normal(size=(5, 2)))
    with pytest.raises(ValueError, match=r"\[t, 3\]"):
        model.predict_with_cov(rng.normal(size=(5,)))
