"""Product-of-experts aggregation (models/poe.py) vs dense oracles.

Single expert: every mode must reduce to the exact GP posterior.
Multi-expert: the aggregation is recomputed by hand from per-expert dense
posteriors (numpy f64) and must agree to solver precision.  Quality: on
synthetics, rBCM prediction must be competitive with the PPA model.
"""

import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessRegression,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.models.poe import PoEPredictor, make_poe_predictor
from spark_gp_tpu.parallel.experts import group_for_experts


def _make_kernel():
    return 1.0 * RBFKernel(0.7, 1e-6, 10) + WhiteNoiseKernel(0.1, 0.0, 1.0)


def _dense_posterior(kernel, theta, xs, ys, x_test):
    """Exact GP (mean, var) at x_test from one expert's rows, f64."""
    import jax.numpy as jnp

    t = jnp.asarray(theta)
    k = np.asarray(kernel.gram(t, jnp.asarray(xs)))
    k_cross = np.asarray(kernel.cross(t, jnp.asarray(x_test), jnp.asarray(xs)))
    k_ss = np.asarray(kernel.self_diag(t, jnp.asarray(x_test)))
    sol = np.linalg.solve(k, ys)
    mean = k_cross @ sol
    var = k_ss - np.einsum(
        "ts,st->t", k_cross, np.linalg.solve(k, k_cross.T)
    )
    return mean, var


# NB "rbcm" deliberately absent: with one expert its entropy weight
# beta = 0.5(log k** - log s2) != 1, so rBCM is NOT the exact posterior at
# E=1 (a known property of the estimator, not a bug); its formula is
# pinned by the hand-aggregation test below instead.
@pytest.mark.parametrize("mode", ["poe", "gpoe", "bcm"])
def test_single_expert_reduces_to_exact_gp(rng, mode):
    x = rng.normal(size=(20, 2))
    y = np.sin(x.sum(axis=1))
    x_test = rng.normal(size=(7, 2))
    kernel = _make_kernel()
    theta = kernel.init_theta()

    pred = make_poe_predictor(kernel, theta, x, y, 20, mode=mode)
    mean, var = pred.predict_with_var(x_test)
    exact_mean, exact_var = _dense_posterior(kernel, theta, x, y, x_test)
    np.testing.assert_allclose(mean, exact_mean, rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(var, exact_var, rtol=1e-8, atol=1e-10)


@pytest.mark.parametrize("mode", ["poe", "gpoe", "bcm", "rbcm"])
def test_multi_expert_matches_hand_aggregation(rng, mode):
    n, s = 34, 12  # E=3, width ceil(34/3)=12 -> 2 padded slots stay inert
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    x_test = rng.normal(size=(5, 2))
    kernel = _make_kernel()
    theta = kernel.init_theta()

    pred = make_poe_predictor(kernel, theta, x, y, s, mode=mode)
    mean, var = pred.predict_with_var(x_test)

    # hand aggregation from dense per-expert posteriors
    data = group_for_experts(x, y, s)
    e = data.num_experts
    mus, variances = [], []
    for j in range(e):
        members = np.arange(j, n, e)
        m_j, v_j = _dense_posterior(kernel, theta, x[members], y[members], x_test)
        mus.append(m_j)
        variances.append(v_j)
    mus = np.stack(mus)  # [E, t]
    variances = np.stack(variances)
    import jax.numpy as jnp

    k_ss = np.asarray(kernel.self_diag(jnp.asarray(theta), jnp.asarray(x_test)))
    if mode == "poe":
        beta = np.ones_like(variances)
        prior_w = 0.0
    elif mode == "gpoe":
        beta = np.ones_like(variances) / e
        prior_w = 0.0
    elif mode == "bcm":
        beta = np.ones_like(variances)
        prior_w = 1.0 - e
    else:
        beta = 0.5 * (np.log(k_ss)[None, :] - np.log(variances))
        prior_w = 1.0 - beta.sum(axis=0)
    prec = (beta / variances).sum(axis=0) + prior_w / k_ss
    expect_mean = (beta / variances * mus).sum(axis=0) / prec
    expect_var = 1.0 / prec

    np.testing.assert_allclose(mean, expect_mean, rtol=1e-8)
    np.testing.assert_allclose(var, expect_var, rtol=1e-8)


def test_rbcm_reverts_to_prior_far_from_data(rng):
    """The robust weighting must wash out in voids: far from every expert,
    variance ~ prior and mean ~ 0 — the failure mode plain PoE gets wrong
    (overconfident: variance shrinks with E)."""
    x = rng.normal(size=(40, 2))
    y = np.sin(x.sum(axis=1))
    far = np.full((3, 2), 40.0)
    kernel = _make_kernel()
    theta = kernel.init_theta()

    import jax.numpy as jnp

    k_ss = np.asarray(kernel.self_diag(jnp.asarray(theta), jnp.asarray(far)))
    rbcm = make_poe_predictor(kernel, theta, x, y, 10, mode="rbcm")
    mean, var = rbcm.predict_with_var(far)
    np.testing.assert_allclose(var, k_ss, rtol=1e-4)
    np.testing.assert_allclose(mean, 0.0, atol=1e-4)

    poe = make_poe_predictor(kernel, theta, x, y, 10, mode="poe")
    _, var_poe = poe.predict_with_var(far)
    assert np.all(var_poe < k_ss / 2)  # ~k**/E: the documented overconfidence


def test_estimator_poe_predictor_competitive_with_ppa(rng):
    """At the FITTED hyperparameters, rBCM held-out RMSE must be in the
    same regime as the PPA model's (neither is uniformly better; a 2x band
    guards against wiring bugs, not philosophy)."""
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import rmse

    x, y = make_synthetics(n=600)
    perm = np.random.default_rng(3).permutation(len(y))
    tr, te = perm[:450], perm[450:]
    x_tr, y_tr, x_te, y_te = x[tr], y[tr], x[te], y[te]
    gp = (
        GaussianProcessRegression()
        .setKernel(
            lambda: 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1)
        )
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(100)
        .setSigma2(1e-3)
        .setSeed(13)
    )
    model = gp.fit(x_tr, y_tr)
    ppa_rmse = rmse(y_te, model.predict(x_te))

    poe = gp.poe_predictor(x_tr, y_tr, model, mode="rbcm")
    poe_rmse = rmse(y_te, poe.predict(x_te))
    assert poe_rmse < max(2.0 * ppa_rmse, 0.11)

    mean, var = poe.predict_with_var(x_te)
    assert var.shape == y_te.shape and np.all(var > 0)


def test_poe_singular_gram_repaired_or_surfaced(rng):
    """A singular-but-PSD expert gram is repaired by the shared adaptive
    jitter ladder (ops/linalg.py) at build time — finite predictions, not
    NaN; a gram the ladder cannot repair (NaN input) still raises the
    advice-bearing error every other factorization path gives."""
    from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException

    x = np.zeros((12, 2))  # duplicate rows, zero-noise kernel: singular gram
    y = np.zeros(12)
    kernel = 1.0 * RBFKernel(0.7, 1e-6, 10)
    poe = make_poe_predictor(kernel, kernel.init_theta(), x, y, 12)
    mean, var = poe.predict_with_var(np.zeros((3, 2)))
    assert np.isfinite(mean).all() and np.isfinite(var).all()

    x_bad = np.full((12, 2), np.nan)  # irreparable: ladder exhausts
    with pytest.raises(NotPositiveDefiniteException):
        make_poe_predictor(kernel, kernel.init_theta(), x_bad, y, 12)


def test_poe_validates(rng):
    with pytest.raises(ValueError, match="unknown PoE mode"):
        make_poe_predictor(
            _make_kernel(), _make_kernel().init_theta(),
            rng.normal(size=(10, 2)), np.zeros(10), 5, mode="blended",
        )
    gp = GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
    with pytest.raises(ValueError, match=r"x must be \[N, p\]"):
        gp.poe_predictor(np.zeros(5), np.zeros(5))


@pytest.mark.parametrize("mode", ["poe", "gpoe", "bcm", "rbcm"])
def test_sharded_poe_matches_single_device(rng, eight_device_mesh, mode):
    """The mesh path (expert axis sharded, one psum per reduction) must
    agree with the single-device path bit-for-bit up to reduction order —
    including the mesh-padded fully-masked experts it adds to even out the
    device split."""
    n, s = 34, 5  # 7 experts -> pads to 8 for the device split
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    x_test = rng.normal(size=(6, 2))
    kernel = _make_kernel()
    theta = kernel.init_theta()

    single = make_poe_predictor(kernel, theta, x, y, s, mode=mode)
    sharded = make_poe_predictor(
        kernel, theta, x, y, s, mode=mode, mesh=eight_device_mesh
    )
    m1, v1 = single.predict_with_var(x_test)
    m2, v2 = sharded.predict_with_var(x_test)
    np.testing.assert_allclose(m2, m1, rtol=1e-10)
    np.testing.assert_allclose(v2, v1, rtol=1e-10)


def test_poe_predict_streams_large_test_sets(rng):
    """The chunked predict path (forced tiny chunk) must agree exactly
    with one-block prediction — bounded memory at any test-set size."""
    x = rng.normal(size=(40, 2))
    y = np.sin(x.sum(axis=1))
    x_test = rng.normal(size=(57, 2))
    pred = make_poe_predictor(
        _make_kernel(), _make_kernel().init_theta(), x, y, 10
    )
    m1, v1 = pred.predict_with_var(x_test)

    pred._PREDICT_CHUNK_ELEMS = 40 * 7  # -> 7-point chunks, ragged tail
    m2, v2 = pred.predict_with_var(x_test)
    np.testing.assert_allclose(m2, m1, rtol=1e-12)
    np.testing.assert_allclose(v2, v1, rtol=1e-12)
