"""Unit behavior of the resilience layer (spark_gp_tpu/resilience/):
jitter-ladder boundaries, quarantine semantics + renormalization, the
retry driver, the circuit breaker state machine, checkpoint integrity
errors, and the serve-path shed/poison accounting.

The end-to-end proofs (fit survives a poisoned expert, kill-and-resume,
breaker under live traffic) live in tests/test_chaos.py.
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.ops.linalg import (
    JITTER_SCHEDULE,
    NotPositiveDefiniteException,
    cholesky_escalated,
    psd_safe_cholesky_np,
)
from spark_gp_tpu.parallel.experts import group_for_experts
from spark_gp_tpu.resilience import (
    BreakerOpenError,
    CircuitBreaker,
    RetryBudgetExceededError,
    retry_with_backoff,
)
from spark_gp_tpu.resilience.quarantine import (
    ExpertQuarantineError,
    diagnose_experts,
    expert_health,
    nonfinite_expert_mask,
    quarantine_experts,
)


def _spd(n, rng, floor=1.0):
    a = rng.normal(size=(n, n))
    return a @ a.T + floor * np.eye(n)


# -- jitter ladder boundaries (ISSUE satellite) ----------------------------


def test_jitter_ladder_psd_needs_none(rng):
    """A healthy SPD matrix factors at rung 0 — zero extra work."""
    mat = _spd(12, rng)
    chol, tau = cholesky_escalated(jnp.asarray(mat))
    assert tau == 0.0
    np.testing.assert_allclose(
        np.asarray(chol), np.linalg.cholesky(mat), rtol=1e-10
    )
    # host path likewise: untouched factorization, no warning rung
    np.testing.assert_allclose(
        psd_safe_cholesky_np(mat, "t"), np.linalg.cholesky(mat), rtol=1e-10
    )


def test_jitter_ladder_fixed_at_rung_k(rng):
    """A rank-deficient (PSD, singular) matrix is repaired at a finite
    rung, and the factor reproduces the matrix to the jitter scale."""
    a = rng.normal(size=(10, 4))
    low = a @ a.T  # rank 4
    chol, tau = cholesky_escalated(jnp.asarray(low))
    assert 0.0 < tau <= JITTER_SCHEDULE[-1]
    assert np.all(np.isfinite(np.asarray(chol)))
    rebuilt = np.asarray(chol) @ np.asarray(chol).T
    scale = np.trace(low) / low.shape[0]
    np.testing.assert_allclose(rebuilt, low, atol=10 * tau * scale + 1e-12)
    # host ladder repairs the same matrix
    assert np.all(np.isfinite(psd_safe_cholesky_np(low, "t")))


def test_jitter_ladder_exhausts(rng):
    """A matrix no bounded diagonal boost can repair raises the advice-
    bearing error on both the device and host paths — including the NaN
    case, where LAPACK can hand back a NaN factor without erroring."""
    indefinite = np.diag([1.0, -1e6])
    for bad in (indefinite, np.full((3, 3), np.nan)):
        with pytest.raises(NotPositiveDefiniteException):
            cholesky_escalated(jnp.asarray(bad))
        with pytest.raises(NotPositiveDefiniteException):
            psd_safe_cholesky_np(bad, "t")


def test_jitter_ladder_batched(rng):
    """One bad matrix in a batch escalates the whole stack's rung; the
    healthy matrices stay numerically intact (trace-relative boost)."""
    good = _spd(6, rng)
    a = rng.normal(size=(6, 2))
    batch = np.stack([good, a @ a.T])
    chol, tau = cholesky_escalated(jnp.asarray(batch))
    assert tau > 0.0 and np.all(np.isfinite(np.asarray(chol)))
    np.testing.assert_allclose(
        np.asarray(chol[0]) @ np.asarray(chol[0]).T, good, rtol=1e-6
    )


# -- quarantine -----------------------------------------------------------


def _stack(rng, n=120, s=30, poison=None, poison_labels=False):
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1))
    if poison is not None:
        e = 4  # n=120, s=30 -> 4 experts
        rows = np.arange(poison, n, e)
        if poison_labels:
            y[rows[::2]] = np.inf
            y[rows[1::2]] = np.nan
        else:
            x[rows, 0] = np.nan
    return group_for_experts(x, y, s)


def test_nonfinite_expert_mask(rng):
    data = _stack(rng, poison=2)
    bad = nonfinite_expert_mask(data)
    assert bad.tolist() == [False, False, True, False]
    assert not nonfinite_expert_mask(_stack(rng)).any()


def test_quarantine_renormalization_noop_matches_full_nll(rng):
    """ISSUE satellite: with nothing dropped the quarantined objective IS
    the full-expert objective — renorm factor exactly 1, identical NLL."""
    from spark_gp_tpu.models.likelihood import batched_nll

    data = _stack(rng)
    kernel = (
        GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
        ._get_kernel()
    )
    theta = kernel.init_theta()
    report = diagnose_experts(kernel, theta, data)
    assert report.clean and report.renorm == 1.0
    same = quarantine_experts(data, report.bad)
    assert same is data  # no copy, no graph change
    nll_full = float(batched_nll(kernel, jnp.asarray(theta), data))
    nll_q = float(batched_nll(kernel, jnp.asarray(theta), same))
    assert nll_full == nll_q


def test_quarantine_drops_only_the_poisoned_expert(rng):
    from spark_gp_tpu.models.likelihood import batched_nll

    data = _stack(rng, poison=1)
    kernel = (
        GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
        ._get_kernel()
    )
    theta = kernel.init_theta()
    nll_e, grad_e = expert_health(kernel, theta, data)
    assert not np.isfinite(nll_e[1])
    assert np.isfinite(np.delete(nll_e, 1)).all()
    assert np.isfinite(np.delete(grad_e, 1)).all()

    report = diagnose_experts(kernel, theta, data)
    assert report.bad.tolist() == [False, True, False, False]
    assert report.renorm == pytest.approx(4 / 3)

    clean = quarantine_experts(data, report.bad)
    assert np.asarray(clean.mask)[1].sum() == 0  # inert
    assert np.isfinite(np.asarray(clean.x)).all()  # benign replacement
    total = float(batched_nll(kernel, jnp.asarray(theta), clean))
    assert np.isfinite(total)
    # the reduced sum is exactly the healthy experts' sum
    assert total == pytest.approx(float(np.delete(nll_e, 1).sum()), rel=1e-12)


def test_quarantine_sanitizes_nonfinite_labels(rng):
    """Regression: labels must be zeroed by SELECTION, not multiplication —
    IEEE NaN*0=NaN and inf*0=NaN, so ``y * keep`` let a label-poisoned
    expert re-poison the very BCM sum quarantine had masked it out of."""
    from spark_gp_tpu.models.likelihood import batched_nll

    data = _stack(rng, poison=1, poison_labels=True)
    kernel = (
        GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
        ._get_kernel()
    )
    theta = kernel.init_theta()
    report = diagnose_experts(kernel, theta, data)
    assert report.bad.tolist() == [False, True, False, False]

    clean = quarantine_experts(data, report.bad)
    assert np.isfinite(np.asarray(clean.y)).all()  # NaN/inf labels gone
    assert np.isfinite(np.asarray(clean.x)).all()
    nll_e, _ = expert_health(kernel, theta, data)
    total = float(batched_nll(kernel, jnp.asarray(theta), clean))
    assert np.isfinite(total)
    assert total == pytest.approx(float(np.delete(nll_e, 1).sum()), rel=1e-12)


def test_quarantine_all_bad_raises(rng):
    data = _stack(rng)
    with pytest.raises(ExpertQuarantineError, match="every expert"):
        quarantine_experts(data, np.ones(data.num_experts, dtype=bool))


def test_diagnose_escalates_jitter_before_quarantine(rng):
    """An exactly singular expert is repaired by a ladder rung, not
    dropped (quarantine is the last resort, after jitter escalation)."""
    x = rng.normal(size=(120, 3))
    y = np.sin(x.sum(axis=1))
    rows = np.arange(1, 120, 4)
    x[rows] = x[rows[0]]  # expert 1: all points identical -> singular Gram
    y[rows] = y[rows[0]]
    data = group_for_experts(x, y, 30)
    gp = (
        GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
        .setSigma2(0.0)
    )
    kernel = gp._get_kernel()
    report = diagnose_experts(kernel, kernel.init_theta(), data)
    assert report.num_dropped == 0
    assert report.num_jittered == 1 and report.jitter[1] > 0
    assert report.renorm == 1.0


# -- retry ----------------------------------------------------------------


def test_retry_with_backoff_recovers_and_repairs():
    calls, delays = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ValueError("boom")
        return "done"

    repaired = []
    out = retry_with_backoff(
        flaky, attempts=3, base_delay_s=0.01, retry_on=(ValueError,),
        on_retry=lambda i, exc: repaired.append((i, str(exc))),
        sleep=delays.append,
    )
    assert out == "done" and len(calls) == 3
    assert repaired == [(0, "boom"), (1, "boom")]
    assert delays == [0.01, 0.02]  # deterministic exponential backoff


def test_retry_budget_exhausts_with_cause():
    def always():
        raise ValueError("persistent")

    with pytest.raises(RetryBudgetExceededError) as err:
        retry_with_backoff(
            always, attempts=2, retry_on=(ValueError,), sleep=lambda _: None
        )
    assert isinstance(err.value.__cause__, ValueError)


def test_retry_does_not_catch_foreign_errors():
    def wrong():
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        retry_with_backoff(wrong, attempts=3, retry_on=(ValueError,))


# -- circuit breaker ------------------------------------------------------


def test_breaker_state_machine():
    clock = [0.0]
    b = CircuitBreaker("m", failure_threshold=2, reset_timeout_s=10.0,
                       clock=lambda: clock[0])
    assert b.state == CircuitBreaker.CLOSED
    b.before_call(); b.record_failure()
    b.before_call(); b.record_failure()       # second consecutive: trips
    assert b.state == CircuitBreaker.OPEN and b.trip_count == 1
    with pytest.raises(BreakerOpenError) as err:
        b.before_call()
    assert err.value.retry_after_s <= 10.0

    clock[0] = 10.5                            # cooldown elapsed
    assert b.state == CircuitBreaker.HALF_OPEN
    b.before_call()                            # the single probe is admitted
    with pytest.raises(BreakerOpenError):
        b.before_call()                        # ...but only one
    b.record_failure()                         # probe failed: re-open
    assert b.state == CircuitBreaker.OPEN and b.trip_count == 2

    clock[0] = 21.0
    b.before_call()
    b.record_success()                         # probe succeeded: closed
    assert b.state == CircuitBreaker.CLOSED
    b.before_call()                            # normal service resumes
    snap = b.snapshot()
    assert snap["state"] == "closed" and snap["trips"] == 2


def test_breaker_success_resets_failure_count():
    b = CircuitBreaker("m", failure_threshold=3, reset_timeout_s=1.0)
    for _ in range(2):
        b.record_failure()
    b.record_success()
    for _ in range(2):
        b.record_failure()
    assert b.state == CircuitBreaker.CLOSED  # never 3 consecutive


# -- checkpoint integrity -------------------------------------------------


def test_host_checkpoint_checksum_and_history(tmp_path):
    from spark_gp_tpu.utils.checkpoint import (
        CheckpointCorruptError,
        LbfgsCheckpointer,
        load_checkpoint,
    )

    kernel = RBFKernel(1.0)
    ck = LbfgsCheckpointer(str(tmp_path), kernel, tag="t", seed=7)
    for k in range(1, 4):
        ck(np.array([float(k)]))
    it, theta, _sig = load_checkpoint(str(tmp_path), tag="t")
    assert it == 3 and theta[0] == 3.0
    payload = json.loads((tmp_path / "lbfgs_state_t.json").read_text())
    assert payload["seed"] == 7
    assert payload["history"] == [[1.0], [2.0], [3.0]]
    assert payload["format_version"] == 2

    payload["theta"] = [999.0]  # tamper without updating the checksum
    (tmp_path / "lbfgs_state_t.json").write_text(json.dumps(payload))
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        load_checkpoint(str(tmp_path), tag="t")


def test_host_checkpoint_mismatch_is_a_named_error(tmp_path):
    """ISSUE satellite: resuming under a different kernel config raises
    CheckpointMismatchError instead of silently proceeding."""
    from spark_gp_tpu.utils.checkpoint import CheckpointMismatchError

    rng = np.random.default_rng(0)
    x = rng.normal(size=(120, 3))
    y = np.sin(x.sum(axis=1))

    def gp(kf):
        return (
            GaussianProcessRegression().setKernel(kf)
            .setDatasetSizeForExpert(40).setActiveSetSize(30)
            .setMaxIter(5).setOptimizer("host")
            .setCheckpointDir(str(tmp_path))
        )

    gp(lambda: RBFKernel(1.0)).fit(x, y)
    with pytest.raises(CheckpointMismatchError, match="different kernel"):
        gp(lambda: 1.0 * RBFKernel(1.0, 1e-6, 10.0)).fit(x, y)


def test_device_checkpoint_corruption_detected(tmp_path):
    from spark_gp_tpu.utils.checkpoint import (
        CheckpointCorruptError,
        DeviceOptimizerCheckpointer,
    )

    saver = DeviceOptimizerCheckpointer(str(tmp_path), "t")
    state = {"a": np.arange(4.0), "b": np.ones((2, 2))}
    saver.save(state, {"kind": "t"})
    assert saver.load(state, {"kind": "t"}) is not None

    # flip bytes in one stored leaf, keeping the archive loadable
    with np.load(saver.path) as npz:
        arrays = {k: npz[k].copy() for k in npz.files}
    arrays["leaf_0"][0] = 12345.0
    np.savez(saver.path.replace(".npz", ""), **arrays)
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        saver.load(state, {"kind": "t"})


# -- serve-path shed accounting ------------------------------------------


def test_deadline_shed_metric_and_structured_error():
    from spark_gp_tpu.serve.queue import (
        DeadlineExpiredError,
        MicroBatchQueue,
        PredictRequest,
    )

    sheds = []
    q = MicroBatchQueue(
        execute=lambda group: None, capacity=8,
        on_timeout=lambda n: sheds.append(n),
    )
    req = PredictRequest(("m", 1), np.zeros((1, 2)), deadline=-1.0)
    q.start()
    try:
        fut = q.submit(req)
        with pytest.raises(DeadlineExpiredError) as err:
            fut.result(timeout=5.0)
        assert err.value.code == "queue.shed.deadline"
        assert sheds == [1]
    finally:
        q.stop()


def test_poisoned_request_isolated_not_the_batch():
    from spark_gp_tpu.serve.queue import MicroBatchQueue, PredictRequest

    poisoned_counts = []

    def execute(group):
        for req in group:
            if np.isnan(req.x).any():
                raise RuntimeError("poisoned payload")
        for req in group:
            req.future.set_result(req.x.sum())

    q = MicroBatchQueue(
        execute=execute, capacity=16, max_wait_s=0.05, max_batch_rows=64,
        on_poison=poisoned_counts.append,
    )
    good1 = PredictRequest(("m", 1), np.ones((2, 2)))
    bad = PredictRequest(("m", 1), np.full((2, 2), np.nan))
    good2 = PredictRequest(("m", 1), np.full((2, 2), 2.0))
    # enqueue BEFORE starting the worker so all three coalesce into one batch
    for req in (good1, bad, good2):
        q.submit(req)
    q.start()
    try:
        assert good1.future.result(timeout=5.0) == 4.0
        assert good2.future.result(timeout=5.0) == 8.0
        with pytest.raises(RuntimeError, match="poisoned"):
            bad.future.result(timeout=5.0)
        assert poisoned_counts == [1]
    finally:
        q.stop()
