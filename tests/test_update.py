"""Incremental model updates (ProjectedProcessRawPredictor.with_additional_data).

Oracle: the PPA statistics are sums over observations, so a model fitted
on part 1 and UPDATED with part 2 must carry exactly the statistics of a
direct computation over all data at the same (kernel, theta, active set)
— computed here through the production expert-grouped ``kmn_stats_jit``
path, which shares no code with the update's per-point accumulation
(masked [E, s] reductions vs a flat [m, t] matmul): each certifies the
other.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel, WhiteNoiseKernel
from spark_gp_tpu.models import ppa
from spark_gp_tpu.parallel.experts import group_for_experts


def _problem(n=360, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    return x, y


def _gp(**kw):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-6, 10) + WhiteNoiseKernel(0.2, 0, 1))
        .setDatasetSizeForExpert(60)
        .setActiveSetSize(50)
        .setMaxIter(20)
        .setSeed(7)
    )
    for k, v in kw.items():
        getattr(gp, k)(v)
    return gp


def _oracle_stats(raw, x_all, y_all):
    """Full-data U1/u2 at the model's (kernel, theta, active) through the
    production expert-grouped statistics program."""
    data = group_for_experts(x_all, y_all, 60)
    with jax.enable_x64():
        u1, u2 = ppa.kmn_stats_jit(
            raw.kernel,
            jnp.asarray(raw.theta, dtype=jnp.float64),
            jnp.asarray(raw.active, dtype=jnp.float64),
            data.x.astype(jnp.float64),
            data.y.astype(jnp.float64),
            data.mask.astype(jnp.float64),
        )
    return np.asarray(u1), np.asarray(u2)


def test_update_matches_full_data_statistics():
    x, y = _problem()
    x1, y1 = x[:240], y[:240]
    x2, y2 = x[240:], y[240:]

    model = _gp().fit(x1, y1)
    updated = model.update(x2, y2)

    u1_full, u2_full = _oracle_stats(model.raw_predictor, x, y)
    np.testing.assert_allclose(updated.raw_predictor.u1, u1_full, rtol=1e-10)
    np.testing.assert_allclose(updated.raw_predictor.u2, u2_full, rtol=1e-10)

    # ... and the re-solved operators equal a direct magic solve on the
    # oracle statistics
    mv, mm = ppa.magic_solve(
        model.raw_predictor.kernel, model.raw_predictor.theta,
        model.raw_predictor.active, u1_full, u2_full,
    )
    # rtol 1e-4, not 1e-9: the statistics agree to ~1e-10 but the normal
    # equations SQUARE the conditioning (PGPH.scala's sigma2*Kmm + U1), so
    # that input difference legitimately amplifies ~1e4x in the solution
    np.testing.assert_allclose(
        updated.raw_predictor.magic_vector, np.asarray(mv), rtol=1e-4
    )
    np.testing.assert_allclose(
        updated.raw_predictor.magic_matrix, np.asarray(mm), rtol=1e-4,
        atol=1e-10,
    )

    # hyperparameters, active set, and the ORIGINAL model are untouched
    np.testing.assert_array_equal(
        updated.raw_predictor.theta, model.raw_predictor.theta
    )
    np.testing.assert_array_equal(
        updated.raw_predictor.active, model.raw_predictor.active
    )
    np.testing.assert_allclose(
        model.raw_predictor.u1, _oracle_stats(model.raw_predictor, x1, y1)[0],
        rtol=1e-10,
    )


def test_update_improves_fit_on_new_region():
    """Data arriving from an unseen input region: the updated model must
    predict it far better than the stale model (the point of the
    capability), while chained single-batch updates equal one big update."""
    rng = np.random.default_rng(3)
    x1 = rng.uniform(0.0, 3.0, size=(300, 1))
    x2 = rng.uniform(3.0, 6.0, size=(150, 1))
    f = lambda x: np.sin(2.0 * x[:, 0])
    y1 = f(x1) + 0.05 * rng.normal(size=300)
    y2 = f(x2) + 0.05 * rng.normal(size=150)

    # active set must span the eventual input range for the update to have
    # basis support there — supply it explicitly (fit_distributed-style)
    model = (
        _gp(setActiveSetSize=80)
        .fit(np.concatenate([x1, x2[:5]]), np.concatenate([y1, y2[:5]]))
    )
    stale_rmse = float(np.sqrt(np.mean((model.predict(x2) - f(x2)) ** 2)))
    updated = model.update(x2, y2)
    new_rmse = float(np.sqrt(np.mean((updated.predict(x2) - f(x2)) ** 2)))
    assert new_rmse < 0.2, new_rmse
    assert new_rmse < stale_rmse * 0.8, (new_rmse, stale_rmse)

    # chaining updates == one combined update (associativity of the sums;
    # rtol 1e-4: the f64 reduction order differs between one 150-column
    # and two 75-column stat matmuls, and the normal equations square the
    # conditioning of that ~1e-13 input noise)
    half = len(x2) // 2
    chained = model.update(x2[:half], y2[:half]).update(x2[half:], y2[half:])
    np.testing.assert_allclose(
        chained.raw_predictor.magic_vector,
        updated.raw_predictor.magic_vector,
        rtol=1e-4,
    )


def test_update_roundtrips_through_save_load(tmp_path):
    x, y = _problem(n=240, seed=5)
    model = _gp().fit(x[:160], y[:160])
    path = str(tmp_path / "model")
    model.save(path)

    from spark_gp_tpu import GaussianProcessRegressionModel

    loaded = GaussianProcessRegressionModel.load(path)
    up_a = model.update(x[160:], y[160:])
    up_b = loaded.update(x[160:], y[160:])
    np.testing.assert_allclose(
        up_a.raw_predictor.magic_vector, up_b.raw_predictor.magic_vector,
        rtol=1e-12,
    )

    # a legacy file without the statistics loads fine but refuses update
    import numpy as _np

    with _np.load(path + ".npz") as data:
        legacy = {k: data[k] for k in data.files if k not in ("u1", "u2")}
    legacy_path = str(tmp_path / "legacy.npz")
    _np.savez(legacy_path, **legacy)
    legacy_model = GaussianProcessRegressionModel.load(legacy_path)
    np.testing.assert_allclose(
        legacy_model.predict(x[:10]), model.predict(x[:10]), rtol=1e-12
    )
    with pytest.raises(ValueError, match="statistics"):
        legacy_model.update(x[160:], y[160:])


def test_update_mean_only_and_validation():
    x, y = _problem(n=200, seed=9)
    model = _gp(setPredictiveVariance=False).fit(x[:150], y[:150])
    updated = model.update(x[150:], y[150:])
    assert updated.raw_predictor.magic_matrix is None
    assert np.all(np.isfinite(updated.predict(x[:20])))

    with pytest.raises(ValueError, match="x_new"):
        model.update(x[150:, :2], y[150:])
    with pytest.raises(ValueError, match="y_new"):
        model.update(x[150:], y[150:][:-1])


def test_laplace_families_do_not_carry_update_statistics():
    """Classifier/count fits must NOT store u1/u2: their statistics sum
    over LATENT targets, so folding raw labels into them would be silently
    wrong — the predictor refuses rather than accepts (r4 review)."""
    from spark_gp_tpu import GaussianProcessClassifier

    rng = np.random.default_rng(11)
    x = rng.normal(size=(120, 2))
    yb = (x.sum(axis=1) > 0).astype(np.float64)
    clf = (
        GaussianProcessClassifier()
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(30)
        .setMaxIter(10)
        .fit(x, yb)
    )
    assert clf.raw_predictor.u1 is None and clf.raw_predictor.u2 is None
    with pytest.raises(ValueError, match="latent"):
        clf.raw_predictor.with_additional_data(x[:5], yb[:5])


def test_update_chunked_accumulation_matches_single_shot():
    """The bounded-memory chunked statistics accumulation equals the
    unchunked sum (same sum, different bracketing)."""
    x, y = _problem(n=300, seed=13)
    model = _gp().fit(x[:200], y[:200])
    raw = model.raw_predictor
    one = raw.with_additional_data(x[200:], y[200:])
    try:
        # force many tiny chunks through the same entry point
        ProjectedProcessRawPredictor = type(raw)
        old = ProjectedProcessRawPredictor._PREDICT_CHUNK_ELEMS
        ProjectedProcessRawPredictor._PREDICT_CHUNK_ELEMS = raw.active.shape[0] * 7
        many = raw.with_additional_data(x[200:], y[200:])
    finally:
        ProjectedProcessRawPredictor._PREDICT_CHUNK_ELEMS = old
    np.testing.assert_allclose(many.u1, one.u1, rtol=1e-12)
    np.testing.assert_allclose(many.u2, one.u2, rtol=1e-12)
