"""Titsias collapsed ELBO (models/sgpr.py) vs dense oracles.

The chunked/batched implementation is checked against the literal dense
bound — log N(y | 0, Q_nn + s2 I) - tr(K_nn - Q_nn)/(2 s2) — plus the two
theoretical pins that make the ELBO an ELBO: it equals the exact log
marginal when the inducing set is the data itself, and lower-bounds it
otherwise.  All f64 on the CPU harness.
"""

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.models.sgpr import batched_elbo_nll
from spark_gp_tpu.parallel.experts import group_for_experts


def _kernel():
    return 1.0 * RBFKernel(0.7, 1e-6, 10)


def _dense_elbo(kernel, theta, x, y, active, sigma2):
    """Literal Titsias eq. 9, dense f64."""
    import jax.numpy as jnp

    t = jnp.asarray(theta)
    knn = np.asarray(kernel.gram(t, jnp.asarray(x)))
    kmm = np.asarray(kernel.gram(t, jnp.asarray(active)))
    knm = np.asarray(kernel.cross(t, jnp.asarray(x), jnp.asarray(active)))
    m = kmm.shape[0]
    kmm = kmm + 1e-6 * np.mean(np.diag(kmm)) * np.eye(m)
    qnn = knm @ np.linalg.solve(kmm, knm.T)
    n = x.shape[0]
    cov = qnn + sigma2 * np.eye(n)
    sign, logdet = np.linalg.slogdet(cov)
    assert sign > 0
    quad = y @ np.linalg.solve(cov, y)
    log_marg = -0.5 * (n * np.log(2 * np.pi) + logdet + quad)
    return log_marg - np.trace(knn - qnn) / (2 * sigma2)


@pytest.mark.parametrize("n,s", [(30, 30), (34, 12)])
def test_elbo_matches_dense_oracle(rng, n, s):
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    active = x[rng.choice(n, size=8, replace=False)]
    kernel = _kernel()
    theta = kernel.init_theta()
    sigma2 = 1e-2

    data = group_for_experts(x, y, s)
    got = -float(batched_elbo_nll(kernel, theta, data, active, sigma2))
    expect = _dense_elbo(kernel, theta, x, y, active, sigma2)
    np.testing.assert_allclose(got, expect, rtol=1e-8)


def test_elbo_equals_exact_marginal_when_inducing_is_data(rng):
    """Q_nn = K_nn when active == x, the trace term vanishes, and the bound
    IS the exact log marginal of K + s2 I (up to the K_mm jitter)."""
    n = 25
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1))
    kernel = _kernel()
    theta = kernel.init_theta()
    sigma2 = 1e-2

    data = group_for_experts(x, y, n)
    got = -float(batched_elbo_nll(kernel, theta, data, x, sigma2))

    import jax.numpy as jnp

    knn = np.asarray(kernel.gram(jnp.asarray(theta), jnp.asarray(x)))
    cov = knn + sigma2 * np.eye(n)
    _, logdet = np.linalg.slogdet(cov)
    exact = -0.5 * (
        n * np.log(2 * np.pi) + logdet + y @ np.linalg.solve(cov, y)
    )
    # the identity holds up to the K_mm jitter (1e-6 relative), which at
    # m = n perturbs Q_nn away from K_nn by ~1e-3 in the bound on this
    # conditioning — and always DOWNWARD (it stays a lower bound)
    assert got <= exact + 1e-8
    np.testing.assert_allclose(got, exact, atol=5e-3)


def test_elbo_lower_bounds_exact_marginal(rng):
    """m < n: the bound must sit BELOW the exact log marginal — the
    property that makes optimizing it principled (Titsias '09 Thm 1)."""
    n = 40
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    kernel = _kernel()
    theta = kernel.init_theta()
    sigma2 = 1e-2

    import jax.numpy as jnp

    knn = np.asarray(kernel.gram(jnp.asarray(theta), jnp.asarray(x)))
    cov = knn + sigma2 * np.eye(n)
    _, logdet = np.linalg.slogdet(cov)
    exact = -0.5 * (
        n * np.log(2 * np.pi) + logdet + y @ np.linalg.solve(cov, y)
    )

    data = group_for_experts(x, y, 20)
    for m in (4, 8, 16):
        active = x[: m]
        elbo = -float(batched_elbo_nll(kernel, theta, data, active, sigma2))
        assert elbo <= exact + 1e-8
    # and the bound tightens as m grows (monotonicity on nested sets)
    elbos = [
        -float(batched_elbo_nll(kernel, theta, data, x[:m], sigma2))
        for m in (4, 8, 16)
    ]
    assert elbos[0] <= elbos[1] <= elbos[2] + 1e-10


def test_elbo_gradient_matches_fd(rng):
    import jax
    import jax.numpy as jnp

    x = rng.normal(size=(33, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=33)
    data = group_for_experts(x, y, 12)
    active = x[:7]
    kernel = _kernel()
    theta0 = jnp.asarray(kernel.init_theta())

    f = lambda t: batched_elbo_nll(kernel, t, data, active, 1e-2)
    grad = np.asarray(jax.grad(f)(theta0))
    eps = 1e-6
    for k in range(theta0.shape[0]):
        dt = np.zeros(theta0.shape[0])
        dt[k] = eps
        fd = (float(f(theta0 + dt)) - float(f(theta0 - dt))) / (2 * eps)
        np.testing.assert_allclose(grad[k], fd, rtol=1e-5, atol=1e-7)


def _mk(objective="elbo", opt="device", **kw):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-3, 20))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(20)
        .setSigma2(1e-2)
        .setSeed(7)
        .setObjective(objective)
        .setOptimizer(opt)
    )
    for name, v in kw.items():
        getattr(gp, name)(v)
    return gp


def test_elbo_fit_end_to_end(rng):
    """setObjective('elbo') fit: final objective is the ELBO NLL at the
    winner ON the pre-selected active set, the SAME set builds the PPA
    model, and prediction quality is sane."""
    x = rng.normal(size=(120, 2))
    y = np.sin(1.2 * x.sum(axis=1)) + 0.05 * rng.normal(size=120)

    model = _mk().fit(x, y)
    pred = model.predict(x)
    assert np.sqrt(np.mean((pred - y) ** 2)) < 0.25

    # recompute the objective at the winner with the model's own active set
    import jax.numpy as jnp

    data = group_for_experts(x, y, 40)
    recomputed = float(
        batched_elbo_nll(
            model.raw_predictor.kernel,
            jnp.asarray(model.raw_predictor.theta, dtype=data.x.dtype),
            data,
            jnp.asarray(model.raw_predictor.active, dtype=data.x.dtype),
            1e-2,
        )
    )
    assert model.instr.metrics["final_nll"] == pytest.approx(
        recomputed, rel=1e-5
    )


def test_elbo_host_and_device_optimizers_agree(rng):
    x = rng.normal(size=(60, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=60)
    m_host = _mk(opt="host").fit(x, y)
    m_dev = _mk(opt="device").fit(x, y)
    assert m_host.instr.metrics["final_nll"] == pytest.approx(
        m_dev.instr.metrics["final_nll"], rel=1e-3
    )


def test_elbo_sharded_gspmd_matches_single(rng, eight_device_mesh):
    """elbo + mesh rides jit/GSPMD: sharded stack in, same optimum out."""
    x = rng.normal(size=(64, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=64)
    single = _mk().setDatasetSizeForExpert(8).fit(x, y)
    sharded = (
        _mk()
        .setDatasetSizeForExpert(8)
        .setMesh(eight_device_mesh)
        .fit(x, y)
    )
    assert sharded.instr.metrics["final_nll"] == pytest.approx(
        single.instr.metrics["final_nll"], rel=1e-5
    )
    np.testing.assert_allclose(
        sharded.predict(x[:9]), single.predict(x[:9]), rtol=1e-4
    )


def test_elbo_multistart_and_checkpointed(rng, tmp_path):
    """The batched multi-start and the segmented checkpointed paths accept
    the elbo objective end to end."""
    x = rng.normal(size=(80, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=80)

    multi = _mk().setNumRestarts(3).fit(x, y)
    assert multi.instr.metrics["num_restarts"] == 3
    assert np.isfinite(multi.instr.metrics["final_nll"])

    ck = _mk().setCheckpointInterval(2).setCheckpointDir(str(tmp_path))
    ck_model = ck.fit(x, y)
    assert np.isfinite(ck_model.instr.metrics["final_nll"])
    import os

    assert any(
        f.startswith("gpr-elbo") for f in os.listdir(tmp_path)
    ), "elbo checkpoint must be objective-keyed"


def test_elbo_checkpoint_keyed_by_objective_surface(rng, tmp_path):
    """Two ELBO fits with different sigma2 (different bounds) sharing a
    checkpoint dir must neither resume from nor clobber each other, on
    both optimizer paths."""
    import os

    x = rng.normal(size=(60, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=60)

    def fit(sigma2, opt):
        return (
            _mk(opt=opt)
            .setSigma2(sigma2)
            .setCheckpointInterval(2)
            .setCheckpointDir(str(tmp_path))
            .fit(x, y)
        )

    fit(1e-2, "device")
    files_a = set(os.listdir(tmp_path))
    fit(1e-3, "device")
    files_b = set(os.listdir(tmp_path))
    # the second fit added its OWN state file; the first one survived
    assert files_a < files_b
    # host path: objective-surface digest rides the json tag too (the
    # run_journal artifact shares the directory — count only state files)
    fit(1e-2, "host")
    fit(1e-3, "host")
    host_tags = [
        f for f in os.listdir(tmp_path)
        if f.startswith("lbfgs_state_") and f.endswith(".json")
    ]
    assert len(host_tags) == 2


def test_elbo_rejects_greedy_without_white_noise(rng):
    """The greedy provider's Seeger scores divide by the model kernel's
    white noise.  The estimator always appends sigma2*Eye, so the 0/0
    hazard exists exactly at setSigma2(0) with a noise-free user kernel —
    reject loudly instead of selecting m duplicate inducing rows."""
    from spark_gp_tpu import GreedilyOptimizingActiveSetProvider

    x = rng.normal(size=(40, 2))
    y = np.sin(x.sum(axis=1))
    gp = (
        _mk()
        .setSigma2(0.0)
        .setActiveSetProvider(GreedilyOptimizingActiveSetProvider())
    )
    with pytest.raises(ValueError, match="nonzero white noise"):
        gp.fit(x, y)
    # with the default nonzero sigma2 the combination is fine (the Eye
    # component supplies the noise) — must NOT raise
    gp2 = _mk().setActiveSetProvider(GreedilyOptimizingActiveSetProvider())
    model = gp2.fit(x, y)
    assert np.isfinite(model.instr.metrics["final_nll"])


def test_elbo_finite_in_float32(rng):
    """The f32 hazard that motivated the whitened formulation: on a
    kmeans-selected inducing set over clustered data, the objective and
    its gradient must stay finite in float32 at the init theta (the
    square-then-whiten formulation NaN'd here)."""
    import jax
    import jax.numpy as jnp

    from spark_gp_tpu import KMeansActiveSetProvider

    from spark_gp_tpu.data import make_synthetics

    x, y = make_synthetics(n=1500)
    gp = (
        GaussianProcessRegression()
        .setDatasetSizeForExpert(100)
        .setActiveSetProvider(KMeansActiveSetProvider())
        .setActiveSetSize(100)
        .setSigma2(1e-2)
        .setSeed(13)
        .setObjective("elbo")
        .setKernel(lambda: 1.0 * RBFKernel(0.1, 1e-6, 10))
    )
    kernel = gp._get_kernel()
    data32 = group_for_experts(x, y, 100, dtype=np.float32)
    active = gp._select_active(kernel, kernel.init_theta(), x, lambda: y, data32)
    theta32 = jnp.asarray(kernel.init_theta(), dtype=jnp.float32)
    active32 = jnp.asarray(active, dtype=jnp.float32)

    f = lambda t: batched_elbo_nll(
        kernel, t, data32, active32, np.float32(1e-2)
    )
    v, g = jax.value_and_grad(f)(theta32)
    assert v.dtype == jnp.float32
    assert np.isfinite(float(v))
    assert np.all(np.isfinite(np.asarray(g)))
