"""Tier-1 smoke for the serve CLI: boot -> warmup-ready -> score -> clean
shutdown, in a subprocess under JAX_PLATFORMS=cpu.

The subprocess is timeout-fenced with a process-group kill on expiry
(the utils/subproc.py hazard pattern: never let a wedged child hold the
suite), but unlike run_captured it needs a stdin leg — the protocol IS
stdin JSON lines.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel


@pytest.fixture(scope="module")
def tiny_model(tmp_path_factory):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(120, 3))
    y = np.sin(x.sum(axis=1))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(30)
        .setSigma2(1e-3)
        .setMaxIter(5)
        .setSeed(1)
        .fit(x, y)
    )
    path = str(tmp_path_factory.mktemp("cli") / "tiny.npz")
    model.save(path)
    return path, model, x


def _run_cli(args, input_text, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the CLI manages a plain single-device CPU process; drop the
    # harness's forced 8-device flag and any compile-cache override
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_gp_tpu.serve", *args],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(input_text, timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        out, err = proc.communicate()
        pytest.fail(f"serve CLI wedged past {timeout}s; stderr: {err[-500:]}")
    return proc.returncode, out, err


def _popen_tcp(args, timeout=240):
    """Boot the CLI in TCP mode; returns (proc, port) once listening."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_gp_tpu.serve", *args, "--port", "0"],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    port = None
    try:
        while True:
            line = proc.stdout.readline()
            if not line:
                pytest.fail("serve CLI exited before listening")
            event = json.loads(line)
            if event.get("event") == "listening":
                port = event["port"]
                break
    except Exception:
        os.killpg(proc.pid, signal.SIGKILL)
        raise
    return proc, port


def _finish_tcp(proc, timeout=60):
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()
        pytest.fail("serve CLI wedged at shutdown")
    finally:
        for stream in (proc.stdin, proc.stdout, proc.stderr):
            if stream is not None:
                stream.close()


def test_cli_boot_score_shutdown(tiny_model):
    path, model, x = tiny_model
    request_rows = x[:3].tolist()
    lines = "\n".join(
        [
            json.dumps({"id": 1, "model": "tiny", "x": request_rows}),
            json.dumps({"cmd": "health"}),
            json.dumps({"cmd": "metrics"}),
            json.dumps({"cmd": "shutdown"}),
        ]
    ) + "\n"
    rc, out, err = _run_cli(
        ["--model", f"tiny={path}", "--max-batch", "16", "--min-bucket", "4",
         "--replica-id", "cli-r0"],
        lines,
    )
    assert rc == 0, err[-500:]
    events = [json.loads(ln) for ln in out.strip().splitlines()]

    # ready is FIRST — warmup completes before any request is answered
    assert events[0]["event"] == "ready"
    assert events[0]["platform"] == "cpu"
    [desc] = events[0]["models"]
    assert desc["name"] == "tiny" and desc["version"] == 1
    # the AOT stage compiled the whole ladder at load
    assert sorted(int(b) for b in desc["compiles"]) == [4, 8, 16]
    assert all(c == 1 for c in desc["compiles"].values())

    by_id = {e["id"]: e for e in events if "id" in e}
    answer = by_id[1]
    assert "error" not in answer, answer
    # the CLI subprocess runs f32 (no x64 harness): parity is approximate
    np.testing.assert_allclose(
        answer["mean"], model.predict(x[:3]), rtol=1e-4, atol=1e-5
    )
    assert len(answer["var"]) == 3

    metrics = next(e for e in events if e.get("event") == "metrics")
    assert metrics["counters"]["requests"] >= 1
    assert "request_latency_s" in metrics["histograms"]

    # the health verb carries replica identity (ISSUE 12): id, pid and
    # build_info, so a router/gpctl can attribute verdicts to THIS process
    health = next(e for e in events if e.get("event") == "health")
    assert health["replica"]["replica_id"] == "cli-r0"
    assert health["replica"]["pid"] > 0
    assert "backend" in health["replica"]["build_info"]

    assert events[-1]["event"] == "shutdown"
    assert events[-1]["requests"] >= 1


def test_cli_rejects_bad_request_and_survives(tiny_model):
    path, _, x = tiny_model
    lines = "\n".join(
        [
            "this is not json",
            json.dumps({"id": 7, "model": "ghost", "x": x[:2].tolist()}),
            json.dumps({"id": 8, "model": "tiny", "x": x[:2].tolist()}),
            json.dumps({"cmd": "shutdown"}),
        ]
    ) + "\n"
    rc, out, err = _run_cli(["--model", f"tiny={path}"], lines)
    assert rc == 0, err[-500:]
    events = [json.loads(ln) for ln in out.strip().splitlines()]
    assert any("bad request line" in e.get("error", "") for e in events)
    by_id = {e["id"]: e for e in events if "id" in e}
    assert "KeyError" in by_id[7]["error"]  # unknown model: error response
    assert "mean" in by_id[8]            # ...and the next request still works


def test_cli_requires_a_model():
    rc, out, err = _run_cli([], "")
    assert rc == 2
    assert "--model" in err


def test_cli_tcp_read_timeout_unpins_vanished_client(tiny_model):
    """ISSUE 12 satellite: a connect-and-vanish client (half-open socket,
    never sends a byte) must be disconnected by the per-connection read
    timeout instead of pinning a reader thread — and a live client on the
    same server keeps being served throughout."""
    import socket

    path, model, x = tiny_model
    proc, port = _popen_tcp(
        ["--model", f"tiny={path}", "--max-batch", "16", "--min-bucket", "4",
         "--conn-read-timeout-s", "1"],
    )
    try:
        # the ghost: connects and never sends anything.  Within the read
        # timeout the server hangs up — a classified serve.conn_idle line
        # then EOF — instead of pinning a reader thread forever
        ghost = socket.create_connection(("127.0.0.1", port), timeout=30)
        ghost.settimeout(30)
        got = b""
        try:
            while True:
                chunk = ghost.recv(4096)
                if not chunk:
                    break
                got += chunk
        except OSError:
            pass
        if got:
            reply = json.loads(got.decode().splitlines()[0])
            assert reply["code"] == "serve.conn_idle", reply
        ghost.close()
        # the server is fully alive after evicting the ghost: a prompt
        # client (no 1s gaps between lines) is served normally
        live = socket.create_connection(("127.0.0.1", port), timeout=30)
        lf = live.makefile("rw")
        for req_id in (1, 2):
            lf.write(json.dumps(
                {"id": req_id, "model": "tiny", "x": x[:2].tolist()}
            ) + "\n")
            lf.flush()
            answer = json.loads(lf.readline())
            assert "mean" in answer, answer
        lf.write(json.dumps({"cmd": "shutdown"}) + "\n")
        lf.flush()
        live.close()
    finally:
        _finish_tcp(proc)


def test_tcp_replica_transport_round_trip_and_unreachable(tiny_model):
    """The fleet router's TCP leg against a REAL CLI replica: predicts
    round-trip through the ring, health carries the replica identity,
    and the process dying surfaces as the failover-eligible
    ReplicaUnreachableError — exactly what the router needs to re-route."""
    from spark_gp_tpu.parallel.coord import (
        InProcessCoordClient,
        InProcessCoordStore,
    )
    from spark_gp_tpu.serve.fleet import FleetMembership
    from spark_gp_tpu.serve.router import (
        FleetRouter,
        ReplicaUnreachableError,
        TcpReplicaTransport,
        failover_eligible,
    )

    path, model, x = tiny_model
    proc, port = _popen_tcp(
        ["--model", f"tiny={path}", "--max-batch", "16", "--min-bucket", "4",
         "--replica-id", "tcp-r0", "--conn-read-timeout-s", "0"],
    )
    transport = TcpReplicaTransport(f"127.0.0.1:{port}", "tcp-r0")
    try:
        membership = FleetMembership(
            InProcessCoordClient(InProcessCoordStore(), 0, 1),
            fleet="tcp", interval_s=0.05,
        )
        membership.register("tcp-r0", address=f"127.0.0.1:{port}")
        router = FleetRouter(
            membership, {"tcp-r0": transport},
            max_batch=16, min_bucket=4, default_timeout_ms=30_000.0,
            poll_interval_s=0.0,
        )
        mean, var = router.predict("tiny", x[:3])
        np.testing.assert_allclose(
            mean, model.predict(x[:3]), rtol=1e-4, atol=1e-5
        )
        assert len(var) == 3
        health = transport.health()
        assert health["replica"]["replica_id"] == "tcp-r0"
        # the replica dies: pending/submit surface the unreachable verdict
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
        deadline = 30.0
        import time as _time
        t0 = _time.monotonic()
        while _time.monotonic() - t0 < deadline:
            try:
                transport.submit("tiny", x[:2], timeout_ms=1_000.0)
                _time.sleep(0.05)
            except ReplicaUnreachableError as exc:
                assert failover_eligible(exc)
                break
        else:
            pytest.fail("dead TCP replica never reported unreachable")
    finally:
        transport.close()
        if proc.poll() is None:
            os.killpg(proc.pid, signal.SIGKILL)
        _finish_tcp(proc, timeout=10)


def test_cli_tcp_connection_limit(tiny_model):
    """Connections past --max-connections are refused at the door with
    one classified code=serve.conn_limit line, never silently queued."""
    import socket

    path, model, x = tiny_model
    proc, port = _popen_tcp(
        ["--model", f"tiny={path}", "--max-batch", "16", "--min-bucket", "4",
         "--max-connections", "1", "--conn-read-timeout-s", "60"],
    )
    try:
        holder = socket.create_connection(("127.0.0.1", port), timeout=30)
        hf = holder.makefile("rw")
        # prove the slot-holder is live before probing the limit
        hf.write(json.dumps(
            {"id": 1, "model": "tiny", "x": x[:2].tolist()}
        ) + "\n")
        hf.flush()
        assert "mean" in json.loads(hf.readline())
        # the second connection is over the bound: one refusal line + EOF
        extra = socket.create_connection(("127.0.0.1", port), timeout=30)
        xf = extra.makefile("r")
        refusal = json.loads(xf.readline())
        assert refusal["code"] == "serve.conn_limit", refusal
        assert xf.readline() == ""  # closed after the refusal
        extra.close()
        # the holder is unaffected
        hf.write(json.dumps(
            {"id": 2, "model": "tiny", "x": x[:2].tolist()}
        ) + "\n")
        hf.flush()
        assert "mean" in json.loads(hf.readline())
        hf.write(json.dumps({"cmd": "shutdown"}) + "\n")
        hf.flush()
        holder.close()
    finally:
        _finish_tcp(proc)
