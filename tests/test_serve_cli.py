"""Tier-1 smoke for the serve CLI: boot -> warmup-ready -> score -> clean
shutdown, in a subprocess under JAX_PLATFORMS=cpu.

The subprocess is timeout-fenced with a process-group kill on expiry
(the utils/subproc.py hazard pattern: never let a wedged child hold the
suite), but unlike run_captured it needs a stdin leg — the protocol IS
stdin JSON lines.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel


@pytest.fixture(scope="module")
def tiny_model(tmp_path_factory):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(120, 3))
    y = np.sin(x.sum(axis=1))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(30)
        .setSigma2(1e-3)
        .setMaxIter(5)
        .setSeed(1)
        .fit(x, y)
    )
    path = str(tmp_path_factory.mktemp("cli") / "tiny.npz")
    model.save(path)
    return path, model, x


def _run_cli(args, input_text, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the CLI manages a plain single-device CPU process; drop the
    # harness's forced 8-device flag and any compile-cache override
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_gp_tpu.serve", *args],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(input_text, timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        out, err = proc.communicate()
        pytest.fail(f"serve CLI wedged past {timeout}s; stderr: {err[-500:]}")
    return proc.returncode, out, err


def test_cli_boot_score_shutdown(tiny_model):
    path, model, x = tiny_model
    request_rows = x[:3].tolist()
    lines = "\n".join(
        [
            json.dumps({"id": 1, "model": "tiny", "x": request_rows}),
            json.dumps({"cmd": "metrics"}),
            json.dumps({"cmd": "shutdown"}),
        ]
    ) + "\n"
    rc, out, err = _run_cli(
        ["--model", f"tiny={path}", "--max-batch", "16", "--min-bucket", "4"],
        lines,
    )
    assert rc == 0, err[-500:]
    events = [json.loads(ln) for ln in out.strip().splitlines()]

    # ready is FIRST — warmup completes before any request is answered
    assert events[0]["event"] == "ready"
    assert events[0]["platform"] == "cpu"
    [desc] = events[0]["models"]
    assert desc["name"] == "tiny" and desc["version"] == 1
    # the AOT stage compiled the whole ladder at load
    assert sorted(int(b) for b in desc["compiles"]) == [4, 8, 16]
    assert all(c == 1 for c in desc["compiles"].values())

    by_id = {e["id"]: e for e in events if "id" in e}
    answer = by_id[1]
    assert "error" not in answer, answer
    # the CLI subprocess runs f32 (no x64 harness): parity is approximate
    np.testing.assert_allclose(
        answer["mean"], model.predict(x[:3]), rtol=1e-4, atol=1e-5
    )
    assert len(answer["var"]) == 3

    metrics = next(e for e in events if e.get("event") == "metrics")
    assert metrics["counters"]["requests"] >= 1
    assert "request_latency_s" in metrics["histograms"]

    assert events[-1]["event"] == "shutdown"
    assert events[-1]["requests"] >= 1


def test_cli_rejects_bad_request_and_survives(tiny_model):
    path, _, x = tiny_model
    lines = "\n".join(
        [
            "this is not json",
            json.dumps({"id": 7, "model": "ghost", "x": x[:2].tolist()}),
            json.dumps({"id": 8, "model": "tiny", "x": x[:2].tolist()}),
            json.dumps({"cmd": "shutdown"}),
        ]
    ) + "\n"
    rc, out, err = _run_cli(["--model", f"tiny={path}"], lines)
    assert rc == 0, err[-500:]
    events = [json.loads(ln) for ln in out.strip().splitlines()]
    assert any("bad request line" in e.get("error", "") for e in events)
    by_id = {e["id"]: e for e in events if "id" in e}
    assert "KeyError" in by_id[7]["error"]  # unknown model: error response
    assert "mean" in by_id[8]            # ...and the next request still works


def test_cli_requires_a_model():
    rc, out, err = _run_cli([], "")
    assert rc == 2
    assert "--model" in err
