"""Worker body for the multi-host coordination-hardening tests
(test_multiprocess.py): kill-and-elastic-resume and dead-host no-hang,
with REAL process boundaries.

Modes (argv[1]):

* ``fit <pid> <nproc> <port> <ckpt_dir>`` — join the cluster, fit a GPR
  over this process's deterministic row shard via the DCN-fallback path
  with coordinated host checkpoints, print ``THETA <json>``.  Chaos is
  staged by the parent through the env (``GP_CHAOS_KILL_AFTER_ITERS``,
  ``GP_CHAOS_DEAD_HOST``, ``GP_COORD_TIMEOUT_S``).  A
  CoordinationTimeoutError exits rc=3 after printing
  ``COORDTIMEOUT missing=<ids>`` — the parent asserts both the exit
  path and the named processes.
* ``resume <nproc_orig> <ckpt_dir>`` — SINGLE process, no cluster: build
  the union of all original shards' expert stacks (same global expert
  assignment, re-sharded) and resume from the coordinated checkpoint —
  the elastic P -> 1 transition.  Prints ``THETA <json>`` and
  ``ELASTIC <n>`` (the coord.elastic_resumes counter).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
# f64 end-to-end: the theta-reproduction proof compares the 2-process
# KV-summed objective against the 1-process union objective, whose f64
# summation-order difference is ~1e-16 — in f32 it shifts the optimum by
# ~1e-5, an order above the 1e-6 acceptance bar
jax.config.update("jax_enable_x64", True)

EXPERT_SIZE = 16


def shard_rows(pid: int):
    import numpy as np

    # sizes grouping to identical expert widths so the union stack can
    # concatenate the per-host stacks (the elastic-resume requirement)
    rng = np.random.default_rng(100 + pid)
    n = 144 if pid == 0 else 112
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.01 * rng.normal(size=n)
    return x, y


def make_gp(ckpt_dir: str):
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    return (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(48)
        .setMaxIter(50)
        .setTol(1e-13)
        .setSeed(3)
        .setCheckpointDir(ckpt_dir)
    )


def mode_fit() -> int:
    pid, nproc, port = int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4])
    ckpt_dir = sys.argv[5]

    from spark_gp_tpu.parallel import distributed as dist
    from spark_gp_tpu.parallel.coord import CoordinationTimeoutError

    dist.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    mesh = dist.global_expert_mesh()
    x, y = shard_rows(pid)
    data = dist.distribute_global_experts(x, y, EXPERT_SIZE, mesh)
    try:
        model = make_gp(ckpt_dir).setMesh(mesh).fit_distributed(data)
    except CoordinationTimeoutError as exc:
        print(f"COORDTIMEOUT missing={list(exc.missing)}", flush=True)
        # hard exit: interpreter teardown would run jax's coordination
        # shutdown barrier, which blocks ~60 s on the already-dead peer
        # and then aborts the process — exactly the hang-on-death behavior
        # the guarded path just avoided
        os._exit(3)
    theta = [float(v) for v in model.raw_predictor.theta]
    print("THETA " + json.dumps({"pid": pid, "theta": theta}), flush=True)
    return 0


def mode_resume() -> int:
    nproc_orig, ckpt_dir = int(sys.argv[2]), sys.argv[3]
    import jax.numpy as jnp

    from spark_gp_tpu.obs.runtime import telemetry
    from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts
    from spark_gp_tpu.parallel.mesh import expert_mesh, shard_experts

    mesh = expert_mesh()
    stacks = [
        shard_experts(
            group_for_experts(*shard_rows(pid), EXPERT_SIZE), mesh
        )
        for pid in range(nproc_orig)
    ]
    union = shard_experts(
        ExpertData(
            x=jnp.concatenate([s.x for s in stacks]),
            y=jnp.concatenate([s.y for s in stacks]),
            mask=jnp.concatenate([s.mask for s in stacks]),
        ),
        mesh,
    )
    model = make_gp(ckpt_dir).setMesh(mesh).fit_distributed(union)
    theta = [float(v) for v in model.raw_predictor.theta]
    print("THETA " + json.dumps({"pid": 0, "theta": theta}), flush=True)
    print(
        f"ELASTIC {int(telemetry.counters.get('coord.elastic_resumes', 0))}"
        f" RESUMED {int(model.instr.metrics.get('resumed_from_iteration', 0))}",
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(mode_fit() if sys.argv[1] == "fit" else mode_resume())
