"""Tier-1 wrapper for the chaos-soak driver (``tools/soak.py``).

The soak's system invariant — every seeded campaign terminates within
deadline with a tolerance-correct result or a single classified error,
no hangs, no thread/artifact leaks — rides tier-1 at the acceptance
budget (``--seeds 25``); the widened ``--deep`` soak runs under the
``slow`` marker.  The broken-rung test proves the falsifiability
contract: a deliberately-wedged ladder rung is caught as an UNCLASSIFIED
violation and reproduces deterministically from the printed seed.
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tools"))

import soak  # noqa: E402


def _scenario_of(seed: int) -> str:
    import numpy as np

    rng = np.random.default_rng(seed)
    return soak.SCENARIOS[int(rng.integers(0, len(soak.SCENARIOS)))]


def test_soak_acceptance_budget_in_process(tmp_path, monkeypatch):
    """25 seeded campaigns — the acceptance criterion's budget — with
    zero hangs and zero unclassified failures.  In-process (the jit
    caches are warm from the suite), cwd pinned to a scratch dir so the
    artifact-leak check bites."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("GP_RUN_JOURNAL_DIR", raising=False)
    scenarios = set()
    for seed in range(25):
        result = soak.run_campaign(seed, deadline_s=120.0)
        assert result["outcome"] == "ok" or result["outcome"].startswith(
            "classified:"
        ), result
        scenarios.add(result["scenario"])
    # the seed range actually sweeps the arsenal, not one lucky scenario
    assert len(scenarios) >= 5, scenarios


def test_broken_rung_reproduces_from_seed(tmp_path, monkeypatch):
    """A deliberately-wedged segmented rung (its in-memory saver raises an
    unclassifiable error) turns an oom_fit campaign into a soak VIOLATION
    — and the violation reproduces from the same seed, deterministically."""
    monkeypatch.chdir(tmp_path)
    from spark_gp_tpu.resilience import fallback

    oom_seed = next(
        s for s in range(200) if _scenario_of(s) == "oom_fit"
    )
    # sanity: the unbroken rung passes this seed
    assert soak.run_campaign(oom_seed)["outcome"] == "ok"

    def wedged(self, state, meta):
        raise RuntimeError("wedged segment rung (deliberate breakage)")

    monkeypatch.setattr(fallback.NullSegmentSaver, "save", wedged)
    with pytest.raises(soak.Violation, match="unclassified"):
        soak.run_campaign(oom_seed)
    # the printed repro seed replays the exact violation
    with pytest.raises(soak.Violation, match="unclassified"):
        soak.run_campaign(oom_seed)


def test_soak_cli_contract(tmp_path):
    """The CLI contract the round driver and the acceptance criteria use:
    one JSON line per campaign + a summary line, exit 0."""
    import json

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("GP_RUN_JOURNAL_DIR", None)
    for var in list(env):
        if var.startswith("GP_CHAOS_"):
            env.pop(var)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "soak.py"),
         "--seeds", "4"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-800:]
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert len(lines) == 5  # 4 campaigns + summary
    assert lines[-1]["summary"]["campaigns"] == 4
    assert lines[-1]["summary"]["passed"] is True


@pytest.mark.slow
def test_soak_deep(tmp_path):
    """The widened soak: 100 seeds at deep shapes (slow marker)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("GP_RUN_JOURNAL_DIR", None)
    for var in list(env):
        if var.startswith("GP_CHAOS_"):
            env.pop(var)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "soak.py"), "--deep"],
        capture_output=True, text=True, timeout=3000, env=env,
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-800:]
