"""Worker body for the REAL multi-process DCN test (test_multiprocess.py).

Each process owns a disjoint shard of the rows (the HDFS-partition analogue,
GaussianProcessCommons.scala:20-24), joins the coordination plane, stitches
its rows into the globally-sharded expert stack, runs the regression,
binary-classifier and multiclass ``fit_distributed`` paths, and prints one
JSON line of results for the parent to cross-check across processes.

Run (by the test): python tests/_mp_worker.py <pid> <nproc> <port>
"""

import json
import os
import sys

# launched as ``python tests/_mp_worker.py`` — sys.path[0] is tests/, so the
# package root must be added explicitly (the parent's pytest path setup does
# not cross the process boundary)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    import numpy as np

    from spark_gp_tpu.parallel import distributed as dist

    dist.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=pid,
    )
    assert dist.num_processes() == nproc
    mesh = dist.global_expert_mesh()

    from spark_gp_tpu import (
        GaussianProcessClassifier,
        GaussianProcessRegression,
        RBFKernel,
    )

    # Disjoint per-process rows; DELIBERATELY unequal counts so the
    # cross-host expert-stack padding (_pad_stack) is exercised.
    rng = np.random.default_rng(100 + pid)
    n_local = 140 if pid == 0 else 104
    x_local = rng.normal(size=(n_local, 2))
    y_local = np.sin(x_local.sum(axis=1)) + 0.01 * rng.normal(size=n_local)

    data = dist.distribute_global_experts(x_local, y_local, 16, mesh)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(48)
        .setMaxIter(15)
        .setSeed(3)
        .setMesh(mesh)
        .fit_distributed(data)
    )
    probe = np.random.default_rng(999).normal(size=(32, 2))  # shared seed
    pred = model.predict(probe)

    yc_local = (x_local.sum(axis=1) > 0).astype(np.float64)
    cdata = dist.distribute_global_experts(x_local, yc_local, 16, mesh)
    cmodel = (
        GaussianProcessClassifier()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(48)
        .setMaxIter(10)
        .setSeed(3)
        .setMesh(mesh)
        .fit_distributed(cdata)
    )
    cpred = cmodel.predict_proba(probe)[:, 1]

    # native multiclass over the same shards (3 quantile-ish buckets)
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    ym_local = np.digitize(x_local.sum(axis=1), [-0.5, 0.5]).astype(np.float64)
    mdata = dist.distribute_global_experts(x_local, ym_local, 16, mesh)
    mmodel = (
        GaussianProcessMulticlassClassifier()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(48)
        .setMaxIter(8)
        .setSeed(3)
        .setMesh(mesh)
        .fit_distributed(mdata)
    )
    mpred = mmodel.predict_raw(probe)

    # training-fit quality on the local shard (loose: tiny maxiter)
    rmse_local = float(
        np.sqrt(np.mean((model.predict(x_local) - y_local) ** 2))
    )
    print(
        "MPRESULT "
        + json.dumps(
            {
                "pid": pid,
                "n_global_devices": len(jax.devices()),
                "pred": np.round(pred, 10).tolist(),
                "cpred": np.round(cpred, 10).tolist(),
                "mpred": np.round(np.asarray(mpred), 10).tolist(),
                "rmse_local": rmse_local,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
