"""BCM likelihood vs an exact-GP oracle.

SURVEY.md §7 step 2: with E = 1 (one expert holding everything) the BCM NLL
must equal the exact GP marginal likelihood; with E > 1 it must equal the sum
of independent per-chunk exact NLLs; padding must not change values; autodiff
gradients must match finite differences of the oracle.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels import Const, EyeKernel, RBFKernel, WhiteNoiseKernel
from spark_gp_tpu.models.likelihood import batched_nll, make_value_and_grad
from spark_gp_tpu.parallel.experts import group_for_experts


def _exact_nll(kernel, theta, x, y):
    """0.5 y^T K^-1 y + 0.5 log|K| — GPR.scala:55-61 (no constant term)."""
    kmat = np.asarray(kernel.gram(jnp.asarray(theta), jnp.asarray(x)))
    sign, logdet = np.linalg.slogdet(kmat)
    alpha = np.linalg.solve(kmat, y)
    return 0.5 * float(y @ alpha) + 0.5 * float(logdet)


@pytest.fixture
def problem(rng):
    n, p = 60, 3
    x = rng.normal(size=(n, p))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    kernel = RBFKernel(1.2) + Const(1e-2) * EyeKernel()
    return x, y, kernel


def test_single_expert_equals_exact_gp(problem):
    x, y, kernel = problem
    theta = kernel.init_theta()
    data = group_for_experts(x, y, dataset_size_for_expert=1000)  # E = 1
    assert data.num_experts == 1
    ours = float(batched_nll(kernel, jnp.asarray(theta), data))
    oracle = _exact_nll(kernel, theta, x, y)
    np.testing.assert_allclose(ours, oracle, rtol=1e-9)


def test_multi_expert_equals_sum_of_chunk_oracles(problem):
    x, y, kernel = problem
    theta = kernel.init_theta()
    data = group_for_experts(x, y, dataset_size_for_expert=13)
    e = data.num_experts
    ours = float(batched_nll(kernel, jnp.asarray(theta), data))
    oracle = sum(
        _exact_nll(kernel, theta, x[np.arange(j, x.shape[0], e)], y[np.arange(j, x.shape[0], e)])
        for j in range(e)
    )
    np.testing.assert_allclose(ours, oracle, rtol=1e-9)


def test_padding_invariance(problem):
    """Fully-masked extra experts and padded tails change nothing."""
    x, y, kernel = problem
    theta = jnp.asarray(kernel.init_theta())
    data = group_for_experts(x, y, dataset_size_for_expert=13)
    padded = data.pad_experts(8)
    v1 = float(batched_nll(kernel, theta, data))
    v2 = float(batched_nll(kernel, theta, padded))
    np.testing.assert_allclose(v1, v2, rtol=1e-12)


def test_value_and_grad_matches_fd(problem):
    x, y, kernel = problem
    data = group_for_experts(x, y, dataset_size_for_expert=20)
    vag = make_value_and_grad(kernel, data)
    theta0 = kernel.init_theta()
    value, grad = vag(jnp.asarray(theta0))

    h = 1e-6
    fd = np.zeros_like(theta0)
    for i in range(theta0.size):
        tp, tm = theta0.copy(), theta0.copy()
        tp[i] += h
        tm[i] -= h
        fd[i] = (float(vag(jnp.asarray(tp))[0]) - float(vag(jnp.asarray(tm))[0])) / (
            2 * h
        )
    np.testing.assert_allclose(np.asarray(grad), fd, rtol=1e-5, atol=1e-8)


def test_trainable_noise_gradient(problem):
    """Gradient flows into WhiteNoise coefficient and scalar amplitude."""
    x, y, _ = problem
    kernel = 1.0 * RBFKernel(0.8) + WhiteNoiseKernel(0.5, 0, 1) + Const(1e-3) * EyeKernel()
    data = group_for_experts(x, y, dataset_size_for_expert=20)
    vag = make_value_and_grad(kernel, data)
    _, grad = vag(jnp.asarray(kernel.init_theta()))
    assert np.all(np.isfinite(np.asarray(grad)))
    assert np.any(np.abs(np.asarray(grad)) > 0)
