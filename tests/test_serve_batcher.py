"""Shape-bucketed micro-batcher guarantees (serve/batcher.py).

The three serving invariants ISSUE 1 names: padded rows never leak into
returned means/variances, bucket selection lands on exact power-of-two
boundaries, and sustained mixed-size traffic compiles each (model,
bucket) executable exactly once — asserted through the batcher's
trace-time compile-counting hook.
"""

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.serve.batcher import (
    BucketOverflowError,
    BucketedPredictor,
    RecompileGuardError,
    bucket_sizes,
)


@pytest.fixture(scope="module")
def fitted():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(200, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=200)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(40)
        .setSigma2(1e-3)
        .setMaxIter(8)
        .setSeed(5)
        .fit(x, y)
    )
    return model, x


def test_bucket_ladder_and_boundaries():
    assert bucket_sizes(256, 8) == (8, 16, 32, 64, 128, 256)
    # non-powers round up on both ends
    assert bucket_sizes(100, 3) == (4, 8, 16, 32, 64, 128)
    assert bucket_sizes(1, 1) == (1,)
    with pytest.raises(ValueError):
        bucket_sizes(4, 32)
    with pytest.raises(ValueError):
        bucket_sizes(0)


def test_bucket_for_exact_boundaries(fitted):
    model, _ = fitted
    bp = BucketedPredictor(model.raw_predictor, max_batch=64, min_bucket=8)
    assert bp.buckets == (8, 16, 32, 64)
    assert bp.bucket_for(1) == 8
    assert bp.bucket_for(8) == 8      # exact fit: no promotion
    assert bp.bucket_for(9) == 16     # one past: next rung
    assert bp.bucket_for(16) == 16
    assert bp.bucket_for(17) == 32
    assert bp.bucket_for(64) == 64
    assert bp.bucket_for(65) is None  # oversize: caller chunks
    # occupancy accounting mirrors the dispatch plan
    assert bp.padded_rows(1) == 8
    assert bp.padded_rows(64) == 64
    assert bp.padded_rows(65) == 64 + 8
    assert bp.padded_rows(130) == 64 + 64 + 8
    assert bp.padded_rows(0) == 0


def test_padding_never_leaks_into_results(fitted):
    """Across every bucket boundary (and the chunked oversize path) the
    bucketed answers equal the unbatched predictor's exactly."""
    model, x = fitted
    raw = model.raw_predictor
    bp = BucketedPredictor(raw, max_batch=64, min_bucket=8)
    bp.warmup()
    for t in (1, 7, 8, 9, 15, 16, 17, 31, 32, 63, 64, 65, 100, 150):
        mean, var = bp.predict(x[:t])
        ref_mean, ref_var = raw(x[:t])
        assert mean.shape == (t,) and var.shape == (t,)
        np.testing.assert_allclose(mean, np.asarray(ref_mean), rtol=1e-10)
        np.testing.assert_allclose(var, np.asarray(ref_var), rtol=1e-10)


def test_one_compile_per_bucket_under_mixed_traffic(fitted):
    """Sustained mixed-size traffic: exactly one XLA trace per bucket,
    all paid at warmup — zero compiles on the hot path."""
    model, x = fitted
    bp = BucketedPredictor(model.raw_predictor, max_batch=64, min_bucket=8)
    counts = bp.warmup()
    assert counts == {8: 1, 16: 1, 32: 1, 64: 1}
    rng = np.random.default_rng(0)
    for t in rng.integers(1, 65, size=50):
        bp.predict(x[: int(t)])
    # float32 payloads must not open a second executable family either
    bp.predict(x[:10].astype(np.float32))
    assert bp.compile_counts == {8: 1, 16: 1, 32: 1, 64: 1}
    assert bp.total_compiles == len(bp.buckets)


def test_recompile_guard_fires_on_unwarmed_bucket(fitted):
    model, x = fitted
    bp = BucketedPredictor(model.raw_predictor, buckets=(8, 16))
    bp.warmup()
    # an in-ladder request is fine...
    bp.predict(x[:5])
    # ...but a frozen surface refuses to grow: simulate a config drift by
    # widening the ladder after warmup
    bp.buckets = (8, 16, 32)
    with pytest.raises(RecompileGuardError, match="not warmed"):
        bp.predict(x[:20])


def test_oversize_chunking_and_overflow_error(fitted):
    model, x = fitted
    bp = BucketedPredictor(model.raw_predictor, max_batch=32, min_bucket=8)
    bp.warmup()
    mean, var = bp.predict(x[:150])  # 32+32+32+32+16+8... chunked
    ref_mean, _ = model.raw_predictor(x[:150])
    np.testing.assert_allclose(mean, np.asarray(ref_mean), rtol=1e-10)
    assert bp.compile_counts == {8: 1, 16: 1, 32: 1}
    with pytest.raises(BucketOverflowError, match="exceeds the largest"):
        bp.predict(x[:150], chunk_oversize=False)


def test_mean_only_mode(fitted):
    model, x = fitted
    bp = BucketedPredictor(
        model.raw_predictor, max_batch=16, min_bucket=8, mean_only=True
    )
    bp.warmup()
    mean, var = bp.predict(x[:10])
    assert var is None
    np.testing.assert_allclose(
        mean, np.asarray(model.raw_predictor.predict_mean(x[:10])), rtol=1e-10
    )


def test_input_validation(fitted):
    model, x = fitted
    bp = BucketedPredictor(model.raw_predictor, max_batch=8)
    with pytest.raises(ValueError, match=r"\[t, 3\]"):
        bp.predict(x[:4, :2])
    with pytest.raises(ValueError, match=r"\[t, 3\]"):
        bp.predict(np.zeros(3))
    mean, var = bp.predict(x[:0])  # empty request: empty answer, no dispatch
    assert mean.shape == (0,) and var.shape == (0,)
