"""Tests for the batched Pallas SPD sweep (ops/pallas_linalg.py).

The TPU kernel is exercised through the Pallas interpreter so CI stays
CPU-only — the math (blocked symmetric sweep) is identical; only the Mosaic
lowering differs.  The public ``spd_inv_logdet`` entry falls back to the
Cholesky path on CPU, which the rest of the suite covers transitively via
the likelihood oracle tests.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from spark_gp_tpu.ops.pallas_linalg import (
    _chol_inv_logdet,
    _pallas_inv_logdet,
    spd_inv_logdet,
)


def _spd_batch(b, n, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=(b, n, n)).astype(dtype)
    return a @ a.transpose(0, 2, 1) + n * np.eye(n, dtype=dtype)


@pytest.mark.parametrize(
    "b,n",
    [
        (1, 4),  # tiny, packed 4x32
        (5, 60),  # packed 2x64 with inner identity pad
        (7, 64),  # packed 2x64 exact
        (8, 128),  # single tile exact
        (3, 100),  # 8-multiple pad: 104 with (32,32,32,8) blocks
        (3, 200),  # multi-block: (64,64,64,8)
        (2, 256),  # multi-block, reduced tile count
        (1, 512),  # largest Pallas size, T=1
    ],
)
def test_sweep_matches_numpy(b, n):
    k = _spd_batch(b, n)
    kinv, ld = _pallas_inv_logdet(jnp.asarray(k), interpret=True)
    kinv_ref = np.linalg.inv(k.astype(np.float64))
    _, ld_ref = np.linalg.slogdet(k.astype(np.float64))
    scale = np.max(np.abs(kinv_ref))
    np.testing.assert_allclose(np.asarray(kinv), kinv_ref, atol=5e-5 * scale)
    np.testing.assert_allclose(np.asarray(ld), ld_ref, rtol=1e-5, atol=1e-4)


def test_sweep_batch_padding():
    # batch not a multiple of the sublane tile: pad entries are identity
    # matrices and must not leak into real outputs
    k = _spd_batch(3, 100, seed=1)
    kinv, ld = _pallas_inv_logdet(jnp.asarray(k), interpret=True)
    assert kinv.shape == (3, 100, 100)
    assert ld.shape == (3,)
    _, ld_ref = np.linalg.slogdet(k.astype(np.float64))
    np.testing.assert_allclose(np.asarray(ld), ld_ref, rtol=1e-5, atol=1e-4)


def test_fallback_matches_sweep():
    k = _spd_batch(4, 32, seed=2)
    kinv_f, ld_f = _chol_inv_logdet(jnp.asarray(k))
    kinv_p, ld_p = _pallas_inv_logdet(jnp.asarray(k), interpret=True)
    np.testing.assert_allclose(
        np.asarray(kinv_f), np.asarray(kinv_p), atol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(ld_f), np.asarray(ld_p), rtol=1e-5, atol=1e-4
    )


def test_custom_vjp_matches_autodiff_cholesky():
    """Gradient through spd_inv_logdet == autodiff through the plain
    Cholesky formulation (the public entry uses the fallback on CPU, so
    this validates the custom VJP formula itself)."""
    k = _spd_batch(3, 20, seed=3, dtype=np.float64)
    y = np.random.default_rng(4).normal(size=(3, 20))

    def nll_via_entry(km):
        kinv, ld = spd_inv_logdet(km)
        alpha = jnp.einsum("bij,bj->bi", kinv, jnp.asarray(y))
        return 0.5 * jnp.einsum("bi,bi->", jnp.asarray(y), alpha) + 0.5 * jnp.sum(ld)

    def nll_via_chol(km):
        chol = jnp.linalg.cholesky(km)
        sol = jax.scipy.linalg.cho_solve((chol, True), y)
        ld = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(chol, axis1=-2, axis2=-1)), axis=-1
        )
        return 0.5 * jnp.einsum("bi,bi->", jnp.asarray(y), sol) + 0.5 * jnp.sum(ld)

    g_entry = jax.grad(nll_via_entry)(jnp.asarray(k))
    g_chol = jax.grad(nll_via_chol)(jnp.asarray(k))
    np.testing.assert_allclose(
        np.asarray(g_entry), np.asarray(g_chol), rtol=1e-8, atol=1e-10
    )


@pytest.mark.tpu
@pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="needs real TPU (Mosaic lowering)"
)
@pytest.mark.parametrize("b,n", [(16, 64), (8, 100), (8, 128), (4, 200)])
def test_mosaic_lowering_matches_fallback_on_tpu(b, n):
    """The compiled (non-interpret) Mosaic lowering — what production fits
    actually run — against the XLA Cholesky fallback on device.  CI runs
    the interpreter only; this closes the lowering gap when a chip is
    present (ADVICE r1: interpret=True never exercises the real kernel)."""
    k = jnp.asarray(_spd_batch(b, n, seed=5))
    kinv_p, ld_p = _pallas_inv_logdet(k, interpret=False)
    kinv_f, ld_f = _chol_inv_logdet(k)
    scale = float(jnp.max(jnp.abs(kinv_f)))
    np.testing.assert_allclose(
        np.asarray(kinv_p), np.asarray(kinv_f), atol=1e-4 * max(scale, 1.0)
    )
    np.testing.assert_allclose(
        np.asarray(ld_p), np.asarray(ld_f), rtol=1e-4, atol=1e-3
    )


def test_non_pd_yields_nan():
    k = np.eye(8, dtype=np.float32)[None].repeat(2, 0)
    k[1, 0, 0] = -1.0  # indefinite
    kinv, ld = _pallas_inv_logdet(jnp.asarray(k), interpret=True)
    assert np.isfinite(np.asarray(ld)[0])
    assert not np.isfinite(np.asarray(ld)[1])


def test_matmul_precision_knob(monkeypatch):
    """GP_MATMUL_PRECISION maps to the lax.Precision enum (trace-time knob
    for the blocked-inverse matmuls and the VJP, r5 MFU campaign), defaults
    to HIGHEST, and the interpreter-mode kernel stays numerically correct
    under the 'high' setting (on CPU all settings lower identically — this
    pins the plumbing; the accuracy/speed trade is measured on hardware by
    benchmarks/roofline.py)."""
    from spark_gp_tpu.ops.pallas_linalg import _matmul_precision

    monkeypatch.delenv("GP_MATMUL_PRECISION", raising=False)
    assert _matmul_precision() == jax.lax.Precision.HIGHEST
    for name, want in (
        ("highest", jax.lax.Precision.HIGHEST),
        ("high", jax.lax.Precision.HIGH),
        ("default", jax.lax.Precision.DEFAULT),
        ("HIGH", jax.lax.Precision.HIGH),  # case-insensitive
    ):
        monkeypatch.setenv("GP_MATMUL_PRECISION", name)
        assert _matmul_precision() == want
    with pytest.raises(ValueError, match="GP_MATMUL_PRECISION"):
        monkeypatch.setenv("GP_MATMUL_PRECISION", "bf16")
        _matmul_precision()

    monkeypatch.setenv("GP_MATMUL_PRECISION", "high")
    k = _spd_batch(2, 36, seed=9)
    # fresh trace so the knob is actually read.  clear_caches, NOT
    # disable_jit: pallas_call's interpret-mode impl re-enters itself
    # unjitted on this jax version (0.4.37) and recurses to death.
    jax.clear_caches()
    kinv, ld = _pallas_inv_logdet(jnp.asarray(k), interpret=True)
    want_inv = np.linalg.inv(k.astype(np.float64))
    np.testing.assert_allclose(np.asarray(kinv), want_inv, atol=5e-4)
    np.testing.assert_allclose(
        np.asarray(ld), np.linalg.slogdet(k.astype(np.float64))[1], rtol=1e-5
    )
