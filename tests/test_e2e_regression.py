"""End-to-end regression acceptance tests — the reference's examples as
asserted quality thresholds (SURVEY.md §4, §6).

Synthetics: 2000 points sin(x)+noise, kernel 1*RBF(0.1,1e-6,10) +
WhiteNoise(0.5,0,1), expert 100, active 100, sigma2 1e-3, KMeans provider —
10-fold CV RMSE < 0.11 (Synthetics.scala:26-33).  A reduced-fold variant is
run here to keep CI fast; the full 10-fold config lives in
examples/synthetics.py.
"""

import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessRegression,
    KMeansActiveSetProvider,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.data import make_synthetics
from spark_gp_tpu.utils.validation import cross_validate, rmse


def _synthetics_gp():
    return (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1))
        .setDatasetSizeForExpert(100)
        .setActiveSetProvider(KMeansActiveSetProvider())
        .setActiveSetSize(100)
        .setSeed(13)
        .setSigma2(1e-3)
    )


def test_synthetics_rmse_under_011():
    """The reference's headline acceptance: RMSE < 0.11 (Synthetics.scala:33)."""
    x, y = make_synthetics()
    score = cross_validate(_synthetics_gp(), x, y, num_folds=3, metric=rmse, seed=13)
    assert score < 0.11, f"synthetics RMSE {score} >= 0.11"


def test_fit_predict_roundtrip():
    x, y = make_synthetics(n=400)
    gp = _synthetics_gp().setActiveSetSize(50)
    model = gp.fit(x, y)
    mean, var = model.predict_with_var(x)
    assert mean.shape == (400,)
    assert var.shape == (400,)
    assert np.all(np.isfinite(mean))
    # predictive variance is positive and includes the noise floor
    assert np.all(var > 0)
    # in-sample fit should track sin(x) closely
    assert rmse(y, mean) < 0.11


def test_model_save_load_roundtrip(tmp_path):
    x, y = make_synthetics(n=300)
    model = _synthetics_gp().setActiveSetSize(40).fit(x, y)
    path = str(tmp_path / "model.npz")
    model.save(path)
    from spark_gp_tpu import GaussianProcessRegressionModel

    restored = GaussianProcessRegressionModel.load(path)
    np.testing.assert_allclose(restored.predict(x[:20]), model.predict(x[:20]), rtol=1e-12)
    # fit provenance rode along: the saved file records the process
    # topology that produced the BCM aggregate (utils/serialization.py)
    # a clean fit records an EMPTY degradation history (the ladder's
    # provenance stamp, resilience/fallback.py)
    assert restored.provenance["process_count"] == 1
    assert restored.provenance["degradations"] == []
    # and the fit-time covariate summary the serve drift monitor scores
    # against (obs/quality.summarize_covariates)
    summary = restored.provenance["covariate_summary"]
    assert summary["dims"] == x.shape[1] and summary["n"] == x.shape[0]
    assert restored.covariate_summary == summary


def test_duplicate_rows_survive_via_jitter(rng):
    """Exactly duplicated training rows make K_mm numerically singular; the
    escalating-jitter PSD repair must keep the fit alive (the reference
    would throw NotPositiveDefiniteException from its eigSym assert)."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    x = rng.normal(size=(100, 2))
    x = np.concatenate([x, x[:40]])  # 40 exact duplicates
    y = np.sin(x.sum(axis=1))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(120)  # active set will include duplicate pairs
        .setSigma2(1e-6)        # tiny noise: K_mm is genuinely near-singular
        .setMaxIter(10)
        .fit(x, y)
    )
    pred = model.predict(x)
    assert np.all(np.isfinite(pred))
    assert float(np.sqrt(np.mean((pred - y) ** 2))) < 0.2


def test_aggregation_depth_accepted():
    """Reference API parity: setAggregationDepth exists (the reference
    declares but never forwards it; XLA owns the reduction shape here)."""
    from spark_gp_tpu import GaussianProcessRegression

    gp = GaussianProcessRegression().setAggregationDepth(2)
    assert gp is not None
    with pytest.raises(ValueError):
        gp.setAggregationDepth(0)
