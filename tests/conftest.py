"""Test harness configuration.

Default harness — the TPU analogue of the reference's ``master("local[10]")``
single-JVM multi-threaded cluster (GPExample.scala:11): 8 virtual CPU devices
via ``--xla_force_host_platform_device_count`` so every ``psum``-sharded code
path is exercised without hardware, with float64 enabled (tests are accuracy
oracles; the TPU f32 path is covered by dtype-specific tests and the bench).

``GP_TEST_PLATFORM=tpu`` switches the session to the real chip (f32) and
runs ONLY the tests marked ``@pytest.mark.tpu`` (the Mosaic lowering parity
checks in test_pallas_linalg.py and the asserted on-chip quality bars in
test_tpu_quality_slice.py); everything else — the f64 accuracy oracles,
whose tolerances are meaningless at f32 — is skipped.
"""

import os

_PLATFORM = (os.environ.get("GP_TEST_PLATFORM") or "cpu").strip().lower()
if _PLATFORM not in ("cpu", "tpu"):
    raise RuntimeError(
        f"GP_TEST_PLATFORM={_PLATFORM!r} is not supported; use 'cpu' (default"
        " 8-virtual-device f64 harness) or 'tpu' (real chip, f32)."
    )

if _PLATFORM == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
else:
    # A leftover JAX_PLATFORMS=cpu (e.g. from a default-harness wrapper)
    # would force the cpu backend and turn the fail-fast below into a
    # misleading "no TPU reachable".  Clear it and let the site's own
    # platform resolution (the axon hook, PJRT plugins) find the chip —
    # hard-pinning "tpu" here would bypass tunnel shims whose registered
    # platform name is site-dependent.
    os.environ.pop("JAX_PLATFORMS", None)

import jax

# The axon TPU site hook overrides JAX_PLATFORMS at import time; the config
# update below wins over it and pins the test session to the 8 virtual CPU
# devices requested above.
if _PLATFORM == "cpu":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
# Persistent compile cache: repeated test runs skip recompilation.  The
# directory is keyed by a host-machine fingerprint: XLA's CPU AOT
# executables are NOT portable across CPU generations, and loading a
# cache written on a different host segfaulted the suite mid-pjit
# (utils/platform.machine_cache_dir rationale).
from spark_gp_tpu.utils.platform import machine_cache_dir

if os.environ.get("GP_TEST_NO_COMPILE_CACHE") != "1":
    jax.config.update(
        "jax_compilation_cache_dir", machine_cache_dir("/tmp/jax_test_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np
import pytest

if _PLATFORM == "tpu":
    # Fail fast if the chip isn't actually there — otherwise the run would
    # silently degrade to single-device CPU f32 with every TPU-only test
    # skipped, and look like a (vacuously) green hardware run.
    # Must be exactly "tpu": the on-TPU-only tests gate on
    # ``jax.default_backend() == "tpu"`` (test_pallas_linalg.py), so any
    # other backend name would produce a vacuously green "hardware" run.
    _backend = jax.default_backend()
    if _backend != "tpu":
        raise RuntimeError(
            "GP_TEST_PLATFORM=tpu but jax.default_backend() is"
            f" {_backend!r}. Either no TPU runtime is reachable, or this"
            " site registers the chip under a different backend name — the"
            ' hardware tests gate on default_backend() == "tpu" and cannot'
            " run against a differently-named backend."
        )


@pytest.fixture(autouse=True, scope="module")
def _bound_live_executables():
    """Clear jax's in-process executable caches between test MODULES.

    The full suite compiles ~350 distinct programs into one process; past
    roughly 300 live XLA:CPU executables the next compile (or persistent-
    cache load) segfaults inside XLA — reproducibly at the same test, with
    and without the on-disk cache, with and without the ctypes native
    loader, while any sub-suite passes alone.  Bounding the live count per
    module keeps the process far from that ceiling; the machine-keyed
    persistent cache (above) makes the post-clear reloads cheap.
    """
    yield
    jax.clear_caches()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "tpu: exercises real-hardware lowering; selected by GP_TEST_PLATFORM=tpu",
    )
    config.addinivalue_line(
        "markers",
        "slow: multi-subprocess artifact-contract guards (~30s each); "
        "deselect with -m 'not slow' for a quick loop",
    )
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (resilience/chaos.py) — "
        "fast and tier-1; select with -m chaos for the resilience-only loop",
    )


def pytest_collection_modifyitems(config, items):
    if _PLATFORM != "tpu":
        return
    skip = pytest.mark.skip(
        reason="f64/virtual-device harness test; tpu mode runs @pytest.mark.tpu only"
    )
    for item in items:
        if "tpu" not in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def eight_device_mesh():
    from spark_gp_tpu.parallel.mesh import expert_mesh

    # Gate on the harness mode, not the device count: the sharded tests are
    # f64 accuracy oracles and belong to the virtual-CPU harness even on a
    # hypothetical multi-chip TPU host.
    if _PLATFORM != "cpu":
        pytest.skip("multi-device paths are covered by the default CPU harness")
    assert len(jax.devices()) == 8, "expected 8 forced host devices"
    return expert_mesh()
