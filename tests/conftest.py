"""Test harness configuration.

The TPU analogue of the reference's ``master("local[10]")`` single-JVM
multi-threaded cluster (GPExample.scala:11): 8 virtual CPU devices via
``--xla_force_host_platform_device_count`` so every ``psum``-sharded code
path is exercised without hardware.  float64 is enabled — tests are accuracy
oracles; the TPU f32 path is covered by dtype-specific tests and the bench.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

# The axon TPU site hook overrides JAX_PLATFORMS at import time; the config
# update below wins over it and pins the test session to the 8 virtual CPU
# devices requested above.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# Persistent compile cache: repeated test runs skip recompilation.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def eight_device_mesh():
    from spark_gp_tpu.parallel.mesh import expert_mesh

    assert len(jax.devices()) == 8, "expected 8 forced host devices"
    return expert_mesh()
