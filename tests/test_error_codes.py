"""Tier-1 wrapper for the wire error-code lint (tools/check_error_codes.py)
and the catalog contract (spark_gp_tpu/serve/codes.py): every ``code=``
string that can reach a client is grammar-clean and registered — the
router failover codes included — so clients' retry/failover branching
and dashboards' error-class slicing can never silently rot on a rename.
"""

import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tools"))

import check_error_codes  # noqa: E402

from spark_gp_tpu.serve import codes  # noqa: E402


def test_error_code_lint_is_clean():
    violations = check_error_codes.find_violations(
        os.path.join(ROOT, "spark_gp_tpu")
    )
    assert violations == [], "\n".join(
        f"{path}:{line}: {code!r}: {why}"
        for path, line, code, why in violations
    )


def test_catalog_entries_are_grammar_clean():
    for code, help_text in codes.ERROR_CODES.items():
        assert codes.grammar_ok(code), code
        assert help_text.strip(), code


@pytest.mark.parametrize("required", [
    # the shed classes clients retry/back off on
    "queue.shed.deadline", "queue.shed.backpressure",
    "queue.shed.draining", "queue.shed.memory", "exec.hung",
    "shed.breaker",
    # the router failover codes (ISSUE 12)
    "router.no_replicas", "router.replica_unreachable",
    "router.failover_exhausted", "router.deadline",
    # TCP connection hygiene
    "serve.conn_limit", "serve.conn_idle",
])
def test_required_codes_are_registered(required):
    assert codes.is_registered(required), required


def test_exception_classes_carry_cataloged_codes():
    """The ``code`` attribute convention: every serve/router exception
    class that puts a code on the wire is registered in the catalog."""
    from spark_gp_tpu.resilience.breaker import BreakerOpenError
    from spark_gp_tpu.serve.lifecycle import (
        DrainingError,
        ExecHungError,
        MemoryPressureError,
    )
    from spark_gp_tpu.serve.queue import DeadlineExpiredError, QueueFullError
    from spark_gp_tpu.serve.router import (
        FailoverExhaustedError,
        NoReplicasError,
        ReplicaUnreachableError,
        RouterDeadlineError,
    )

    for cls in (
        BreakerOpenError, DrainingError, ExecHungError, MemoryPressureError,
        DeadlineExpiredError, QueueFullError, FailoverExhaustedError,
        NoReplicasError, ReplicaUnreachableError, RouterDeadlineError,
    ):
        assert codes.is_registered(cls.code), cls.__name__


def test_lint_catches_an_unregistered_code(tmp_path):
    """Falsifiability: a rogue code= emission is actually flagged."""
    rogue = tmp_path / "rogue.py"
    rogue.write_text(
        "class Oops(RuntimeError):\n"
        "    code = 'queue.shed.not_a_thing'\n"
        "reply = {'error': 'x', 'code': 'Bad.Grammar'}\n"
    )
    violations = check_error_codes.find_violations(str(tmp_path))
    found = {code for _, _, code, _ in violations}
    assert found == {"queue.shed.not_a_thing", "Bad.Grammar"}
