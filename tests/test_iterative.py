"""Iterative expert inference — the CG/Lanczos solver lane (ops/iterative.py).

ISSUE 14's acceptance bars as tier-1 assertions: the batched
preconditioned-CG solve matches the exact factorization at machine
precision; the SLQ log-det / Hutchinson-surrogate legs hold their
documented stochastic bars (f64 tight, f32 on a looser ladder — the
test_precision_policy convention); fitted theta matches the exact lane on
every family across host / one-dispatch / sharded entry points; the
jitter-escalation operand rides both lanes identically; the
preconditioner rank actually buys convergence; ``GP_SOLVER_LANE=exact``
(the default) is bit-for-bit today's path; the lane rides the PR 7 gram
cache (gram-forbidden spy kernel); the memory planner's iterative rung
rows under-cut the native factor-stack model; and no module outside
``ops/`` calls a raw batched factorization (tools/check_solver_pins.py).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessClassifier,
    GaussianProcessMulticlassClassifier,
    GaussianProcessPoissonRegression,
    GaussianProcessRegression,
    RBFKernel,
)
from spark_gp_tpu.kernels.base import Const, EyeKernel, prepare_gram_cache
from spark_gp_tpu.models.likelihood import batched_nll, make_value_and_grad
from spark_gp_tpu.ops import iterative as it
from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: fitted-theta parity bar between the lanes: the iterative lane's
#: log-det/trace legs are STOCHASTIC estimators (fixed-seed, smooth),
#: so the optima differ by the estimator bias, not float noise —
#: documented in docs/ROOFLINE.md ("Iterative solver lane")
THETA_REL_BAR = 5e-2


@pytest.fixture(autouse=True)
def _clean_solver_lane(monkeypatch):
    """Every test starts and ends on the default (exact) lane — the knob
    is process-global state (the test_precision_policy convention)."""
    for var in [v for v in os.environ if v.startswith("GP_SOLVER_")]:
        monkeypatch.delenv(var, raising=False)
    it.set_solver_lane(None)
    yield
    it.set_solver_lane(None)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _spd_stack(rng, e=3, s=48, dtype=np.float64, diag=1e-2):
    x = rng.normal(size=(e, s, 3))
    d = ((x[:, :, None, :] - x[:, None, :, :]) ** 2).sum(-1)
    k = np.exp(-d / 2.0) + diag * np.eye(s)[None]
    return jnp.asarray(k.astype(dtype))


def _expert_stack(rng, n=240, s=40, dtype=np.float64):
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    data = group_for_experts(x, y, s)
    return ExpertData(
        x=jnp.asarray(np.asarray(data.x), dtype=dtype),
        y=jnp.asarray(np.asarray(data.y), dtype=dtype),
        mask=jnp.asarray(np.asarray(data.mask), dtype=dtype),
    )


# -- lane plumbing ----------------------------------------------------------


def test_solver_lane_plumbing_env_setter_scope_roundtrip(monkeypatch):
    """Resolution order: scope > setter > env > exact default; the auto
    lane resolves by expert size against GP_SOLVER_AUTO_THRESHOLD;
    invalid names fail loud and NAMED at every entry point."""
    assert it.active_solver_lane() == "exact"
    assert it.resolve_solver(4096) == "exact"

    monkeypatch.setenv("GP_SOLVER_LANE", "iterative")
    assert it.active_solver_lane() == "iterative"

    assert it.set_solver_lane("auto") is None
    assert it.active_solver_lane() == "auto"
    # auto: iterative at/above the threshold, exact below
    assert it.resolve_solver(1024) == "iterative"
    assert it.resolve_solver(1023) == "exact"
    monkeypatch.setenv("GP_SOLVER_AUTO_THRESHOLD", "256")
    assert it.resolve_solver(256) == "iterative"
    assert it.resolve_solver(255) == "exact"
    monkeypatch.delenv("GP_SOLVER_AUTO_THRESHOLD")

    assert it.set_solver_lane("exact") == "auto"
    with it.solver_lane_scope("iterative"):
        assert it.active_solver_lane() == "iterative"
        with it.solver_lane_scope("exact"):
            assert it.active_solver_lane() == "exact"
        assert it.active_solver_lane() == "iterative"
    assert it.active_solver_lane() == "exact"
    with it.solver_lane_scope(None):
        assert it.active_solver_lane() == "exact"
    it.set_solver_lane(None)
    assert it.active_solver_lane() == "iterative"  # env again

    with pytest.raises(ValueError, match="GP_SOLVER_LANE"):
        monkeypatch.setenv("GP_SOLVER_LANE", "cg")
        it.active_solver_lane()
    monkeypatch.delenv("GP_SOLVER_LANE")
    with pytest.raises(ValueError, match="set_solver_lane"):
        it.set_solver_lane("lanczos")
    with pytest.raises(ValueError, match="solver_lane_scope"):
        with it.solver_lane_scope("bbmm"):
            pass


def test_estimator_setter_is_fluent_and_process_wide():
    gp = GaussianProcessRegression()
    assert gp.setSolverLane("iterative") is gp
    assert it.active_solver_lane() == "iterative"
    gp.set_solver_lane("exact")
    assert it.active_solver_lane() == "exact"
    with pytest.raises(ValueError):
        gp.setSolverLane("turbo")


# -- numerical cores --------------------------------------------------------


def test_pivoted_cholesky_preconditioner(rng):
    """Greedy partial pivoted Cholesky: L L^T approximates K from the
    dominant pivots, P = L L^T + delta I is SPD, and the Woodbury apply
    matches the dense P^-1."""
    k = _spd_stack(rng, e=2, s=40)
    lmat, delta = it.pivoted_cholesky(k, rank=16)
    cfac = it.woodbury_factor(lmat, delta)
    k_np = np.asarray(k)
    l_np = np.asarray(lmat)
    d_np = np.asarray(delta)
    assert np.all(d_np > 0)
    # rank-16 of a fast-decaying RBF spectrum captures most of the mass
    for e in range(k_np.shape[0]):
        resid = np.linalg.norm(k_np[e] - l_np[e] @ l_np[e].T) / np.linalg.norm(
            k_np[e]
        )
        assert resid < 0.2, resid
        p_dense = l_np[e] @ l_np[e].T + d_np[e] * np.eye(k_np.shape[-1])
        v = rng.normal(size=(k_np.shape[-1], 3))
        got = np.asarray(
            it.woodbury_apply(
                lmat[e : e + 1], delta[e : e + 1], cfac[e : e + 1],
                jnp.asarray(v)[None],
            )
        )[0]
        np.testing.assert_allclose(got, np.linalg.solve(p_dense, v), rtol=1e-8)
    # exact preconditioner log-det
    ld = np.asarray(it.woodbury_logdet(lmat, delta, cfac))
    for e in range(k_np.shape[0]):
        p_dense = l_np[e] @ l_np[e].T + d_np[e] * np.eye(k_np.shape[-1])
        np.testing.assert_allclose(
            ld[e], np.linalg.slogdet(p_dense)[1], rtol=1e-10
        )


@pytest.mark.parametrize(
    "dtype,solve_tol,logdet_tol,grad_tol",
    [
        (np.float64, 1e-8, 5e-2, 2e-2),
        (np.float32, 1e-3, 8e-2, 5e-2),
    ],
    ids=["f64", "f32"],
)
def test_inv_quad_logdet_parity(rng, dtype, solve_tol, logdet_tol, grad_tol):
    """CG-vs-exact on small s: the quadratic term is machine-exact at
    convergence (variational value + exact -a a^T gradient); the SLQ
    log-det and the Hutchinson gradient hold the documented stochastic
    ladder (probes bound the variance, not the dtype)."""
    k = _spd_stack(rng, e=3, s=48, dtype=dtype)
    y = jnp.asarray(rng.normal(size=(3, 48)).astype(dtype))
    cfg = it.SolverConfig(iters=48, probes=16, rank=24, tol=1e-10, seed=0)
    quad, logdet = it.inv_quad_logdet(k, y, cfg)
    k_np = np.asarray(k, dtype=np.float64)
    y_np = np.asarray(y, dtype=np.float64)
    quad_e = np.array([
        y_np[e] @ np.linalg.solve(k_np[e], y_np[e]) for e in range(3)
    ])
    ld_e = np.array([np.linalg.slogdet(k_np[e])[1] for e in range(3)])
    np.testing.assert_allclose(np.asarray(quad), quad_e, rtol=solve_tol)
    rel_ld = np.max(np.abs(np.asarray(logdet) - ld_e) / np.abs(ld_e))
    assert rel_ld < logdet_tol, rel_ld

    # gradient parity of the summed NLL against the exact lane
    def nll_iter(km):
        q, l = it.inv_quad_logdet(km, y, cfg)
        return 0.5 * jnp.sum(q) + 0.5 * jnp.sum(l)

    def nll_exact(km):
        chol = jnp.linalg.cholesky(km)
        a = jax.scipy.linalg.cho_solve((chol, True), y[..., None])[..., 0]
        ld = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(chol, axis1=-2, axis2=-1)), axis=-1
        )
        return 0.5 * jnp.einsum("es,es->", y, a) + 0.5 * jnp.sum(ld)

    g_it = np.asarray(jax.grad(nll_iter)(k), dtype=np.float64)
    g_ex = np.asarray(jax.grad(nll_exact)(k), dtype=np.float64)
    rel_g = np.max(np.abs(g_it - g_ex)) / np.max(np.abs(g_ex))
    assert rel_g < grad_tol, rel_g


def test_spd_solve_and_factored_solve_machine_precision(rng):
    """The Laplace-system solvers are implicit-differentiation exact (no
    stochastic legs): CG under custom_linear_solve at machine precision,
    for both the materialized B stack and the factored multiclass
    operator."""
    k = _spd_stack(rng, e=2, s=40)
    b_mat = jnp.eye(40)[None] + 0.25 * k
    rhs = jnp.asarray(rng.normal(size=(2, 40)))
    cfg = it.SolverConfig(iters=80, probes=8, rank=8, tol=1e-13, seed=0)
    x_it = np.asarray(it.spd_solve(b_mat, rhs, cfg))
    x_ex = np.asarray(jnp.linalg.solve(b_mat, rhs[..., None])[..., 0])
    np.testing.assert_allclose(x_it, x_ex, rtol=1e-8, atol=1e-10)

    # gradient through the solve (implicit differentiation)
    def loss_it(m):
        return jnp.sum(it.spd_solve(m, rhs, cfg) ** 2)

    def loss_ex(m):
        return jnp.sum(jnp.linalg.solve(m, rhs[..., None])[..., 0] ** 2)

    g_it = np.asarray(jax.grad(loss_it)(b_mat))
    g_ex = np.asarray(jax.grad(loss_ex)(b_mat))
    np.testing.assert_allclose(g_it, g_ex, rtol=1e-6, atol=1e-8)

    # factored operator (I + S^T K_blk S) vs its dense materialization
    e, s, c = 2, 16, 3
    k_small = _spd_stack(rng, e=e, s=s)
    smat = jnp.asarray(rng.normal(size=(e, s, c, c)) * 0.3)
    b = jnp.asarray(rng.normal(size=(e, s, c)))
    got = np.asarray(it.factored_solve(k_small, smat, b, cfg))
    for ei in range(e):
        # dense B' over the [sC] flattening used by the operator
        dense = np.eye(s * c)
        for col in range(s * c):
            v = np.zeros((s, c))
            v[col // c, col % c] = 1.0
            sv = np.einsum("scd,sd->sc", np.asarray(smat)[ei], v)
            ksv = np.einsum("st,tc->sc", np.asarray(k_small)[ei], sv)
            out = v + np.einsum("sdc,sd->sc", np.asarray(smat)[ei], ksv)
            dense[:, col] = out.reshape(-1)
        want = np.linalg.solve(dense, np.asarray(b)[ei].reshape(-1))
        np.testing.assert_allclose(
            got[ei].reshape(-1), want, rtol=1e-7, atol=1e-9
        )


def test_preconditioner_rank_sensitivity(rng):
    """More preconditioner rank buys convergence: at a fixed (small)
    iteration budget the achieved residual improves monotonically in k
    on an ill-conditioned stack."""
    k = _spd_stack(rng, e=2, s=64, diag=1e-2)
    y = jnp.asarray(rng.normal(size=(2, 64)))

    def max_resid(rank):
        lmat, delta = it.pivoted_cholesky(k, rank)
        cfac = it.woodbury_factor(lmat, delta)
        res = it.batched_pcg(
            lambda v: jnp.einsum("est,etn->esn", k, v),
            y[..., None],
            precond=lambda v: it.woodbury_apply(lmat, delta, cfac, v),
            iters=8,
            tol=1e-12,
        )
        return float(jnp.max(res.rel_resid))

    r2, r16, r48 = max_resid(2), max_resid(16), max_resid(48)
    assert r48 < r16 < r2, (r2, r16, r48)
    assert r48 < 1e-2


def test_jitter_operand_parity(rng, monkeypatch):
    """The resilience layer's traced jitter-escalation operand rides both
    lanes: the SAME boosted matrix feeds whichever solver runs, so the
    two lanes agree on the jittered objective to the stochastic bar and
    the jitter moves both by the same amount (delta measured above the
    probe noise: 32 probes, a ladder-scale 3e-2 boost)."""
    monkeypatch.setenv("GP_SOLVER_PROBES", "32")
    data = _expert_stack(rng)
    kernel = 1.0 * RBFKernel(0.6, 1e-6, 10.0) + Const(1e-3) * EyeKernel()
    theta = jnp.asarray(kernel.init_theta(), dtype=data.x.dtype)

    def nll(lane, jitter):
        with it.solver_lane_scope(lane):
            return float(batched_nll(kernel, theta, data, jitter=jitter))

    jit_vec = jnp.full((data.x.shape[0],), 3e-2, dtype=data.x.dtype)
    for jitter in (None, jit_vec):
        exact = nll("exact", jitter)
        iterv = nll("iterative", jitter)
        assert abs(iterv - exact) / abs(exact) < 2e-2, (exact, iterv)
    # the boost moves both lanes the same way
    d_exact = nll("exact", jit_vec) - nll("exact", None)
    d_iter = nll("iterative", jit_vec) - nll("iterative", None)
    assert abs(d_iter - d_exact) / max(abs(d_exact), 1e-12) < 0.1


# -- the lane through the estimators ---------------------------------------


def _families(rng, n=240):
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    return x, {
        "gpr": (GaussianProcessRegression, y),
        "gpc": (GaussianProcessClassifier, (y > 0).astype(np.float64)),
        "gp_poisson": (
            GaussianProcessPoissonRegression,
            rng.poisson(np.exp(np.clip(y, -2.0, 2.0))).astype(np.float64),
        ),
        "gpc_mc": (
            GaussianProcessMulticlassClassifier,
            np.digitize(y, [-0.5, 0.5]).astype(np.float64),
        ),
    }


def _estimator(cls, optimizer, mesh=None):
    gp = (
        cls()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(30)
        .setSigma2(1e-3)
        .setMaxIter(5)
        .setSeed(7)
        .setOptimizer(optimizer)
    )
    if mesh is not None:
        gp.setMesh(mesh)
    return gp


def _rel_theta_delta(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return float(np.max(np.abs(a - b)) / max(np.max(np.abs(a)), 1e-12))


def test_fitted_theta_parity_all_families_host(rng):
    """Acceptance: on every family, the host-optimizer fit at the
    iterative lane lands within the documented stochastic bar of the
    exact lane's optimum, and the engaged lane is provenance-stamped."""
    x, families = _families(rng)
    for name, (cls, yv) in families.items():
        thetas = {}
        for lane in ("exact", "iterative"):
            it.set_solver_lane(lane)
            try:
                model = _estimator(cls, "host").fit(x, yv)
            finally:
                it.set_solver_lane(None)
            thetas[lane] = np.asarray(model.raw_predictor.theta)
            assert model.instr.metrics.get("solver_lane") == lane, name
            if lane == "iterative":
                assert model.instr.metrics["solver.residual"] < 1e-2, name
                assert model.instr.metrics["solver.cg_iters"] >= 1, name
        delta = _rel_theta_delta(thetas["exact"], thetas["iterative"])
        assert delta <= THETA_REL_BAR, (name, delta)


def test_fitted_theta_parity_device_one_dispatch(rng):
    """The one-dispatch device entry points carry the solver lane as a
    static jit argument: regression + binary classifier parity."""
    x, families = _families(rng)
    for name in ("gpr", "gpc"):
        cls, yv = families[name]
        thetas = {}
        for lane in ("exact", "iterative"):
            it.set_solver_lane(lane)
            try:
                model = _estimator(cls, "device").fit(x, yv)
            finally:
                it.set_solver_lane(None)
            thetas[lane] = np.asarray(model.raw_predictor.theta)
            assert model.instr.metrics.get("solver_lane") == lane, name
        delta = _rel_theta_delta(thetas["exact"], thetas["iterative"])
        assert delta <= THETA_REL_BAR, (name, delta)


def test_fitted_theta_parity_sharded(rng, eight_device_mesh):
    """The shard_map fit path resolves the lane inside each local program
    (one psum'd objective either way): sharded iterative theta matches
    the sharded exact theta within the bar."""
    x, families = _families(rng, n=320)
    cls, yv = families["gpr"]
    thetas = {}
    for lane in ("exact", "iterative"):
        it.set_solver_lane(lane)
        try:
            model = _estimator(cls, "device", mesh=eight_device_mesh).fit(
                x, yv
            )
        finally:
            it.set_solver_lane(None)
        thetas[lane] = np.asarray(model.raw_predictor.theta)
    delta = _rel_theta_delta(thetas["exact"], thetas["iterative"])
    assert delta <= THETA_REL_BAR, delta


def test_kill_switch_exact_is_bit_for_bit(rng, monkeypatch):
    """GP_SOLVER_LANE=exact (and the unset default) is today's path
    bit-for-bit: identical theta BITS, no solver.* convergence metrics,
    solver_lane stamped 'exact'."""
    x, families = _families(rng)
    cls, yv = families["gpr"]
    default_model = _estimator(cls, "host").fit(x, yv)
    monkeypatch.setenv("GP_SOLVER_LANE", "exact")
    pinned_model = _estimator(cls, "host").fit(x, yv)
    np.testing.assert_array_equal(
        np.asarray(default_model.raw_predictor.theta),
        np.asarray(pinned_model.raw_predictor.theta),
    )
    for model in (default_model, pinned_model):
        assert model.instr.metrics["solver_lane"] == "exact"
        assert not any(
            k.startswith("solver.") for k in model.instr.metrics
        )


def test_auto_lane_resolves_by_expert_size(rng, monkeypatch):
    """auto = exact below the threshold, iterative at/above it — resolved
    from the trace-static expert size, stamped truthfully."""
    x, families = _families(rng)
    cls, yv = families["gpr"]
    monkeypatch.setenv("GP_SOLVER_LANE", "auto")
    monkeypatch.setenv("GP_SOLVER_AUTO_THRESHOLD", "64")
    below = _estimator(cls, "host").fit(x, yv)  # s = 40 < 64
    assert below.instr.metrics["solver_lane"] == "exact"
    monkeypatch.setenv("GP_SOLVER_AUTO_THRESHOLD", "40")
    above = _estimator(cls, "host").fit(x, yv)  # s = 40 >= 40
    assert above.instr.metrics["solver_lane"] == "iterative"
    assert "solver.residual" in above.instr.metrics


# -- gram cache + provenance ------------------------------------------------


class _GramForbiddenRBF(RBFKernel):
    """RBF whose ``gram`` refuses to trace: proves the iterative lane
    rides the theta-invariant cache (``gram_from_cache``), never the raw
    distance contraction (the test_gram_cache spy-kernel contract)."""

    def gram(self, theta, x):
        raise AssertionError(
            "kernel.gram was called inside a cached iterative objective"
        )


def test_iterative_lane_rides_gram_cache(rng):
    data = _expert_stack(rng)
    kernel = (
        1.0 * _GramForbiddenRBF(0.6, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    )
    theta = jnp.asarray(
        np.asarray(kernel.init_theta()), dtype=data.x.dtype
    )
    cache = prepare_gram_cache(kernel, data.x)
    assert cache is not None
    it.set_solver_lane("iterative")
    try:
        value, grad = make_value_and_grad(kernel, data, cache=cache)(theta)
    finally:
        it.set_solver_lane(None)
    assert np.isfinite(float(value))
    assert np.all(np.isfinite(np.asarray(grad)))
    # without the cache the spy bites — the test tests itself
    it.set_solver_lane("iterative")
    try:
        with pytest.raises(AssertionError, match="cached iterative"):
            make_value_and_grad(kernel, data)(theta)
    finally:
        it.set_solver_lane(None)


def test_solver_provenance_journal_and_saved_model(rng, tmp_path, monkeypatch):
    """The engaged lane + convergence stats land in the run journal and
    the saved model's provenance_json (the gram_cache_engaged mirror)."""
    import json

    monkeypatch.setenv("GP_RUN_JOURNAL_DIR", str(tmp_path))
    x, families = _families(rng)
    cls, yv = families["gpr"]
    it.set_solver_lane("iterative")
    try:
        model = _estimator(cls, "host").fit(x, yv)
    finally:
        it.set_solver_lane(None)
    journal = model.run_journal
    assert journal["solver_lane"] == "iterative"
    assert journal["metrics"]["solver_lane"] == "iterative"
    assert journal["metrics"]["solver.residual"] < 1e-2
    with open(journal["path"], encoding="utf-8") as fh:
        persisted = json.load(fh)
    assert persisted["solver_lane"] == "iterative"
    path = str(tmp_path / "iter_model.npz")
    model.save(path)
    from spark_gp_tpu.models.gpr import GaussianProcessRegressionModel

    loaded = GaussianProcessRegressionModel.load(path)
    solver = loaded.provenance["solver"]
    assert solver["solver_lane"] == "iterative"
    assert solver["solver.residual"] < 1e-2
    assert solver["solver.precond_rank"] >= 1


# -- memory planning --------------------------------------------------------


def test_memplan_iterative_rung_rows(rng):
    """The analytic iterative-rung rows (resilience/memplan.py): skinny
    CG workspace under-cuts the native factor-stack model, increasingly
    so at large s — and plan_fit_dispatch offers the rung as a pre-sized
    candidate preferred over segment halving."""
    from spark_gp_tpu.resilience import memplan

    for s, p in ((256, 3), (2048, 3)):
        native = memplan.fit_dispatch_bytes(4, s, p, 4, "native")
        iterative = memplan.fit_dispatch_bytes(4, s, p, 4, "iterative")
        assert iterative < native, (s, native, iterative)
    # the ratio grows with s: the skinny term is O(s (k + r)) against
    # the native model's O(s^2) factor liveness
    r_small = memplan.fit_dispatch_bytes(4, 256, 3, 4, "native") / (
        memplan.fit_dispatch_bytes(4, 256, 3, 4, "iterative")
    )
    r_big = memplan.fit_dispatch_bytes(4, 2048, 3, 4, "native") / (
        memplan.fit_dispatch_bytes(4, 2048, 3, 4, "iterative")
    )
    assert r_big >= r_small

    # the plan offers the rung (device one-dispatch config) and picks it
    # under a budget between the iterative and native predictions
    x = rng.normal(size=(160, 3))
    y = np.sin(x.sum(axis=1))
    gp = _estimator(GaussianProcessRegression, "device")
    data = gp._group(x, y)
    e, s = int(data.x.shape[0]), int(data.x.shape[1])
    itemsize = int(np.dtype(data.x.dtype).itemsize)
    native_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(e, s, 3, itemsize, "native")
    )
    iter_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(e, s, 3, itemsize, "iterative")
    )
    budget = (iter_pred + native_pred) / 2.0
    plan = memplan.plan_fit_dispatch.__wrapped__ if hasattr(
        memplan.plan_fit_dispatch, "__wrapped__"
    ) else memplan.plan_fit_dispatch
    decision = None
    try:
        os.environ["GP_MEMPLAN_LIMIT_BYTES"] = str(budget)
        decision = plan(gp, None, data)
    finally:
        os.environ.pop("GP_MEMPLAN_LIMIT_BYTES", None)
    assert decision is not None
    assert decision.chosen == "iterative" and decision.fits is True
    names = [c["name"] for c in decision.candidates]
    assert names[:2] == ["native", "iterative"]


# -- the lint ---------------------------------------------------------------


def test_no_raw_cholesky_outside_ops():
    """tools/check_solver_pins.py as a tier-1 gate: every dense SPD
    factorization/solve outside ops/ routes through the solver policy —
    a new raw jnp.linalg.cholesky / cho_solve call fails here before it
    ever lands (and is invisible to GP_SOLVER_LANE if it does)."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_solver_pins
    finally:
        sys.path.pop(0)

    violations = check_solver_pins.find_pins(
        os.path.join(ROOT, "spark_gp_tpu")
    )
    assert violations == [], (
        "raw batched factorizations outside ops/ (route through "
        "ops/linalg or ops/iterative, or mark '# solver-pin-ok'):\n"
        + "\n".join(f"{p}:{n}: {l}" for p, n, l in violations)
    )
    assert check_solver_pins.main([os.path.join(ROOT, "spark_gp_tpu")]) == 0
    # the AST walk is jax-rooted only: host numpy factorization in e.g.
    # resilience/chaos.py (the LinAlgError injector) is deliberately
    # out of scope
    assert check_solver_pins._is_banned(["jnp", "linalg", "cholesky"])
    assert check_solver_pins._is_banned(
        ["jax", "scipy", "linalg", "cho_solve"]
    )
    assert not check_solver_pins._is_banned(["np", "linalg", "cholesky"])
