"""Checkpoint/resume tests (utils/checkpoint.py + segmented device fits).

The reference has no resume story — Spark lineage recomputes lost work
(SURVEY.md §5).  Here a killed fit must restart from persisted optimizer
state: theta-only JSON for the host optimizer, the full L-BFGS state pytree
for the device optimizer (VERDICT r1 #6: the device loop previously could
not checkpoint at all, and load_checkpoint had no consumer).
"""

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, GaussianProcessClassifier, RBFKernel
from spark_gp_tpu.utils.checkpoint import (
    DeviceOptimizerCheckpointer,
    load_checkpoint,
)


def _problem(n=240, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    return x, y


def _gp(tmpdir=None, interval=3):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(50)
        .setMaxIter(25)
        .setOptimizer("device")
        .setSeed(3)
    )
    if tmpdir is not None:
        gp.setCheckpointDir(str(tmpdir)).setCheckpointInterval(interval)
    return gp


def test_segmented_fit_matches_one_shot(tmp_path):
    """The K-iteration segmented driver converges to the same theta as the
    single-dispatch device fit."""
    x, y = _problem()
    model_one = _gp().fit(x, y)
    model_seg = _gp(tmp_path).fit(x, y)
    np.testing.assert_allclose(
        model_one.raw_predictor.theta,
        model_seg.raw_predictor.theta,
        rtol=1e-5,
    )
    assert (tmp_path / "gpr_device_lbfgs.npz").exists()


def test_kill_and_resume_reaches_same_theta(tmp_path):
    """A fit killed mid-run resumes from the persisted state and lands on
    the same optimum as an uninterrupted fit."""
    x, y = _problem(seed=1)
    theta_full = _gp(tmp_path / "full").fit(x, y).raw_predictor.theta

    # "kill" after a few iterations: cap max_iter low, then restart uncapped
    interrupted = _gp(tmp_path / "resume").setMaxIter(4)
    int_iters = interrupted.fit(x, y).instr.metrics["lbfgs_iters"]
    ck = DeviceOptimizerCheckpointer(str(tmp_path / "resume"), "gpr")
    assert ck.path and (tmp_path / "resume" / "gpr_device_lbfgs.npz").exists()

    resumed = _gp(tmp_path / "resume").fit(x, y)  # full maxIter again
    np.testing.assert_allclose(
        resumed.raw_predictor.theta, theta_full, rtol=1e-5
    )
    # resume really consumed the state: the cumulative counter continues
    # from the interrupted run's persisted count instead of restarting at
    # zero.  Anchor on the count actually persisted, not the cap: under
    # heavy CPU load XLA's thread partitioning can perturb summation order
    # enough that the capped run converges just UNDER its cap, in which
    # case the resumed run legitimately reports that same count.
    assert resumed.instr.metrics["lbfgs_iters"] >= int_iters
    if int_iters >= 4:  # the interrupted run really was capped mid-descent
        assert resumed.instr.metrics["lbfgs_iters"] > 4


def test_stale_checkpoint_ignored(tmp_path):
    """A checkpoint from a different configuration must not be trusted."""
    x, y = _problem(seed=2)
    _gp(tmp_path).fit(x, y)
    gp2 = (
        _gp(tmp_path)
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-6, 10.0))  # 2 hypers now
    )
    with pytest.warns(UserWarning, match="ignoring device checkpoint"):
        model = gp2.fit(x, y)
    assert model.raw_predictor.theta.shape[0] == 2  # scale + rbf sigma


def test_finished_checkpoint_not_reused_for_different_data(tmp_path):
    """A converged checkpoint must not short-circuit a fit on NEW data of
    the same shape (caught in review: meta previously carried no data
    identity, so fit #2 returned fit #1's theta with zero iterations)."""
    x1, y1 = _problem(seed=6)
    _gp(tmp_path).fit(x1, y1)
    x2, y2 = _problem(seed=7)  # same shapes, different content
    with pytest.warns(UserWarning, match="ignoring device checkpoint"):
        model2 = _gp(tmp_path).fit(x2, y2)
    theta2_ref = _gp().fit(x2, y2).raw_predictor.theta
    np.testing.assert_allclose(
        model2.raw_predictor.theta, theta2_ref, rtol=1e-5
    )


def test_checkpoint_not_reused_for_different_kernel_same_dim(tmp_path):
    """A converged checkpoint from a DIFFERENT kernel with the SAME
    theta_dim and data must be ignored (r4 review: meta previously keyed on
    theta_dim only, so RBF->Matern with one hyperparameter each silently
    resumed the old optimum).  Same for a tol change."""
    from spark_gp_tpu import Matern52Kernel

    x, y = _problem(seed=8)
    _gp(tmp_path).fit(x, y)  # kernel: RBFKernel(1.0), theta_dim 1
    gp2 = _gp(tmp_path).setKernel(lambda: Matern52Kernel(1.0))  # theta_dim 1
    with pytest.warns(UserWarning, match="ignoring device checkpoint"):
        model2 = gp2.fit(x, y)
    theta_ref = (
        _gp().setKernel(lambda: Matern52Kernel(1.0)).fit(x, y)
        .raw_predictor.theta
    )
    np.testing.assert_allclose(model2.raw_predictor.theta, theta_ref, rtol=1e-5)

    # different tol on the same kernel/data: state is also not resumable
    with pytest.warns(UserWarning, match="ignoring device checkpoint"):
        _gp(tmp_path).setKernel(lambda: Matern52Kernel(1.0)).setTol(1e-4).fit(x, y)


def test_kernel_fingerprint_full_identity():
    """The fingerprint sees bounds and nested structure, not just describe."""
    from spark_gp_tpu import WhiteNoiseKernel
    from spark_gp_tpu.utils.checkpoint import kernel_fingerprint

    a = kernel_fingerprint(1.0 * RBFKernel(0.1, 1e-6, 10.0))
    b = kernel_fingerprint(1.0 * RBFKernel(0.1, 1e-6, 20.0))  # bounds differ
    c = kernel_fingerprint(
        1.0 * RBFKernel(0.1, 1e-6, 10.0) + WhiteNoiseKernel(0.5, 0, 1)
    )
    assert a != b and a != c and b != c
    # process-stable: a fresh equal spec renders identically
    assert a == kernel_fingerprint(1.0 * RBFKernel(0.1, 1e-6, 10.0))


def test_segment_meta_distinguishes_starting_points():
    """ThetaOverrideKernel (the multi-start wrapper) excludes its starting
    point from _spec by design, so the resume guard must carry theta0's
    VALUES — a finished checkpoint from start A must not answer for a fit
    from start B (r4 review)."""
    from spark_gp_tpu import Matern52Kernel
    from spark_gp_tpu.kernels.base import ThetaOverrideKernel
    from spark_gp_tpu.utils.checkpoint import segment_meta

    x = np.zeros((2, 4, 3))
    y = np.zeros((2, 4))
    mask = np.ones((2, 4))
    k = Matern52Kernel(1.0)

    def meta_for(t0):
        wrapped = ThetaOverrideKernel(k, [t0])
        return segment_meta(
            "gpr", wrapped, 1e-6, True, wrapped.init_theta(), x, y, mask
        )

    a, b = meta_for(0.5), meta_for(2.0)
    assert a["kernel"] == b["kernel"]  # spec identity intentionally equal
    assert a != b  # ... but the recorded starting point differs


def test_classifier_segmented_resume(tmp_path):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(160, 2))
    y = (x.sum(axis=1) > 0).astype(np.float64)
    def gp(d):
        return (
            GaussianProcessClassifier()
            .setKernel(lambda: RBFKernel(1.0))
            .setDatasetSizeForExpert(40)
            .setActiveSetSize(40)
            .setMaxIter(15)
            .setOptimizer("device")
            .setCheckpointDir(str(d))
            .setCheckpointInterval(4)
        )

    theta_full = gp(tmp_path / "a").fit(x, y).raw_predictor.theta
    gp(tmp_path / "b").setMaxIter(3).fit(x, y)
    resumed = gp(tmp_path / "b").fit(x, y)
    np.testing.assert_allclose(resumed.raw_predictor.theta, theta_full, rtol=1e-4)
    acc = float(np.mean(resumed.predict(x) == y))
    assert acc > 0.9


def test_host_optimizer_resume_consumes_checkpoint(tmp_path):
    """The host path writes theta per iteration and resumes from it
    (load_checkpoint finally has a consumer — VERDICT r1 weak #3)."""
    x, y = _problem(seed=4)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(50)
        .setMaxIter(20)
        .setOptimizer("host")
        .setCheckpointDir(str(tmp_path))
    )
    model = gp.fit(x, y)
    ck = load_checkpoint(str(tmp_path), tag="GaussianProcessRegression")
    assert ck is not None
    it, theta, _sig = ck
    assert it >= 1 and theta.shape == model.raw_predictor.theta.shape

    # restart: resumes from saved theta (converges immediately or quickly)
    model2 = gp.fit(x, y)
    assert model2.instr.metrics["lbfgs_iters"] <= model.instr.metrics["lbfgs_iters"]
    np.testing.assert_allclose(
        model2.raw_predictor.theta, model.raw_predictor.theta, rtol=1e-3
    )


def test_sharded_segmented_fit(tmp_path, eight_device_mesh):
    """Segmented checkpointing composes with the sharded device loop."""
    x, y = _problem(n=320, seed=5)
    gp = _gp(tmp_path, interval=5).setMesh(eight_device_mesh)
    model = gp.fit(x, y)
    theta_plain = _gp().setMesh(eight_device_mesh).fit(x, y).raw_predictor.theta
    np.testing.assert_allclose(model.raw_predictor.theta, theta_plain, rtol=1e-5)
