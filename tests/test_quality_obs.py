"""Statistical health plane (ISSUE 13, spark_gp_tpu/obs/quality.py):
streaming calibration statistics, the multi-window verdict engine, the
pending-ring feedback join behind the serve ``observe`` verb, covariate
drift detection against the fit-time provenance summary, fit-time
per-expert quality telemetry, and the gpctl renderers.

The statistics themselves carry seeded property tests: a WELL-SPECIFIED
model (labels drawn exactly from the served distributions) must show
~uniform PIT and coverage inside the binomial CI — and never alert —
while the chaos faults (``chaos.miscalibrate`` σ-scaling,
``chaos.drift_inputs`` covariate shift) must trip their alerts within a
bounded number of observations.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.obs.quality import (
    COVERAGE_LEVELS,
    DriftMonitor,
    PendingRing,
    QualityDisabledError,
    QualityMonitor,
    UnknownRequestError,
    summarize_covariates,
)
from spark_gp_tpu.resilience import chaos
from spark_gp_tpu.serve import GPServeServer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the ISSUE 13 acceptance bound: injected faults must alarm within this
#: many graded observations; the clean twin must never alarm within it
ALERT_BUDGET = 512


def _calibrated_stream(rng, n, sigma_truth_factor=1.0):
    """(mean, var, y): the model claims N(mean, var); the labels are
    drawn from N(mean, (factor * sigma)^2) — factor 1 is the
    well-specified case, factor 2 a served sigma shrunk 2x below truth."""
    mean = rng.normal(size=n)
    sigma = np.abs(rng.normal(1.0, 0.3, size=n)) + 0.2
    y = mean + sigma_truth_factor * sigma * rng.standard_normal(n)
    return mean, sigma**2, y


def _fit(seed=3, n=160):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(40)
        .setSigma2(1e-3)
        .setMaxIter(5)
        .setSeed(seed)
        .fit(x, y)
    )
    return model, x, y


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    model, x, y = _fit()
    path = str(tmp_path_factory.mktemp("quality") / "model.npz")
    model.save(path)
    return path, model, x, y


# -- the statistics themselves (seeded property tests) ---------------------


@pytest.mark.parametrize("seed", [0, 7, 23])
def test_well_specified_stream_is_calibrated_and_never_alerts(seed):
    rng = np.random.default_rng(seed)
    monitor = QualityMonitor(window=128, breach_windows=2)
    n = 4096
    mean, var, y = _calibrated_stream(rng, n)
    monitor.observe(mean, var, y)
    snap = monitor.snapshot()
    assert snap["observations"] == n
    assert snap["windows_closed"] == n // 128
    # coverage within a 4-sigma binomial CI of each nominal level
    for level in COVERAGE_LEVELS:
        p = float(level) / 100.0
        ci = 4.0 * np.sqrt(p * (1.0 - p) / n)
        assert abs(snap["coverage"][level] - p) < ci, (level, snap)
    # z-statistics near the standard normal
    assert abs(snap["z_mean"]) < 5.0 / np.sqrt(n)
    assert abs(snap["z_std"] - 1.0) < 0.1
    # PIT ~ uniform: chi^2 over the lifetime histogram under a generous
    # bound (df=19; 60 is past the 1e-4 tail even per window)
    pit = np.asarray(snap["pit"], dtype=np.float64)
    expected = n / len(pit)
    chi2 = float(np.sum((pit - expected) ** 2) / expected)
    assert chi2 < 60.0, (chi2, pit)
    # the clean run NEVER alerted
    assert snap["alert"] is False
    assert all(not w["breached"] for w in snap["recent_windows"]), snap


@pytest.mark.parametrize("seed", [1, 11, 29])
def test_sigma_shrink_trips_alert_within_budget(seed):
    rng = np.random.default_rng(seed)
    monitor = QualityMonitor(window=128, breach_windows=2)
    mean, var, y = _calibrated_stream(rng, ALERT_BUDGET, sigma_truth_factor=2.0)
    tripped_at = 0
    for i in range(ALERT_BUDGET):
        monitor.observe(mean[i : i + 1], var[i : i + 1], y[i : i + 1])
        if monitor.alert:
            tripped_at = i + 1
            break
    assert 0 < tripped_at <= ALERT_BUDGET, "2x sigma-shrink never alerted"
    assert monitor.alert_reasons, monitor.snapshot()


def test_systematic_bias_trips_alert():
    rng = np.random.default_rng(5)
    monitor = QualityMonitor(window=128, breach_windows=2)
    mean, var, y = _calibrated_stream(rng, ALERT_BUDGET)
    monitor.observe(mean, var, y + 2.0)  # labels systematically shifted
    assert monitor.alert
    assert any(
        "z_mean" in r or "coverage" in r or "pit" in r
        for r in monitor.alert_reasons
    )


def test_alert_recovers_after_clean_window():
    rng = np.random.default_rng(9)
    monitor = QualityMonitor(window=64, breach_windows=2)
    mean, var, y = _calibrated_stream(rng, 256, sigma_truth_factor=3.0)
    monitor.observe(mean, var, y)
    assert monitor.alert
    mean, var, y = _calibrated_stream(rng, 256)
    monitor.observe(mean, var, y)
    assert not monitor.alert  # clean windows clear the verdict


# -- pending ring ----------------------------------------------------------


def test_pending_ring_join_is_idempotent_and_bounded():
    ring = PendingRing(capacity=4)
    for i in range(6):
        ring.put(f"r{i}", np.zeros(2), np.ones(2))
    assert ring.depth() == 4 and ring.evicted == 2
    with pytest.raises(UnknownRequestError):  # evicted oldest-first
        ring.join("r0")
    mean, var = ring.join("r5")
    assert mean.shape == (2,)
    assert ring.join("r5") is None  # duplicate: idempotent no-op
    with pytest.raises(UnknownRequestError):
        ring.join("never")
    # a re-served id overwrites instead of double-counting
    ring.put("dup", np.zeros(1), np.ones(1))
    ring.put("dup", np.zeros(1) + 1.0, np.ones(1))
    mean, _ = ring.join("dup")
    assert float(mean[0]) == 1.0
    # a length-mismatched join raises WITHOUT consuming the entry: the
    # client's corrected retry must still find the prediction pending,
    # not an idempotent-duplicate no-op that silently loses the labels
    ring.put("mis", np.zeros(3), np.ones(3))
    with pytest.raises(ValueError, match="3 row"):
        ring.join("mis", expect_rows=2)
    mean, _ = ring.join("mis", expect_rows=3)
    assert mean.shape == (3,)


# -- covariate summary + drift --------------------------------------------


def test_covariate_summary_shape_and_provenance_round_trip(
    saved_model, tmp_path
):
    path, model, x, y = saved_model
    summary = getattr(model.instr, "covariate_summary", None)
    assert summary is not None
    assert summary["dims"] == 3 and summary["n"] > 0
    assert len(summary["mean"]) == 3 and len(summary["std"]) == 3
    assert summary["active_dist"]["q50"] <= summary["active_dist"]["q99"]
    # the saved model carries it in provenance_json; load restores it
    from spark_gp_tpu.utils.serialization import load_model

    loaded = load_model(path)
    assert loaded.covariate_summary == summary
    # and a load -> save -> load round trip keeps it (the model-attr leg)
    path2 = str(tmp_path / "round.npz")
    loaded.save(path2)
    assert load_model(path2).covariate_summary == summary


def test_drift_monitor_clean_vs_shifted():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2000, 4))
    summary = summarize_covariates(x, active=x[:64])
    clean = DriftMonitor(summary, window=64, breach_windows=2)
    for _ in range(ALERT_BUDGET // 16):
        clean.score_rows(rng.normal(size=(16, 4)))
    assert not clean.alert, clean.snapshot()
    drifted = DriftMonitor(summary, window=64, breach_windows=2)
    for _ in range(16):
        drifted.score_rows(rng.normal(size=(16, 4)) + 3.0)
    assert drifted.alert
    assert drifted.windows_closed == 4
    assert any("mean_shift" in r or "out_of_mass" in r
               for r in drifted.alert_reasons)


def test_drift_monitor_bounds_per_batch_cost_by_sampling():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(2000, 4))
    summary = summarize_covariates(x)
    capped = DriftMonitor(summary, window=64, breach_windows=2)
    capped.score_rows(rng.normal(size=(256, 4)))
    assert capped.rows == 16  # stride-sampled down to the cap
    # an uncapped monitor folds every row — one oversized batch closes
    # as many FULL windows as it spans
    full = DriftMonitor(
        summary, window=64, breach_windows=2, max_rows_per_batch=None
    )
    full.score_rows(rng.normal(size=(256, 4)) + 3.0)
    assert full.rows == 256 and full.windows_closed == 4
    assert full.alert


def test_drift_monitors_are_per_version_so_canary_alternation_counts():
    """A canary rollout alternates stable/candidate dispatches of the
    same model name: each version must keep ITS OWN drift monitor (a
    single last-seen-version slot would rebuild on every alternation and
    reset the window before it could ever close — drift alerting dead
    exactly while a canary is active)."""
    from types import SimpleNamespace

    from spark_gp_tpu.obs.quality import ServeQualityPlane
    from spark_gp_tpu.serve.metrics import ServingMetrics

    rng = np.random.default_rng(5)
    x = rng.normal(size=(2000, 4))
    summary = summarize_covariates(x, active=x[:64])

    def entry(version):
        return SimpleNamespace(
            version=version,
            model=SimpleNamespace(covariate_summary=summary),
        )

    plane = ServeQualityPlane(
        ServingMetrics(), window=32, drift_window=64, breach_windows=2
    )
    stable, candidate = entry(1), entry(2)
    # 32 alternating 8-row drifted dispatches per version: each version's
    # monitor accumulates 8 rows/dispatch (under the 16-row cap), so both
    # close windows and alert despite the alternation
    for _ in range(32):
        for e in (stable, candidate):
            plane._process(
                "m", e, [], None, None, rng.normal(size=(8, 4)) + 3.0
            )
    state = plane._state_for("m")
    monitors = {v: d for v, d in state.drifts.items()}
    assert set(monitors) == {1, 2}
    for version, monitor in monitors.items():
        assert monitor.windows_closed >= 2, (version, monitor.snapshot())
        assert monitor.alert, version
    assert plane.alert_reason("m") is not None
    # the bound holds: stale versions are evicted oldest-first
    for version in range(3, 9):
        plane._state_for("m", entry(version))
    assert len(plane._state_for("m").drifts) == 4


def test_summarize_covariates_degenerate_inputs():
    assert summarize_covariates(np.zeros((1, 3))) is None
    assert summarize_covariates(np.full((8, 2), np.nan)) is None
    # constant dims must not divide by zero
    summary = summarize_covariates(np.ones((32, 2)))
    assert summary is not None and summary["std"] == [0.0, 0.0]
    DriftMonitor(summary).score_rows(np.ones((8, 2)))


# -- serve integration -----------------------------------------------------


def _boot(path, **kw):
    server = GPServeServer(
        max_batch=32, min_bucket=8, max_wait_ms=1.0,
        request_timeout_ms=10_000.0, **kw,
    )
    server.register("m", path)
    server.start()
    return server


def test_observe_joins_labels_and_health_carries_snapshot(saved_model):
    path, model, x, y = saved_model
    server = _boot(path, quality_window=32)
    try:
        fut = server.submit("m", x[:4], request_id="r1")
        mean, var = fut.result(10.0)
        # a wrong-length observation is a client error that does NOT
        # consume the pending entry — the corrected retry still grades
        with pytest.raises(ValueError, match="4 row"):
            server.observe("m", "r1", y[:3])
        out = server.observe("m", "r1", y[:4])
        assert out["joined"] == 4 and out["duplicate"] is False
        # idempotent duplicate
        dup = server.observe("m", "r1", y[:4])
        assert dup["joined"] == 0 and dup["duplicate"] is True
        assert server.metrics.counter("quality.observe.duplicate") == 1
        with pytest.raises(UnknownRequestError):
            server.observe("m", "never-served", y[:1])
        assert (
            server.metrics.counter("quality.observe.unknown_request") == 1
        )
        health = server.health()
        calib = health["quality"]["models"]["m"]["calibration"]
        assert calib["observations"] == 4
        assert health["quality"]["models"]["m"]["pending"]["depth"] == 0
        # a request WITHOUT an id is never parked
        server.submit("m", x[:2]).result(10.0)
        assert server.quality.snapshot()["models"]["m"]["pending"]["depth"] == 0
    finally:
        server.stop()


def test_quality_disabled_server_rejects_observe(saved_model):
    path, model, x, y = saved_model
    server = _boot(path, quality=False)
    try:
        assert server.quality is None
        server.submit("m", x[:2], request_id="r1").result(10.0)
        with pytest.raises(QualityDisabledError) as err:
            server.observe("m", "r1", y[:2])
        assert err.value.code == "observe.disabled"
        assert server.health()["quality"] == {"enabled": False}
    finally:
        server.stop()


@pytest.mark.chaos
def test_chaos_miscalibrate_trips_serve_alert_and_degrades(saved_model):
    """The acceptance proof at the server level: a clean feedback loop
    never alerts; the 2x sigma-shrink injector alerts within the budget
    and flips health to degraded."""
    path, model, x, y = saved_model
    rng = np.random.default_rng(17)
    server = _boot(path, quality_window=64)

    def feed(n_obs, sigma_truth_factor):
        done = 0
        i = 0
        while done < n_obs:
            rid = f"f{sigma_truth_factor}-{i}"
            i += 1
            row = int(rng.integers(0, x.shape[0] - 8))
            mean, var = server.submit(
                "m", x[row : row + 4], request_id=rid
            ).result(10.0)
            labels = np.asarray(mean) + sigma_truth_factor * np.sqrt(
                np.asarray(var)
            ) * rng.standard_normal(4)
            server.observe("m", rid, labels)
            done += 4
            if server.health()["quality"]["alerting"]:
                return done
        return 0

    try:
        assert feed(ALERT_BUDGET, 1.0) == 0, "clean twin alerted"
        assert server.health()["status"] == "ok"
        with chaos.miscalibrate(0.5):
            tripped = feed(ALERT_BUDGET, 2.0)
        assert 0 < tripped <= ALERT_BUDGET
        health = server.health()
        assert health["status"] == "degraded"
        assert server.metrics.counter("quality.alerts") >= 1
        assert server.metrics.gauges.get("quality.alert.m") == 1.0
    finally:
        server.stop()


@pytest.mark.chaos
def test_chaos_drift_inputs_trips_drift_alert(saved_model):
    path, model, x, y = saved_model
    server = _boot(path)

    def pump(n_rows):
        done = 0
        while done < n_rows:
            row = done % (x.shape[0] - 8)
            server.submit("m", x[row : row + 8]).result(10.0)
            done += 8
            if server.health()["quality"]["alerting"]:
                return done
        return 0

    try:
        assert pump(ALERT_BUDGET) == 0, "clean traffic raised drift alert"
        shift = 4.0 * float(x.std())
        with chaos.drift_inputs(shift):
            tripped = pump(ALERT_BUDGET)
        assert 0 < tripped <= ALERT_BUDGET
        assert server.metrics.counter("drift.alerts") >= 1
        assert server.metrics.gauges.get("drift.alert.m") == 1.0
        assert server.health()["status"] == "degraded"
    finally:
        server.stop()


@pytest.mark.chaos
def test_canary_quality_guard_vetoes_promotion(saved_model):
    """A candidate that clears the shadow-score bar while the model is
    under an active miscalibration alert must roll back, not promote."""
    from spark_gp_tpu.serve.lifecycle import CanaryPolicy

    path, model, x, y = saved_model
    rng = np.random.default_rng(23)
    server = _boot(path, quality_window=32)
    try:
        # drive the model into a quality alert with miscalibrated labels
        for i in range(40):
            rid = f"g{i}"
            row = int(rng.integers(0, x.shape[0] - 8))
            mean, var = server.submit(
                "m", x[row : row + 4], request_id=rid
            ).result(10.0)
            server.observe(
                "m", rid,
                np.asarray(mean)
                + 3.0 * np.sqrt(np.asarray(var)) * rng.standard_normal(4),
            )
        assert server.quality.alert_reason("m") is not None
        # same model file as candidate: shadow deltas are 0 (clean), so
        # without the guard it would promote after promote_after scores
        server.register(
            "m", path,
            canary_policy=CanaryPolicy(
                fraction=1.0, promote_after=3, quality_guard=True
            ),
        )
        for i in range(8):
            server.submit("m", x[i : i + 2]).result(10.0)
            if server.canaries.active("m") is None:
                break
        assert server.metrics.counter("canary.rollbacks") == 1
        assert server.metrics.counter("canary.promotions") == 0
        quarantined = server.canaries.snapshot()["quarantined"]
        assert any(
            "quality alert" in reason for reason in quarantined.values()
        ), quarantined
    finally:
        server.stop()


# -- fleet forwarding ------------------------------------------------------


@pytest.mark.chaos
def test_router_forwards_observation_to_answering_replica(saved_model):
    from spark_gp_tpu.parallel.coord import (
        InProcessCoordClient,
        InProcessCoordStore,
    )
    from spark_gp_tpu.serve.fleet import FleetMembership, LocalReplica
    from spark_gp_tpu.serve.router import FleetRouter

    path, model, x, y = saved_model
    store = InProcessCoordStore()
    membership = FleetMembership(
        InProcessCoordClient(store, 0, 1), fleet="q",
        interval_s=0.05, straggler_after_s=5.0, dead_after_s=10.0,
    )
    replicas = []
    for i in range(2):
        server = GPServeServer(
            max_batch=16, min_bucket=8, max_wait_ms=1.0,
            request_timeout_ms=10_000.0, replica_id=f"r{i}",
        )
        server.register("m", path)
        server.start()
        replica = LocalReplica(server, f"r{i}", membership)
        replica.register()
        replicas.append(replica)
    router = FleetRouter(
        membership,
        transports={r.replica_id: r.transport for r in replicas},
        max_batch=16, min_bucket=8, default_timeout_ms=10_000.0,
        poll_interval_s=0.0,
    )
    try:
        for replica in replicas:
            replica.heartbeat()
        mean, var = router.predict("m", x[:4], request_id="fleet-1")
        result = router.observe("m", "fleet-1", y[:4])
        assert result["joined"] == 4
        assert router.metrics.counter("router.observes") == 1
        # the observation landed on exactly ONE replica — the answerer
        joined_counts = [
            r.server.metrics.counter("quality.observations")
            for r in replicas
        ]
        assert sorted(joined_counts) == [0.0, 4.0], joined_counts
        with pytest.raises(UnknownRequestError):
            router.observe("m", "nobody-answered-this", y[:1])
        # id-LESS fleet traffic (the router mints an internal hedging id)
        # must consume neither the router's answered memory nor any
        # replica's bounded pending ring — those minted ids can never
        # receive a label, and parking them would evict observable ones
        def pending_total():
            return sum(
                r.server.quality.snapshot()["models"]
                .get("m", {"pending": {"depth": 0}})["pending"]["depth"]
                for r in replicas
            )

        depth_before = pending_total()
        router.predict("m", x[:4])
        for r in replicas:
            r.server.quality.flush()
        assert len(router._answered) == 1  # just "fleet-1"
        assert pending_total() == depth_before
        # the fleet page aggregates quality verdicts per replica
        sampled = router.sample_fleet()
        assert set(sampled["quality_alerting"]) == {"r0", "r1"}
        assert all(v == [] for v in sampled["quality_alerting"].values())
    finally:
        router.close()
        for replica in replicas:
            replica.stop()


# -- fit-time telemetry + journal + gpctl ----------------------------------


def test_fit_stamps_expert_quality_into_journal(saved_model):
    path, model, x, y = saved_model
    journal = model.run_journal
    assert journal["schema_version"] >= 2
    eq = journal["expert_quality"]
    assert eq is not None
    assert eq["experts"] == 4 and eq["active"] == 4
    assert len(eq["nll"]) == 4 and len(eq["weight"]) == 4
    assert all(np.isfinite(v) for v in eq["nll"])
    assert all(w == 1.0 for w in eq["weight"])
    metrics = model.instr.metrics
    assert metrics["expert_quality.nll_spread"] >= 0.0
    assert metrics["expert_quality.jitter_max"] == 0.0
    assert metrics["expert_quality.weight_min"] == 1.0


def test_expert_telemetry_kill_switch(monkeypatch):
    monkeypatch.setenv("GP_EXPERT_TELEMETRY", "0")
    monkeypatch.setenv("GP_COVARIATE_SUMMARY", "0")
    model, x, y = _fit(seed=5)
    assert getattr(model.instr, "expert_quality", None) is None
    assert getattr(model.instr, "covariate_summary", None) is None
    assert (model.run_journal or {}).get("expert_quality") is None


def test_validate_journal_contract(tmp_path):
    from spark_gp_tpu.obs.runtime import (
        JOURNAL_SCHEMA_VERSION,
        validate_journal,
    )

    model, x, y = _fit(seed=7)
    journal = {k: v for k, v in model.run_journal.items() if k != "path"}
    assert validate_journal(journal) == []
    # legacy journals without the stamp stay valid — including true
    # pre-forensics/pre-ladder documents that predate pid/build_info/
    # degradations entirely
    legacy = dict(journal)
    legacy.pop("schema_version")
    assert validate_journal(legacy) == []
    for key in ("pid", "build_info", "degradations"):
        legacy.pop(key)
    assert validate_journal(legacy) == []
    # ... but a STAMPED journal must carry the v2 keys
    stamped = dict(journal)
    del stamped["pid"]
    assert any("pid" in p for p in validate_journal(stamped))
    # a NEWER schema_version is a problem (unknown semantics)
    future = dict(journal, schema_version=JOURNAL_SCHEMA_VERSION + 1)
    assert any("newer" in p for p in validate_journal(future))
    broken = dict(journal)
    del broken["timings"]
    broken["spans"] = "nope"
    problems = validate_journal(broken)
    assert any("timings" in p for p in problems)
    assert any("spans" in p for p in problems)


def _gpctl(*args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "tools.gpctl", *args],
        capture_output=True, text=True, timeout=120, env=env, cwd=ROOT,
    )


@pytest.fixture(scope="module")
def journal_dir(tmp_path_factory, saved_model):
    path, model, x, y = saved_model
    directory = str(tmp_path_factory.mktemp("journals"))
    journal = dict(model.run_journal)
    journal.pop("path", None)
    with open(os.path.join(directory, "run_journal_q-1-p1-t1.json"), "w") as fh:
        json.dump(journal, fh, default=str)
    return directory


def test_gpctl_show_validates_journal_schema(journal_dir, tmp_path):
    good = os.path.join(journal_dir, "run_journal_q-1-p1-t1.json")
    out = _gpctl("show", good)
    assert out.returncode == 0, out.stderr
    assert "expert_quality" in out.stdout
    # a malformed journal exits 1 with the problems named — the bundle
    # validation contract, now for journals
    with open(good, encoding="utf-8") as fh:
        doc = json.load(fh)
    doc.pop("timings")
    doc["schema_version"] = 99
    bad = str(tmp_path / "run_journal_bad-1-p1-t1.json")
    with open(bad, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    out = _gpctl("show", bad)
    assert out.returncode == 1, (out.stdout, out.stderr)
    assert "SCHEMA" in out.stderr
    assert "timings" in out.stderr and "newer" in out.stderr


def test_gpctl_events_lists_and_filters(journal_dir):
    out = _gpctl("events", journal_dir)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip(), "no events listed"
    # --grep filters by event name; an unmatched pattern exits 2
    out = _gpctl("events", journal_dir, "--grep", "compile")
    if out.returncode == 0:
        assert all(
            "compile" in line for line in out.stdout.strip().splitlines()
        )
    else:
        assert out.returncode == 2
    out = _gpctl("events", journal_dir, "--grep", "no_such_event_name")
    assert out.returncode == 2
    out = _gpctl("events", journal_dir, "--grep", "[broken")
    assert out.returncode == 2


def test_gpctl_quality_renders_expert_table(journal_dir):
    out = _gpctl("quality", journal_dir)
    assert out.returncode == 0, out.stderr
    assert "nll_spread=" in out.stdout
    out = _gpctl("quality", "--experts", journal_dir)
    assert out.returncode == 0
    assert "expert" in out.stdout and "weight" in out.stdout


def test_quality_metrics_render_on_openmetrics_page(saved_model):
    path, model, x, y = saved_model
    server = _boot(path, quality_window=32)
    try:
        mean, var = server.submit("m", x[:4], request_id="om1").result(10.0)
        server.observe("m", "om1", y[:4])
        page = server.openmetrics()
        assert "gp_quality_observations_total" in page
        assert 'gp_quality_z_std{model="m"}' in page
    finally:
        server.stop()
