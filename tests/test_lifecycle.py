"""Serve lifecycle hardening (spark_gp_tpu/serve/lifecycle.py): graceful
drain, canary rollout with auto-rollback, hang watchdog, memory-pressure
admission, bounded registry retention.

The ISSUE 7 acceptance proofs live here (plus the CLI drain proof at the
real process boundary):
(a) drain completes in-flight work and rejects new submits with
    ``code=queue.shed.draining``;
(b) a chaos-hung predict trips the watchdog within its hang deadline
    while the other model keeps answering;
(c) a guard-breaching canary auto-rolls back with zero failed requests
    on the stable version;
(d) eviction actually frees the retired version's compiled bucket cache.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.resilience.breaker import BreakerOpenError, CircuitBreaker
from spark_gp_tpu.resilience.chaos import FlakyPredictor, hang_model
from spark_gp_tpu.serve import (
    CanaryPolicy,
    DrainingError,
    ExecHungError,
    GPServeServer,
    MemoryAdmissionGate,
    MemoryPressureError,
    ModelRegistry,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def two_models(tmp_path_factory):
    def fit(seed):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(120, 3))
        y = np.sin(x.sum(axis=1))
        return (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(1.0))
            .setDatasetSizeForExpert(30).setActiveSetSize(30)
            .setMaxIter(5).setSeed(seed).fit(x, y)
        ), x

    d = tmp_path_factory.mktemp("lifecycle")
    model_a, x = fit(1)
    model_b, _ = fit(7)
    pa, pb = str(d / "a.npz"), str(d / "b.npz")
    model_a.save(pa)
    model_b.save(pb)
    return pa, pb, x


def _server(**kw):
    defaults = dict(max_batch=16, min_bucket=8, max_wait_ms=1.0)
    defaults.update(kw)
    return GPServeServer(**defaults)


# -- graceful drain --------------------------------------------------------


def test_drain_completes_inflight_and_rejects_new(two_models):
    pa, _, x = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    futs = [server.submit("m", x[i : i + 3]) for i in range(12)]

    server.begin_drain()
    health = server.health()
    assert health["status"] == "draining"
    assert health["lifecycle"]["draining"]

    with pytest.raises(DrainingError) as exc:
        server.submit("m", x[:3])
    assert exc.value.code == "queue.shed.draining"
    assert server.metrics.counter("queue.shed.draining") == 1

    assert server.drain(deadline_s=30.0) is True
    # every in-flight/queued request completed with an ANSWER, not an error
    for fut in futs:
        mean, var = fut.result(timeout=0.1)
        assert np.isfinite(mean).all() and len(mean) == 3
    assert server.metrics.counter("lifecycle.drains") == 1
    assert server.health()["lifecycle"]["state"] == "stopped"
    hist = server.metrics.histogram("lifecycle.drain_s")
    assert hist is not None and hist.snapshot()["count"] == 1


def test_drain_past_deadline_fails_leftovers_fast(two_models):
    pa, _, x = two_models
    # max_batch 8 and 8-row requests: one request per dispatch, so the
    # backlog is 8 SERIAL slow dispatches the tiny deadline cannot cover
    # (smaller requests would coalesce into one batch and all complete)
    server = _server(max_batch=8, request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    entry = server.registry.get("m")
    entry.predictor = FlakyPredictor(entry.predictor, latency_s=0.25)
    futs = [server.submit("m", x[:8]) for _ in range(8)]
    # far too short for 8 serial 0.25s dispatches: the drain must give up
    # at the deadline and fail the leftovers instead of blocking forever
    assert server.drain(deadline_s=0.05) is False
    outcomes = []
    for fut in futs:
        try:
            fut.result(timeout=5.0)
            outcomes.append("ok")
        except RuntimeError:
            outcomes.append("failed")
    assert "failed" in outcomes  # leftovers were NOT silently completed


# -- hang watchdog ---------------------------------------------------------


def test_watchdog_trips_hung_model_while_other_keeps_serving(two_models):
    pa, pb, x = two_models
    server = _server(
        hang_timeout_s=0.25, breaker_reset_s=30.0, request_timeout_ms=None
    )
    server.register("hang", pa)
    server.register("ok", pb)
    server.start()

    def timed_ok_predicts(k=5):
        samples = []
        for _ in range(k):
            t0 = time.monotonic()
            mean, _ = server.predict("ok", x[:4], timeout_ms=5000)
            samples.append(time.monotonic() - t0)
            assert np.isfinite(mean).all() and len(mean) == 4
        return sorted(samples)

    clean = timed_ok_predicts()  # model B's clean baseline, same process
    hanging = hang_model(server, "hang", hang_forever=True, max_block_s=30.0)
    try:
        t0 = time.monotonic()
        fut = server.submit("hang", x[:4])
        with pytest.raises(ExecHungError) as exc:
            fut.result(timeout=5.0)
        # the verdict came from the WATCHDOG near its deadline — not from a
        # request deadline (disabled here) and not after the full block
        assert time.monotonic() - t0 < 3.0
        assert exc.value.code == "exec.hung"
        assert hanging.hung == 1

        # the model's breaker tripped: rejected at the door, no dispatch
        with pytest.raises(BreakerOpenError):
            server.submit("hang", x[:4])
        assert server.metrics.counter("exec.hung") == 1
        assert server.metrics.counter("lifecycle.watchdog_trips") == 1
        assert server.metrics.counter("breaker.trips") == 1

        # the OTHER model keeps serving: the replacement worker dispatches
        # even though the hung thread is still parked in the device call —
        # and its tail latency stays within 2x its clean baseline (plus a
        # small absolute floor so a shared-CI scheduling blip cannot flake
        # a sub-millisecond comparison)
        after = timed_ok_predicts()
        assert after[-1] <= max(2.0 * clean[-1], 0.25), (clean, after)

        health = server.health()
        assert health["status"] == "degraded"
        assert health["broken_models"] == ["hang"]
        assert health["lifecycle"]["watchdog"]["trips"] == 1
    finally:
        hanging.release()
        server.stop()


def test_released_hang_does_not_double_answer(two_models):
    """The stale dispatch eventually returns AFTER the watchdog answered:
    its futures are already failed, its breaker outcome is void — nothing
    may double-set or close the tripped breaker."""
    pa, _, x = two_models
    server = _server(
        hang_timeout_s=0.2, breaker_reset_s=30.0, request_timeout_ms=None
    )
    server.register("m", pa)
    server.start()
    hanging = hang_model(server, "m", hang_first=1, max_block_s=30.0)
    try:
        fut = server.submit("m", x[:4])
        with pytest.raises(ExecHungError):
            fut.result(timeout=5.0)
        hanging.release()  # the wedged thread now unwinds with a SUCCESS
        time.sleep(0.3)
        # the stale success must not have closed the watchdog-tripped breaker
        assert server._breaker_for("m").state == CircuitBreaker.OPEN
        with pytest.raises(ExecHungError):
            fut.result(timeout=0.1)  # still the hang verdict, not a result
    finally:
        hanging.release()
        server.stop()


# -- canary rollout --------------------------------------------------------


def test_clean_canary_auto_promotes(two_models):
    pa, _, x = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    try:
        entry = server.rollout(
            "m",
            canary_policy=CanaryPolicy(fraction=0.5, promote_after=3),
        )
        assert entry.version == 2
        # candidate is NOT the default yet: the latest pointer stays put
        assert server.registry.get("m").version == 1
        assert server.health()["lifecycle"]["canary"]["active"]["m"][
            "candidate"
        ] == 2

        for i in range(12):
            mean, _ = server.predict("m", x[i : i + 3], timeout_ms=5000)
            assert np.isfinite(mean).all()
            if server.registry.get("m").version == 2:
                break
        assert server.registry.get("m").version == 2  # promoted
        assert server.metrics.counter("canary.promotions") == 1
        assert server.metrics.counter("canary.shadow_scores") >= 3
        assert server.canaries.active("m") is None
        # the predecessor survives bounded retention (max_versions=2)
        assert server.registry.get("m", 1).version == 1
    finally:
        server.stop()


def test_guard_breaching_canary_rolls_back_zero_stable_failures(two_models):
    pa, pb, x = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    try:
        server.rollout("m", pb, canary_fraction=0.5)  # a DIFFERENT model
        failed = 0
        for i in range(10):
            try:
                mean, _ = server.predict("m", x[i : i + 3], timeout_ms=5000)
                assert np.isfinite(mean).all()
            except Exception:  # noqa: BLE001 — counting is the assertion
                failed += 1
        # the breach rolled the candidate back...
        assert server.metrics.counter("canary.breaches") >= 1
        assert server.metrics.counter("canary.rollbacks") == 1
        assert server.registry.get("m").version == 1
        with pytest.raises(KeyError):
            server.registry.get("m", 2)  # retired + released
        assert "m:2" in server.canaries.snapshot()["quarantined"]
        # ...and NOT ONE request failed: the canary slice was answered by
        # the (working) candidate before the verdict, the rest by stable
        assert failed == 0
    finally:
        server.stop()


def test_erroring_canary_rolls_back_without_tripping_stable_breaker(two_models):
    pa, _, x = two_models
    server = _server(request_timeout_ms=None, breaker_threshold=2)
    server.register("m", pa)
    server.start()
    try:
        entry = server.rollout(
            "m",
            canary_policy=CanaryPolicy(
                fraction=1.0, max_errors=2, promote_after=100
            ),
        )
        broken = server.registry.get("m", entry.version)
        broken.predictor = FlakyPredictor(broken.predictor, fail_forever=True)
        errors = 0
        for i in range(6):
            try:
                server.predict("m", x[i : i + 3], timeout_ms=5000)
            except RuntimeError:
                errors += 1
        assert errors == 2  # exactly the canary error budget
        assert server.metrics.counter("canary.rollbacks") == 1
        assert server.registry.get("m").version == 1
        # candidate failures never counted against the NAME-level breaker
        # the stable version serves behind
        assert server._breaker_for("m").state == CircuitBreaker.CLOSED
        mean, _ = server.predict("m", x[:3], timeout_ms=5000)
        assert np.isfinite(mean).all()
    finally:
        server.stop()


def test_hung_canary_rolls_back_without_tripping_stable_breaker(two_models):
    """A WEDGED candidate (not merely raising) counts against the canary
    error budget, never the name-level breaker — a hung canary must not
    shed stable traffic."""
    pa, _, x = two_models
    server = _server(hang_timeout_s=0.2, request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    entry = server.rollout(
        "m",
        canary_policy=CanaryPolicy(fraction=1.0, max_errors=1, promote_after=100),
    )
    hanging = hang_model(
        server, "m", version=entry.version, hang_forever=True, max_block_s=30.0
    )
    try:
        fut = server.submit("m", x[:3])  # fraction 1.0: routed to candidate
        with pytest.raises(ExecHungError):
            fut.result(timeout=5.0)
        assert server.metrics.counter("canary.rollbacks") == 1
        assert server._breaker_for("m").state == CircuitBreaker.CLOSED
        mean, _ = server.predict("m", x[:3], timeout_ms=5000)  # stable serves
        assert np.isfinite(mean).all()
    finally:
        hanging.release()
        server.stop()


def test_queued_canary_requests_survive_rollback(two_models):
    """Default-traffic requests pinned to the candidate while QUEUED are
    re-served by the stable latest after a rollback, not failed on a
    version the client never asked for."""
    pa, pb, x = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    entry = server.rollout(
        "m", pb,
        canary_policy=CanaryPolicy(fraction=1.0, max_errors=1, promote_after=100),
    )
    # server not started: every submit pins to the candidate and queues
    futs = [server.submit("m", x[i : i + 3]) for i in range(4)]
    # one request EXPLICITLY pinned to the candidate version: that is a
    # contract ("serve THAT one or fail"), not re-routable default traffic
    pinned = server.submit("m", x[:3], version=entry.version)
    server.canaries.observe_error("m", entry.version)  # rollback NOW
    assert server.metrics.counter("canary.rollbacks") == 1
    server.start()
    try:
        for fut in futs:
            mean, _ = fut.result(timeout=5.0)
            assert np.isfinite(mean).all() and len(mean) == 3
        with pytest.raises(KeyError):
            pinned.result(timeout=5.0)
    finally:
        server.stop()


def test_direct_reload_supersedes_active_canary(two_models):
    """A plain reload during an active canary cancels the experiment
    first — otherwise retention would evict the canary's incumbent and
    the orphaned controller could later drag the latest pointer
    backwards onto the stale candidate."""
    pa, pb, x = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    try:
        server.rollout(
            "m",
            canary_policy=CanaryPolicy(fraction=1.0, promote_after=100),
        )
        v3 = server.reload("m", pb)  # direct reload wins
        assert server.canaries.active("m") is None
        assert server.metrics.counter("canary.rollbacks") == 1
        assert server.registry.get("m").version == v3.version == 3
        with pytest.raises(KeyError):
            server.registry.get("m", 2)  # the cancelled candidate is gone
        mean, _ = server.predict("m", x[:3], timeout_ms=5000)
        assert np.isfinite(mean).all()
        assert server.registry.get("m").version == 3  # never dragged back
    finally:
        server.stop()


def test_retired_incumbent_resolves_canary_by_promotion(two_models):
    """An operator retiring the incumbent out from under an active canary
    must not wedge the state machine: with nothing left to score against,
    the candidate (the only version serving) is formally promoted."""
    pa, _, x = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    try:
        server.rollout(
            "m",
            canary_policy=CanaryPolicy(fraction=1.0, promote_after=100),
        )
        server.registry.retire("m", 1)
        mean, _ = server.predict("m", x[:3], timeout_ms=5000)
        assert np.isfinite(mean).all()
        assert server.registry.get("m").version == 2
        assert server.canaries.active("m") is None
        assert server.metrics.counter("canary.promotions") == 1
    finally:
        server.stop()


def test_replace_worker_after_stop_does_not_respawn(two_models):
    """A hang verdict racing stop() must not repopulate the worker slot —
    that would break the stop/start cycle (start() would see a live
    thread and never clear the stopping flag)."""
    pa, _, x = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    server.stop()
    server._queue.replace_worker()  # the racing verdict's recovery call
    assert server._queue._thread is None
    server.start()  # the cycle still works
    try:
        mean, _ = server.predict("m", x[:3], timeout_ms=5000)
        assert np.isfinite(mean).all()
    finally:
        server.stop()


def test_second_rollout_while_canary_active_is_refused_and_released(two_models):
    pa, pb, _ = two_models
    server = _server(request_timeout_ms=None)
    server.register("m", pa)
    server.start()
    try:
        server.rollout(
            "m", canary_policy=CanaryPolicy(fraction=0.5, promote_after=100)
        )
        with pytest.raises(ValueError, match="active canary"):
            server.rollout("m", pb, canary_fraction=0.5)
        # the refused candidate (v3) was retired, not leaked as an
        # unroutable warmed entry
        with pytest.raises(KeyError):
            server.registry.get("m", 3)
        assert server.canaries.active("m")["candidate"] == 2
    finally:
        server.stop()


def test_hung_incumbent_during_shadow_scoring_blames_incumbent(two_models):
    """When the INCUMBENT wedges during shadow scoring, the verdict must
    land on it (name-level breaker), not roll back the healthy candidate
    whose answer already succeeded — otherwise a broken incumbent would
    kill every redeploy attempt while itself serving on."""
    pa, _, x = two_models
    server = _server(
        hang_timeout_s=0.25, breaker_reset_s=30.0, request_timeout_ms=None
    )
    server.register("m", pa)
    server.start()
    server.rollout(
        "m",
        canary_policy=CanaryPolicy(
            fraction=1.0, promote_after=100, max_errors=100
        ),
    )
    hanging = hang_model(
        server, "m", version=1, hang_forever=True, max_block_s=30.0
    )
    try:
        fut = server.submit("m", x[:3])  # candidate answers, scoring wedges
        with pytest.raises(ExecHungError):
            fut.result(timeout=5.0)
        assert server.metrics.counter("canary.rollbacks") == 0
        assert server.canaries.active("m") is not None  # candidate survives
        assert server._breaker_for("m").state == CircuitBreaker.OPEN
    finally:
        hanging.release()
        server.stop()


# -- bounded retention / eviction ------------------------------------------


def test_eviction_frees_retired_bucket_cache(two_models):
    pa, pb, _ = two_models
    reg = ModelRegistry(max_batch=16, min_bucket=8, max_versions=1)
    v1 = reg.register("m", pa)
    old_predictor = v1.predictor
    assert old_predictor.released is False
    v2 = reg.reload("m", pb)
    assert reg.metrics.counter("registry.evictions") == 1
    assert old_predictor.released is True
    assert old_predictor._jit is None and old_predictor._theta is None
    with pytest.raises(RuntimeError, match="released"):
        v1.predict(np.zeros((2, 3)))
    assert reg.get("m") is v2
    with pytest.raises(KeyError):
        reg.get("m", 1)


def test_release_defers_free_until_inflight_predict_finishes(two_models):
    """Eviction racing an in-flight predict: the hot-swap invariant says
    a batch that already resolved the version completes against its warm
    executables — release refuses NEW predicts immediately but frees the
    compiled surface only when the last in-flight call exits."""
    pa, _, x = two_models
    reg = ModelRegistry(max_batch=16, min_bucket=8)
    predictor = reg.register("m", pa).predictor

    started, resume = threading.Event(), threading.Event()
    original = predictor._normalize

    def gated_normalize(x_test):  # runs AFTER the refcount is taken
        started.set()
        assert resume.wait(10.0)
        return original(x_test)

    predictor._normalize = gated_normalize
    results = []
    worker = threading.Thread(
        target=lambda: results.append(predictor.predict(x[:4])), daemon=True
    )
    worker.start()
    assert started.wait(5.0)
    predictor.release()  # mid-flight eviction
    assert predictor.released and predictor._jit is not None  # deferred
    resume.set()
    worker.join(10.0)
    mean, var = results[0]
    assert np.isfinite(mean).all() and len(mean) == 4  # in-flight survived
    assert predictor._jit is None  # ...and the free ran right after
    with pytest.raises(RuntimeError, match="released"):
        predictor.predict(x[:4])


def test_stop_after_begin_drain_clears_draining_gauge(two_models):
    pa, _, _ = two_models
    server = _server()
    server.register("m", pa)
    server.start()
    server.begin_drain()
    server.stop()
    assert server.metrics.snapshot()["gauges"]["lifecycle.draining"] == 0.0


def test_retire_repoints_latest_and_releases(two_models):
    pa, pb, _ = two_models
    reg = ModelRegistry(max_batch=16, min_bucket=8, max_versions=4)
    reg.register("m", pa)
    v2 = reg.reload("m", pb)
    assert reg.get("m") is v2
    assert reg.retire("m", 2) is True
    assert reg.get("m").version == 1  # latest repointed to the survivor
    assert v2.predictor.released is True
    assert reg.retire("m", 9) is False


# -- memory-pressure admission ---------------------------------------------


def test_memory_gate_hysteresis_and_priority_floor():
    usage = {"bytes": 50.0}
    gate = MemoryAdmissionGate(
        limit_bytes=100.0, high_watermark=0.9, low_watermark=0.5,
        sample_interval_s=0.0, sampler=lambda: usage["bytes"],
    )
    gate.check(priority=0)  # healthy: admitted

    usage["bytes"] = 95.0  # past the high watermark: shed low priority
    with pytest.raises(MemoryPressureError) as exc:
        gate.check(priority=0)
    assert exc.value.code == "queue.shed.memory"
    gate.check(priority=1)  # at the floor: still admitted

    usage["bytes"] = 70.0  # between the watermarks: hysteresis holds shed
    with pytest.raises(MemoryPressureError):
        gate.check(priority=0)

    usage["bytes"] = 40.0  # under the low watermark: automatic recovery
    gate.check(priority=0)
    snap = gate.snapshot()
    assert snap["shedding"] is False and snap["sheds"] == 2


def test_server_sheds_on_memory_pressure_with_code(two_models):
    pa, _, x = two_models
    server = _server(request_timeout_ms=None)
    # GB-scale limit: the watermark latch is what this test exercises —
    # the per-request predicted bytes (KBs) stay far inside headroom, so
    # the memplan leg never decides here (it has its own tests in
    # test_memplan.py)
    usage = {"bytes": 0.95e9}
    server.memory_gate = MemoryAdmissionGate(
        limit_bytes=1e9, high_watermark=0.9, low_watermark=0.5,
        sample_interval_s=0.0, sampler=lambda: usage["bytes"],
    )
    server.register("m", pa)
    server.start()
    try:
        with pytest.raises(MemoryPressureError):
            server.submit("m", x[:3])
        assert server.metrics.counter("queue.shed.memory") == 1
        health = server.health()
        assert health["status"] == "degraded"
        assert health["lifecycle"]["memory"]["shedding"] is True
        # priority >= the floor is what "shed the LOWEST-priority work" means
        mean, _ = server.submit("m", x[:3], priority=1).result(timeout=5.0)
        assert np.isfinite(mean).all()
        usage["bytes"] = 0.4e9
        mean, _ = server.submit("m", x[:3]).result(timeout=5.0)  # recovered
        assert np.isfinite(mean).all()
    finally:
        server.stop()


# -- the CLI drain proof (real process boundary) ---------------------------


def test_cli_sigterm_drains_and_exits_zero(two_models):
    pa, _, x = two_models
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_gp_tpu.serve",
         "--model", f"m={pa}", "--drain-deadline-s", "20"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env, start_new_session=True,
    )
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["event"] == "ready"
        rows = x[:3].tolist()
        for i in (1, 2):
            proc.stdin.write(json.dumps({"id": i, "model": "m", "x": rows}) + "\n")
        proc.stdin.flush()
        answers = [json.loads(proc.stdout.readline()) for _ in (1, 2)]
        # in-flight work answered; stdin stays OPEN — the exit below is
        # the signal path, not EOF
        assert all("mean" in a for a in answers), answers
        # a canary reload whose load+warmup is (likely) still compiling on
        # its side thread when the signal lands: the drain exit must not
        # abort in native code under interpreter finalization (regression
        # — "terminate called without an active exception")
        proc.stdin.write(json.dumps(
            {"cmd": "reload", "model": "m", "canary_fraction": 0.5}
        ) + "\n")
        proc.stdin.flush()
        time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    except Exception:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.communicate()
        raise
    assert proc.returncode == 0, err[-800:]
    events = [json.loads(ln) for ln in out.strip().splitlines() if ln.strip()]
    shutdown = next(e for e in events if e.get("event") == "shutdown")
    assert shutdown["drained"] is True
    assert shutdown["requests"] >= 2
