"""Tests for the hardened DCN coordination layer (parallel/coord.py).

Everything here is tier-1: logical "hosts" are threads sharing an
:class:`InProcessCoordStore`, deadlines run on a fake clock where real
waiting would cost seconds, and the acceptance proofs — no-hang under a
dead host, kill-one-host-mid-fit then ELASTIC resume on a different
process count reproducing the uninterrupted theta — run entirely
in-process.  The full-fidelity subprocess variants live in
``tests/test_multiprocess.py``.
"""

import json
import threading
import time

import numpy as np
import pytest

from spark_gp_tpu.parallel import coord
from spark_gp_tpu.parallel.coord import (
    CoordinationTimeoutError,
    DcnContext,
    HeartbeatMonitor,
    InProcessCoordClient,
    InProcessCoordStore,
)


class FakeClock:
    """Deterministic clock whose ``sleep`` advances time — a 120 s deadline
    resolves in microseconds of wall-clock."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += max(float(dt), 1e-4)


def _client(store, pid, nproc, clock=None):
    return InProcessCoordClient(
        store, pid, nproc,
        clock=clock if clock is not None else time.monotonic,
        sleep=clock.sleep if clock is not None else None,
    )


# -- barriers / allgather ---------------------------------------------------


def test_barrier_timeout_names_missing_processes_fake_clock():
    clock = FakeClock()
    store = InProcessCoordStore()
    c0 = _client(store, 0, 3, clock)
    # processes 1 and 2 never arrive; the deadline must resolve with BOTH
    # named, without any real waiting
    t0 = time.monotonic()
    with pytest.raises(CoordinationTimeoutError) as err:
        c0.barrier("b", timeout_s=120.0)
    assert time.monotonic() - t0 < 5.0  # fake clock: no real 120 s wait
    assert err.value.missing == (1, 2)
    assert "missing process id(s) [1, 2]" in str(err.value)
    assert err.value.timeout_s == 120.0


def test_barrier_completes_across_threads():
    store = InProcessCoordStore()
    errors = []

    def arrive(pid, delay):
        time.sleep(delay)
        try:
            _client(store, pid, 2).barrier("sync", timeout_s=10.0)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [
        threading.Thread(target=arrive, args=(pid, 0.05 * pid))
        for pid in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []


def test_kv_allgather_orders_by_pid():
    store = InProcessCoordStore()
    out = {}

    def gather(pid):
        client = _client(store, pid, 3)
        out[pid] = coord.kv_allgather(
            "g/0", f"payload-{pid}".encode(), client=client, timeout_s=10.0
        )

    threads = [threading.Thread(target=gather, args=(pid,)) for pid in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    expected = [b"payload-0", b"payload-1", b"payload-2"]
    assert out[0] == out[1] == out[2] == expected


def test_kv_allgather_timeout_names_dead_process():
    clock = FakeClock()
    store = InProcessCoordStore()
    c0 = _client(store, 0, 2, clock)
    with pytest.raises(CoordinationTimeoutError) as err:
        coord.kv_allgather("g/1", b"x", client=c0, timeout_s=60.0)
    assert err.value.missing == (1,)


def test_allreduce_is_deterministic_and_identical_across_hosts():
    store = InProcessCoordStore()
    results = {}

    def reduce(pid):
        ctx = DcnContext(_client(store, pid, 2), timeout_s=10.0)
        value, grad = ctx.allreduce_arrays(
            "vag",
            np.asarray([1.25 if pid == 0 else 2.5]),
            np.asarray([0.1, 0.2]) * (pid + 1),
        )
        results[pid] = (value, grad)

    threads = [threading.Thread(target=reduce, args=(pid,)) for pid in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    np.testing.assert_array_equal(results[0][0], results[1][0])
    np.testing.assert_array_equal(results[0][1], results[1][1])
    np.testing.assert_allclose(results[0][0], [3.75])
    np.testing.assert_allclose(results[0][1], [0.3, 0.6])


# -- heartbeat / liveness ---------------------------------------------------


def _counter(key):
    from spark_gp_tpu.obs.runtime import telemetry

    return telemetry.counters.get(key, 0.0)


def test_heartbeat_monitor_flags_straggler_then_dead_then_recovery():
    clock = FakeClock()
    store = InProcessCoordStore()
    m0 = HeartbeatMonitor(
        _client(store, 0, 2, clock),
        interval_s=1.0, straggler_after_s=3.0, dead_after_s=10.0,
    )
    m1 = HeartbeatMonitor(
        _client(store, 1, 2, clock),
        interval_s=1.0, straggler_after_s=3.0, dead_after_s=10.0,
    )
    stragglers_before = _counter("coord.stragglers")
    dead_before = _counter("coord.dead_hosts")

    m0.poll_once()
    m1.poll_once()
    m0.poll_once()  # observes pid 1's stamp
    assert m0.stragglers() == [] and m0.dead_pids() == []

    clock.t += 5.0  # pid 1 goes quiet past the straggler threshold
    m0.poll_once()
    assert m0.stragglers() == [1]
    assert _counter("coord.stragglers") == stragglers_before + 1

    clock.t += 6.0  # now past the dead threshold
    m0.poll_once()
    assert m0.dead_pids() == [1]
    assert m0.stragglers() == []
    assert _counter("coord.dead_hosts") == dead_before + 1

    m1.poll_once()  # pid 1 comes back
    m0.poll_once()
    assert m0.dead_pids() == [] and m0.stragglers() == []

    snap = m0.snapshot()
    assert snap["process_count"] == 2
    assert snap["dead"] == [] and snap["stragglers"] == []


def test_allgather_aborts_early_on_dead_verdict():
    """A gather must not sleep out its full deadline once the heartbeat
    monitor has already declared the awaited peer dead."""
    store = InProcessCoordStore()
    c0 = _client(store, 0, 2)  # real clock: proves the EARLY abort
    t0 = time.monotonic()
    with pytest.raises(CoordinationTimeoutError) as err:
        coord.kv_allgather(
            "g/2", b"x", client=c0, timeout_s=30.0,
            dead_pids=lambda: [1],
        )
    assert time.monotonic() - t0 < 5.0
    assert err.value.missing == (1,)


# -- chaos hooks ------------------------------------------------------------


def test_straggler_host_delays_guarded_collective():
    from spark_gp_tpu.resilience import chaos

    with chaos.StragglerHost(0.05):
        assert chaos.apply_straggler_delay("any_op") == 0.05
    assert chaos.apply_straggler_delay("any_op") == 0.0
    with chaos.StragglerHost(0.05, op="vag"):
        assert chaos.apply_straggler_delay("ckpt") == 0.0
        assert chaos.apply_straggler_delay("vag/3") == 0.05


def test_dead_host_raises_before_collective():
    from spark_gp_tpu.resilience import chaos

    with chaos.DeadHost(exit_process=False):
        assert chaos.heartbeats_suppressed()
        with pytest.raises(chaos.SimulatedPreemption):
            coord.guard_collective("stitch")
    assert not chaos.heartbeats_suppressed()


def test_kill_process_after_validates():
    from spark_gp_tpu.resilience import chaos

    with pytest.raises(ValueError):
        chaos.kill_process_after(0)


# -- elastic-resume metadata ------------------------------------------------


def test_mesh_shape_and_elastic_meta():
    import jax

    from spark_gp_tpu.parallel.mesh import expert_mesh, mesh_shape

    assert mesh_shape(None) is None
    mesh = expert_mesh()
    assert mesh_shape(mesh) == [["experts", len(jax.devices())]]
    meta = coord.elastic_meta(
        mesh, num_experts=8, expert_size=16, process_count=4
    )
    assert meta["process_count"] == 4
    assert meta["expert_assignment"] == {"num_experts": 8, "expert_size": 16}
    json.dumps(meta)  # must be JSON-serializable (checkpoint payloads)


def test_elastic_device_checkpoint_resumes_across_process_counts(tmp_path):
    """Identity match + different process count = elastic resume (loads,
    counted); identity mismatch on a multi-host payload = hard error."""
    from spark_gp_tpu.utils.checkpoint import (
        DeviceOptimizerCheckpointer,
        ElasticResumeError,
    )

    state = {"a": np.arange(6.0), "b": np.ones((2, 2))}
    meta = {"kind": "t", "theta_dim": 3}
    writer = DeviceOptimizerCheckpointer(
        str(tmp_path), "el",
        elastic=coord.elastic_meta(None, num_experts=8, expert_size=16,
                                   process_count=2),
    )
    writer.save(state, meta)

    resumes_before = _counter("coord.elastic_resumes")
    reader = DeviceOptimizerCheckpointer(
        str(tmp_path), "el",
        elastic=coord.elastic_meta(None, num_experts=8, expert_size=16,
                                   process_count=1),
    )
    loaded = reader.load(state, meta)
    assert loaded is not None
    np.testing.assert_array_equal(loaded["a"], state["a"])
    assert _counter("coord.elastic_resumes") == resumes_before + 1

    # identity mismatch against a 2-process coordinated payload: hard error,
    # never the legacy silent warn-and-ignore
    with pytest.raises(ElasticResumeError, match="2-process coordinated"):
        reader.load(state, {"kind": "t", "theta_dim": 4})


# -- coordinated checkpointing ---------------------------------------------


def _run_hosts(fns):
    """Run one callable per logical host on its own thread; return
    {pid: exception_or_None}."""
    outcomes = {}

    def runner(pid, fn):
        try:
            fn()
            outcomes[pid] = None
        except BaseException as exc:  # noqa: BLE001 — collected for asserts
            outcomes[pid] = exc

    threads = [
        threading.Thread(target=runner, args=(pid, fn))
        for pid, fn in enumerate(fns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return outcomes


def test_coordinated_host_checkpointer_writer_election_and_digest(tmp_path):
    from spark_gp_tpu.kernels.rbf import RBFKernel
    from spark_gp_tpu.utils.checkpoint import (
        LbfgsCheckpointer,
        load_checkpoint_payload,
    )

    kernel = RBFKernel(1.0)
    store = InProcessCoordStore()
    theta = np.asarray([0.5])

    def host(pid):
        def run():
            ctx = DcnContext(_client(store, pid, 2), timeout_s=10.0)
            inner = LbfgsCheckpointer(
                str(tmp_path), kernel, tag="coordtest", seed=0,
                elastic=coord.elastic_meta(None, process_count=2),
            )
            ck = coord.CoordinatedLbfgsCheckpointer(inner, ctx)
            ck(theta)  # identical state on both hosts
        return run

    outcomes = _run_hosts([host(0), host(1)])
    assert outcomes == {0: None, 1: None}
    payload = load_checkpoint_payload(str(tmp_path), tag="coordtest")
    assert payload["iteration"] == 1
    assert payload["elastic"]["process_count"] == 2
    np.testing.assert_allclose(payload["theta"], [0.5])


def test_coordinated_device_checkpointer_load_broadcasts_from_writer(tmp_path):
    """Only process 0 holds the npz (it is the elected writer; after
    rescheduling the peers sit on fresh disks) — load must ship process
    0's validated state to every peer, or peers fresh-init at n_iter=0
    and the segment barriers desynchronize immediately."""
    from spark_gp_tpu.utils.checkpoint import DeviceOptimizerCheckpointer

    state = {"a": np.arange(5.0), "b": np.full((2, 3), 7.0)}
    meta = {"kind": "t"}
    # process 0's disk has the checkpoint; process 1's directory is empty
    DeviceOptimizerCheckpointer(str(tmp_path / "p0"), "bc").save(state, meta)

    store = InProcessCoordStore()
    loaded = {}

    def host(pid):
        def run():
            ctx = DcnContext(_client(store, pid, 2), timeout_s=10.0)
            ck = coord.CoordinatedDeviceCheckpointer(
                DeviceOptimizerCheckpointer(str(tmp_path / f"p{pid}"), "bc"),
                ctx,
            )
            loaded[pid] = ck.load(state, meta)
        return run

    outcomes = _run_hosts([host(0), host(1)])
    assert outcomes == {0: None, 1: None}
    for pid in range(2):
        assert loaded[pid] is not None, f"pid {pid} fresh-inits"
        np.testing.assert_array_equal(loaded[pid]["a"], state["a"])
        np.testing.assert_array_equal(loaded[pid]["b"], state["b"])


def test_heartbeat_flags_peer_that_never_stamped():
    """A peer that dies before its FIRST stamp must still escalate — the
    liveness registry seeds every expected pid at the first poll."""
    clock = FakeClock()
    store = InProcessCoordStore()
    m0 = HeartbeatMonitor(
        _client(store, 0, 2, clock),
        interval_s=1.0, straggler_after_s=3.0, dead_after_s=10.0,
    )
    m0.poll_once()  # pid 1 has never stamped
    clock.t += 50.0
    m0.poll_once()
    assert m0.dead_pids() == [1]


def test_allgather_round_keys_are_garbage_collected():
    store = InProcessCoordStore()

    def host(pid):
        def run():
            ctx = DcnContext(_client(store, pid, 2), timeout_s=10.0)
            for _ in range(5):
                ctx.allgather_arrays("gc", np.ones(2))
        return run

    outcomes = _run_hosts([host(0), host(1)])
    assert outcomes == {0: None, 1: None}
    live = [k for k in store.kv if k.startswith("ag/gc/")]
    # rounds 0..2 GC'd (r-2 rule at rounds 2..4); only the last two
    # rounds' keys may remain
    assert len(live) <= 4, sorted(live)


def test_coordinated_checkpointer_catches_diverged_host(tmp_path):
    """Two hosts whose lockstep states differ must fail the digest
    cross-check — a silently forked training run is the one outcome the
    coordinated protocol exists to rule out."""
    from spark_gp_tpu.kernels.rbf import RBFKernel
    from spark_gp_tpu.utils.checkpoint import (
        CheckpointMismatchError,
        LbfgsCheckpointer,
    )

    kernel = RBFKernel(1.0)
    store = InProcessCoordStore()

    def host(pid):
        def run():
            ctx = DcnContext(_client(store, pid, 2), timeout_s=10.0)
            inner = LbfgsCheckpointer(
                str(tmp_path), kernel, tag="div", seed=0,
            )
            ck = coord.CoordinatedLbfgsCheckpointer(inner, ctx)
            ck(np.asarray([0.5 if pid == 0 else 0.75]))  # DIVERGED
        return run

    outcomes = _run_hosts([host(0), host(1)])
    # the all-to-all digest exchange makes the fork visible EVERYWHERE —
    # the writer included, each naming the peer(s) that differ from it
    assert isinstance(outcomes[0], CheckpointMismatchError)
    assert isinstance(outcomes[1], CheckpointMismatchError)
    assert "forked" in str(outcomes[1])
    assert "[0]" in str(outcomes[1]) and "[1]" in str(outcomes[0])


# -- the DCN-fallback fit: lockstep, no-hang, elastic resume ---------------


def _half_rows(pid):
    # sizes chosen so both halves group to expert_size 16 exactly (the
    # union stack for the elastic-resume run concatenates the two local
    # stacks, which needs matching expert widths)
    rng = np.random.default_rng(100 + pid)
    n = 144 if pid == 0 else 112
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.01 * rng.normal(size=n)
    return x, y


def _host_mesh(pid):
    # each in-process "host" owns a DISJOINT half of the virtual devices,
    # exactly like real processes own their local chips.  Sharing one
    # mesh between the two fit threads would run two collective programs
    # concurrently over the same devices — XLA rendezvous can interleave
    # their schedules and deadlock (observed on single-core CI hosts).
    import jax

    from spark_gp_tpu.parallel.mesh import expert_mesh

    devs = jax.devices()
    half = max(1, len(devs) // 2)
    return expert_mesh(devs[pid * half:(pid + 1) * half])


def _local_stack(pid):
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import shard_experts

    x, y = _half_rows(pid)
    mesh = _host_mesh(pid)
    return shard_experts(group_for_experts(x, y, 16), mesh), mesh


def _gp(maxiter=50, ckpt_dir=None):
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(48)
        .setMaxIter(maxiter)
        .setTol(1e-10)
        .setSeed(3)
    )
    if ckpt_dir is not None:
        gp.setCheckpointDir(str(ckpt_dir))
    return gp


def _dcn_fit(pid, ctx, results, ckpt_dir=None, maxiter=50):
    coord.set_dcn_context_for_testing(ctx)
    try:
        data, mesh = _local_stack(pid)
        model = _gp(maxiter, ckpt_dir).setMesh(mesh).fit_distributed(data)
        results[pid] = model
    except BaseException as exc:  # noqa: BLE001 — collected for asserts
        results[pid] = exc
    finally:
        coord.set_dcn_context_for_testing(None)


def _run_dcn_pair(ctxs, ckpt_dir=None, maxiter=50):
    results = {}
    threads = [
        threading.Thread(
            target=_dcn_fit, args=(pid, ctxs[pid], results, ckpt_dir, maxiter)
        )
        for pid in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def _pair_ctxs(store, timeout_s=30.0, ctx_cls=DcnContext, **kw):
    return [
        ctx_cls(_client(store, pid, 2), timeout_s=timeout_s, **kw)
        for pid in range(2)
    ]


def test_dcn_fit_two_logical_hosts_lockstep():
    """Two logical hosts, disjoint unequal row shards, KV-store reductions:
    both converge to the IDENTICAL model (bit-equal theta and predictions
    — the deterministic pid-ordered sum at work) and the joint fit learns
    the shared function."""
    results = _run_dcn_pair(_pair_ctxs(InProcessCoordStore()))
    for pid in range(2):
        assert not isinstance(results[pid], BaseException), results[pid]
    m0, m1 = results[0], results[1]
    np.testing.assert_array_equal(
        m0.raw_predictor.theta, m1.raw_predictor.theta
    )
    probe = np.random.default_rng(999).normal(size=(32, 2))
    np.testing.assert_array_equal(m0.predict(probe), m1.predict(probe))
    x0, y0 = _half_rows(0)
    rmse = float(np.sqrt(np.mean((m0.predict(x0) - y0) ** 2)))
    assert rmse < 0.15, rmse


class _DyingCtx(DcnContext):
    """A host that dies (stops participating) after N objective rounds —
    the in-process DeadHost: it never publishes round N+1, so its peer
    must hit the deadline guard, not hang."""

    def __init__(self, client, timeout_s=None, die_after_vag_rounds=10**9):
        super().__init__(client, timeout_s=timeout_s)
        self.die_after = die_after_vag_rounds
        self._vag_rounds = 0

    def allgather_bytes(self, name, payload):
        if name == "vag":
            self._vag_rounds += 1
            if self._vag_rounds > self.die_after:
                from spark_gp_tpu.resilience.chaos import SimulatedPreemption

                raise SimulatedPreemption(
                    f"chaos: host died before vag round {self._vag_rounds}"
                )
        return super().allgather_bytes(name, payload)


def test_dcn_fit_dead_host_raises_named_timeout_within_deadline(tmp_path):
    """The no-hang guarantee: host 1 dies mid-fit; host 0 must raise
    CoordinationTimeoutError NAMING process 1 within the configured
    deadline — never block past it."""
    from spark_gp_tpu.resilience.chaos import SimulatedPreemption

    store = InProcessCoordStore()
    ctxs = [
        DcnContext(_client(store, 0, 2), timeout_s=3.0),
        _DyingCtx(_client(store, 1, 2), timeout_s=3.0,
                  die_after_vag_rounds=6),
    ]
    t0 = time.monotonic()
    results = _run_dcn_pair(ctxs, ckpt_dir=tmp_path, maxiter=50)
    elapsed = time.monotonic() - t0
    assert isinstance(results[1], SimulatedPreemption)
    assert isinstance(results[0], CoordinationTimeoutError), results[0]
    assert results[0].missing == (1,)
    assert "1" in str(results[0])
    # deadline 3 s + some slack for the fit work itself — nowhere near a hang
    assert elapsed < 30.0, elapsed
    # the coordinated checkpoints survived host 0's abort: iteration state
    # is on disk for the elastic resume (next test runs the full proof)
    from spark_gp_tpu.utils.checkpoint import load_checkpoint_payload

    payload = load_checkpoint_payload(
        str(tmp_path), tag="GaussianProcessRegression"
    )
    assert payload is not None and payload["iteration"] >= 1
    assert payload["elastic"]["process_count"] == 2


def _union_stack():
    import jax.numpy as jnp

    from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts
    from spark_gp_tpu.parallel.mesh import expert_mesh, shard_experts

    mesh = expert_mesh()
    stacks = []
    for pid in range(2):
        # reproduce each host's LOCAL padded layout (its _host_mesh) so
        # the union is the same global expert assignment the 2-process
        # checkpoints were written against
        x, y = _half_rows(pid)
        stacks.append(
            shard_experts(group_for_experts(x, y, 16), _host_mesh(pid))
        )
    # host-side concat: the two stacks live on disjoint device halves
    union = ExpertData(
        x=jnp.asarray(np.concatenate([np.asarray(s.x) for s in stacks])),
        y=jnp.asarray(np.concatenate([np.asarray(s.y) for s in stacks])),
        mask=jnp.asarray(
            np.concatenate([np.asarray(s.mask) for s in stacks])
        ),
    )
    return shard_experts(union, mesh), mesh


def test_kill_one_host_then_elastic_resume_on_different_process_count(tmp_path):
    """THE elastic-resume acceptance proof, in-process: a 2-host DCN fit is
    killed mid-run (host 1 dies; host 0 stops at the named timeout with
    coordinated checkpoints on disk), then a 1-process fit over the SAME
    global expert assignment resumes from the 2-process checkpoint —
    different process count, elastic-counted — and lands on the
    uninterrupted fit's theta to atol 1e-6."""
    # uninterrupted reference: the same 2-host DCN fit, run to convergence
    ref = _run_dcn_pair(_pair_ctxs(InProcessCoordStore()))
    assert not isinstance(ref[0], BaseException), ref[0]
    theta_ref = ref[0].raw_predictor.theta

    # killed run: host 1 dies after 6 objective rounds
    store = InProcessCoordStore()
    ctxs = [
        DcnContext(_client(store, 0, 2), timeout_s=3.0),
        _DyingCtx(_client(store, 1, 2), timeout_s=3.0,
                  die_after_vag_rounds=6),
    ]
    results = _run_dcn_pair(ctxs, ckpt_dir=tmp_path)
    assert isinstance(results[0], CoordinationTimeoutError)

    # elastic resume: ONE process, the union of both hosts' expert stacks
    # (same global expert assignment — only the sharding changed), same
    # checkpoint dir.  The 2-process stamp on the payload vs the 1-process
    # fit is the elastic transition under test.
    resumes_before = _counter("coord.elastic_resumes")
    union, mesh = _union_stack()
    resumed = _gp(ckpt_dir=tmp_path).setMesh(mesh).fit_distributed(union)
    assert _counter("coord.elastic_resumes") == resumes_before + 1
    assert resumed.instr.metrics.get("resumed_from_iteration", 0) >= 1
    np.testing.assert_allclose(
        resumed.raw_predictor.theta, theta_ref, atol=1e-6
    )


# -- liveness surfaces ------------------------------------------------------


def test_plain_fit_checkpoints_stay_local_on_clusters(tmp_path):
    """A plain per-host fit() on a multi-process cluster must keep PLAIN
    local checkpoint writers: two INDEPENDENT fits coordinating through
    shared KV gathers would spuriously digest-mismatch (and resume from
    each other's payloads).  Only fit_distributed coordinates."""
    store = InProcessCoordStore()
    ctx = DcnContext(_client(store, 0, 2), timeout_s=0.5)
    coord.set_dcn_context_for_testing(ctx)
    try:
        x, y = _half_rows(0)
        _gp(maxiter=3, ckpt_dir=tmp_path).fit(x, y)
    finally:
        coord.set_dcn_context_for_testing(None)
    assert (tmp_path / "lbfgs_state_GaussianProcessRegression.json").exists()
    # no coordination traffic: the fit never touched the KV store
    assert not [k for k in store.kv if k.startswith("ag/")], store.kv.keys()


def _ledger(events):
    from spark_gp_tpu.parallel.coord import LivenessLedger

    return LivenessLedger(
        straggler_after_s=3.0, dead_after_s=10.0,
        on_straggler=lambda i, age: events.append(("straggler", i)),
        on_dead=lambda i, age: events.append(("dead", i)),
        on_recover=lambda i: events.append(("recover", i)),
    )


def test_liveness_ledger_recovered_peer_reescalates():
    """recovery clears the flag COMPLETELY: a peer that recovers and then
    goes quiet again must re-walk the straggler → dead escalation (and
    fire the callbacks again) — a one-shot flag would make the second
    outage invisible."""
    events = []
    ledger = _ledger(events)
    ledger.observe(0.0, {"r1": 1})
    ledger.observe(4.0, {"r1": 1})  # stamp unchanged past the bar
    assert ledger.stragglers() == ["r1"]
    ledger.observe(5.0, {"r1": 2})  # fresh stamp: recovered
    assert ledger.stragglers() == [] and ledger.dead() == []
    ledger.observe(9.1, {"r1": 2})  # quiet again, 4.1 s past the stamp
    assert ledger.stragglers() == ["r1"]
    ledger.observe(16.0, {"r1": 2})
    assert ledger.dead() == ["r1"]
    assert events == [
        ("straggler", "r1"), ("recover", "r1"),
        ("straggler", "r1"), ("dead", "r1"),
    ]


def test_liveness_ledger_dead_before_first_stamp():
    """An EXPECTED peer that never stamps must still escalate: seeding at
    first sight is what keeps a process that died before its first
    heartbeat from reading as healthy forever."""
    events = []
    ledger = _ledger(events)
    ledger.observe(0.0, {"r0": 1}, expected=("r0", "r1"))
    assert ledger.dead() == []
    ledger.observe(11.0, {"r0": 2}, expected=("r0", "r1"))
    assert ledger.dead() == ["r1"]
    assert ledger.stragglers() == []  # r0 kept stamping
    assert ("dead", "r1") in events
    # re-seeding an already-tracked identity must not reset its age
    assert ledger.last_seen()["r1"] == (-1, 0.0)


def test_liveness_ledger_stamp_counter_rollover_counts_as_seen():
    """A restarted peer's stamp counter starts over BELOW its old value;
    'seen' is any counter CHANGE, not an increase — otherwise a restart
    reads as silence until the new counter passes the old one."""
    events = []
    ledger = _ledger(events)
    ledger.observe(0.0, {"r1": 997})
    ledger.observe(4.0, {"r1": 997})
    assert ledger.stragglers() == ["r1"]
    ledger.observe(5.0, {"r1": 1})  # restarted: counter rolled over
    assert ledger.stragglers() == [] and ledger.dead() == []
    assert ("recover", "r1") in events
    assert ledger.last_seen()["r1"] == (1, 5.0)
    # forget drops the identity entirely: a politely-deregistered member
    # must not re-enter the scan as dead
    ledger.observe(20.0, {})
    assert ledger.dead() == ["r1"]
    ledger.forget("r1")
    assert ledger.dead() == [] and "r1" not in ledger.last_seen()


def test_liveness_snapshot_none_single_process():
    assert coord.liveness_snapshot() is None


def test_serve_health_reports_coord_liveness_when_distributed():
    clock = FakeClock()
    store = InProcessCoordStore()
    monitor = HeartbeatMonitor(
        _client(store, 0, 2, clock),
        interval_s=1.0, straggler_after_s=3.0, dead_after_s=10.0,
    )
    ctx = DcnContext(_client(store, 0, 2), monitor=monitor)
    coord.set_dcn_context_for_testing(ctx)
    try:
        # stamp both, then let pid 1 die
        monitor.poll_once()
        _client(store, 1, 2, clock).set(
            "heartbeat/1", json.dumps({"n": 1, "t": clock.t}).encode()
        )
        monitor.poll_once()
        clock.t += 50.0
        monitor.poll_once()
        from spark_gp_tpu.serve.server import GPServeServer

        health = GPServeServer().health()
        assert health["coord"]["dead"] == [1]
        assert health["status"] in ("degraded", "unready")
        snap = coord.liveness_snapshot()
        assert snap["dead"] == [1]
    finally:
        coord.set_dcn_context_for_testing(None)


# -- preemption watcher -----------------------------------------------------


def test_staged_preemption_stops_fit_at_save_boundary(tmp_path):
    """PR 2's PreemptingCheckpointer semantics through the watcher flag: a
    staged preemption makes the fit stop right after the next checkpoint
    save with PreemptedError; the state on disk resumes the fit."""
    from spark_gp_tpu.resilience import chaos

    x, y = _half_rows(0)
    try:
        chaos.stage_preemption(True)
        with pytest.raises(coord.PreemptedError):
            _gp(maxiter=30, ckpt_dir=tmp_path).fit(x, y)
    finally:
        chaos.stage_preemption(False)
    from spark_gp_tpu.utils.checkpoint import load_checkpoint_payload

    payload = load_checkpoint_payload(
        str(tmp_path), tag="GaussianProcessRegression"
    )
    assert payload is not None and payload["iteration"] == 1
    # cleared: the resumed fit completes and matches the clean fit
    resumed = _gp(maxiter=30, ckpt_dir=tmp_path).fit(x, y)
    clean = _gp(maxiter=30).fit(x, y)
    np.testing.assert_allclose(
        resumed.raw_predictor.theta, clean.raw_predictor.theta, atol=1e-6
    )


def test_preemption_watcher_install_is_idempotent():
    import signal

    prev = signal.getsignal(signal.SIGTERM)
    try:
        assert coord.install_preemption_watcher()
        assert coord.install_preemption_watcher()
        assert not coord.preemption_requested()
    finally:
        # the permanent watcher is an opt-in for real training drivers;
        # the test process must get its disposition back
        signal.signal(signal.SIGTERM, prev)
        coord._WATCHER_INSTALLED = False
        coord.clear_preemption_for_testing()


def test_preemption_watch_scoped_install_restore_and_consume():
    """The production wiring: the handler exists only inside the scope,
    the previous disposition comes back on exit, and a CONSUMED
    preemption (save boundary raised PreemptedError) is not re-delivered
    — the process survives scope exit."""
    import signal

    prev = signal.getsignal(signal.SIGTERM)
    with coord.preemption_watch():
        inside = signal.getsignal(signal.SIGTERM)
        assert inside is not prev  # scoped handler active
        inside(signal.SIGTERM, None)  # simulate delivery: flag only
        assert coord.preemption_requested()
        coord.note_preemption_observed()
        coord.consume_preemption()  # what _raise_if_preempted does
        assert not coord.preemption_requested()
    # restored, flag clear, and (since consumed) nothing was re-delivered
    assert signal.getsignal(signal.SIGTERM) is prev
    coord.clear_preemption_for_testing()


# -- lints ------------------------------------------------------------------


def test_collective_guards_lint_is_clean():
    import os
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo_root, "tools"))
    try:
        import check_collective_guards

        assert check_collective_guards.main(
            [os.path.join(repo_root, "spark_gp_tpu")]
        ) == 0
    finally:
        sys.path.pop(0)


def test_collective_guards_lint_catches_raw_calls(tmp_path):
    import os
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo_root, "tools"))
    try:
        import check_collective_guards

        bad = tmp_path / "pkg" / "x.py"
        bad.parent.mkdir()
        bad.write_text(
            "from jax.experimental import multihost_utils\n"
            "import jax\n"
            "def f(a):\n"
            "    jax.distributed.initialize()\n"
            "    return multihost_utils.process_allgather(a)\n"
            "def g(a):\n"
            "    return multihost_utils.broadcast_one_to_all(a)"
            "  # collective-guard-ok\n"
        )
        violations = check_collective_guards.find_violations(
            str(tmp_path / "pkg")
        )
        flagged = {what for _, _, what in violations}
        assert "from jax.experimental import ..." in flagged
        assert "jax.distributed.initialize" in flagged
        assert "multihost_utils.process_allgather" in flagged
        # the exempted line stays out
        assert not any("broadcast_one_to_all" in w for w in flagged)
    finally:
        sys.path.pop(0)
