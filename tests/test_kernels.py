"""Kernel algebra tests.

Ports the reference's test strategy (RBFKernelTest.scala,
ARDRBFKernelTest.scala — SURVEY.md §4): golden 3x3 matrices on the same
3-point 2-d fixture, finite-difference derivative oracles (now through
``jax.test_util.check_grads`` + explicit FD), cross-kernel values, plus new
coverage the reference lacks: composition DSL bounds/slicing, white-noise
accounting, Eye behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels import (
    ARDRBFKernel,
    Const,
    EyeKernel,
    RBFKernel,
    Scalar,
    WhiteNoiseKernel,
)

# The reference's fixture: RBFKernelTest.scala:27
DATASET = np.array([[1.0, 2.0], [2.0, 3.0], [5.0, 7.0]])


def test_rbf_golden_matrix():
    """Golden values from RBFKernelTest.scala:33-38 (sigma = sqrt(0.2))."""
    k = RBFKernel(np.sqrt(0.2))
    gram = np.asarray(k.gram(jnp.asarray(k.init_theta()), jnp.asarray(DATASET)))
    expected = np.array(
        [
            [1.000000e00, 6.737947e-03, 3.053624e-45],
            [6.737947e-03, 1.000000e00, 7.187782e-28],
            [3.053624e-45, 7.187782e-28, 1.000000e00],
        ]
    )
    np.testing.assert_allclose(gram, expected, atol=1e-4)


def test_rbf_cross_golden():
    """RBFKernelTest.scala:62-76: cross kernel of first point vs rest."""
    k = RBFKernel(np.sqrt(0.2))
    theta = jnp.asarray(k.init_theta())
    cross = np.asarray(
        k.cross(theta, jnp.asarray(DATASET[:1]), jnp.asarray(DATASET[1:]))
    )
    np.testing.assert_allclose(
        cross, np.array([[6.737947e-03, 3.053624e-45]]), atol=1e-4
    )


def _fd_grad(fn, theta, h=1e-6):
    theta = np.asarray(theta, dtype=np.float64)
    grad = np.zeros_like(theta)
    for i in range(theta.size):
        tp, tm = theta.copy(), theta.copy()
        tp[i] += h
        tm[i] -= h
        grad[i] = (fn(tp) - fn(tm)) / (2 * h)
    return grad


@pytest.mark.parametrize(
    "kernel",
    [
        RBFKernel(0.2),
        ARDRBFKernel(np.array([0.2, 0.3])),
        1.0 * RBFKernel(0.5),
        1.0 * ARDRBFKernel(2, beta=0.7) + WhiteNoiseKernel(0.5, 0, 1),
        Scalar(2.0).between(0).and_(30) * RBFKernel(0.3) + Const(0.1) * EyeKernel(),
    ],
    ids=["rbf", "ard", "scaled-rbf", "composite", "dsl-composite"],
)
def test_gram_autodiff_matches_finite_difference(kernel):
    """The FD oracle of RBFKernelTest.scala:41-60 / ARDRBFKernelTest.scala:11-31,
    applied to autodiff gradients of a scalar functional of the Gram matrix."""
    x = jnp.asarray(DATASET)
    w = jnp.asarray(np.random.default_rng(0).normal(size=(3, 3)))

    def functional(theta):
        return float(jnp.sum(w * kernel.gram(jnp.asarray(theta), x)))

    theta0 = kernel.init_theta()
    auto = np.asarray(
        jax.grad(lambda t: jnp.sum(w * kernel.gram(t, x)))(jnp.asarray(theta0))
    )
    fd = _fd_grad(functional, theta0)
    np.testing.assert_allclose(auto, fd, rtol=1e-5, atol=1e-7)


def test_eye_kernel():
    k = EyeKernel()
    theta = jnp.zeros((0,))
    x = jnp.asarray(DATASET)
    np.testing.assert_allclose(np.asarray(k.gram(theta, x)), np.eye(3))
    np.testing.assert_allclose(
        np.asarray(k.cross(theta, x[:2], x)), np.zeros((2, 3))
    )
    assert float(k.white_noise_var(theta)) == 1.0
    np.testing.assert_allclose(np.asarray(k.self_diag(theta, x)), np.ones(3))


def test_white_noise_kernel_dsl():
    """WhiteNoiseKernel(init, lo, hi) = (init between lo and hi) * Eye
    (kernel/Kernel.scala:166-169)."""
    k = WhiteNoiseKernel(0.5, 0.0, 1.0)
    assert k.n_hypers == 1
    np.testing.assert_allclose(k.init_theta(), [0.5])
    lo, hi = k.bounds()
    np.testing.assert_allclose(lo, [0.0])
    np.testing.assert_allclose(hi, [1.0])
    theta = jnp.asarray([0.25])
    x = jnp.asarray(DATASET)
    np.testing.assert_allclose(np.asarray(k.gram(theta, x)), 0.25 * np.eye(3))
    assert float(k.white_noise_var(theta)) == 0.25


def test_composite_theta_layout():
    """Sum concatenates children; trainable scalar prepends its coefficient
    (SumOfKernels.scala:19-26, ScalarTimesKernel.scala:78-84)."""
    k = 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1)
    assert k.n_hypers == 3
    np.testing.assert_allclose(k.init_theta(), [1.0, 0.1, 0.5])
    lo, hi = k.bounds()
    np.testing.assert_allclose(lo, [0.0, 1e-6, 0.0])
    np.testing.assert_allclose(hi, [np.inf, 10.0, 1.0])


def test_const_scale_has_no_hypers():
    k = Const(0.5) * RBFKernel(0.2)
    assert k.n_hypers == 1  # only the RBF sigma
    x = jnp.asarray(DATASET)
    theta = jnp.asarray(k.init_theta())
    inner = RBFKernel(0.2)
    np.testing.assert_allclose(
        np.asarray(k.gram(theta, x)),
        0.5 * np.asarray(inner.gram(theta, x)),
    )


def test_negative_scalar_rejected():
    with pytest.raises(ValueError):
        Scalar(-1.0) * RBFKernel()


def test_white_noise_var_composes():
    """whiteNoiseVar sums across Sum and scales through Scalar
    (SumOfKernels.scala:62, ScalarTimesKernel.scala:28)."""
    k = RBFKernel(1.0) + Const(1e-3) * EyeKernel()
    theta = jnp.asarray(k.init_theta())
    assert float(k.white_noise_var(theta)) == pytest.approx(1e-3)
    k2 = RBFKernel(1.0) + WhiteNoiseKernel(0.5, 0, 1) + Const(1e-3) * EyeKernel()
    theta2 = jnp.asarray(k2.init_theta())
    assert float(k2.white_noise_var(theta2)) == pytest.approx(0.5 + 1e-3)


def test_ard_matches_reference_convention():
    """ARD uses exp(-|(xi-xj)*beta|^2) — beta multiplies, no 1/2 factor
    (ARDRBFKernel.scala:43-46)."""
    beta = np.array([0.2, 0.3])
    k = ARDRBFKernel(beta)
    x = jnp.asarray(DATASET)
    gram = np.asarray(k.gram(jnp.asarray(beta), x))
    diff = DATASET[0] - DATASET[1]
    expected01 = np.exp(-np.sum((diff * beta) ** 2))
    np.testing.assert_allclose(gram[0, 1], expected01, rtol=1e-12)
    np.testing.assert_allclose(np.diag(gram), np.ones(3), rtol=1e-12)


# --- Matérn family (capability beyond the reference) ----------------------


def test_matern_values_match_closed_form(rng):
    """Golden values of the three Matérn correlations at hand-computed
    scaled distances."""
    import math

    from spark_gp_tpu.kernels.matern import (
        Matern12Kernel, Matern32Kernel, Matern52Kernel,
    )

    x = np.array([[0.0], [1.0]])
    sigma = 2.0
    r = 1.0
    k12 = np.asarray(Matern12Kernel(sigma).gram(np.array([sigma]), jnp.asarray(x)))
    assert np.isclose(k12[0, 1], math.exp(-r / sigma), atol=1e-9)
    a3 = math.sqrt(3) * r / sigma
    k32 = np.asarray(Matern32Kernel(sigma).gram(np.array([sigma]), jnp.asarray(x)))
    assert np.isclose(k32[0, 1], (1 + a3) * math.exp(-a3), atol=1e-9)
    a5 = math.sqrt(5) * r / sigma
    k52 = np.asarray(Matern52Kernel(sigma).gram(np.array([sigma]), jnp.asarray(x)))
    assert np.isclose(k52[0, 1], (1 + a5 + a5 * a5 / 3) * math.exp(-a5), atol=1e-9)
    # unit diagonal (up to the sqrt-guard's 1e-12)
    for k in (k12, k32, k52):
        np.testing.assert_allclose(np.diag(k), 1.0, atol=1e-9)


@pytest.mark.parametrize("cls_args", [
    ("Matern12Kernel", (1.3,)),
    ("Matern32Kernel", (0.7,)),
    ("Matern52Kernel", (2.1,)),
    ("ARDMatern32Kernel", (np.array([0.5, 1.5, 0.9]),)),
    ("ARDMatern52Kernel", (np.array([1.1, 0.3, 2.0]),)),
])
def test_matern_gradients_finite_difference(rng, cls_args):
    """Autodiff NLL-style gradient vs central finite differences — the
    RBFKernelTest.scala pattern applied to the new family; also exercises
    the coincident-point sqrt guard (the gram includes the diagonal)."""
    import jax

    from spark_gp_tpu.kernels import matern

    cls_name, args = cls_args
    kernel = getattr(matern, cls_name)(*args)
    x = jnp.asarray(rng.normal(size=(12, 3)))
    w = jnp.asarray(rng.normal(size=(12, 12)))

    def scalar_of_theta(theta):
        return jnp.sum(w * kernel.gram(theta, x))

    theta0 = jnp.asarray(kernel.init_theta())
    grad = np.asarray(jax.grad(scalar_of_theta)(theta0))
    assert np.all(np.isfinite(grad))
    h = 1e-6
    for i in range(theta0.shape[0]):
        e = np.zeros(theta0.shape[0])
        e[i] = h
        fd = (scalar_of_theta(theta0 + e) - scalar_of_theta(theta0 - e)) / (2 * h)
        np.testing.assert_allclose(grad[i], float(fd), rtol=2e-4, atol=1e-7)


def test_matern_psd_and_dsl_composition(rng):
    from spark_gp_tpu import Const, EyeKernel, Matern52Kernel

    k = 1.0 * Matern52Kernel(1.0) + Const(1e-3) * EyeKernel()
    x = jnp.asarray(rng.normal(size=(40, 2)))
    gram = np.asarray(k.gram(jnp.asarray(k.init_theta()), x))
    eig = np.linalg.eigvalsh(0.5 * (gram + gram.T))
    assert eig.min() > 0


def test_matern_end_to_end_fit(rng):
    """A rough (OU-like) 1-d signal: Matérn 3/2 fits it through the full
    estimator pipeline."""
    from spark_gp_tpu import GaussianProcessRegression, Matern32Kernel

    n = 400
    x = np.linspace(0, 4, n)[:, None]
    y = np.sin(3 * x[:, 0]) + 0.05 * rng.normal(size=n)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * Matern32Kernel(0.5, 1e-3, 10.0))
        .setActiveSetSize(80)
        .setMaxIter(25)
        .fit(x, y)
    )
    from spark_gp_tpu.utils.validation import rmse

    assert rmse(y, model.predict(x)) < 0.1


# --- Rational quadratic / periodic / dot-product / polynomial families -----


def test_rational_quadratic_matches_closed_form(rng):
    from spark_gp_tpu import RationalQuadraticKernel

    sigma, alpha = 0.8, 1.7
    k = RationalQuadraticKernel(sigma, alpha)
    x = rng.normal(size=(6, 3))
    gram = np.asarray(k.gram(jnp.asarray(k.init_theta()), jnp.asarray(x)))
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    expected = (1.0 + d2 / (2 * alpha * sigma**2)) ** (-alpha)
    np.testing.assert_allclose(gram, expected, rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(np.diag(gram), 1.0, rtol=1e-12)


def test_rational_quadratic_limits_to_rbf(rng):
    """alpha -> inf recovers the RBF correlation (scale-mixture identity)."""
    from spark_gp_tpu import RationalQuadraticKernel

    sigma = 0.9
    k = RationalQuadraticKernel(sigma, 1e6)
    x = rng.normal(size=(5, 2))
    gram = np.asarray(k.gram(jnp.asarray(k.init_theta()), jnp.asarray(x)))
    d2 = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(gram, np.exp(-d2 / (2 * sigma**2)), rtol=1e-4)


def test_periodic_matches_closed_form(rng):
    """Per-dimension ExpSineSquared: the PSD multi-d form (sum of
    sin^2 over dimensions), cross-checked against the direct formula."""
    from spark_gp_tpu import PeriodicKernel

    period, ell = 1.3, 0.6
    k = PeriodicKernel(period, ell)
    x = rng.normal(size=(6, 2))
    gram = np.asarray(k.gram(jnp.asarray(k.init_theta()), jnp.asarray(x)))
    diffs = x[:, None, :] - x[None, :, :]
    s2 = (np.sin(np.pi * diffs / period) ** 2).sum(-1)
    expected = np.exp(-2.0 * s2 / ell**2)
    np.testing.assert_allclose(gram, expected, rtol=1e-6, atol=1e-9)
    # exact periodicity: shifting any dimension by a whole period is invisible
    shifted = x + np.array([period, 2 * period])
    cross = np.asarray(
        k.cross(jnp.asarray(k.init_theta()), jnp.asarray(shifted), jnp.asarray(x))
    )
    np.testing.assert_allclose(np.diag(cross), 1.0, atol=1e-9)


def test_dot_product_and_polynomial_match_closed_form(rng):
    from spark_gp_tpu import DotProductKernel, PolynomialKernel

    x = rng.normal(size=(5, 3))
    t = rng.normal(size=(2, 3))
    s0 = 0.7
    k = DotProductKernel(s0)
    theta = jnp.asarray(k.init_theta())
    np.testing.assert_allclose(
        np.asarray(k.gram(theta, jnp.asarray(x))), s0**2 + x @ x.T, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(k.cross(theta, jnp.asarray(t), jnp.asarray(x))),
        s0**2 + t @ x.T, rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(k.diag(theta, jnp.asarray(x))),
        s0**2 + (x * x).sum(-1), rtol=1e-6,
    )

    c, d = 1.2, 3
    kp = PolynomialKernel(d, c)
    thetap = jnp.asarray(kp.init_theta())
    np.testing.assert_allclose(
        np.asarray(kp.gram(thetap, jnp.asarray(x))), (x @ x.T + c) ** d,
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(kp.self_diag(thetap, jnp.asarray(x))),
        ((x * x).sum(-1) + c) ** d, rtol=1e-6,
    )


def test_polynomial_degree_validation():
    from spark_gp_tpu import PolynomialKernel

    with pytest.raises(ValueError):
        PolynomialKernel(0)


@pytest.mark.parametrize("kernel_factory", [
    lambda: __import__("spark_gp_tpu").RationalQuadraticKernel(0.8, 1.7),
    lambda: __import__("spark_gp_tpu").PeriodicKernel(1.3, 0.6),
    lambda: __import__("spark_gp_tpu").DotProductKernel(0.7),
    lambda: __import__("spark_gp_tpu").PolynomialKernel(3, 1.2),
], ids=["rq", "periodic", "dot", "poly"])
def test_family_gradients_finite_difference(rng, kernel_factory):
    """Autodiff vs central FD on a random functional of the Gram matrix,
    including the diagonal (all four families are smooth at r = 0 — no
    sqrt guard involved, unlike Matérn)."""
    kernel = kernel_factory()
    x = jnp.asarray(rng.normal(size=(10, 3)))
    w = jnp.asarray(rng.normal(size=(10, 10)))

    def scalar_of_theta(theta):
        return jnp.sum(w * kernel.gram(theta, x))

    theta0 = jnp.asarray(kernel.init_theta())
    grad = np.asarray(jax.grad(scalar_of_theta)(theta0))
    assert np.all(np.isfinite(grad))
    fd = _fd_grad(lambda t: float(scalar_of_theta(jnp.asarray(t))), theta0)
    np.testing.assert_allclose(grad, fd, rtol=2e-4, atol=1e-7)


def test_family_psd_and_dsl_composition(rng):
    """Each new family is PSD after the standard jitter, composes through
    the DSL, and hashes as a jit-static spec."""
    from spark_gp_tpu import (
        Const,
        DotProductKernel,
        EyeKernel,
        PeriodicKernel,
        PolynomialKernel,
        RationalQuadraticKernel,
    )

    x = jnp.asarray(rng.normal(size=(30, 2)))
    for base in (
        RationalQuadraticKernel(1.0, 1.0),
        PeriodicKernel(2.0, 1.0),
        DotProductKernel(1.0),
        PolynomialKernel(2, 1.0),
    ):
        k = 1.0 * base + Const(1e-3) * EyeKernel()
        gram = np.asarray(k.gram(jnp.asarray(k.init_theta()), x))
        eig = np.linalg.eigvalsh(0.5 * (gram + gram.T))
        assert eig.min() > 0, type(base).__name__
        assert hash(k) == hash(1.0 * base + Const(1e-3) * EyeKernel())


def test_periodic_end_to_end_fit(rng):
    """A strictly periodic signal: the Periodic kernel extrapolates a full
    period beyond the training range, which no stationary-decay kernel can."""
    from spark_gp_tpu import GaussianProcessRegression, PeriodicKernel

    n = 300
    x = np.linspace(0, 6, n)[:, None]
    y = np.sin(2 * np.pi * x[:, 0]) + 0.05 * rng.normal(size=n)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * PeriodicKernel(0.9, 1.0, 1e-2, 10.0))
        .setActiveSetSize(60)
        .setMaxIter(30)
        .fit(x, y)
    )
    x_far = np.linspace(6, 7, 50)[:, None]  # one period past the data
    from spark_gp_tpu.utils.validation import rmse

    assert rmse(np.sin(2 * np.pi * x_far[:, 0]), model.predict(x_far)) < 0.15


def test_dot_product_end_to_end_fit(rng):
    """A linear target: DotProduct + noise recovers it through the full
    estimator pipeline (Bayesian linear regression as a GP)."""
    from spark_gp_tpu import (
        DotProductKernel,
        GaussianProcessRegression,
        WhiteNoiseKernel,
    )

    n, p = 500, 4
    x = rng.normal(size=(n, p))
    w = np.array([1.5, -2.0, 0.5, 3.0])
    y = x @ w + 0.05 * rng.normal(size=n)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: DotProductKernel(1.0) + WhiteNoiseKernel(0.1, 0, 1))
        .setActiveSetSize(50)
        .setMaxIter(25)
        .fit(x, y)
    )
    from spark_gp_tpu.utils.validation import rmse

    assert rmse(y, model.predict(x)) < 0.15


def test_two_hyper_bounds_broadcast():
    """Scalar bounds apply to both hyperparameters; a length-2 sequence
    gives one box per hyperparameter (period vs lengthscale differ)."""
    from spark_gp_tpu import PeriodicKernel, RationalQuadraticKernel

    k = PeriodicKernel(1.0, 1.0, 1e-2, 10.0)
    lo, hi = k.bounds()
    np.testing.assert_allclose(lo, [1e-2, 1e-2])
    np.testing.assert_allclose(hi, [10.0, 10.0])

    k2 = PeriodicKernel(1.0, 1.0, lower=[0.5, 1e-3], upper=[2.0, np.inf])
    lo2, hi2 = k2.bounds()
    np.testing.assert_allclose(lo2, [0.5, 1e-3])
    np.testing.assert_allclose(hi2, [2.0, np.inf])

    k3 = RationalQuadraticKernel()
    lo3, hi3 = k3.bounds()
    np.testing.assert_allclose(lo3, [1e-6, 1e-6])
    np.testing.assert_allclose(hi3, [np.inf, np.inf])
    # distinct bounds are part of the jit-static spec hash
    assert hash(k2) != hash(PeriodicKernel(1.0, 1.0))


# --- ProductKernel (k1 * k2, Schur product) --------------------------------


def test_product_kernel_values_and_layout(rng):
    from spark_gp_tpu import PeriodicKernel, ProductKernel, RBFKernel

    k1, k2 = RBFKernel(0.7), PeriodicKernel(1.3, 0.9)
    k = k1 * k2
    assert isinstance(k, ProductKernel)
    assert k.n_hypers == 3
    np.testing.assert_allclose(k.init_theta(), [0.7, 1.3, 0.9])
    x = jnp.asarray(rng.normal(size=(8, 2)))
    t = jnp.asarray(rng.normal(size=(3, 2)))
    theta = jnp.asarray(k.init_theta())
    np.testing.assert_allclose(
        np.asarray(k.gram(theta, x)),
        np.asarray(k1.gram(theta[:1], x)) * np.asarray(k2.gram(theta[1:], x)),
        rtol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(k.cross(theta, t, x)),
        np.asarray(k1.cross(theta[:1], t, x))
        * np.asarray(k2.cross(theta[1:], t, x)),
        rtol=1e-12,
    )
    np.testing.assert_allclose(np.asarray(k.self_diag(theta, t)), 1.0)
    assert float(k.white_noise_var(theta)) == 0.0
    # PSD by the Schur product theorem (+ standard jitter)
    gram = np.asarray(k.gram(theta, x)) + 1e-10 * np.eye(8)
    assert np.linalg.eigvalsh(gram).min() > 0


def test_product_kernel_gradients_finite_difference(rng):
    from spark_gp_tpu import Matern32Kernel, RBFKernel

    k = RBFKernel(0.6) * Matern32Kernel(1.1)
    x = jnp.asarray(rng.normal(size=(9, 2)))
    w = jnp.asarray(rng.normal(size=(9, 9)))

    def functional(theta):
        return float(jnp.sum(w * k.gram(jnp.asarray(theta), x)))

    theta0 = k.init_theta()
    auto = np.asarray(
        jax.grad(lambda t: jnp.sum(w * k.gram(t, x)))(jnp.asarray(theta0))
    )
    fd = _fd_grad(functional, theta0)
    np.testing.assert_allclose(auto, fd, rtol=2e-4, atol=1e-7)


def test_quasi_periodic_end_to_end_fit(rng):
    """Periodic signal with a slow amplitude drift: RBF * Periodic (the
    canonical quasi-periodic composition the reference's Sum-only algebra
    cannot express) recovers it through the full pipeline."""
    from spark_gp_tpu import (
        GaussianProcessRegression,
        PeriodicKernel,
        RBFKernel,
        WhiteNoiseKernel,
    )

    n = 400
    x = np.linspace(0, 8, n)[:, None]
    y = np.exp(-0.05 * x[:, 0]) * np.sin(2 * np.pi * x[:, 0]) + 0.05 * rng.normal(
        size=n
    )
    model = (
        GaussianProcessRegression()
        .setKernel(
            lambda: 1.0
            * (RBFKernel(4.0, 0.5, 50.0) * PeriodicKernel(0.9, 1.0, 1e-2, 10.0))
            + WhiteNoiseKernel(0.1, 0, 1)
        )
        .setActiveSetSize(80)
        .setMaxIter(30)
        .fit(x, y)
    )
    from spark_gp_tpu.utils.validation import rmse

    assert rmse(y, model.predict(x)) < 0.1


def test_product_kernel_rejects_noise_factors():
    from spark_gp_tpu import Const, EyeKernel, RBFKernel, Scalar, WhiteNoiseKernel

    with pytest.raises(ValueError, match="white-noise"):
        (RBFKernel(1.0) + WhiteNoiseKernel(0.1, 0, 1)) * RBFKernel(0.5)
    with pytest.raises(ValueError, match="white-noise"):
        RBFKernel(1.0) * EyeKernel()
    # The guard is structural: noise that is ZERO at init_theta but can
    # train to a nonzero ridge must be rejected too (a numeric probe at the
    # initial point would let these through).
    with pytest.raises(ValueError, match="white-noise"):
        (RBFKernel(1.0) + WhiteNoiseKernel(0.0, 0.0, 1.0)) * RBFKernel(0.5)
    with pytest.raises(ValueError, match="white-noise"):
        (RBFKernel(1.0) + Scalar(0.0) * EyeKernel()) * RBFKernel(0.5)
    # ... while a non-trainable zero coefficient is genuinely inert and OK.
    k = (RBFKernel(1.0) + Const(0.0) * EyeKernel()) * RBFKernel(0.5)
    assert float(k.white_noise_var(jnp.asarray(k.init_theta()))) == 0.0


def test_ard_rational_quadratic(rng):
    """Closed-form values, theta layout (beta..., alpha appended), FD
    gradients, and the alpha -> inf RBF-ARD limit."""
    from spark_gp_tpu import ARDRationalQuadraticKernel

    beta = np.array([0.4, 1.2, 0.8])
    alpha = 1.6
    k = ARDRationalQuadraticKernel(beta, alpha=alpha)
    assert k.n_hypers == 4
    np.testing.assert_allclose(k.init_theta(), [0.4, 1.2, 0.8, 1.6])
    lo, hi = k.bounds()
    np.testing.assert_allclose(lo, [0.0, 0.0, 0.0, 1e-6])  # beta prunable
    x = rng.normal(size=(7, 3))
    theta = jnp.asarray(k.init_theta())
    gram = np.asarray(k.gram(theta, jnp.asarray(x)))
    d2 = (((x[:, None, :] - x[None, :, :]) * beta) ** 2).sum(-1)
    np.testing.assert_allclose(
        gram, (1.0 + d2 / alpha) ** (-alpha), rtol=1e-6
    )
    np.testing.assert_allclose(np.diag(gram), 1.0, rtol=1e-12)

    # FD gradients through every hyperparameter incl. the appended alpha
    w = jnp.asarray(rng.normal(size=(7, 7)))

    def functional(t):
        return float(jnp.sum(w * k.gram(jnp.asarray(t), jnp.asarray(x))))

    auto = np.asarray(
        jax.grad(lambda t: jnp.sum(w * k.gram(t, jnp.asarray(x))))(theta)
    )
    fd = _fd_grad(functional, k.init_theta())
    np.testing.assert_allclose(auto, fd, rtol=2e-4, atol=1e-7)

    # alpha -> inf recovers ARD-RBF with the SAME betas (the no-1/2
    # reference convention, ARDRBFKernel.scala:43-46)
    from spark_gp_tpu import ARDRBFKernel

    # convergence error is O(d^4 / alpha): 1e7 puts it well under the rtol
    k_inf = ARDRationalQuadraticKernel(beta, alpha=1e7)
    gram_inf = np.asarray(
        k_inf.gram(jnp.asarray(k_inf.init_theta()), jnp.asarray(x))
    )
    gram_rbf = np.asarray(
        ARDRBFKernel(beta).gram(jnp.asarray(beta), jnp.asarray(x))
    )
    np.testing.assert_allclose(gram_inf, gram_rbf, rtol=1e-4)


def test_every_family_describes_itself(rng):
    """kernel.describe(theta) — the 'Optimal kernel:' instrumentation line
    (GPC.scala:89's toString analogue) — must produce a non-empty string
    for every family and composite at its init theta."""
    from spark_gp_tpu import (
        ARDMatern32Kernel,
        ARDRationalQuadraticKernel,
        ARDRBFKernel,
        Const,
        DotProductKernel,
        EyeKernel,
        Matern12Kernel,
        Matern32Kernel,
        Matern52Kernel,
        PeriodicKernel,
        PolynomialKernel,
        RationalQuadraticKernel,
        RBFKernel,
        SpectralMixtureKernel,
        WhiteNoiseKernel,
    )
    from spark_gp_tpu.kernels.base import ThetaOverrideKernel

    kernels = [
        RBFKernel(0.5),
        ARDRBFKernel(3, 0.7),
        Matern12Kernel(1.0),
        Matern32Kernel(1.0),
        Matern52Kernel(1.0),
        ARDMatern32Kernel(np.array([0.5, 1.5])),
        RationalQuadraticKernel(0.8, 1.2),
        ARDRationalQuadraticKernel(2, 0.6, alpha=1.5),
        PeriodicKernel(1.3, 0.9),
        DotProductKernel(0.7),
        PolynomialKernel(3, 1.2),
        SpectralMixtureKernel(2, 2),
        1.0 * RBFKernel(0.5) + WhiteNoiseKernel(0.1, 0, 1),
        RBFKernel(2.0) * PeriodicKernel(1.0),
        Const(0.5) * EyeKernel(),
    ]
    kernels.append(ThetaOverrideKernel(kernels[0], np.array([2.0])))
    for k in kernels:
        desc = k.describe(k.init_theta())
        assert isinstance(desc, str), type(k).__name__
        # Const(c)*Eye legitimately renders non-empty; everything must
        # at least not crash, and non-Eye kernels must be non-empty
        if not isinstance(k, type(Const(0.5) * EyeKernel())):
            assert len(desc) > 0, type(k).__name__


# --- SpectralMixtureKernel (Wilson & Adams '13) ------------------------------


def test_spectral_mixture_matches_literal_formula(rng):
    from spark_gp_tpu import SpectralMixtureKernel

    p, q = 2, 3
    k = SpectralMixtureKernel(p, q)
    theta = np.asarray(k.init_theta()) * (1 + 0.3 * rng.random(k.n_hypers))
    xa = rng.normal(size=(6, p))
    xb = rng.normal(size=(5, p))

    got = np.asarray(k.cross(jnp.asarray(theta), jnp.asarray(xa), jnp.asarray(xb)))
    w = theta[:q]
    mu = theta[q:q + q * p].reshape(q, p)
    v = theta[q + q * p:].reshape(q, p)
    expect = np.zeros((6, 5))
    for i in range(6):
        for j in range(5):
            tau = xa[i] - xb[j]
            for c in range(q):
                expect[i, j] += w[c] * np.prod(
                    np.exp(-2 * np.pi**2 * tau**2 * v[c])
                    * np.cos(2 * np.pi * tau * mu[c])
                )
    np.testing.assert_allclose(got, expect, rtol=1e-10)
    np.testing.assert_allclose(
        np.asarray(k.diag(jnp.asarray(theta), jnp.asarray(xa))), w.sum()
    )


def test_spectral_mixture_q1_mu0_is_ard_rbf(rng):
    """Q=1, mu=0: k = w exp(-2 pi^2 sum_d tau_d^2 v_d) — the ARD RBF with
    beta_d = sqrt(2) pi sqrt(v_d) (reference convention: beta multiplies,
    gram = exp(-sum (beta_d tau_d)^2))."""
    from spark_gp_tpu import ARDRBFKernel, SpectralMixtureKernel

    p = 3
    v = np.array([0.4, 1.0, 2.5])
    sm = SpectralMixtureKernel(
        p, 1, weights=[1.0], means=np.zeros((1, p)), scales=v[None, :]
    )
    beta = np.sqrt(2.0) * np.pi * np.sqrt(v)
    ard = ARDRBFKernel(beta)
    x = rng.normal(size=(8, p)) * 0.2

    g_sm = np.asarray(sm.gram(jnp.asarray(sm.init_theta()), jnp.asarray(x)))
    g_ard = np.asarray(ard.gram(jnp.asarray(ard.init_theta()), jnp.asarray(x)))
    np.testing.assert_allclose(g_sm, g_ard, rtol=1e-6, atol=1e-9)


def test_spectral_mixture_fd_gradients(rng):
    from spark_gp_tpu import SpectralMixtureKernel

    k = SpectralMixtureKernel(2, 2)
    x = rng.normal(size=(7, 2))
    y = rng.normal(size=7)
    theta0 = np.asarray(k.init_theta()) * (1 + 0.2 * rng.random(k.n_hypers))

    def functional(t):
        g = k.gram(jnp.asarray(t), jnp.asarray(x))
        return float(y @ np.asarray(g) @ y)

    grad = np.asarray(
        jax.grad(
            lambda t: jnp.asarray(y) @ k.gram(t, jnp.asarray(x)) @ jnp.asarray(y)
        )(jnp.asarray(theta0))
    )
    fd = _fd_grad(functional, theta0)
    np.testing.assert_allclose(grad, fd, rtol=1e-5, atol=1e-7)


def test_spectral_mixture_psd_and_fit(rng):
    """Gram PSD on random inputs; a 1-D periodic-plus-trend signal fits
    through the estimator end-to-end and interpolates well."""
    from spark_gp_tpu import GaussianProcessRegression, SpectralMixtureKernel

    k = SpectralMixtureKernel(1, 2)
    x = rng.normal(size=(40, 1))
    g = np.asarray(k.gram(jnp.asarray(k.init_theta()), jnp.asarray(x)))
    eigs = np.linalg.eigvalsh(0.5 * (g + g.T))
    assert eigs.min() > -1e-8

    xs = np.linspace(0, 4, 120)[:, None]
    ys = np.cos(2 * np.pi * 1.5 * xs[:, 0]) + 0.05 * rng.normal(size=120)
    gp = (
        GaussianProcessRegression()
        .setKernel(
            lambda: 1.0 * SpectralMixtureKernel(
                1, 2, means=np.array([[0.5], [1.5]])
            )
        )
        .setDatasetSizeForExpert(60)
        .setActiveSetSize(40)
        .setSigma2(1e-3)
        .setSeed(3)
        .setMaxIter(60)
    )
    model = gp.fit(xs, ys)
    pred = model.predict(xs)
    assert np.sqrt(np.mean((pred - ys) ** 2)) < 0.2


def test_spectral_mixture_model_serialization_roundtrip(rng):
    """A model fitted with an SM composite must save/load to identical
    predictions (the spec-based kernel reconstruction is generic, but the
    newest family locks the contract in)."""
    import os
    import tempfile

    from spark_gp_tpu import (
        GaussianProcessRegression, SpectralMixtureKernel, WhiteNoiseKernel,
    )
    from spark_gp_tpu.models.gpr import GaussianProcessRegressionModel

    x = rng.normal(size=(60, 1))
    y = np.sin(3 * x[:, 0])
    m = (
        GaussianProcessRegression()
        .setKernel(
            lambda: 1.0 * SpectralMixtureKernel(1, 2)
            + WhiteNoiseKernel(0.05, 0, 1)
        )
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(20)
        .setSigma2(1e-3)
        .setSeed(1)
        .setMaxIter(20)
        .fit(x, y)
    )
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "m.npz")
        m.save(path)
        m2 = GaussianProcessRegressionModel.load(path)
    np.testing.assert_allclose(m2.predict(x), m.predict(x), rtol=1e-10)
