"""Kernel algebra tests.

Ports the reference's test strategy (RBFKernelTest.scala,
ARDRBFKernelTest.scala — SURVEY.md §4): golden 3x3 matrices on the same
3-point 2-d fixture, finite-difference derivative oracles (now through
``jax.test_util.check_grads`` + explicit FD), cross-kernel values, plus new
coverage the reference lacks: composition DSL bounds/slicing, white-noise
accounting, Eye behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels import (
    ARDRBFKernel,
    Const,
    EyeKernel,
    RBFKernel,
    Scalar,
    WhiteNoiseKernel,
)

# The reference's fixture: RBFKernelTest.scala:27
DATASET = np.array([[1.0, 2.0], [2.0, 3.0], [5.0, 7.0]])


def test_rbf_golden_matrix():
    """Golden values from RBFKernelTest.scala:33-38 (sigma = sqrt(0.2))."""
    k = RBFKernel(np.sqrt(0.2))
    gram = np.asarray(k.gram(jnp.asarray(k.init_theta()), jnp.asarray(DATASET)))
    expected = np.array(
        [
            [1.000000e00, 6.737947e-03, 3.053624e-45],
            [6.737947e-03, 1.000000e00, 7.187782e-28],
            [3.053624e-45, 7.187782e-28, 1.000000e00],
        ]
    )
    np.testing.assert_allclose(gram, expected, atol=1e-4)


def test_rbf_cross_golden():
    """RBFKernelTest.scala:62-76: cross kernel of first point vs rest."""
    k = RBFKernel(np.sqrt(0.2))
    theta = jnp.asarray(k.init_theta())
    cross = np.asarray(
        k.cross(theta, jnp.asarray(DATASET[:1]), jnp.asarray(DATASET[1:]))
    )
    np.testing.assert_allclose(
        cross, np.array([[6.737947e-03, 3.053624e-45]]), atol=1e-4
    )


def _fd_grad(fn, theta, h=1e-6):
    theta = np.asarray(theta, dtype=np.float64)
    grad = np.zeros_like(theta)
    for i in range(theta.size):
        tp, tm = theta.copy(), theta.copy()
        tp[i] += h
        tm[i] -= h
        grad[i] = (fn(tp) - fn(tm)) / (2 * h)
    return grad


@pytest.mark.parametrize(
    "kernel",
    [
        RBFKernel(0.2),
        ARDRBFKernel(np.array([0.2, 0.3])),
        1.0 * RBFKernel(0.5),
        1.0 * ARDRBFKernel(2, beta=0.7) + WhiteNoiseKernel(0.5, 0, 1),
        Scalar(2.0).between(0).and_(30) * RBFKernel(0.3) + Const(0.1) * EyeKernel(),
    ],
    ids=["rbf", "ard", "scaled-rbf", "composite", "dsl-composite"],
)
def test_gram_autodiff_matches_finite_difference(kernel):
    """The FD oracle of RBFKernelTest.scala:41-60 / ARDRBFKernelTest.scala:11-31,
    applied to autodiff gradients of a scalar functional of the Gram matrix."""
    x = jnp.asarray(DATASET)
    w = jnp.asarray(np.random.default_rng(0).normal(size=(3, 3)))

    def functional(theta):
        return float(jnp.sum(w * kernel.gram(jnp.asarray(theta), x)))

    theta0 = kernel.init_theta()
    auto = np.asarray(
        jax.grad(lambda t: jnp.sum(w * kernel.gram(t, x)))(jnp.asarray(theta0))
    )
    fd = _fd_grad(functional, theta0)
    np.testing.assert_allclose(auto, fd, rtol=1e-5, atol=1e-7)


def test_eye_kernel():
    k = EyeKernel()
    theta = jnp.zeros((0,))
    x = jnp.asarray(DATASET)
    np.testing.assert_allclose(np.asarray(k.gram(theta, x)), np.eye(3))
    np.testing.assert_allclose(
        np.asarray(k.cross(theta, x[:2], x)), np.zeros((2, 3))
    )
    assert float(k.white_noise_var(theta)) == 1.0
    np.testing.assert_allclose(np.asarray(k.self_diag(theta, x)), np.ones(3))


def test_white_noise_kernel_dsl():
    """WhiteNoiseKernel(init, lo, hi) = (init between lo and hi) * Eye
    (kernel/Kernel.scala:166-169)."""
    k = WhiteNoiseKernel(0.5, 0.0, 1.0)
    assert k.n_hypers == 1
    np.testing.assert_allclose(k.init_theta(), [0.5])
    lo, hi = k.bounds()
    np.testing.assert_allclose(lo, [0.0])
    np.testing.assert_allclose(hi, [1.0])
    theta = jnp.asarray([0.25])
    x = jnp.asarray(DATASET)
    np.testing.assert_allclose(np.asarray(k.gram(theta, x)), 0.25 * np.eye(3))
    assert float(k.white_noise_var(theta)) == 0.25


def test_composite_theta_layout():
    """Sum concatenates children; trainable scalar prepends its coefficient
    (SumOfKernels.scala:19-26, ScalarTimesKernel.scala:78-84)."""
    k = 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1)
    assert k.n_hypers == 3
    np.testing.assert_allclose(k.init_theta(), [1.0, 0.1, 0.5])
    lo, hi = k.bounds()
    np.testing.assert_allclose(lo, [0.0, 1e-6, 0.0])
    np.testing.assert_allclose(hi, [np.inf, 10.0, 1.0])


def test_const_scale_has_no_hypers():
    k = Const(0.5) * RBFKernel(0.2)
    assert k.n_hypers == 1  # only the RBF sigma
    x = jnp.asarray(DATASET)
    theta = jnp.asarray(k.init_theta())
    inner = RBFKernel(0.2)
    np.testing.assert_allclose(
        np.asarray(k.gram(theta, x)),
        0.5 * np.asarray(inner.gram(theta, x)),
    )


def test_negative_scalar_rejected():
    with pytest.raises(ValueError):
        Scalar(-1.0) * RBFKernel()


def test_white_noise_var_composes():
    """whiteNoiseVar sums across Sum and scales through Scalar
    (SumOfKernels.scala:62, ScalarTimesKernel.scala:28)."""
    k = RBFKernel(1.0) + Const(1e-3) * EyeKernel()
    theta = jnp.asarray(k.init_theta())
    assert float(k.white_noise_var(theta)) == pytest.approx(1e-3)
    k2 = RBFKernel(1.0) + WhiteNoiseKernel(0.5, 0, 1) + Const(1e-3) * EyeKernel()
    theta2 = jnp.asarray(k2.init_theta())
    assert float(k2.white_noise_var(theta2)) == pytest.approx(0.5 + 1e-3)


def test_ard_matches_reference_convention():
    """ARD uses exp(-|(xi-xj)*beta|^2) — beta multiplies, no 1/2 factor
    (ARDRBFKernel.scala:43-46)."""
    beta = np.array([0.2, 0.3])
    k = ARDRBFKernel(beta)
    x = jnp.asarray(DATASET)
    gram = np.asarray(k.gram(jnp.asarray(beta), x))
    diff = DATASET[0] - DATASET[1]
    expected01 = np.exp(-np.sum((diff * beta) ** 2))
    np.testing.assert_allclose(gram[0, 1], expected01, rtol=1e-12)
    np.testing.assert_allclose(np.diag(gram), np.ones(3), rtol=1e-12)
