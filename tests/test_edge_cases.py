"""Degenerate-input regression guards: the shapes a switching user hits
first (tiny N, N below the expert/active sizes, constant or duplicate
data, single-class labels, empty test sets) must produce finite models,
not crashes."""

import numpy as np

from spark_gp_tpu import (
    GaussianProcessClassifier,
    GaussianProcessMulticlassClassifier,
    GaussianProcessPoissonRegression,
    GaussianProcessRegression,
)


def _finite(a):
    assert np.all(np.isfinite(np.asarray(a)))


def test_gpr_tiny_n_below_expert_and_active_sizes(rng):
    x = rng.normal(size=(5, 2))
    y = np.sin(x.sum(1))
    model = GaussianProcessRegression().setMaxIter(5).fit(x, y)
    _finite(model.predict(x))
    mean, var = model.predict_with_var(x)
    _finite(mean)
    _finite(var)


def test_gpr_single_point():
    model = GaussianProcessRegression().setMaxIter(3).fit(
        np.zeros((1, 2)), np.array([1.0])
    )
    _finite(model.predict(np.zeros((1, 2))))


def test_gpr_constant_targets(rng):
    x = rng.normal(size=(50, 2))
    model = GaussianProcessRegression().setMaxIter(5).fit(x, np.full(50, 3.0))
    pred = model.predict(x)
    _finite(pred)
    np.testing.assert_allclose(pred, 3.0, atol=0.2)


def test_gpr_all_duplicate_rows():
    x = np.tile(np.array([[0.3, -1.2]]), (30, 1))
    model = GaussianProcessRegression().setMaxIter(3).fit(x, np.ones(30))
    _finite(model.predict(x))


def test_gpr_active_set_larger_than_n(rng):
    x = rng.normal(size=(50, 2))
    y = np.sin(x.sum(1))
    model = (
        GaussianProcessRegression().setActiveSetSize(500).setMaxIter(3).fit(x, y)
    )
    _finite(model.predict(x))


def test_gpr_empty_test_set(rng):
    x = rng.normal(size=(40, 2))
    model = GaussianProcessRegression().setMaxIter(3).fit(x, np.sin(x.sum(1)))
    assert model.predict(np.zeros((0, 2))).shape == (0,)


def test_gpc_single_class_present(rng):
    x = rng.normal(size=(50, 2))
    model = GaussianProcessClassifier().setMaxIter(3).fit(x, np.zeros(50))
    pred = model.predict(x)
    _finite(pred)
    proba = model.predict_proba(x)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)


def test_multiclass_label_gap(rng):
    """Labels {0, 2} with class 1 absent: C = 3 is inferred from the max
    label; the empty class simply never wins."""
    x = rng.normal(size=(60, 2))
    y = np.where(x.sum(1) > 0, 2.0, 0.0)
    model = (
        GaussianProcessMulticlassClassifier()
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(20)
        .setMaxIter(3)
        .fit(x, y)
    )
    assert model.num_classes == 3
    pred = model.predict(x)
    assert set(np.unique(pred)) <= {0.0, 1.0, 2.0}


def test_poisson_all_zero_counts(rng):
    x = rng.normal(size=(50, 2))
    model = GaussianProcessPoissonRegression().setMaxIter(3).fit(x, np.zeros(50))
    rate = model.predict_rate(x)
    _finite(rate)
    assert np.all(rate >= 0)
