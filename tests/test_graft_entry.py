"""Guard the driver entry points (__graft_entry__.py).

The driver compile-checks ``entry()`` single-chip and executes
``dryrun_multichip(N)`` on N virtual devices at round end — a regression
there would otherwise surface only in the driver's artifacts, after the
fact.  The conftest already forces the 8-virtual-device CPU platform, so
the full multichip path (all four families sharded on the mesh) runs here
exactly as the driver runs it.
"""

import os
import sys

import jax
import numpy as np
import pytest


@pytest.fixture(scope="module")
def graft_entry():
    # repo root from __file__ (the _mp_worker.py pattern): correct under
    # any checkout location and never a stale sibling checkout
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import __graft_entry__ as mod

    return mod


def test_entry_compiles_and_runs(graft_entry):
    fn, args = graft_entry.entry()
    value, grad = jax.jit(fn)(*args)
    assert np.isfinite(float(value))
    assert np.all(np.isfinite(np.asarray(grad)))


def test_dryrun_multichip_eight_devices(graft_entry, eight_device_mesh):
    # eight_device_mesh fixture guarantees the 8-device platform is up
    graft_entry.dryrun_multichip(8)
