"""Dataset loader/generator contracts (shapes, determinism, CSV hooks)."""

import numpy as np

from spark_gp_tpu.data import (
    load_airfoil,
    load_iris,
    load_mnist_binary,
    load_protein,
    load_year_msd,
    make_benchmark_data,
    make_synthetics,
)


def test_synthetics_shape_and_noise():
    x, y = make_synthetics()
    assert x.shape == (2000, 1) and y.shape == (2000,)
    # y = sin(x) + N(0, 0.01): residuals should look like the noise
    resid = y - np.sin(x[:, 0])
    assert abs(resid.std() - 0.1) < 0.02


def test_airfoil_shape():
    x, y = load_airfoil()
    assert x.shape == (1503, 5) and y.shape == (1503,)


def test_iris_shape_and_classes():
    x, y = load_iris()
    assert x.shape == (150, 4)
    assert sorted(np.unique(y)) == [0.0, 1.0, 2.0]
    assert np.bincount(y.astype(int)).tolist() == [50, 50, 50]


def test_mnist_binary_synthetic_standin():
    x, y = load_mnist_binary()
    assert x.shape[1] == 784
    assert set(np.unique(y)) == {0.0, 1.0}
    x2, y2 = load_mnist_binary()
    np.testing.assert_array_equal(x, x2)  # deterministic


def test_mnist_binary_csv(tmp_path):
    """Label-first CSV path — the reference's mnist68.csv format
    (MNIST.scala:22-26) with non-target digits filtered out."""
    rows = np.array(
        [
            [6.0, 0.1, 0.2],
            [8.0, 0.3, 0.4],
            [3.0, 9.9, 9.9],  # dropped: not in (6, 8)
            [6.0, 0.5, 0.6],
        ]
    )
    path = tmp_path / "mnist.csv"
    np.savetxt(path, rows, delimiter=",")
    x, y = load_mnist_binary(str(path))
    assert x.shape == (3, 2)
    np.testing.assert_array_equal(y, [0.0, 1.0, 0.0])


def test_protein_standin_and_subsample():
    x, y = load_protein(n=500)
    assert x.shape == (500, 9) and y.shape == (500,)


def test_year_msd_standin_and_subsample():
    x, y = load_year_msd(n=300)
    assert x.shape == (300, 90) and y.shape == (300,)


def test_missing_csv_path_raises():
    """An explicitly-passed but absent CSV must not silently fall back to
    synthetic data."""
    import pytest

    for loader in (load_mnist_binary, load_protein, load_year_msd):
        with pytest.raises((FileNotFoundError, OSError)):
            loader("/no/such/file.csv")


def test_benchmark_data():
    x, y = make_benchmark_data(1000)
    assert x.shape == (1000, 3)
    np.testing.assert_allclose(y, np.sin(x.sum(axis=1) / 1000.0))


def test_gp_data_dir_snap_in(tmp_path, monkeypatch):
    """Real-data snap-in (VERDICT r4 #5): dropping a real CSV into
    $GP_DATA_DIR flips the loaders from stand-in to real data with zero
    code change, and the provenance strings record which was used."""
    import numpy as np

    from spark_gp_tpu.data import (
        dataset_provenance,
        find_dataset_file,
        load_protein,
        load_year_msd,
    )

    # no GP_DATA_DIR: stand-in path, provenance says so
    monkeypatch.delenv("GP_DATA_DIR", raising=False)
    assert find_dataset_file("protein") is None
    assert "stand-in" in dataset_provenance("protein")
    x_synth, _ = load_protein(n=50)
    assert x_synth.shape == (50, 9)

    # plant a tiny CASP-shaped CSV (header + RMSD,F1..F9 rows)
    rng = np.random.default_rng(3)
    rows = np.concatenate(
        [rng.uniform(0, 10, size=(20, 1)), rng.normal(size=(20, 9))], axis=1
    )
    csv = tmp_path / "CASP.csv"
    header = "RMSD," + ",".join(f"F{i}" for i in range(1, 10))
    np.savetxt(csv, rows, delimiter=",", header=header, comments="")
    monkeypatch.setenv("GP_DATA_DIR", str(tmp_path))

    assert find_dataset_file("protein") == str(csv)
    assert dataset_provenance("protein") == "real (CASP.csv)"
    x, y = load_protein()
    assert x.shape == (20, 9)
    np.testing.assert_allclose(y, rows[:, 0])
    np.testing.assert_allclose(x, rows[:, 1:])

    # year_msd in the same dir: header-less year,F1..F90
    msd = np.concatenate(
        [rng.integers(1950, 2011, size=(15, 1)).astype(float),
         rng.normal(size=(15, 90))], axis=1,
    )
    np.savetxt(tmp_path / "YearPredictionMSD.csv", msd, delimiter=",")
    xm, ym = load_year_msd()
    assert xm.shape == (15, 90)
    np.testing.assert_allclose(ym, msd[:, 0])
    # explicit path still wins over discovery
    x2, _ = load_protein(str(csv))
    np.testing.assert_allclose(x2, x)


def test_mnist_snap_in_uses_real_csv(tmp_path, monkeypatch):
    """A discoverable mnist68.csv (label-first, MNIST.scala:22-26 format)
    replaces the synthetic stand-in and filters to the digit pair."""
    import numpy as np

    from spark_gp_tpu.data import load_mnist_binary

    rng = np.random.default_rng(5)
    labels = np.array([6, 8, 6, 8, 3, 6])[:, None].astype(float)
    pixels = rng.uniform(0, 255, size=(6, 784)).round(0)
    np.savetxt(tmp_path / "mnist68.csv",
               np.concatenate([labels, pixels], axis=1), delimiter=",")
    monkeypatch.setenv("GP_DATA_DIR", str(tmp_path))

    x, y = load_mnist_binary()
    assert x.shape == (5, 784)  # the label-3 row is filtered out
    np.testing.assert_array_equal(np.unique(y), [0.0, 1.0])
    assert y.sum() == 2  # two 8s
