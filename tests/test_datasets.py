"""Dataset loader/generator contracts (shapes, determinism, CSV hooks)."""

import numpy as np

from spark_gp_tpu.data import (
    load_airfoil,
    load_iris,
    load_mnist_binary,
    load_protein,
    load_year_msd,
    make_benchmark_data,
    make_synthetics,
)


def test_synthetics_shape_and_noise():
    x, y = make_synthetics()
    assert x.shape == (2000, 1) and y.shape == (2000,)
    # y = sin(x) + N(0, 0.01): residuals should look like the noise
    resid = y - np.sin(x[:, 0])
    assert abs(resid.std() - 0.1) < 0.02


def test_airfoil_shape():
    x, y = load_airfoil()
    assert x.shape == (1503, 5) and y.shape == (1503,)


def test_iris_shape_and_classes():
    x, y = load_iris()
    assert x.shape == (150, 4)
    assert sorted(np.unique(y)) == [0.0, 1.0, 2.0]
    assert np.bincount(y.astype(int)).tolist() == [50, 50, 50]


def test_mnist_binary_synthetic_standin():
    x, y = load_mnist_binary()
    assert x.shape[1] == 784
    assert set(np.unique(y)) == {0.0, 1.0}
    x2, y2 = load_mnist_binary()
    np.testing.assert_array_equal(x, x2)  # deterministic


def test_mnist_binary_csv(tmp_path):
    """Label-first CSV path — the reference's mnist68.csv format
    (MNIST.scala:22-26) with non-target digits filtered out."""
    rows = np.array(
        [
            [6.0, 0.1, 0.2],
            [8.0, 0.3, 0.4],
            [3.0, 9.9, 9.9],  # dropped: not in (6, 8)
            [6.0, 0.5, 0.6],
        ]
    )
    path = tmp_path / "mnist.csv"
    np.savetxt(path, rows, delimiter=",")
    x, y = load_mnist_binary(str(path))
    assert x.shape == (3, 2)
    np.testing.assert_array_equal(y, [0.0, 1.0, 0.0])


def test_protein_standin_and_subsample():
    x, y = load_protein(n=500)
    assert x.shape == (500, 9) and y.shape == (500,)


def test_year_msd_standin_and_subsample():
    x, y = load_year_msd(n=300)
    assert x.shape == (300, 90) and y.shape == (300,)


def test_missing_csv_path_raises():
    """An explicitly-passed but absent CSV must not silently fall back to
    synthetic data."""
    import pytest

    for loader in (load_mnist_binary, load_protein, load_year_msd):
        with pytest.raises((FileNotFoundError, OSError)):
            loader("/no/such/file.csv")


def test_benchmark_data():
    x, y = make_benchmark_data(1000)
    assert x.shape == (1000, 3)
    np.testing.assert_allclose(y, np.sin(x.sum(axis=1) / 1000.0))
