"""Multi-start hyperparameter optimization (setNumRestarts)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel, WhiteNoiseKernel
from spark_gp_tpu.kernels.base import ThetaOverrideKernel


def test_theta_override_kernel_delegates(rng):
    inner = 1.0 * RBFKernel(0.5, 1e-6, 10.0) + WhiteNoiseKernel(0.1, 0, 1)
    t_new = np.array([2.0, 1.5, 0.3])
    k = ThetaOverrideKernel(inner, t_new)
    np.testing.assert_allclose(k.init_theta(), t_new)
    lo, hi = k.bounds()
    lo_i, hi_i = inner.bounds()
    np.testing.assert_allclose(lo, lo_i)
    np.testing.assert_allclose(hi, hi_i)
    x = jnp.asarray(rng.normal(size=(6, 2)))
    theta = jnp.asarray(t_new)
    np.testing.assert_allclose(
        np.asarray(k.gram(theta, x)), np.asarray(inner.gram(theta, x)),
        rtol=1e-15,
    )
    assert float(k.white_noise_var(theta)) == float(
        inner.white_noise_var(theta)
    )
    assert hash(k) != hash(inner)
    assert hash(k) == hash(ThetaOverrideKernel(inner, t_new))
    with pytest.raises(ValueError, match="entries"):
        ThetaOverrideKernel(inner, np.array([1.0]))


def _problem(rng, n=300):
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    return x, y


def _make_gp(restarts=1):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-3, 10.0))
        .setActiveSetSize(50)
        .setMaxIter(15)
        .setSeed(7)
    )
    if restarts > 1:
        gp = gp.setNumRestarts(restarts)
    return gp


def test_best_of_restarts_never_worse_than_single(rng):
    """Restart 0 IS the single fit (same seed, deterministic), so the
    best-of-R final NLL can only be <= the single fit's."""
    x, y = _problem(rng)
    single = _make_gp().fit(x, y)
    multi = _make_gp(3).fit(x, y)
    nll_single = float(single.instr.metrics["final_nll"])
    nll_multi = float(multi.instr.metrics["final_nll"])
    assert nll_multi <= nll_single + 1e-9
    assert multi.instr.metrics["num_restarts"] == 3
    assert 0 <= multi.instr.metrics["best_restart"] < 3
    # the winner is a working model
    from spark_gp_tpu.utils.validation import rmse

    assert rmse(y, multi.predict(x)) < 0.2


def test_restarts_reject_checkpointing(tmp_path):
    gp = _make_gp(2).setCheckpointDir(str(tmp_path))
    with pytest.raises(ValueError, match="not combinable"):
        gp.fit(np.zeros((10, 2)), np.zeros(10))


def test_restarts_validation():
    with pytest.raises(ValueError, match=">= 1"):
        GaussianProcessRegression().setNumRestarts(0)


@pytest.mark.parametrize("make", ["binary", "multiclass"])
def test_restarts_on_classifiers(rng, make):
    from spark_gp_tpu import (
        GaussianProcessClassifier,
        GaussianProcessMulticlassClassifier,
    )

    x = rng.normal(size=(120, 2))
    if make == "binary":
        y = (x.sum(axis=1) > 0).astype(np.float64)
        est = GaussianProcessClassifier()
    else:
        y = np.digitize(x.sum(axis=1), [-0.5, 0.5]).astype(np.float64)
        est = GaussianProcessMulticlassClassifier()
    model = (
        est.setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(60)
        .setActiveSetSize(30)
        .setMaxIter(10)
        .setNumRestarts(2)
        .fit(x, y)
    )
    assert model.instr.metrics["num_restarts"] == 2
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.8, acc


def test_theta_override_shares_jit_identity():
    """Different starting points share one jit-static identity (restarts
    must not recompile every fit/predict program)."""
    inner = 1.0 * RBFKernel(0.5, 1e-6, 10.0)
    a = ThetaOverrideKernel(inner, np.array([1.0, 0.5]))
    b = ThetaOverrideKernel(inner, np.array([2.0, 3.0]))
    assert hash(a) == hash(b) and a == b
    np.testing.assert_allclose(a.init_theta(), [1.0, 0.5])
    np.testing.assert_allclose(b.init_theta(), [2.0, 3.0])


def test_restarts_in_fit_distributed(rng, eight_device_mesh):
    from spark_gp_tpu.parallel import distributed as dist

    x, y = _problem(rng, n=240)
    gdata = dist.distribute_global_experts(x, y, 30, eight_device_mesh)
    model = (
        _make_gp(2)
        .setMesh(eight_device_mesh)
        .fit_distributed(gdata)
    )
    assert model.instr.metrics["num_restarts"] == 2
    assert "restart_1_nll" in model.instr.metrics


def test_batched_device_multistart(rng):
    """GPR + device optimizer + restarts takes the batched one-dispatch
    path: per-restart NLLs are recorded, the winner selection is internally
    consistent (final_nll equals the best lane's NLL, best_restart points
    at it), and the model is sound."""
    x, y = _problem(rng)
    batched = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-3, 10.0))
        .setActiveSetSize(50)
        .setMaxIter(15)
        .setSeed(7)
        .setNumRestarts(3)
        .setOptimizer("device")
        .fit(x, y)
    )
    m = batched.instr.metrics
    assert m["num_restarts"] == 3
    nlls = np.array([m[f"restart_{r}_nll"] for r in range(3)])
    best = int(m["best_restart"])
    np.testing.assert_allclose(m["final_nll"], nlls[best], rtol=1e-6)
    assert nlls[best] == nlls.min()
    from spark_gp_tpu.utils.validation import rmse

    assert rmse(y, batched.predict(x)) < 0.2


def test_batched_device_multistart_classifier(rng):
    from spark_gp_tpu import GaussianProcessClassifier

    x = rng.normal(size=(150, 2))
    y = (x.sum(axis=1) > 0).astype(np.float64)
    model = (
        GaussianProcessClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(75)
        .setActiveSetSize(40)
        .setMaxIter(10)
        .setSeed(7)
        .setNumRestarts(3)
        .setOptimizer("device")
        .fit(x, y)
    )
    m = model.instr.metrics
    assert m["num_restarts"] == 3
    nlls = np.array([m[f"restart_{r}_nll"] for r in range(3)])
    np.testing.assert_allclose(m["final_nll"], nlls[int(m["best_restart"])], rtol=1e-6)
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.9, acc


@pytest.mark.parametrize("family", ["multiclass", "poisson"])
def test_batched_device_multistart_mc_and_poisson(rng, family):
    if family == "multiclass":
        from spark_gp_tpu import GaussianProcessMulticlassClassifier as Est

        x = rng.normal(size=(120, 2))
        y = np.digitize(x.sum(axis=1), [-0.5, 0.5]).astype(np.float64)
    else:
        from spark_gp_tpu import GaussianProcessPoissonRegression as Est

        x = np.linspace(0, 4, 120)[:, None]
        y = rng.poisson(np.exp(1 + np.sin(2 * x[:, 0]))).astype(np.float64)
    model = (
        Est()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(60)
        .setActiveSetSize(30)
        .setMaxIter(8)
        .setSeed(7)
        .setNumRestarts(3)
        .setOptimizer("device")
        .fit(x, y)
    )
    m = model.instr.metrics
    assert m["num_restarts"] == 3
    nlls = np.array([m[f"restart_{r}_nll"] for r in range(3)])
    np.testing.assert_allclose(
        m["final_nll"], nlls[int(m["best_restart"])], rtol=1e-6
    )
    # the winner's PPA tail must produce a sound model, not just metrics
    if family == "multiclass":
        acc = float(np.mean(model.predict(x) == y))
        assert acc > 0.85, acc
    else:
        rate = model.predict_rate(x)
        assert np.all(np.isfinite(rate)) and np.all(rate >= 0)
        rel = float(np.mean(np.abs(rate - np.exp(1 + np.sin(2 * x[:, 0])))
                    / np.exp(1 + np.sin(2 * x[:, 0]))))
        assert rel < 0.4, rel


def test_restart_winner_model_roundtrips(rng, tmp_path):
    """A multi-start winner's model may carry a ThetaOverrideKernel inside
    its predictor; save/load must round-trip it (pickle of the wrapper +
    composite spec) with identical predictions."""
    from spark_gp_tpu import GaussianProcessRegressionModel

    x, y = _problem(rng, n=200)
    model = _make_gp(3).fit(x, y)
    path = str(tmp_path / "winner")
    model.save(path)
    loaded = GaussianProcessRegressionModel.load(path)
    np.testing.assert_allclose(
        loaded.predict(x[:30]), model.predict(x[:30]), rtol=1e-12
    )
    # the loaded kernel still describes itself (the instrumentation path)
    desc = loaded.raw_predictor.kernel.describe(
        loaded.raw_predictor.theta
    )
    assert isinstance(desc, str) and len(desc) > 0
