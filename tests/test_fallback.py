"""Degradation-ladder tests (``resilience/fallback.py``, ISSUE 9).

Covers the closed failure taxonomy + classifier, every ladder entry
point against chaos-injected execution faults (fit one-dispatch ->
segmented, predict chunk-halving -> host solve, device magic solve ->
host solve, sharded fit -> single host), the ``GP_GUARD_ACTION=degrade``
strict-lane re-fit, provenance/journal stamping, the ``GP_FALLBACK=0``
raw-propagation kill switch, and the exception-hygiene lint that keeps
the taxonomy from rotting.
"""

import json
import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.data import make_benchmark_data
from spark_gp_tpu.resilience import chaos, fallback


def _gp(optimizer="device", max_iter=6, expert=50, **kw):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.1))
        .setDatasetSizeForExpert(expert)
        .setActiveSetSize(expert)
        .setSeed(13)
        .setSigma2(1e-3)
        .setMaxIter(max_iter)
        .setOptimizer(optimizer)
    )
    return gp


@pytest.fixture(scope="module")
def problem():
    return make_benchmark_data(800)


@pytest.fixture(scope="module")
def clean_model(problem):
    x, y = problem
    return _gp().fit(x, y)


# -- taxonomy / classifier --------------------------------------------------


def test_taxonomy_is_closed_and_catalogued():
    from spark_gp_tpu.obs import names

    assert fallback.UNKNOWN in fallback.FAILURE_CLASSES
    for cls in fallback.FAILURE_CLASSES:
        # every class is representable in the fallback.failures.* pattern
        assert names.is_registered(f"fallback.failures.{cls}")


def test_classifier_maps_framework_exceptions():
    from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException
    from spark_gp_tpu.parallel.coord import CoordinationTimeoutError
    from spark_gp_tpu.resilience.quarantine import (
        ExpertQuarantineError,
        NonFiniteFitError,
    )
    from spark_gp_tpu.resilience.retry import RetryBudgetExceededError

    cf = fallback.classify_failure
    assert cf(NotPositiveDefiniteException()) == fallback.NOT_PSD_EXHAUSTED
    assert cf(NonFiniteFitError("x")) == fallback.NON_FINITE_EXHAUSTED
    assert cf(ExpertQuarantineError("x")) == fallback.NON_FINITE_EXHAUSTED
    assert cf(
        CoordinationTimeoutError("barrier", 5.0, [1, 3])
    ) == fallback.COORD_TIMEOUT
    assert cf(fallback.GuardBreachError("mixed", 1.0, 0.01)) == (
        fallback.GUARD_BREACH
    )
    assert cf(MemoryError()) == fallback.OOM
    assert cf(ValueError("boom")) == fallback.UNKNOWN
    assert cf(RuntimeError("some random runtime thing")) == fallback.UNKNOWN


def test_classifier_maps_xla_runtime_errors_by_message():
    from jaxlib.xla_extension import XlaRuntimeError

    cf = fallback.classify_failure
    assert cf(XlaRuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory allocating 123 bytes"
    )) == fallback.OOM
    assert cf(XlaRuntimeError(
        "INTERNAL: during compilation: Mosaic failed to lower"
    )) == fallback.COMPILE
    assert cf(XlaRuntimeError("UNIMPLEMENTED: whatever")) == fallback.UNKNOWN


def test_classifier_follows_retry_budget_cause():
    from spark_gp_tpu.resilience.retry import RetryBudgetExceededError

    inner = MemoryError()
    wrapped = RetryBudgetExceededError("fit failed")
    wrapped.__cause__ = inner
    assert fallback.classify_failure(wrapped) == fallback.OOM


# -- fit ladder: injected OOM / compile -------------------------------------


def test_injected_oom_completes_via_iterative_rung(problem, clean_model, tmp_path, monkeypatch):
    """The acceptance contract: a RESOURCE_EXHAUSTED on the one-dispatch
    device fit completes through the ITERATIVE solver rung (ISSUE 14 —
    the oom class tries the CG/Lanczos lane first: the SAME dispatch
    shape, skinny CG workspace instead of factor stacks — so a memory
    budget the exact program exceeds admits the re-fit) with the
    achieved objective inside the lane's documented stochastic
    tolerance, fallback metrics emitted, and the classified failure +
    rung sequence recorded in the run journal and the saved model's
    provenance_json.  The budget is chaos-staged between the two rungs'
    modeled bytes with the PLANNER disabled, so the reactive ladder —
    not pre-sizing — is what carries the fit."""
    from spark_gp_tpu.parallel.experts import num_experts_for
    from spark_gp_tpu.resilience import memplan

    x, y = problem
    monkeypatch.setenv("GP_RUN_JOURNAL_DIR", str(tmp_path))
    monkeypatch.setenv("GP_MEMPLAN", "0")
    from spark_gp_tpu.obs.runtime import telemetry

    e = num_experts_for(x.shape[0], 50)
    itemsize = int(np.dtype(np.asarray(x).dtype).itemsize)
    native_raw = memplan.fit_dispatch_bytes(
        e, 50, x.shape[1], itemsize, "native"
    )
    iter_raw = memplan.fit_dispatch_bytes(
        e, 50, x.shape[1], itemsize, "iterative"
    )
    assert iter_raw < native_raw
    before = telemetry.snapshot()["counters"]
    with chaos.memory_limit_bytes((iter_raw + native_raw) / 2.0) as fired:
        model = _gp().fit(x, y)
    assert fired[0] == 1
    # objective-level parity: theta can ride a flat amplitude ridge at
    # this small iteration budget, but the achieved objective must match
    # within the iterative lane's documented stochastic bar
    nll_clean = float(clean_model.instr.metrics["final_nll"])
    nll_degr = float(model.instr.metrics["final_nll"])
    assert abs(nll_degr - nll_clean) / max(abs(nll_clean), 1.0) <= 1e-2
    # metrics
    assert model.instr.metrics["fallback.engaged"] == 1.0
    after = telemetry.snapshot()["counters"]
    assert after.get("fallback.transitions", 0) > before.get(
        "fallback.transitions", 0
    )
    assert after.get("fallback.failures.oom", 0) > before.get(
        "fallback.failures.oom", 0
    )
    # degradation history: classified class + rung sequence
    (transition,) = model.degradations
    assert transition["failure_class"] == "oom"
    assert transition["from"] == "native"
    assert transition["to"] == "iterative"
    # run journal carries it
    assert model.run_journal["degradations"] == model.degradations
    with open(model.run_journal["path"]) as fh:
        persisted = json.load(fh)
    assert persisted["degradations"] == model.degradations
    # saved model provenance carries it
    path = str(tmp_path / "degraded.npz")
    model.save(path)
    from spark_gp_tpu.models.gpr import GaussianProcessRegressionModel

    loaded = GaussianProcessRegressionModel.load(path)
    assert loaded.provenance["degradations"] == model.degradations


def test_injected_compile_failure_walks_ladder(problem, clean_model):
    x, y = problem
    with chaos.failing_compile(times=1, op="fit.device") as fired:
        model = _gp().fit(x, y)
    assert fired[0] == 1
    assert [d["failure_class"] for d in model.degradations] == ["compile"]
    np.testing.assert_allclose(
        model.raw_predictor.theta, clean_model.raw_predictor.theta,
        atol=1e-6,
    )


def test_kill_switch_restores_raw_propagation(problem, monkeypatch):
    """GP_FALLBACK=0: the raw injected XlaRuntimeError propagates — type,
    message, no degradation metrics, no model."""
    x, y = problem
    monkeypatch.setenv("GP_FALLBACK", "0")
    with chaos.oom_after_calls(0, op="one_dispatch"):
        with pytest.raises(Exception) as excinfo:
            _gp().fit(x, y)
    assert type(excinfo.value).__name__ == "XlaRuntimeError"
    assert "RESOURCE_EXHAUSTED" in str(excinfo.value)
    assert not isinstance(excinfo.value, fallback.DegradationExhaustedError)


def test_persistent_oom_raises_single_classified_error(problem):
    """Every rung OOMs -> ONE DegradationExhaustedError naming the class
    and the rung history (cause chained) — the soak invariant."""
    x, y = problem
    with chaos.oom_after_calls(0, op="fit."):  # matches EVERY rung's dispatch
        with pytest.raises(fallback.DegradationExhaustedError) as excinfo:
            _gp().fit(x, y)
    err = excinfo.value
    assert err.failure_class == fallback.OOM
    assert [d["to"] for d in err.degradations] == [
        "iterative", "matfree", "segmented", "host_f64",
    ]
    assert err.__cause__ is not None
    assert fallback.classify_failure(err) == fallback.OOM


def test_unknown_failures_never_degrade(problem, monkeypatch):
    """An unclassifiable exception re-raises raw — the ladder only
    degrades what it can name."""
    x, y = problem

    calls = {"n": 0}
    import spark_gp_tpu.models.likelihood as lk

    original = lk.fit_gpr_device

    def boom(*args, **kw):
        calls["n"] += 1
        raise ValueError("totally novel failure")

    monkeypatch.setattr(lk, "fit_gpr_device", boom)
    monkeypatch.setattr("spark_gp_tpu.models.gpr.fit_gpr_device", boom, raising=False)
    with pytest.raises(ValueError, match="totally novel"):
        _gp().fit(x, y)
    assert calls["n"] == 1  # no re-execution


def test_numeric_exhaustion_keeps_raw_error_on_f64_harness(problem):
    """host_f64 applies to non_finite/not_psd exhaustion only when there
    is precision headroom; on this x64 harness the pre-ladder advice-
    bearing errors must propagate untouched (today's behavior)."""
    import jax

    assert jax.config.jax_enable_x64
    gp = _gp()
    assert not fallback._fit_rung_applies(
        gp, "host_f64", fallback.NON_FINITE_EXHAUSTED, {"native"}
    )
    assert not fallback._fit_rung_applies(
        gp, "host_f64", fallback.NOT_PSD_EXHAUSTED, {"native"}
    )
    # oom/compile DO get the host rung regardless of dtype headroom
    assert fallback._fit_rung_applies(
        gp, "host_f64", fallback.OOM, {"native", "segmented"}
    )


def test_segmented_rung_applicability_gates():
    gp = _gp()
    assert fallback._fit_rung_applies(gp, "segmented", fallback.OOM, {"native"})
    # checkpointed fits are already segmented
    gp_ck = _gp().setCheckpointDir("/tmp/nope")
    assert not fallback._fit_rung_applies(
        gp_ck, "segmented", fallback.OOM, {"native"}
    )
    # batched multi-start has no segment driver
    gp_ms = _gp().setNumRestarts(3)
    assert not fallback._fit_rung_applies(
        gp_ms, "segmented", fallback.OOM, {"native"}
    )
    # host-optimizer fits have no one-dispatch program to segment
    gp_host = _gp(optimizer="host")
    assert not fallback._fit_rung_applies(
        gp_host, "segmented", fallback.OOM, {"native"}
    )


# -- guard breach -----------------------------------------------------------


@pytest.fixture
def forced_guard_breach(monkeypatch):
    from spark_gp_tpu.ops import precision

    monkeypatch.setitem(precision.GUARD_BARS, "mixed", -1.0)
    prev = precision.set_precision_lane("mixed")
    yield
    precision.set_precision_lane(prev)


def test_guard_breach_degrades_to_strict_lane(problem, forced_guard_breach, monkeypatch):
    """GP_GUARD_ACTION=degrade: a guard-breaching mixed-lane fit re-runs
    on the strict lane, guard passing (strict emits no guard), with the
    degradation flagged in provenance."""
    x, y = problem
    monkeypatch.setenv("GP_GUARD_ACTION", "degrade")
    model = _gp().fit(x, y)
    (transition,) = model.degradations
    assert transition["failure_class"] == "guard_breach"
    assert transition["to"] == "strict_lane"
    # the re-fit ran strict: no breach metric, lane recorded strict
    assert model.instr.metrics["precision_lane"] == "strict"
    assert "mixed_precision_guard.breach" not in model.instr.metrics


def test_guard_breach_default_stays_log_only(problem, forced_guard_breach):
    """Default GP_GUARD_ACTION (log): breach warns + metrics, fit
    completes on its lane — pre-ladder behavior bit-for-bit."""
    x, y = problem
    model = _gp().fit(x, y)
    assert model.instr.metrics["mixed_precision_guard.breach"] == 1.0
    assert model.instr.metrics["precision_lane"] == "mixed"
    assert getattr(model, "degradations", None) is None


def test_guard_breach_degrades_distributed_fit_too(
    problem, forced_guard_breach, monkeypatch, eight_device_mesh
):
    """fit_distributed under GP_GUARD_ACTION=degrade: a breaching
    mixed-lane fit re-runs strict through the sharded ladder instead of
    crashing with a raw GuardBreachError."""
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import shard_experts

    x, y = problem
    monkeypatch.setenv("GP_GUARD_ACTION", "degrade")
    data = shard_experts(group_for_experts(x, y, 50), eight_device_mesh)
    model = _gp().setMesh(eight_device_mesh).fit_distributed(data)
    (transition,) = model.degradations
    assert transition["entry"] == "fit_sharded"
    assert transition["failure_class"] == "guard_breach"
    assert transition["to"] == "strict_lane"
    assert model.instr.metrics["precision_lane"] == "strict"
    assert "mixed_precision_guard.breach" not in model.instr.metrics


def test_degradations_survive_save_load_save(problem, tmp_path):
    """The provenance stamp is PERMANENT: a save -> load -> save round
    trip must not launder a degraded fit into a clean one."""
    x, y = problem
    with chaos.oom_after_calls(0, op="one_dispatch"):
        model = _gp().fit(x, y)
    from spark_gp_tpu.models.gpr import GaussianProcessRegressionModel

    first = str(tmp_path / "first.npz")
    model.save(first)
    loaded = GaussianProcessRegressionModel.load(first)
    second = str(tmp_path / "second.npz")
    loaded.save(second)
    reloaded = GaussianProcessRegressionModel.load(second)
    assert reloaded.provenance["degradations"] == model.degradations


def test_guard_action_env_validation(monkeypatch):
    from spark_gp_tpu.ops.precision import guard_action

    assert guard_action() == "log"
    monkeypatch.setenv("GP_GUARD_ACTION", "degrade")
    assert guard_action() == "degrade"
    monkeypatch.setenv("GP_GUARD_ACTION", "explode")
    with pytest.raises(ValueError, match="GP_GUARD_ACTION"):
        guard_action()


# -- predict ladder ---------------------------------------------------------


def test_predict_oom_halves_chunk_to_fit(problem, clean_model):
    """An allocator ceiling the initial chunk exceeds: halvings get the
    dispatch under it and the answer matches the clean path."""
    x, _ = problem
    want = clean_model.predict(x[:500])
    with chaos.oom_after_calls(0, op="predict.chunk", rows_above=130) as fired:
        got = clean_model.predict(x[:500])
    assert fired[0] >= 1
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


def test_predict_oom_falls_to_host_solve(problem, clean_model):
    """Every chunk OOMs: the eager host-f64 rung answers (variance path
    included)."""
    x, _ = problem
    mean_ref, var_ref = clean_model.predict_with_var(x[:200])
    with chaos.oom_after_calls(0, op="predict.chunk") as fired:
        mean, var = clean_model.predict_with_var(x[:200])
    assert fired[0] >= 1
    np.testing.assert_allclose(mean, mean_ref, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(var, var_ref, rtol=1e-9, atol=1e-12)


def test_predict_kill_switch(problem, clean_model, monkeypatch):
    x, _ = problem
    monkeypatch.setenv("GP_FALLBACK", "off")
    with chaos.oom_after_calls(0, op="predict.chunk"):
        with pytest.raises(Exception) as excinfo:
            clean_model.predict(x[:64])
    assert "RESOURCE_EXHAUSTED" in str(excinfo.value)


# -- magic-solve ladder -----------------------------------------------------


def test_magic_solve_oom_falls_to_host(monkeypatch):
    from spark_gp_tpu.kernels.base import Const, EyeKernel
    import spark_gp_tpu.models.ppa as ppa

    rng = np.random.default_rng(0)
    kernel = RBFKernel(1.5) + Const(1e-3) * EyeKernel()
    m = 128
    active = rng.normal(size=(m, 3))
    b = rng.normal(size=(m, m)) / np.sqrt(m)
    u1 = b @ b.T * m * 0.01
    u2 = rng.normal(size=m)
    theta = kernel.init_theta()
    mv_ref, mm_ref = ppa.magic_solve(kernel, theta, active, u1, u2)
    monkeypatch.setattr(ppa, "_DEVICE_SOLVE_MIN_M", 64)
    with chaos.oom_after_calls(0, op="ppa.magic_solve") as fired:
        mv, mm = ppa.magic_solve(kernel, theta, active, u1, u2)
    assert fired[0] == 1
    np.testing.assert_allclose(mv, mv_ref, rtol=1e-12)
    np.testing.assert_allclose(mm, mm_ref, rtol=1e-12)


def test_magic_solve_not_psd_stays_raw(monkeypatch):
    """Numerical failure: the ladder must NOT mask the advice-bearing
    error with a host re-run."""
    from spark_gp_tpu.kernels.base import Const, EyeKernel
    from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException
    import spark_gp_tpu.models.ppa as ppa

    rng = np.random.default_rng(0)
    kernel = RBFKernel(1.5) + Const(1e-3) * EyeKernel()
    active = rng.normal(size=(64, 3))
    with pytest.raises(NotPositiveDefiniteException):
        ppa.magic_solve(
            kernel, kernel.init_theta(), active,
            -np.eye(64), np.zeros(64),
        )


# -- sharded fit ladder -----------------------------------------------------


def test_sharded_fit_degrades_to_single_host(problem, eight_device_mesh):
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import shard_experts

    x, y = problem
    mesh = eight_device_mesh
    data = shard_experts(group_for_experts(x, y, 50), mesh)
    clean = _gp().setMesh(mesh).fit_distributed(data)
    with chaos.oom_after_calls(0, op="sharded") as fired:
        degraded = _gp().setMesh(mesh).fit_distributed(data)
    assert fired[0] == 1
    (transition,) = degraded.degradations
    assert transition["entry"] == "fit_sharded"
    assert transition["to"] == "single_host"
    np.testing.assert_allclose(
        degraded.raw_predictor.theta, clean.raw_predictor.theta, atol=1e-6
    )


def test_dcn_fallback_rung_unavailable_single_process():
    from spark_gp_tpu.parallel import coord

    assert coord.dcn_fallback_available(None) is False
    # an already-bound DCN context rules the rung out too
    assert coord.dcn_fallback_available(object()) is False


# -- chaos injectors --------------------------------------------------------


def test_oom_injector_env_channel(monkeypatch):
    monkeypatch.setenv("GP_CHAOS_OOM_AFTER_CALLS", "1")
    monkeypatch.setenv("GP_CHAOS_OOM_OP", "fit.device")
    chaos.maybe_injected_failure("fit.device.one_dispatch")  # call 1 allowed
    with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
        chaos.maybe_injected_failure("fit.device.one_dispatch")
    # non-matching op untouched
    chaos.maybe_injected_failure("predict.chunk")
    # reset the consumed env state for other tests
    chaos._mp_state.update(
        oom_after=None, oom_op=None, oom_rows_above=None, oom_calls=0,
        oom_fired=None,
    )


def test_compile_injector_is_bounded():
    with chaos.failing_compile(times=2) as fired:
        for _ in range(2):
            with pytest.raises(Exception, match="compilation"):
                chaos.maybe_injected_failure("fit.device.one_dispatch")
        chaos.maybe_injected_failure("fit.device.one_dispatch")  # clean
    assert fired[0] == 2


def test_oom_injector_rows_filter():
    with chaos.oom_after_calls(0, op="predict", rows_above=100) as fired:
        chaos.maybe_injected_failure("predict.chunk", rows=64)  # under
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            chaos.maybe_injected_failure("predict.chunk", rows=256)
    assert fired[0] == 1


# -- exception hygiene lint -------------------------------------------------


def test_exception_hygiene_lint_is_clean():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import check_exception_hygiene

    violations = check_exception_hygiene.find_violations(
        os.path.join(ROOT, "spark_gp_tpu")
    )
    assert violations == [], violations


def test_exception_hygiene_lint_catches_unmarked_broad_except(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import check_exception_hygiene

    bad = tmp_path / "bad.py"
    bad.write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n"
        "try:\n    pass\nexcept (ValueError, BaseException):\n    pass\n"
        "try:\n    pass\nexcept:\n    pass\n"
        "try:\n    pass\nexcept ValueError:\n    pass\n"  # fine
        "try:\n    pass\n"
        "except Exception:  # classified-failure-site: test\n    pass\n"
    )
    violations = check_exception_hygiene.find_violations(str(tmp_path))
    assert len(violations) == 3
    kinds = {v[2] for v in violations}
    assert kinds == {"except Exception", "except BaseException", "bare except"}
    assert check_exception_hygiene.main([str(tmp_path)]) == 1
    assert check_exception_hygiene.main(
        [os.path.join(ROOT, "spark_gp_tpu")]
    ) == 0
