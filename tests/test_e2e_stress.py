"""Regression guards for the BASELINE stress configs on their synthetic
stand-ins (zero-egress environment: the real UCI CSVs are absent, the
loaders generate deterministic same-shape surrogates).

CI-feasible sizes with deliberately loose bounds: these exist so that a
regression in the 784-d RBF path, the high-dimensional ARD path, or the
large-N ingest pipeline fails a test instead of only degrading the
quality artifacts (VERDICT r2 weak #7)."""

import numpy as np

from spark_gp_tpu import (
    ARDRBFKernel,
    GaussianProcessClassifier,
    GaussianProcessRegression,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.data import load_mnist_binary, load_protein, load_year_msd
from spark_gp_tpu.ops.scaling import fit_scaler, scale
from spark_gp_tpu.utils.validation import accuracy, rmse, train_validation_split


def _fit_standin(loader, n, active, max_iter=10):
    x, y = loader(None, n=n)
    rng = np.random.default_rng(13)
    perm = rng.permutation(x.shape[0])
    cut = int(0.8 * x.shape[0])
    tr, te = perm[:cut], perm[cut:]
    mean, std = (np.asarray(s) for s in fit_scaler(x[tr]))
    x = (x - mean) / std
    y_mean, y_std = y[tr].mean(), y[tr].std()
    ys = (y - y_mean) / y_std
    gp = (
        GaussianProcessRegression()
        .setKernel(
            lambda: 1.0 * ARDRBFKernel(x.shape[1], x.shape[1] ** -0.5)
            + WhiteNoiseKernel(0.1, 0.0, 1.0)
        )
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(active)
        .setMaxIter(max_iter)
        .setSeed(13)
    )
    model = gp.fit(x[tr], ys[tr])
    return float(rmse(ys[te], model.predict(x[te])))


def test_protein_standin_bound():
    """9-d ARD path at the protein shape: scaled-target RMSE clearly below
    the trivial predictor (std == 1.0)."""
    assert _fit_standin(load_protein, 2000, 128) < 0.7


def test_year_msd_standin_bound():
    """90-d ARD path at the Year-MSD shape (the widest feature space in the
    configs): must still beat the trivial predictor by a margin."""
    assert _fit_standin(load_year_msd, 2500, 128) < 0.8


def test_mnist_standin_bound():
    """784-d RBF classifier path at the MNIST shape.

    The stand-in plants a calibrated class overlap (Bayes accuracy 0.970,
    datasets.py) so accuracy bars are falsifiable: this tiny config
    (1500 rows, expert/active 50) lands 0.833 healthy — the 0.80 bar
    trips a Laplace-path regression instead of the old always-1.0 pass
    on the separable generator."""
    x, y = load_mnist_binary()
    rng = np.random.default_rng(3)
    sub = rng.choice(x.shape[0], size=1500, replace=False)
    x, y = np.asarray(scale(x[sub])), y[sub]
    gp = (
        GaussianProcessClassifier()
        .setDatasetSizeForExpert(50)
        .setActiveSetSize(50)
        .setKernel(lambda: RBFKernel(10.0))
        .setTol(1e-3)
        .setMaxIter(20)
    )
    score = train_validation_split(
        gp, x, y, train_ratio=0.8, metric=accuracy, seed=13
    )
    assert score > 0.80, score
