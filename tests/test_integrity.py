"""Numerical integrity plane (resilience/integrity.py): SDC defense.

Silent data corruption — a host or device computing WRONG numbers while
heartbeating on time — is invisible to every liveness surface this repo
already has.  This file proves the defense layers on CPU with the
deterministic chaos injectors (``chaos.corrupt_host`` /
``corrupt_device`` / ``corrupt_replica``):

* attested collectives: every DCN payload carries a content digest +
  identity + round binding, verified on every host before the
  deterministic sum — corruption is attributed to the PUBLISHING pid;
* value-level magnitude attestation on array gathers (internally
  consistent bytes from a corrupted compute still get caught);
* duplicate-dispatch spot checks during DCN fits: a sampled round makes
  the target host republish one expert block and its claimed (NLL,
  |grad|); every host recomputes from the published bytes and the trust
  ledger quarantines the minority — the fit stops with a CLASSIFIED
  ``sdc`` error naming the pid, never a silent wrong answer;
* the blocked sharded Cholesky's redundancy tripwire (replicated
  diagonal panels digest-compared across devices);
* serve-side cross-replica answer verification: a corrupt replica's
  (μ, σ²) is out-voted and the replica evicted from the ring;
* model-artifact sha256 sidecars refused on digest mismatch;
* ``GP_INTEGRITY=0`` kills every check and reproduces bit-identical fit
  results.
"""

import glob
import json
import os
import shutil
import threading

import numpy as np
import pytest

from spark_gp_tpu.parallel import coord
from spark_gp_tpu.parallel.coord import (
    DcnContext,
    InProcessCoordClient,
    InProcessCoordStore,
)
from spark_gp_tpu.resilience import chaos, integrity
from spark_gp_tpu.resilience.fallback import SDC, classify_failure


def _counter(key):
    from spark_gp_tpu.obs.runtime import telemetry

    return telemetry.counters.get(key, 0.0)


# -- attestation format ----------------------------------------------------


def test_seal_unseal_roundtrip_and_passthrough():
    payload = b"\x00\x01expert-bytes" * 7
    sealed = integrity.seal("vag/3", 1, payload)
    assert sealed.startswith(b"GPIA1\n")
    assert integrity.unseal("vag/3", 1, sealed) == payload
    # unsealed blobs pass through: peers running GP_INTEGRITY=0 (or
    # direct kv_allgather users outside the plane) interoperate
    assert integrity.unseal("vag/3", 1, payload) == payload


def test_unseal_attributes_every_failure_mode():
    sealed = integrity.seal("vag/3", 1, b"payload")
    flipped = bytearray(sealed)
    flipped[-1] ^= 1  # last byte = payload, not header
    with pytest.raises(integrity.AttestationError) as err:
        integrity.unseal("vag/3", 1, bytes(flipped))
    assert err.value.code == "digest_mismatch" and err.value.pid == 1

    with pytest.raises(integrity.AttestationError) as err:
        integrity.unseal("vag/3", 0, sealed)  # read from the wrong slot
    assert err.value.code == "identity_mismatch"

    with pytest.raises(integrity.AttestationError) as err:
        integrity.unseal("vag/4", 1, sealed)  # round-4 read of a round-3 seal
    assert err.value.code == "stale_replay"
    # every integrity error classifies as the sdc failure class
    assert classify_failure(err.value) == SDC


def test_bounds_violation_flags_finite_magnitudes_only():
    # non-finite values pass: the DCN plane exchanges them deliberately
    # (synchronized per-expert recovery owns that failure mode)
    assert not integrity.bounds_violation([np.array([np.inf, np.nan, 1.0])])
    assert not integrity.bounds_violation([np.array([1e17, -1e17])])
    assert integrity.bounds_violation([np.array([1.0, -1e19])])


def test_tolerance_ladder_rungs():
    a = np.array([1.0, -2.0, 3.5])
    assert integrity.ladder_rung(a, a.copy()) == "exact"
    assert integrity.ladder_rung(a, a * (1.0 + 1e-10)) == "tight"
    assert integrity.ladder_rung(a, a * (1.0 + 1e-7)) == "loose"
    assert integrity.ladder_rung(a, a * 2.0) is None
    # matching non-finite patterns agree exactly (the honest case for a
    # deliberately-exchanged non-finite round)
    nonf = np.array([np.inf, 1.0, np.nan])
    assert integrity.ladder_rung(nonf, nonf.copy()) == "exact"
    assert integrity.ladder_rung(nonf, np.array([1.0, 1.0, np.nan])) is None


def test_spot_check_decisions_are_pure_functions_of_the_round():
    # lockstep safety: every host must reach the identical decision and
    # target with no extra coordination round
    for k in range(8):
        assert integrity.should_spot_check(k, p=1.0)
        assert not integrity.should_spot_check(k, p=0.0)
        assert integrity.spot_check_target(k, 2) == integrity.spot_check_target(k, 2)
    targets = {integrity.spot_check_target(k, 2) for k in range(64)}
    assert targets == {0, 1}  # the audit rotates over every host
    fired = sum(integrity.should_spot_check(k, p=0.25) for k in range(400))
    assert 50 <= fired <= 150  # hash-uniform around p


def test_kill_switch_disables_the_plane(monkeypatch):
    monkeypatch.setenv("GP_INTEGRITY", "0")
    assert not integrity.enabled()
    monkeypatch.setenv("GP_INTEGRITY", "off")
    assert not integrity.enabled()
    monkeypatch.delenv("GP_INTEGRITY", raising=False)
    assert integrity.enabled()


# -- trust ledger ----------------------------------------------------------


def test_trust_ledger_escalation_repayment_and_terminal_quarantine():
    events = []
    ledger = integrity.TrustLedger(
        quarantine_after_strikes=2,
        on_suspect=lambda i, r: events.append(("suspect", i, r)),
        on_quarantined=lambda i, r: events.append(("quarantined", i, r)),
    )
    assert ledger.state(7) == integrity.TRUSTED
    assert ledger.record_disagreement(7, reason="verifier") == integrity.SUSPECT
    # one agreeing observation repays one strike: transient glitches decay
    assert ledger.record_clean(7) == integrity.TRUSTED
    assert ledger.record_disagreement(7) == integrity.SUSPECT
    assert ledger.record_disagreement(7) == integrity.QUARANTINED
    # quarantine is terminal (until forget): clean observations cannot
    # resurrect a host the evidence already convicted
    assert ledger.record_clean(7) == integrity.QUARANTINED
    assert ledger.quarantined() == [7]
    # a definitive verdict (failed digest, contradicted claim) skips the
    # strike budget entirely
    assert (
        ledger.record_disagreement(9, definitive=True, reason="digest")
        == integrity.QUARANTINED
    )
    assert [kind for kind, _, _ in events].count("quarantined") == 2
    assert ledger.snapshot()["quarantined"] == [7, 9]
    ledger.forget(7)  # a replaced host re-enters trusted
    assert ledger.state(7) == integrity.TRUSTED


# -- attested collectives under chaos (two logical hosts) ------------------


def _pair_ctxs(store, timeout_s=30.0):
    return [
        DcnContext(InProcessCoordClient(store, pid, 2), timeout_s=timeout_s)
        for pid in range(2)
    ]


def _on_pair(ctxs, fn):
    """Run ``fn(pid, ctx)`` on two lockstep threads; exceptions are
    collected, not raised."""
    results = {}

    def run(pid):
        try:
            results[pid] = fn(pid, ctxs[pid])
        except BaseException as exc:  # noqa: BLE001 — collected for asserts
            results[pid] = exc

    threads = [
        threading.Thread(target=run, args=(pid,)) for pid in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def test_corrupt_host_bitflip_refused_and_attributed_to_publisher():
    ctxs = _pair_ctxs(InProcessCoordStore())
    before = _counter("integrity.attestation_failures")
    with chaos.corrupt_host(1, kind="bitflip") as fired:
        results = _on_pair(
            ctxs,
            lambda pid, ctx: ctx.allgather_bytes("vag", b"contribution-%d" % pid),
        )
    assert fired[0] >= 1
    for pid in range(2):
        exc = results[pid]
        assert isinstance(exc, integrity.AttestationError), exc
        # attributed to the PUBLISHING host, on every host identically
        assert exc.pid == 1 and exc.code == "digest_mismatch"
        assert classify_failure(exc) == SDC
    assert _counter("integrity.attestation_failures") >= before + 2
    for ctx in ctxs:
        assert 1 in ctx.trust.quarantined()


def test_corrupt_host_stuck_replay_caught_by_round_binding():
    ctxs = _pair_ctxs(InProcessCoordStore())

    def two_rounds(pid, ctx):
        ctx.allgather_bytes("vag", b"round-one-%d" % pid)
        return ctx.allgather_bytes("vag", b"round-two-%d" % pid)

    with chaos.corrupt_host(1, kind="stuck"):
        results = _on_pair(ctxs, two_rounds)
    for pid in range(2):
        exc = results[pid]
        assert isinstance(exc, integrity.AttestationError), exc
        assert exc.pid == 1 and exc.code == "stale_replay"


def test_corrupt_host_scale_caught_by_magnitude_attestation():
    """The wrong-COMPUTE fault: the scale kind corrupts values BEFORE
    packing/sealing, so digests verify — only the value-level bound
    catches it at the gather."""
    ctxs = _pair_ctxs(InProcessCoordStore())
    with chaos.corrupt_host(1, kind="scale", scale=1e19):
        results = _on_pair(
            ctxs, lambda pid, ctx: ctx.allgather_arrays("vag", np.ones(4)),
        )
    for pid in range(2):
        exc = results[pid]
        assert isinstance(exc, integrity.AttestationError), exc
        assert exc.pid == 1 and exc.code == "bounds"
    for ctx in ctxs:
        assert 1 in ctx.trust.quarantined()


def test_clean_gathers_are_transparent_through_the_seal():
    ctxs = _pair_ctxs(InProcessCoordStore())
    results = _on_pair(
        ctxs, lambda pid, ctx: ctx.allreduce_arrays("vag", np.full(3, pid + 1.0)),
    )
    for pid in range(2):
        assert not isinstance(results[pid], BaseException), results[pid]
        np.testing.assert_array_equal(results[pid][0], np.full(3, 3.0))


# -- sharded-Cholesky panel tripwire ---------------------------------------


def _spd(m, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.normal(size=(m, m))
    return a @ a.T + m * np.eye(m)


def test_panel_tripwire_catches_device_corruption(monkeypatch):
    import jax

    from spark_gp_tpu.ops.dist_linalg import sharded_cholesky
    from spark_gp_tpu.parallel.mesh import expert_mesh

    monkeypatch.setenv("GP_INTEGRITY_PANEL_SAMPLE", "1.0")
    mesh = expert_mesh(jax.devices()[:4])
    a = _spd(64)
    checks_before = _counter("integrity.panel_checks")
    # clean checked run: exact factor, tripwire silent
    l = np.asarray(sharded_cholesky(mesh, a, block=8))
    np.testing.assert_allclose(
        np.tril(l), np.linalg.cholesky(a), atol=1e-10
    )
    assert _counter("integrity.panel_checks") > checks_before
    # corrupt ONE device's replicated diagonal-panel copy: the divergence
    # is detected and attributed to that device
    with chaos.corrupt_device(2, scale=1e3):
        with pytest.raises(integrity.PanelMismatchError) as err:
            sharded_cholesky(mesh, a, block=8)
    assert err.value.pid == 2 and err.value.code == "panel_divergence"
    assert classify_failure(err.value) == SDC
    # kill switch: the unchecked program runs the corruption silently —
    # exactly the wrong-answer outcome the tripwire exists to prevent
    monkeypatch.setenv("GP_INTEGRITY", "0")
    with chaos.corrupt_device(2, scale=1e3):
        silent = np.asarray(sharded_cholesky(mesh, a, block=8))
    assert not np.allclose(np.tril(silent), np.linalg.cholesky(a))


# -- the fit-side SDC acceptance proof -------------------------------------


def _half_rows(pid):
    rng = np.random.default_rng(100 + pid)
    n = 144 if pid == 0 else 112
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.01 * rng.normal(size=n)
    return x, y


def _host_mesh(pid):
    # disjoint device halves per logical host (the test_coord idiom):
    # sharing one mesh between two concurrent collective programs can
    # interleave XLA rendezvous schedules and deadlock
    import jax

    from spark_gp_tpu.parallel.mesh import expert_mesh

    devs = jax.devices()
    half = max(1, len(devs) // 2)
    return expert_mesh(devs[pid * half:(pid + 1) * half])


def _local_stack(pid):
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import shard_experts

    x, y = _half_rows(pid)
    mesh = _host_mesh(pid)
    return shard_experts(group_for_experts(x, y, 16), mesh), mesh


def _gp(maxiter=50, ckpt_dir=None):
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(48)
        .setMaxIter(maxiter)
        .setTol(1e-10)
        .setSeed(3)
    )
    if ckpt_dir is not None:
        gp.setCheckpointDir(str(ckpt_dir))
    return gp


def _dcn_fit(pid, ctx, results, maxiter=50):
    coord.set_dcn_context_for_testing(ctx)
    try:
        data, mesh = _local_stack(pid)
        results[pid] = _gp(maxiter).setMesh(mesh).fit_distributed(data)
    except BaseException as exc:  # noqa: BLE001 — collected for asserts
        results[pid] = exc
    finally:
        coord.set_dcn_context_for_testing(None)


def _run_dcn_fit_pair(ctxs, maxiter=50):
    results = {}
    threads = [
        threading.Thread(target=_dcn_fit, args=(pid, ctxs[pid], results, maxiter))
        for pid in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def _union_fit(maxiter=50):
    """The recovery leg: one process fits the union of both hosts' rows —
    the fleet resumed WITHOUT the corrupted host's involvement."""
    import jax.numpy as jnp

    from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts
    from spark_gp_tpu.parallel.mesh import expert_mesh, shard_experts

    mesh = expert_mesh()
    stacks = []
    for pid in range(2):
        x, y = _half_rows(pid)
        stacks.append(shard_experts(group_for_experts(x, y, 16), _host_mesh(pid)))
    union = ExpertData(
        x=jnp.asarray(np.concatenate([np.asarray(s.x) for s in stacks])),
        y=jnp.asarray(np.concatenate([np.asarray(s.y) for s in stacks])),
        mask=jnp.asarray(np.concatenate([np.asarray(s.mask) for s in stacks])),
    )
    return _gp(maxiter).setMesh(mesh).fit_distributed(shard_experts(union, mesh))


def test_sdc_fit_corrupt_host_quarantined_never_silent(monkeypatch, tmp_path):
    """THE fit-side acceptance proof: a 2-host DCN fit where host 1's
    compute is silently corrupted (scale fault — internally consistent
    bytes, valid digests) must NOT complete with a wrong answer.  The
    duplicate-dispatch spot check catches the disagreement, the trust
    ledger quarantines pid 1 on EVERY host identically, the fit stops
    with a classified ``sdc`` error naming the pid, an incident bundle
    records the verdict — and the fleet minus the corrupted host
    reproduces the clean twin's NLL."""
    monkeypatch.setenv("GP_INTEGRITY_DUPCHECK_P", "1.0")  # audit every round
    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path / "incidents"))

    # clean twin: the uncorrupted reference fit
    ref = _run_dcn_fit_pair(_pair_ctxs(InProcessCoordStore()))
    for pid in range(2):
        assert not isinstance(ref[pid], BaseException), ref[pid]
    nll_ref = ref[0].instr.metrics["final_nll"]

    # corrupted twin: host 1 publishes silently-scaled values everywhere
    quarantined_before = _counter("integrity.host_quarantined")
    with chaos.corrupt_host(1, kind="scale", scale=32.0) as fired:
        results = _run_dcn_fit_pair(_pair_ctxs(InProcessCoordStore()))
    assert fired[0] >= 1
    for pid in range(2):
        exc = results[pid]
        assert isinstance(exc, integrity.HostQuarantinedError), exc
        assert exc.pid == 1
        assert classify_failure(exc) == SDC
    assert _counter("integrity.host_quarantined") >= quarantined_before + 1

    # the incident bundle names the corrupted pid and the sdc class
    bundles = glob.glob(str(tmp_path / "incidents" / "*.json"))
    assert bundles, "terminal sdc failure must dump an incident bundle"
    dumped = " ".join(open(p).read() for p in bundles)
    assert '"sdc"' in dumped and "pid 1" in dumped

    # recovery: the fleet without the corrupted host lands on the clean
    # twin's answer (same global data, elastic-counted single process)
    resumed = _union_fit()
    nll_resumed = resumed.instr.metrics["final_nll"]
    assert abs(nll_resumed - nll_ref) <= 5e-3 * max(1.0, abs(nll_ref)), (
        nll_resumed, nll_ref,
    )
    x0, y0 = _half_rows(0)
    rmse = float(np.sqrt(np.mean((resumed.predict(x0) - y0) ** 2)))
    assert rmse < 0.15, rmse


def test_integrity_off_fit_is_bit_identical(monkeypatch):
    """GP_INTEGRITY=0 is a true kill switch: the attested fit and the
    unattested fit produce bit-identical thetas and predictions (the
    plane observes; it never perturbs)."""
    on = _run_dcn_fit_pair(_pair_ctxs(InProcessCoordStore()), maxiter=20)
    assert not isinstance(on[0], BaseException), on[0]
    monkeypatch.setenv("GP_INTEGRITY", "0")
    off = _run_dcn_fit_pair(_pair_ctxs(InProcessCoordStore()), maxiter=20)
    assert not isinstance(off[0], BaseException), off[0]
    np.testing.assert_array_equal(
        on[0].raw_predictor.theta, off[0].raw_predictor.theta
    )
    probe = np.random.default_rng(5).normal(size=(16, 2))
    np.testing.assert_array_equal(on[0].predict(probe), off[0].predict(probe))


# -- serve-side answer verification ----------------------------------------


def _fit_small(seed=3, n=160):
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(40)
        .setSigma2(1e-3)
        .setMaxIter(8)
        .setSeed(seed)
        .fit(x, y)
    )
    return model, x


@pytest.fixture(scope="module")
def small_model(tmp_path_factory):
    model, x = _fit_small()
    path = str(tmp_path_factory.mktemp("integrity") / "model.npz")
    model.save(path)
    return path, model, x


def _three_replica_fleet(path, hedge_after_s=None):
    from spark_gp_tpu.serve import GPServeServer
    from spark_gp_tpu.serve.fleet import FleetMembership, LocalReplica
    from spark_gp_tpu.serve.router import FleetRouter

    store = InProcessCoordStore()
    membership = FleetMembership(
        InProcessCoordClient(store, 0, 1), fleet="integ",
        interval_s=0.05, straggler_after_s=0.15, dead_after_s=0.35,
    )
    replicas = []
    for i in range(3):
        server = GPServeServer(
            max_batch=16, min_bucket=8, max_wait_ms=1.0, capacity=256,
            request_timeout_ms=10_000.0, replica_id=f"r{i}",
        )
        server.register("m", path)
        server.start()
        replica = LocalReplica(server, f"r{i}", membership)
        replica.register()
        replicas.append(replica)
    router = FleetRouter(
        membership,
        transports={r.replica_id: r.transport for r in replicas},
        max_batch=16, min_bucket=8, default_timeout_ms=10_000.0,
        hedge_after_s=hedge_after_s, poll_interval_s=0.0,
    )
    return replicas, router


def test_sdc_serve_corrupt_replica_outvoted_and_evicted(
    small_model, monkeypatch,
):
    """THE serve-side acceptance proof: one of three replicas serves
    silently wrong answers while heartbeating healthily.  With every
    request verified (fraction 1.0), the mismatch is caught within the
    sampling budget, the corrupt replica is out-voted two-to-one and
    evicted from the ring — and ZERO verified requests return a
    mismatched answer (the client always gets the majority)."""
    monkeypatch.setenv("GP_INTEGRITY_SERVE_FRACTION", "1.0")
    path, model, x = small_model
    replicas, router = _three_replica_fleet(path)
    by_id = {r.replica_id: r for r in replicas}
    try:
        probe = x[:4]
        mean_ref = np.asarray(model.predict(probe))
        # corrupt the replica that OWNS this key, so its wrong answer is
        # the one every un-verified request would have returned
        owner = router.route("m", probe.shape[0])[0]
        corrupting = chaos.corrupt_replica(by_id[owner], factor=1e3)
        evicted_before = _counter("integrity.replica_evicted")
        answers = [router.predict("m", probe)[0] for _ in range(6)]
        # zero mismatched answers: every verified request returned the
        # honest majority, including those the corrupt owner answered
        for mean in answers:
            np.testing.assert_allclose(mean, mean_ref, rtol=1e-6)
        assert corrupting.calls >= 1  # the corrupted path actually served
        fleet = router.sample_fleet()
        assert owner in fleet["evicted"]
        assert fleet["trust"]["quarantined"] == [owner]
        assert _counter("integrity.replica_evicted") >= evicted_before + 1
        assert _counter("integrity.replica_mismatch") >= 1
        # post-eviction traffic routes around the corrupt replica
        served_before = corrupting.calls
        for _ in range(4):
            mean, _ = router.predict("m", probe)
            np.testing.assert_allclose(mean, mean_ref, rtol=1e-6)
        assert corrupting.calls == served_before
    finally:
        for r in replicas:
            r.server.stop()
        router.close()


def test_serve_verification_never_evicts_the_last_replica(
    small_model, monkeypatch,
):
    monkeypatch.setenv("GP_INTEGRITY_SERVE_FRACTION", "1.0")
    path, model, x = small_model
    replicas, router = _three_replica_fleet(path)
    try:
        # quarantine every replica by hand: only two may actually leave
        # the ring — a degraded answer beats no answer
        for r in replicas:
            router._trust.record_disagreement(
                r.replica_id, definitive=True, reason="test"
            )
        assert len(router.sample_fleet()["evicted"]) == 2
        mean, _ = router.predict("m", x[:2])
        np.testing.assert_allclose(
            mean, np.asarray(model.predict(x[:2])), rtol=1e-6
        )
    finally:
        for r in replicas:
            r.server.stop()
        router.close()


def test_serve_verification_off_by_kill_switch(small_model, monkeypatch):
    monkeypatch.setenv("GP_INTEGRITY", "0")
    monkeypatch.setenv("GP_INTEGRITY_SERVE_FRACTION", "1.0")
    path, model, x = small_model
    replicas, router = _three_replica_fleet(path)
    by_id = {r.replica_id: r for r in replicas}
    try:
        probe = x[:4]
        owner = router.route("m", probe.shape[0])[0]
        corrupting = chaos.corrupt_replica(by_id[owner], factor=1e3)
        mean, _ = router.predict("m", probe)
        # the silent wrong answer: exactly what GP_INTEGRITY=0 buys back
        assert corrupting.calls >= 1
        assert not np.allclose(mean, np.asarray(model.predict(probe)))
        assert router.sample_fleet()["evicted"] == []
    finally:
        for r in replicas:
            r.server.stop()
        router.close()


# -- model-artifact sidecars -----------------------------------------------


def test_artifact_sidecar_roundtrip_and_corruption(small_model, tmp_path):
    from spark_gp_tpu.utils.checkpoint import CheckpointCorruptError
    from spark_gp_tpu.utils.serialization import load_model

    path, model, x = small_model
    assert os.path.exists(path + integrity.SIDECAR_SUFFIX)
    verified_before = _counter("integrity.artifact_verified")
    loaded = load_model(path)
    assert _counter("integrity.artifact_verified") >= verified_before + 1
    np.testing.assert_allclose(
        np.asarray(loaded.predict(x[:4])), np.asarray(model.predict(x[:4]))
    )
    # swap in DIFFERENT valid model bytes under the same sidecar: the
    # digest gate refuses before np.load can deserialize wrong bytes
    other, _ = _fit_small(seed=11, n=120)
    corrupt_path = str(tmp_path / "corrupt.npz")
    other.save(corrupt_path)
    victim = str(tmp_path / "victim.npz")
    shutil.copy(path, victim)
    shutil.copy(path + integrity.SIDECAR_SUFFIX, victim + integrity.SIDECAR_SUFFIX)
    shutil.copy(corrupt_path, victim)
    with pytest.raises(CheckpointCorruptError) as err:
        load_model(victim)
    assert err.value.code == integrity.ARTIFACT_DIGEST_CODE
    assert integrity.ARTIFACT_DIGEST_CODE in str(err.value)


def test_artifact_sidecar_registry_and_kill_switch(
    small_model, tmp_path, monkeypatch,
):
    from spark_gp_tpu.serve import ModelRegistry
    from spark_gp_tpu.utils.checkpoint import CheckpointCorruptError
    from spark_gp_tpu.utils.serialization import load_model

    path, model, x = small_model
    victim = str(tmp_path / "victim.npz")
    other, _ = _fit_small(seed=11, n=120)
    other.save(victim)
    # stamp a sidecar from the ORIGINAL artifact over the other's bytes
    shutil.copy(path + integrity.SIDECAR_SUFFIX, victim + integrity.SIDECAR_SUFFIX)
    # the serve registry refuses the corrupted artifact at bind time
    reg = ModelRegistry(max_batch=16, min_bucket=8)
    with pytest.raises(CheckpointCorruptError):
        reg.register("victim", victim)
    # legacy artifacts (no sidecar) load without complaint
    os.remove(victim + integrity.SIDECAR_SUFFIX)
    other.save(victim)
    os.remove(victim + integrity.SIDECAR_SUFFIX)
    assert load_model(victim) is not None
    # kill switch: the corrupted pair loads (operator's explicit choice)
    other.save(victim)
    shutil.copy(path + integrity.SIDECAR_SUFFIX, victim + integrity.SIDECAR_SUFFIX)
    monkeypatch.setenv("GP_INTEGRITY", "0")
    assert load_model(victim) is not None
