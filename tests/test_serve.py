"""Registry, server, queue and metrics behavior (spark_gp_tpu.serve),
plus the serving-adjacent contracts in utils/: the .npz format_version
gate and the failed-phase metric marker.
"""

import threading
import time

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.serve import (
    GPServeServer,
    LatencyHistogram,
    ModelRegistry,
    QueueFullError,
    RequestTimeoutError,
    ServingMetrics,
)


def _fit(seed, n=160):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(40)
        .setSigma2(1e-3)
        .setMaxIter(8)
        .setSeed(seed)
        .fit(x, y)
    )
    return model, x


@pytest.fixture(scope="module")
def saved_model(tmp_path_factory):
    model, x = _fit(3)
    path = str(tmp_path_factory.mktemp("serve") / "model.npz")
    model.save(path)
    return path, model, x


# -- registry -------------------------------------------------------------


def test_registry_register_get_and_versions(saved_model):
    path, model, x = saved_model
    reg = ModelRegistry(max_batch=32, min_bucket=8)
    entry = reg.register("m", path)
    assert (entry.name, entry.version) == ("m", 1)
    # warmup ran at load: every bucket compiled exactly once, AOT
    assert entry.predictor.compile_counts == {8: 1, 16: 1, 32: 1}
    assert reg.get("m") is entry and reg.get("m", 1) is entry
    with pytest.raises(KeyError, match="no model named"):
        reg.get("nope")
    with pytest.raises(KeyError, match="no version 9"):
        reg.get("m", 9)
    with pytest.raises(ValueError, match="already registered"):
        reg.register("m", path, version=1)
    mean, var = entry.predict(x[:5])
    np.testing.assert_allclose(
        mean, np.asarray(model.raw_predictor(x[:5])[0]), rtol=1e-10
    )
    assert var is not None


def test_registry_hot_swap_on_reload(saved_model, tmp_path):
    path, model, x = saved_model
    other, _ = _fit(11)
    other_path = str(tmp_path / "other.npz")
    other.save(other_path)

    reg = ModelRegistry(max_batch=16, min_bucket=8)
    v1 = reg.register("m", path)
    v2 = reg.reload("m", other_path)  # new source hot-swapped in
    assert v2.version == 2
    assert reg.get("m") is v2          # latest pointer moved...
    assert reg.get("m", 1) is v1       # ...old version stays addressable
    # the swap is a real model change, not a re-wrap
    m1 = v1.predict(x[:8])[0]
    m2 = v2.predict(x[:8])[0]
    assert not np.allclose(m1, m2)
    # reload without a path re-reads the latest's own source
    v3 = reg.reload("m")
    assert v3.version == 3 and v3.path == other_path
    np.testing.assert_allclose(v3.predict(x[:8])[0], m2, rtol=1e-12)
    with pytest.raises(KeyError, match="to reload"):
        reg.reload("ghost")


# -- server / queue -------------------------------------------------------


def test_server_round_trip_mixed_sizes(saved_model):
    path, model, x = saved_model
    server = GPServeServer(max_batch=32, min_bucket=8, max_wait_ms=1.0)
    server.register("m", path)
    server.start()
    assert server.ready()
    try:
        sizes = [1, 5, 8, 13, 2, 30, 7, 32, 9, 3]
        futs = [
            server.submit("m", x[i * 4 : i * 4 + t])
            for i, t in enumerate(sizes)
        ]
        for (i, t), fut in zip(enumerate(sizes), futs):
            mean, var = fut.result(timeout=10.0)
            ref_mean, ref_var = model.raw_predictor(x[i * 4 : i * 4 + t])
            np.testing.assert_allclose(mean, np.asarray(ref_mean), rtol=1e-10)
            np.testing.assert_allclose(var, np.asarray(ref_var), rtol=1e-10)
        # compile-once invariant holds THROUGH the server path
        entry = server.registry.get("m")
        assert entry.predictor.compile_counts == {8: 1, 16: 1, 32: 1}
        snap = server.snapshot()
        assert snap["counters"]["requests"] == len(sizes)
        assert snap["histograms"]["request_latency_s"]["count"] == len(sizes)
        assert snap["histograms"]["request_latency_s"]["p99"] > 0
        assert 0 < snap["histograms"]["batch_occupancy"]["max"] <= 1.0
    finally:
        server.stop()


def test_server_coalesces_concurrent_requests(saved_model):
    """Requests arriving inside one max-wait window share a dispatch:
    fewer batches than requests under a burst."""
    path, _, x = saved_model
    server = GPServeServer(max_batch=64, min_bucket=8, max_wait_ms=20.0)
    server.register("m", path)
    server.start()
    try:
        futs = [server.submit("m", x[i : i + 2]) for i in range(12)]
        for fut in futs:
            fut.result(timeout=10.0)
        assert server.metrics.counter("batches") < 12
        assert server.metrics.counter("requests") == 12
    finally:
        server.stop()


def test_backpressure_sheds_load_with_clear_error(saved_model):
    path, _, x = saved_model
    # worker never started: the bounded queue must reject at the door
    server = GPServeServer(max_batch=16, capacity=2)
    server.register("m", path)
    server.submit("m", x[:2])
    server.submit("m", x[:2])
    with pytest.raises(QueueFullError, match="at capacity"):
        server.submit("m", x[:2])
    assert server.metrics.counter("shed") == 1


def test_per_request_timeout_expires_in_queue(saved_model):
    path, _, x = saved_model
    server = GPServeServer(max_batch=16, request_timeout_ms=10.0)
    server.register("m", path)
    fut = server.submit("m", x[:2])           # enqueued, nobody serving
    time.sleep(0.05)                          # deadline passes in queue
    server.start()                            # worker now drains it
    with pytest.raises(RequestTimeoutError, match="deadline expired"):
        fut.result(timeout=10.0)
    assert server.metrics.counter("timeouts") == 1
    server.stop()


def test_submit_validation_fails_fast(saved_model):
    path, _, x = saved_model
    server = GPServeServer(max_batch=16)
    server.register("m", path)
    with pytest.raises(KeyError):
        server.submit("ghost", x[:2])
    with pytest.raises(ValueError, match=r"\[t, 3\]"):
        server.submit("m", x[:2, :2])
    # a 1-D row is promoted to [1, p], not rejected
    server.start()
    try:
        mean, var = server.submit("m", x[0]).result(timeout=10.0)
        assert mean.shape == (1,)
    finally:
        server.stop()


def test_stop_then_start_serves_again(saved_model):
    """stop/start are symmetric: a restarted server accepts and answers
    requests instead of shedding with 'queue is stopped'."""
    path, model, x = saved_model
    server = GPServeServer(max_batch=16)
    server.register("m", path)
    server.start()
    server.submit("m", x[:2]).result(timeout=10.0)
    server.stop()
    server.start()
    try:
        mean, _ = server.submit("m", x[:2]).result(timeout=10.0)
        np.testing.assert_allclose(
            mean, np.asarray(model.raw_predictor(x[:2])[0]), rtol=1e-10
        )
    finally:
        server.stop()


def test_stop_drains_queued_requests(saved_model):
    path, model, x = saved_model
    server = GPServeServer(max_batch=16)
    server.register("m", path)
    futs = [server.submit("m", x[i : i + 2]) for i in range(4)]
    server.start()
    server.stop(drain=True)
    for fut in futs:
        mean, _ = fut.result(timeout=1.0)  # already done post-drain
        assert mean.shape == (2,)


# -- metrics --------------------------------------------------------------


def test_latency_histogram_percentiles():
    hist = LatencyHistogram(capacity=100)
    assert hist.snapshot()["count"] == 0
    assert hist.snapshot()["p50"] is None
    for v in range(1, 101):
        hist.observe(float(v))
    snap = hist.snapshot()
    assert snap["count"] == 100
    assert snap["p50"] == pytest.approx(50.5)
    assert snap["p99"] == pytest.approx(99.01)
    assert snap["max"] == 100.0
    # ring buffer: old samples age out, count keeps the lifetime total
    for _ in range(100):
        hist.observe(7.0)
    snap = hist.snapshot()
    assert snap["count"] == 200 and snap["max"] == 7.0


def test_serving_metrics_concurrent_increments():
    metrics = ServingMetrics()

    def hammer():
        for _ in range(500):
            metrics.inc("hits")
            metrics.observe("lat", 0.001)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert metrics.counter("hits") == 2000
    assert metrics.histogram("lat").snapshot()["count"] == 2000
    metrics.set_gauge("depth", 3)
    assert metrics.snapshot()["gauges"]["depth"] == 3.0


# -- utils satellites -----------------------------------------------------


def test_saved_models_carry_format_version(saved_model):
    path, _, _ = saved_model
    from spark_gp_tpu.utils.serialization import FORMAT_VERSION

    with np.load(path, allow_pickle=False) as data:
        assert int(data["format_version"]) == FORMAT_VERSION


def test_future_format_version_raises_friendly_error(saved_model, tmp_path):
    path, _, _ = saved_model
    from spark_gp_tpu.utils.serialization import ModelFormatError, load_model

    with np.load(path, allow_pickle=False) as data:
        arrays = {k: data[k] for k in data.files}
    arrays["format_version"] = np.array(99)
    future_path = str(tmp_path / "future.npz")
    np.savez(future_path, **arrays)
    with pytest.raises(ModelFormatError, match=r"v99.*reads up to v"):
        load_model(future_path)


def test_legacy_file_without_format_version_loads(saved_model, tmp_path):
    path, model, x = saved_model
    from spark_gp_tpu.utils.serialization import load_model

    with np.load(path, allow_pickle=False) as data:
        arrays = {k: data[k] for k in data.files if k != "format_version"}
    legacy_path = str(tmp_path / "legacy.npz")
    np.savez(legacy_path, **arrays)
    loaded = load_model(legacy_path)
    np.testing.assert_allclose(
        loaded.predict(x[:5]), model.predict(x[:5]), rtol=1e-12
    )


def test_failing_phase_records_failed_metric():
    from spark_gp_tpu.utils.instrumentation import Instrumentation

    instr = Instrumentation(name="t")
    with pytest.raises(RuntimeError, match="boom"):
        with instr.phase("serve_warmup"):
            raise RuntimeError("boom")
    assert instr.metrics["serve_warmup.failed"] == 1.0
    assert instr.timings["serve_warmup"] >= 0.0  # timing still recorded
    # a healthy phase leaves no failure marker behind
    with instr.phase("ok_phase"):
        pass
    assert "ok_phase.failed" not in instr.metrics
