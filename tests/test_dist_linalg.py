"""Parity tests for the mesh-sharded blocked Cholesky (ops/dist_linalg.py)."""

import numpy as np
import jax.numpy as jnp

from spark_gp_tpu.ops import dist_linalg


def _spd(rng, m):
    b = rng.normal(size=(m, m)) / np.sqrt(m)
    return b @ b.T * m * 0.1 + np.eye(m)


def test_sharded_cholesky_matches_numpy(rng, eight_device_mesh):
    m = 8 * 16 * 3  # 3 panels per device at block=16
    a = _spd(rng, m)
    l_sh = dist_linalg.sharded_cholesky(eight_device_mesh, jnp.asarray(a), block=16)
    l_np = np.linalg.cholesky(a)
    np.testing.assert_allclose(np.asarray(l_sh), l_np, rtol=1e-9, atol=1e-10)


def test_sharded_solve_vector_and_matrix(rng, eight_device_mesh):
    m = 8 * 16 * 2
    a = _spd(rng, m)
    l_sh = dist_linalg.sharded_cholesky(eight_device_mesh, jnp.asarray(a), block=16)

    v = rng.normal(size=m)
    x = np.asarray(dist_linalg.sharded_chol_solve(eight_device_mesh, l_sh, jnp.asarray(v), block=16))
    np.testing.assert_allclose(a @ x, v, rtol=1e-8, atol=1e-9)

    rhs = rng.normal(size=(m, 7))
    xm = np.asarray(dist_linalg.sharded_chol_solve(eight_device_mesh, l_sh, jnp.asarray(rhs), block=16))
    np.testing.assert_allclose(a @ xm, rhs, rtol=1e-8, atol=1e-9)


def test_sharded_inverse_via_identity_rhs(rng, eight_device_mesh):
    m = 8 * 16
    a = _spd(rng, m)
    l_sh = dist_linalg.sharded_cholesky(eight_device_mesh, jnp.asarray(a), block=16)
    inv = np.asarray(
        dist_linalg.sharded_chol_solve(
            eight_device_mesh, l_sh, jnp.eye(m), block=16
        )
    )
    np.testing.assert_allclose(inv, np.linalg.inv(a), rtol=1e-7, atol=1e-9)


def test_pad_spd_roundtrip(rng, eight_device_mesh):
    """Identity padding: factoring/solving the padded system reproduces the
    unpadded solution exactly on the real block."""
    m, m_pad = 100, 8 * 16
    a = _spd(rng, m)
    a_pad = dist_linalg.pad_spd(a, m_pad)
    l_sh = dist_linalg.sharded_cholesky(eight_device_mesh, jnp.asarray(a_pad), block=16)
    v = np.zeros(m_pad)
    v[:m] = rng.normal(size=m)
    x = np.asarray(dist_linalg.sharded_chol_solve(eight_device_mesh, l_sh, jnp.asarray(v), block=16))
    np.testing.assert_allclose(a @ x[:m], v[:m], rtol=1e-8, atol=1e-9)
    np.testing.assert_allclose(x[m:], 0.0, atol=1e-12)


def test_block_granularity_rejected(rng, eight_device_mesh):
    import pytest

    with pytest.raises(ValueError, match="multiple"):
        dist_linalg.sharded_cholesky(
            eight_device_mesh, jnp.asarray(_spd(rng, 100)), block=16
        )
