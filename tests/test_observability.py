"""The unified observability layer (spark_gp_tpu/obs/): span tracing,
OpenMetrics exposition, runtime telemetry, run journal, metric-name lint.

Grammar-checks the exposition page with a strict line parser (not a
substring sniff — a malformed page fails the real scrapers silently),
exercises span nesting/attribution across threads, forces a recompile to
prove the compile counters move, and drives one end-to-end fit whose run
journal must carry the optimizer phases, a compile event and a memory
gauge (the ISSUE 4 acceptance proof).
"""

import json
import os
import re
import socket
import threading

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.obs import expo, names, runtime, trace
from spark_gp_tpu.serve.metrics import ServingMetrics
from spark_gp_tpu.utils.instrumentation import Instrumentation, maybe_profile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def fitted(tmp_path_factory):
    """One tiny fitted model + its run journal, shared by the e2e tests."""
    journal_dir = str(tmp_path_factory.mktemp("journal"))
    prev = os.environ.get("GP_RUN_JOURNAL_DIR")
    os.environ["GP_RUN_JOURNAL_DIR"] = journal_dir
    try:
        rng = np.random.default_rng(7)
        x = rng.normal(size=(120, 3))
        y = np.sin(x.sum(axis=1))
        model = (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(1.0))
            .setDatasetSizeForExpert(30)
            .setActiveSetSize(30)
            .setSigma2(1e-3)
            .setMaxIter(4)
            .setSeed(3)
            .setOptimizer("host")
            .fit(x, y)
        )
    finally:
        if prev is None:
            os.environ.pop("GP_RUN_JOURNAL_DIR", None)
        else:
            os.environ["GP_RUN_JOURNAL_DIR"] = prev
    path = str(tmp_path_factory.mktemp("model") / "obs_tiny.npz")
    model.save(path)
    return model, path, journal_dir, x


# -- span tracer ------------------------------------------------------------


def test_span_nesting_and_attribution():
    with trace.span("outer", kind="test") as outer:
        with trace.span("inner") as inner:
            assert trace.current_span() is inner
            assert trace.add_event("tick", n=1)
        assert trace.current_span() is outer
    assert inner.parent_id == outer.span_id
    assert inner.trace_id == outer.trace_id
    assert inner.root == "outer"
    assert outer.parent_id is None
    assert outer.attrs == {"kind": "test"}
    assert inner.events[0]["name"] == "tick"
    spans = trace.spans_for_trace(outer.trace_id)
    assert [s.name for s in spans] == ["outer", "inner"]
    tree = trace.span_tree(spans)
    assert len(tree) == 1 and tree[0]["name"] == "outer"
    assert [c["name"] for c in tree[0]["children"]] == ["inner"]


def test_span_contexts_are_thread_isolated():
    """Two threads nesting concurrently must never adopt each other's
    parents: the contextvar stack is per-thread."""
    results = {}
    barrier = threading.Barrier(2, timeout=10)

    def worker(tag):
        with trace.span(f"root_{tag}") as root:
            barrier.wait()  # both roots open simultaneously
            with trace.span(f"child_{tag}") as child:
                barrier.wait()
            results[tag] = (root, child)

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in ("a", "b")
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for tag in ("a", "b"):
        root, child = results[tag]
        assert child.parent_id == root.span_id
        assert child.trace_id == root.trace_id
        assert child.root == f"root_{tag}"
    assert results["a"][0].trace_id != results["b"][0].trace_id


def test_tracing_disabled_is_noop():
    trace.set_tracing(False)
    try:
        before = len(trace.RING.snapshot())
        with trace.span("ghost") as s:
            assert s is trace.NOOP_SPAN
            assert trace.current_span() is None
            assert not trace.add_event("dropped")
        assert len(trace.RING.snapshot()) == before
    finally:
        trace.set_tracing(None)


def test_span_error_status_and_exports(tmp_path):
    with pytest.raises(ValueError):
        with trace.span("doomed") as s:
            raise ValueError("boom")
    assert s.status == "error"
    assert s.events[0] == pytest.approx(s.events[0])  # events recorded
    assert s.events[0]["type"] == "ValueError"

    jsonl = tmp_path / "spans.jsonl"
    n = trace.export_jsonl(str(jsonl), trace.spans_for_trace(s.trace_id))
    assert n == 1
    row = json.loads(jsonl.read_text().splitlines()[0])
    assert row["name"] == "doomed" and row["status"] == "error"

    doc = trace.chrome_trace(trace.spans_for_trace(s.trace_id))
    complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert complete[0]["name"] == "doomed" and complete[0]["dur"] >= 0
    assert instants and instants[0]["name"] == "error"


def test_instrumentation_phase_emits_span():
    instr = Instrumentation(name="spantest")
    with trace.span("fit.spantest") as root:
        with instr.phase("optimize_hypers"):
            pass
    spans = trace.spans_for_trace(root.trace_id)
    phase_spans = [s for s in spans if s.name == "optimize_hypers"]
    assert phase_spans and phase_spans[0].parent_id == root.span_id
    assert phase_spans[0].attrs["instr"] == "spantest"
    assert instr.timings["optimize_hypers"] > 0


def test_instrumentation_thread_safety():
    """The satellite fix: phase/log_metric are read-modify-writes shared
    across serve threads — hammer one instance and check nothing is lost."""
    instr = Instrumentation(name="hammer")
    n_threads, n_iters = 8, 200

    def worker(idx):
        for i in range(n_iters):
            with instr.phase("contended"):
                pass
            instr.log_metric(f"restart_{idx}_nll", float(i))

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # every thread's last write survived, and no timing increment vanished
    for idx in range(n_threads):
        assert instr.metrics[f"restart_{idx}_nll"] == float(n_iters - 1)
    assert instr.timings["contended"] > 0


# -- OpenMetrics exposition -------------------------------------------------

_FAMILY = r"[a-z_:][a-z0-9_:]*"
_META_RE = re.compile(rf"^# (TYPE|HELP|UNIT) ({_FAMILY})( .+)?$")
_VALUE = r"(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|NaN|\+Inf|-Inf)"
_SAMPLE_RE = re.compile(rf"^({_FAMILY})(\{{([^{{}}]*)\}})? {_VALUE}$")
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"$')


def _parse_openmetrics(page: str) -> dict:
    """Strict line-grammar parse; returns {family: {type, samples}} where
    samples is [(sample_name, labels_text, value)].  Raises AssertionError
    on any spec violation this page could exhibit."""
    lines = page.splitlines()
    assert lines, "empty page"
    assert lines[-1] == "# EOF", f"page must end with # EOF, got {lines[-1]!r}"
    assert page.endswith("\n"), "page must end with a newline"
    families: dict = {}
    current = None
    for line in lines[:-1]:
        meta = _META_RE.match(line)
        if meta:
            kind, family = meta.group(1), meta.group(2)
            if kind == "TYPE":
                assert family not in families, f"duplicate TYPE for {family}"
                families[family] = {"type": meta.group(3).strip(), "samples": []}
                current = family
            else:
                assert current == family, f"{kind} outside its family block"
            continue
        sample = _SAMPLE_RE.match(line)
        assert sample, f"line matches neither metadata nor sample: {line!r}"
        name, labels_text = sample.group(1), sample.group(3)
        assert current is not None and name.startswith(current), (
            f"sample {name} before its TYPE line (current family {current})"
        )
        if labels_text:
            for part in labels_text.split(","):
                assert _LABEL_RE.match(part), f"bad label {part!r}"
        families[current]["samples"].append(
            (name, labels_text or "", float(sample.group(4).replace("Inf", "inf")))
        )
    # per-type sample-name rules
    for family, info in families.items():
        suffixes = {name[len(family):] for name, _, _ in info["samples"]}
        if info["type"] == "counter":
            assert suffixes == {"_total"}, (family, suffixes)
        elif info["type"] == "gauge":
            assert suffixes == {""}, (family, suffixes)
        elif info["type"] == "histogram":
            assert suffixes <= {"_bucket", "_count", "_sum"}, (family, suffixes)
    return families


def _exercised_metrics() -> ServingMetrics:
    m = ServingMetrics(name="expotest")
    m.inc("requests", 5)
    m.inc("queue.shed.deadline", 2)
    m.set_gauge("queue_depth", 3)
    m.set_gauge("breaker.open.modelx", 1.0)
    for v in (0.001, 0.004, 0.2, 1.5):
        m.observe("request_latency_s", v)
    with m.phase("load.modelx"):
        pass
    m.log_metric("final_nll", -12.5)
    m.metrics["precision_lane"] = "strict"  # string-valued diagnostic
    return m


def test_openmetrics_grammar_and_semantics():
    page = expo.render_openmetrics(_exercised_metrics())
    families = _parse_openmetrics(page)
    assert families["gp_requests"]["type"] == "counter"
    assert families["gp_requests"]["samples"] == [("gp_requests_total", "", 5.0)]
    assert families["gp_queue_shed_deadline"]["samples"][0][2] == 2.0
    assert families["gp_queue_depth"]["type"] == "gauge"
    # the histogram: cumulative buckets, monotone, +Inf == count, sum right
    hist = families["gp_request_latency_seconds"]
    assert hist["type"] == "histogram"
    buckets = [
        (lbl, v) for name, lbl, v in hist["samples"]
        if name.endswith("_bucket")
    ]
    counts = [v for _, v in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert buckets[-1][0].endswith('le="+Inf"')
    count = [v for n, _, v in hist["samples"] if n.endswith("_count")][0]
    total = [v for n, _, v in hist["samples"] if n.endswith("_sum")][0]
    assert buckets[-1][1] == count == 4
    assert total == pytest.approx(0.001 + 0.004 + 0.2 + 1.5)
    # phase timings ride as one labeled counter family
    phases = families["gp_phase_seconds"]
    assert any('phase="load.modelx"' in lbl for _, lbl, _ in phases["samples"])
    # numeric fit metrics under gp_fit_metric, strings under gp_fit_info
    assert any(
        'key="final_nll"' in lbl for _, lbl, _ in
        families["gp_fit_metric"]["samples"]
    )
    assert any(
        'value="strict"' in lbl for _, lbl, _ in
        families["gp_fit_info"]["samples"]
    )


def test_histogram_series_stay_cumulative_past_window():
    """The _bucket/_count/_sum series must be MONOTONIC counters over the
    histogram's lifetime, not the recency window: Prometheus rate() and
    histogram_quantile() read a decreasing count as a counter reset."""
    m = ServingMetrics(name="cumtest", histogram_capacity=8)
    for _ in range(20):
        m.observe("request_latency_s", 0.002)  # 20 obs >> capacity 8
    bounds, counts, count, total = m.histogram("request_latency_s").cumulative()
    assert count == 20, "count must not freeze at the window capacity"
    assert total == pytest.approx(20 * 0.002)
    # distribution shift: past observations never leave their buckets
    le_0005 = counts[bounds.index(0.005)]
    assert le_0005 == 20
    for _ in range(5):
        m.observe("request_latency_s", 0.9)
    bounds2, counts2, count2, _ = m.histogram("request_latency_s").cumulative()
    assert count2 == 25
    assert counts2[bounds2.index(0.005)] == le_0005, (
        "bucket counts must never decrease"
    )
    page = expo.render_openmetrics(m)
    families = _parse_openmetrics(page)
    hist = families["gp_request_latency_seconds"]
    count_sample = [v for n, _, v in hist["samples"] if n.endswith("_count")]
    assert count_sample == [25.0]


def test_run_journals_do_not_clobber_across_fits(tmp_path):
    from spark_gp_tpu.obs.runtime import write_run_journal

    instr = Instrumentation(name="ClobberProbe")
    paths = set()
    for _ in range(2):
        with trace.span("fit.ClobberProbe") as root:
            pass
        journal = write_run_journal(
            instr, root, None, journal_dir=str(tmp_path)
        )
        assert journal["path"] is not None
        paths.add(journal["path"])
    assert len(paths) == 2, "two fits must persist two distinct journals"
    assert all(os.path.exists(p) for p in paths)


def test_artifact_retention_prunes_oldest_per_pattern(tmp_path):
    """GP_ARTIFACT_RETENTION=K keeps the newest K run journals AND host
    checkpoints (per pattern, by mtime); unrelated files are untouched."""
    from spark_gp_tpu.obs.runtime import prune_artifacts

    def touch(name, age_s):
        path = tmp_path / name
        path.write_text("{}")
        stamp = 1_700_000_000 - age_s
        os.utime(path, (stamp, stamp))
        return path

    journals = [touch(f"run_journal_gp-{i}.json", age_s=i * 60) for i in range(4)]
    states = [touch(f"lbfgs_state_tag{i}.json", age_s=i * 60) for i in range(3)]
    keeper = touch("model.npz", age_s=9999)  # not an artifact pattern

    removed = prune_artifacts(str(tmp_path), keep=2)
    assert removed == 3  # 2 old journals + 1 old checkpoint
    assert [p.exists() for p in journals] == [True, True, False, False]
    assert [p.exists() for p in states] == [True, True, False]
    assert keeper.exists()


def test_artifact_retention_protects_fresh_write_on_mtime_tie(tmp_path):
    """mtime has filesystem-tick granularity: a same-tick neighbor whose
    name sorts higher must not win the tiebreak against the artifact the
    GC was invoked FOR (regression — keep=1 deleted the just-written
    journal and kept a stale lexically-larger one)."""
    from spark_gp_tpu.obs.runtime import prune_artifacts

    fresh = tmp_path / "run_journal_aaa-fresh.json"
    stale = tmp_path / "run_journal_zzz-stale.json"
    for path in (stale, fresh):
        path.write_text("{}")
        os.utime(path, (1_700_000_000, 1_700_000_000))  # identical tick

    assert prune_artifacts(str(tmp_path), keep=1, protect=str(fresh)) == 1
    assert fresh.exists() and not stale.exists()


def test_artifact_retention_is_opt_in_via_env(tmp_path, monkeypatch):
    from spark_gp_tpu.obs.runtime import prune_artifacts, write_run_journal

    monkeypatch.delenv("GP_ARTIFACT_RETENTION", raising=False)
    for i in range(3):
        (tmp_path / f"run_journal_old-{i}.json").write_text("{}")
    assert prune_artifacts(str(tmp_path)) == 0  # unset: operator-managed

    monkeypatch.setenv("GP_ARTIFACT_RETENTION", "nonsense")
    assert prune_artifacts(str(tmp_path)) == 0  # invalid: disabled, no raise

    # the journal writer applies retention after each persist
    monkeypatch.setenv("GP_ARTIFACT_RETENTION", "1")
    instr = Instrumentation(name="RetentionProbe")
    with trace.span("fit.RetentionProbe") as root:
        pass
    journal = write_run_journal(instr, root, None, journal_dir=str(tmp_path))
    survivors = sorted(
        p for p in os.listdir(tmp_path) if p.startswith("run_journal_")
    )
    assert survivors == [os.path.basename(journal["path"])]


def test_openmetrics_pattern_collapses_to_label():
    page = expo.render_openmetrics(_exercised_metrics())
    families = _parse_openmetrics(page)
    # breaker.open.modelx -> ONE family with a model label, not a family
    # per model name (obs/names.py pattern labels)
    breaker = families["gp_breaker_open"]
    assert breaker["samples"] == [("gp_breaker_open", 'model="modelx"', 1.0)]


def test_scrape_listener_answers_http():
    metrics = _exercised_metrics()
    listener = expo.ScrapeListener(
        lambda: expo.render_openmetrics(metrics), port=0
    )
    try:
        with socket.create_connection(("127.0.0.1", listener.port), 5) as conn:
            conn.sendall(b"GET /metrics HTTP/1.0\r\n\r\n")
            conn.settimeout(5)
            blob = b""
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                blob += chunk
    finally:
        listener.stop()
    head, _, body = blob.partition(b"\r\n\r\n")
    assert b"200 OK" in head.splitlines()[0]
    assert expo.CONTENT_TYPE.encode() in head
    _parse_openmetrics(body.decode("utf-8"))


# -- runtime telemetry ------------------------------------------------------


def test_compile_counter_increments_on_forced_recompile():
    import jax
    import jax.numpy as jnp

    runtime.telemetry.install()

    @jax.jit
    def probe(a):
        return (a * 2.0).sum()

    def traces():
        return runtime.telemetry.snapshot()["counters"].get(
            "compile.traces", 0.0
        )

    base = traces()
    with trace.span("recompile.test"):
        probe(jnp.ones((23,)))  # first shape: one trace
        after_first = traces()
        probe(jnp.ones((23,)))  # warm dispatch: no trace
        assert traces() == after_first
        probe(jnp.ones((29,)))  # forced recompile: a NEW shape retraces
    after_second = traces()
    assert after_first >= base + 1
    assert after_second >= after_first + 1
    # attribution followed the active trace root
    by_entry = runtime.telemetry.snapshot()["per_entry"]["compile.traces"]
    assert by_entry.get("recompile.test", 0.0) >= 2


def test_memory_sampling_always_produces_a_gauge():
    sample = runtime.telemetry.sample_memory()
    # device HBM stats on TPU/GPU, host RSS fallback everywhere — some
    # memory gauge must exist on every backend
    assert sample, "no memory gauge from any source"
    assert all(k.startswith("memory.") for k in sample)
    assert any(v > 0 for v in sample.values())


# -- run journal (the fit-side acceptance proof) ----------------------------


def _tree_nodes(nodes):
    for node in nodes:
        yield node
        yield from _tree_nodes(node["children"])


def test_run_journal_end_to_end(fitted):
    model, _, journal_dir, _ = fitted
    journal = model.run_journal
    assert journal["format"] == runtime.JOURNAL_FORMAT
    # persisted next to the checkpoints (GP_RUN_JOURNAL_DIR here) under a
    # per-fit unique name: repeated fits sharing a dir must not clobber
    path = journal["path"]
    assert path is not None and os.path.exists(path)
    assert os.path.dirname(path) == journal_dir
    assert os.path.basename(path).startswith(
        "run_journal_GaussianProcessRegression-"
    )
    with open(path) as fh:
        on_disk = json.load(fh)
    assert on_disk["format"] == journal["format"]

    # the span tree contains the optimizer phases under the fit root
    all_nodes = list(_tree_nodes(journal["spans"]))
    by_name = {node["name"] for node in all_nodes}
    assert "fit.GaussianProcessRegression" in by_name
    assert {"group_experts", "optimize_hypers", "magic_solve"} <= by_name

    # >= 1 compile event: counted in the deltas AND visible as span events
    assert journal["compiles"]["compile.traces"] >= 1
    compile_events = [
        e for node in all_nodes for e in node["events"]
        if e["name"].startswith("compile.")
    ]
    assert compile_events, "no compile span events in the tree"

    # a memory gauge was sampled on phase boundaries
    assert journal["memory"]["peak"], journal["memory"]
    assert journal["memory"]["samples"]
    assert {s["phase"] for s in journal["memory"]["samples"]} >= {
        "start", "optimize_hypers", "end",
    }
    assert journal["precision_lane"] in ("strict", "mixed", "fast")


def test_laplace_family_journal_captures_screen_quarantine(tmp_path, monkeypatch):
    """The observation shell must wrap the WHOLE post-validation fit body
    on every family (not just GPR): the group_experts phase — and any
    data-screen quarantine fired inside it — belongs to the fit's root
    span, so the journal's quarantine.events carries the transition."""
    from spark_gp_tpu import GaussianProcessClassifier
    from spark_gp_tpu.parallel.experts import num_experts_for
    from spark_gp_tpu.resilience.chaos import poison_expert

    monkeypatch.setenv("GP_RUN_JOURNAL_DIR", str(tmp_path))
    rng = np.random.default_rng(5)
    x = rng.normal(size=(120, 2))
    y = (x.sum(axis=1) > 0).astype(np.float64)
    n_e = num_experts_for(len(x), 30)
    xp, yp = poison_expert(x, y, expert=1, num_experts=n_e, kind="nan")
    model = (
        GaussianProcessClassifier()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(20)
        .setSigma2(1e-2)
        .setMaxIter(3)
        .setSeed(3)
        .fit(xp, yp)
    )
    journal = model.run_journal
    by_name = {node["name"] for node in _tree_nodes(journal["spans"])}
    assert "fit.GaussianProcessClassifier" in by_name
    assert "group_experts" in by_name, "grouping phase outside the root span"
    events = journal["quarantine"]["events"]
    assert any(e["name"] == "experts.quarantined" for e in events), events
    assert journal["quarantine"]["experts_quarantined"] >= 1


# -- serve CLI: openmetrics verb (the serve-side acceptance proof) ----------


def test_serve_stream_openmetrics_verb(fitted):
    import io

    from spark_gp_tpu.serve.__main__ import _serve_stream
    from spark_gp_tpu.serve.server import GPServeServer

    _, path, _, x = fitted
    server = GPServeServer(max_batch=8, min_bucket=4, request_timeout_ms=None)
    server.register("tiny", path)
    server.start()
    try:
        out = io.StringIO()
        lines = [
            json.dumps({"id": 1, "model": "tiny", "x": x[:3].tolist()}),
            json.dumps({"cmd": "metrics", "format": "openmetrics"}),
            json.dumps({"cmd": "metrics", "format": "nope"}),
            json.dumps({"cmd": "shutdown"}),
        ]
        assert _serve_stream(server, lines, out, threading.Lock())
    finally:
        server.stop()
    replies = [json.loads(line) for line in out.getvalue().splitlines()]
    assert replies[0]["id"] == 1 and len(replies[0]["mean"]) == 3
    page_reply = replies[1]
    assert page_reply["event"] == "metrics"
    assert page_reply["format"] == "openmetrics"
    families = _parse_openmetrics(page_reply["body"])
    # the acceptance series: queue, breaker, latency histogram
    assert "gp_queue_depth" in families
    assert families["gp_breaker_open"]["samples"] == [
        ("gp_breaker_open", 'model="tiny"', 0.0)
    ]
    assert families["gp_request_latency_seconds"]["type"] == "histogram"
    assert families["gp_requests"]["samples"][0][2] >= 1
    # runtime telemetry rode along (serve bucket traces from the warmup)
    assert families["gp_compile_bucket_traces"]["samples"][0][2] >= 1
    assert "unknown metrics format" in replies[2]["error"]


# -- GP_TRACE_DIR (satellite: profiler capture without code change) ---------


def test_maybe_profile_honors_gp_trace_dir(tmp_path, monkeypatch):
    import jax.numpy as jnp

    target = tmp_path / "profile"
    monkeypatch.setenv("GP_TRACE_DIR", str(target))
    with maybe_profile(None):
        jnp.arange(8).sum().block_until_ready()
    produced = [
        os.path.join(dirpath, name)
        for dirpath, _, filenames in os.walk(target)
        for name in filenames
    ]
    assert produced, "GP_TRACE_DIR set but no profiler artifacts captured"
    # and the env must be read at CALL time, not cached at import
    monkeypatch.delenv("GP_TRACE_DIR")
    with maybe_profile(None):
        pass  # no jax.profiler context — would raise on nested traces


# -- metric-name catalog + lint ---------------------------------------------


def test_catalog_is_self_consistent():
    seen = set()
    for spec in names.CATALOG:
        assert names.grammar_ok(spec.key), spec.key
        assert spec.kind in (
            "counter", "gauge", "histogram", "metric", "phase",
            "event", "info",
        )
        assert spec.key not in seen, f"duplicate catalog entry {spec.key}"
        seen.add(spec.key)
    assert names.lookup("breaker.open.anything").label == "model"
    assert names.lookup("restart_3_nll").kind == "metric"
    assert names.lookup("no.such.key") is None
    assert names.is_registered("restart_*_nll")
    assert not names.is_registered("restart_*")


def test_metric_names_lint_is_clean():
    import sys

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_metric_names
    finally:
        sys.path.pop(0)
    violations = check_metric_names.find_violations(
        os.path.join(ROOT, "spark_gp_tpu")
    )
    assert violations == [], "\n".join(
        f"{p}:{n}: {k}: {why}" for p, n, k, why in violations
    )


def test_metric_names_lint_catches_violations(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_metric_names
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        'metrics.inc("Not.Lower.Case")\n'
        'instr.log_metric(f"unregistered.{x}.key", 1.0)\n'
        'instr.metrics["also.unregistered"] = 1.0\n'
        'metrics.inc("exempted.key")  # metric-name-ok\n'
        'instr.log_metric(variable_key, 1.0)\n'  # not statically checkable
    )
    violations = check_metric_names.find_violations(str(tmp_path))
    keys = {k for _, _, k, _ in violations}
    assert keys == {"Not.Lower.Case", "unregistered.*.key", "also.unregistered"}
