"""Expert aggregation plane (models/aggregation.py).

Five contract groups from ISSUE 16: the ``GP_AGG_POLICY=poe`` kill
switch is bit-for-bit with the unconfigured path; gPoE/rBCM/healed
predict-time aggregation matches numpy closed forms on tiny E (resolved
through the policy lane, not just the explicit ``mode=``); the weighted
NLL composes with quarantine masking (a masked expert contributes
exactly 0 whatever its weight); host / one-dispatch device / sharded
fits land the same theta under uniform fractional weights; and fit-time
correlation-aware selection drops the duplicated half of a redundant
stack at no held-out quality loss.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from spark_gp_tpu import (
    GaussianProcessRegression,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.models import aggregation as agg
from spark_gp_tpu.models.likelihood import batched_nll
from spark_gp_tpu.models.poe import make_poe_predictor
from spark_gp_tpu.parallel.experts import group_for_experts
from spark_gp_tpu.resilience.quarantine import (
    ExpertQuarantineError,
    renorm_factor,
)


def _make_kernel():
    return 1.0 * RBFKernel(0.7, 1e-6, 10) + WhiteNoiseKernel(0.1, 0.0, 1.0)


def _regression(rng, n=240):
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    return x, y


def _estimator(optimizer="host", mesh=None):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(30)
        .setSigma2(1e-3)
        .setMaxIter(5)
        .setSeed(7)
        .setOptimizer(optimizer)
    )
    if mesh is not None:
        gp.setMesh(mesh)
    return gp


def _duplicated(rng, base_n=160):
    """Pairwise-duplicated rows: under round-robin grouping with an even
    expert count, expert 2j+1 holds exactly expert 2j's points — half the
    stack is redundant by construction."""
    xb = rng.normal(size=(base_n, 2))
    yb = np.sin(xb.sum(axis=1)) + 0.05 * rng.normal(size=base_n)
    return np.repeat(xb, 2, axis=0), np.repeat(yb, 2)


# -- the poe kill switch ----------------------------------------------------


def test_poe_policy_is_bit_for_bit(rng, monkeypatch):
    """GP_AGG_POLICY=poe (the explicit kill switch) must reproduce the
    unconfigured fit AND its predictions bitwise — the plane's default
    path is today's code, not a near-copy."""
    x, y = _regression(rng)
    xq = rng.normal(size=(9, 2))

    monkeypatch.delenv("GP_AGG_POLICY", raising=False)
    base = _estimator().fit(x, y)
    assert base.instr.metrics["agg.policy"] == "poe"

    monkeypatch.setenv("GP_AGG_POLICY", "poe")
    pinned = _estimator().fit(x, y)
    assert pinned.instr.metrics["agg.policy"] == "poe"

    np.testing.assert_array_equal(
        np.asarray(base.raw_predictor.theta),
        np.asarray(pinned.raw_predictor.theta),
    )
    np.testing.assert_array_equal(base.predict(xq), pinned.predict(xq))


def test_policy_lane_resolution_order(monkeypatch):
    """scope > process override > env > poe default, and the jit key is
    the resolved policy."""
    monkeypatch.delenv("GP_AGG_POLICY", raising=False)
    assert agg.active_agg_policy() == "poe"
    assert not agg.policy_engaged()
    # an unengaged plane leaves mode=None consumers on their own default
    assert agg.resolve_predictor_mode(None, default="rbcm") == "rbcm"

    monkeypatch.setenv("GP_AGG_POLICY", "gpoe")
    assert agg.active_agg_policy() == "gpoe"
    assert agg.policy_engaged()
    assert agg.resolve_predictor_mode(None, default="rbcm") == "gpoe"

    prev = agg.set_agg_policy("rbcm")
    try:
        assert agg.active_agg_policy() == "rbcm"
        with agg.agg_policy_scope("healed"):
            assert agg.active_agg_policy() == "healed"
            assert agg.agg_jit_key() == "healed"
        assert agg.active_agg_policy() == "rbcm"
    finally:
        agg.set_agg_policy(prev)
    # explicit mode always wins over the lane
    assert agg.resolve_predictor_mode("poe") == "poe"

    with pytest.raises(ValueError):
        agg.set_agg_policy("bayes")


# -- closed-form parity through the policy lane -----------------------------


def _dense_posterior(kernel, theta, xs, ys, x_test):
    t = jnp.asarray(theta)
    k = np.asarray(kernel.gram(t, jnp.asarray(xs)), dtype=np.float64)
    k_cross = np.asarray(
        kernel.cross(t, jnp.asarray(x_test), jnp.asarray(xs)),
        dtype=np.float64,
    )
    k_ss = np.asarray(
        kernel.self_diag(t, jnp.asarray(x_test)), dtype=np.float64
    )
    sol = np.linalg.solve(k, np.asarray(ys, dtype=np.float64))
    mean = k_cross @ sol
    var = k_ss - np.einsum("ts,st->t", k_cross, np.linalg.solve(k, k_cross.T))
    return mean, var, k_ss


@pytest.mark.parametrize("mode", ["gpoe", "rbcm", "healed"])
def test_policy_closed_form_parity(rng, mode):
    """Each robust policy — resolved through the aggregation LANE with
    ``mode=None`` — matches its numpy closed form built from dense
    per-expert posteriors."""
    n, s = 30, 10  # E = 3
    x, y = _regression(rng, n=n)
    x_test = rng.normal(size=(6, 2))
    kernel = _make_kernel()
    theta = kernel.init_theta()

    with agg.agg_policy_scope(mode):
        pred = make_poe_predictor(kernel, theta, x, y, s, mode=None)
        assert pred.mode == mode
        mean, var = pred.predict_with_var(x_test)

    e = 3
    mus, vs = [], []
    for j in range(e):
        members = np.arange(j, n, e)
        m_j, v_j, k_ss = _dense_posterior(
            kernel, theta, x[members], y[members], x_test
        )
        mus.append(m_j)
        vs.append(v_j)
    mus, vs = np.asarray(mus), np.asarray(vs)

    if mode == "gpoe":
        prec = np.sum((1.0 / e) / vs, axis=0)
        m_ref = np.sum((1.0 / e) * mus / vs, axis=0) / prec
    else:
        beta = 0.5 * (np.log(k_ss)[None, :] - np.log(vs))
        if mode == "healed":
            beta = np.maximum(beta, 0.0)
            bs = beta.sum(axis=0)
            prec = np.where(
                bs > 0, np.sum(beta / vs, axis=0) / np.where(bs > 0, bs, 1.0),
                1.0 / k_ss,
            )
            m_ref = np.where(
                bs > 0,
                np.sum(beta * mus / vs, axis=0) / np.where(bs > 0, bs, 1.0),
                0.0,
            ) / prec
        else:  # rbcm
            prec = np.sum(beta / vs, axis=0) + (1.0 - beta.sum(axis=0)) / k_ss
            m_ref = np.sum(beta * mus / vs, axis=0) / prec

    np.testing.assert_allclose(mean, m_ref, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(var, 1.0 / prec, rtol=1e-6, atol=1e-8)


def test_healed_is_convex_never_sharper_than_best_expert(rng):
    """The healed product's variance can never undercut its sharpest
    expert (a convex combination of precisions) — the defining repair of
    PoE/rBCM overconfidence."""
    n, s = 40, 10  # E = 4
    x, y = _regression(rng, n=n)
    x_test = rng.normal(size=(25, 2)) * 2.0  # include points far from data
    kernel = _make_kernel()
    theta = kernel.init_theta()

    _, var = make_poe_predictor(
        kernel, theta, x, y, s, mode="healed"
    ).predict_with_var(x_test)
    e = 4
    expert_vars = np.stack([
        _dense_posterior(kernel, theta, x[np.arange(j, n, e)],
                         y[np.arange(j, n, e)], x_test)[1]
        for j in range(e)
    ])
    assert np.all(var >= expert_vars.min(axis=0) * (1.0 - 1e-8))


# -- weighted NLL + quarantine composition ----------------------------------


def test_masked_expert_contributes_exactly_zero(rng):
    """Quarantine masking IS w_e = 0: a masked expert's inert identity
    block contributes NLL_e = 0 exactly, so its weight slot is
    irrelevant — the two mechanisms compose through one reduction."""
    x, y = _regression(rng, n=160)
    data = group_for_experts(x, y, 40)  # E = 4
    kernel = _make_kernel()
    theta = jnp.asarray(kernel.init_theta(), dtype=data.x.dtype)

    masked = data.with_experts_masked(np.array([False, True, False, False]))
    w_zero = jnp.asarray([1.0, 0.0, 1.0, 1.0], dtype=data.x.dtype)
    w_wild = jnp.asarray([1.0, 7.25, 1.0, 1.0], dtype=data.x.dtype)
    nll_zero = float(batched_nll(kernel, theta, masked, weights=w_zero))
    nll_wild = float(batched_nll(kernel, theta, masked, weights=w_wild))
    assert nll_zero == nll_wild  # w * 0 == 0 bitwise, not approximately

    # and the weighted sum equals the manual per-expert recomputation
    per_expert = [
        float(batched_nll(
            kernel, theta,
            data.with_experts_masked(np.arange(4) != j),
        ))
        for j in range(4)
    ]
    w = jnp.asarray([0.25, 0.5, 1.0, 2.0], dtype=data.x.dtype)
    manual = float(np.dot(np.asarray(w), per_expert))
    weighted = float(batched_nll(kernel, theta, data, weights=w))
    np.testing.assert_allclose(weighted, manual, rtol=1e-10)


def test_weighted_renorm_generalizes_quarantine_factor():
    """Uniform unit weights with d zeros reduce weighted_renorm_factor to
    quarantine's count-based renorm_factor exactly."""
    w = np.array([1.0, 0.0, 1.0, 1.0])
    assert agg.weighted_renorm_factor(w, 4) == renorm_factor(4, 1)
    assert agg.weighted_renorm_factor(np.ones(6), 6) == 1.0
    with pytest.raises(ExpertQuarantineError):
        agg.weighted_renorm_factor(np.zeros(3), 3)


def test_effective_expert_count():
    assert agg.effective_expert_count(np.ones(8)) == pytest.approx(8.0)
    assert agg.effective_expert_count([1.0, 0.0, 0.0]) == pytest.approx(1.0)
    assert agg.effective_expert_count(np.zeros(4)) == 0.0
    # halving half the weights: (3)^2 / (2*1 + 2*0.25) = 3.6
    assert agg.effective_expert_count([1, 1, 0.5, 0.5]) == pytest.approx(3.6)


def test_weighted_expert_sum_none_is_exact_sum(rng):
    v = jnp.asarray(rng.normal(size=(5, 3)))
    assert float(agg.weighted_expert_sum(v)) == float(jnp.sum(v))
    w = jnp.asarray([1.0, 0.5, 0.0, 2.0, 1.0])
    np.testing.assert_allclose(
        float(agg.weighted_expert_sum(v, w)),
        float(jnp.sum(w[:, None] * v)),
        rtol=1e-12,
    )


# -- host / device / sharded parity under uniform weights -------------------


def test_uniform_weight_parity_host_device_sharded(
    rng, eight_device_mesh, monkeypatch
):
    """Downweight selection on the pairwise-duplicated stack hands every
    expert w_e = 1/2 — a uniform weight vector threaded through the
    host, one-dispatch device, and shard_map fit drivers.  All three
    must land the same theta, and (the objective being an exact global
    rescale) the same optimum as the unweighted fit."""
    x, y = _duplicated(rng, base_n=160)  # E = 8 experts of 40, all paired

    monkeypatch.delenv("GP_AGG_POLICY", raising=False)
    monkeypatch.delenv("GP_AGG_SELECT", raising=False)
    base = _estimator("host").fit(x, y)

    monkeypatch.setenv("GP_AGG_SELECT", "1")
    monkeypatch.setenv("GP_AGG_SELECT_MODE", "downweight")
    thetas = {}
    for name, kwargs in (
        ("host", {}),
        ("device", {}),
        ("sharded", {"mesh": eight_device_mesh}),
    ):
        optimizer = "host" if name == "host" else "device"
        model = _estimator(optimizer, **kwargs).fit(x, y)
        thetas[name] = np.asarray(model.raw_predictor.theta)
        w = np.asarray(model.instr.agg_weights)
        np.testing.assert_allclose(w, 0.5)  # every expert in a pair of 2
        assert model.instr.metrics["agg.renorm"] == pytest.approx(2.0)
        assert model.instr.metrics["agg.effective_experts"] == pytest.approx(
            8.0
        )

    # host scipy and device-resident L-BFGS take different float paths;
    # 5e-3 is an order above the observed delta and an order below the
    # repo-wide THETA_REL_BAR used for the solver lanes
    scale = max(np.max(np.abs(thetas["host"])), 1e-12)
    for name in ("device", "sharded"):
        delta = np.max(np.abs(thetas[name] - thetas["host"])) / scale
        assert delta <= 5e-3, (name, delta)
    # w = c * ones rescales the objective; the optimizer must find the
    # unweighted optimum (path differences allowed, hence the looser bar)
    base_delta = np.max(
        np.abs(thetas["host"] - np.asarray(base.raw_predictor.theta))
    ) / scale
    assert base_delta <= 5e-3, base_delta


# -- fit-time correlation-aware selection -----------------------------------


def test_select_experts_keeps_independent_chunks(rng):
    """iid chunks are NOT redundant: centered sketches decorrelate and
    selection must keep the whole stack (the do-no-harm contract)."""
    x, y = _regression(rng, n=320)
    report = agg.select_experts(
        group_for_experts(x, y, 40), mode="drop", seed=3
    )
    assert report.num_dropped == 0
    assert report.clean
    assert report.renorm == 1.0


def test_select_experts_drops_duplicated_half(rng):
    x, y = _duplicated(rng, base_n=160)
    data = group_for_experts(x, y, 40)  # E = 8, experts 2j/2j+1 identical
    report = agg.select_experts(data, mode="drop", seed=3)
    assert report.num_dropped == 4
    np.testing.assert_array_equal(
        report.drop, np.tile([False, True], 4)
    )
    assert report.renorm == pytest.approx(2.0)

    down = agg.select_experts(data, mode="downweight", seed=3)
    assert down.num_dropped == 0
    np.testing.assert_allclose(down.weights, 0.5)


def test_select_experts_ignores_fully_masked_experts(rng):
    """Already-quarantined (fully masked) experts stay at w_e = 0 and
    never claim a live expert as redundant."""
    x, y = _duplicated(rng, base_n=160)
    data = group_for_experts(x, y, 40).with_experts_masked(
        np.array([True, False, False, False, False, False, False, False])
    )
    report = agg.select_experts(data, mode="drop", seed=3)
    assert report.num_active == 7
    assert report.weights[0] == 0.0
    assert not report.drop[0]  # masked beforehand, not dropped by selection
    # expert 1 (the masked expert's duplicate) survives: its partner is
    # out of the game, and every other pair still collapses
    assert not report.drop[1]
    assert report.num_dropped == 3


def test_selection_fit_drops_quarter_at_one_percent_nll(rng, monkeypatch):
    """Acceptance: on the redundant-chunks workload the fit drops >= 25%
    of the experts (here exactly half) and the held-out NLPD moves by
    <= 1% versus the selection-off fit."""
    x, y = _duplicated(rng, base_n=240)  # E = 12 experts of 40
    x_te = rng.normal(size=(160, 2))
    y_te = np.sin(x_te.sum(axis=1)) + 0.05 * rng.normal(size=160)

    def fit_nlpd():
        model = _estimator("host").fit(x, y)
        mean, var = model.predict_with_var(x_te)
        var = np.maximum(np.asarray(var, np.float64), 1e-12)
        err = y_te - np.asarray(mean, np.float64)
        nlpd = float(
            np.mean(0.5 * np.log(2 * np.pi * var) + err ** 2 / (2 * var))
        )
        return model, nlpd

    monkeypatch.delenv("GP_AGG_SELECT", raising=False)
    monkeypatch.delenv("GP_AGG_SELECT_MODE", raising=False)
    off_model, nlpd_off = fit_nlpd()
    assert "agg.selection_dropped" not in off_model.instr.metrics

    monkeypatch.setenv("GP_AGG_SELECT", "1")
    on_model, nlpd_on = fit_nlpd()
    m = on_model.instr.metrics
    assert m["agg.selection_dropped"] >= 0.25 * 12
    assert m["agg.renorm"] == pytest.approx(2.0)
    # signed: selection may only DEGRADE held-out NLPD by <= 1%
    assert nlpd_on - nlpd_off <= 0.01 * max(abs(nlpd_off), 1e-9), (
        nlpd_off, nlpd_on,
    )
    # provenance: the saved-model stamp carries the selection outcome
    assert m["agg.policy"] == "poe"
