"""Component tests: Gauss–Hermite integrator (the reference's IntegratorTest
oracle), scaling, the L-BFGS-B driver, checkpointing, validation harness."""

import jax.nn
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.ops.integrator import Integrator
from spark_gp_tpu.ops.scaling import fit_scaler, scale


def test_integrator_vs_monte_carlo(rng):
    """E[sigmoid(X)], X ~ N(0.5, 3) vs 100k-sample MC within 3 SE —
    util/IntegratorTest.scala:11-26."""
    mean, variance = 0.5, 3.0
    integrator = Integrator(100)
    result = float(
        integrator.expected_of_function_of_normal(mean, variance, jax.nn.sigmoid)
    )
    samples = rng.normal(mean, np.sqrt(variance), size=100_000)
    vals = 1.0 / (1.0 + np.exp(-samples))
    mc = vals.mean()
    se = vals.std() / np.sqrt(len(vals))
    assert abs(mc - result) < 3 * se


def test_integrator_batched():
    integrator = Integrator(32)
    means = jnp.asarray([0.0, 1.0, -2.0])
    variances = jnp.asarray([1.0, 0.5, 2.0])
    out = integrator.expected_of_function_of_normal(means, variances, jax.nn.sigmoid)
    assert out.shape == (3,)
    # linear function: E[aX+b] = a mu + b regardless of variance
    lin = integrator.expected_of_function_of_normal(means, variances, lambda x: 2 * x + 1)
    np.testing.assert_allclose(np.asarray(lin), 2 * np.asarray(means) + 1, rtol=1e-10)


def test_scale_zscores(rng):
    x = jnp.asarray(rng.normal(loc=5.0, scale=3.0, size=(200, 4)))
    s = np.asarray(scale(x))
    np.testing.assert_allclose(s.mean(axis=0), 0.0, atol=1e-10)
    np.testing.assert_allclose(s.std(axis=0), 1.0, rtol=1e-10)


def test_scale_constant_column(rng):
    """Zero-variance dims clamp to 1 (Scaling.scala:18) — no division by 0."""
    x = np.ones((50, 2))
    x[:, 1] = rng.normal(size=50)
    s = np.asarray(scale(jnp.asarray(x)))
    np.testing.assert_allclose(s[:, 0], 0.0)
    assert np.all(np.isfinite(s))


def test_fit_scaler_roundtrip(rng):
    x = rng.normal(size=(100, 3))
    mean, std = fit_scaler(jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray((jnp.asarray(x) - mean) / std), np.asarray(scale(jnp.asarray(x)))
    )


def test_lbfgsb_respects_bounds():
    from spark_gp_tpu.optimize.lbfgsb import minimize_lbfgsb

    def vag(theta):
        # minimum at theta = (-3, 7), outside the box [0,1] x [0,5]
        g = 2 * (theta - np.array([-3.0, 7.0]))
        return float(np.sum((theta - np.array([-3.0, 7.0])) ** 2)), g

    res = minimize_lbfgsb(
        vag, np.array([0.5, 0.5]), np.array([0.0, 0.0]), np.array([1.0, 5.0])
    )
    np.testing.assert_allclose(res.theta, [0.0, 5.0], atol=1e-8)
    assert res.success


def test_lbfgsb_nonfinite_first_eval_raises():
    from spark_gp_tpu.optimize.lbfgsb import minimize_lbfgsb
    from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException

    def vag(theta):
        return float("nan"), np.zeros_like(theta)

    with pytest.raises(NotPositiveDefiniteException):
        minimize_lbfgsb(vag, np.array([1.0]), np.array([0.0]), np.array([2.0]))


def test_checkpointer_roundtrip(tmp_path):
    from spark_gp_tpu.kernels import RBFKernel
    from spark_gp_tpu.utils.checkpoint import LbfgsCheckpointer, load_checkpoint

    ck = LbfgsCheckpointer(str(tmp_path), RBFKernel(1.0))
    ck(np.array([0.7]))
    ck(np.array([0.9]))
    it, theta, sig = load_checkpoint(str(tmp_path))
    assert it == 2
    np.testing.assert_allclose(theta, [0.9])
    assert sig == RBFKernel(1.0).describe(np.zeros(1))


def test_checkpoint_resume_through_estimator(tmp_path):
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.checkpoint import load_checkpoint

    x, y = make_synthetics(n=200)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.5, 1e-6, 10))
        .setActiveSetSize(20)
        .setCheckpointDir(str(tmp_path))
    )
    gp.fit(x, y)
    state = load_checkpoint(str(tmp_path), tag="GaussianProcessRegression")
    assert state is not None
    assert state[0] >= 1
    # The default hyper space is log-domain; the checkpoint must nonetheless
    # hold LINEAR-domain theta (inside the kernel's box bounds), so a resume
    # can seed theta0 from it directly.
    _, theta, _ = state
    assert np.all(theta >= 1e-6) and np.all(theta <= 10.0)


def test_kfold_partitions_everything():
    from spark_gp_tpu.utils.validation import kfold_indices

    seen = []
    for train, test in kfold_indices(103, 10, seed=3):
        assert set(train) & set(test) == set()
        assert len(train) + len(test) == 103
        seen.extend(test)
    assert sorted(seen) == list(range(103))


def test_profile_dir_produces_trace(tmp_path):
    """setProfileDir wires maybe_profile around the fit: a jax.profiler trace
    must land in the directory (SURVEY.md §5 tracing row)."""
    import os

    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.data import make_synthetics

    x, y = make_synthetics(n=120)
    trace_dir = str(tmp_path / "trace")
    (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.5, 1e-6, 10))
        .setActiveSetSize(20)
        .setMaxIter(3)
        .setProfileDir(trace_dir)
        .fit(x, y)
    )
    produced = []
    for root, _dirs, files in os.walk(trace_dir):
        produced.extend(os.path.join(root, f) for f in files)
    assert produced, "no profiler trace files written"


def test_ovr_predict_proba(rng):
    """OvR normalized sigmoid scores: rows sum to 1, argmax agrees with
    predict, column order follows classes_."""
    from spark_gp_tpu import GaussianProcessClassifier, RBFKernel
    from spark_gp_tpu.utils.validation import OneVsRest

    x = rng.normal(size=(90, 2))
    y = np.digitize(x.sum(axis=1), [-0.5, 0.5]).astype(np.float64)
    ovr = OneVsRest(
        lambda: GaussianProcessClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(45)
        .setActiveSetSize(20)
        .setMaxIter(5)
    ).fit(x, y)
    proba = ovr.predict_proba(x[:25])
    assert proba.shape == (25, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-9)
    np.testing.assert_array_equal(
        ovr.classes_[np.argmax(proba, axis=1)], ovr.predict(x[:25])
    )


# --- platform preflight (utils/platform.py) ---------------------------------


def test_preflight_backend_honors_pinned_env(monkeypatch):
    # conftest pins JAX_PLATFORMS=cpu: the pinned path must return it
    # without probing anything
    from spark_gp_tpu.utils import platform as plat

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    from spark_gp_tpu.utils import subproc

    def _no_probe(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("pinned env must not spawn a probe subprocess")

    monkeypatch.setattr(subproc, "run_captured", _no_probe)
    assert plat.preflight_backend() == "cpu"


def test_preflight_backend_healthy_probe_reports_platform(
    monkeypatch, tmp_path
):
    from spark_gp_tpu.utils import platform as plat
    from spark_gp_tpu.utils import subproc
    from spark_gp_tpu.utils.subproc import CapturedRun

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "_marker_path", lambda: str(tmp_path / "m"))

    def _healthy(cmd, timeout_s, **kw):
        return CapturedRun(0, "tpu\n", "")

    monkeypatch.setattr(subproc, "run_captured", _healthy)
    assert plat.preflight_backend(timeout_s=5.0) == "tpu"
    # a healthy probe must NOT pin the environment
    assert "JAX_PLATFORMS" not in __import__("os").environ

    # ...and its verdict is cached: a second call within the TTL must not
    # spawn another probe subprocess
    def _no_probe(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("fresh healthy verdict must skip the probe")

    monkeypatch.setattr(subproc, "run_captured", _no_probe)
    assert plat.preflight_backend(timeout_s=5.0) == "tpu"
    # TTL=0 disables the cache and probes again
    monkeypatch.setenv("GP_PREFLIGHT_CACHE_TTL", "0")
    monkeypatch.setattr(subproc, "run_captured", _healthy)
    assert plat.preflight_backend(timeout_s=5.0) == "tpu"


def test_preflight_backend_hung_probe_pins_fallback(monkeypatch, tmp_path):
    from spark_gp_tpu.utils import platform as plat
    from spark_gp_tpu.utils import subproc
    from spark_gp_tpu.utils.subproc import CapturedRun

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "_marker_path", lambda: str(tmp_path / "m"))

    def _hang(cmd, timeout_s, **kw):
        return CapturedRun(None, "", "")

    monkeypatch.setattr(subproc, "run_captured", _hang)
    # jax.config.update("jax_platforms", ...) may be rejected once a backend
    # exists in this test process; the contract under test is the env pin +
    # returned platform, so tolerate the config update either way
    try:
        got = plat.preflight_backend(timeout_s=0.1)
    except RuntimeError:
        pytest.skip("backend already initialized; config update refused")
    assert got == "cpu"
    assert __import__("os").environ.get("JAX_PLATFORMS") == "cpu"


def test_preflight_backend_fast_failure_reports_cause(monkeypatch, tmp_path, caplog):
    """A probe that dies quickly (broken install, not a hang) must surface
    its returncode and stderr in the warning, not the hang message."""
    import logging

    from spark_gp_tpu.utils import platform as plat
    from spark_gp_tpu.utils import subproc
    from spark_gp_tpu.utils.subproc import CapturedRun

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "_marker_path", lambda: str(tmp_path / "m"))

    def _dies(cmd, timeout_s, **kw):
        return CapturedRun(1, "", "ImportError: libfoo.so missing")

    monkeypatch.setattr(subproc, "run_captured", _dies)
    with caplog.at_level(logging.WARNING, logger="spark_gp_tpu.utils.platform"):
        got = plat.preflight_backend(timeout_s=5.0)
    assert got == "cpu"
    assert "rc=1" in caplog.text
    assert "libfoo.so missing" in caplog.text
    assert "hung" not in caplog.text


def test_nlpd_formula_and_calibration(rng):
    """nlpd matches the Gaussian log-density by hand, and a miscalibrated
    variance (too small OR too large) scores worse than the truth."""
    from spark_gp_tpu.utils.validation import nlpd

    y = rng.normal(size=500)
    mu = np.zeros(500)
    v = np.ones(500)
    by_hand = np.mean(0.5 * (np.log(2 * np.pi * v) + (y - mu) ** 2 / v))
    assert nlpd(y, mu, v) == pytest.approx(by_hand)
    assert nlpd(y, mu, v) < nlpd(y, mu, v * 25)
    assert nlpd(y, mu, v) < nlpd(y, mu, v / 25)


def test_cross_validate_routes_variance_metric(rng):
    """cross_validate must call predict_with_var for needs_variance
    metrics and produce a finite, sane NLPD on an easy problem."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel, WhiteNoiseKernel
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import cross_validate, nlpd

    x, y = make_synthetics(n=300)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1))
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(50)
        .setSigma2(1e-3)
        .setSeed(13)
    )
    score = cross_validate(gp, x, y, num_folds=3, metric=nlpd, seed=13)
    # a calibrated GP on sin(x)+N(0,0.01): NLPD should be strongly negative
    # (densities > 1); an uninformative N(0,1) predictor scores ~1.42
    assert np.isfinite(score)
    assert score < 0.0


def test_param_grid_builder_cartesian():
    from spark_gp_tpu.utils.validation import ParamGridBuilder

    grid = (
        ParamGridBuilder()
        .addGrid("setSigma2", [1e-3, 1e-2])
        .addGrid("setActiveSetSize", [25, 50, 75])
        .build()
    )
    assert len(grid) == 6
    assert {"setSigma2": 1e-2, "setActiveSetSize": 75} in grid
    # empty grid: one all-defaults cell (Iris.scala:29-33 wires exactly this)
    assert ParamGridBuilder().build() == [{}]


def test_cross_validate_param_grid_picks_and_refits():
    """Grid search must score every cell on the same folds, pick by the
    metric's direction, and refit the winner on the full data."""
    from spark_gp_tpu.utils.validation import (
        CrossValidationResult,
        ParamGridBuilder,
        cross_validate,
        rmse,
    )

    class ToyEstimator:
        """predict(x) = bias: best rmse at the bias closest to E[y]."""

        def __init__(self):
            self.bias = 0.0
            self.fit_sizes = []

        def setBias(self, value):
            self.bias = value
            return self

        def fit(self, x, y):
            self.fit_sizes.append(len(x))
            return self

        def predict(self, x_test):
            return np.full(len(x_test), self.bias)

    x = np.arange(30, dtype=np.float64)[:, None]
    y = np.full(30, 2.0)
    grid = ParamGridBuilder().addGrid("setBias", [0.0, 2.0, 5.0]).build()
    res = cross_validate(
        ToyEstimator(), x, y, num_folds=3, metric=rmse, param_grid=grid
    )
    assert isinstance(res, CrossValidationResult)
    assert len(res.scores) == 3
    assert res.best_params == {"setBias": 2.0}
    assert res.best_score == pytest.approx(0.0)
    # refit happened on the FULL data with the winning config
    assert res.best_model is not None
    assert res.best_model.bias == 2.0
    assert res.best_model.fit_sizes[-1] == 30
    # larger-is-better metrics flip the pick
    def neg_rmse(y_true, y_pred):
        return -rmse(y_true, y_pred)

    neg_rmse.greater_is_better = True
    res2 = cross_validate(
        ToyEstimator(), x, y, num_folds=3, metric=neg_rmse,
        param_grid=grid, refit=False,
    )
    assert res2.best_params == {"setBias": 2.0}
    assert res2.best_model is None
    # param_grid=None keeps the historical float-returning signature
    plain = cross_validate(ToyEstimator(), x, y, num_folds=3, metric=rmse)
    assert isinstance(plain, float)


def test_cross_validate_param_grid_on_real_gp():
    """End-to-end: a 2-cell sigma2 grid on synthetics — the well-specified
    noise level must win and the refitted model must predict sanely."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel, WhiteNoiseKernel
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import ParamGridBuilder, cross_validate, rmse

    x, y = make_synthetics(n=240)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1))
        .setDatasetSizeForExpert(80)
        .setActiveSetSize(40)
        .setSeed(13)
    )
    grid = ParamGridBuilder().addGrid("setSigma2", [1e-3, 25.0]).build()
    res = cross_validate(gp, x, y, num_folds=3, metric=rmse, param_grid=grid)
    # sigma2=25 drowns sin(x) (unit amplitude) in assumed noise
    assert res.best_params == {"setSigma2": 1e-3}
    pred = res.best_model.predict(x[:50])
    assert rmse(y[:50], pred) < 0.2


def test_nlpd_variance_floor_is_finite_and_not_rewarding():
    """ADVICE r4: a degenerate var=0 prediction must score finitely
    terribly — no inf from residual^2/tiny, no ~-354 reward for exact
    interpolation."""
    from spark_gp_tpu.utils.validation import nlpd

    y = np.array([1.0, 2.0, 3.0])
    # zero variance + nonzero residual: finite, terrible
    bad = nlpd(y, y + 0.1, np.zeros(3))
    assert np.isfinite(bad)
    assert bad > 1e6
    # zero variance + exact interpolation: bounded reward, far from -354
    interp = nlpd(y, y, np.zeros(3))
    assert np.isfinite(interp)
    assert interp > -20.0


def test_preflight_backend_probes_pinned_platform(monkeypatch, tmp_path):
    """A JAX_PLATFORMS pin that is NOT the fallback still gets probed (site
    profiles export the tunnel platform globally — r5); a hung pinned
    backend falls back, and GP_HONOR_PINNED_PLATFORM=1 restores the old
    wedge-on-principle contract."""
    from spark_gp_tpu.utils import platform as plat

    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.delenv("GP_HONOR_PINNED_PLATFORM", raising=False)
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "honor_platform_env", lambda: None)
    monkeypatch.setattr(plat, "_marker_path", lambda: str(tmp_path / "m"))

    from spark_gp_tpu.utils import subproc
    from spark_gp_tpu.utils.subproc import CapturedRun

    def _hang(cmd, timeout_s, **kw):
        return CapturedRun(None, "", "")

    monkeypatch.setattr(subproc, "run_captured", _hang)
    try:
        got = plat.preflight_backend(timeout_s=0.1)
    except RuntimeError:
        got = None
    if got is not None:
        assert got == "cpu"
        assert __import__("os").environ["JAX_PLATFORMS"] == "cpu"

    # honor flag: no probe, pin returned as-is
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("GP_HONOR_PINNED_PLATFORM", "1")

    def _no_probe(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("honored pin must not spawn a probe")

    monkeypatch.setattr(subproc, "run_captured", _no_probe)
    assert plat.preflight_backend(timeout_s=0.1) == "axon"


def test_preflight_cached_verdict_is_platform_scoped(monkeypatch, tmp_path):
    """A cached healthy-cpu verdict must not green-light a different pinned
    platform."""
    from spark_gp_tpu.utils import platform as plat
    from spark_gp_tpu.utils import subproc
    from spark_gp_tpu.utils.subproc import CapturedRun

    marker = tmp_path / "m"
    monkeypatch.setattr(plat, "_marker_path", lambda: str(marker))
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "honor_platform_env", lambda: None)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    plat._write_healthy_marker("cpu")
    assert plat._read_healthy_marker() == "cpu"
    # unpinned: the cached verdict short-circuits the probe
    def _no_probe(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("cached verdict must short-circuit the probe")

    monkeypatch.setattr(subproc, "run_captured", _no_probe)
    assert plat.preflight_backend(timeout_s=0.1) == "cpu"
    # pinned to a different platform: cached cpu verdict must NOT apply
    monkeypatch.setenv("JAX_PLATFORMS", "axon")

    probed = {}

    def _probe_runs(cmd, timeout_s, **kw):
        probed["yes"] = True
        return CapturedRun(0, "axon\n", "")

    monkeypatch.setattr(subproc, "run_captured", _probe_runs)
    assert plat.preflight_backend(timeout_s=0.1) == "axon"
    assert probed.get("yes")


def test_cross_validate_param_grid_nan_cell_never_wins():
    """A degenerate cell whose folds score NaN must lose to any finite
    cell (min/max would otherwise keep a NaN first element); an all-NaN
    grid raises instead of silently refitting a broken config."""
    from spark_gp_tpu.utils.validation import cross_validate

    class NaNable:
        def __init__(self):
            self.mode = "nan"

        def setMode(self, value):
            self.mode = value
            return self

        def fit(self, x, y):
            return self

        def predict(self, x_test):
            fill = np.nan if self.mode == "nan" else 1.0
            return np.full(len(x_test), fill)

    x = np.arange(12, dtype=np.float64)[:, None]
    y = np.full(12, 1.0)
    res = cross_validate(
        NaNable(), x, y, num_folds=3,
        param_grid=[{"setMode": "nan"}, {"setMode": "ok"}],
    )
    assert res.best_params == {"setMode": "ok"}
    assert np.isfinite(res.best_score)
    with pytest.raises(ValueError, match="non-finite"):
        cross_validate(
            NaNable(), x, y, num_folds=3, param_grid=[{"setMode": "nan"}]
        )


def test_chip_peaks_and_precision_passes():
    """The shared chip-spec table (ops/precision.py): known generations
    resolve both peaks, CPU hosts resolve to the nominal host-proxy
    figures (so CPU-fallback bench rounds report a non-null
    est_mfu_vs_bf16_peak through the same pipeline — ISSUE 3), truly
    unknown kinds resolve to None (consumers then report MFU as null
    rather than guessing), and the pass-count table covers exactly the
    policy's gram/linalg mode vocabulary."""
    from spark_gp_tpu.ops.precision import PRECISION_PASSES, chip_peaks

    tf, bw = chip_peaks("TPU v5 lite")
    assert (tf, bw) == (197.0, 819.0)
    tf, bw = chip_peaks("TPU v4")
    assert (tf, bw) == (275.0, 1228.0)
    # v5p/v6e rows exist so est_mfu_vs_bf16_peak is non-null there too
    assert chip_peaks("TPU v5p")[0] == 459.0
    assert chip_peaks("TPU v6e")[0] == 918.0
    # the CPU host-proxy row (a PLUMBING proxy — bench.py marks such
    # rounds as fallback; never comparable to the TPU rows)
    assert chip_peaks("TFRT_CPU_0 whatever") == (0.5, 40.0)
    assert chip_peaks("some fpga thing") == (None, None)
    # the mode vocabulary (the lanes' HIGHEST/strict default is pinned by
    # test_matmul_precision_knob in test_pallas_linalg.py)
    assert set(PRECISION_PASSES) == {
        "highest", "high", "default", "compensated"
    }
