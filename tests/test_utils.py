"""Component tests: Gauss–Hermite integrator (the reference's IntegratorTest
oracle), scaling, the L-BFGS-B driver, checkpointing, validation harness."""

import jax.nn
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.ops.integrator import Integrator
from spark_gp_tpu.ops.scaling import fit_scaler, scale


def test_integrator_vs_monte_carlo(rng):
    """E[sigmoid(X)], X ~ N(0.5, 3) vs 100k-sample MC within 3 SE —
    util/IntegratorTest.scala:11-26."""
    mean, variance = 0.5, 3.0
    integrator = Integrator(100)
    result = float(
        integrator.expected_of_function_of_normal(mean, variance, jax.nn.sigmoid)
    )
    samples = rng.normal(mean, np.sqrt(variance), size=100_000)
    vals = 1.0 / (1.0 + np.exp(-samples))
    mc = vals.mean()
    se = vals.std() / np.sqrt(len(vals))
    assert abs(mc - result) < 3 * se


def test_integrator_batched():
    integrator = Integrator(32)
    means = jnp.asarray([0.0, 1.0, -2.0])
    variances = jnp.asarray([1.0, 0.5, 2.0])
    out = integrator.expected_of_function_of_normal(means, variances, jax.nn.sigmoid)
    assert out.shape == (3,)
    # linear function: E[aX+b] = a mu + b regardless of variance
    lin = integrator.expected_of_function_of_normal(means, variances, lambda x: 2 * x + 1)
    np.testing.assert_allclose(np.asarray(lin), 2 * np.asarray(means) + 1, rtol=1e-10)


def test_scale_zscores(rng):
    x = jnp.asarray(rng.normal(loc=5.0, scale=3.0, size=(200, 4)))
    s = np.asarray(scale(x))
    np.testing.assert_allclose(s.mean(axis=0), 0.0, atol=1e-10)
    np.testing.assert_allclose(s.std(axis=0), 1.0, rtol=1e-10)


def test_scale_constant_column(rng):
    """Zero-variance dims clamp to 1 (Scaling.scala:18) — no division by 0."""
    x = np.ones((50, 2))
    x[:, 1] = rng.normal(size=50)
    s = np.asarray(scale(jnp.asarray(x)))
    np.testing.assert_allclose(s[:, 0], 0.0)
    assert np.all(np.isfinite(s))


def test_fit_scaler_roundtrip(rng):
    x = rng.normal(size=(100, 3))
    mean, std = fit_scaler(jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray((jnp.asarray(x) - mean) / std), np.asarray(scale(jnp.asarray(x)))
    )


def test_lbfgsb_respects_bounds():
    from spark_gp_tpu.optimize.lbfgsb import minimize_lbfgsb

    def vag(theta):
        # minimum at theta = (-3, 7), outside the box [0,1] x [0,5]
        g = 2 * (theta - np.array([-3.0, 7.0]))
        return float(np.sum((theta - np.array([-3.0, 7.0])) ** 2)), g

    res = minimize_lbfgsb(
        vag, np.array([0.5, 0.5]), np.array([0.0, 0.0]), np.array([1.0, 5.0])
    )
    np.testing.assert_allclose(res.theta, [0.0, 5.0], atol=1e-8)
    assert res.success


def test_lbfgsb_nonfinite_first_eval_raises():
    from spark_gp_tpu.optimize.lbfgsb import minimize_lbfgsb
    from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException

    def vag(theta):
        return float("nan"), np.zeros_like(theta)

    with pytest.raises(NotPositiveDefiniteException):
        minimize_lbfgsb(vag, np.array([1.0]), np.array([0.0]), np.array([2.0]))


def test_checkpointer_roundtrip(tmp_path):
    from spark_gp_tpu.kernels import RBFKernel
    from spark_gp_tpu.utils.checkpoint import LbfgsCheckpointer, load_checkpoint

    ck = LbfgsCheckpointer(str(tmp_path), RBFKernel(1.0))
    ck(np.array([0.7]))
    ck(np.array([0.9]))
    it, theta, sig = load_checkpoint(str(tmp_path))
    assert it == 2
    np.testing.assert_allclose(theta, [0.9])
    assert sig == RBFKernel(1.0).describe(np.zeros(1))


def test_checkpoint_resume_through_estimator(tmp_path):
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.checkpoint import load_checkpoint

    x, y = make_synthetics(n=200)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.5, 1e-6, 10))
        .setActiveSetSize(20)
        .setCheckpointDir(str(tmp_path))
    )
    gp.fit(x, y)
    state = load_checkpoint(str(tmp_path), tag="GaussianProcessRegression")
    assert state is not None
    assert state[0] >= 1
    # The default hyper space is log-domain; the checkpoint must nonetheless
    # hold LINEAR-domain theta (inside the kernel's box bounds), so a resume
    # can seed theta0 from it directly.
    _, theta, _ = state
    assert np.all(theta >= 1e-6) and np.all(theta <= 10.0)


def test_kfold_partitions_everything():
    from spark_gp_tpu.utils.validation import kfold_indices

    seen = []
    for train, test in kfold_indices(103, 10, seed=3):
        assert set(train) & set(test) == set()
        assert len(train) + len(test) == 103
        seen.extend(test)
    assert sorted(seen) == list(range(103))


def test_profile_dir_produces_trace(tmp_path):
    """setProfileDir wires maybe_profile around the fit: a jax.profiler trace
    must land in the directory (SURVEY.md §5 tracing row)."""
    import os

    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.data import make_synthetics

    x, y = make_synthetics(n=120)
    trace_dir = str(tmp_path / "trace")
    (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.5, 1e-6, 10))
        .setActiveSetSize(20)
        .setMaxIter(3)
        .setProfileDir(trace_dir)
        .fit(x, y)
    )
    produced = []
    for root, _dirs, files in os.walk(trace_dir):
        produced.extend(os.path.join(root, f) for f in files)
    assert produced, "no profiler trace files written"


def test_ovr_predict_proba(rng):
    """OvR normalized sigmoid scores: rows sum to 1, argmax agrees with
    predict, column order follows classes_."""
    from spark_gp_tpu import GaussianProcessClassifier, RBFKernel
    from spark_gp_tpu.utils.validation import OneVsRest

    x = rng.normal(size=(90, 2))
    y = np.digitize(x.sum(axis=1), [-0.5, 0.5]).astype(np.float64)
    ovr = OneVsRest(
        lambda: GaussianProcessClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-2, 10.0))
        .setDatasetSizeForExpert(45)
        .setActiveSetSize(20)
        .setMaxIter(5)
    ).fit(x, y)
    proba = ovr.predict_proba(x[:25])
    assert proba.shape == (25, 3)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-9)
    np.testing.assert_array_equal(
        ovr.classes_[np.argmax(proba, axis=1)], ovr.predict(x[:25])
    )


# --- platform preflight (utils/platform.py) ---------------------------------


def test_preflight_backend_honors_pinned_env(monkeypatch):
    # conftest pins JAX_PLATFORMS=cpu: the pinned path must return it
    # without probing anything
    from spark_gp_tpu.utils import platform as plat

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    def _no_probe(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("pinned env must not spawn a probe subprocess")

    monkeypatch.setattr("subprocess.run", _no_probe)
    assert plat.preflight_backend() == "cpu"


def test_preflight_backend_healthy_probe_reports_platform(
    monkeypatch, tmp_path
):
    import subprocess as sp

    from spark_gp_tpu.utils import platform as plat

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "_marker_path", lambda: str(tmp_path / "m"))

    def _healthy(cmd, **kw):
        return sp.CompletedProcess(cmd, 0, stdout="tpu\n", stderr="")

    monkeypatch.setattr(sp, "run", _healthy)
    assert plat.preflight_backend(timeout_s=5.0) == "tpu"
    # a healthy probe must NOT pin the environment
    assert "JAX_PLATFORMS" not in __import__("os").environ

    # ...and its verdict is cached: a second call within the TTL must not
    # spawn another probe subprocess
    def _no_probe(*a, **k):  # pragma: no cover - failure mode
        raise AssertionError("fresh healthy verdict must skip the probe")

    monkeypatch.setattr(sp, "run", _no_probe)
    assert plat.preflight_backend(timeout_s=5.0) == "tpu"
    # TTL=0 disables the cache and probes again
    monkeypatch.setenv("GP_PREFLIGHT_CACHE_TTL", "0")
    monkeypatch.setattr(sp, "run", _healthy)
    assert plat.preflight_backend(timeout_s=5.0) == "tpu"


def test_preflight_backend_hung_probe_pins_fallback(monkeypatch, tmp_path):
    import subprocess as sp

    from spark_gp_tpu.utils import platform as plat

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "_marker_path", lambda: str(tmp_path / "m"))

    def _hang(cmd, **kw):
        raise sp.TimeoutExpired(cmd, kw.get("timeout"))

    monkeypatch.setattr(sp, "run", _hang)
    # jax.config.update("jax_platforms", ...) may be rejected once a backend
    # exists in this test process; the contract under test is the env pin +
    # returned platform, so tolerate the config update either way
    try:
        got = plat.preflight_backend(timeout_s=0.1)
    except RuntimeError:
        pytest.skip("backend already initialized; config update refused")
    assert got == "cpu"
    assert __import__("os").environ.get("JAX_PLATFORMS") == "cpu"


def test_preflight_backend_fast_failure_reports_cause(monkeypatch, tmp_path, caplog):
    """A probe that dies quickly (broken install, not a hang) must surface
    its returncode and stderr in the warning, not the hang message."""
    import logging
    import subprocess as sp

    from spark_gp_tpu.utils import platform as plat

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(plat, "backends_already_initialized", lambda: False)
    monkeypatch.setattr(plat, "_marker_path", lambda: str(tmp_path / "m"))

    def _dies(cmd, **kw):
        return sp.CompletedProcess(
            cmd, 1, stdout="", stderr="ImportError: libfoo.so missing"
        )

    monkeypatch.setattr(sp, "run", _dies)
    with caplog.at_level(logging.WARNING, logger="spark_gp_tpu.utils.platform"):
        got = plat.preflight_backend(timeout_s=5.0)
    assert got == "cpu"
    assert "rc=1" in caplog.text
    assert "libfoo.so missing" in caplog.text
    assert "hung" not in caplog.text


def test_nlpd_formula_and_calibration(rng):
    """nlpd matches the Gaussian log-density by hand, and a miscalibrated
    variance (too small OR too large) scores worse than the truth."""
    from spark_gp_tpu.utils.validation import nlpd

    y = rng.normal(size=500)
    mu = np.zeros(500)
    v = np.ones(500)
    by_hand = np.mean(0.5 * (np.log(2 * np.pi * v) + (y - mu) ** 2 / v))
    assert nlpd(y, mu, v) == pytest.approx(by_hand)
    assert nlpd(y, mu, v) < nlpd(y, mu, v * 25)
    assert nlpd(y, mu, v) < nlpd(y, mu, v / 25)


def test_cross_validate_routes_variance_metric(rng):
    """cross_validate must call predict_with_var for needs_variance
    metrics and produce a finite, sane NLPD on an easy problem."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel, WhiteNoiseKernel
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import cross_validate, nlpd

    x, y = make_synthetics(n=300)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1))
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(50)
        .setSigma2(1e-3)
        .setSeed(13)
    )
    score = cross_validate(gp, x, y, num_folds=3, metric=nlpd, seed=13)
    # a calibrated GP on sin(x)+N(0,0.01): NLPD should be strongly negative
    # (densities > 1); an uninformative N(0,1) predictor scores ~1.42
    assert np.isfinite(score)
    assert score < 0.0
