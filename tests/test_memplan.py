"""Predictive memory planning (resilience/memplan.py).

The contract under test: with a resolvable device budget the planner
picks the largest predicted-safe configuration BEFORE the first dispatch
(fit rung, predict chunk, serve admission), every decision is
provenance-stamped with ``predicted >= modeled-actual`` by construction,
the compiled ``memory_analysis`` path brackets the analytic model, and
``GP_MEMPLAN=0`` restores the reactive crash-then-degrade behavior
bit-for-bit.  The chaos ``memory_limit_bytes`` injector makes all of it
provable on CPU: it is both the planner's budget and the modeled
allocator at the dispatch choke points.
"""

import json
import os

import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessClassifier,
    GaussianProcessMulticlassClassifier,
    GaussianProcessPoissonRegression,
    GaussianProcessRegression,
    RBFKernel,
)
from spark_gp_tpu.data import make_benchmark_data
from spark_gp_tpu.obs import cost as obs_cost
from spark_gp_tpu.obs.runtime import telemetry
from spark_gp_tpu.parallel.experts import num_experts_for
from spark_gp_tpu.resilience import chaos, memplan

pytestmark = pytest.mark.chaos

EXPERT = 40


def _itemsize() -> int:
    # the harness runs x64 (conftest): stacks and predict inputs are f64
    import jax

    return 8 if jax.config.jax_enable_x64 else 4


@pytest.fixture(autouse=True)
def _clean_planner():
    memplan.reset_calibration()
    memplan.set_memory_planning(None)
    yield
    memplan.reset_calibration()
    memplan.set_memory_planning(None)


@pytest.fixture(scope="module")
def problem():
    x, y = make_benchmark_data(240)
    return np.asarray(x), np.asarray(y)


def _gp(optimizer="device", max_iter=3):
    return (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.1))
        .setDatasetSizeForExpert(EXPERT)
        .setActiveSetSize(EXPERT)
        .setSeed(13)
        .setSigma2(1e-3)
        .setMaxIter(max_iter)
        .setOptimizer(optimizer)
    )


def _counters():
    return dict(telemetry.snapshot()["counters"])


def _fit_limit_between_segment_and_native(x):
    """A budget under which the native dispatch is over but both smaller
    rungs (iterative solver / segmented) fit (f32 stack)."""
    e = num_experts_for(x.shape[0], EXPERT)
    native_raw = memplan.fit_dispatch_bytes(
        e, EXPERT, x.shape[1], _itemsize(), "native"
    )
    seg_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(e, EXPERT, x.shape[1], _itemsize(), "segmented")
    )
    iter_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(e, EXPERT, x.shape[1], _itemsize(), "iterative")
    )
    assert seg_pred < native_raw and iter_pred < native_raw
    return (max(seg_pred, iter_pred) + native_raw) / 2.0


# -- fit dispatch pre-sizing -------------------------------------------------


def test_fit_plan_presizes_iterative_no_oom(problem):
    x, y = problem
    clean = _gp().fit(x, y)
    limit = _fit_limit_between_segment_and_native(x)
    before = _counters()
    with chaos.memory_limit_bytes(limit) as fired:
        model = _gp().fit(x, y)
    after = _counters()
    # no first-request OOM: the plan sized down BEFORE the dispatch
    assert fired[0] == 0
    assert after.get("fallback.failures.oom", 0.0) == before.get(
        "fallback.failures.oom", 0.0
    )
    assert not getattr(model, "degradations", [])
    assert after.get("plan.hit", 0.0) == before.get("plan.hit", 0.0) + 1
    # provenance: the decision rows, predicted >= modeled actual <= budget
    rows = model.instr.memory_plan
    # ISSUE 14: the iterative solver rung is the preferred pre-sized
    # smaller configuration (same dispatch shape, skinny CG workspace)
    assert rows[0]["chosen"] == "iterative" and rows[0]["fits"] is True
    assert rows[0]["raw_bytes"] <= rows[0]["predicted_bytes"] <= limit
    names = [c["name"] for c in rows[0]["candidates"]]
    assert names == ["native", "iterative", "matfree", "segmented"]
    # the iterative rung changes numerics within its documented bar:
    # objective-level parity (theta itself is ill-determined on this
    # workload's flat amplitude ridge at a 3-iteration budget)
    nll_clean = float(clean.instr.metrics["final_nll"])
    nll_plan = float(model.instr.metrics["final_nll"])
    assert abs(nll_plan - nll_clean) / max(abs(nll_clean), 1.0) <= 1e-2


def test_fit_kill_switch_restores_reactive_ladder(problem):
    x, y = problem
    limit = _fit_limit_between_segment_and_native(x)
    memplan.set_memory_planning(False)
    before = _counters()
    with chaos.memory_limit_bytes(limit) as fired:
        model = _gp().fit(x, y)
    after = _counters()
    # today's behavior bit-for-bit: crash at native, degrade reactively
    # (the oom class's first rung is now the iterative solver lane)
    assert fired[0] >= 1
    assert after.get("fallback.failures.oom", 0.0) > before.get(
        "fallback.failures.oom", 0.0
    )
    assert [d["to"] for d in model.degradations] == ["iterative"]
    assert not getattr(model.instr, "memory_plan", None)
    assert after.get("plan.hit", 0.0) == before.get("plan.hit", 0.0)


def test_fit_plan_miss_counted_when_nothing_fits(problem):
    x, y = problem
    # a budget even the smallest staged dispatch (the matfree rung's
    # skinny workspace) exceeds: the plan records a fits=False decision,
    # the dispatch OOMs, and the reactive ladder backstops through the
    # host rung — plan.miss is the alert trail
    e = num_experts_for(x.shape[0], EXPERT)
    smallest_raw = min(
        memplan.fit_dispatch_bytes(
            e, EXPERT, x.shape[1], _itemsize(), rung
        )
        for rung in ("segmented", "matfree")
    )
    before = _counters()
    with chaos.memory_limit_bytes(smallest_raw / 2.0) as fired:
        model = _gp().fit(x, y)
    after = _counters()
    assert fired[0] >= 1
    assert after.get("plan.miss", 0.0) > before.get("plan.miss", 0.0)
    rows = model.instr.memory_plan
    assert rows and rows[0]["fits"] is False
    # the backstop carried the fit, walking the remaining rungs (the
    # staged budget also rejects the iterative re-fit's modeled bytes)
    assert [d["to"] for d in model.degradations][-1] == "host_f64"


# -- predict chunk pre-sizing ------------------------------------------------


@pytest.fixture(scope="module")
def fitted(problem):
    x, y = problem
    model = _gp(optimizer="host").fit(x, y)
    return model, model.predict(x[:64])


def _predict_limit_between(m, p, big_rows, small_rows):
    big = memplan.predict_dispatch_bytes(big_rows, m, p, _itemsize(), True)
    small_pred = memplan.predicted_bytes(
        memplan.predict_dispatch_bytes(small_rows, m, p, _itemsize(), True)
    )
    assert small_pred < big
    return (small_pred + big) / 2.0


def test_predict_plan_shrinks_chunk_no_oom(problem, fitted):
    x, _ = problem
    model, ref = fitted
    m, p = model.raw_predictor.active.shape
    limit = _predict_limit_between(m, p, 64, 16)
    before = _counters()
    with chaos.memory_limit_bytes(limit) as fired:
        pred = model.predict(x[:64])
    after = _counters()
    assert fired[0] == 0
    assert after.get("fallback.transitions", 0.0) == before.get(
        "fallback.transitions", 0.0
    )
    assert after.get("plan.hit", 0.0) > before.get("plan.hit", 0.0)
    np.testing.assert_allclose(pred, ref, atol=1e-6)


def test_predict_kill_switch_restores_halving_ladder(problem, fitted):
    x, _ = problem
    model, ref = fitted
    m, p = model.raw_predictor.active.shape
    limit = _predict_limit_between(m, p, 64, 16)
    memplan.set_memory_planning(False)
    before = _counters()
    with chaos.memory_limit_bytes(limit) as fired:
        pred = model.predict(x[:64])
    after = _counters()
    # the pre-plan behavior: OOM at the default chunk, halve reactively
    assert fired[0] >= 1
    assert after.get("fallback.transitions", 0.0) > before.get(
        "fallback.transitions", 0.0
    )
    np.testing.assert_allclose(pred, ref, atol=1e-6)


# -- predicted vs measured (compiled memory_analysis) ------------------------


def _family_fits(x, y):
    rng = np.random.default_rng(7)
    y_bin = (y > np.median(y)).astype(np.float64)
    y_mc = rng.integers(0, 3, size=y.shape[0])
    y_cnt = rng.poisson(2.0, size=y.shape[0]).astype(np.float64)

    def cfg(est):
        return (
            est.setKernel(lambda: RBFKernel(0.1))
            .setDatasetSizeForExpert(EXPERT)
            .setActiveSetSize(EXPERT)
            .setSeed(13)
            .setSigma2(1e-3)
            .setMaxIter(2)
            .setOptimizer("device")
        )

    return [
        ("gpr", cfg(GaussianProcessRegression()), y, 1),
        ("gpc", cfg(GaussianProcessClassifier()), y_bin, 1),
        ("gpc_mc", cfg(GaussianProcessMulticlassClassifier()), y_mc, 3),
        ("gp_poisson", cfg(GaussianProcessPoissonRegression()), y_cnt, 1),
    ]


def test_predicted_brackets_compiled_peak_all_families(problem):
    """The analytic model must BRACKET the compiler's own memory_analysis
    peak (extracted through obs/cost.py's signature-cached lower+compile
    path) for all four family fits and the PPA predict: predicted >=
    compiled, and within a sane conservatism factor."""
    x, y = problem
    e = num_experts_for(x.shape[0], EXPERT)
    obs_cost.set_cost_metering(True)
    try:
        for name, est, targets, n_targets in _family_fits(x, y):
            memplan.reset_calibration()
            model = est.fit(x, targets)
            peaks = {
                entry: peak for entry, peak in memplan.compiled_peaks().items()
                if entry.startswith("fit.")
            }
            assert peaks, f"{name}: no compiled peak metered"
            compiled = max(peaks.values())
            predicted = memplan.predicted_bytes(memplan.fit_dispatch_bytes(
                e, EXPERT, x.shape[1], _itemsize(), "native", n_targets
            ))
            assert predicted >= compiled, (name, predicted, compiled)
            assert predicted <= compiled * 200, (name, predicted, compiled)
        # PPA predict: the predict.ppa entry
        memplan.reset_calibration()
        pred_model = model  # the poisson model's raw predictor serves
        pred_model.predict(x[:64])
        compiled = memplan.compiled_peak("predict.ppa")
        assert compiled is not None and compiled > 0
        m, p = pred_model.raw_predictor.active.shape
        predicted = memplan.predicted_bytes(
            memplan.predict_dispatch_bytes(64, m, p, _itemsize(), True)
        )
        assert compiled <= predicted <= compiled * 200
    finally:
        obs_cost.set_cost_metering(None)


def test_calibration_ratchets_model_upward():
    raw = memplan.fit_dispatch_bytes(4, 32, 3, 4, "native")
    # a measured peak ABOVE the model doubles the key's scale; a smaller
    # one never ratchets down
    memplan.observe_measured(memplan.fit_model_key(None, "native"), raw, raw * 2.0)
    assert memplan.fit_dispatch_bytes(4, 32, 3, 4, "native") == (
        pytest.approx(raw * 2.0)
    )
    memplan.observe_measured(memplan.fit_model_key(None, "native"), raw, raw * 0.5)
    assert memplan.fit_dispatch_bytes(4, 32, 3, 4, "native") == (
        pytest.approx(raw * 2.0)
    )


# -- plan cache identity (signature-cached lower+compile) --------------------


def test_same_signature_never_relowers():
    class FakeCompiled:
        def cost_analysis(self):
            return {"flops": 8.0, "bytes accessed": 64.0}

        def memory_analysis(self):
            return None

    class FakeJitted:
        lowered = 0

        def lower(self, *args, **kwargs):
            FakeJitted.lowered += 1

            class Lowered:
                def compile(self_inner):
                    return FakeCompiled()

            return Lowered()

    jitted = FakeJitted()
    a = np.zeros((8, 3), dtype=np.float32)
    first = obs_cost.measure(jitted, (a,))
    second = obs_cost.measure(jitted, (np.ones((8, 3), dtype=np.float32),))
    assert FakeJitted.lowered == 1  # same signature: served from cache
    assert first is second
    obs_cost.measure(jitted, (np.zeros((16, 3), dtype=np.float32),))
    assert FakeJitted.lowered == 2  # a new shape IS a new signature


# -- serve admission ---------------------------------------------------------


class _StubPredictor:
    n_features = 3
    active_rows = 40
    dtype = np.float32
    mean_only = True

    @staticmethod
    def padded_rows(n):
        return max(8, n)


def test_predict_request_bytes_uses_padded_bucket_shape():
    small = memplan.predict_request_bytes(_StubPredictor(), 2)
    # padded to the 8-row bucket: a 2-row request costs the dispatch that
    # actually runs
    assert small == memplan.predicted_bytes(
        memplan.predict_dispatch_bytes(8, 40, 3, 4, True)
    )
    memplan.set_memory_planning(False)
    assert memplan.predict_request_bytes(_StubPredictor(), 2) is None


def test_gate_sheds_on_predicted_headroom_and_recovers():
    from spark_gp_tpu.serve.lifecycle import (
        MemoryAdmissionGate,
        MemoryPressureError,
    )

    usage = {"bytes": 100.0}
    gate = MemoryAdmissionGate(
        limit_bytes=1000.0, sample_interval_s=0.0,
        sampler=lambda: usage["bytes"],
    )
    before = _counters()
    gate.check(priority=0, predicted_bytes=500.0)  # fits headroom
    with pytest.raises(MemoryPressureError) as exc:
        gate.check(priority=0, predicted_bytes=950.0)
    assert exc.value.code == "queue.shed.memory"
    assert exc.value.predicted_bytes == 950.0
    gate.check(priority=1, predicted_bytes=950.0)  # the floor still admits
    usage["bytes"] = 10.0
    gate.check(priority=0, predicted_bytes=950.0)  # instant recovery
    snap = gate.snapshot()
    assert snap["plan_sheds"] == 1 and snap["sheds"] == 1
    assert snap["shedding"] is False  # hysteresis latch never engaged
    after = _counters()
    assert after.get("plan.shed", 0.0) == before.get("plan.shed", 0.0) + 1


def test_gate_watermark_hysteresis_untouched_without_prediction():
    from spark_gp_tpu.serve.lifecycle import (
        MemoryAdmissionGate,
        MemoryPressureError,
    )

    usage = {"bytes": 95.0}
    gate = MemoryAdmissionGate(
        limit_bytes=100.0, high_watermark=0.9, low_watermark=0.5,
        sample_interval_s=0.0, sampler=lambda: usage["bytes"],
    )
    with pytest.raises(MemoryPressureError):
        gate.check(priority=0)
    usage["bytes"] = 70.0  # between the watermarks: the latch holds
    with pytest.raises(MemoryPressureError):
        gate.check(priority=0)
    assert gate.snapshot()["plan_sheds"] == 0


# -- provenance: journal + incident bundle -----------------------------------


def test_journal_stamps_predicted_vs_actual(problem, tmp_path, monkeypatch):
    x, y = problem
    monkeypatch.setenv("GP_RUN_JOURNAL_DIR", str(tmp_path))
    limit = _fit_limit_between_segment_and_native(x)
    with chaos.memory_limit_bytes(limit):
        model = _gp().fit(x, y)
    path = model.run_journal["path"]
    assert path is not None
    with open(path, encoding="utf-8") as fh:
        journal = json.load(fh)
    rows = journal["memory_plan"]
    assert rows and rows[0]["chosen"] == "iterative"
    assert rows[0]["predicted_bytes"] >= rows[0]["raw_bytes"]
    # actuals stamped at journal time (device peak is None on CPU — the
    # key must still be present, like-for-like comparisons only)
    assert "actual_peak_bytes" in rows[0]
    assert rows[0]["margin_breach"] is False


def test_incident_bundle_carries_plan_rows_on_terminal_oom(
    problem, tmp_path, monkeypatch
):
    x, y = problem
    monkeypatch.setenv("GP_INCIDENT_DIR", str(tmp_path))
    from spark_gp_tpu.resilience.fallback import DegradationExhaustedError

    # a generous budget (the plan admits native: fits=True) + an injected
    # OOM at EVERY choke point: the ladder exhausts, and the terminal
    # bundle must carry the plan rows next to the measured gauges —
    # predicted-vs-actual on OOM, the debuggable-artifact contract
    with chaos.memory_limit_bytes(1e12):
        with chaos.oom_after_calls(0):
            with pytest.raises(DegradationExhaustedError):
                _gp().fit(x, y)
    bundles = [p for p in os.listdir(tmp_path) if p.startswith("incident_")]
    assert len(bundles) == 1
    with open(tmp_path / bundles[0], encoding="utf-8") as fh:
        bundle = json.load(fh)
    rows = bundle["memory_plan"]
    assert rows and rows[0]["entry"] == "fit" and rows[0]["fits"] is True
    assert bundle["failure_class"] == "oom"


def test_gpctl_plan_renders_predicted_vs_actual(
    problem, tmp_path, monkeypatch
):
    """``python -m tools.gpctl plan DIR`` prints the journals' plan table
    (exit 0) and exits 2 with a readable note on plan-free artifacts."""
    import subprocess
    import sys

    x, y = problem
    monkeypatch.setenv("GP_RUN_JOURNAL_DIR", str(tmp_path))
    limit = _fit_limit_between_segment_and_native(x)
    with chaos.memory_limit_bytes(limit):
        _gp().fit(x, y)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "tools.gpctl", "plan", str(tmp_path)],
        capture_output=True, text=True, timeout=60, cwd=root,
    )
    assert out.returncode == 0, out.stderr
    assert "iterative" in out.stdout and "predicted" in out.stdout
    empty = subprocess.run(
        [sys.executable, "-m", "tools.gpctl", "plan", str(tmp_path / "nope")],
        capture_output=True, text=True, timeout=60, cwd=root,
    )
    assert empty.returncode == 2


# -- knobs -------------------------------------------------------------------


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("GP_MEMPLAN", "0")
    assert not memplan.enabled()
    monkeypatch.setenv("GP_MEMPLAN", "1")
    assert memplan.enabled()
    monkeypatch.setenv("GP_MEMPLAN_MARGIN", "2.0")
    assert memplan.margin() == 2.0
    monkeypatch.setenv("GP_MEMPLAN_MARGIN", "0.5")
    assert memplan.margin() == 1.0  # floored: a margin < 1 is a footgun
    monkeypatch.setenv("GP_MEMPLAN_LIMIT_BYTES", "123456")
    assert memplan.memory_budget_bytes() == 123456.0
    with chaos.memory_limit_bytes(999.0):
        # the chaos stage models the runtime: it wins over the env knob
        assert memplan.memory_budget_bytes() == 999.0


def test_plan_dispatch_none_without_budget(monkeypatch):
    monkeypatch.delenv("GP_MEMPLAN_LIMIT_BYTES", raising=False)
    # CPU backend reports no bytes_limit and no chaos limit is staged:
    # planning imposes no constraint — today's path exactly
    assert memplan.plan_dispatch("fit", [("native", 100.0)]) is None
