"""Matrix-free expert inference — the streaming gram·vector lane (ISSUE 20).

The acceptance bars as tier-1 assertions: every fused family's streamed
matvec matches the dense ``K @ v`` product; the Pallas kernel
(interpret mode) is bit-equivalent to its ``lax.scan`` row-panel oracle;
the matfree NLL/grad match the iterative lane within 1e-5; the compiled
matfree objective carries NO ``[E, s, s]`` buffer while the iterative
compile provably does; a gram-forbidden spy kernel runs the matfree lane
untouched and a prepare-less custom kernel silently falls back to the
materialized path bit-for-bit; budget-aware ``auto`` resolution flips
both directions on ``GP_MEMPLAN_LIMIT_BYTES``; the s = 8192 fit is
plan-admitted under a staged limit the iterative gram exceeds (zero
reactive rungs, ``plan.miss`` = 0); the on-device redundancy scorer
matches the host oracle; and the pin checker bans gram-materializing
calls inside the solver engine files.
"""

import os
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.kernels.base import (
    Const,
    EyeKernel,
    supports_matfree,
)
from spark_gp_tpu.kernels.families import (
    DotProductKernel,
    PeriodicKernel,
    PolynomialKernel,
    RationalQuadraticKernel,
)
from spark_gp_tpu.kernels.matern import (
    Matern12Kernel,
    Matern32Kernel,
    Matern52Kernel,
)
from spark_gp_tpu.models.likelihood import (
    batched_nll,
    make_value_and_grad,
    masked_matfree_operator,
)
from spark_gp_tpu.ops import iterative as it
from spark_gp_tpu.ops.pallas_matvec import (
    TILE_TRANSFORMS,
    matvec_tile,
    matvec_tiles,
    streamed_matvec,
)
from spark_gp_tpu.parallel.experts import ExpertData, group_for_experts

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: lane parity bar from the ISSUE: matfree and iterative run the SAME
#: CG/SLQ program (same probes, same seed, same preconditioner rank), so
#: the only daylight is matvec summation order — float noise, not
#: estimator bias
NLL_GRAD_REL_BAR = 1e-5


@pytest.fixture(autouse=True)
def _clean_solver_lane(monkeypatch):
    """Every test starts and ends on the default (exact) lane, with no
    inherited solver/matvec/memplan knobs (the test_iterative.py
    convention — the knobs are process-global state)."""
    for var in [
        v for v in os.environ
        if v.startswith(("GP_SOLVER_", "GP_MATVEC_", "GP_MEMPLAN"))
    ]:
        monkeypatch.delenv(var, raising=False)
    it.set_solver_lane(None)
    yield
    it.set_solver_lane(None)


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _expert_stack(rng, n=240, s=40, dtype=np.float64):
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    data = group_for_experts(x, y, s)
    return ExpertData(
        x=jnp.asarray(np.asarray(data.x), dtype=dtype),
        y=jnp.asarray(np.asarray(data.y), dtype=dtype),
        mask=jnp.asarray(np.asarray(data.mask), dtype=dtype),
    )


# -- the streaming engine ---------------------------------------------------


def _fused_families(rng):
    """One instance of every family whose tile transform is fused."""
    return [
        RBFKernel(0.7, 1e-6, 10.0),
        Matern12Kernel(0.8, 1e-6, 10.0),
        Matern32Kernel(0.8, 1e-6, 10.0),
        Matern52Kernel(0.8, 1e-6, 10.0),
        RationalQuadraticKernel(0.9, 1.3),
        DotProductKernel(0.5),
        PolynomialKernel(2, 0.7),
    ]


def test_streamed_matvec_matches_dense_every_fused_family(rng):
    """K @ v from streamed tiles == K @ v from the materialized gram, for
    every registered tile transform, at a tile that does NOT divide s
    (the ragged last panel is the easy thing to get wrong)."""
    s, p = 53, 4
    x = jnp.asarray(rng.normal(size=(s, p)))
    v = jnp.asarray(rng.normal(size=(s, 2)))
    for kernel in _fused_families(rng):
        assert supports_matfree(kernel), kernel
        theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=x.dtype)
        dense = kernel.gram(theta, x) @ v
        mcache = kernel.prepare_matvec(x)
        for tile in (8, 16, s):
            streamed = kernel.matvec_from_prepared(
                theta, mcache, v, tile=tile
            )
            np.testing.assert_allclose(
                np.asarray(streamed), np.asarray(dense),
                rtol=1e-10, atol=1e-10,
                err_msg=f"{type(kernel).__name__} tile={tile}",
            )


def test_streamed_matvec_batched_and_vector_rhs(rng):
    """Leading expert batch dims vmap through; a rank-1 RHS round-trips
    through the [., 1] column path."""
    e, s, p = 3, 24, 3
    x = jnp.asarray(rng.normal(size=(e, s, p)))
    v = jnp.asarray(rng.normal(size=(e, s)))
    kernel = RBFKernel(0.6, 1e-6, 10.0)
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=x.dtype)
    out = streamed_matvec(x, v, TILE_TRANSFORMS["rbf"], theta, tile=8)
    assert out.shape == (e, s)
    dense = jnp.einsum(
        "eij,ej->ei", jax.vmap(lambda xe: kernel.gram(theta, xe))(x), v
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dense), rtol=1e-10, atol=1e-10
    )


def test_pallas_interpret_bit_equivalent_to_scan(rng):
    """The fused Pallas kernel (interpret mode off-TPU) walks the same
    (i, j) tile schedule in the same accumulation order as the scan
    fallback — bitwise identical output, the oracle that makes the lane
    tier-1-provable without hardware."""
    s, p = 64, 4
    x = jnp.asarray(rng.normal(size=(s, p)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(s, 3)).astype(np.float32))
    for name in ("rbf", "matern32", "rq"):
        theta = jnp.asarray([0.8, 1.3][: 2 if name == "rq" else 1],
                            dtype=jnp.float32)
        scan = streamed_matvec(
            x, v, TILE_TRANSFORMS[name],
            theta, kind="sqdist", tile=16,
        )
        fused = streamed_matvec(
            x, v, TILE_TRANSFORMS[name],
            theta, kind="sqdist", tile=16, interpret=True,
        )
        assert np.array_equal(np.asarray(scan), np.asarray(fused)), name


def test_matvec_tile_knob_and_tile_count(monkeypatch):
    assert matvec_tile(4096) == 512  # default
    assert matvec_tile(100) == 100  # clamped to s
    monkeypatch.setenv("GP_MATVEC_TILE", "128")
    assert matvec_tile(4096) == 128
    assert matvec_tiles(4096, 128) == 32
    assert matvec_tiles(100) == 1


def test_incapable_families_stay_materialized():
    """ARD metrics / periodic / products have no streaming form — the
    capability gate must say so (the fallback contract rides on it)."""
    assert not supports_matfree(PeriodicKernel(1.0, 1.0))
    assert not supports_matfree(
        RBFKernel(0.7, 1e-6, 10.0) * Matern32Kernel(0.8, 1e-6, 10.0)
    )
    # composites of capable children compose
    assert supports_matfree(
        1.0 * RBFKernel(0.7, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    )


# -- the matfree solver program ---------------------------------------------


def test_pivoted_cholesky_cols_bitwise_vs_materialized(rng):
    """The column-oracle factorization is the SAME scan as the dense one
    — bit-for-bit, so the matfree preconditioner is not a new numeric."""
    e, s = 3, 48
    x = rng.normal(size=(e, s, 3))
    d = ((x[:, :, None, :] - x[:, None, :, :]) ** 2).sum(-1)
    k = jnp.asarray(np.exp(-d / 2.0) + 1e-2 * np.eye(s)[None])
    dense_l, dense_delta = it.pivoted_cholesky(k, 10)
    diag0 = jnp.diagonal(k, axis1=-2, axis2=-1)

    def col_fn(piv):
        return jnp.take_along_axis(k, piv[..., None, None], axis=-1)[..., 0]

    streamed_l, streamed_delta = it.pivoted_cholesky_cols(diag0, col_fn, 10)
    assert np.array_equal(np.asarray(dense_l), np.asarray(streamed_l))
    assert np.array_equal(np.asarray(dense_delta), np.asarray(streamed_delta))


def test_matfree_nll_and_grad_parity_vs_iterative(rng):
    """The lane-vs-lane bar: same CG/SLQ program, injected matvec vs
    materialized gram — NLL and gradient within 1e-5 (measured ~1e-14;
    the bar leaves headroom for f32 accelerators), with a ragged masked
    expert and the jitter operand engaged."""
    kernel = 1.0 * RBFKernel(0.7, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    data = _expert_stack(rng, n=230, s=48)  # last expert ragged
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=data.x.dtype)
    for jitter in (None, 1e-3):
        vals = {}
        for lane in ("iterative", "matfree"):
            it.set_solver_lane(lane)
            try:
                fn = jax.value_and_grad(
                    lambda th: batched_nll(
                        kernel, th, data, jitter=jitter
                    )
                )
                vals[lane] = fn(theta)
            finally:
                it.set_solver_lane(None)
        (v_it, g_it), (v_mf, g_mf) = vals["iterative"], vals["matfree"]
        assert abs(float(v_it - v_mf)) / abs(float(v_it)) < NLL_GRAD_REL_BAR
        g_scale = max(float(np.max(np.abs(np.asarray(g_it)))), 1e-12)
        assert (
            float(np.max(np.abs(np.asarray(g_it - g_mf)))) / g_scale
            < NLL_GRAD_REL_BAR
        ), (jitter, np.asarray(g_it), np.asarray(g_mf))


def test_compiled_matfree_objective_has_no_ess_buffer(rng):
    """The memory proof: the lowered+compiled matfree objective contains
    NO [E, s, s]-shaped tensor anywhere in its optimized HLO, while the
    iterative compile provably does (the self-test that the probe can
    see gram buffers at all).  CPU's memory_analysis() reports zero
    temps, so the buffer scan is on the compiled module text."""
    kernel = 1.0 * RBFKernel(0.7, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    data = _expert_stack(rng, n=512, s=256)
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=data.x.dtype)
    s = int(data.x.shape[1])
    gram_shape = re.compile(rf"\[(?:\d+,)?{s},{s}\]")

    def compiled_text(lane, tile):
        it.set_solver_lane(lane)
        try:
            os.environ["GP_MATVEC_TILE"] = str(tile)
            fn = jax.value_and_grad(
                lambda th: batched_nll(kernel, th, data, jitter=1e-3)
            )
            return jax.jit(fn).lower(theta).compile().as_text()
        finally:
            os.environ.pop("GP_MATVEC_TILE", None)
            it.set_solver_lane(None)

    assert gram_shape.search(compiled_text("iterative", 64)), (
        "probe self-test: the iterative compile should carry the "
        "materialized [E, s, s] gram"
    )
    hits = gram_shape.findall(compiled_text("matfree", 64))
    assert not hits, (
        f"[.., {s}, {s}] buffers survived in the compiled matfree "
        f"objective: {hits[:5]}"
    )


class _GramForbiddenRBF(RBFKernel):
    """RBF whose materialized-gram entry points refuse to trace: proves
    the matfree objective touches the operator only through the
    streaming protocol (prepare_matvec / matvec_from_prepared / diag /
    cross columns)."""

    def gram(self, theta, x):
        raise AssertionError("kernel.gram inside a matfree objective")

    def gram_from_cache(self, theta, cache):
        raise AssertionError(
            "kernel.gram_from_cache inside a matfree objective"
        )


def test_matfree_lane_never_materializes_spy_kernel(rng):
    data = _expert_stack(rng)
    kernel = (
        1.0 * _GramForbiddenRBF(0.6, 1e-6, 10.0)
        + Const(1e-2) * EyeKernel()
    )
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=data.x.dtype)
    it.set_solver_lane("matfree")
    try:
        value, grad = make_value_and_grad(kernel, data)(theta)
    finally:
        it.set_solver_lane(None)
    assert np.isfinite(float(value))
    assert np.all(np.isfinite(np.asarray(grad)))
    # the spy bites on the materialized lane — the test tests itself
    it.set_solver_lane("iterative")
    try:
        with pytest.raises(AssertionError, match="matfree objective"):
            make_value_and_grad(kernel, data)(theta)
    finally:
        it.set_solver_lane(None)


class _PrepareLessRBF(RBFKernel):
    """A user kernel predating the streaming protocol: no
    prepare_matvec/matvec_from_prepared.  The matfree lane must fall
    back to the materialized iterative path bit-for-bit."""

    prepare_matvec = None
    matvec_from_prepared = None


def test_prepare_less_kernel_falls_back_bit_for_bit(rng):
    kernel = (
        1.0 * _PrepareLessRBF(0.6, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    )
    assert not supports_matfree(kernel)
    data = _expert_stack(rng)
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=data.x.dtype)
    out = {}
    for lane in ("iterative", "matfree"):
        it.set_solver_lane(lane)
        try:
            out[lane] = make_value_and_grad(kernel, data)(theta)
        finally:
            it.set_solver_lane(None)
    assert np.array_equal(
        np.asarray(out["iterative"][0]), np.asarray(out["matfree"][0])
    )
    assert np.array_equal(
        np.asarray(out["iterative"][1]), np.asarray(out["matfree"][1])
    )


def test_solver_report_matvec_mode(rng):
    """solver_report with an injected operator (no kmat) reports the
    program that executed — residual at the CG tolerance, same dict
    shape as the materialized mode."""
    kernel = 1.0 * RBFKernel(0.7, 1e-6, 10.0) + Const(1e-2) * EyeKernel()
    data = _expert_stack(rng, n=120, s=24)
    theta = jnp.asarray(np.asarray(kernel.init_theta()), dtype=data.x.dtype)
    mv, mv_sg, diag_sg, col_sg = masked_matfree_operator(
        kernel, theta, data.x, data.mask, jitter=None
    )
    report = it.solver_report(
        None, data.y * data.mask, matvec=mv_sg, diag=diag_sg, col_fn=col_sg
    )
    assert report["residual"] <= 1e-2
    assert report["cg_iters"] >= 1
    assert report["quad_finite"] and report["logdet_finite"]
    for key in ("precond_rank", "probes"):
        assert key in report
    with pytest.raises(ValueError):
        it.solver_report(None, data.y)  # operator mode needs the closures


# -- budget-aware auto resolution -------------------------------------------


def test_auto_resolution_flips_both_ways_on_budget(rng, monkeypatch):
    """A tight GP_MEMPLAN_LIMIT_BYTES flips an s-large auto fit to
    matfree BEFORE the reactive ladder reacts; a generous budget (or no
    budget) keeps the iterative lane.  Both directions, same shapes."""
    from spark_gp_tpu.resilience import memplan

    s, e, p, itemsize = 4096, 4, 3, 8
    iter_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(e, s, p, itemsize, "iterative")
    )
    matfree_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(e, s, p, itemsize, "matfree")
    )
    assert matfree_pred < iter_pred
    monkeypatch.setenv("GP_SOLVER_LANE", "auto")
    kwargs = dict(num_experts=e, n_features=p, itemsize=itemsize)
    # no budget: size-threshold behavior is unchanged
    assert it.resolve_solver(s, **kwargs) == "iterative"
    assert it.resolve_solver(64, **kwargs) == "exact"
    # tight budget (between the two predictions): flips to matfree
    monkeypatch.setenv(
        "GP_MEMPLAN_LIMIT_BYTES",
        str(int((matfree_pred + iter_pred) / 2)),
    )
    assert it.resolve_solver(s, **kwargs) == "matfree"
    assert it.resolve_solver(64, **kwargs) == "exact"  # exact still wins
    # generous budget: flips back
    monkeypatch.setenv("GP_MEMPLAN_LIMIT_BYTES", str(int(2 * iter_pred)))
    assert it.resolve_solver(s, **kwargs) == "iterative"
    # the budget salts the jit key so retrace happens on flip
    monkeypatch.setenv(
        "GP_MEMPLAN_LIMIT_BYTES",
        str(int((matfree_pred + iter_pred) / 2)),
    )
    key_tight = it.solver_jit_key()
    monkeypatch.setenv("GP_MEMPLAN_LIMIT_BYTES", str(int(2 * iter_pred)))
    key_loose = it.solver_jit_key()
    assert key_tight != key_loose


def test_memplan_matfree_rung_rows():
    """The matfree byte model carries NO gram term: its rows undercut
    the iterative rung ever more steeply with s (O(s) vs O(s^2))."""
    from spark_gp_tpu.resilience import memplan

    for s in (256, 2048, 8192):
        matfree = memplan.fit_dispatch_bytes(4, s, 3, 4, "matfree")
        iterative = memplan.fit_dispatch_bytes(4, s, 3, 4, "iterative")
        assert matfree < iterative, (s, matfree, iterative)
    r_small = memplan.fit_dispatch_bytes(4, 256, 3, 4, "iterative") / (
        memplan.fit_dispatch_bytes(4, 256, 3, 4, "matfree")
    )
    r_big = memplan.fit_dispatch_bytes(4, 8192, 3, 4, "iterative") / (
        memplan.fit_dispatch_bytes(4, 8192, 3, 4, "matfree")
    )
    assert r_big > r_small


def test_s8192_fit_plan_admitted_under_staged_limit(rng, monkeypatch):
    """The acceptance run: one s = 8192 expert under a staged memory
    limit the iterative gram stack exceeds.  The fit must be
    plan-admitted onto the matfree rung up front — plan.miss 0, zero
    reactive ladder rungs — and stamp solver_lane=matfree.  The device
    one-dispatch optimizer is the planned path (the host optimizer's
    per-evaluation programs are exempt from planning); tiny
    CG/probe/rank/L-BFGS budgets keep the CPU walltime down; they do
    not change what is being proven (the program's memory shape)."""
    from spark_gp_tpu.resilience import memplan

    n, s = 8192, 8192
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    itemsize = 8  # tests run x64
    iter_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(1, s, 2, itemsize, "iterative")
    )
    matfree_pred = memplan.predicted_bytes(
        memplan.fit_dispatch_bytes(1, s, 2, itemsize, "matfree")
    )
    budget = (matfree_pred + iter_pred) / 2
    assert matfree_pred <= budget < iter_pred
    monkeypatch.setenv("GP_MEMPLAN_LIMIT_BYTES", str(int(budget)))
    monkeypatch.setenv("GP_SOLVER_LANE", "auto")
    monkeypatch.setenv("GP_SOLVER_MAX_ITERS", "3")
    monkeypatch.setenv("GP_SOLVER_PROBES", "1")
    monkeypatch.setenv("GP_SOLVER_PRECOND_RANK", "2")
    monkeypatch.setenv("GP_SOLVER_CG_TOL", "1e-3")
    monkeypatch.setenv("GP_MATVEC_TILE", "1024")
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(s)
        .setActiveSetSize(16)
        .setSeed(3)
        .setTol(1e-3)
        .setMaxIter(1)
        .setOptimizer("device")
        .fit(x, y)
    )
    metrics = model.instr.metrics
    assert metrics["solver_lane"] == "matfree", metrics
    assert metrics.get("solver.matfree_engaged") == 1.0
    assert metrics.get("plan.miss", 0.0) == 0.0, metrics
    assert metrics.get("fallback.engaged", 0.0) == 0.0, metrics
    assert metrics.get("fallback.transitions", 0.0) == 0.0, metrics
    rows = [r for r in model.instr.memory_plan if r["entry"] == "fit"]
    assert rows and rows[-1]["fits"] is True, rows
    # the plan's starting ("native") candidate IS the matfree-priced
    # program — resolve_solver already flipped the auto lane, so the
    # first rung is admitted at the streaming byte model while every
    # materialized candidate is priced over the staged budget
    cands = {c["name"]: c for c in rows[-1]["candidates"]}
    assert rows[-1]["chosen"] == "native", rows
    assert cands["native"]["fits"] is True
    assert cands["native"]["predicted_bytes"] <= rows[-1]["budget_bytes"]
    assert cands["native"]["predicted_bytes"] < iter_pred
    if "iterative" in cands:  # the materialized rung: priced over budget
        assert cands["iterative"]["fits"] is False, cands["iterative"]


# -- the satellites ---------------------------------------------------------


def test_redundancy_scorer_device_matches_host(rng):
    """PR 15's selection sketch scoring, moved on-device: the jitted
    batched centered-cosine must match the host scorer to float noise,
    and GP_AGG_DEVICE_SCORE=0 must restore the host path exactly."""
    from spark_gp_tpu.models import aggregation as agg

    sketches = rng.normal(size=(24, 64))
    sketches[3] = sketches[7]  # one exact duplicate pair
    host = agg.redundancy_matrix_host(sketches)
    device = agg.redundancy_matrix(sketches)
    np.testing.assert_allclose(device, host, rtol=1e-12, atol=1e-12)
    assert device[3, 7] > 0.999
    os.environ["GP_AGG_DEVICE_SCORE"] = "0"
    try:
        forced_host = agg.redundancy_matrix(sketches)
    finally:
        os.environ.pop("GP_AGG_DEVICE_SCORE", None)
    assert np.array_equal(forced_host, host)


def test_sweep_matvec_rows(rng):
    """benchmarks/pallas_sweep.py's fused-matvec lane: importable, one
    labeled row per size, finite timings (interpret mode on CPU)."""
    sys.path.insert(0, os.path.join(ROOT, "benchmarks"))
    try:
        import pallas_sweep
    finally:
        sys.path.pop(0)
    rows = pallas_sweep.sweep_matvec(sizes=(16,), iters=1)
    assert len(rows) == 1
    row = rows[0]
    assert row["lane"] == "matvec" and row["n"] == 16
    assert row["pallas_us_per_matvec"] > 0
    assert row["scan_us_per_matvec"] > 0


def test_no_gram_materialization_inside_solver_engine():
    """tools/check_solver_pins.py's matfree extension as a tier-1 gate:
    a gram_from_cache / prepare_gram_cache call inside ops/iterative.py
    or ops/pallas_matvec.py fails here before it silently rebuilds the
    [E, s, s] buffer the lane exists to avoid."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_solver_pins
    finally:
        sys.path.pop(0)
    violations = check_solver_pins.find_matvec_pins(
        os.path.join(ROOT, "spark_gp_tpu")
    )
    assert violations == [], (
        "gram-materializing calls inside the solver engine files:\n"
        + "\n".join(f"{p}:{n}: {l}" for p, n, l in violations)
    )
    assert check_solver_pins.main([os.path.join(ROOT, "spark_gp_tpu")]) == 0
