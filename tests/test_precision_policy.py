"""Mixed-precision lane guarantees (ops/precision.py, ops/distance.py).

ISSUE 3's acceptance bars as tier-1 assertions: the compensated split-bf16
gram path agrees with the strict (HIGHEST) lane to rtol <= 1e-5 on f32
inputs across every kernel family; mixed-lane grams stay Cholesky-factorable
under the shared JITTER_SCHEDULE; the lane knob round-trips through env,
setter, scope, and the fluent estimator param; the L-BFGS segment carry and
the serve batcher's request buffer are actually donated; and no module
outside ``ops/`` pins a raw ``lax.Precision`` literal
(tools/check_precision_pins.py).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu import (
    ARDRBFKernel,
    DotProductKernel,
    GaussianProcessRegression,
    Matern12Kernel,
    Matern32Kernel,
    Matern52Kernel,
    PeriodicKernel,
    PolynomialKernel,
    RationalQuadraticKernel,
    RBFKernel,
    SpectralMixtureKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.ops import precision
from spark_gp_tpu.ops.distance import mxu_inner, sq_dist, weighted_sq_dist
from spark_gp_tpu.ops.precision import (
    GUARD_BARS,
    LANES,
    active_lane,
    get_policy,
    precision_lane_scope,
    set_precision_lane,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_lane(monkeypatch):
    """Every test starts and ends on the default (strict) lane with no
    env refinements — the knob is process-global state."""
    monkeypatch.delenv("GP_PRECISION_LANE", raising=False)
    monkeypatch.delenv("GP_PRECISION_GRAM", raising=False)
    monkeypatch.delenv("GP_MATMUL_PRECISION", raising=False)
    set_precision_lane(None)
    yield
    set_precision_lane(None)


@pytest.fixture
def rng():
    return np.random.default_rng(11)


# every kernel family with a gram contraction (the sq-dist members, the
# feature-map Periodic, the dot-product members, and the SM mixture);
# p=3 matches each ctor below
_FAMILIES = {
    "rbf": lambda: RBFKernel(0.4),
    "ard_rbf": lambda: ARDRBFKernel(np.array([0.3, 0.6, 1.1])),
    "matern12": lambda: Matern12Kernel(0.8),
    "matern32": lambda: Matern32Kernel(0.8),
    "matern52": lambda: Matern52Kernel(0.8),
    "rq": lambda: RationalQuadraticKernel(0.8, 1.7),
    "periodic": lambda: PeriodicKernel(1.3, 0.6),
    "dot": lambda: DotProductKernel(0.7),
    "poly": lambda: PolynomialKernel(3, 1.2),
    "spectral_mixture": lambda: SpectralMixtureKernel(3, q=2),
    "composite": lambda: 1.0 * RBFKernel(0.4) + WhiteNoiseKernel(0.5, 0, 1),
}

# per-lane accuracy ladder, relative to max|gram| at strict: the
# compensated split drops only the lo.lo term (~2^-18 relative — same
# order as f32 rounding), the 1-pass fast lane keeps bf16's ~2^-8
_LANE_RTOL = {"mixed": 1e-5, "fast": 5e-2}


def _gram_at(kernel, theta, x, lane):
    set_precision_lane(lane)
    try:
        return np.asarray(kernel.gram(theta, x), dtype=np.float64)
    finally:
        set_precision_lane(None)


@pytest.mark.parametrize("family", sorted(_FAMILIES), ids=sorted(_FAMILIES))
@pytest.mark.parametrize("lane", ["mixed", "fast"])
def test_gram_parity_vs_strict_all_families(family, lane, rng):
    """ISSUE 3 acceptance: the compensated (mixed-lane) gram agrees with
    the strict lane to rtol <= 1e-5 on f32 inputs for EVERY kernel
    family; the fast lane holds its own (much looser) bar."""
    kernel = _FAMILIES[family]()
    theta = jnp.asarray(kernel.init_theta(), dtype=jnp.float32)
    x = jnp.asarray(rng.normal(size=(48, 3)), dtype=jnp.float32)

    k_strict = _gram_at(kernel, theta, x, "strict")
    k_lane = _gram_at(kernel, theta, x, lane)

    scale = np.max(np.abs(k_strict))
    assert scale > 0
    err = np.max(np.abs(k_lane - k_strict)) / scale
    assert err <= _LANE_RTOL[lane], (
        f"{family} gram at lane {lane!r}: rel err {err:.3e} exceeds "
        f"{_LANE_RTOL[lane]:.0e} vs strict"
    )


def test_compensated_sq_dist_small_distances(rng):
    """The cancellation case HIGHEST exists for: near-coincident points.
    The compensated path must keep tiny squared distances accurate
    relative to the matrix scale — at 1-pass bf16 they collapse to 0 or
    go wild, which is exactly what the fast lane's looser bar admits."""
    base = rng.normal(size=(32, 4)).astype(np.float32)
    # pairs at ~1e-3 separation on O(1) coordinates: |x|^2 terms ~10,
    # distances ~1e-5 — the three-term identity cancels ~6 digits
    x = jnp.asarray(
        np.concatenate([base, base + 1e-3 * rng.normal(size=base.shape)]),
        dtype=jnp.float32,
    )
    set_precision_lane("strict")
    d_strict = np.asarray(sq_dist(x, x), dtype=np.float64)
    set_precision_lane("mixed")
    d_mixed = np.asarray(sq_dist(x, x), dtype=np.float64)
    w = jnp.asarray(np.array([1.0, 0.5, 2.0, 1.5]), dtype=jnp.float32)
    d_strict_w = np.asarray(weighted_sq_dist(x, x, w), dtype=np.float64)
    d_mixed_w = np.asarray(weighted_sq_dist(x, x, w), dtype=np.float64)
    set_precision_lane(None)

    scale = np.max(d_strict)
    assert np.max(np.abs(d_mixed - d_strict)) / scale < 1e-5
    assert np.max(np.abs(d_mixed_w - d_strict_w)) / np.max(d_strict_w) < 1e-5
    # distances stay clamped nonnegative on every lane
    assert np.min(d_mixed) >= 0.0


def test_f64_inputs_are_lane_immune(rng):
    """The PPA statistics path: f64 contractions bypass the lane entirely
    (lax.Precision is inert there), so the magic-equation statistics are
    bitwise identical on every lane."""
    x64 = jnp.asarray(rng.normal(size=(24, 3)), dtype=jnp.float64)
    if x64.dtype != jnp.float64:
        pytest.skip("x64 disabled in this harness")
    outs = {}
    for lane in LANES:
        set_precision_lane(lane)
        outs[lane] = np.asarray(mxu_inner(x64, x64))
    set_precision_lane(None)
    np.testing.assert_array_equal(outs["strict"], outs["mixed"])
    np.testing.assert_array_equal(outs["strict"], outs["fast"])


def test_mixed_gram_cholesky_stable_under_jitter_schedule(rng):
    """Downstream stability: a mixed-lane RBF gram (with near-duplicate
    rows — the worst cancellation case) plus the usual sigma2 diagonal
    must factor under the shared JITTER_SCHEDULE without exhausting the
    ladder, and reconstruct to gram accuracy."""
    from spark_gp_tpu.ops.linalg import JITTER_SCHEDULE, cholesky_escalated

    base = rng.normal(size=(40, 3)).astype(np.float32)
    x = jnp.asarray(
        np.concatenate([base, base + 1e-4 * rng.normal(size=base.shape)]),
        dtype=jnp.float32,
    )
    kernel = RBFKernel(0.7)
    theta = jnp.asarray(kernel.init_theta(), dtype=jnp.float32)
    set_precision_lane("mixed")
    k = kernel.gram(theta, x)
    set_precision_lane(None)
    kmat = k + 1e-3 * jnp.eye(k.shape[0], dtype=k.dtype)
    chol, tau_max = cholesky_escalated(kmat, "mixed-lane gram")
    chol = np.asarray(chol, dtype=np.float64)
    assert np.all(np.isfinite(chol))
    assert tau_max <= JITTER_SCHEDULE[-1]
    recon = chol @ chol.T
    rel = np.max(np.abs(recon - np.asarray(kmat, dtype=np.float64)))
    assert rel / np.max(np.abs(np.asarray(kmat))) < 1e-4


def test_lane_plumbing_env_setter_scope_roundtrip(monkeypatch):
    """Resolution order: scope > setter > env > strict default; invalid
    names fail loud and NAMED at every entry point."""
    assert active_lane() == "strict"
    assert get_policy() == LANES["strict"]

    monkeypatch.setenv("GP_PRECISION_LANE", "mixed")
    assert active_lane() == "mixed"
    assert get_policy().gram == "compensated"

    # the setter wins over env and returns the previous override
    assert set_precision_lane("fast") is None
    assert active_lane() == "fast"
    assert set_precision_lane("strict") == "fast"
    # a scope wins over both and restores on exit (even nested)
    with precision_lane_scope("mixed"):
        assert active_lane() == "mixed"
        with precision_lane_scope("fast"):
            assert active_lane() == "fast"
        assert active_lane() == "mixed"
    assert active_lane() == "strict"
    # None-scope is a no-op passthrough
    with precision_lane_scope(None):
        assert active_lane() == "strict"
    # clearing the setter falls back to env
    set_precision_lane(None)
    assert active_lane() == "mixed"

    with pytest.raises(ValueError, match="GP_PRECISION_LANE"):
        monkeypatch.setenv("GP_PRECISION_LANE", "bf16")
        active_lane()
    monkeypatch.delenv("GP_PRECISION_LANE")
    with pytest.raises(ValueError, match="set_precision_lane"):
        set_precision_lane("fastest")
    with pytest.raises(ValueError, match="precision_lane_scope"):
        with precision_lane_scope("loose"):
            pass

    # per-stage env refinements override the lane's defaults
    monkeypatch.setenv("GP_PRECISION_GRAM", "high")
    monkeypatch.setenv("GP_MATMUL_PRECISION", "default")
    policy = get_policy()
    assert policy.gram == "high"
    assert policy.linalg == "default"
    monkeypatch.setenv("GP_PRECISION_GRAM", "six-pass")
    with pytest.raises(ValueError, match="GP_PRECISION_GRAM"):
        get_policy()


def test_estimator_setter_is_fluent_and_process_wide():
    """setPrecisionLane is a veneer over the process knob — the fluent
    call returns the estimator and flips the ambient lane."""
    gp = GaussianProcessRegression()
    assert gp.setPrecisionLane("mixed") is gp
    assert active_lane() == "mixed"
    # snake_case alias rides along like the other params
    gp.set_precision_lane("strict")
    assert active_lane() == "strict"
    with pytest.raises(ValueError):
        gp.setPrecisionLane("turbo")


def _tiny_expert_stack(rng, e=2, s=16, p=2):
    x = jnp.asarray(rng.normal(size=(e, s, p)), dtype=jnp.float32)
    y = jnp.asarray(
        np.sin(np.asarray(x).sum(axis=-1)), dtype=jnp.float32
    )
    mask = jnp.ones((e, s), dtype=jnp.float32)
    return x, y, mask


def test_lbfgs_segment_carry_is_donated(rng):
    """The fit-side donation contract: the segment-advance program aliases
    the L-BFGS state carry into its output (HLO carries the aliasing
    annotation), and executing it consumes the input state's buffers —
    so run_segmented's carry never double-buffers in HBM."""
    from spark_gp_tpu.models.likelihood import (
        gpr_device_segment_init,
        gpr_device_segment_run,
    )

    kernel = RBFKernel(0.5, 1e-3, 10.0)
    theta0 = jnp.asarray(kernel.init_theta(), dtype=jnp.float32)
    lower, upper = (
        jnp.asarray(b, dtype=jnp.float32) for b in kernel.bounds()
    )
    x, y, mask = _tiny_expert_stack(rng)
    state = gpr_device_segment_init(
        kernel, None, True, theta0, lower, upper, x, y, mask
    )
    limit = jnp.asarray(3, jnp.int32)
    tol = jnp.asarray(1e-6, jnp.float32)

    lowered = gpr_device_segment_run.lower(
        kernel, None, True, state, lower, upper, x, y, mask, limit, tol
    )
    assert "tf.aliasing_output" in lowered.as_text()

    new_state = gpr_device_segment_run(
        kernel, None, True, state, lower, upper, x, y, mask, limit, tol
    )
    # the donated carry is consumed: its buffers are gone, the returned
    # state is alive and well — live-buffer count stays flat per segment
    assert state.theta.is_deleted()
    assert state.s_hist.is_deleted()
    assert state.y_hist.is_deleted()
    assert not new_state.theta.is_deleted()
    assert np.isfinite(float(new_state.f))
    # ... and the next segment chains off the returned state
    final = gpr_device_segment_run(
        kernel, None, True, new_state, lower, upper, x, y, mask,
        jnp.asarray(6, jnp.int32), tol,
    )
    assert new_state.theta.is_deleted()
    assert np.all(np.isfinite(np.asarray(final.theta)))


def test_batcher_request_buffer_donation_annotations():
    """The predict-side donation contract: the batcher's donating jit
    variant aliases the padded request buffer (arg 4) into its output.
    Lowered explicitly (CPU backends construct the non-donating variant),
    so the annotation is asserted regardless of harness hardware."""
    from spark_gp_tpu.serve.batcher import BucketedPredictor

    rng = np.random.default_rng(3)
    x = rng.normal(size=(120, 2))
    y = np.sin(x.sum(axis=1))
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(20)
        .setSigma2(1e-3)
        .setMaxIter(3)
        .setSeed(5)
        .fit(x, y)
    )
    bp = BucketedPredictor(model.raw_predictor, max_batch=16, min_bucket=8)
    raw = model.raw_predictor
    dtype = jnp.float32
    args = (
        jnp.asarray(raw.theta, dtype=dtype),
        jnp.asarray(raw.active, dtype=dtype),
        jnp.asarray(raw.magic_vector, dtype=dtype),
        jnp.asarray(raw.magic_matrix, dtype=dtype),
        jnp.zeros((8, 2), dtype=dtype),
    )
    donating = bp._make_jit(donate=True)
    assert "tf.aliasing_output" in donating.lower(*args).as_text()
    # the construction-time lane is captured and pinned on the surface
    assert bp.precision_lane == active_lane()


def test_mixed_fit_emits_precision_guard(rng):
    """Every fit at a non-default lane carries the mixed_precision_guard
    artifact: the three relative deltas vs the strict lane plus the
    breach flag, under the lane's bar on this healthy synthetic; a
    strict fit records its lane and no guard deltas."""
    x = rng.normal(size=(300, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=300)

    def fit():
        return (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(1.0))
            .setDatasetSizeForExpert(50)
            .setActiveSetSize(30)
            .setSigma2(1e-3)
            .setMaxIter(5)
            .setSeed(7)
            .fit(x, y)
        )

    set_precision_lane("mixed")
    model = fit()
    set_precision_lane(None)
    metrics = model.instr.metrics
    assert metrics["precision_lane"] == "mixed"
    for leg in ("delta_nll_rel", "delta_grad_rel", "delta_predict_rel"):
        val = metrics[f"mixed_precision_guard.{leg}"]
        assert np.isfinite(val) and val >= 0.0
    assert metrics["mixed_precision_guard.breach"] == 0.0
    worst = max(
        metrics["mixed_precision_guard.delta_nll_rel"],
        metrics["mixed_precision_guard.delta_grad_rel"],
        metrics["mixed_precision_guard.delta_predict_rel"],
    )
    assert worst <= GUARD_BARS["mixed"]

    strict_model = fit()
    strict_metrics = strict_model.instr.metrics
    assert strict_metrics["precision_lane"] == "strict"
    assert not any(
        k.startswith("mixed_precision_guard.") for k in strict_metrics
    )
    # the two lanes' models agree on predictions (the guard's promise,
    # checked end-to-end on the full posterior mean)
    mean_m = model.predict(x)
    mean_s = strict_model.predict(x)
    scale = float(np.max(np.abs(mean_s)))
    assert float(np.max(np.abs(mean_m - mean_s))) / scale < 1e-3


def test_no_raw_precision_pins_outside_ops():
    """tools/check_precision_pins.py as a tier-1 gate: all MXU precision
    choices route through the policy — a new raw ``lax.Precision`` pin
    outside ops/ fails here before it ever lands."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_precision_pins
    finally:
        sys.path.pop(0)

    violations = check_precision_pins.find_pins(
        os.path.join(ROOT, "spark_gp_tpu")
    )
    assert violations == [], (
        "raw lax.Precision pins outside ops/ (route through "
        "ops/precision.py or mark '# precision-pin-ok'):\n"
        + "\n".join(f"{p}:{n}: {l}" for p, n, l in violations)
    )
    # the tool's CLI contract: exit 0 on a clean tree
    assert check_precision_pins.main([os.path.join(ROOT, "spark_gp_tpu")]) == 0
