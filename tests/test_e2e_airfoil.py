"""Airfoil parity regression guard.

Full parity is the reference's 10-fold CV RMSE < 2.1
(Airfoil.scala:24; verified: 2.011 on TPU f32 hot path + f64 PPA stats,
2.012 on CPU f64 — run ``python examples/airfoil.py``).  CI runs a reduced
4-fold variant (less training data per fold -> slightly looser bound) to
stay fast.
"""

import numpy as np

from spark_gp_tpu import ARDRBFKernel, Const, EyeKernel, GaussianProcessRegression
from spark_gp_tpu.data import load_airfoil
from spark_gp_tpu.ops.scaling import scale
from spark_gp_tpu.utils.validation import cross_validate, rmse


def test_airfoil_4fold_rmse():
    x, y = load_airfoil()
    x = np.asarray(scale(x))
    gp = (
        GaussianProcessRegression()
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(1000)
        .setSigma2(1e-4)
        .setKernel(lambda: 1.0 * ARDRBFKernel(5) + Const(1.0) * EyeKernel())
        .setSeed(13)
    )
    score = cross_validate(gp, x, y, num_folds=4, metric=rmse, seed=13)
    assert score < 2.3, f"airfoil 4-fold RMSE {score} regressed"
