"""Airfoil parity regression guard — the reference's OWN bar, not a proxy:
10-fold CV RMSE < 2.1 (Airfoil.scala:24).  Recorded runs: 2.011 on TPU f32
hot path + f64 PPA stats, 2.013 on CPU (QUALITY_r03.json airfoil part).
"""

import numpy as np

from spark_gp_tpu import ARDRBFKernel, Const, EyeKernel, GaussianProcessRegression
from spark_gp_tpu.data import load_airfoil
from spark_gp_tpu.ops.scaling import scale
from spark_gp_tpu.utils.validation import cross_validate, rmse


def test_airfoil_10fold_rmse_parity_bar():
    x, y = load_airfoil()
    x = np.asarray(scale(x))
    gp = (
        GaussianProcessRegression()
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(1000)
        .setSigma2(1e-4)
        .setKernel(lambda: 1.0 * ARDRBFKernel(5) + Const(1.0) * EyeKernel())
        .setSeed(13)
    )
    score = cross_validate(gp, x, y, num_folds=10, metric=rmse, seed=13)
    assert score < 2.1, f"airfoil 10-fold RMSE {score} breaks the parity bar"
