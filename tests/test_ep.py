"""Expectation Propagation engine tests (models/ep.py, models/gpc_ep.py).

Oracle strategy: brute-force numerical integration of the defining
integrals on n <= 2 (scipy dblquad against the probit-Bernoulli GP
posterior — no structure shared with the implementation), finite
differences for the hyperparameter gradient, padding inertness, and
e2e accuracy/calibration parity with the Laplace engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels.base import Const, EyeKernel
from spark_gp_tpu.kernels.rbf import RBFKernel
from spark_gp_tpu.models.ep import (
    _ep_log_z,
    _posterior_marginals,
    batched_neg_logz_ep,
    ep_fit_sites,
)
from spark_gp_tpu.parallel.experts import ExpertData


def _brute_force(K, y_pm):
    """(log Z, posterior mean) by 2-d numerical integration."""
    from scipy import integrate, stats

    def density(f1, f2):
        f = np.array([f1, f2])
        return (
            stats.norm.cdf(y_pm[0] * f1)
            * stats.norm.cdf(y_pm[1] * f2)
            * stats.multivariate_normal.pdf(f, mean=np.zeros(2), cov=K)
        )

    z, _ = integrate.dblquad(
        lambda f2, f1: density(f1, f2), -12, 12, -12, 12,
        epsabs=1e-12, epsrel=1e-10,
    )
    mu = np.array([
        integrate.dblquad(
            lambda f2, f1: [f1, f2][i] * density(f1, f2), -12, 12, -12, 12,
            epsabs=1e-12, epsrel=1e-10,
        )[0] / z
        for i in range(2)
    ])
    return np.log(z), mu


@pytest.mark.parametrize("labels", [(1.0, 1.0), (1.0, -1.0)])
def test_ep_matches_brute_force_integration(rng, labels):
    a = rng.normal(size=(2, 2))
    K = a @ a.T + 0.5 * np.eye(2)
    y_pm = np.asarray(labels)
    logz_true, mu_true = _brute_force(K, y_pm)

    km = jnp.asarray(K[None])
    ypm = jnp.asarray(y_pm[None])
    mask = jnp.ones((1, 2))
    tau, nu, sweeps = ep_fit_sites(
        km, ypm, mask, jnp.zeros((1, 2)), jnp.zeros((1, 2)), 1e-12,
        max_sweeps=200,
    )
    assert int(sweeps) < 200  # converged, not capped
    logz_ep = float(_ep_log_z(km, ypm, mask, tau, nu)[0])
    _, mu_ep, _ = _posterior_marginals(km, tau, nu)
    # EP's intrinsic approximation error at n=2 probit is ~1e-5
    np.testing.assert_allclose(logz_ep, logz_true, atol=2e-4)
    np.testing.assert_allclose(np.asarray(mu_ep[0]), mu_true, atol=2e-4)


def test_ep_gradient_matches_finite_difference(rng):
    n = 10
    x = rng.normal(size=(n, 2))
    y01 = (x.sum(axis=1) > 0).astype(np.float64)
    kernel = RBFKernel(0.8) + Const(1e-2) * EyeKernel()
    data = ExpertData(
        x=jnp.asarray(x[None]), y=jnp.asarray(y01[None]),
        mask=jnp.ones((1, n)),
    )
    sites0 = (jnp.zeros((1, n)), jnp.zeros((1, n)))

    def nll(t):
        value, grad, _ = batched_neg_logz_ep(
            kernel, 1e-12, jnp.asarray(np.array([t])), data, sites0
        )
        return float(value), float(grad[0])

    _, grad = nll(0.8)
    h = 1e-6
    fd = (nll(0.8 + h)[0] - nll(0.8 - h)[0]) / (2 * h)
    np.testing.assert_allclose(grad, fd, rtol=5e-5)


def test_ep_padding_is_inert(rng):
    n = 9
    x = rng.normal(size=(n, 2))
    y01 = (x.sum(axis=1) > 0).astype(np.float64)
    kernel = RBFKernel(0.9) + Const(1e-2) * EyeKernel()
    theta = jnp.asarray(np.array([0.9]))

    def run(xa, ya, maska):
        data = ExpertData(
            x=jnp.asarray(xa[None]), y=jnp.asarray(ya[None]),
            mask=jnp.asarray(maska[None]),
        )
        sites0 = (jnp.zeros((1, len(ya))), jnp.zeros((1, len(ya))))
        return batched_neg_logz_ep(kernel, 1e-12, theta, data, sites0)

    v0, g0, _ = run(x, y01, np.ones(n))
    pad = 3
    xp = np.concatenate([x, np.broadcast_to(x[:1], (pad, 2))])
    yp = np.concatenate([y01, np.zeros(pad)])
    mp = np.concatenate([np.ones(n), np.zeros(pad)])
    v1, g1, sites1 = run(xp, yp, mp)
    np.testing.assert_allclose(float(v1), float(v0), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0), rtol=1e-7)
    # padded sites never move
    np.testing.assert_array_equal(np.asarray(sites1[0][0, n:]), 0.0)


@pytest.mark.parametrize("optimizer", ["host", "device"])
def test_ep_estimator_end_to_end(rng, optimizer):
    from spark_gp_tpu import GaussianProcessEPClassifier

    n = 300
    x = rng.normal(size=(n, 2))
    y = (np.sin(x[:, 0]) + x[:, 1] > 0).astype(np.float64)
    model = (
        GaussianProcessEPClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-3, 10.0))
        .setDatasetSizeForExpert(60)
        .setActiveSetSize(60)
        .setMaxIter(20)
        .setOptimizer(optimizer)
        .fit(x, y)
    )
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.9, acc
    proba = model.predict_proba(x[:20])
    assert proba.shape == (20, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-12)
    # the closed-form averaged probit proba shrinks toward 1/2 vs the
    # unaveraged one (variance always widens the predictive)
    p_avg = model.predict_proba(x[:20], averaged=True)[:, 1]
    p_map = model.predict_proba(x[:20], averaged=False)[:, 1]
    assert np.all(np.abs(p_avg - 0.5) <= np.abs(p_map - 0.5) + 1e-12)


def test_ep_matches_laplace_quality(rng, eight_device_mesh):
    """Same data, same kernel/config: the two inference engines must land
    in the same accuracy regime (they approximate the same posterior), and
    the sharded EP fit must match the single-device EP fit."""
    from spark_gp_tpu import GaussianProcessClassifier, GaussianProcessEPClassifier

    n = 300
    x = rng.normal(size=(n, 2))
    y = (np.sin(x[:, 0]) + x[:, 1] > 0).astype(np.float64)
    # 8% label flips: separable data sends the ML amplitude to infinity
    # (the probit analogue of separable logistic regression), where the
    # two runs would stop at arbitrary different huge values — label noise
    # gives the evidence an interior optimum both runs agree on
    flip = rng.random(n) < 0.08
    y = np.where(flip, 1.0 - y, y)

    def fit(cls, mesh=None, opt="device"):
        g = (
            cls()
            .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-3, 10.0))
            .setDatasetSizeForExpert(60)
            .setActiveSetSize(60)
            .setMaxIter(20)
            .setOptimizer(opt)
        )
        if mesh is not None:
            g.setMesh(mesh)
        return g.fit(x, y)

    acc_laplace = float(np.mean(fit(GaussianProcessClassifier).predict(x) == y))
    m_ep = fit(GaussianProcessEPClassifier)
    acc_ep = float(np.mean(m_ep.predict(x) == y))
    assert acc_ep >= acc_laplace - 0.03, (acc_ep, acc_laplace)

    m_ep_sh = fit(GaussianProcessEPClassifier, mesh=eight_device_mesh)
    np.testing.assert_allclose(
        m_ep_sh.raw_predictor.theta, m_ep.raw_predictor.theta, rtol=1e-3
    )


def test_ep_distributed_and_save_load(rng, eight_device_mesh, tmp_path):
    from spark_gp_tpu import (
        GaussianProcessClassificationModel,
        GaussianProcessEPClassifier,
    )
    from spark_gp_tpu.parallel import distributed as dist

    n = 240
    x = rng.normal(size=(n, 2))
    y = (x.sum(axis=1) > 0).astype(np.float64)
    gdata = dist.distribute_global_experts(x, y, 40, eight_device_mesh)
    model = (
        GaussianProcessEPClassifier()
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(40)
        .setMaxIter(15)
        .setMesh(eight_device_mesh)
        .setOptimizer("device")
        .fit_distributed(gdata)
    )
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.9, acc

    path = str(tmp_path / "ep_model")
    model.save(path)
    # round-trips as the EP model class (own serialization kind): the
    # probit head — including the closed-form averaged probabilities —
    # survives, instead of silently downgrading to the sigmoid model
    from spark_gp_tpu import GaussianProcessEPClassificationModel

    loaded = GaussianProcessEPClassificationModel.load(path)
    np.testing.assert_allclose(
        loaded.predict(x[:20]), model.predict(x[:20]), rtol=1e-12
    )
    np.testing.assert_allclose(
        loaded.predict_proba(x[:20], averaged=True),
        model.predict_proba(x[:20], averaged=True),
        rtol=1e-12,
    )
    # the parent loader also preserves the engine (EP is a subclass)
    via_parent = GaussianProcessClassificationModel.load(path)
    assert isinstance(via_parent, GaussianProcessEPClassificationModel)


def test_ep_checkpoint_dir_falls_back_to_host_and_resumes(rng, tmp_path):
    """setCheckpointDir routes the EP fit through the host driver (the
    device-segmented variant is not wired for EP); the host theta
    checkpointer must write and resume as usual."""
    from spark_gp_tpu import GaussianProcessEPClassifier
    from spark_gp_tpu.utils.checkpoint import load_checkpoint

    n = 200
    x = rng.normal(size=(n, 2))
    y = (x.sum(axis=1) > 0).astype(np.float64)
    flip = rng.random(n) < 0.1
    y = np.where(flip, 1.0 - y, y)

    def gp():
        return (
            GaussianProcessEPClassifier()
            .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-3, 10.0))
            .setDatasetSizeForExpert(50)
            .setActiveSetSize(40)
            .setMaxIter(15)
            .setOptimizer("device")  # checkpoint dir overrides to host
            .setCheckpointDir(str(tmp_path))
        )

    model = gp().fit(x, y)
    ck = load_checkpoint(str(tmp_path), tag="GaussianProcessEPClassifier")
    assert ck is not None and ck[0] >= 1
    model2 = gp().fit(x, y)  # resumes from the persisted theta
    np.testing.assert_allclose(
        model2.raw_predictor.theta, model.raw_predictor.theta, rtol=1e-3
    )
    # resume-specific oracle (a from-scratch refit could also reproduce
    # theta): starting from the persisted optimum, the second run must
    # converge in no more iterations than the first took
    assert (
        model2.instr.metrics["lbfgs_iters"]
        <= model.instr.metrics["lbfgs_iters"]
    )


def test_ep_f32_device_path_is_finite(rng):
    """The TPU device path runs f32; the f64 harness never exercises that
    precision.  The one-dispatch EP fit on an f32 stack must stay finite
    and classify sensibly (cavity math involves 1/sigma^2 cancellations
    that could blow up in single precision)."""
    from spark_gp_tpu.models.ep import fit_gpc_ep_device
    from spark_gp_tpu.optimize.lbfgsb import log_space_applicable
    from spark_gp_tpu.parallel.experts import group_for_experts, ungroup

    n = 200
    x = rng.normal(size=(n, 2))
    y = (np.sin(x[:, 0]) + x[:, 1] > 0).astype(np.float64)
    flip = rng.random(n) < 0.1
    y = np.where(flip, 1.0 - y, y)

    kernel = 1.0 * RBFKernel(1.0, 1e-3, 10.0)
    data = group_for_experts(x, y, 50, dtype=np.float32)
    assert data.x.dtype == jnp.float32
    log_space = log_space_applicable(kernel.init_theta(), kernel.bounds()[0])
    lower, upper = kernel.bounds()
    theta, sites, mu, f, n_iter, _, _ = fit_gpc_ep_device(
        kernel, 1e-4, log_space,
        jnp.asarray(kernel.init_theta(), jnp.float32),
        jnp.asarray(lower, jnp.float32), jnp.asarray(upper, jnp.float32),
        data.x, data.y, data.mask,
        jnp.asarray(15, jnp.int32),
    )
    assert np.all(np.isfinite(np.asarray(theta)))
    assert np.isfinite(float(f))
    mu_np = np.asarray(mu)
    assert np.all(np.isfinite(mu_np))
    # latent sign agrees with the (noisy) labels on most points
    latent = ungroup(mu_np, n)
    agree = float(np.mean((latent > 0) == (y > 0.5)))
    assert agree > 0.8, agree


def test_ep_batched_multistart(rng):
    """setNumRestarts with the device optimizer runs all EP restarts as one
    vmapped dispatch and reports the winner's diagnostics."""
    from spark_gp_tpu import GaussianProcessEPClassifier

    n = 240
    x = rng.normal(size=(n, 2))
    y = (np.sin(x[:, 0]) + x[:, 1] > 0).astype(np.float64)
    flip = rng.random(n) < 0.1
    y = np.where(flip, 1.0 - y, y)

    model = (
        GaussianProcessEPClassifier()
        .setKernel(lambda: 1.0 * RBFKernel(1.0, 1e-3, 10.0))
        .setDatasetSizeForExpert(60)
        .setActiveSetSize(50)
        .setMaxIter(15)
        .setOptimizer("device")
        .setNumRestarts(3)
        .setSeed(5)
        .fit(x, y)
    )
    acc = float(np.mean(model.predict(x) == y))
    assert acc > 0.85, acc
    assert "best_restart" in model.instr.metrics
    assert model.instr.metrics["num_restarts"] == 3
    assert all(f"restart_{r}_nll" in model.instr.metrics for r in range(3))
