"""Tests for the native C++ data-plane runtime (spark_gp_tpu/native).

The library is compiled on first use with g++; when the toolchain is
missing the whole module degrades to numpy and these tests skip.
"""

import os
import tempfile

import numpy as np
import pytest

from spark_gp_tpu import native
from spark_gp_tpu.data.datasets import _read_csv

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def _write_csv(text: str) -> str:
    fd, path = tempfile.mkstemp(suffix=".csv")
    with os.fdopen(fd, "w") as fh:
        fh.write(text)
    return path


def test_read_csv_matches_numpy():
    rng = np.random.default_rng(0)
    data = rng.normal(size=(1000, 7))
    path = _write_csv(
        "\n".join(",".join(f"{v:.17g}" for v in row) for row in data) + "\n"
    )
    try:
        parsed = native.read_csv(path)
        np.testing.assert_array_equal(parsed, np.loadtxt(path, delimiter=","))
        np.testing.assert_allclose(parsed, data, rtol=0, atol=0)
    finally:
        os.unlink(path)


def test_read_csv_skiprows_blank_lines_no_trailing_newline():
    path = _write_csv("header,line\n1,2\n\n3.5,-4e2\n  \n5,6")
    try:
        parsed = native.read_csv(path, skip_rows=1)
        np.testing.assert_allclose(
            parsed, [[1.0, 2.0], [3.5, -400.0], [5.0, 6.0]]
        )
    finally:
        os.unlink(path)


def test_read_csv_errors():
    with pytest.raises(FileNotFoundError):
        native.read_csv("/nonexistent/definitely_missing.csv")
    path = _write_csv("1,2\n3,banana\n")
    try:
        with pytest.raises(ValueError, match="malformed"):
            native.read_csv(path)
    finally:
        os.unlink(path)
    ragged = _write_csv("1,2\n3,4,5\n")
    try:
        with pytest.raises(ValueError, match="malformed"):
            native.read_csv(ragged)
    finally:
        os.unlink(ragged)


def test_zscore_matches_numpy_semantics():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(500, 4)) * [1.0, 10.0, 0.1, 1.0] + [0, 5, -3, 0]
    x[:, 3] = 2.0  # zero-variance column stays unscaled (Scaling.scala:18)
    z = native.zscore(x)
    mean = x.mean(axis=0)
    std = x.std(axis=0)
    std[std == 0] = 1.0
    np.testing.assert_allclose(z, (x - mean) / std, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(z[:, 3], 0.0, atol=1e-12)


def test_dataset_helper_uses_native_and_matches_fallback(monkeypatch):
    rng = np.random.default_rng(2)
    data = rng.normal(size=(50, 3))
    path = _write_csv("\n".join(",".join(map(str, r)) for r in data) + "\n")
    try:
        fast = _read_csv(path)
        monkeypatch.setattr(native, "available", lambda: False)
        slow = _read_csv(path)
        np.testing.assert_array_equal(fast, slow)
    finally:
        os.unlink(path)


def test_large_parallel_parse():
    rng = np.random.default_rng(3)
    data = rng.normal(size=(20000, 12))  # > 64 KiB: exercises the threaded path
    path = _write_csv("\n".join(",".join(f"{v:.17g}" for v in row) for row in data))
    try:
        parsed = native.read_csv(path)
        assert parsed.shape == (20000, 12)
        np.testing.assert_allclose(parsed, data)
    finally:
        os.unlink(path)
