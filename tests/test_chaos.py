"""End-to-end fault injection (`pytest -m chaos`): the resilience layer
proven against live faults driven by resilience/chaos.py — deterministic
(seed/count-driven), fast, and part of tier-1.

The three ISSUE acceptance proofs:
(a) a fit with one NaN-injected expert converges within 2% of the clean
    fit's NLL (after quarantine renormalization);
(b) a fit preempted mid-run resumes from the persisted optimizer state
    and reaches the same final theta (atol 1e-6) as an uninterrupted fit;
(c) a model whose predict raises trips its circuit breaker while the
    server keeps answering health probes and other models' requests.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from spark_gp_tpu import GaussianProcessRegression, RBFKernel
from spark_gp_tpu.parallel.experts import num_experts_for
from spark_gp_tpu.resilience.chaos import (
    PREEMPTION_EXIT_CODE,
    PreemptingCheckpointer,
    SimulatedPreemption,
    break_model,
    failing_cholesky,
    poison_expert,
)

pytestmark = pytest.mark.chaos


def _problem(n=240, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=n)
    return x, y


def _gp(optimizer="device", tmpdir=None, interval=3, max_iter=25):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(40)
        .setActiveSetSize(50)
        .setMaxIter(max_iter)
        .setOptimizer(optimizer)
        .setSeed(3)
    )
    if tmpdir is not None:
        gp.setCheckpointDir(str(tmpdir)).setCheckpointInterval(interval)
    return gp


# -- (a) NaN-injected expert ----------------------------------------------


def test_nan_expert_fit_within_2pct_of_clean_nll():
    # device optimizer only: NaN data is caught by the pre-fit screen
    # BEFORE the optimizer runs, so the host variant exercises an
    # identical path (the host failure-driven recovery is covered by
    # test_conditioning_fault_recovered_by_jitter_not_quarantine below)
    x, y = _problem()
    clean = _gp("device").fit(x, y)
    nll_clean = clean.instr.metrics["final_nll"]

    e = num_experts_for(x.shape[0], 40)
    xp, yp = poison_expert(x, y, expert=2, num_experts=e, kind="nan", seed=1)
    model = _gp("device").fit(xp, yp)

    assert model.instr.metrics["experts_quarantined"] == 1
    renorm = model.instr.metrics["bcm_renorm"]
    assert renorm == pytest.approx(e / (e - 1))
    nll = model.instr.metrics["final_nll_renormalized"]
    assert nll == pytest.approx(model.instr.metrics["final_nll"] * renorm)
    assert abs(nll - nll_clean) <= 0.02 * abs(nll_clean)
    # the survivor predicts, finitely, over the whole input range
    assert np.isfinite(model.predict(x[:20])).all()


def test_inf_label_expert_fit_within_2pct_of_clean_nll():
    """The label-fault class (kind="inf": infinite LABELS, not features).
    Regression for the ``y * keep`` masking bug: inf*0=NaN re-poisoned
    the quarantined sum, so the screen logged a quarantine yet the fit
    still died — labels are now zeroed by selection."""
    x, y = _problem()
    clean = _gp("device").fit(x, y)
    nll_clean = clean.instr.metrics["final_nll"]

    e = num_experts_for(x.shape[0], 40)
    xp, yp = poison_expert(x, y, expert=2, num_experts=e, kind="inf")
    model = _gp("device").fit(xp, yp)

    assert model.instr.metrics["experts_quarantined"] == 1
    nll = model.instr.metrics["final_nll_renormalized"]
    assert np.isfinite(nll)
    assert abs(nll - nll_clean) <= 0.02 * abs(nll_clean)
    assert np.isfinite(model.predict(x[:20])).all()


@pytest.mark.parametrize("kind", ["nan", "inf"])
def test_poisoned_expert_fit_distributed_quarantined(kind, eight_device_mesh):
    """Regression: the sharded entry point's prepare/fit_once closures
    captured the ORIGINAL stack, so the screened (quarantined) stack was
    silently discarded and fit_distributed died on the very fault the
    screen had just diagnosed.  prepare now receives the screened data."""
    from spark_gp_tpu.parallel.experts import group_for_experts

    x, y = _problem()
    e = num_experts_for(x.shape[0], 30)  # 8 experts: divides the mesh
    gp = lambda: (
        GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(50).setMaxIter(10).setSeed(3)
        .setMesh(eight_device_mesh)
    )
    clean = gp().fit_distributed(group_for_experts(x, y, 30))
    nll_clean = clean.instr.metrics["final_nll"]

    xp, yp = poison_expert(x, y, expert=1, num_experts=e, kind=kind)
    model = gp().fit_distributed(group_for_experts(xp, yp, 30))
    assert model.instr.metrics["experts_quarantined"] == 1
    nll = model.instr.metrics["final_nll_renormalized"]
    assert np.isfinite(nll)
    # survive-and-converge is the claim here; losing 1/8 of the data
    # legitimately moves the renormalized objective a few percent (the
    # tight 2% acceptance bar is the single-chip proof above)
    assert abs(nll - nll_clean) <= 0.05 * abs(nll_clean)
    assert np.isfinite(model.predict(x[:10])).all()


def test_conditioning_fault_recovered_by_jitter_not_quarantine():
    """A finite fault (an exactly singular expert at sigma2=0) is repaired
    by the adaptive jitter ladder — no expert is lost.  Host optimizer:
    this drives the failure-driven recovery path end to end through the
    host L-BFGS (non-finite first evaluation -> NotPositiveDefinite ->
    probe -> jitter -> retry); the device variant of the same driver is
    covered by the kill/NaN tests."""
    x, y = _problem(seed=4)
    e = num_experts_for(x.shape[0], 40)
    xp, yp = poison_expert(x, y, expert=1, num_experts=e, kind="dup")
    model = _gp("host", max_iter=15).setSigma2(0.0).fit(xp, yp)
    assert model.instr.metrics["experts_jittered"] == 1
    assert model.instr.metrics.get("experts_quarantined", 0) == 0
    assert model.instr.metrics["fit_retries"] >= 1
    assert np.isfinite(model.instr.metrics["final_nll"])


def test_injected_cholesky_failures_climb_the_ladder(rng):
    """Raised host Cholesky: the ladder absorbs transient failures and
    only an exhausted ladder raises."""
    from spark_gp_tpu.ops.linalg import (
        NotPositiveDefiniteException,
        psd_safe_cholesky_np,
    )

    a = rng.normal(size=(8, 8))
    spd = a @ a.T + 8 * np.eye(8)
    with failing_cholesky(times=2) as fired:
        chol = psd_safe_cholesky_np(spd, "chaos")
    assert fired[0] == 2 and np.all(np.isfinite(chol))

    from spark_gp_tpu.ops.linalg import JITTER_SCHEDULE

    with failing_cholesky(times=100) as fired:
        with pytest.raises(NotPositiveDefiniteException):
            psd_safe_cholesky_np(spd, "chaos")
    assert fired[0] == len(JITTER_SCHEDULE)  # one try per ladder rung


# -- (b) preemption kill-and-resume ---------------------------------------


def _preempting_factory(kill_after, **kw):
    import spark_gp_tpu.utils.checkpoint as ckpt

    original = ckpt.DeviceOptimizerCheckpointer

    def factory(directory, tag="gp", **ck_kw):
        # pass through e.g. the elastic stamp _make_device_checkpointer adds
        return PreemptingCheckpointer(
            original(directory, tag, **ck_kw), kill_after_saves=kill_after,
            **kw
        )

    return factory


def test_kill_and_resume_reaches_same_theta(tmp_path, monkeypatch):
    """Preempted mid-fit (after the 2nd checkpoint save), the restarted
    fit resumes from persisted state and lands on the SAME theta (atol
    1e-6) as the never-interrupted run — the resumed segments re-dispatch
    the identical compiled programs from the identical state."""
    x, y = _problem(seed=1)
    reference = _gp(tmpdir=tmp_path / "ref").fit(x, y)
    theta_ref = reference.raw_predictor.theta

    monkeypatch.setattr(
        "spark_gp_tpu.utils.checkpoint.DeviceOptimizerCheckpointer",
        _preempting_factory(kill_after=2),
    )
    with pytest.raises(SimulatedPreemption):
        _gp(tmpdir=tmp_path / "run").fit(x, y)
    monkeypatch.undo()
    assert (tmp_path / "run" / "gpr_device_lbfgs.npz").exists()

    resumed = _gp(tmpdir=tmp_path / "run").fit(x, y)
    np.testing.assert_allclose(
        resumed.raw_predictor.theta, theta_ref, atol=1e-6
    )
    # the resume consumed the persisted state: iterations continued past
    # the preemption point rather than restarting from iteration 0
    assert resumed.instr.metrics["lbfgs_iters"] == (
        reference.instr.metrics["lbfgs_iters"]
    )


@pytest.mark.slow
def test_kill_and_resume_across_real_process_death(tmp_path):
    """Full-fidelity preemption: the fit runs in a subprocess that
    ``os._exit(137)``s right after a checkpoint save (no unwinding, no
    atexit — a SIGKILL analogue), then a fresh process resumes to the
    uninterrupted optimum."""
    x, y = _problem(seed=1)
    reference = _gp(tmpdir=tmp_path / "ref").fit(x, y)

    code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
import jax
jax.config.update("jax_enable_x64", True)
from spark_gp_tpu.utils.platform import machine_cache_dir
jax.config.update("jax_compilation_cache_dir", machine_cache_dir("/tmp/jax_test_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
import numpy as np
import spark_gp_tpu.utils.checkpoint as ckpt
from spark_gp_tpu.resilience.chaos import PreemptingCheckpointer
_orig = ckpt.DeviceOptimizerCheckpointer
ckpt.DeviceOptimizerCheckpointer = lambda d, t="gp", **kw: PreemptingCheckpointer(
    _orig(d, t, **kw), kill_after_saves=2, exit_process=True
)
from spark_gp_tpu import GaussianProcessRegression, RBFKernel
rng = np.random.default_rng(1)
x = rng.normal(size=(240, 3))
y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=240)
(GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
 .setDatasetSizeForExpert(40).setActiveSetSize(50).setMaxIter(25)
 .setOptimizer("device").setSeed(3)
 .setCheckpointDir({str(tmp_path / "run")!r}).setCheckpointInterval(3)
 .fit(x, y))
os._exit(0)  # unreachable: the checkpointer must have killed us
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        start_new_session=True,
    )
    try:
        _, err = proc.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.communicate()
        pytest.fail("preemption subprocess wedged")
    assert proc.returncode == PREEMPTION_EXIT_CODE, err[-800:]
    assert (tmp_path / "run" / "gpr_device_lbfgs.npz").exists()

    resumed = _gp(tmpdir=tmp_path / "run").fit(x, y)
    np.testing.assert_allclose(
        resumed.raw_predictor.theta, reference.raw_predictor.theta, atol=1e-6
    )


# -- (c) serving: breaker + health under a broken model -------------------


@pytest.fixture(scope="module")
def two_models(tmp_path_factory):
    def fit(seed):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(120, 3))
        y = np.sin(x.sum(axis=1))
        return (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(1.0))
            .setDatasetSizeForExpert(30).setActiveSetSize(30)
            .setMaxIter(5).setSeed(seed).fit(x, y)
        ), x

    d = tmp_path_factory.mktemp("chaos_serve")
    model_a, x = fit(1)
    model_b, _ = fit(2)
    pa, pb = str(d / "a.npz"), str(d / "b.npz")
    model_a.save(pa)
    model_b.save(pb)
    return pa, pb, x


def test_breaker_isolates_broken_model_and_recovers(two_models):
    from spark_gp_tpu.resilience.breaker import BreakerOpenError
    from spark_gp_tpu.serve.server import GPServeServer

    pa, pb, x = two_models
    server = GPServeServer(
        max_batch=16, min_bucket=8, max_wait_ms=1.0,
        breaker_threshold=3, breaker_reset_s=0.1,
    )
    server.register("bad", pa)
    server.register("ok", pb)
    server.start()
    try:
        flaky = break_model(server, "bad", fail_forever=True)
        for _ in range(3):
            with pytest.raises(RuntimeError, match="chaos"):
                server.predict("bad", x[:4], timeout_ms=5000)
        assert flaky.calls >= 3
        # tripped: rejected at the DOOR now — no queue slot, no dispatch
        calls_when_open = flaky.calls
        with pytest.raises(BreakerOpenError):
            server.submit("bad", x[:4])
        assert flaky.calls == calls_when_open
        assert server.metrics.counter("shed.breaker") >= 1
        assert server.metrics.counter("breaker.trips") >= 1

        health = server.health()
        assert health["status"] == "degraded"
        assert health["broken_models"] == ["bad"]
        assert health["ready"]

        # the healthy model never noticed
        mean, var = server.predict("ok", x[:4], timeout_ms=5000)
        assert np.isfinite(mean).all() and len(mean) == 4

        # heal the model; after the cooldown the half-open probe closes
        # the breaker and service resumes
        flaky.fail_forever = False
        time.sleep(0.15)
        mean, _ = server.predict("bad", x[:4], timeout_ms=5000)
        assert np.isfinite(mean).all()
        assert server.health()["status"] == "ok"
        assert server.snapshot()["breakers"]["bad"]["state"] == "closed"
    finally:
        server.stop()


def test_cli_survives_broken_model_keeps_health_and_others(two_models):
    """The ISSUE acceptance proof at the REAL process boundary: one model
    broken (chaos env hook), every request to it errors, yet the CLI
    answers health and the other model's requests and shuts down clean."""
    pa, pb, x = two_models
    rows = x[:3].tolist()
    lines = "\n".join(
        [
            json.dumps({"op": "health"}),
            json.dumps({"id": 1, "model": "bad", "x": rows}),
            json.dumps({"id": 2, "model": "bad", "x": rows}),
            json.dumps({"id": 3, "model": "bad", "x": rows}),
            json.dumps({"id": 4, "model": "ok", "x": rows}),
            json.dumps({"cmd": "metrics"}),
            json.dumps({"cmd": "shutdown"}),
        ]
    ) + "\n"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["GP_CHAOS_BREAK_MODEL"] = "bad"
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_gp_tpu.serve",
         "--model", f"bad={pa}", "--model", f"ok={pb}",
         # threshold 1: the first failed dispatch trips, regardless of how
         # the three bad requests happen to coalesce (isolation re-runs
         # are breaker-unguarded payload probes and never count)
         "--breaker-threshold", "1"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env, start_new_session=True,
    )
    try:
        out, err = proc.communicate(lines, timeout=240)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        out, err = proc.communicate()
        pytest.fail(f"serve CLI wedged; stderr: {err[-500:]}")
    assert proc.returncode == 0, err[-800:]
    events = [json.loads(ln) for ln in out.strip().splitlines()]

    health = next(e for e in events if e.get("event") == "health")
    assert health["status"] in ("ok", "degraded")  # answered, either way
    assert sorted(health["models"]) == ["bad", "ok"]

    by_id = {e["id"]: e for e in events if "id" in e}
    for req_id in (1, 2, 3):
        assert "error" in by_id[req_id], by_id[req_id]
    assert "mean" in by_id[4], by_id[4]  # the healthy model kept serving

    # metrics rides the ordered writer queue, so by the time it is
    # emitted every earlier predict has resolved: the breaker MUST have
    # tripped by now (threshold 1, at least one failed dispatch)
    metrics = next(e for e in events if e.get("event") == "metrics")
    assert metrics["counters"]["predict.failures"] >= 1
    assert metrics["breakers"]["bad"]["trips"] >= 1
    assert events[-1]["event"] == "shutdown"
