"""Tests for the multi-host helpers (parallel/distributed.py).

True multi-process DCN behavior can't run in a single-container CI; what is
testable: the single-process degradation path end-to-end on the 8-device
simulated mesh, initialize()'s no-op contract, and the padding algebra used
to equalize per-host expert stacks.
"""

import numpy as np
import jax

from spark_gp_tpu.parallel import distributed as dist
from spark_gp_tpu.parallel.experts import group_for_experts
from spark_gp_tpu.parallel.mesh import EXPERT_AXIS


def test_initialize_single_process_noop():
    dist.initialize()  # must not raise or spin up a coordinator
    assert dist.num_processes() == 1


def test_initialize_after_backend_hard_fails_on_coordinator_env(monkeypatch):
    """Coordinator env vars indicate a REAL multi-process launch: silently
    continuing single-process would train 1/P of the data per host with no
    error (and burn the pod allocation) — must hard-fail once the XLA
    backend is up (VERDICT r3 weak #6)."""
    import pytest

    jax.devices()  # ensure the backend is initialized
    monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:8476")
    with pytest.raises(RuntimeError, match="after the XLA backend"):
        dist.initialize()


def test_initialize_after_backend_hard_fails_on_multihost_hostnames(monkeypatch):
    import pytest

    jax.devices()
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-0,host-1,host-2")
    with pytest.raises(RuntimeError, match="after the XLA backend"):
        dist.initialize()


def test_initialize_after_backend_hard_fails_on_explicit_args():
    import pytest

    jax.devices()
    with pytest.raises(RuntimeError, match="after the XLA backend"):
        dist.initialize(
            coordinator_address="10.0.0.1:8476", num_processes=2, process_id=0
        )


def test_initialize_after_backend_single_host_site_warns(monkeypatch):
    """A single-host TPU site (TPU_WORKER_HOSTNAMES=localhost, no
    coordinator) is NOT a multi-process launch: defensive library calls must
    degrade to single-process with a warning, not crash."""
    import pytest

    jax.devices()
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    with pytest.warns(RuntimeWarning, match="Continuing single-process"):
        dist.initialize()
    assert dist.num_processes() == 1


def test_silent_degrade_counts_coord_degraded_metric(monkeypatch):
    """The RuntimeWarning branches are how pod misconfiguration ships: a
    warning scrolls by, the fit trains 1/P of the data.  Each silent
    degrade must ALSO land in the telemetry (``coord.degraded``) and as a
    span event, so OpenMetrics pages and run journals carry the evidence."""
    import pytest

    from spark_gp_tpu.obs import trace as obs_trace
    from spark_gp_tpu.obs.runtime import telemetry

    jax.devices()
    monkeypatch.delenv("COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    before = telemetry.counters.get("coord.degraded", 0.0)
    with obs_trace.span("degrade_probe") as root:
        with pytest.warns(RuntimeWarning, match="Continuing single-process"):
            dist.initialize()
    assert telemetry.counters.get("coord.degraded", 0.0) == before + 1
    assert any(e["name"] == "coord.degraded" for e in root.events)


def test_global_mesh_spans_devices():
    mesh = dist.global_expert_mesh()
    assert mesh.axis_names == (EXPERT_AXIS,)
    assert mesh.devices.size == len(jax.devices())


def test_distribute_single_process_matches_shard_experts():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(130, 3))
    y = rng.normal(size=130)
    mesh = dist.global_expert_mesh()
    data = dist.distribute_global_experts(x, y, 16, mesh)
    ref = group_for_experts(x, y, 16).pad_experts(mesh.devices.size)
    np.testing.assert_array_equal(np.asarray(data.x), np.asarray(ref.x))
    np.testing.assert_array_equal(np.asarray(data.mask), np.asarray(ref.mask))
    # sharded on the expert axis across the whole mesh
    assert data.x.sharding.spec[0] == EXPERT_AXIS


def test_distributed_fit_on_simulated_mesh():
    """The helper's output feeds the sharded fit path directly."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    rng = np.random.default_rng(1)
    x = rng.normal(size=(400, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=400)
    mesh = dist.global_expert_mesh()
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setDatasetSizeForExpert(50)
        .setActiveSetSize(60)
        .setMaxIter(15)
        .setMesh(mesh)
        .fit(x, y)
    )
    pred = model.predict(x)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.2


def test_sample_active_from_stack_replicated_valid_rows():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(130, 3))
    y = rng.normal(size=130)
    mesh = dist.global_expert_mesh()
    data = dist.distribute_global_experts(x, y, 16, mesh)
    active = dist.sample_active_from_stack(data, 20, seed=7, mesh=mesh)
    assert active.shape == (20, 3)
    # every selected row is a real data row (mask excluded padding)
    rows = {tuple(np.round(r, 12)) for r in x}
    for r in np.asarray(active):
        assert tuple(np.round(r, 12)) in rows
    # deterministic across "hosts": same seed -> same selection
    again = dist.sample_active_from_stack(data, 20, seed=7, mesh=mesh)
    np.testing.assert_array_equal(active, again)


def test_fit_distributed_single_process():
    """fit_distributed consumes a pre-sharded stack end-to-end."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    rng = np.random.default_rng(4)
    x = rng.normal(size=(400, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=400)
    mesh = dist.global_expert_mesh()
    data = dist.distribute_global_experts(x, y, 50, mesh)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(60)
        .setMaxIter(15)
        .setMesh(mesh)
        .fit_distributed(data)
    )
    pred = model.predict(x)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.2


def test_pad_stack_algebra():
    rng = np.random.default_rng(2)
    data = group_for_experts(rng.normal(size=(60, 2)), rng.normal(size=60), 10)
    padded = dist._pad_stack(data, data.num_experts + 2, data.expert_size + 3)
    assert padded.x.shape == (data.num_experts + 2, data.expert_size + 3, 2)
    # padded slots masked out; real slots preserved
    np.testing.assert_array_equal(
        np.asarray(padded.x)[: data.num_experts, : data.expert_size],
        np.asarray(data.x),
    )
    np.testing.assert_array_equal(
        np.asarray(padded.mask)[: data.num_experts, : data.expert_size],
        np.asarray(data.mask),
    )
    assert float(np.asarray(padded.mask)[data.num_experts :].sum()) == 0.0
    assert float(np.asarray(padded.mask)[:, data.expert_size :].sum()) == 0.0
    assert np.all(np.isfinite(np.asarray(padded.x)))


def test_gpc_fit_distributed_single_process():
    """Classifier fit from a pre-sharded stack: end-to-end on the 8-device
    mesh, quality parity with plain fit (VERDICT r2 missing #1)."""
    from spark_gp_tpu import GaussianProcessClassifier
    from spark_gp_tpu.utils.validation import accuracy

    rng = np.random.default_rng(7)
    x = rng.normal(size=(240, 2))
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    mesh = dist.global_expert_mesh()

    def gpc():
        return (
            GaussianProcessClassifier()
            .setDatasetSizeForExpert(30)
            .setActiveSetSize(40)
            .setMaxIter(20)
        )

    a_plain = accuracy(y, gpc().setMesh(mesh).fit(x, y).predict(x))
    data = dist.distribute_global_experts(x, y, 30, mesh)
    model = gpc().setMesh(mesh).fit_distributed(data)
    a_dist = accuracy(y, model.predict(x))
    assert a_dist >= 0.9
    assert a_dist >= a_plain - 0.05, (a_dist, a_plain)


def test_gpc_fit_distributed_rejects_bad_labels():
    from spark_gp_tpu import GaussianProcessClassifier

    rng = np.random.default_rng(8)
    x = rng.normal(size=(64, 2))
    y = rng.normal(size=64)  # not {0,1}
    mesh = dist.global_expert_mesh()
    data = dist.distribute_global_experts(x, y, 8, mesh)
    import pytest

    with pytest.raises(ValueError, match="0 and 1"):
        GaussianProcessClassifier().setMesh(mesh).fit_distributed(data)


def test_fit_distributed_with_kmeans_and_greedy_providers():
    """kmeans/greedy providers run natively from the sharded stack instead
    of degrading to random (VERDICT r2 missing #2)."""
    from spark_gp_tpu import (
        GaussianProcessRegression,
        GreedilyOptimizingActiveSetProvider,
        KMeansActiveSetProvider,
        RBFKernel,
    )

    rng = np.random.default_rng(9)
    x = rng.normal(size=(400, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=400)
    mesh = dist.global_expert_mesh()
    data = dist.distribute_global_experts(x, y, 50, mesh)

    import warnings

    for provider in (KMeansActiveSetProvider(), GreedilyOptimizingActiveSetProvider()):
        with warnings.catch_warnings():
            warnings.simplefilter("error")  # any fallback warning = failure
            model = (
                GaussianProcessRegression()
                .setKernel(lambda: RBFKernel(1.0))
                .setActiveSetSize(60)
                .setMaxIter(15)
                .setActiveSetProvider(provider)
                .setMesh(mesh)
                .fit_distributed(data)
            )
        pred = model.predict(x)
        rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
        assert rmse < 0.2, (type(provider).__name__, rmse)


def test_gpc_fit_distributed_with_greedy_provider():
    """The classifier's distributed provider path selects over the LATENT
    targets from the sharded stack (GPClf.scala:62-65 substitutes f for y);
    greedy must run natively — no fallback warning — and produce a working
    model."""
    import warnings

    from spark_gp_tpu import (
        GaussianProcessClassifier,
        GreedilyOptimizingActiveSetProvider,
    )
    from spark_gp_tpu.utils.validation import accuracy

    rng = np.random.default_rng(11)
    x = rng.normal(size=(240, 2))
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    mesh = dist.global_expert_mesh()
    data = dist.distribute_global_experts(x, y, 30, mesh)

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        model = (
            GaussianProcessClassifier()
            .setDatasetSizeForExpert(30)
            .setActiveSetSize(40)
            .setMaxIter(15)
            .setActiveSetProvider(GreedilyOptimizingActiveSetProvider())
            .setMesh(mesh)
            .fit_distributed(data)
        )
    assert accuracy(y, model.predict(x)) >= 0.9


def test_fit_distributed_elbo_objective():
    """setObjective('elbo') through fit_distributed: the provider selects
    the inducing set from the sharded stack up front (no host holds the
    rows), the GSPMD objective trains over the mesh, and the same set
    builds the PPA model."""
    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    rng = np.random.default_rng(4)
    x = rng.normal(size=(400, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=400)
    mesh = dist.global_expert_mesh()
    data = dist.distribute_global_experts(x, y, 50, mesh)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(60)
        .setMaxIter(15)
        .setSigma2(1e-2)
        .setObjective("elbo")
        .setMesh(mesh)
        .fit_distributed(data)
    )
    pred = model.predict(x)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.2
    assert np.isfinite(model.instr.metrics["final_nll"])
    assert model.raw_predictor.active.shape == (60, 3)
