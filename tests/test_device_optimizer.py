"""On-device L-BFGS parity with the host SciPy driver."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessClassifier,
    GaussianProcessRegression,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.data import load_iris, make_synthetics
from spark_gp_tpu.optimize.lbfgs_device import lbfgs_minimize_device
from spark_gp_tpu.utils.validation import accuracy, rmse


def test_quadratic_with_box():
    """Minimum outside the box lands on the boundary (L-BFGS-B semantics)."""
    target = jnp.asarray([-3.0, 7.0])

    def vag(theta, aux):
        return jnp.sum((theta - target) ** 2), 2 * (theta - target), aux

    theta, f, _, n_iter, _, stalled = lbfgs_minimize_device(
        vag,
        jnp.asarray([0.5, 0.5]),
        jnp.asarray([0.0, 0.0]),
        jnp.asarray([1.0, 5.0]),
        jnp.zeros(()),
        max_iter=jnp.asarray(100),
        tol=jnp.asarray(1e-10),
    )
    np.testing.assert_allclose(np.asarray(theta), [0.0, 5.0], atol=1e-6)
    assert not bool(stalled)


def test_rosenbrock_unbounded():
    def vag(theta, aux):
        a, b = theta[0], theta[1]
        f = (1 - a) ** 2 + 100 * (b - a**2) ** 2
        g = jnp.asarray(
            [-2 * (1 - a) - 400 * a * (b - a**2), 200 * (b - a**2)]
        )
        return f, g, aux

    theta, f, _, n_iter, _, _ = lbfgs_minimize_device(
        vag,
        jnp.asarray([-1.2, 1.0]),
        jnp.asarray([-jnp.inf, -jnp.inf]),
        jnp.asarray([jnp.inf, jnp.inf]),
        jnp.zeros(()),
        max_iter=jnp.asarray(300),
        tol=jnp.asarray(1e-14),
    )
    np.testing.assert_allclose(np.asarray(theta), [1.0, 1.0], atol=1e-4)


def test_stalled_line_search_reported():
    """A line search that can never accept a step must surface stalled=True,
    distinct from convergence (VERDICT r2 weak #4)."""

    def vag(theta, aux):
        # Adversarial gradient pointing away from descent: every candidate
        # along the search direction increases f, so Armijo never passes.
        return jnp.sum(theta), -jnp.ones_like(theta), aux

    theta, f, _, n_iter, _, stalled = lbfgs_minimize_device(
        vag,
        jnp.asarray([1.0, 2.0]),
        jnp.asarray([-jnp.inf, -jnp.inf]),
        jnp.asarray([jnp.inf, jnp.inf]),
        jnp.zeros(()),
        max_iter=jnp.asarray(50),
        tol=jnp.asarray(1e-12),
    )
    assert bool(stalled)
    assert int(n_iter) < 50  # ended by stall, not the iteration cap


def _gpr(opt, mesh=None):
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1))
        .setDatasetSizeForExpert(60)
        .setActiveSetSize(60)
        .setSeed(13)
        .setSigma2(1e-3)
        .setOptimizer(opt)
    )
    if mesh is not None:
        gp.setMesh(mesh)
    return gp


def test_gpr_device_matches_host_quality():
    x, y = make_synthetics(n=500)
    m_host = _gpr("host").fit(x, y)
    m_dev = _gpr("device").fit(x, y)
    r_host = rmse(y, m_host.predict(x))
    r_dev = rmse(y, m_dev.predict(x))
    assert r_dev < 0.11
    np.testing.assert_allclose(r_dev, r_host, atol=2e-3)
    # both paths surface the termination status the same way: a healthy fit
    # reports lbfgs_stalled == 0 (host: scipy success; device: line-search
    # exhaustion flag)
    assert m_host.instr.metrics["lbfgs_stalled"] == 0
    assert m_dev.instr.metrics["lbfgs_stalled"] == 0


def test_gpr_device_sharded(eight_device_mesh):
    x, y = make_synthetics(n=500)
    r = rmse(y, _gpr("device", eight_device_mesh).fit(x, y).predict(x))
    assert r < 0.11


def test_gpc_device_matches_host_quality(eight_device_mesh):
    x, y = load_iris()
    yb = (y == 2.0).astype(np.float64)

    def gpc(opt, mesh=None):
        g = (
            GaussianProcessClassifier()
            .setDatasetSizeForExpert(20)
            .setActiveSetSize(30)
            .setOptimizer(opt)
        )
        if mesh is not None:
            g.setMesh(mesh)
        return g

    a_host = accuracy(yb, gpc("host").fit(x, yb).predict(x))
    a_dev = accuracy(yb, gpc("device").fit(x, yb).predict(x))
    a_dev_sh = accuracy(yb, gpc("device", eight_device_mesh).fit(x, yb).predict(x))
    assert a_dev >= a_host - 0.02
    assert a_dev_sh >= a_host - 0.02


def test_multistart_frozen_lane_keeps_own_diagnostics():
    """Under vmap the batched while_loop steps every lane until ALL are
    done; the body's done guard must freeze finished lanes so a lane that
    converged early reports its OWN n_iter/stalled, not the global loop
    count (ADVICE r3: a converged lane whose line search could no longer
    move used to end flagged 'stalled')."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        lbfgs_minimize_device_multistart,
    )

    target = jnp.asarray([2.0, -1.0])

    def vag(theta, aux):
        return jnp.sum((theta - target) ** 2), 2 * (theta - target), aux

    # lane 0 starts AT the optimum (converges on iteration 1);
    # lane 1 starts far away (needs several iterations)
    theta0 = jnp.stack([target, target + 40.0])
    thetas, fs, _, iters, fevs, stalls = jax.vmap(
        lambda t0: lbfgs_minimize_device(
            vag, t0,
            jnp.asarray([-jnp.inf, -jnp.inf]), jnp.asarray([jnp.inf, jnp.inf]),
            jnp.zeros(()), max_iter=jnp.asarray(100), tol=jnp.asarray(1e-10),
        )
    )(theta0)
    assert int(iters[1]) > int(iters[0])  # lanes genuinely differ
    assert int(iters[0]) <= 2  # frozen at its own convergence, not global
    # entry-point KKT: the stationary lane skips the line search entirely
    # (n_fev stays at the init evaluation) instead of burning max_ls evals
    assert int(fevs[0]) == 1
    assert not bool(stalls[0])  # converged, never re-flagged as stalled
    assert not bool(stalls[1])
    np.testing.assert_allclose(np.asarray(thetas[0]), np.asarray(target), atol=1e-8)
    np.testing.assert_allclose(np.asarray(thetas[1]), np.asarray(target), atol=1e-6)

    # the multistart wrapper returns the winner's own diagnostics
    theta_b, f_b, _, it_b, fev_b, st_b, f_all, best = (
        lbfgs_minimize_device_multistart(
            vag, theta0,
            jnp.asarray([-jnp.inf, -jnp.inf]), jnp.asarray([jnp.inf, jnp.inf]),
            jnp.zeros(()), max_iter=100, tol=1e-10,
        )
    )
    assert int(best) == 0 and int(it_b) <= 2 and not bool(st_b)


def test_cauchy_point_matches_path_oracle():
    """The generalized Cauchy point is the FIRST LOCAL minimizer of the
    quadratic model along the projected steepest-descent path (Byrd et al.
    1995 — the piecewise quadratic can have several local minima and the CP
    algorithm stops at the first) — checked against a brute-force dense
    sampling of m(P(x - t g)) over t (no structure shared with the
    implementation)."""
    from spark_gp_tpu.optimize.lbfgs_device import _cauchy_point

    rng = np.random.default_rng(3)
    for trial in range(20):
        h = int(rng.integers(2, 7))
        a = rng.normal(size=(h, h))
        b_mat = a @ a.T + 0.5 * np.eye(h)  # SPD model Hessian
        x = rng.normal(size=h)
        g = rng.normal(size=h)
        lower = x - rng.uniform(0.05, 3.0, size=h)
        upper = x + rng.uniform(0.05, 3.0, size=h)

        z_c, fixed = _cauchy_point(
            jnp.asarray(x), jnp.asarray(g), jnp.asarray(lower),
            jnp.asarray(upper), jnp.asarray(b_mat),
        )
        z_c = np.asarray(z_c)

        def model(z):
            return g @ z + 0.5 * z @ b_mat @ z

        # brute force along the projected path: first local minimizer
        ts = np.linspace(0.0, 20.0, 200001)
        zs = np.clip(x[None] - ts[:, None] * g[None], lower, upper) - x[None]
        vals = (zs @ g) + 0.5 * np.einsum("ti,ij,tj->t", zs, b_mat, zs)
        rising = np.nonzero(np.diff(vals) > 0)[0]
        first_min = vals[rising[0]] if rising.size else vals[-1]
        np.testing.assert_allclose(
            model(z_c), first_min, atol=1e-4, err_msg=str(trial)
        )
        # and the Cauchy point lies on the path (some t reproduces it)
        assert np.min(np.max(np.abs(zs - z_c[None]), axis=1)) < 5e-4


def test_subspace_step_is_quasi_newton_in_interior():
    """With no active bounds the LBFGSB proposal must equal the
    unconstrained quasi-Newton step -B^-1 g for the SAME dense B built from
    the history."""
    from spark_gp_tpu.optimize.lbfgs_device import (
        _dense_b_from_history,
        _lbfgsb_direction,
    )

    rng = np.random.default_rng(5)
    h, m_hist = 4, 10
    s_hist = np.zeros((m_hist, h))
    y_hist = np.zeros((m_hist, h))
    # three curvature pairs with s.y > 0
    for i in range(3):
        s = rng.normal(size=h)
        y = s + 0.3 * rng.normal(size=h)
        if s @ y < 0:
            y = -y
        s_hist[i] = s
        y_hist[i] = y
    count = jnp.asarray(3, jnp.int32)
    head = jnp.asarray(3, jnp.int32)
    x = jnp.asarray(rng.normal(size=h))
    g = jnp.asarray(rng.normal(size=h))
    inf = jnp.asarray(np.full(h, np.inf))

    d = _lbfgsb_direction(
        x, g, -inf, inf, jnp.asarray(s_hist), jnp.asarray(y_hist),
        count, head, m_hist,
    )
    b_mat = np.asarray(
        _dense_b_from_history(
            jnp.asarray(s_hist), jnp.asarray(y_hist), count, head, m_hist
        )
    )
    np.testing.assert_allclose(
        np.asarray(d), -np.linalg.solve(b_mat, np.asarray(g)), rtol=1e-8
    )


def test_lbfgsb_matches_scipy_on_bounded_problems():
    """Converged iterates match scipy's reference L-BFGS-B on problems whose
    minima sit on faces, corners, and in the interior of the box."""
    import scipy.optimize

    target = jnp.asarray([-3.0, 7.0, 0.2])

    def quad(t):
        return jnp.sum((t - target) ** 2), 2 * (t - target)

    def rosen(t):
        a, b = t[0], t[1]
        f = (1 - a) ** 2 + 100 * (b - a ** 2) ** 2
        g = jnp.stack(
            [-2 * (1 - a) - 400 * a * (b - a ** 2), 200 * (b - a ** 2)]
        )
        return f, g

    problems = [
        # bounded quadratic, minimum on a face
        (quad, np.asarray([0.5, 0.5, 0.5]),
         np.asarray([0.0, 0.0, 0.0]), np.asarray([1.0, 5.0, 1.0])),
        # Rosenbrock with the unconstrained minimum excluded (corner active)
        (rosen, np.asarray([-1.2, 0.5]),
         np.asarray([-2.0, -1.0]), np.asarray([0.8, 0.6])),
        # interior minimum (bounds inactive)
        (rosen, np.asarray([-1.2, 1.0]),
         np.asarray([-5.0, -5.0]), np.asarray([5.0, 5.0])),
    ]

    for fn, x0, lo, hi in problems:
        ref = scipy.optimize.minimize(
            lambda t: tuple(np.asarray(v, dtype=np.float64) for v in fn(jnp.asarray(t))),
            x0, jac=True, method="L-BFGS-B",
            bounds=list(zip(lo, hi)),
            options={"maxiter": 500, "ftol": 1e-15, "gtol": 1e-12},
        )

        def vag(theta, aux):
            f, g = fn(theta)
            return f, g, aux

        theta, f, _, n_iter, _, stalled = lbfgs_minimize_device(
            vag, jnp.asarray(x0), jnp.asarray(lo), jnp.asarray(hi),
            jnp.zeros(()), max_iter=jnp.asarray(500), tol=jnp.asarray(1e-12),
        )
        np.testing.assert_allclose(np.asarray(theta), ref.x, atol=2e-5)


def test_device_matches_host_on_airfoil_ard_config():
    """The airfoil kernel (trainable scale + 5-d ARD + const noise) in
    LINEAR hyper space: ARD lower bounds sit at 0 and fitted betas
    routinely land ON the boundary — the regime the generalized-Cauchy/
    subspace step exists for.  (setHyperSpace("linear") is load-bearing:
    under the default "auto" this config optimizes log-reparameterized
    with bounds mapped to infinity, and no bound is ever active.)  Device
    fit must match host-scipy quality on a real subset."""
    from spark_gp_tpu import ARDRBFKernel, Const, EyeKernel
    from spark_gp_tpu.data import load_airfoil
    from spark_gp_tpu.ops.scaling import scale

    x, y = load_airfoil()
    x = np.asarray(scale(x))[:600]
    y = y[:600]

    def gp(opt):
        return (
            GaussianProcessRegression()
            .setKernel(lambda: 1.0 * ARDRBFKernel(5) + Const(1.0) * EyeKernel())
            .setDatasetSizeForExpert(100)
            .setActiveSetSize(200)
            .setSigma2(1e-4)
            .setMaxIter(40)
            .setSeed(13)
            .setHyperSpace("linear")
            .setOptimizer(opt)
        )

    m_host = gp("host").fit(x, y)
    m_dev = gp("device").fit(x, y)
    r_host = rmse(y, m_host.predict(x))
    r_dev = rmse(y, m_dev.predict(x))
    # the boundary regime is genuinely active: linear-space airfoil drives
    # ARD betas onto their 0 lower bound (scipy lands all 5 there and
    # collapses to the constant kernel; measured r_host ~6.1 vs r_dev ~4.0
    # with 3 betas bound-active — linear space is exactly the bad scaling
    # setHyperSpace's docstring warns about, which is the point: bounds
    # must actually engage)
    theta_dev = m_dev.raw_predictor.theta  # [C, beta1..beta5, (const)]
    assert np.sum(theta_dev[1:6] == 0.0) >= 1  # some ARD beta bound-active
    # ... but NOT the constant-kernel collapse (amplitude alive, at least
    # one beta alive, and quality strictly better than the collapsed
    # model's ~6.1 = y's std)
    assert theta_dev[0] > 0.0
    assert np.sum(theta_dev[1:6] > 0.0) >= 1
    assert r_dev < 5.0, r_dev
    # and the device LBFGSB must not be WORSE than scipy's in the same
    # coordinates (it is currently substantially better)
    assert r_dev < r_host * 1.15 + 0.1, (r_dev, r_host)


def test_invalid_optimizer_rejected():
    with pytest.raises(ValueError):
        GaussianProcessRegression().setOptimizer("banana")
