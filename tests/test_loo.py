"""Leave-one-out diagnostics (models/loo.py) vs brute-force oracles.

The closed-form LOO identities (R&W eqs. 5.10-5.12) are checked against
the definition: for every point, actually delete it, condition the exact
GP on the expert's remaining points, and predict at the deleted input.
Everything runs f64 on the CPU harness, so agreement is to solver
precision, not statistical tolerance.
"""

import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessRegression,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.models.loo import loo_diagnostics
from spark_gp_tpu.parallel.experts import group_for_experts


def _make_kernel():
    return 1.0 * RBFKernel(0.7, 1e-6, 10) + WhiteNoiseKernel(0.1, 0.0, 1.0)


def _brute_force_loo(kernel, theta, xs, ys):
    """Definitionally delete each point of ONE expert and predict it from
    the rest: mu = k_i^T K_{-i}^-1 y_{-i},
    var = k(x_i, x_i) - k_i^T K_{-i}^-1 k_i."""
    import jax.numpy as jnp

    k_full = np.asarray(kernel.gram(jnp.asarray(theta), jnp.asarray(xs)))
    n = xs.shape[0]
    mus, variances = np.empty(n), np.empty(n)
    for i in range(n):
        keep = [j for j in range(n) if j != i]
        k_rest = k_full[np.ix_(keep, keep)]
        k_cross = k_full[np.ix_([i], keep)][0]
        sol = np.linalg.solve(k_rest, ys[keep])
        mus[i] = k_cross @ sol
        variances[i] = k_full[i, i] - k_cross @ np.linalg.solve(
            k_rest, k_cross
        )
    return mus, variances


@pytest.mark.parametrize("n,s", [(24, 24), (37, 10)])
def test_loo_matches_deleted_point_oracle(rng, n, s):
    """Single- and multi-expert (ragged tail) shapes against the oracle."""
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    kernel = _make_kernel()
    theta = kernel.init_theta()

    got = loo_diagnostics(kernel, theta, x, y, s)
    assert got["loo_mean"].shape == (n,)

    # replicate the round-robin grouping to know each expert's members
    data = group_for_experts(x, y, s)
    e = data.num_experts
    for j in range(e):
        members = np.arange(j, n, e)
        mus, variances = _brute_force_loo(
            kernel, theta, x[members], y[members]
        )
        np.testing.assert_allclose(
            got["loo_mean"][members], mus, rtol=1e-8, atol=1e-8
        )
        np.testing.assert_allclose(
            got["loo_var"][members], variances, rtol=1e-8, atol=1e-8
        )

    # log densities follow from the verified moments
    resid = y - got["loo_mean"]
    expect_logp = -0.5 * (
        np.log(2 * np.pi * got["loo_var"]) + resid**2 / got["loo_var"]
    )
    np.testing.assert_allclose(
        got["loo_log_density"], expect_logp, rtol=1e-8
    )
    assert got["loo_log_pseudo_likelihood"] == pytest.approx(
        expect_logp.sum()
    )
    assert got["loo_rmse"] == pytest.approx(np.sqrt(np.mean(resid**2)))


def test_estimator_loo_uses_fitted_theta(rng):
    """gp.loo(x, y, model) must evaluate at the FITTED hyperparameters:
    its result equals loo_diagnostics at model theta and (on data with a
    clearly wrong init) improves on the init-theta pseudo-likelihood."""
    x = rng.normal(size=(60, 2))
    y = np.sin(1.7 * x.sum(axis=1)) + 0.05 * rng.normal(size=60)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(5.0, 1e-3, 20) + WhiteNoiseKernel(0.5, 1e-4, 1.0))
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(30)
        .setSigma2(1e-3)
        .setSeed(5)
    )
    model = gp.fit(x, y)

    got = gp.loo(x, y, model)
    direct = loo_diagnostics(
        model.raw_predictor.kernel, model.raw_predictor.theta, x, y, 30
    )
    np.testing.assert_allclose(got["loo_mean"], direct["loo_mean"], rtol=1e-12)

    at_init = gp.loo(x, y)
    assert (
        got["loo_log_pseudo_likelihood"]
        > at_init["loo_log_pseudo_likelihood"]
    )


def test_loo_validates_shapes():
    gp = GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
    with pytest.raises(ValueError, match=r"x must be \[N, p\]"):
        gp.loo(np.zeros(5), np.zeros(5))
    with pytest.raises(ValueError, match=r"y must be \[N\]"):
        gp.loo(np.zeros((5, 2)), np.zeros(4))
