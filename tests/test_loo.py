"""Leave-one-out diagnostics (models/loo.py) vs brute-force oracles.

The closed-form LOO identities (R&W eqs. 5.10-5.12) are checked against
the definition: for every point, actually delete it, condition the exact
GP on the expert's remaining points, and predict at the deleted input.
Everything runs f64 on the CPU harness, so agreement is to solver
precision, not statistical tolerance.
"""

import numpy as np
import pytest

from spark_gp_tpu import (
    GaussianProcessRegression,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.models.loo import loo_diagnostics
from spark_gp_tpu.parallel.experts import group_for_experts


def _make_kernel():
    return 1.0 * RBFKernel(0.7, 1e-6, 10) + WhiteNoiseKernel(0.1, 0.0, 1.0)


def _brute_force_loo(kernel, theta, xs, ys):
    """Definitionally delete each point of ONE expert and predict it from
    the rest: mu = k_i^T K_{-i}^-1 y_{-i},
    var = k(x_i, x_i) - k_i^T K_{-i}^-1 k_i."""
    import jax.numpy as jnp

    k_full = np.asarray(kernel.gram(jnp.asarray(theta), jnp.asarray(xs)))
    n = xs.shape[0]
    mus, variances = np.empty(n), np.empty(n)
    for i in range(n):
        keep = [j for j in range(n) if j != i]
        k_rest = k_full[np.ix_(keep, keep)]
        k_cross = k_full[np.ix_([i], keep)][0]
        sol = np.linalg.solve(k_rest, ys[keep])
        mus[i] = k_cross @ sol
        variances[i] = k_full[i, i] - k_cross @ np.linalg.solve(
            k_rest, k_cross
        )
    return mus, variances


@pytest.mark.parametrize("n,s", [(24, 24), (37, 10)])
def test_loo_matches_deleted_point_oracle(rng, n, s):
    """Single- and multi-expert (ragged tail) shapes against the oracle."""
    x = rng.normal(size=(n, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=n)
    kernel = _make_kernel()
    theta = kernel.init_theta()

    got = loo_diagnostics(kernel, theta, x, y, s)
    assert got["loo_mean"].shape == (n,)

    # replicate the round-robin grouping to know each expert's members
    data = group_for_experts(x, y, s)
    e = data.num_experts
    for j in range(e):
        members = np.arange(j, n, e)
        mus, variances = _brute_force_loo(
            kernel, theta, x[members], y[members]
        )
        np.testing.assert_allclose(
            got["loo_mean"][members], mus, rtol=1e-8, atol=1e-8
        )
        np.testing.assert_allclose(
            got["loo_var"][members], variances, rtol=1e-8, atol=1e-8
        )

    # log densities follow from the verified moments
    resid = y - got["loo_mean"]
    expect_logp = -0.5 * (
        np.log(2 * np.pi * got["loo_var"]) + resid**2 / got["loo_var"]
    )
    np.testing.assert_allclose(
        got["loo_log_density"], expect_logp, rtol=1e-8
    )
    assert got["loo_log_pseudo_likelihood"] == pytest.approx(
        expect_logp.sum()
    )
    assert got["loo_rmse"] == pytest.approx(np.sqrt(np.mean(resid**2)))


def test_estimator_loo_uses_fitted_theta(rng):
    """gp.loo(x, y, model) must evaluate at the FITTED hyperparameters:
    its result equals loo_diagnostics at model theta and (on data with a
    clearly wrong init) improves on the init-theta pseudo-likelihood."""
    x = rng.normal(size=(60, 2))
    y = np.sin(1.7 * x.sum(axis=1)) + 0.05 * rng.normal(size=60)
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * RBFKernel(5.0, 1e-3, 20) + WhiteNoiseKernel(0.5, 1e-4, 1.0))
        .setDatasetSizeForExpert(30)
        .setActiveSetSize(30)
        .setSigma2(1e-3)
        .setSeed(5)
    )
    model = gp.fit(x, y)

    got = gp.loo(x, y, model)
    direct = loo_diagnostics(
        model.raw_predictor.kernel, model.raw_predictor.theta, x, y, 30
    )
    np.testing.assert_allclose(got["loo_mean"], direct["loo_mean"], rtol=1e-12)

    at_init = gp.loo(x, y)
    assert (
        got["loo_log_pseudo_likelihood"]
        > at_init["loo_log_pseudo_likelihood"]
    )


def test_loo_validates_shapes():
    gp = GaussianProcessRegression().setKernel(lambda: RBFKernel(1.0))
    with pytest.raises(ValueError, match=r"x must be \[N, p\]"):
        gp.loo(np.zeros(5), np.zeros(5))
    with pytest.raises(ValueError, match=r"y must be \[N\]"):
        gp.loo(np.zeros((5, 2)), np.zeros(4))


# --- the LOO training objective (setObjective("loo")) ------------------------


def test_batched_loo_nll_gradient_matches_fd(rng):
    import jax
    import jax.numpy as jnp

    from spark_gp_tpu.models.loo import batched_loo_nll
    from spark_gp_tpu.parallel.experts import group_for_experts

    x = rng.normal(size=(33, 2))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=33)
    data = group_for_experts(x, y, 12)
    kernel = _make_kernel()
    theta0 = jnp.asarray(kernel.init_theta())

    f = lambda t: batched_loo_nll(kernel, t, data)
    grad = np.asarray(jax.grad(f)(theta0))
    eps = 1e-6
    for k in range(theta0.shape[0]):
        dt = np.zeros(theta0.shape[0])
        dt[k] = eps
        fd = (float(f(theta0 + dt)) - float(f(theta0 - dt))) / (2 * eps)
        np.testing.assert_allclose(grad[k], fd, rtol=1e-5, atol=1e-7)


def test_loo_objective_fit_improves_pseudo_likelihood(rng):
    """A fit under setObjective('loo') must (a) report the LOO objective as
    its final objective value and (b) reach at least as good a LOO pseudo-
    likelihood as the marginal-NLL fit evaluated post hoc."""
    x = rng.normal(size=(80, 2))
    y = np.sin(1.3 * x.sum(axis=1)) + 0.1 * rng.normal(size=80)

    def mk(objective):
        return (
            GaussianProcessRegression()
            .setKernel(
                lambda: 1.0 * RBFKernel(1.0, 1e-3, 20)
                + WhiteNoiseKernel(0.3, 1e-4, 1.0)
            )
            .setDatasetSizeForExpert(40)
            .setActiveSetSize(30)
            .setSigma2(1e-3)
            .setSeed(3)
            .setObjective(objective)
        )

    loo_fit = mk("loo").fit(x, y)
    marg_fit = mk("marginal").fit(x, y)

    gp = mk("loo")
    at_loo = gp.loo(x, y, loo_fit)["loo_log_pseudo_likelihood"]
    at_marg = gp.loo(x, y, marg_fit)["loo_log_pseudo_likelihood"]
    assert at_loo >= at_marg - 1e-6

    # the reported final objective is the LOO objective at the winner
    from spark_gp_tpu.models.loo import batched_loo_nll
    from spark_gp_tpu.parallel.experts import group_for_experts

    import jax.numpy as jnp

    data = group_for_experts(x, y, 40)
    recomputed = float(
        batched_loo_nll(
            loo_fit.raw_predictor.kernel,
            jnp.asarray(loo_fit.raw_predictor.theta, dtype=data.x.dtype),
            data,
        )
    )
    assert loo_fit.instr.metrics["final_nll"] == pytest.approx(
        recomputed, rel=1e-5
    )
    # and -sum(log densities) from the diagnostics agrees with the objective
    assert -at_loo == pytest.approx(recomputed, rel=1e-5)


def test_loo_objective_host_and_device_optimizers_agree(rng):
    x = rng.normal(size=(48, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=48)

    def mk(opt):
        return (
            GaussianProcessRegression()
            .setKernel(lambda: _make_kernel())
            .setDatasetSizeForExpert(24)
            .setActiveSetSize(20)
            .setSigma2(1e-3)
            .setSeed(7)
            .setObjective("loo")
            .setOptimizer(opt)
        )

    m_host = mk("host").fit(x, y)
    m_dev = mk("device").fit(x, y)
    assert m_host.instr.metrics["final_nll"] == pytest.approx(
        m_dev.instr.metrics["final_nll"], rel=1e-3
    )


def test_set_objective_validates():
    with pytest.raises(ValueError, match="unknown objective"):
        GaussianProcessRegression().setObjective("evidence")


def test_loo_objective_checkpoints_isolated_from_marginal(rng, tmp_path):
    """Checkpoints are objective-keyed on BOTH optimizer paths: a loo fit
    in the same dir neither resumes from nor overwrites a marginal fit's
    state."""
    from spark_gp_tpu.utils.checkpoint import load_checkpoint

    x = rng.normal(size=(40, 2))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=40)

    def mk(objective, opt):
        return (
            GaussianProcessRegression()
            .setKernel(lambda: _make_kernel())
            .setDatasetSizeForExpert(20)
            .setActiveSetSize(16)
            .setSigma2(1e-3)
            .setMaxIter(4)
            .setOptimizer(opt)
            .setObjective(objective)
            .setCheckpointDir(str(tmp_path))
        )

    # host path: per-iteration json, tag = class name [+ objective]
    mk("marginal", "host").fit(x, y)
    marg_state = load_checkpoint(
        str(tmp_path), tag="GaussianProcessRegression"
    )
    assert marg_state is not None

    mk("loo", "host").fit(x, y)
    loo_state = load_checkpoint(
        str(tmp_path), tag="GaussianProcessRegression-loo"
    )
    assert loo_state is not None
    # the marginal state survived the loo fit untouched
    after = load_checkpoint(str(tmp_path), tag="GaussianProcessRegression")
    np.testing.assert_array_equal(np.asarray(after[1]), np.asarray(marg_state[1]))

    # device segmented path: distinct npz file tags
    import os

    dev_dir = tmp_path / "dev"
    mk("marginal", "device").setCheckpointInterval(2).setCheckpointDir(
        str(dev_dir)
    ).fit(x, y)
    assert os.path.exists(dev_dir / "gpr_device_lbfgs.npz")
    before = (dev_dir / "gpr_device_lbfgs.npz").read_bytes()
    mk("loo", "device").setCheckpointInterval(2).setCheckpointDir(
        str(dev_dir)
    ).fit(x, y)
    assert os.path.exists(dev_dir / "gpr-loo_device_lbfgs.npz")
    assert (dev_dir / "gpr_device_lbfgs.npz").read_bytes() == before
