"""Laplace approximation vs dense oracles — coverage the reference lacks
entirely (its Laplace loop is untested, SURVEY.md §4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_gp_tpu.kernels import Const, EyeKernel, RBFKernel
from spark_gp_tpu.models.laplace import (
    expert_neg_logz_and_grad,
    laplace_mode,
    make_laplace_objective,
)
from spark_gp_tpu.parallel.experts import group_for_experts


def _oracle_mode(kmat, y, iters=200):
    """Plain, step-size-1 Newton iteration for the posterior mode (R&W 3.1),
    run to numerical convergence in f64 — the long-run oracle."""
    n = len(y)
    f = np.zeros(n)
    for _ in range(iters):
        pi = 1.0 / (1.0 + np.exp(-f))
        w = pi * (1.0 - pi)
        sqw = np.sqrt(w)
        b_mat = np.eye(n) + sqw[:, None] * kmat * sqw[None, :]
        chol_l = np.linalg.cholesky(b_mat)
        b = w * f + (y - pi)
        v = np.linalg.solve(chol_l, sqw * (kmat @ b))
        a = b - sqw * np.linalg.solve(chol_l.T, v)
        f = kmat @ a
    return f, a


@pytest.fixture
def clf_problem(rng):
    n, p = 30, 2
    x = rng.normal(size=(n, p))
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    kernel = RBFKernel(1.0) + Const(1e-3) * EyeKernel()
    theta = jnp.asarray(kernel.init_theta())
    kmat = np.asarray(kernel.gram(theta, jnp.asarray(x)))
    return x, y, kernel, theta, kmat


def test_mode_matches_longrun_oracle(clf_problem):
    x, y, kernel, theta, kmat = clf_problem
    f_oracle, _ = _oracle_mode(kmat, y)
    mask = jnp.ones(len(y))
    f, _ = laplace_mode(jnp.asarray(kmat), jnp.asarray(y), mask, jnp.zeros(len(y)), 1e-10)
    np.testing.assert_allclose(np.asarray(f), f_oracle, rtol=1e-6, atol=1e-8)


def test_logz_matches_oracle(clf_problem):
    """log Z = -a^T f/2 + sum log sigmoid((2y-1) f) - sum log diag L
    at the converged mode (R&W eq. 3.32, GPClf.scala:113)."""
    x, y, kernel, theta, kmat = clf_problem
    f_oracle, a_oracle = _oracle_mode(kmat, y)
    pi = 1.0 / (1.0 + np.exp(-f_oracle))
    w = pi * (1 - pi)
    sqw = np.sqrt(w)
    b_mat = np.eye(len(y)) + sqw[:, None] * kmat * sqw[None, :]
    chol_l = np.linalg.cholesky(b_mat)
    obj = -0.5 * a_oracle @ f_oracle + np.sum(
        np.log(1.0 / (1.0 + np.exp(-(2 * y - 1) * f_oracle)))
    )
    logz_oracle = obj - np.sum(np.log(np.diag(chol_l)))

    mask = jnp.ones(len(y))
    neg_logz, _, _ = expert_neg_logz_and_grad(
        kernel, 1e-10, theta, jnp.asarray(x), jnp.asarray(y), mask, jnp.zeros(len(y))
    )
    np.testing.assert_allclose(-float(neg_logz), logz_oracle, rtol=1e-6)


def test_gradient_matches_finite_difference(clf_problem):
    """Algorithm 5.1 gradient vs central FD of -log Z in theta — validates
    the s1/s2/s3 implicit-correction assembly (GPClf.scala:113-128)."""
    x, y, kernel, theta, _ = clf_problem
    mask = jnp.ones(len(y))
    f0 = jnp.zeros(len(y))
    tol = 1e-12

    def neg_logz(th):
        v, _, _ = expert_neg_logz_and_grad(
            kernel, tol, jnp.asarray(th), jnp.asarray(x), jnp.asarray(y), mask, f0
        )
        return float(v)

    _, grad, _ = expert_neg_logz_and_grad(
        kernel, tol, theta, jnp.asarray(x), jnp.asarray(y), mask, f0
    )
    theta0 = np.asarray(theta)
    h = 1e-5
    fd = np.zeros_like(theta0)
    for i in range(theta0.size):
        tp, tm = theta0.copy(), theta0.copy()
        tp[i] += h
        tm[i] -= h
        fd[i] = (neg_logz(tp) - neg_logz(tm)) / (2 * h)
    np.testing.assert_allclose(np.asarray(grad), fd, rtol=1e-4, atol=1e-6)


def test_padding_invariance(clf_problem, rng):
    """Padded points must not change -log Z or the gradient."""
    x, y, kernel, theta, _ = clf_problem
    n = len(y)
    mask_full = jnp.ones(n)
    v1, g1, f1 = expert_neg_logz_and_grad(
        kernel, 1e-8, theta, jnp.asarray(x), jnp.asarray(y), mask_full, jnp.zeros(n)
    )
    # pad with 5 junk points, masked out
    xp = np.concatenate([x, rng.normal(size=(5, x.shape[1]))])
    yp = np.concatenate([y, np.ones(5)])
    maskp = jnp.asarray(np.concatenate([np.ones(n), np.zeros(5)]))
    v2, g2, f2 = expert_neg_logz_and_grad(
        kernel, 1e-8, theta, jnp.asarray(xp), jnp.asarray(yp), maskp, jnp.zeros(n + 5)
    )
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-9)
    np.testing.assert_allclose(np.asarray(f2)[n:], 0.0, atol=1e-12)


def test_warm_start_carries(clf_problem):
    """Second evaluation starting from the converged f terminates immediately
    at the same objective (the reference's warm-start semantics,
    GPClf.scala:53-60)."""
    x, y, kernel, theta, _ = clf_problem
    data = group_for_experts(x, y, dataset_size_for_expert=15)
    obj = make_laplace_objective(kernel, data, 1e-6)
    f0 = jnp.zeros_like(data.y)
    v1, g1, f1 = obj(theta, f0)
    v2, g2, f2 = obj(theta, f1)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-8)
    np.testing.assert_allclose(np.asarray(f1), np.asarray(f2), rtol=1e-6, atol=1e-9)


def test_inverse_branch_matches_cholesky_branch(clf_problem, monkeypatch):
    """The TPU ("inv") factor branch — explicit B^-1 from the fused kernel —
    must agree with the CPU Cholesky branch.  CI has no TPU, so the inv
    branch is forced by stubbing the backend gate with the inverse-based
    fallback (same (kinv, logdet) contract as the Pallas kernel)."""
    from spark_gp_tpu.models.laplace import batched_neg_logz
    from spark_gp_tpu.ops import pallas_linalg

    x, y, kernel, theta, _ = clf_problem
    data = group_for_experts(x, y, dataset_size_for_expert=8)
    f0 = jnp.zeros_like(data.y)

    v_chol, g_chol, f_chol = batched_neg_logz(kernel, 1e-10, theta, data, f0)

    monkeypatch.setattr(pallas_linalg, "_use_pallas", lambda k: True)
    monkeypatch.setattr(
        pallas_linalg, "spd_inv_logdet", pallas_linalg._chol_inv_logdet
    )
    v_inv, g_inv, f_inv = batched_neg_logz(kernel, 1e-10, theta, data, f0)

    np.testing.assert_allclose(float(v_inv), float(v_chol), rtol=1e-10)
    np.testing.assert_allclose(np.asarray(g_inv), np.asarray(g_chol), rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(np.asarray(f_inv), np.asarray(f_chol), rtol=1e-9, atol=1e-11)
