"""Expert grouping (pad + reshape) vs the reference's round-robin semantics
(GaussianProcessCommons.scala:26-31)."""

import numpy as np
import pytest

from spark_gp_tpu.parallel.experts import group_for_experts, num_experts_for


def test_num_experts_rounding():
    """E = Math.round(N / s) — half-up (GPC.scala:27)."""
    assert num_experts_for(2000, 100) == 20
    assert num_experts_for(1503, 100) == 15
    assert num_experts_for(149, 100) == 1
    assert num_experts_for(150, 100) == 2  # 1.5 rounds half-up
    assert num_experts_for(50, 100) == 1  # never 0


def test_round_robin_assignment():
    n, p = 103, 2
    x = np.arange(n * p, dtype=np.float64).reshape(n, p)
    y = np.arange(n, dtype=np.float64)
    data = group_for_experts(x, y, 10)
    e = data.num_experts
    assert e == 10
    # expert j holds points j, j+E, j+2E, ...
    for j in range(e):
        idx = np.arange(j, n, e)
        real = int(np.sum(np.asarray(data.mask)[j]))
        assert real == len(idx)
        np.testing.assert_allclose(np.asarray(data.y)[j, :real], y[idx])
        np.testing.assert_allclose(np.asarray(data.x)[j, :real], x[idx])
    # all points accounted for exactly once
    assert int(np.sum(np.asarray(data.mask))) == n


def test_padding_masked():
    x = np.random.default_rng(0).normal(size=(7, 3))
    y = np.random.default_rng(1).normal(size=7)
    data = group_for_experts(x, y, 2)  # E = round(3.5) = 4, s = 2
    assert data.num_experts == 4
    assert data.expert_size == 2
    mask = np.asarray(data.mask)
    assert mask.sum() == 7
    # padded labels are zero
    yg = np.asarray(data.y)
    np.testing.assert_allclose(yg[mask == 0.0], 0.0)


def test_pad_experts_to_device_multiple():
    x = np.random.default_rng(0).normal(size=(30, 2))
    y = np.zeros(30)
    data = group_for_experts(x, y, 10)  # E = 3
    padded = data.pad_experts(8)
    assert padded.num_experts == 8
    np.testing.assert_allclose(np.asarray(padded.mask)[3:], 0.0)
    # original experts intact
    np.testing.assert_allclose(np.asarray(padded.x)[:3], np.asarray(data.x))


def test_group_ungroup_roundtrip_property():
    """Property sweep over random (N, s): grouping then ungrouping the
    targets recovers them exactly in original order; the mask counts
    exactly N real slots; every expert's width is the common s = ceil(N/E)
    (the ragged-tail layout, SURVEY hard part #5)."""
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    from spark_gp_tpu.parallel.experts import ungroup

    @settings(max_examples=60, deadline=None)
    @given(
        n=st.integers(1, 400),
        s=st.integers(1, 150),
        seed=st.integers(0, 2**31 - 1),
    )
    def check(n, s, seed):
        rng = np.random.default_rng(seed)
        x = rng.normal(size=(n, 2))
        y = rng.normal(size=n)
        data = group_for_experts(x, y, s)
        e = num_experts_for(n, s)
        assert data.x.shape[0] == e
        assert data.x.shape[1] == -(-n // e)  # common width = ceil(N/E)
        assert int(np.sum(np.asarray(data.mask))) == n
        # targets round-trip exactly, in original order
        np.testing.assert_array_equal(
            ungroup(np.asarray(data.y), n), y
        )
        # every real slot holds the right row of x
        xg = np.asarray(data.x)
        mask = np.asarray(data.mask).astype(bool)
        width = xg.shape[1]
        point = np.arange(e)[:, None] + np.arange(width)[None, :] * e
        np.testing.assert_array_equal(xg[mask], x[point[mask]])

    check()
