"""REAL multi-process DCN tests: two OS processes, one JAX device each,
Gloo collectives between them — the closest CI analogue of a 2-host pod.

Exercises what the single-process suite cannot (VERDICT r2 weak #6): the
cross-host expert stitching of ``distribute_global_experts`` with UNEQUAL
per-process row counts (``_pad_stack``, ``process_allgather``,
``host_local_array_to_global_array``), the collective active-set draw, and
both estimators' ``fit_distributed`` running their psum/all-gather programs
across a genuine process boundary.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.xfail(
    reason="container jax 0.4.37: multihost_utils.process_allgather fails "
    "with 'Multiprocess computations aren't implemented on the CPU backend' "
    "inside distribute_global_experts (_mp_worker.py:53) — a jitted "
    "cross-process collective the CPU/Gloo backend of this jax version "
    "cannot run; pre-existing at seed (CHANGES.md PR 1), needs a jax "
    "upgrade or a KV-store allgather fallback in parallel/distributed.py",
    strict=False,
)
def test_two_process_fit_distributed():
    # bounded by the workers' communicate(timeout=560) below
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_mp_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # fresh processes: no 8-device forcing — one device per process, so the
    # global mesh genuinely spans the process boundary
    env["XLA_FLAGS"] = ""
    env.pop("JAX_NUM_PROCESSES", None)

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=560)
            outs.append(out)
    finally:
        # a hung worker (e.g. a deadlocked collective) must not leak past
        # the test holding the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("MPRESULT "):
                r = json.loads(line[len("MPRESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, f"missing worker results: {outs}"

    r0, r1 = results[0], results[1]
    assert r0["n_global_devices"] == 2
    # the fitted model is replicated: both processes must predict the SAME
    # values on the shared probe set (regression, binary classifier and multiclass)
    np.testing.assert_allclose(r0["pred"], r1["pred"], rtol=0, atol=1e-8)
    np.testing.assert_allclose(r0["cpred"], r1["cpred"], rtol=0, atol=1e-8)
    np.testing.assert_allclose(r0["mpred"], r1["mpred"], rtol=0, atol=1e-8)
    # and the joint fit actually learned the shared function
    assert r0["rmse_local"] < 0.2, r0["rmse_local"]
