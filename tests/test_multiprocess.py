"""REAL multi-process DCN tests: two OS processes, one JAX device each,
Gloo collectives between them — the closest CI analogue of a 2-host pod.

Exercises what the single-process suite cannot (VERDICT r2 weak #6): the
cross-host expert stitching of ``distribute_global_experts`` with UNEQUAL
per-process row counts (``_pad_stack``, ``process_allgather``,
``host_local_array_to_global_array``), the collective active-set draw, and
both estimators' ``fit_distributed`` running their psum/all-gather programs
across a genuine process boundary.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_fit_distributed():
    # Formerly xfailed: this jax's CPU runtime refuses ANY cross-process
    # computation ("Multiprocess computations aren't implemented"), so both
    # the old process_allgather(dims) AND the fit's own collectives were
    # unrunnable.  parallel/coord.py's DCN-fallback mode fixed both: dims
    # ride coord.kv_allgather and the fit's cross-host sums ((NLL, grad)
    # per evaluation, (U1, u2), the active-set rows) ride the KV store
    # while each host runs local compiled programs — the reference's
    # treeAggregate architecture on the jax coordination service.
    # bounded by the workers' communicate(timeout=560) below
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "_mp_worker.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # fresh processes: no 8-device forcing — one device per process, so the
    # global mesh genuinely spans the process boundary
    env["XLA_FLAGS"] = ""
    env.pop("JAX_NUM_PROCESSES", None)
    # this test proves the 3-family DCN fit parity across REAL process
    # boundaries; the duplicate-dispatch spot-check plane would compile an
    # extra probe program in each worker without adding coverage here —
    # its own proofs live in tests/test_integrity.py and the sdc_fit soak
    # scenario (attested gathers still run: GP_INTEGRITY stays on)
    env["GP_INTEGRITY_DUPCHECK_P"] = "0"

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=560)
            outs.append(out)
    finally:
        # a hung worker (e.g. a deadlocked collective) must not leak past
        # the test holding the coordinator port
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("MPRESULT "):
                r = json.loads(line[len("MPRESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, f"missing worker results: {outs}"

    r0, r1 = results[0], results[1]
    assert r0["n_global_devices"] == 2
    # the fitted model is replicated: both processes must predict the SAME
    # values on the shared probe set (regression, binary classifier and multiclass)
    np.testing.assert_allclose(r0["pred"], r1["pred"], rtol=0, atol=1e-8)
    np.testing.assert_allclose(r0["cpred"], r1["cpred"], rtol=0, atol=1e-8)
    np.testing.assert_allclose(r0["mpred"], r1["mpred"], rtol=0, atol=1e-8)
    # and the joint fit actually learned the shared function
    assert r0["rmse_local"] < 0.2, r0["rmse_local"]


def _coord_worker_cmd(mode_args):
    worker = os.path.join(os.path.dirname(__file__), "_mp_coord_worker.py")
    return [sys.executable, worker] + [str(a) for a in mode_args]


def _clean_env(**extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # one device per process: a REAL process boundary
    env.pop("JAX_NUM_PROCESSES", None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _run_pair(args_by_pid, envs, timeout_s=300):
    procs = [
        subprocess.Popen(
            _coord_worker_cmd(args), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env,
        )
        for args, env in zip(args_by_pid, envs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout_s)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return procs, outs


def _theta_from(out: str):
    for line in out.splitlines():
        if line.startswith("THETA "):
            return np.asarray(json.loads(line[len("THETA "):])["theta"])
    raise AssertionError(f"no THETA line in:\n{out[-2000:]}")


def test_two_process_dead_host_raises_named_timeout_no_hang(tmp_path):
    """THE no-hang acceptance proof over a real process boundary: process 1
    is a chaos DeadHost (os._exit before its first DCN collective);
    process 0 must raise CoordinationTimeoutError NAMING process 1 within
    the configured deadline — never block past it."""
    import time

    port = _free_port()
    # pure timer: process 0 parks at its first collective and the KV poll
    # deadline fires — 5 s is still far above poll granularity and below
    # the runtime's own ~10 s failure detection
    deadline_s = 5
    t0 = time.monotonic()
    procs, outs = _run_pair(
        [
            ["fit", 0, 2, port, str(tmp_path / "ck")],
            ["fit", 1, 2, port, str(tmp_path / "ck")],
        ],
        [
            _clean_env(GP_COORD_TIMEOUT_S=deadline_s),
            _clean_env(GP_COORD_TIMEOUT_S=deadline_s, GP_CHAOS_DEAD_HOST=1),
        ],
        timeout_s=180,
    )
    elapsed = time.monotonic() - t0
    from spark_gp_tpu.resilience.chaos import PREEMPTION_EXIT_CODE

    assert procs[1].returncode == PREEMPTION_EXIT_CODE, outs[1][-1500:]
    assert procs[0].returncode == 3, outs[0][-1500:]
    assert "COORDTIMEOUT missing=[1]" in outs[0], outs[0][-1500:]
    # startup + one deadline + teardown; nowhere near the 180 s hang fence
    assert elapsed < 120.0, elapsed


@pytest.mark.slow
def test_two_process_kill_then_elastic_resume_matches_uninterrupted(tmp_path):
    """The elastic-resume acceptance proof over REAL process death: an
    uninterrupted 2-process DCN fit gives the reference theta; the same
    fit is rerun with process 1 staged to os._exit(137) after 3
    checkpoint saves (process 0 stops at the named timeout, coordinated
    checkpoints on disk); then ONE fresh process resumes the union stack
    from the 2-process checkpoint and must reproduce the reference theta
    to atol 1e-6."""
    # 1. uninterrupted reference
    port = _free_port()
    procs, outs = _run_pair(
        [
            ["fit", 0, 2, port, str(tmp_path / "ref_ck")],
            ["fit", 1, 2, port, str(tmp_path / "ref_ck")],
        ],
        [_clean_env(), _clean_env()],
        timeout_s=280,
    )
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    theta_ref = _theta_from(outs[0])
    np.testing.assert_array_equal(theta_ref, _theta_from(outs[1]))

    # 2. killed run: process 1 dies after its 3rd coordinated save
    from spark_gp_tpu.resilience.chaos import PREEMPTION_EXIT_CODE

    port = _free_port()
    procs, outs = _run_pair(
        [
            ["fit", 0, 2, port, str(tmp_path / "ck")],
            ["fit", 1, 2, port, str(tmp_path / "ck")],
        ],
        [
            _clean_env(GP_COORD_TIMEOUT_S=8),
            _clean_env(GP_COORD_TIMEOUT_S=8, GP_CHAOS_KILL_AFTER_ITERS=3),
        ],
        timeout_s=280,
    )
    assert procs[1].returncode == PREEMPTION_EXIT_CODE, outs[1][-1500:]
    assert procs[0].returncode == 3, outs[0][-1500:]
    assert "COORDTIMEOUT missing=[1]" in outs[0]
    assert os.path.exists(
        tmp_path / "ck" / "lbfgs_state_GaussianProcessRegression.json"
    )

    # 3. elastic resume: one process, union stack, different process count
    proc = subprocess.Popen(
        _coord_worker_cmd(["resume", 2, str(tmp_path / "ck")]),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_clean_env(),
    )
    out, _ = proc.communicate(timeout=280)
    assert proc.returncode == 0, out[-2000:]
    assert "ELASTIC 1" in out, out[-1500:]  # the P=2 -> P'=1 transition
    np.testing.assert_allclose(_theta_from(out), theta_ref, atol=1e-6)
