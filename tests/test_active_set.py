"""Active-set provider tests (ASP.scala counterparts)."""

import numpy as np
import pytest

from spark_gp_tpu import (
    Const,
    EyeKernel,
    GreedilyOptimizingActiveSetProvider,
    KMeansActiveSetProvider,
    RBFKernel,
    RandomActiveSetProvider,
)


@pytest.fixture
def points(rng):
    # two well-separated clusters in 2-d
    a = rng.normal(size=(60, 2)) * 0.2
    b = rng.normal(size=(60, 2)) * 0.2 + np.array([5.0, 5.0])
    x = np.concatenate([a, b])
    y = np.concatenate([np.zeros(60), np.ones(60)])
    return x, y


def _kernel():
    return RBFKernel(1.0) + Const(1e-2) * EyeKernel()


def test_random_provider_samples_points(points):
    x, y = points
    k = _kernel()
    active = RandomActiveSetProvider(10, x, y, k, k.init_theta(), seed=7)
    assert active.shape == (10, 2)
    # every active point is an actual training point
    for row in active:
        assert np.any(np.all(np.isclose(x, row), axis=1))
    # deterministic under the same seed (ASP.scala uses the seed param)
    again = RandomActiveSetProvider(10, x, y, k, k.init_theta(), seed=7)
    np.testing.assert_allclose(active, again)


def test_kmeans_provider_finds_clusters(points):
    x, y = points
    k = _kernel()
    active = KMeansActiveSetProvider(max_iter=20)(2, x, y, k, k.init_theta(), seed=0)
    assert active.shape == (2, 2)
    centers = np.sort(active, axis=0)
    np.testing.assert_allclose(centers[0], [0.0, 0.0], atol=0.5)
    np.testing.assert_allclose(centers[1], [5.0, 5.0], atol=0.5)


def test_kmeans_more_clusters_than_needed(points):
    x, y = points
    k = _kernel()
    active = KMeansActiveSetProvider()(30, x, y, k, k.init_theta(), seed=0)
    assert active.shape == (30, 2)
    assert np.all(np.isfinite(active))


def test_greedy_provider_selects_informative_points(points, rng):
    """Greedy Seeger selection spreads across both clusters and is
    deterministic given the seed."""
    x, y = points
    k = _kernel()
    active = GreedilyOptimizingActiveSetProvider()(8, x, y, k, k.init_theta(), seed=3)
    assert active.shape == (8, 2)
    # both clusters represented
    near_a = np.sum(np.linalg.norm(active, axis=1) < 2.0)
    near_b = np.sum(np.linalg.norm(active - np.array([5.0, 5.0]), axis=1) < 2.0)
    assert near_a > 0 and near_b > 0
    # no duplicate selections
    assert np.unique(np.round(active, 9), axis=0).shape[0] == 8


def test_greedy_improves_over_random_on_fit(rng):
    """On density-skewed data, random sampling wastes its budget on the dense
    cluster while Seeger's information gain spreads the active set — greedy
    must beat random outright, for every seed tried (a vacuous bound here
    would hide a broken scorer; the order-exact oracle test below pins the
    exact semantics)."""
    from spark_gp_tpu import GaussianProcessRegression
    from spark_gp_tpu.utils.validation import rmse

    # 270 points crowded into [0, 0.5], 30 spread over (0.5, 10]: m=12 random
    # picks land ~11:1 in the crowd, leaving the tail unmodelled.
    x = np.concatenate(
        [rng.uniform(0.0, 0.5, size=270), rng.uniform(0.5, 10.0, size=30)]
    )[:, None]
    y = np.sin(x[:, 0] * 1.5) + 0.01 * rng.normal(size=300)

    def fit_with(provider, seed):
        gp = (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(0.3, 1e-6, 10))
            .setActiveSetSize(12)
            .setActiveSetProvider(provider)
            .setSeed(seed)
        )
        model = gp.fit(x, y)
        return rmse(y, model.predict(x))

    for seed in (5, 11):
        r_greedy = fit_with(GreedilyOptimizingActiveSetProvider(), seed)
        r_random = fit_with(RandomActiveSetProvider, seed)
        assert r_greedy < r_random, (r_greedy, r_random)
        assert r_greedy < 0.05, r_greedy  # absolute: tail is actually covered


def _dense_seeger_order(kernel, theta, x, y, m, first_idx):
    """Test-only oracle: the reference's per-round recomputed Seeger scoring
    (ASP.scala:84-136) — explicit inverses refactored from scratch each
    round, no incremental state.  Returns the selected index sequence."""
    import jax.numpy as jnp
    import scipy.linalg

    theta_j = jnp.asarray(theta)
    sigma2 = float(kernel.white_noise_var(theta_j))
    k_diag = np.asarray(kernel.diag(theta_j, jnp.asarray(x)))
    chosen = [int(first_idx)]
    for _ in range(1, m):
        a = x[np.asarray(chosen)]
        kmm = np.asarray(kernel.gram(theta_j, jnp.asarray(a)))  # noise diag in
        kmn = np.asarray(kernel.cross(theta_j, jnp.asarray(a), jnp.asarray(x)))
        kmm_inv = scipy.linalg.inv(kmm)  # ASP.scala:88, inv() verbatim
        pd = sigma2 * kmm + kmn @ kmn.T
        pd_inv = scipy.linalg.inv(pd)  # ASP.scala:100
        magic = scipy.linalg.solve(pd, kmn @ y)  # ASP.scala:102
        p_vec = np.einsum("kn,kl,ln->n", kmn, kmm_inv, kmn)  # ASP.scala:113
        q_vec = np.einsum("kn,kl,ln->n", kmn, pd_inv, kmn)  # ASP.scala:114
        mu = kmn.T @ magic  # ASP.scala:115
        li2 = k_diag - p_vec
        with np.errstate(invalid="ignore", divide="ignore"):
            ratio2 = sigma2 / li2
            ksi = 1.0 / (ratio2 + 1.0 - q_vec)
            kappa = ksi * (1.0 + 2.0 * ratio2)
            delta = -0.5 * np.log(ratio2) - 0.5 * (
                np.log(ksi)
                + ksi * (1.0 - kappa) / sigma2 * (y - mu) ** 2
                - kappa
                + 2.0
            )
        delta[np.isnan(delta)] = -np.inf  # ASP.scala:130 NaN filter
        delta[np.asarray(chosen)] = -np.inf
        chosen.append(int(np.argmax(delta)))
    return chosen


def test_greedy_matches_dense_seeger_oracle(rng):
    """Order-exact parity: the incremental-Cholesky selection must pick the
    SAME point sequence as the reference's dense recomputed scoring
    (ASP.scala:106-128) in f64."""
    import jax.numpy as jnp

    from spark_gp_tpu.models.greedy import _greedy_select

    x = rng.normal(size=(200, 3))
    y = np.sin(x.sum(axis=1)) + 0.1 * rng.normal(size=200)
    kernel = _kernel()
    theta = kernel.init_theta()
    m, first = 25, 17

    oracle_idx = _dense_seeger_order(kernel, theta, x, y, m, first)
    got_pts, got_idx, _ = _greedy_select(
        kernel, m, jnp.asarray(theta), jnp.asarray(x), jnp.asarray(y),
        jnp.ones(200), jnp.asarray(first, dtype="int32"),
    )
    np.testing.assert_array_equal(np.asarray(got_idx), oracle_idx)
    np.testing.assert_allclose(np.asarray(got_pts), x[oracle_idx], atol=1e-9)


def test_greedy_sharded_matches_single_device(rng, eight_device_mesh):
    """The shard_map'd selection (candidate axis over 8 devices, psum/pmax
    collectives) must reproduce the unsharded core point-for-point,
    including with a masked (padded) stack."""
    import jax.numpy as jnp

    from spark_gp_tpu.models.greedy import _greedy_select, _greedy_select_sharded
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import shard_experts

    x = rng.normal(size=(210, 2))  # deliberately not divisible: padding
    y = np.sin(x.sum(axis=1))
    kernel = _kernel()
    theta = jnp.asarray(kernel.init_theta())
    data = shard_experts(group_for_experts(x, y, 16), eight_device_mesh)

    # unsharded reference run over the same flattened (padded+masked) layout
    xf = jnp.asarray(np.asarray(data.x).reshape(-1, 2))
    yf = jnp.asarray(np.asarray(data.y).reshape(-1))
    mf = jnp.asarray(np.asarray(data.mask).reshape(-1))
    first = int(np.flatnonzero(np.asarray(mf) > 0)[5])

    single, single_idx, single_d = _greedy_select(
        kernel, 12, theta, xf, yf, mf, jnp.asarray(first, dtype="int32")
    )
    sharded, sharded_idx, sharded_d = _greedy_select_sharded(
        kernel, 12, eight_device_mesh, theta, data.x, data.y, data.mask,
        jnp.asarray(first, dtype="int32"),
    )
    single, sharded = np.asarray(single), np.asarray(sharded)
    np.testing.assert_array_equal(np.asarray(sharded_idx), np.asarray(single_idx))
    np.testing.assert_allclose(sharded, single, atol=1e-10)
    # the Δ-profile diagnostic must agree across the two paths too
    np.testing.assert_allclose(
        np.asarray(sharded_d), np.asarray(single_d), atol=1e-10
    )
    # every selected point is a real (unpadded) data row
    rows = {tuple(np.round(r, 12)) for r in x}
    for r in sharded:
        assert tuple(np.round(r, 12)) in rows


def test_kmeans_from_stack_matches_clusters(rng, eight_device_mesh):
    """Sharded-Lloyd k-means over a padded expert stack finds the same two
    cluster centers as the host path."""
    from spark_gp_tpu.parallel.experts import group_for_experts
    from spark_gp_tpu.parallel.mesh import shard_experts

    a = rng.normal(size=(60, 2)) * 0.2
    b = rng.normal(size=(60, 2)) * 0.2 + np.array([5.0, 5.0])
    x = np.concatenate([a, b])
    y = np.concatenate([np.zeros(60), np.ones(60)])
    data = shard_experts(group_for_experts(x, y, 16), eight_device_mesh)

    k = _kernel()
    active = KMeansActiveSetProvider(max_iter=20).from_stack(
        2, data, k, k.init_theta(), 0, eight_device_mesh
    )
    assert active.shape == (2, 2)
    centers = np.sort(np.asarray(active), axis=0)
    np.testing.assert_allclose(centers[0], [0.0, 0.0], atol=0.5)
    np.testing.assert_allclose(centers[1], [5.0, 5.0], atol=0.5)


def test_greedy_flat_delta_profile_warning(rng, caplog):
    """The airfoil-shaped pathology (late picks remote in kernel space,
    Δ-profile never decays — PARITY.md) must warn at SELECTION time; the
    payoff regime (density-skewed data, decaying profile) must stay quiet."""
    import logging

    from spark_gp_tpu.models.greedy import (
        greedy_active_set,
        warn_on_flat_delta_profile,
    )

    # unit-level: synthetic profiles on both sides of the calibrated 0.95 bar
    flat = np.concatenate([[np.nan], np.full(23, 100.0)])
    with caplog.at_level(logging.WARNING, logger="spark_gp_tpu"):
        ratio = warn_on_flat_delta_profile(flat)
    assert ratio is not None and ratio >= 0.95
    assert any("not decaying" in r.message for r in caplog.records)

    caplog.clear()
    decaying = np.concatenate([[np.nan], np.geomspace(100.0, 1.0, 23)])
    with caplog.at_level(logging.WARNING, logger="spark_gp_tpu"):
        ratio = warn_on_flat_delta_profile(decaying)
    assert ratio is not None and ratio < 0.95
    assert not caplog.records
    # too-short profiles never accuse anyone
    assert warn_on_flat_delta_profile(np.full(5, 1.0)) is None

    # end-to-end on the airfoil-shaped regime: heavy-tailed targets whose
    # outliers sit far apart in kernel space (the measured r5 calibration
    # used real airfoil: ratios 1.05-5.7 vs 0.22-0.84 in the payoff regime)
    from spark_gp_tpu.data import load_airfoil
    from spark_gp_tpu.kernels.base import Const, EyeKernel
    from spark_gp_tpu import ARDRBFKernel

    xa, ya = load_airfoil()
    xa = (xa - xa.mean(0)) / xa.std(0)
    ya = (ya - ya.mean()) / ya.std()
    kernel = 1.0 * ARDRBFKernel(np.full(xa.shape[1], 1.0), 1e-6, 10) + (
        Const(1e-4) * EyeKernel()
    )
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="spark_gp_tpu"):
        greedy_active_set(32, xa, ya, kernel, kernel.init_theta(), seed=13)
    assert any("not decaying" in r.message for r in caplog.records)
