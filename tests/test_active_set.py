"""Active-set provider tests (ASP.scala counterparts)."""

import numpy as np
import pytest

from spark_gp_tpu import (
    Const,
    EyeKernel,
    GreedilyOptimizingActiveSetProvider,
    KMeansActiveSetProvider,
    RBFKernel,
    RandomActiveSetProvider,
)


@pytest.fixture
def points(rng):
    # two well-separated clusters in 2-d
    a = rng.normal(size=(60, 2)) * 0.2
    b = rng.normal(size=(60, 2)) * 0.2 + np.array([5.0, 5.0])
    x = np.concatenate([a, b])
    y = np.concatenate([np.zeros(60), np.ones(60)])
    return x, y


def _kernel():
    return RBFKernel(1.0) + Const(1e-2) * EyeKernel()


def test_random_provider_samples_points(points):
    x, y = points
    k = _kernel()
    active = RandomActiveSetProvider(10, x, y, k, k.init_theta(), seed=7)
    assert active.shape == (10, 2)
    # every active point is an actual training point
    for row in active:
        assert np.any(np.all(np.isclose(x, row), axis=1))
    # deterministic under the same seed (ASP.scala uses the seed param)
    again = RandomActiveSetProvider(10, x, y, k, k.init_theta(), seed=7)
    np.testing.assert_allclose(active, again)


def test_kmeans_provider_finds_clusters(points):
    x, y = points
    k = _kernel()
    active = KMeansActiveSetProvider(max_iter=20)(2, x, y, k, k.init_theta(), seed=0)
    assert active.shape == (2, 2)
    centers = np.sort(active, axis=0)
    np.testing.assert_allclose(centers[0], [0.0, 0.0], atol=0.5)
    np.testing.assert_allclose(centers[1], [5.0, 5.0], atol=0.5)


def test_kmeans_more_clusters_than_needed(points):
    x, y = points
    k = _kernel()
    active = KMeansActiveSetProvider()(30, x, y, k, k.init_theta(), seed=0)
    assert active.shape == (30, 2)
    assert np.all(np.isfinite(active))


def test_greedy_provider_selects_informative_points(points, rng):
    """Greedy Seeger selection spreads across both clusters and is
    deterministic given the seed."""
    x, y = points
    k = _kernel()
    active = GreedilyOptimizingActiveSetProvider()(8, x, y, k, k.init_theta(), seed=3)
    assert active.shape == (8, 2)
    # both clusters represented
    near_a = np.sum(np.linalg.norm(active, axis=1) < 2.0)
    near_b = np.sum(np.linalg.norm(active - np.array([5.0, 5.0]), axis=1) < 2.0)
    assert near_a > 0 and near_b > 0
    # no duplicate selections
    assert np.unique(np.round(active, 9), axis=0).shape[0] == 8


def test_greedy_improves_over_random_on_fit(rng):
    """On density-skewed data, random sampling wastes its budget on the dense
    cluster while Seeger's information gain spreads the active set — greedy
    must beat random outright, for every seed tried (a vacuous bound here
    would hide a broken scorer; the order-exact oracle test below pins the
    exact semantics)."""
    from spark_gp_tpu import GaussianProcessRegression
    from spark_gp_tpu.utils.validation import rmse

    # 270 points crowded into [0, 0.5], 30 spread over (0.5, 10]: m=12 random
    # picks land ~11:1 in the crowd, leaving the tail unmodelled.
    x = np.concatenate(
        [rng.uniform(0.0, 0.5, size=270), rng.uniform(0.5, 10.0, size=30)]
    )[:, None]
    y = np.sin(x[:, 0] * 1.5) + 0.01 * rng.normal(size=300)

    def fit_with(provider, seed):
        gp = (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(0.3, 1e-6, 10))
            .setActiveSetSize(12)
            .setActiveSetProvider(provider)
            .setSeed(seed)
        )
        model = gp.fit(x, y)
        return rmse(y, model.predict(x))

    for seed in (5, 11):
        r_greedy = fit_with(GreedilyOptimizingActiveSetProvider(), seed)
        r_random = fit_with(RandomActiveSetProvider, seed)
        assert r_greedy < r_random, (r_greedy, r_random)
        assert r_greedy < 0.05, r_greedy  # absolute: tail is actually covered
