"""Property-based kernel-algebra tests (hypothesis).

The example-based tests in test_kernels.py pin golden values and FD
gradients for each family; these properties instead exercise RANDOM
composite kernel trees (sums, trainable/const scales, Schur products over
noise-free factors) and assert the algebraic invariants every composite
must satisfy:

* gram is symmetric PSD (Schur/sum/scale closure under the composition
  rules, the reason ProductKernel rejects noise factors);
* ``diag``/``self_diag`` agree with ``gram``'s diagonal;
* the noise split invariant ``gram == cross(x, x) + white_noise_var * I``
  (crossKernel carries no delta ridge, kernel/Kernel.scala:151-161 —
  this is THE contract the PPA statistics and greedy scorer lean on);
* theta layout: init/bounds lengths equal ``n_hypers`` and init is
  feasible;
* spec identity: an identically-reconstructed tree is ``==`` and hashes
  equal (the jit-static cache key contract);
* the summed gram is autodiff-differentiable with finite gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis (baked into the "
    "dev image; optional elsewhere)"
)
from hypothesis import given, settings, strategies as st  # noqa: E402

from spark_gp_tpu import (
    ARDRBFKernel,
    Const,
    DotProductKernel,
    EyeKernel,
    Matern32Kernel,
    Matern52Kernel,
    PeriodicKernel,
    RationalQuadraticKernel,
    RBFKernel,
    Scalar,
    SpectralMixtureKernel,
    WhiteNoiseKernel,
)

P_DIM = 2  # ARD kernels must match the data dimension

# positive hyperparameter values kept in a well-conditioned band
pos = st.floats(0.3, 3.0)


def _noise_free_leaf():
    return st.one_of(
        st.builds(lambda s: RBFKernel(s, 1e-6, 10.0), pos),
        st.builds(lambda b: ARDRBFKernel(P_DIM, b), pos),
        st.builds(lambda s: Matern32Kernel(s), pos),
        st.builds(lambda s: Matern52Kernel(s), pos),
        st.builds(lambda p, l: PeriodicKernel(p, l), pos, pos),
        st.builds(lambda s, a: RationalQuadraticKernel(s, a), pos, pos),
        st.builds(
            lambda m1, m2: SpectralMixtureKernel(
                P_DIM, 2, means=np.array([[m1] * P_DIM, [m2] * P_DIM])
            ),
            pos, pos,
        ),
        st.builds(lambda s: DotProductKernel(s), pos),
    )


def _noise_free_tree(max_depth=2):
    # products may only combine noise-free factors (ProductKernel guard)
    return st.recursive(
        _noise_free_leaf(),
        lambda children: st.one_of(
            st.builds(lambda a, b: a + b, children, children),
            st.builds(lambda a, b: a * b, children, children),
            st.builds(lambda c, a: Scalar(c) * a, pos, children),
            st.builds(lambda c, a: Const(c) * a, pos, children),
        ),
        max_leaves=4,
    )


def _kernel_tree():
    # optionally add noise at the top level, like every real model kernel
    return st.one_of(
        _noise_free_tree(),
        st.builds(
            lambda k, i: k + WhiteNoiseKernel(i, 0.0, 1.0),
            _noise_free_tree(),
            st.floats(0.0, 0.8),
        ),
        st.builds(
            lambda k, c: k + Const(c) * EyeKernel(),
            _noise_free_tree(),
            st.floats(0.0, 0.5),
        ),
    )


def _data(seed, n=6):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(n, P_DIM)))


@settings(max_examples=30, deadline=None)
@given(kernel=_kernel_tree(), seed=st.integers(0, 2**31 - 1))
def test_gram_symmetric_psd_and_diag_consistent(kernel, seed):
    x = _data(seed)
    theta = jnp.asarray(kernel.init_theta())
    gram = np.asarray(kernel.gram(theta, x))
    np.testing.assert_allclose(gram, gram.T, atol=1e-10)
    eigs = np.linalg.eigvalsh(gram + 1e-9 * np.eye(gram.shape[0]))
    assert eigs.min() > -1e-8, eigs.min()
    np.testing.assert_allclose(
        np.asarray(kernel.diag(theta, x)), np.diagonal(gram), rtol=1e-10,
        atol=1e-12,
    )
    np.testing.assert_allclose(
        np.asarray(kernel.self_diag(theta, x)),
        np.asarray(kernel.diag(theta, x)),
        rtol=1e-10, atol=1e-12,
    )


@settings(max_examples=30, deadline=None)
@given(kernel=_kernel_tree(), seed=st.integers(0, 2**31 - 1))
def test_noise_split_invariant(kernel, seed):
    """gram == cross(x, x) + white_noise_var * I for EVERY composite —
    crossKernel never carries the delta ridge."""
    x = _data(seed)
    theta = jnp.asarray(kernel.init_theta())
    gram = np.asarray(kernel.gram(theta, x))
    cross = np.asarray(kernel.cross(theta, x, x))
    wn = float(kernel.white_noise_var(theta))
    np.testing.assert_allclose(
        gram, cross + wn * np.eye(gram.shape[0]), atol=1e-10
    )


@settings(max_examples=30, deadline=None)
@given(kernel=_kernel_tree())
def test_theta_layout_and_spec_identity(kernel):
    theta0 = kernel.init_theta()
    lo, hi = kernel.bounds()
    assert theta0.shape == lo.shape == hi.shape == (kernel.n_hypers,)
    assert np.all(lo <= theta0) and np.all(theta0 <= hi)
    assert isinstance(kernel.describe(theta0), str)
    # spec identity: the hash/eq contract jit-static caching relies on
    import pickle

    rebuilt = pickle.loads(pickle.dumps(kernel))
    assert rebuilt == kernel and hash(rebuilt) == hash(kernel)


@settings(max_examples=15, deadline=None)
@given(kernel=_kernel_tree(), seed=st.integers(0, 2**31 - 1))
def test_gram_autodiff_gradients_finite(kernel, seed):
    x = _data(seed)
    theta = jnp.asarray(kernel.init_theta())
    if theta.size == 0:
        return
    grad = jax.grad(lambda t: jnp.sum(kernel.gram(t, x)))(theta)
    assert np.all(np.isfinite(np.asarray(grad)))
