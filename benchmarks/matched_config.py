"""Matched-config reconciliation lane (VERDICT r4 #3).

Round 2's driver-captured bench recorded **247k pts/s** (N=100k, s=100,
async pipeline, healthy tunnel); round 4's salvaged window recorded
**80.7k pts/s** (N=300k, GP_SYNC_PHASES=1, ~200 ms tunnel RTT).  The 3x
gap was attributed to RTT + sync mode in prose only.  This script settles
it with data from ONE window at the r2-matched config:

* N=100,000 rows of ``make_benchmark_data`` (PerformanceBenchmark.scala
  shape), s=100 experts, RBF(0.1), sigma2=1e-3, seed 13, maxIter 30,
  device optimizer — byte-for-byte the bench.py primary at BENCH_N=100000;
* the SAME compiled programs timed twice: async (GP_SYNC_PHASES=0, the
  TPU default r2 ran under) and sync-phase (GP_SYNC_PHASES=1, what r4's
  window was forced into);
* the tunnel RTT measured around the fits (median of 20 trivial
  device round trips), so the per-phase sync tax is quantified, not
  asserted.

Emits ONE JSON line; the watcher saves it as TPU_WINDOW_MATCHED.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("MATCHED_N", 100_000))
EXPERT = int(os.environ.get("MATCHED_EXPERT", 100))
MAX_ITER = int(os.environ.get("MATCHED_MAXITER", 30))


def _rtt_ms(reps: int = 20) -> dict:
    """Median/p90 device round-trip latency: dispatch one trivial op and
    block — the floor every synced phase boundary pays."""
    import jax
    import jax.numpy as jnp

    one = jnp.ones(())
    f = jax.jit(lambda v: v + 1.0)
    jax.block_until_ready(f(one))  # compile outside the timed reps
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(f(one))
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return {
        "median_ms": round(times[len(times) // 2], 3),
        "p90_ms": round(times[int(len(times) * 0.9) - 1], 3),
        "reps": reps,
    }


def main() -> None:
    import jax

    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.data import make_benchmark_data

    x, y = make_benchmark_data(N)

    def make_gp(iters: int):
        return (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(0.1))
            .setDatasetSizeForExpert(EXPERT)
            .setActiveSetSize(EXPERT)
            .setSeed(13)
            .setSigma2(1e-3)
            .setMaxIter(iters)
            .setOptimizer("device")
        )

    result = {
        "config": {
            "n_points": N, "expert_size": EXPERT, "max_iter": MAX_ITER,
            "note": "byte-for-byte the r2 BENCH primary config "
            "(BENCH_r02.json: 247124.8 pts/s, fit 0.405s, 14 evals)",
        },
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "rtt_before": _rtt_ms(),
    }

    rows = {}
    for mode, flag in (("async", "0"), ("sync_phases", "1")):
        os.environ["GP_SYNC_PHASES"] = flag
        make_gp(1).fit(x, y)  # compile (shared: max_iter is traced)
        t0 = time.perf_counter()
        model = make_gp(MAX_ITER).fit(x, y)
        dt = time.perf_counter() - t0
        rows[mode] = {
            "fit_seconds": round(dt, 4),
            "train_points_per_sec": round(N / dt, 1),
            "lbfgs_evals": int(model.instr.metrics.get("lbfgs_nfev", 1)),
            "phase_seconds": {
                k: round(v, 4) for k, v in model.instr.timings.items()
            },
        }
    result["rows"] = rows
    result["rtt_after"] = _rtt_ms()

    a, s = rows["async"]["train_points_per_sec"], rows["sync_phases"]["train_points_per_sec"]
    result["summary"] = {
        "async_vs_sync_ratio": round(a / s, 3) if s else None,
        "r2_reference_pts_per_sec": 247124.8,
        "async_vs_r2_ratio": round(a / 247124.8, 3),
        "note": (
            "async_vs_r2_ratio ~1 closes the r2/r4 gap as config+mode; "
            "substantially <1 with a high RTT points at tunnel latency; "
            "<1 with r2-like RTT means a real regression to chase"
        ),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
