"""Large-m hardware lane (VERDICT r4 #4).

The sharded f64 magic solve engages only at m >= 2048
(``models/ppa.py:_DEVICE_SOLVE_MIN_M``), airfoil's reference config is
m=1000 (host-numpy solve path), and no recorded artifact has ever fitted
at m >= 2048 on TPU — `tests/test_dist_linalg.py` proves the blocked
Cholesky on virtual devices, but nothing proved the dispatch boundary +
predict at large m on real hardware.  This lane records, in one window:

1. a synthetic fit at m=4096 (device/sharded O(m^3) solve ENGAGED), with
   an RMSE bar, predict throughput, and phase timings showing where the
   m^3 work ran;
2. airfoil at its reference config (m=1000, Airfoil.scala:24-33 kernel)
   on the TPU f32 path, with the train-RMSE recorded against the
   reference's own 10-fold < 2.1 context.

Emits ONE JSON line; the watcher saves it as TPU_WINDOW_LARGE_M.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

M_LARGE = int(os.environ.get("LARGE_M", 4096))
N_LARGE = int(os.environ.get("LARGE_M_N", 120_000))


def _fit_row(gp, x, y, x_eval, y_eval, rmse_bar) -> dict:
    from spark_gp_tpu.utils.validation import rmse

    t0 = time.perf_counter()
    model = gp.fit(x, y)
    fit_seconds = time.perf_counter() - t0
    t0 = time.perf_counter()
    pred = model.predict(x_eval)
    predict_seconds = time.perf_counter() - t0
    score = float(rmse(y_eval, pred))
    return {
        "fit_seconds": round(fit_seconds, 3),
        "train_points_per_sec": round(x.shape[0] / fit_seconds, 1),
        "predict_points_per_sec": round(x_eval.shape[0] / predict_seconds, 1),
        "rmse": score,
        "rmse_bar": rmse_bar,
        "passed": bool(score < rmse_bar),
        "phase_seconds": {
            k: round(v, 4) for k, v in model.instr.timings.items()
        },
        "lbfgs_evals": int(model.instr.metrics.get("lbfgs_nfev", 1)),
    }


def main() -> None:
    import jax
    import numpy as np

    from spark_gp_tpu import (
        ARDRBFKernel,
        Const,
        EyeKernel,
        GaussianProcessRegression,
        RBFKernel,
    )
    from spark_gp_tpu.models.ppa import _DEVICE_SOLVE_MIN_M

    # phase timings must each carry their own compute, not be absorbed by
    # the async pipeline: the m^3 solve's location in the profile is the
    # point of this artifact
    os.environ["GP_SYNC_PHASES"] = "1"

    result = {
        "platform": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "device_solve_min_m": int(_DEVICE_SOLVE_MIN_M),
    }

    # --- lane 1: m=4096 synthetic, sharded magic solve engaged -----------
    rng = np.random.default_rng(42)
    x = rng.uniform(size=(N_LARGE, 3))
    y = np.sin(2.0 * np.pi * x @ np.array([1.0, 0.7, 0.4])) + 0.05 * rng.normal(
        size=N_LARGE
    )
    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.3, 1e-6, 10))
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(M_LARGE)
        .setSeed(13)
        .setSigma2(1e-3)
        .setMaxIter(int(os.environ.get("LARGE_M_MAXITER", 10)))
    )
    assert M_LARGE >= _DEVICE_SOLVE_MIN_M, (
        f"m={M_LARGE} would take the host-numpy solve path; this lane "
        f"exists to exercise the device path (m >= {_DEVICE_SOLVE_MIN_M})"
    )
    # smooth 3-d surface, 5% noise: a 4096-point active set models it well
    # under the f32 device path — 0.15 is a real bar, not a formality
    result["m4096_synthetic"] = _fit_row(
        gp, x, y, x[:20_000], y[:20_000], rmse_bar=0.15
    )
    result["m4096_synthetic"]["m"] = M_LARGE
    result["m4096_synthetic"]["n"] = N_LARGE

    # --- lane 2: airfoil at the reference m=1000 config ------------------
    from spark_gp_tpu.data import load_airfoil
    from spark_gp_tpu.ops.scaling import scale

    xa, ya = load_airfoil()
    xa = np.asarray(scale(xa))
    gp_a = (
        GaussianProcessRegression()
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(1000)
        .setSigma2(1e-4)
        .setKernel(lambda: 1.0 * ARDRBFKernel(5) + Const(1.0) * EyeKernel())
        .setSeed(13)
    )
    # train-set RMSE on the full data (the example's 10-fold CV < 2.1 bar
    # runs 10 fits — too dear for a window; train RMSE < 2.1 is implied by
    # it and still catches a broken device path)
    result["airfoil_m1000"] = _fit_row(gp_a, xa, ya, xa, ya, rmse_bar=2.1)
    result["airfoil_m1000"]["m"] = 1000
    result["airfoil_m1000"]["n"] = int(xa.shape[0])

    result["passed"] = bool(
        result["m4096_synthetic"]["passed"] and result["airfoil_m1000"]["passed"]
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
