"""Measured roofline for the fit hot loop (VERDICT r4 #1).

The r4 verdict's top item: the recorded MFU is ~0.2% and nothing on record
says WHY — small-s batched linalg could be memory-bound (low MFU is then
the hardware's answer, not a bug), or the stack could be leaving compute
on the table.  This script measures, per expert size s in {128, 256, 512}:

* the batched Gram build (``kernel.gram`` — sq-dist matmul + exp),
* the fused SPD inverse+logdet forward (``spd_inv_logdet`` — the Pallas
  Mosaic kernel on TPU f32, exactly the production routing),
* the full L-BFGS objective evaluation (value+grad through both),

each with analytic FLOPs and HBM bytes, achieved TFLOP/s and GB/s, and the
fractions of the chip's bf16-matmul and HBM-bandwidth peaks — plus a
big-matmul calibration row per precision mode showing what THIS stack can
reach on THIS chip (the realistic ceiling, net of runtime overheads).

Mixed-precision lane: ``GP_MATMUL_PRECISION`` (ops/pallas_linalg.py) is a
trace-time knob, so the parent process measures ``highest`` (the
production default) and re-runs itself in a child with
``GP_MATMUL_PRECISION=high`` (3-pass bf16x3, ~2x matmul rate at ~1e-6
error), then fits the synthetics config at both settings and records the
RMSE/NLL deltas as the quality guard — ``high`` is only worth shipping if
the guard holds on hardware.

Emits ONE JSON line (last line of stdout), watcher-envelope friendly.
Run: ``python benchmarks/roofline.py`` (any backend; the verdict-grade
numbers need the real chip — the watcher runs it inside TPU windows).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_gp_tpu.utils.subproc import run_captured  # noqa: E402

# chip peaks and precision-pass costs: ONE importable home shared with
# bench.py so the two can never disagree about a chip's peak
from spark_gp_tpu.ops.precision import PRECISION_PASSES, chip_peaks  # noqa: E402

TOTAL_POINTS = int(os.environ.get("ROOFLINE_TOTAL", 65536))
EXPERT_SIZES = tuple(
    int(v) for v in os.environ.get("ROOFLINE_SIZES", "128,256,512").split(",")
)
P_DIM = 8
REPEATS = int(os.environ.get("ROOFLINE_REPEATS", 3))


def _timed(fn, *args):
    """Min wall time over REPEATS (1 warm-up/compile call first)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _peaks():
    import jax

    kind = jax.devices()[0].device_kind.lower()
    return (kind, *chip_peaks(kind))


def _row(name, seconds, flops, bytes_, tflops_peak, gbps_peak, passes=6):
    tfs = flops / seconds / 1e12
    gbs = bytes_ / seconds / 1e9
    row = {
        "op": name,
        "seconds": round(seconds, 6),
        "gflops_nominal": round(flops / 1e9, 3),
        "gbytes_hbm_min": round(bytes_ / 1e9, 4),
        "achieved_tflops_per_sec": round(tfs, 4),
        "achieved_gb_per_sec": round(gbs, 2),
    }
    if tflops_peak:
        # two ceilings: raw bf16 peak (the MFU denominator every round
        # reports) and the precision-adjusted matmul-rate ceiling
        row["mfu_vs_bf16_peak"] = round(tfs / tflops_peak, 5)
        row["frac_of_precision_ceiling"] = round(
            tfs / (tflops_peak / passes), 5
        )
    if gbps_peak:
        row["frac_of_hbm_peak"] = round(gbs / gbps_peak, 5)
    if tflops_peak and gbps_peak:
        row["bound"] = (
            "memory" if row["frac_of_hbm_peak"] >= row["frac_of_precision_ceiling"]
            else "compute"
        )
    return row


def measure(precision: str) -> dict:
    os.environ["GP_MATMUL_PRECISION"] = precision
    import jax
    import jax.numpy as jnp
    import numpy as np

    from spark_gp_tpu import RBFKernel
    from spark_gp_tpu.kernels.base import Const, EyeKernel
    from spark_gp_tpu.ops.pallas_linalg import spd_inv_logdet

    kind, tflops_peak, gbps_peak = _peaks()
    passes = PRECISION_PASSES[precision]
    report = {
        "precision": precision,
        "device_kind": kind,
        "platform": jax.default_backend(),
        "bf16_peak_tflops": tflops_peak,
        "hbm_peak_gbps": gbps_peak,
        "total_points": TOTAL_POINTS,
    }

    # calibration: one big matmul at this precision — the stack's ceiling.
    # The precision is passed EXPLICITLY from the policy resolution
    # (ops/precision.matmul_precision reads GP_MATMUL_PRECISION at trace
    # time): a bare `u @ v` ignores the knob entirely and runs 1-pass
    # bf16, so the 'highest' lane was reporting bf16 throughput against
    # the 6-pass ceiling — a ~6x flattering calibration row.
    from spark_gp_tpu.ops.precision import matmul_precision

    dim = 4096
    a = jnp.asarray(np.random.default_rng(0).normal(size=(dim, dim)), jnp.float32)
    mm = jax.jit(
        lambda u, v: jnp.matmul(u, v, precision=matmul_precision())
    )
    secs = _timed(mm, a, a)
    report["calibration_matmul_4096"] = _row(
        f"matmul {dim}^3 f32 (trace-time precision={precision})",
        secs, 2.0 * dim**3, 3 * dim * dim * 4, tflops_peak, gbps_peak, passes,
    )

    kernel = 1.0 * RBFKernel(0.5, 1e-6, 10) + Const(1e-3) * EyeKernel()
    theta = jnp.asarray(kernel.init_theta(), jnp.float32)
    rows = []
    for s in EXPERT_SIZES:
        e = max(1, TOTAL_POINTS // s)
        rng = np.random.default_rng(s)
        xe = jnp.asarray(rng.normal(size=(e, s, P_DIM)), jnp.float32)
        ye = jnp.asarray(rng.normal(size=(e, s)), jnp.float32)

        gram = jax.jit(jax.vmap(lambda xb: kernel.gram(theta, xb)))
        g_secs = _timed(gram, xe)
        # nominal: the sq-dist inner product (2 e s^2 p) + exp/elementwise
        rows.append(_row(
            f"gram_build s={s} E={e}", g_secs,
            2.0 * e * s * s * P_DIM,
            (e * s * P_DIM + e * s * s) * 4.0,
            tflops_peak, gbps_peak, 6,  # sq_dist pins HIGHEST by design
        ))

        kmat = gram(xe)
        fwd = jax.jit(lambda k: spd_inv_logdet(k))
        f_secs = _timed(fwd, kmat)
        rows.append(_row(
            f"spd_inv_logdet_fwd s={s} E={e}", f_secs,
            2.0 * e * s**3,
            2.0 * e * s * s * 4.0,
            tflops_peak, gbps_peak, passes,
        ))

        def objective(th, xb, yb):
            km = jax.vmap(lambda x1: kernel.gram(th, x1))(xb)
            kinv, logdet = spd_inv_logdet(km)
            alpha = jnp.einsum("eij,ej->ei", kinv, yb)
            return 0.5 * jnp.einsum("ei,ei->", yb, alpha) + 0.5 * jnp.sum(logdet)

        vg = jax.jit(jax.value_and_grad(objective))
        vg_secs = _timed(vg, theta, xe, ye)
        rows.append(_row(
            f"objective_value_and_grad s={s} E={e}", vg_secs,
            6.0 * e * s**3 + 4.0 * e * s * s * (P_DIM + 2),
            4.0 * e * s * s * 4.0,
            tflops_peak, gbps_peak, passes,
        ))
    report["rows"] = rows
    return report


def quality_fit() -> dict:
    """Synthetics-config fit at the ambient GP_MATMUL_PRECISION: the
    mixed-precision quality guard (RMSE bar + converged NLL)."""
    from examples.synthetics import make_gp
    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import rmse

    x, y = make_synthetics()
    cut = 1600
    gp = make_gp()
    model = gp.fit(x[:cut], y[:cut])
    pred = model.predict(x[cut:])
    return {
        "precision": os.environ.get("GP_MATMUL_PRECISION", "highest"),
        "rmse_holdout": float(rmse(y[cut:], pred)),
        "nll": float(model.instr.metrics.get("final_nll", float("nan"))),
    }


def _run_child(precision: str) -> dict:
    """One precision lane in a fresh process.  Two reasons this is a
    subprocess and the parent NEVER touches jax: the precision knob is
    trace-time (a fresh process is the only clean full retrace), and libtpu
    is single-process-exclusive — a parent holding the chip would doom
    every child to an init failure.

    Runs through utils.subproc.run_captured, NOT subprocess.run: run()'s
    timeout path drains the killed child's pipes with an UNBOUNDED
    communicate(), so a tunnel helper process inheriting the pipe write
    ends would wedge a standalone roofline run past its own fence (the
    exact hazard bench.py's supervisor already defends against)."""
    env = dict(os.environ)
    env["GP_MATMUL_PRECISION"] = precision
    # 600s default: both lanes must fit inside bench.py's outer
    # BENCH_ROOFLINE_TIMEOUT=1500s fence with slack
    child = run_captured(
        [sys.executable, os.path.abspath(__file__), "--child"],
        float(os.environ.get("ROOFLINE_CHILD_TIMEOUT", 600)), env=env,
    )
    for line in reversed(child.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    status = "timed out" if child.timed_out else f"rc={child.returncode}"
    raise RuntimeError(
        f"no JSON from {precision} lane ({status}): "
        + (child.stderr or "")[-300:]
    )


def main() -> None:
    if "--child" in sys.argv:
        out = {"measure": measure(os.environ["GP_MATMUL_PRECISION"]),
               "quality": quality_fit()}
        print(json.dumps(out))
        return

    report = {"captured": time.strftime("%Y-%m-%dT%H:%M:%S")}
    for precision in ("highest", "high"):
        try:
            payload = _run_child(precision)
            report[precision] = payload["measure"]
            report[f"quality_{precision}"] = payload["quality"]
        except Exception as exc:  # noqa: BLE001 — record and keep going
            report[f"{precision}_error"] = f"{type(exc).__name__}: {exc}"[:300]
        # incremental emit after EVERY lane: consumers parse the LAST JSON
        # line, so a kill during the second lane still salvages the first
        # (the same early-emit convention as bench.py's primary metric)
        print(json.dumps(report), flush=True)

    if "quality_high" in report and "quality_highest" in report:
        q_hi, q3 = report["quality_highest"], report["quality_high"]
        bar = 0.11  # Synthetics.scala:33
        report["mixed_precision_guard"] = {
            "rmse_delta": abs(q3["rmse_holdout"] - q_hi["rmse_holdout"]),
            "both_under_bar": bool(
                q_hi["rmse_holdout"] < bar and q3["rmse_holdout"] < bar
            ),
            "bar": bar,
            "verdict": (
                "high is quality-safe on this config"
                if q3["rmse_holdout"] < bar
                else "high BREACHES the quality bar — keep highest"
            ),
        }
    print(json.dumps(report))


if __name__ == "__main__":
    main()
