"""Background TPU-uptime watcher (round-4 tunnel mitigation).

The axon TPU tunnel has been down for the whole round, hanging inside
backend init rather than failing fast.  This watcher polls in a detached
loop; the moment a probe subprocess reports a real TPU it

1. runs ``python bench.py`` (which persists the XLA compile cache, emits
   its primary metric line immediately, and — r5 — appends the
   post-worker roofline/mixed-precision lane), saving the JSON to
   ``TPU_WINDOW_BENCH.json``;
2. runs the Mosaic-lowering parity tests PLUS the asserted on-chip
   quality slice (``tests/test_tpu_quality_slice.py``), saving the pytest
   tail to ``TPU_WINDOW_TESTS.json``;
3. runs the r2-reconciliation matched-config lane
   (``TPU_WINDOW_MATCHED.json``) and the large-m lane
   (``TPU_WINDOW_LARGE_M.json``);
4. runs the Pallas expert-size sweep, saving ``TPU_WINDOW_PALLAS.json``;

re-probing between lanes (a tunnel that dies mid-window abandons the
remaining lanes instead of serially burning their timeouts), then keeps
polling — later windows refresh the artifacts.  Everything is
best-effort and timeout-fenced; the watcher itself never touches the
device in-process (a hung init inside this process would kill the loop).

The TPU_WINDOW_* artifacts are deliberately NOT gitignored: the round
driver commits uncommitted work at round end, so a window that opens
after the interactive session's turns are exhausted still lands its
hardware evidence in the repo.  Each artifact is a JSON envelope
``{"captured": ts, "stdout_tail": ..., "stderr_tail": ...}`` — parse the
last JSON line of ``stdout_tail`` for bench/sweep results.

Run: ``nohup python benchmarks/tpu_window_watcher.py &`` from the repo
root.  Stop: kill the pid in ``TPU_WINDOW_WATCHER.pid``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spark_gp_tpu.utils.subproc import run_captured  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: where _run writes lane artifacts; the dress rehearsal points it at a
#: scratch dir so rehearsal envelopes never clobber real TPU evidence
ART_DIR = ROOT
PROBE = (
    # a computed round trip, not just enumeration: the r5 tunnel failure
    # mode can register the platform / list devices yet hang on first
    # compute — a window only "opens" if the chip actually runs something
    "import jax, jax.numpy as jnp; d = jax.devices(); "
    "jax.block_until_ready(jnp.ones(()) + 1); print(d[0].platform)"
)


def _probe_tpu(timeout_s: float = 90.0) -> bool:
    if os.environ.get("GP_WATCHER_ASSUME_UP") == "1":
        # dress-rehearsal override: pretend the window is open so the
        # full capture sequence runs on CPU (rehearse() below)
        return True
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    r = run_captured([sys.executable, "-c", PROBE], timeout_s, env=env)
    return (not r.timed_out) and r.stdout.strip().endswith("tpu")


def _bench_fence_s() -> float:
    """Bench-lane fence sized from the knobs bench.py actually honors,
    instead of a hardcoded 4500 s that happened to equal the defaults
    with ZERO slack (an operator raising BENCH_WORKER_TIMEOUT would have
    silently had the watcher kill a healthy bench mid-measurement).

    Budget: every preflight attempt of BOTH plans — the default plan's
    ``pf_attempts`` (with bench.py's linear 15 s-per-attempt backoff
    sleeps between them) plus the CPU-fallback plan's single attempt —
    TWO worker runs (both plans run when the first fails), the
    post-worker roofline, and a fixed supervisor/IO margin."""
    pf_timeout = float(os.environ.get("BENCH_PREFLIGHT_TIMEOUT", 150))
    pf_attempts = int(os.environ.get("BENCH_PREFLIGHT_ATTEMPTS", 4))
    worker = float(os.environ.get("BENCH_WORKER_TIMEOUT", 2400))
    roofline = float(os.environ.get("BENCH_ROOFLINE_TIMEOUT", 1500))
    backoff = 15.0 * pf_attempts * (pf_attempts - 1) / 2.0
    return (
        (pf_attempts + 1) * pf_timeout + backoff + 2.0 * worker
        + roofline + 300.0
    )


def _run(cmd, out_path, timeout_s, env=None):
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S")
    # run_captured, not subprocess.run: run()'s post-kill pipe drain is
    # unbounded, so a tunnel helper holding the pipes would wedge the
    # watcher loop forever — exactly the failure mode being monitored
    r = run_captured(cmd, timeout_s, env=env or dict(os.environ), cwd=ROOT)
    envelope = {
        "captured": stamp,
        "command": cmd,
        "stdout_tail": r.stdout[-20000:],
        "stderr_tail": r.stderr[-4000:],
    }
    if r.timed_out:
        envelope["timed_out_after_s"] = timeout_s
    else:
        envelope["returncode"] = r.returncode
    # Never clobber previously-captured good evidence with a worse capture:
    # park the new envelope alongside the artifact instead when this run
    # failed/timed out while the prior recorded a clean exit, OR when the
    # prior measured on TPU and this run didn't reach the chip (bench.py's
    # CPU-fallback plan exits 0 but its numbers are not comparable).
    target = os.path.join(ART_DIR, out_path)
    prior = None
    if os.path.exists(target):
        try:
            with open(target) as fh:
                prior = json.load(fh)
        except Exception:  # noqa: BLE001 — unreadable prior: overwrite it
            prior = None
    if isinstance(prior, dict):
        failed_vs_clean = (
            envelope.get("returncode") != 0 and prior.get("returncode") == 0
        )
        lost_the_chip = (
            _captured_platform(prior) == "tpu"
            and _captured_platform(envelope) != "tpu"
        )
        if failed_vs_clean or lost_the_chip:
            target = target + ".failed"
    with open(target, "w") as fh:
        json.dump(envelope, fh, indent=1)
        fh.write("\n")


def _captured_platform(envelope):
    """Platform recorded in an envelope's last parseable stdout JSON line
    (bench.py detail.platform), or None for non-bench artifacts."""
    for line in reversed((envelope.get("stdout_tail") or "").splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            detail = parsed.get("detail")
            if isinstance(detail, dict) and "platform" in detail:
                return detail["platform"]
            return parsed.get("platform")
    return None


def capture_window(note) -> bool:
    """One full window capture: every lane in priority order, re-probing
    the tunnel between lanes and abandoning the rest the moment it dies
    (windows can be shorter than the full sequence; a dead tunnel would
    otherwise burn every remaining lane's whole timeout for nothing).

    Lane order is deliberate: bench first (it lands the round's headline
    number, warms the persistent compile cache, and appends the
    post-worker roofline; its fence is derived from the constituent
    timeout knobs — ``_bench_fence_s`` — and it prints the primary line
    early so even a fence trip salvages the measurement), then the
    Mosaic + on-chip-quality tests (VERDICT r4 #2), the matched-config
    and large-m lanes (r4 #3/#4), and the Pallas sweep last.
    """
    env = dict(os.environ)
    rehearsal = env.get("GP_WATCHER_REHEARSAL") == "1"
    if rehearsal:
        # dress rehearsal (rehearse() below): the SAME five-lane sequence
        # on the CPU backend — tiny configs, real subprocesses
        env["JAX_PLATFORMS"] = "cpu"
    else:
        env.pop("JAX_PLATFORMS", None)
    tenv = dict(env)
    tenv["GP_TEST_PLATFORM"] = "cpu" if rehearsal else "tpu"
    lanes = [
        ([sys.executable, "bench.py"],
         "TPU_WINDOW_BENCH.json", _bench_fence_s(), env, "bench"),
        ([sys.executable, "-m", "pytest",
          "tests/test_pallas_linalg.py",
          "tests/test_tpu_quality_slice.py", "-q"],
         "TPU_WINDOW_TESTS.json", 1500, tenv,
         "mosaic + quality-slice tests"),
        ([sys.executable, "benchmarks/matched_config.py"],
         "TPU_WINDOW_MATCHED.json", 1800, env, "matched-config lane"),
        ([sys.executable, "benchmarks/large_m.py"],
         "TPU_WINDOW_LARGE_M.json", 1800, env, "large-m lane"),
        ([sys.executable, "benchmarks/pallas_sweep.py"],
         "TPU_WINDOW_PALLAS.json", 1800, env, "pallas sweep"),
    ]
    for i, (cmd, out_path, timeout_s, lane_env, name) in enumerate(lanes):
        _run(cmd, out_path, timeout_s, lane_env)
        note(f"{name} done")
        if i + 1 < len(lanes) and not _probe_tpu():
            note("tunnel died mid-window — abandoning remaining lanes")
            return False
    note("window capture finished")
    return True


#: env-forced tiny configs for the dress rehearsal: every lane's real
#: knobs at CPU-budget sizes (the same shapes test_bench_contract proves)
REHEARSAL_ENV = {
    "GP_WATCHER_REHEARSAL": "1",
    "GP_WATCHER_ASSUME_UP": "1",
    "JAX_PLATFORMS": "cpu",
    "BENCH_N": "1500", "BENCH_EXPERT": "50", "BENCH_MXU_EXPERT": "64",
    "BENCH_MAXITER": "3", "BENCH_PREFLIGHT_TIMEOUT": "120",
    "BENCH_PREFLIGHT_ATTEMPTS": "1",
    "MATCHED_N": "2000", "MATCHED_EXPERT": "50", "MATCHED_MAXITER": "3",
    "LARGE_M": "2048", "LARGE_M_N": "12000", "LARGE_M_MAXITER": "2",
    "PALLAS_SWEEP_SIZES": "32,64", "PALLAS_SWEEP_ITERS": "2",
    # the fused gram·vector streaming lane (ISSUE 20) rides the same
    # sweep subprocess; tiny sizes keep the interpret-mode pass cheap
    "PALLAS_SWEEP_MATVEC_SIZES": "32,64",
}


def rehearse(out_dir: str, note=print) -> dict:
    """Watcher dress rehearsal: the FULL five-lane window sequence through
    :func:`capture_window` itself — real subprocess lanes at env-forced
    tiny CPU configs, artifacts written to ``out_dir`` (never the real
    ``TPU_WINDOW_*`` evidence).  Returns a summary dict (also written to
    ``out_dir/WATCHER_REHEARSAL.json``) recording, per lane, the envelope
    validity, returncode/timeout and captured platform — the proof the
    whole capture plumbing works BEFORE the next real tunnel window, not
    during it.
    """
    global ART_DIR
    prev_art, prev_env = ART_DIR, {}
    for key, value in REHEARSAL_ENV.items():
        prev_env[key] = os.environ.get(key)
        os.environ[key] = value
    ART_DIR = out_dir
    os.makedirs(out_dir, exist_ok=True)
    notes = []

    def _note(msg):
        notes.append(msg)
        note(msg)

    start = time.time()
    try:
        completed = capture_window(_note)
    finally:
        ART_DIR = prev_art
        for key, value in prev_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
    lanes = {}
    for name in ("BENCH", "TESTS", "MATCHED", "LARGE_M", "PALLAS"):
        path = os.path.join(out_dir, f"TPU_WINDOW_{name}.json")
        lane = {"artifact": os.path.basename(path), "present": False}
        if os.path.exists(path):
            try:
                with open(path) as fh:
                    envelope = json.load(fh)
                lane.update(
                    present=True,
                    valid_envelope=all(
                        k in envelope
                        for k in ("captured", "command", "stdout_tail")
                    ) and (
                        "returncode" in envelope
                        or "timed_out_after_s" in envelope
                    ),
                    returncode=envelope.get("returncode"),
                    timed_out=("timed_out_after_s" in envelope),
                    platform=_captured_platform(envelope),
                )
                if name == "PALLAS":
                    # the sweep's fused gram·vector rows (ISSUE 20):
                    # rehearsal proof that lane 5 now carries the
                    # streaming-matvec measurements too
                    lane["matvec_rows"] = (
                        '"lane": "matvec"'
                        in (envelope.get("stdout_tail") or "")
                    )
            except ValueError as exc:
                lane.update(valid_envelope=False, error=str(exc)[:200])
        lanes[name] = lane
    summary = {
        "format": "spark_gp_tpu.watcher_rehearsal/v1",
        "captured": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "completed_window": completed,
        "wall_seconds": round(time.time() - start, 1),
        "env": dict(REHEARSAL_ENV),
        "lanes": lanes,
        "notes": notes,
    }
    with open(os.path.join(out_dir, "WATCHER_REHEARSAL.json"), "w") as fh:
        json.dump(summary, fh, indent=1)
        fh.write("\n")
    return summary


def main() -> None:
    with open(os.path.join(ROOT, "TPU_WINDOW_WATCHER.pid"), "w") as fh:
        fh.write(str(os.getpid()))
    log = open(os.path.join(ROOT, "TPU_WINDOW_WATCHER.log"), "a")

    def note(msg):
        log.write(f"{time.strftime('%H:%M:%S')} {msg}\n")
        log.flush()

    note("watcher started")
    failed_probes = 0
    while True:
        if _probe_tpu():
            failed_probes = 0
            note("TPU REACHABLE — capturing artifacts")
            if capture_window(note):
                # full capture landed: nothing new to gain for a while
                note("sleeping 15 min before re-probe")
                time.sleep(900)
            # bailed mid-window: fall through to the normal 3-min probe
            # cadence so a quickly-reopening window isn't missed
        else:
            # heartbeat every ~30 min of failed probes: a silent log reads
            # as "watcher died", not "tunnel stayed down" — post-mortems
            # need to tell the two apart
            failed_probes += 1
            if failed_probes % 10 == 0:
                note(f"tunnel still down ({failed_probes} failed probes)")
            time.sleep(180)


if __name__ == "__main__":
    if "--rehearse" in sys.argv:
        # dress rehearsal: full five-lane capture on CPU, artifacts into
        # ./rehearsal (or the next argument after --rehearse)
        idx = sys.argv.index("--rehearse")
        target = (
            sys.argv[idx + 1] if len(sys.argv) > idx + 1
            else os.path.join(ROOT, "rehearsal")
        )
        summary = rehearse(target)
        sys.exit(0 if all(
            lane.get("valid_envelope") for lane in summary["lanes"].values()
        ) else 1)
    main()
