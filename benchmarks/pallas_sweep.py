"""Pallas vs XLA-Cholesky sweep over expert sizes, on the real device.

The headline optimization replaces XLA's batched factor/solve/invert chain
with the fused Pallas kernel (ops/pallas_linalg.py); this sweep verifies it
wins at every expert size the estimator defaults and stress configs use —
including the packed small sizes (s <= 64) and the multi-block large sizes
(128 < s <= 512) added in round 2 (VERDICT r1 #4).

Run on TPU:  python benchmarks/pallas_sweep.py
Prints one JSON line per size:
  {"n": s, "batch": B, "pallas_us_per_matrix": ..., "xla_us_per_matrix": ...,
   "speedup": ...}
"""

from __future__ import annotations

import os as _os
import sys as _sys

# runnable as ``python benchmarks/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import json
import time

import numpy as np


def _bench(fn, k, iters: int = 20) -> float:
    import jax

    out = fn(k)  # compile + warm
    jax.block_until_ready(out)
    start = time.perf_counter()
    for _ in range(iters):
        out = fn(k)
    jax.block_until_ready(out)
    return (time.perf_counter() - start) / iters


def sweep(sizes=(32, 64, 100, 128, 200, 256, 512), iters: int = 20,
          on_row=None) -> list:
    """Time the fused Pallas kernel vs XLA's batched Cholesky chain at each
    expert size; returns one dict per size (importable — bench.py embeds a
    compressed sweep in its TPU runs so the artifact is captured on real
    hardware automatically).  ``on_row`` is called with each row as it is
    measured — main() prints through it so a mid-sweep tunnel death still
    leaves every completed size on stdout (r4's window died exactly here,
    during a remote compile, and the buffered design lost the whole sweep).
    """
    import jax
    import jax.numpy as jnp

    from spark_gp_tpu.ops.pallas_linalg import (
        _chol_inv_logdet,
        _pallas_inv_logdet,
    )

    backend = jax.default_backend()
    interpret = backend != "tpu"

    rng = np.random.default_rng(0)
    rows = []
    for n in sizes:
        # batch sized to ~100k matrix elements of work per call
        b = max(8, min(1024, 4_000_000 // (n * n)))
        a = rng.normal(size=(b, n, n)).astype(np.float32)
        k = jnp.asarray(a @ a.transpose(0, 2, 1) + n * np.eye(n, dtype=np.float32))

        pallas_fn = jax.jit(lambda m: _pallas_inv_logdet(m, interpret))
        xla_fn = jax.jit(_chol_inv_logdet)
        t_pallas = _bench(pallas_fn, k, iters)
        t_xla = _bench(xla_fn, k, iters)

        row = {
            "n": n,
            "batch": b,
            "pallas_us_per_matrix": round(t_pallas / b * 1e6, 2),
            "xla_us_per_matrix": round(t_xla / b * 1e6, 2),
            "speedup": round(t_xla / t_pallas, 2),
            "backend": backend,
        }
        rows.append(row)
        if on_row is not None:
            on_row(row)
    return rows


def sweep_matvec(sizes=(512, 1024, 2048), iters: int = 20,
                 on_row=None) -> list:
    """Time the fused gram·vector streaming kernel (ops/pallas_matvec.py)
    against its bit-equivalent ``lax.scan`` row-panel fallback at each
    expert size — the matfree solver lane's engine (ISSUE 20).  Rows carry
    ``"lane": "matvec"`` so watcher/bench consumers can split them from
    the factorization sweep's rows.  Off-TPU the Pallas path runs in
    interpret mode (timings prove plumbing, not performance — same
    contract as :func:`sweep`)."""
    import jax
    import jax.numpy as jnp

    from spark_gp_tpu.ops.pallas_matvec import (
        TILE_TRANSFORMS,
        matvec_tile,
        streamed_matvec,
    )

    backend = jax.default_backend()
    interpret = backend != "tpu"

    rng = np.random.default_rng(1)
    rows = []
    for n in sizes:
        tile = matvec_tile(n)
        x = jnp.asarray(rng.normal(size=(n, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(n, 4)).astype(np.float32))
        params = jnp.asarray([0.5], dtype=jnp.float32)

        transform = TILE_TRANSFORMS["rbf"]
        pallas_fn = jax.jit(lambda xx, vv: streamed_matvec(
            xx, vv, transform, params, kind="sqdist",
            interpret=interpret or None,
        ))
        scan_fn = jax.jit(lambda xx, vv: streamed_matvec(
            xx, vv, transform, params, kind="sqdist", differentiable=True
        ))
        t_pallas = _bench(lambda k: pallas_fn(x, k), v, iters)
        t_scan = _bench(lambda k: scan_fn(x, k), v, iters)

        row = {
            "lane": "matvec",
            "n": n,
            "tile": tile,
            "pallas_us_per_matvec": round(t_pallas * 1e6, 2),
            "scan_us_per_matvec": round(t_scan * 1e6, 2),
            "speedup": round(t_scan / t_pallas, 2),
            "backend": backend,
        }
        rows.append(row)
        if on_row is not None:
            on_row(row)
    return rows


def main() -> None:
    import jax

    if jax.default_backend() != "tpu":
        print(json.dumps({"warning": f"backend={jax.default_backend()}: "
                          "Pallas runs in interpret mode; timings are NOT "
                          "meaningful"}), flush=True)
    # print AS each size completes (flushed): partial sweeps survive a
    # mid-run tunnel death in the watcher's captured stdout.  Size/iter
    # knobs exist for the watcher dress rehearsal (interpret-mode CPU runs
    # are ~100x slower per matrix; a tiny sweep still proves the lane).
    sizes_env = _os.environ.get("PALLAS_SWEEP_SIZES", "").strip()
    kwargs = {}
    if sizes_env:
        kwargs["sizes"] = tuple(int(s) for s in sizes_env.split(","))
    iters_env = _os.environ.get("PALLAS_SWEEP_ITERS", "").strip()
    if iters_env:
        kwargs["iters"] = int(iters_env)
    emit = lambda row: print(json.dumps(row), flush=True)  # noqa: E731
    sweep(on_row=emit, **kwargs)
    # the fused gram·vector streaming lane rides the same knobs: the
    # watcher rehearsal pins tiny sizes so the interpret-mode pass stays
    # inside the rehearsal budget while still proving lane 5's plumbing
    mv_sizes_env = _os.environ.get("PALLAS_SWEEP_MATVEC_SIZES", "").strip()
    mv_kwargs = dict(kwargs)
    if mv_sizes_env:
        mv_kwargs["sizes"] = tuple(int(s) for s in mv_sizes_env.split(","))
    sweep_matvec(on_row=emit, **mv_kwargs)


if __name__ == "__main__":
    main()
