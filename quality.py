"""Quality + scaling artifact runner.

The perf benchmark (bench.py) records throughput; this records everything
else the framework claims: the airfoil parity bar, classifier throughput,
the stress-config stand-ins, the virtual-mesh weak-scaling shape, and (on
TPU) the Pallas kernel sweep — as machine-checkable JSON instead of
docstring assertions.

Run: ``python quality.py [--out QUALITY.json] [--parts a,b,...]``
Each part runs in its own subprocess under a timeout (the TPU runtime here
can hang inside backend init — same supervisor pattern as bench.py); a part
failure records an error entry instead of killing the run.

Parts:
  airfoil        10-fold CV RMSE on UCI airfoil, the reference's < 2.1 bar
                 (Airfoil.scala:24)
  iris           10-fold OneVsRest accuracy on UCI iris (Iris.scala:35
                 prints it unasserted; recorded here)
  iris_native_mc 10-fold accuracy on iris through the NATIVE multiclass
                 (softmax Laplace) estimator, same folds as `iris`
  iris_ep        10-fold accuracy on iris through the EP (probit) engine,
                 same folds as `iris` (engines must agree in regime)
  poisson        count-regression rate-recovery error (the generic-
                 likelihood Laplace path), seeded synthetic; includes a
                 Negative Binomial sub-fit on overdispersed counts with
                 its own bar (both gate the part's passed flag)
  gpc_mnist      784-d MNIST-shaped binary classifier: accuracy + fit
                 seconds + points/s (the Laplace inner loop is the novel
                 expensive path VERDICT r2 flagged as unmeasured)
  protein        46k-shape stand-in, subsampled: RMSE + wall-clock guard
  year_msd       515k-shape stand-in, subsampled: RMSE + wall-clock guard
  greedy_scale   greedy Seeger selection at the Year-MSD shape (m=512),
                 wall-clock + quality vs random at the same m
  greedy_vs_random  the demonstrated-payoff regime (density-skewed data,
                 small m): greedy must BEAT the best of 3 random seeds
                 (asserted); the airfoil negative result is in PARITY.md
  loo            LOO diagnostics vs reality on synthetics: the one-
                 factorization loo_rmse must track the true 10-fold CV
                 RMSE (ratio bar) and clear the example's 0.11 quality bar
  objectives     the three training objectives (marginal / loo / elbo)
                 head-to-head on held-out synthetics: RMSE + NLPD per
                 objective; every objective must clear the example's
                 RMSE bar (none is allowed to be broken)
  spectral_mixture  pattern extrapolation: an SM kernel + batched
                 multi-start must extrapolate a two-frequency signal a
                 full period past the data (asserted < 0.1 RMSE) where
                 the RBF kernel reverts to the mean (~0.8, recorded)
  weak_scaling   1/2/4/8 virtual CPU devices, fixed per-device load, the
                 sharded device-L-BFGS fit (records the curve's shape; on a
                 shared-core host this tracks compile/exec health, not true
                 parallel speedup — real scaling needs real chips)
  pallas_sweep   the s in {32..512} fused-kernel sweep (TPU only; skipped
                 with a note elsewhere)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_ALL_PARTS = (
    "airfoil", "iris", "iris_native_mc", "iris_ep", "poisson", "gpc_mnist",
    "protein", "year_msd", "greedy_scale", "greedy_vs_random", "loo",
    "objectives", "aggregation", "spectral_mixture", "weak_scaling",
    "pallas_sweep",
)


def _assert_platform() -> None:
    """Re-assert JAX_PLATFORMS over site hooks that rewrite the resolved
    config at import time (utils/platform.py rationale; same guard as
    bench.py's preflight).  Without this, a part meant for CPU can hang
    inside TPU backend init."""
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax

        jax.config.update("jax_platforms", p)


_PREFLIGHT_CODE = (
    "import json, os, jax; "
    "p = os.environ.get('JAX_PLATFORMS'); "
    "p and jax.config.update('jax_platforms', p); "
    "ds = jax.devices(); "
    "print(json.dumps({'backend': ds[0].platform, 'device': str(ds[0])}))"
)


# --------------------------------------------------------------- parts ----

def part_airfoil() -> dict:
    _assert_platform()
    import numpy as np

    from spark_gp_tpu import (
        ARDRBFKernel, Const, EyeKernel, GaussianProcessRegression,
    )
    from spark_gp_tpu.data import load_airfoil
    from spark_gp_tpu.ops.scaling import scale
    from spark_gp_tpu.utils.validation import cross_validate, rmse

    x, y = load_airfoil()
    x = np.asarray(scale(x))
    gp = (
        GaussianProcessRegression()
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(1000)
        .setSigma2(1e-4)
        .setKernel(lambda: 1.0 * ARDRBFKernel(5) + Const(1.0) * EyeKernel())
        .setSeed(13)
    )
    start = time.perf_counter()
    score = cross_validate(gp, x, y, num_folds=10, metric=rmse, seed=13)
    return {
        "rmse_10fold": float(score),
        "bar": 2.1,
        "passed": bool(score < 2.1),
        "seconds": time.perf_counter() - start,
    }


def part_iris() -> dict:
    """10-fold OneVsRest accuracy on UCI iris (the reference prints this
    without asserting, Iris.scala:35; recorded here so regressions in the
    OvR/Laplace path are visible)."""
    _assert_platform()
    from examples.iris import make_gpc  # single source of the Iris.scala:26 config
    from spark_gp_tpu.data import load_iris
    from spark_gp_tpu.utils.validation import OneVsRest, accuracy, cross_validate

    x, y = load_iris()

    start = time.perf_counter()
    score = cross_validate(
        OneVsRest(make_gpc), x, y, num_folds=10, metric=accuracy, seed=13
    )
    return {
        "accuracy_10fold": float(score),
        "bar": 0.9,
        "passed": bool(score > 0.9),
        "seconds": time.perf_counter() - start,
    }


def part_iris_native_mc() -> dict:
    """10-fold accuracy on iris through the NATIVE multiclass estimator
    (softmax Laplace, one coupled model per fold) at the same expert/active
    configuration as the OvR part — recorded so the two multiclass routes
    can be compared release over release."""
    _assert_platform()
    from examples.iris import make_native_gpc
    from spark_gp_tpu.data import load_iris
    from spark_gp_tpu.utils.validation import accuracy, cross_validate

    x, y = load_iris()
    start = time.perf_counter()
    # same cross_validate folds/seed as part_iris, so the two routes are
    # compared on identical splits
    score = cross_validate(
        make_native_gpc(), x, y, num_folds=10, metric=accuracy, seed=13
    )
    return {
        "accuracy_10fold": float(score),
        "bar": 0.9,
        "passed": bool(score > 0.9),
        "seconds": time.perf_counter() - start,
    }


def part_iris_ep() -> dict:
    """10-fold accuracy on iris through the EP inference engine (probit,
    OneVsRest over binary EP classifiers) on the same folds as `iris` —
    the two engines approximate the same posterior and must land in the
    same accuracy regime."""
    _assert_platform()
    from examples.iris import make_ep_gpc  # single source of the iris config
    from spark_gp_tpu.data import load_iris
    from spark_gp_tpu.utils.validation import OneVsRest, accuracy, cross_validate

    x, y = load_iris()
    start = time.perf_counter()
    score = cross_validate(
        OneVsRest(make_ep_gpc), x, y, num_folds=10, metric=accuracy, seed=13
    )
    return {
        "accuracy_10fold": float(score),
        "bar": 0.9,
        "passed": bool(score > 0.9),
        "seconds": time.perf_counter() - start,
    }


def part_poisson() -> dict:
    """Count-regression quality: mean relative rate-recovery error on a
    seeded synthetic Poisson problem (rate = exp(1 + sin 2x), n = 2000),
    plus a Negative Binomial sub-fit on gamma-Poisson (overdispersed)
    counts from the same latent rate — both MEASURED bars gate this
    part's ``passed`` flag (the nested ``neg_binomial.passed`` attributes
    a failure to the right estimator); an NB exception is recorded as
    ``neg_binomial.error`` without gating, per the harness policy that
    errors are not quality regressions."""
    _assert_platform()
    import numpy as np

    from spark_gp_tpu import GaussianProcessPoissonRegression, RBFKernel

    rng = np.random.default_rng(42)
    n = 2000
    x = np.linspace(0, 4, n)[:, None]
    rate = np.exp(1.0 + np.sin(2 * x[:, 0]))
    y = rng.poisson(rate).astype(np.float64)
    start = time.perf_counter()
    model = (
        GaussianProcessPoissonRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setActiveSetSize(100)
        .setMaxIter(25)
        .fit(x, y)
    )
    fit_seconds = time.perf_counter() - start
    rel = float(np.mean(np.abs(model.predict_rate(x) - rate) / rate))

    # Negative Binomial sibling on genuinely overdispersed (gamma-Poisson)
    # counts from the same latent rate — records the second generic-
    # likelihood family with its own bar.
    r_disp = 2.0
    nb_bar = 0.15
    # Own failure fence (import included: an import-time NB break must not
    # abort the part either): an exception in the NB path records an error
    # entry and — per the harness policy that errored parts are recorded
    # but do not flip the exit code — leaves gating to the Poisson bar,
    # which stays enforced.  Only a MEASURED NB bar miss fails the part.
    try:
        from spark_gp_tpu import GaussianProcessNegativeBinomialRegression

        lam = rate * rng.gamma(shape=r_disp, scale=1.0 / r_disp, size=n)
        y_nb = rng.poisson(lam).astype(np.float64)
        nb_start = time.perf_counter()
        nb_model = (
            GaussianProcessNegativeBinomialRegression(dispersion=r_disp)
            .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
            .setActiveSetSize(100)
            .setMaxIter(25)
            .fit(x, y_nb)
        )
        nb_seconds = time.perf_counter() - nb_start
        nb_rel = float(np.mean(np.abs(nb_model.predict_rate(x) - rate) / rate))
        nb_detail = {
            "dispersion": r_disp,
            "mean_relative_rate_error": nb_rel,
            # looser bar: the data carry mean + mean^2/2 variance, ~3x the
            # Poisson part's noise at these rates
            "bar": nb_bar,
            "passed": bool(nb_rel < nb_bar),
            "fit_seconds": nb_seconds,
        }
        nb_ok = bool(nb_rel < nb_bar)
    except Exception as exc:  # noqa: BLE001 — keep the Poisson gate alive
        nb_detail = {"error": f"{type(exc).__name__}: {exc}"[:300]}
        nb_ok = True  # error recorded, not gated (harness policy)

    return {
        "mean_relative_rate_error": rel,
        # examples/poisson.py asserts the same bar; r03 recorded 0.024
        "bar": 0.1,
        "passed": bool(rel < 0.1 and nb_ok),
        "n": n,
        "fit_seconds": fit_seconds,
        "train_points_per_sec": n / fit_seconds,
        "neg_binomial": nb_detail,
    }


def part_gpc_mnist() -> dict:
    _assert_platform()
    import numpy as np

    from spark_gp_tpu import GaussianProcessClassifier, RBFKernel
    from spark_gp_tpu.data import load_mnist_binary
    from spark_gp_tpu.ops.scaling import scale
    from spark_gp_tpu.utils.validation import accuracy, train_validation_split

    from spark_gp_tpu.data import dataset_provenance, find_dataset_file
    from spark_gp_tpu.data.datasets import MNIST_STANDIN_BAYES_ACCURACY

    is_real = find_dataset_file("mnist") is not None
    x, y = load_mnist_binary()  # real CSV when discoverable, else stand-in
    x = np.asarray(scale(x))
    gp = (
        GaussianProcessClassifier()
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(100)
        .setKernel(lambda: RBFKernel(10.0))
        .setTol(1e-3)
    )
    start = time.perf_counter()
    score = train_validation_split(
        gp, x, y, train_ratio=0.8, metric=accuracy, seed=13
    )
    seconds = time.perf_counter() - start
    n_train = int(0.8 * x.shape[0])
    # Falsifiable stand-in bar (VERDICT next #5): the stand-in now plants
    # a CALIBRATED class overlap (Bayes accuracy 0.970 — datasets.py);
    # the healthy 784-d Laplace path lands ~0.87 against that ceiling
    # (this round's calibration), so a bar at 0.84 trips any accuracy
    # regression beyond ~3 points.  The old separable stand-in recorded
    # 1.0 — its 0.95 bar could only catch total breakage.  Real CSVs
    # keep a loose catastrophe guard (no published reference number).
    if is_real:
        bar, bar_source = 0.9, "real-data catastrophe guard"
    else:
        bar, bar_source = 0.84, (
            f"planted Bayes accuracy {MNIST_STANDIN_BAYES_ACCURACY} - "
            "healthy-path margin (calibrated 0.8725 this round)"
        )
    return {
        "accuracy": float(score),
        "bar": bar,
        "bar_source": bar_source,
        "standin_bayes_accuracy": (
            None if is_real else MNIST_STANDIN_BAYES_ACCURACY
        ),
        "passed": bool(score > bar),
        "n_points": int(x.shape[0]),
        "n_features": int(x.shape[1]),
        "fit_predict_seconds": seconds,
        "train_points_per_sec": n_train / seconds,
        "data": dataset_provenance("mnist"),
    }


def _prep_regression(loader, n):
    """Shared load/split/standardize prep for the regression parts.

    Returns ``(x, ys, tr, te, y_mean, y_std)`` with features z-scored and
    targets standardized using training-split statistics only."""
    import numpy as np

    from spark_gp_tpu.ops.scaling import fit_scaler

    x, y = loader(None, n=n)
    rng = np.random.default_rng(13)
    perm = rng.permutation(x.shape[0])
    cut = int(0.8 * x.shape[0])
    tr, te = perm[:cut], perm[cut:]
    mean, std = (np.asarray(s) for s in fit_scaler(x[tr]))
    x = (x - mean) / std
    y_mean, y_std = y[tr].mean(), y[tr].std()
    ys = (y - y_mean) / y_std
    return x, ys, tr, te, y_mean, y_std


def _ard_kernel_factory(p: int):
    """The stress configs' kernel: dimension-aware ARD init + trained noise."""
    from spark_gp_tpu import ARDRBFKernel, WhiteNoiseKernel

    return lambda: (
        1.0 * ARDRBFKernel(p, p ** -0.5) + WhiteNoiseKernel(0.1, 0.0, 1.0)
    )


def _stress_regression(
    loader, n, expert, active, max_iter, structural_budget, dataset,
    real_bar=0.9,
) -> dict:
    _assert_platform()
    import math

    from spark_gp_tpu import GaussianProcessRegression
    from spark_gp_tpu.data import dataset_provenance, find_dataset_file
    from spark_gp_tpu.data.datasets import standin_noise_floor
    from spark_gp_tpu.utils.validation import rmse

    # real-data snap-in (VERDICT r4 #5): the loader auto-discovers a real
    # CSV under $GP_DATA_DIR; the part records which source it used and
    # switches to the real-data bar (the stand-in bars are stated against
    # the generator's planted signal-to-noise ratio and don't transfer)
    is_real = find_dataset_file(dataset) is not None
    noise_floor = None
    if is_real:
        bar, bar_source = real_bar, (
            "real-data catastrophe guard (scaled RMSE; no published "
            "reference number exists for this config — BASELINE.json "
            "records configs only)"
        )
    else:
        # Falsifiable stand-in bar (VERDICT next #5): stated against the
        # PLANTED signal-to-noise ratio rather than a free constant.  The
        # scaled-RMSE floor is the generator's own noise
        # (datasets.standin_noise_floor); the structural budget is the
        # healthy fit's model error at this config plus 10% headroom
        # (calibrated this round: protein 0.4763 total -> 0.457
        # structural; year_msd 0.4962 -> 0.468).  bar^2 = budget^2 +
        # floor^2, so a regression in the PPA/ARD fit path — which can
        # only grow the structural term — trips the bar, while the old
        # flat 0.55 left ~15% of silent headroom.
        noise_floor = standin_noise_floor(dataset)
        bar = math.hypot(structural_budget, noise_floor)
        bar_source = (
            "planted SNR: sqrt(structural_budget^2 + noise_floor^2) = "
            f"sqrt({structural_budget}^2 + {noise_floor:.4f}^2)"
        )

    x, ys, tr, te, y_mean, y_std = _prep_regression(loader, n)

    gp = (
        GaussianProcessRegression()
        .setKernel(_ard_kernel_factory(x.shape[1]))
        .setDatasetSizeForExpert(expert)
        .setActiveSetSize(active)
        .setMaxIter(max_iter)
        .setSeed(13)
    )
    start = time.perf_counter()
    model = gp.fit(x[tr], ys[tr])
    fit_seconds = time.perf_counter() - start
    pred_scaled = model.predict(x[te])
    y_te = ys[te] * y_std + y_mean
    score = float(rmse(ys[te], pred_scaled))
    return {
        "rmse": float(rmse(y_te, pred_scaled * y_std + y_mean)),
        "rmse_scaled": score,
        # stand-in bars are derived from the planted SNR (above); real
        # data swaps in the catastrophe guard
        "bar": round(bar, 4),
        "bar_source": bar_source,
        "noise_floor": (
            None if noise_floor is None else round(noise_floor, 4)
        ),
        "passed": bool(score < bar),
        "n": int(x.shape[0]),
        "p": int(x.shape[1]),
        "expert": expert,
        "active": active,
        "max_iter": max_iter,
        "fit_seconds": fit_seconds,
        "train_points_per_sec": len(tr) / fit_seconds,
        "data": dataset_provenance(dataset),
    }


def part_protein() -> dict:
    from spark_gp_tpu.data import load_protein

    n = int(os.environ.get("QUALITY_PROTEIN_N", 8000))
    return _stress_regression(
        # structural budget 0.502 = healthy 0.457 structural error x 1.10
        # (bar lands ~0.52; the flat 0.55 had silent headroom)
        load_protein, n, 100, 256, 15, structural_budget=0.502,
        dataset="protein",
        # sparse-GP literature lands ~0.6-0.75 scaled RMSE on CASP at
        # comparable m; 0.9 only catches a broken fit, not a mediocre one
        real_bar=0.9,
    )


def part_year_msd() -> dict:
    from spark_gp_tpu.data import load_year_msd

    n = int(os.environ.get("QUALITY_YEAR_N", 20000))
    return _stress_regression(
        # structural budget 0.515 = healthy 0.468 structural error x 1.10
        load_year_msd, n, 100, 256, 15, structural_budget=0.515,
        dataset="year_msd",
        real_bar=0.95,  # year prediction: scaled RMSE ~0.85-0.95 is typical
    )


def part_greedy_scale() -> dict:
    """Greedy Seeger selection at the Year-MSD shape (90-d, subsampled N,
    m = 512): wall-clock + fit quality vs random selection at the same m —
    the provider the reference caps at toy sizes running at scale."""
    _assert_platform()
    from spark_gp_tpu import (
        GaussianProcessRegression,
        GreedilyOptimizingActiveSetProvider,
        RandomActiveSetProvider,
    )
    from spark_gp_tpu.data import load_year_msd
    from spark_gp_tpu.utils.validation import rmse

    n = int(os.environ.get("QUALITY_GREEDY_N", 50000))
    m = int(os.environ.get("QUALITY_GREEDY_M", 512))
    x, ys, tr, te, _, _ = _prep_regression(load_year_msd, n)

    def make_gp(provider, max_iter):
        return (
            GaussianProcessRegression()
            .setKernel(_ard_kernel_factory(x.shape[1]))
            .setDatasetSizeForExpert(100)
            .setActiveSetSize(m)
            .setActiveSetProvider(provider)
            .setMaxIter(max_iter)
            .setSeed(13)
        )

    # Warm the jit cache OUTSIDE the timed window — including the greedy
    # selection kernel itself (its m-round fori_loop is a substantial
    # compile): both providers' timed fits then measure steady-state cost
    # only, so neither side is charged one-time compilation.
    make_gp(RandomActiveSetProvider, 1).fit(x[tr], ys[tr])
    make_gp(GreedilyOptimizingActiveSetProvider(), 1).fit(x[tr], ys[tr])

    out = {"n": int(x.shape[0]), "p": int(x.shape[1]), "m": m}
    for name, provider in (
        ("greedy", GreedilyOptimizingActiveSetProvider()),
        ("random", RandomActiveSetProvider),
    ):
        gp = make_gp(provider, 12)
        start = time.perf_counter()
        model = gp.fit(x[tr], ys[tr])
        seconds = time.perf_counter() - start
        out[name] = {
            "fit_seconds": seconds,
            "active_set_seconds": model.instr.timings.get("active_set"),
            "rmse_scaled": float(rmse(ys[te], model.predict(x[te]))),
        }
    return out


def part_greedy_vs_random() -> dict:
    """The regime where Seeger selection PAYS, with an asserted gap
    (VERDICT r3 item 4): density-skewed data, small m.  95% of the points
    crowd into 2.5% of the input range, so m=24 random picks land ~23:1 in
    the crowd and leave the tail unmodelled, while the information-gain
    criterion spreads the set (measured: greedy ~0.011 vs random ~0.15,
    stable across data seeds).  Asserted: greedy beats the BEST of three
    random seeds.  The flip side — on airfoil at m in {16, 32, 64} greedy
    is 3-8x WORSE than random (info gain chases boundary/outlier points) —
    is recorded in PARITY.md; the reference's own default is random
    (GaussianProcessParams.scala:33)."""
    _assert_platform()
    import numpy as np

    from spark_gp_tpu import (
        GaussianProcessRegression,
        GreedilyOptimizingActiveSetProvider,
        RandomActiveSetProvider,
        RBFKernel,
    )
    from spark_gp_tpu.utils.validation import rmse

    rng = np.random.default_rng(7)
    n = 2000
    x = np.concatenate(
        [rng.uniform(0.0, 0.5, size=int(0.95 * n)),
         rng.uniform(0.5, 20.0, size=n - int(0.95 * n))]
    )[:, None]
    y = np.sin(1.5 * x[:, 0]) + 0.01 * rng.normal(size=n)
    m = 24

    def fit_rmse(provider, seed):
        gp = (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(0.3, 1e-6, 10))
            .setActiveSetSize(m)
            .setActiveSetProvider(provider)
            .setMaxIter(30)
            .setSeed(seed)
        )
        start = time.perf_counter()
        model = gp.fit(x, y)
        return float(rmse(y, model.predict(x))), time.perf_counter() - start

    greedy_rmse, greedy_seconds = fit_rmse(
        GreedilyOptimizingActiveSetProvider(), 13
    )
    randoms = [fit_rmse(RandomActiveSetProvider, s) for s in (13, 17, 29)]
    random_rmses = [r for r, _ in randoms]
    best_random = min(random_rmses)
    return {
        "n": n,
        "m": m,
        "greedy_rmse": greedy_rmse,
        "greedy_seconds": greedy_seconds,
        "random_rmses": random_rmses,
        "best_random_rmse": best_random,
        "gap_vs_best_random": best_random - greedy_rmse,
        # two asserted bars: greedy strictly beats the best random draw,
        # and covers the sparse tail in absolute terms
        "passed": bool(greedy_rmse < best_random and greedy_rmse < 0.05),
        "regime": (
            "density-skewed 1-d (95% of mass in 2.5% of the range), m=24; "
            "greedy LOSES on airfoil at small m — see PARITY.md"
        ),
    }


def part_loo() -> dict:
    """LOO diagnostics vs reality (models/loo.py, R&W §5.4.2).

    The whole point of the one-factorization LOO summary is predicting
    generalization without refits — so assert it does: on the synthetics
    config, ``loo_rmse`` at the fitted hyperparameters must land within a
    factor-2 band of the true 10-fold CV RMSE (which refits per fold) and
    clear the example's 0.11 bar itself."""
    _assert_platform()
    from examples.synthetics import make_gp  # single source of the config

    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import cross_validate, rmse

    x, y = make_synthetics()
    mk = make_gp

    start = time.perf_counter()
    gp = mk()
    model = gp.fit(x, y)
    diag = gp.loo(x, y, model)
    cv_rmse = float(cross_validate(mk(), x, y, num_folds=10, metric=rmse, seed=13))
    ratio = diag["loo_rmse"] / cv_rmse
    return {
        "loo_rmse": diag["loo_rmse"],
        "cv_rmse_10fold": cv_rmse,
        "ratio": float(ratio),
        "loo_log_pseudo_likelihood": diag["loo_log_pseudo_likelihood"],
        "ratio_band": [0.5, 2.0],
        "bar": 0.11,
        "passed": bool(0.5 < ratio < 2.0 and diag["loo_rmse"] < 0.11),
        "seconds": time.perf_counter() - start,
    }


def part_objectives() -> dict:
    """The three training objectives head-to-head (marginal NLL / LOO
    pseudo-likelihood / Titsias ELBO) at the same config on held-out
    synthetics: RMSE + NLPD (the proper scoring rule) per objective.
    Quality bar: every objective must clear the synthetics example's
    0.11 RMSE — an objective that breaks the model fails the part."""
    _assert_platform()
    import numpy as np

    from examples.synthetics import make_gp as mk  # single config source

    from spark_gp_tpu.data import make_synthetics
    from spark_gp_tpu.utils.validation import nlpd, rmse

    x, y = make_synthetics()
    perm = np.random.default_rng(5).permutation(len(y))
    tr, te = perm[:1500], perm[1500:]

    start = time.perf_counter()
    out, bar, passed = {}, 0.11, True
    for objective in ("marginal", "loo", "elbo"):
        model = mk(objective).fit(x[tr], y[tr])
        mean, var = model.predict_with_var(x[te])
        r = float(rmse(y[te], mean))
        out[objective] = {
            "rmse": r,
            "nlpd": float(nlpd(y[te], mean, var)),
            "final_objective": float(model.instr.metrics["final_nll"]),
        }
        passed = passed and r < bar
    return {
        **out,
        "bar": bar,
        "passed": bool(passed),
        "seconds": time.perf_counter() - start,
    }


def _policy_scores(gp, model, x_tr, ys_tr, x_te, ys_te, modes):
    """Held-out NLPD / 90% coverage / scaled RMSE per aggregation policy
    at the SAME fitted hyperparameters (only the predict-time combination
    differs — the comparison isolates the aggregation plane)."""
    import numpy as np

    out = {}
    for mode in modes:
        pred = gp.poe_predictor(x_tr, ys_tr, model=model, mode=mode)
        mu, var = pred.predict_with_var(x_te)
        var = np.maximum(np.asarray(var, np.float64), 1e-12)
        err = np.asarray(ys_te, np.float64) - np.asarray(mu, np.float64)
        out[mode] = {
            "nlpd": float(
                np.mean(0.5 * np.log(2 * np.pi * var) + err ** 2 / (2 * var))
            ),
            # 1.6449 = z_{0.95}: central 90% interval of the predictive
            # Gaussian; empirical coverage should sit near 0.90
            "coverage90": float(np.mean(np.abs(err) <= 1.6449 * np.sqrt(var))),
            "rmse_scaled": float(np.sqrt(np.mean(err ** 2))),
        }
    return out


def part_aggregation() -> dict:
    """Expert-aggregation policies on the stand-ins built to separate
    them (data/datasets.py: make_clustered, make_heteroscedastic).

    Clustered at E = 64 (each expert pinned to one of 8 disjoint
    clusters): far from its cluster every expert reverts to the prior,
    and plain PoE multiplies 64 near-prior precisions into overconfident
    variance, while the healed product (Healing PoGPs, arXiv 2102.07106)
    normalizes the entropy weights and stays calibrated.  rBCM's
    UNnormalized beta is recorded as the contrast — its informed-expert
    precision inflates by beta > 1, the exact defect the healed
    normalization removes.  Calibrated bars: healed must beat PoE on
    held-out NLPD, land 90% coverage inside [0.84, 0.97], and keep
    scaled RMSE under the planted-SNR bar (structural budget x 1.10
    composed with clustered_noise_floor, the _stress_regression
    pattern); PoE's overconfidence must be DEMONSTRATED (coverage below
    0.80 — if PoE ever lands calibrated here the stand-in stopped
    separating the policies and the bars need recalibration).  The
    heteroscedastic ramp re-checks the coverage band where noise is
    input-dependent: a stationary GP is honest only on AVERAGE, and the
    healed average-coverage bar is stated against the planted
    LOW -> HIGH sigma profile."""
    _assert_platform()
    import math

    import numpy as np

    from spark_gp_tpu import (
        ARDRBFKernel, GaussianProcessRegression, WhiteNoiseKernel,
    )
    from spark_gp_tpu.data.datasets import (
        clustered_noise_floor, make_clustered, make_heteroscedastic,
    )

    def make_gp(p: int, ls: float):
        return (
            GaussianProcessRegression()
            .setKernel(
                lambda: 1.0 * ARDRBFKernel(p, ls)
                + WhiteNoiseKernel(0.1, 0.0, 1.0)
            )
            .setDatasetSizeForExpert(64)
            .setActiveSetSize(256)
            .setMaxIter(15)
            .setSeed(13)
        )

    start = time.perf_counter()

    # --- clustered, E = 64: the disjoint-expert regime ---
    n_tr, n_te = 4096, 1024
    x, y = make_clustered(n_tr + n_te)  # row i in cluster i % 8; the
    # head/tail split keeps BOTH splits cycling through all clusters
    # (4096 % 8 == 0) and preserves the expert-per-cluster pinning
    x_tr, x_te = x[:n_tr], x[n_tr:]
    y_mean, y_std = y[:n_tr].mean(), y[:n_tr].std()
    ys = (y - y_mean) / y_std
    gp = make_gp(x.shape[1], 0.7)
    model = gp.fit(x_tr, ys[:n_tr])
    clustered = _policy_scores(
        gp, model, x_tr, ys[:n_tr], x_te, ys[n_tr:],
        ("poe", "gpoe", "rbcm", "healed"),
    )

    # planted-SNR RMSE bar, same derivation as _stress_regression:
    # healthy healed structural error 0.0553 x 1.10, composed with the
    # generator's own noise floor
    floor = clustered_noise_floor()
    rmse_bar = math.hypot(0.0609, floor)

    # --- heteroscedastic ramp: average-coverage honesty ---
    xh, yh, _sigma = make_heteroscedastic(3072)
    te = np.zeros(len(yh), bool)
    te[::3] = True  # every 3rd point out: both ends of the ramp held out
    xh_tr, yh_tr = xh[~te], yh[~te]
    h_mean, h_std = yh_tr.mean(), yh_tr.std()
    gph = make_gp(1, 0.5)
    modelh = gph.fit(xh_tr, (yh_tr - h_mean) / h_std)
    hetero = _policy_scores(
        gph, modelh, xh_tr, (yh_tr - h_mean) / h_std,
        xh[te], (yh[te] - h_mean) / h_std, ("poe", "healed"),
    )

    cov_band = [0.84, 0.97]
    passed = (
        clustered["healed"]["nlpd"] < clustered["poe"]["nlpd"]
        and cov_band[0] <= clustered["healed"]["coverage90"] <= cov_band[1]
        and clustered["poe"]["coverage90"] < 0.80
        and clustered["healed"]["rmse_scaled"] < rmse_bar
        and hetero["healed"]["nlpd"] < hetero["poe"]["nlpd"]
        and cov_band[0] <= hetero["healed"]["coverage90"] <= cov_band[1]
    )
    return {
        "clustered": clustered,
        "heteroscedastic": hetero,
        "num_experts": n_tr // 64,
        "rmse_bar": round(rmse_bar, 4),
        "rmse_bar_source": (
            "planted SNR: sqrt(0.0609^2 + clustered_noise_floor^2) = "
            f"sqrt(0.0609^2 + {floor:.4f}^2)"
        ),
        "coverage_band": cov_band,
        "passed": bool(passed),
        "seconds": time.perf_counter() - start,
    }


def part_spectral_mixture() -> dict:
    """Pattern extrapolation (Wilson & Adams '13, the SM kernel's raison
    d'etre): train on three periods of a two-frequency signal, predict a
    full period PAST the data.  The SM kernel with batched device
    multi-start recovers the spectral peaks and extrapolates to the noise
    floor (asserted); the RBF kernel — the best the reference could field
    — reverts to the prior mean (recorded as the contrast).  Also the
    demonstrated payoff of the one-dispatch vmapped multi-start: restart 0
    alone lands in a local optimum at ~0.79 RMSE."""
    _assert_platform()
    import numpy as np

    from examples.timeseries import make_data, make_gp  # single source

    from spark_gp_tpu.utils.validation import rmse

    xs, ys, xe, ye = make_data()

    start = time.perf_counter()
    sm = make_gp("sm", 8).fit(xs, ys)
    sm_rmse = float(rmse(ye, sm.predict(xe)))
    rbf = make_gp("rbf", 8).fit(xs, ys)
    rbf_rmse = float(rmse(ye, rbf.predict(xe)))
    return {
        "sm_extrapolation_rmse": sm_rmse,
        "rbf_extrapolation_rmse": rbf_rmse,
        "signal_std": float(np.std(ye)),
        "noise_std": 0.03,
        "bar": 0.1,
        "passed": bool(sm_rmse < 0.1),
        "seconds": time.perf_counter() - start,
    }


def part_weak_scaling() -> dict:
    """Per-device-load-constant scaling over 1/2/4/8 virtual devices; each
    point is a fresh subprocess so the forced device count applies."""
    results = []
    for d in (1, 2, 4, 8):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={d}"
        )
        env["QUALITY_SCALE_DEVICES"] = str(d)
        timeout = float(os.environ.get("QUALITY_PART_TIMEOUT", 900))
        out, err = _run_sub(["--scale-point"], timeout, env)
        results.append(out if out is not None else {"devices": d, "error": err})
    return {"points": results}


def scale_point() -> None:
    """One weak-scaling measurement (subprocess body)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.data import make_benchmark_data
    from spark_gp_tpu.parallel.mesh import expert_mesh

    d = int(os.environ["QUALITY_SCALE_DEVICES"])
    assert len(jax.devices()) == d
    n = 6400 * d  # constant per-device load
    x, y = make_benchmark_data(n)
    mesh = expert_mesh()

    def fit(iters):
        return (
            GaussianProcessRegression()
            .setKernel(lambda: RBFKernel(0.1))
            .setDatasetSizeForExpert(100)
            .setActiveSetSize(100)
            .setSigma2(1e-3)
            .setMaxIter(iters)
            .setOptimizer("device")
            .setMesh(mesh)
            .fit(x, y)
        )

    fit(1)  # compile warm-up (shared executable: max_iter is traced)
    start = time.perf_counter()
    model = fit(15)
    seconds = time.perf_counter() - start
    print(json.dumps({
        "devices": d,
        "n_points": n,
        "fit_seconds": seconds,
        "points_per_sec": n / seconds,
        "lbfgs_evals": int(model.instr.metrics.get("lbfgs_nfev", -1)),
    }))


def part_pallas_sweep() -> dict:
    _assert_platform()
    import jax

    if jax.default_backend() != "tpu":
        return {
            "skipped": f"backend={jax.default_backend()}; the fused-kernel "
            "sweep is only meaningful on real TPU hardware"
        }
    from spark_gp_tpu.utils.subproc import run_captured

    proc = run_captured(
        [sys.executable, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                      "benchmarks", "pallas_sweep.py")],
        1800,
    )
    rows = []
    for line in proc.stdout.strip().splitlines():
        try:
            rows.append(json.loads(line))
        except ValueError:
            pass
    out = {"rows": rows} if rows else {"error": proc.stderr[-300:]}
    if proc.timed_out:
        # partial rows must never read as a complete sweep
        out["truncated"] = "sweep timed out after 1800s"
    return out


# ---------------------------------------------------------- supervisor ----

def _run_sub(args, timeout_s, env):
    # run_captured (group kill + fenced drain): a wedged tunnel helper
    # holding this part-worker's pipes must not hang the supervisor past
    # its own per-part timeout (utils/subproc.py rationale)
    from spark_gp_tpu.utils.subproc import run_captured

    me = os.path.abspath(__file__)
    out = run_captured([sys.executable, me] + args, timeout_s, env=env)
    if out.timed_out:
        return None, f"timed out after {timeout_s:.0f}s"
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):
            return parsed, None
    tail = (out.stderr or out.stdout).strip().splitlines()
    return None, (tail[-1][-300:] if tail else f"rc={out.returncode}")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", type=str, default=None)
    parser.add_argument("--parts", type=str, default=",".join(_ALL_PARTS))
    parser.add_argument("--part", type=str, default=None,
                        help="(internal) run one part inline")
    parser.add_argument("--scale-point", action="store_true",
                        help="(internal) one weak-scaling measurement")
    args = parser.parse_args()

    if args.scale_point:
        scale_point()
        return 0
    if args.part:
        print(json.dumps(globals()[f"part_{args.part}"]()))
        return 0

    import platform as _platform

    report = {
        "host": _platform.node(),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "parts": {},
    }
    # Backend probe in a subprocess (never in-process: the TPU tunnel can
    # hang inside a C call during init — bench.py's supervisor rationale).
    try:
        from spark_gp_tpu.utils.subproc import run_captured

        probe = run_captured(
            [sys.executable, "-c", _PREFLIGHT_CODE], 120,
            env=dict(os.environ),
        )
        if probe.timed_out:
            report["backend"] = "unavailable: probe hung past 120s"
        else:
            report.update(json.loads(probe.stdout.strip().splitlines()[-1]))
    except Exception as exc:
        report["backend"] = f"unavailable: {type(exc).__name__}"

    for part in args.parts.split(","):
        part = part.strip()
        if not part:
            continue
        timeout = float(os.environ.get("QUALITY_PART_TIMEOUT", 2400))
        if part == "weak_scaling":
            # runs its own subprocesses
            try:
                report["parts"][part] = part_weak_scaling()
            except Exception as exc:
                report["parts"][part] = {"error": str(exc)[:300]}
            continue
        out, err = _run_sub(["--part", part], timeout, dict(os.environ))
        report["parts"][part] = out if out is not None else {"error": err}

    # Enforced bars: any part that ran and failed its threshold fails the
    # whole run (VERDICT r3 weak #4 — silent quality regressions must not
    # sail through).  Parts that errored/timed out are recorded but do not
    # flip the exit code (a flaky tunnel is not a quality regression).
    failed = sorted(
        name
        for name, part in report["parts"].items()
        if isinstance(part, dict) and part.get("passed") is False
    )
    report["failed_bars"] = failed

    text = json.dumps(report, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
