"""Grid-searched cross-validation — the CrossValidator + ParamGridBuilder
pairing of classification/examples/Iris.scala:29-33, exercised with a
NON-empty grid (the reference wires the builder but leaves it empty).

Searches sigma2 x active-set-size on the Synthetics.scala problem: the
well-specified noise level must win every time, and the refitted best
model must clear the example's own RMSE bar.

Run: python examples/grid_search.py [--folds 5]
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from spark_gp_tpu.utils.platform import preflight_backend

import argparse

from spark_gp_tpu.data import make_synthetics
from spark_gp_tpu.utils.validation import ParamGridBuilder, cross_validate, rmse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--folds", type=int, default=5)
    args = parser.parse_args()

    preflight_backend()

    from examples.synthetics import make_gp

    x, y = make_synthetics()
    # sigma2=25 drowns the unit-amplitude sin() in assumed noise (the
    # trainable WhiteNoise term can compensate mild misspecification, so
    # the bad cell must be decisively bad for a deterministic winner)
    grid = (
        ParamGridBuilder()
        .addGrid("setSigma2", [1e-3, 25.0])  # true noise var is 0.01
        .addGrid("setActiveSetSize", [50, 100])
        .build()
    )
    res = cross_validate(
        make_gp(), x, y, num_folds=args.folds, metric=rmse, seed=13,
        param_grid=grid,
    )
    for params, score in res.scores:
        print(f"  {params} -> RMSE {score:.4f}")
    print(f"best: {res.best_params} (RMSE {res.best_score:.4f})")

    assert res.best_params["setSigma2"] == 1e-3, res.best_params
    assert res.best_score < 0.11, res.best_score
    # the refitted best model predicts on new queries
    pred = res.best_model.predict(x[:200])
    holdout = rmse(y[:200], pred)
    print(f"refit train-slice RMSE: {holdout:.4f}")
    assert holdout < 0.11
    print("OK")


if __name__ == "__main__":
    main()
