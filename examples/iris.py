"""Iris example — classification/examples/Iris.scala:10-36.

3-class iris via one-vs-rest over the binary GP classifier (the
reference's exact setup); expert 20, active 30; prints 10-fold CV
accuracy.  ``--native`` switches to the native multiclass softmax-Laplace
estimator instead — one coupled model per fold rather than 3 binary fits
(capability beyond the reference).  ``--ep`` keeps the one-vs-rest route
but swaps the inference engine to Expectation Propagation (probit link,
moment matching — better-calibrated probabilities than Laplace).

Run: python examples/iris.py [--folds 10] [--native | --ep]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend

import argparse

import numpy as np

from spark_gp_tpu import GaussianProcessClassifier
from spark_gp_tpu.data import load_iris
from spark_gp_tpu.utils.validation import OneVsRest, accuracy, kfold_indices


def make_gpc():
    """The reference's iris configuration (Iris.scala:26): expert 20, active 30.

    Single definition shared with quality.py's recorded artifact so the
    measured model can never drift from the documented example.
    """
    return GaussianProcessClassifier().setDatasetSizeForExpert(20).setActiveSetSize(30)


def make_native_gpc():
    """Native multiclass variant at the same expert/active configuration."""
    from spark_gp_tpu import GaussianProcessMulticlassClassifier

    return (
        GaussianProcessMulticlassClassifier()
        .setDatasetSizeForExpert(20)
        .setActiveSetSize(30)
    )


def make_ep_gpc():
    """EP (probit) engine at the same expert/active configuration."""
    from spark_gp_tpu import GaussianProcessEPClassifier

    return (
        GaussianProcessEPClassifier()
        .setDatasetSizeForExpert(20)
        .setActiveSetSize(30)
    )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--folds", type=int, default=10)
    engine = parser.add_mutually_exclusive_group()
    engine.add_argument(
        "--native", action="store_true",
        help="native multiclass softmax-Laplace instead of one-vs-rest",
    )
    engine.add_argument(
        "--ep", action="store_true",
        help="Expectation Propagation engine (probit) for the binary fits",
    )
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    x, y = load_iris()

    scores = []
    for train_idx, test_idx in kfold_indices(x.shape[0], args.folds, seed=13):
        if args.native:
            clf = make_native_gpc().fit(x[train_idx], y[train_idx])
        elif args.ep:
            clf = OneVsRest(make_ep_gpc).fit(x[train_idx], y[train_idx])
        else:
            clf = OneVsRest(make_gpc).fit(x[train_idx], y[train_idx])
        scores.append(accuracy(y[test_idx], clf.predict(x[test_idx])))
    print("Accuracy: " + str(float(np.mean(scores))))


if __name__ == "__main__":
    main()
