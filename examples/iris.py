"""Iris example — classification/examples/Iris.scala:10-36.

3-class iris via one-vs-rest over the binary GP classifier; expert 20,
active 30; prints 10-fold CV accuracy.

Run: python examples/iris.py [--folds 10]
"""

import argparse

import numpy as np

from spark_gp_tpu import GaussianProcessClassifier
from spark_gp_tpu.data import load_iris
from spark_gp_tpu.utils.validation import OneVsRest, accuracy, kfold_indices


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--folds", type=int, default=10)
    args = parser.parse_args()

    x, y = load_iris()

    def make_gpc():
        return GaussianProcessClassifier().setDatasetSizeForExpert(20).setActiveSetSize(30)

    scores = []
    for train_idx, test_idx in kfold_indices(x.shape[0], args.folds, seed=13):
        ovr = OneVsRest(make_gpc).fit(x[train_idx], y[train_idx])
        scores.append(accuracy(y[test_idx], ovr.predict(x[test_idx])))
    print("Accuracy: " + str(float(np.mean(scores))))


if __name__ == "__main__":
    main()
