"""MNIST example — classification/examples/MNIST.scala:13-46.

Binary 6-vs-8 GP classification on 784-d pixels: z-scored features,
RBF(10) kernel, tol 1e-3, 80/20 train/validation split, accuracy printed
(the reference prints without asserting, MNIST.scala:40).

The reference's ``data/mnist68.csv`` blob is absent upstream
(.MISSING_LARGE_BLOBS); pass ``--csv`` with a label-first MNIST CSV to
reproduce the original task, otherwise a deterministic synthetic 784-d
two-class problem of the same shape keeps the pipeline runnable.

Run: python examples/mnist.py [--csv path] [--expert 100] [--active 100]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend

import argparse

import numpy as np

from spark_gp_tpu import GaussianProcessClassifier, RBFKernel
from spark_gp_tpu.data import load_mnist_binary
from spark_gp_tpu.ops.scaling import scale
from spark_gp_tpu.utils.validation import accuracy, train_validation_split


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--csv", type=str, default=None,
                        help="label-first MNIST csv (MNIST.scala:22-26 format)")
    parser.add_argument("--expert", type=int, default=100)
    parser.add_argument("--active", type=int, default=100)
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    x, y = load_mnist_binary(args.csv)
    x = np.asarray(scale(x))  # MNIST.scala:22 scales features

    gp = (
        GaussianProcessClassifier()
        .setDatasetSizeForExpert(args.expert)
        .setActiveSetSize(args.active)
        .setKernel(lambda: RBFKernel(10.0))
        .setTol(1e-3)
    )

    score = train_validation_split(gp, x, y, train_ratio=0.8, metric=accuracy, seed=13)
    print("Accuracy: " + str(score))


if __name__ == "__main__":
    main()
