"""Serving round trip — fit, save, boot the serve CLI, score over it.

Demonstrates the online-inference subsystem end to end
(docs/SERVING.md): a model is fitted and saved as a versioned ``.npz``,
``python -m spark_gp_tpu.serve`` boots in a subprocess, warms every
(model, bucket) executable before reporting ready, and this client
streams a mixed-size batch of JSON-line requests through the
micro-batcher, checking the answers against in-process predictions.

Run: python examples/serve_client.py [--requests 40]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from spark_gp_tpu.utils.platform import preflight_backend

import argparse
import json
import subprocess
import tempfile

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=40)
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel (utils/platform.py)
    preflight_backend()

    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2000, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=2000)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.5))
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(100)
        .setSigma2(1e-3)
        .setSeed(13)
        .fit(x, y)
    )

    with tempfile.TemporaryDirectory() as tmp:
        path = _os.path.join(tmp, "model.npz")
        model.save(path)

        # mixed request sizes: the server pads each to its bucket, so the
        # whole mix runs on the executables warmed before "ready"
        sizes = [1, 3, 8, 20, 64][: max(1, args.requests)]
        while len(sizes) < args.requests:
            sizes.append(sizes[len(sizes) % 5])
        requests = []
        for i, t in enumerate(sizes):
            row = (i * 31) % (2000 - 64)
            requests.append(
                {"id": i, "model": "demo", "x": x[row : row + t].tolist()}
            )
        lines = (
            "\n".join(json.dumps(r) for r in requests)
            + "\n" + json.dumps({"cmd": "metrics"})
            + "\n" + json.dumps({"cmd": "shutdown"}) + "\n"
        )

        env = dict(_os.environ)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [_sys.executable, "-m", "spark_gp_tpu.serve",
             "--model", f"demo={path}", "--max-batch", "64"],
            input=lines, capture_output=True, text=True, timeout=600,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        events = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]

    ready = events[0]
    assert ready["event"] == "ready", ready
    print(f"ready on {ready['platform']}; "
          f"{ready['buckets_warmed']} buckets warmed at load")

    by_id = {e["id"]: e for e in events if "id" in e}
    worst = 0.0
    for req in requests:
        answer = by_id[req["id"]]
        assert "error" not in answer, answer
        local = model.predict(np.asarray(req["x"]))
        worst = max(worst, float(np.max(np.abs(np.asarray(answer["mean"]) - local))))
    # the CLI subprocess predicts in f32; in-process f64 — parity is approximate
    assert worst < 1e-3, worst
    print(f"{len(requests)} requests round-tripped; "
          f"max |serve - local| = {worst:.2e}")

    metrics = next(e for e in events if e.get("event") == "metrics")
    lat = metrics["histograms"]["request_latency_s"]
    occ = metrics["histograms"]["batch_occupancy"]
    print(f"latency p50 {lat['p50'] * 1e3:.2f} ms / p99 {lat['p99'] * 1e3:.2f} ms; "
          f"batches {metrics['counters']['batches']:.0f}; "
          f"occupancy p50 {occ['p50']:.2f}")
    print("OK")


if __name__ == "__main__":
    main()
