"""Serving round trip — fit, save, boot the serve CLI, score over it.

Demonstrates the online-inference subsystem end to end
(docs/SERVING.md): a model is fitted and saved as a versioned ``.npz``,
``python -m spark_gp_tpu.serve`` boots in a subprocess, warms every
(model, bucket) executable before reporting ready, and this client
streams a mixed-size batch of JSON-line requests through the
micro-batcher, checking the answers against in-process predictions.

Part two boots the same CLI in TCP mode and demonstrates the
**fleet-client pattern** (docs/SERVING.md "Fleet"): retry with bounded
exponential backoff + jitter on classified shed codes, one ``request_id``
per LOGICAL request reused verbatim on every resend, and answers
recorded BY request_id so a duplicated reply can never double-count.

Part three closes the **feedback loop** (docs/SERVING.md "The observe
verb"): once ground-truth labels for the predicted rows become known —
in production that is minutes to days later — the client sends them
back via ``{"cmd": "observe", "request_id": ..., "y": [...]}``.  The
server joins each label set to the (μ, σ) it served for that
request_id, grades the prediction into the model's streaming
calibration monitor (obs/quality.py), and the ``health`` verb then
carries the calibration snapshot (coverage, z-statistics, alert state).
Observations are idempotent per request_id — the same retry pattern as
predicts, with a duplicate join counted as a no-op.

Run: python examples/serve_client.py [--requests 40]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

from spark_gp_tpu.utils.platform import preflight_backend

import argparse
import json
import random
import socket
import subprocess
import tempfile
import time

import numpy as np

# Shed/transient codes a client should RETRY (with backoff) — the server
# is telling you "not now", not "never" (spark_gp_tpu/serve/codes.py has
# the full catalog; anything else is a client error: do NOT retry it).
RETRYABLE_CODES = {
    "queue.shed.backpressure",  # full queue: back off, the burst will pass
    "queue.shed.draining",      # replica shutting down: another will answer
    "queue.shed.memory",        # memory gate: retry when pressure recedes
    "shed.breaker",             # model breaker cooling: retry after reset
}


def send_with_retry(rf, wf, request, answers, attempts=4, backoff_s=0.05):
    """The fleet-client pattern, inline:

    1. the caller mints ONE ``request_id`` per logical request and this
       function reuses it VERBATIM on every resend — the server stamps
       it on its predict span (and any incident bundle), so all attempts
       of one logical request stitch into one server-side story;
    2. classified shed codes are retried with bounded exponential
       backoff + jitter (a fleet under failover sheds transiently; a
       retry stampede without jitter would re-converge on the same
       recovering replica).  Unclassified errors raise immediately —
       no replica answers a malformed request differently;
    3. answers land in ``answers`` KEYED BY request_id — an overwrite,
       never an append — so a duplicated/re-sent reply cannot
       double-count one logical request in the client's results.
    """
    request_id = request["request_id"]
    last = None
    for attempt in range(attempts):
        wf.write(json.dumps(request) + "\n")
        wf.flush()
        reply = json.loads(rf.readline())
        if reply.get("request_id") is not None:
            answers[reply["request_id"]] = reply  # keyed: idempotent
        if "error" not in reply:
            return reply
        last = reply
        if reply.get("code") not in RETRYABLE_CODES:
            raise RuntimeError(f"unretryable reply: {reply}")
        time.sleep(backoff_s * (2 ** attempt) * (1.0 + random.random()))
    raise RuntimeError(
        f"request {request_id} still shed after {attempts} attempts: {last}"
    )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=40)
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel (utils/platform.py)
    preflight_backend()

    from spark_gp_tpu import GaussianProcessRegression, RBFKernel

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2000, 3))
    y = np.sin(x.sum(axis=1)) + 0.05 * rng.normal(size=2000)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(0.5))
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(100)
        .setSigma2(1e-3)
        .setSeed(13)
        .fit(x, y)
    )

    with tempfile.TemporaryDirectory() as tmp:
        path = _os.path.join(tmp, "model.npz")
        model.save(path)

        # mixed request sizes: the server pads each to its bucket, so the
        # whole mix runs on the executables warmed before "ready"
        sizes = [1, 3, 8, 20, 64][: max(1, args.requests)]
        while len(sizes) < args.requests:
            sizes.append(sizes[len(sizes) % 5])
        requests = []
        for i, t in enumerate(sizes):
            row = (i * 31) % (2000 - 64)
            requests.append(
                {"id": i, "model": "demo", "x": x[row : row + t].tolist()}
            )
        lines = (
            "\n".join(json.dumps(r) for r in requests)
            + "\n" + json.dumps({"cmd": "metrics"})
            + "\n" + json.dumps({"cmd": "shutdown"}) + "\n"
        )

        env = dict(_os.environ)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [_sys.executable, "-m", "spark_gp_tpu.serve",
             "--model", f"demo={path}", "--max-batch", "64"],
            input=lines, capture_output=True, text=True, timeout=600,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr[-800:]
        events = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]

    ready = events[0]
    assert ready["event"] == "ready", ready
    print(f"ready on {ready['platform']}; "
          f"{ready['buckets_warmed']} buckets warmed at load")

    by_id = {e["id"]: e for e in events if "id" in e}
    worst = 0.0
    for req in requests:
        answer = by_id[req["id"]]
        assert "error" not in answer, answer
        local = model.predict(np.asarray(req["x"]))
        worst = max(worst, float(np.max(np.abs(np.asarray(answer["mean"]) - local))))
    # the CLI subprocess predicts in f32; in-process f64 — parity is approximate
    assert worst < 1e-3, worst
    print(f"{len(requests)} requests round-tripped; "
          f"max |serve - local| = {worst:.2e}")

    metrics = next(e for e in events if e.get("event") == "metrics")
    lat = metrics["histograms"]["request_latency_s"]
    occ = metrics["histograms"]["batch_occupancy"]
    print(f"latency p50 {lat['p50'] * 1e3:.2f} ms / p99 {lat['p99'] * 1e3:.2f} ms; "
          f"batches {metrics['counters']['batches']:.0f}; "
          f"occupancy p50 {occ['p50']:.2f}")

    # -- part two: the fleet-client pattern over TCP ----------------------
    with tempfile.TemporaryDirectory() as tmp:
        path = _os.path.join(tmp, "model.npz")
        model.save(path)
        env = dict(_os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [_sys.executable, "-m", "spark_gp_tpu.serve",
             "--model", f"demo={path}", "--max-batch", "64",
             "--port", "0", "--replica-id", "demo-r0"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env,
        )
        try:
            while True:  # wait for the TCP listener
                event = json.loads(proc.stdout.readline())
                if event.get("event") == "listening":
                    port = event["port"]
                    break
            conn = socket.create_connection(("127.0.0.1", port), timeout=60)
            rf, wf = conn.makefile("r"), conn.makefile("w")
            answers = {}
            logical = []
            for i in range(8):
                row = (i * 31) % (2000 - 8)
                req = {
                    "id": i,
                    "model": "demo",
                    "x": x[row : row + 4].tolist(),
                    # ONE id per logical request, reused on every resend
                    "request_id": f"req-{i}",
                }
                logical.append(req)
                send_with_retry(rf, wf, req, answers)
            # simulate a client-side timeout + resend of request 3: the
            # SAME request_id goes back on the wire...
            send_with_retry(rf, wf, logical[3], answers)
            # ...and the keyed bookkeeping counts it exactly once
            assert len(answers) == len(logical), (len(answers), len(logical))
            assert all(f"req-{i}" in answers for i in range(8))
            assert all("mean" in a for a in answers.values())

            # -- part three: the feedback loop ----------------------------
            # delayed ground-truth labels flow back via the observe verb,
            # keyed by the SAME request_id the predict used; the server
            # joins them to the (μ, σ) it served and grades calibration
            joined = 0
            for i, req in enumerate(logical):
                rows = np.asarray(req["x"]).shape[0]
                row = (i * 31) % (2000 - 8)
                wf.write(json.dumps({
                    "cmd": "observe",
                    "model": "demo",
                    "request_id": req["request_id"],
                    "y": y[row : row + rows].tolist(),
                }) + "\n")
                wf.flush()
                reply = json.loads(rf.readline())
                assert reply.get("event") == "observed", reply
                assert "error" not in reply, reply
                joined += reply["joined"]
            # re-observing request 3 is the idempotent duplicate: joined 0
            req3 = logical[3]
            row3 = (3 * 31) % (2000 - 8)
            wf.write(json.dumps({
                "cmd": "observe", "model": "demo",
                "request_id": req3["request_id"],
                "y": y[row3 : row3 + 4].tolist(),
            }) + "\n")
            wf.flush()
            dup = json.loads(rf.readline())
            assert dup.get("duplicate") is True and dup["joined"] == 0, dup
            # an unknown request_id fails with the classified wire code
            wf.write(json.dumps({
                "cmd": "observe", "model": "demo",
                "request_id": "never-served", "y": [0.0],
            }) + "\n")
            wf.flush()
            unknown = json.loads(rf.readline())
            assert unknown.get("code") == "observe.unknown_request", unknown
            # the calibration snapshot rides the health verb
            wf.write(json.dumps({"cmd": "health"}) + "\n")
            wf.flush()
            health = json.loads(rf.readline())
            calib = health["quality"]["models"]["demo"]["calibration"]
            assert calib["observations"] == joined, (calib, joined)
            print(
                f"feedback loop: {joined} labels joined; calibration "
                f"z_std={calib['z_std']:.2f} alert={calib['alert']} "
                f"(status {health['status']})"
            )

            wf.write(json.dumps({"cmd": "shutdown"}) + "\n")
            wf.flush()
            conn.close()
            proc.wait(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    print("fleet-client pattern: 8 logical requests, 9 sends, "
          f"{len(answers)} answers — no double count")
    print("OK")


if __name__ == "__main__":
    main()
