"""Multi-host (multi-process) distributed training demo.

Two modes:

* **Launcher** (default): spawns ``--procs`` local worker processes, each
  with one CPU device, joined by a Gloo coordination plane — a faithful
  single-machine rehearsal of a multi-host TPU pod (same code path:
  ``jax.distributed`` + global mesh + ``fit_distributed``).

      python examples/multihost.py --procs 2

* **Worker** (what each pod host runs in production).  On a real TPU pod,
  run this per host with your launcher of choice and OMIT ``--port``: the
  TPU VM runtime populates the environment, so ``dist.initialize()`` is
  called with no arguments and discovers the coordinator itself
  (``--port`` wires a 127.0.0.1 coordinator and is only for the local
  launcher mode above):

      python examples/multihost.py --worker

Each worker holds only its own shard of the rows — no process ever sees the
full dataset; the expert stack, likelihood collectives, active-set draw and
PPA statistics all run as mesh programs.
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import os
import subprocess
import sys


def worker(pid: int, nproc: int, port: int) -> None:
    import jax

    # re-assert the launcher's platform choice over site hooks that rewrite
    # JAX_PLATFORMS at import time (and NEVER probe the backend before this
    # line — a dead TPU tunnel hangs inside init); unset = production pod,
    # where the TPU runtime environment drives everything
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        jax.config.update("jax_platforms", p)
    import numpy as np

    from spark_gp_tpu import GaussianProcessRegression, RBFKernel
    from spark_gp_tpu.parallel import distributed as dist

    dist.initialize(
        coordinator_address=f"127.0.0.1:{port}" if port else None,
        num_processes=nproc if port else None,
        process_id=pid if port else None,
    )
    mesh = dist.global_expert_mesh()

    # This host's shard of the data (in production: its slice of the file
    # set — the HDFS-partition analogue, GaussianProcessCommons.scala:20-24)
    rng = np.random.default_rng(42 + pid)
    n_local = 2000
    x_local = rng.normal(size=(n_local, 3))
    y_local = np.sin(x_local.sum(axis=1)) + 0.05 * rng.normal(size=n_local)

    data = dist.distribute_global_experts(x_local, y_local, 100, mesh)
    model = (
        GaussianProcessRegression()
        .setKernel(lambda: RBFKernel(1.0))
        .setActiveSetSize(100)
        .setMaxIter(30)
        .setMesh(mesh)
        .fit_distributed(data)
    )
    rmse_local = float(
        np.sqrt(np.mean((model.predict(x_local) - y_local) ** 2))
    )
    print(
        f"[proc {pid}/{nproc}] devices={len(jax.devices())} "
        f"local_rmse={rmse_local:.4f}",
        flush=True,
    )
    assert rmse_local < 0.2


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--procs", type=int, default=2)
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--pid", type=int, default=0)
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args()

    if args.worker:
        worker(args.pid, args.procs, args.port)
        return

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--pid", str(pid), "--procs", str(args.procs),
             "--port", str(port)],
            env=env,
        )
        for pid in range(args.procs)
    ]
    # Poll loop: one crashed worker leaves its peers deadlocked in a
    # collective, so kill the survivors as soon as any worker fails (and
    # bound the whole demo at 600s); the finally also covers Ctrl-C or any
    # launcher exception — workers must never outlive the launcher.
    import time

    try:
        deadline = time.monotonic() + 600
        while any(p.poll() is None for p in procs):
            failed = any(
                rc not in (None, 0) for rc in (p.poll() for p in procs)
            )
            if failed or time.monotonic() > deadline:
                break
            time.sleep(0.2)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    rc = [p.poll() for p in procs]
    if any(rc):
        raise SystemExit(f"worker failures: {rc}")
    print(f"OK: {args.procs}-process distributed fit")


if __name__ == "__main__":
    main()
