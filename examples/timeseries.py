"""Pattern extrapolation with the spectral mixture kernel.

Trains on three periods of a two-frequency signal and predicts a FULL
PERIOD past the data — the task Wilson & Adams '13 built the SM kernel
for, and one the reference's RBF family cannot do (it reverts to the
prior mean outside the data; run with ``--rbf`` to see).  Multi-start
matters: the SM likelihood is multimodal in the frequencies, and the
batched device multi-start (all restarts in one vmapped dispatch) is
what finds the spectral peaks.

Run: python examples/timeseries.py [--restarts 8] [--rbf]
Asserts extrapolation RMSE < 0.1 on the SM path (noise floor 0.03).
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse

import numpy as np

from spark_gp_tpu import (
    GaussianProcessRegression,
    RBFKernel,
    SpectralMixtureKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.utils.validation import rmse

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend


def signal(x):
    """The two-frequency target — SINGLE source for this example and the
    quality part that guards it (quality.py spectral_mixture)."""
    return (
        np.cos(2 * np.pi * 1.0 * x[:, 0])
        + 0.5 * np.cos(2 * np.pi * 2.6 * x[:, 0])
    )


def make_data():
    """(x_train, y_train, x_extrap, y_extrap): three periods in, one out."""
    rng = np.random.default_rng(0)
    xs = np.linspace(0, 3, 240)[:, None]
    xe = np.linspace(3, 4, 60)[:, None]
    return xs, signal(xs) + 0.03 * rng.normal(size=240), xe, signal(xe)


def make_gp(kind: str = "sm", restarts: int = 8):
    """``kind``: "sm" (spectral mixture) or "rbf" (the failure mode)."""
    if kind == "rbf":
        kernel_factory = lambda: (
            1.0 * RBFKernel(1.0, 1e-3, 100) + WhiteNoiseKernel(0.05, 0, 1)
        )
    else:
        kernel_factory = lambda: (
            1.0 * SpectralMixtureKernel(
                1, 3, means=np.array([[0.8], [2.0], [3.0]])
            )
            + WhiteNoiseKernel(0.05, 0, 1)
        )
    return (
        GaussianProcessRegression()
        .setKernel(kernel_factory)
        .setDatasetSizeForExpert(120)
        .setActiveSetSize(100)
        .setSigma2(1e-3)
        .setSeed(3)
        .setMaxIter(150)
        .setNumRestarts(restarts)
    )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--restarts", type=int, default=8)
    parser.add_argument(
        "--rbf", action="store_true",
        help="fit the RBF kernel instead (demonstrates the failure mode: "
        "reverts to the mean outside the data, no assertion)",
    )
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    xs, ys, xe, ye = make_data()
    model = make_gp("rbf" if args.rbf else "sm", args.restarts).fit(xs, ys)
    score = rmse(ye, model.predict(xe))
    which = "RBF" if args.rbf else "SM"
    print(f"{which} extrapolation RMSE over (3, 4]: {score}")
    if not args.rbf:
        assert score < 0.1, "spectral peaks not recovered"
        print("OK (< 0.1)")


if __name__ == "__main__":
    main()
