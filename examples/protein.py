"""UCI Protein (CASP) 46k regression — BASELINE.json stress config 4.

45730 points, 9 features: stresses the product-of-experts reduction (~457
experts at the default expert size).  ARD kernel + trained noise, z-scored
features, 80/20 split RMSE.  No counterpart example exists in the reference
(its largest committed dataset is airfoil at 1503 rows); the config comes
from BASELINE.json.

Run: python examples/protein.py [--csv path] [--n N] [--expert 100]
     [--active 1000] [--maxiter 50]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend

import argparse
import time

import numpy as np

from spark_gp_tpu import ARDRBFKernel, GaussianProcessRegression, WhiteNoiseKernel
from spark_gp_tpu.data import load_protein
from spark_gp_tpu.ops.scaling import fit_scaler
from spark_gp_tpu.utils.validation import rmse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--csv", type=str, default=None, help="UCI CASP csv")
    parser.add_argument("--n", type=int, default=None, help="subsample size")
    parser.add_argument("--expert", type=int, default=100)
    parser.add_argument("--active", type=int, default=1000)
    parser.add_argument("--maxiter", type=int, default=50)
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    x, y = load_protein(args.csv, n=args.n)

    rng = np.random.default_rng(13)
    perm = rng.permutation(x.shape[0])
    cut = int(0.8 * x.shape[0])
    tr, te = perm[:cut], perm[cut:]

    # Normalization statistics from the training split only — no test
    # leakage into the reported RMSE.
    mean, std = (np.asarray(s) for s in fit_scaler(x[tr]))
    x = (x - mean) / std
    y_mean, y_std = y[tr].mean(), y[tr].std()
    y_scaled = (y - y_mean) / y_std

    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * ARDRBFKernel(x.shape[1], x.shape[1] ** -0.5)
        + WhiteNoiseKernel(0.1, 0.0, 1.0))
        .setDatasetSizeForExpert(args.expert)
        .setActiveSetSize(args.active)
        .setSigma2(1e-3)
        .setMaxIter(args.maxiter)
        .setSeed(13)
    )

    start = time.perf_counter()
    model = gp.fit(x[tr], y_scaled[tr])
    fit_s = time.perf_counter() - start
    pred = np.asarray(model.predict(x[te])) * y_std + y_mean
    print(f"TIME: {fit_s * 1000.0:.0f} ms  ({cut} points)")
    print("RMSE: " + str(rmse(y[te], pred)))


if __name__ == "__main__":
    main()
