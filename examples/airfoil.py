"""Airfoil example — regression/examples/Airfoil.scala:9-33.

UCI airfoil self-noise (1503 rows, 5 features), z-scored; kernel
1*ARDRBF(5) + 1.const*Eye; expert 100, active 1000, sigma2 1e-4; asserts
10-fold CV RMSE < 2.1.

Run: python examples/airfoil.py [--folds 10]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend

import argparse

import numpy as np

from spark_gp_tpu import (
    ARDRBFKernel,
    Const,
    EyeKernel,
    GaussianProcessRegression,
)
from spark_gp_tpu.data import load_airfoil
from spark_gp_tpu.ops.scaling import scale
from spark_gp_tpu.utils.validation import cross_validate, rmse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--folds", type=int, default=10)
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    x, y = load_airfoil()
    x = np.asarray(scale(x))  # Airfoil.scala:16 scales features (not labels)

    gp = (
        GaussianProcessRegression()
        .setDatasetSizeForExpert(100)
        .setActiveSetSize(1000)
        .setSigma2(1e-4)
        .setKernel(lambda: 1.0 * ARDRBFKernel(5) + Const(1.0) * EyeKernel())
        .setSeed(13)
    )

    score = cross_validate(gp, x, y, num_folds=args.folds, metric=rmse, seed=13)
    print("RMSE: " + str(score))
    assert score < 2.1
    print("OK (< 2.1)")


if __name__ == "__main__":
    main()
