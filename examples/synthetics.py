"""Synthetics example — regression/examples/Synthetics.scala:11-34.

2000 points of sin(x) + N(0, 0.01); kernel 1*RBF(0.1, 1e-6, 10) +
WhiteNoise(0.5, 0, 1); KMeans active-set provider; expert 100, active 100,
sigma2 1e-3; asserts 10-fold CV RMSE < 0.11.

Run: python examples/synthetics.py [--folds 10]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend

import argparse

from spark_gp_tpu import (
    GaussianProcessRegression,
    KMeansActiveSetProvider,
    RBFKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.data import make_synthetics
from spark_gp_tpu.utils.validation import cross_validate, rmse


def make_gp(objective: str = "marginal"):
    """The Synthetics.scala:11-34 configuration, parameterized by the
    training objective — SINGLE source for this example and the quality
    parts that guard it (quality.py loo / objectives)."""
    if objective == "elbo":
        # sigma2 is the likelihood noise under the bound; no stacked
        # trainable nugget (models/sgpr.py kernel note)
        kernel_factory = lambda: 1.0 * RBFKernel(0.1, 1e-6, 10)
        sigma2 = 1e-2
    else:
        kernel_factory = lambda: (
            1.0 * RBFKernel(0.1, 1e-6, 10) + WhiteNoiseKernel(0.5, 0, 1)
        )
        sigma2 = 1e-3
    return (
        GaussianProcessRegression()
        .setKernel(kernel_factory)
        .setDatasetSizeForExpert(100)
        .setActiveSetProvider(KMeansActiveSetProvider())
        .setActiveSetSize(100)
        .setSeed(13)
        .setSigma2(sigma2)
        .setObjective(objective)
    )


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--folds", type=int, default=10)
    parser.add_argument(
        "--objective", choices=("marginal", "loo", "elbo"), default="marginal",
        help="training objective: the reference's marginal NLL, the LOO "
        "pseudo-likelihood (R&W 5.4.2), or the Titsias SGPR bound — all "
        "three clear the 0.11 bar (quality.py part 'objectives')",
    )
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    x, y = make_synthetics()
    gp = make_gp(args.objective)

    score = cross_validate(gp, x, y, num_folds=args.folds, metric=rmse, seed=13)
    print("RMSE: " + str(score))
    assert score < 0.11
    print("OK (< 0.11)")


if __name__ == "__main__":
    main()
