"""Count GP regression example — model families beyond the reference
(akopich/spark-gp ships Gaussian regression and binary classification
only).

Seeded synthetic counts with rate = exp(1 + sin 2x); fits the log-rate GP
via the generic-likelihood Laplace core and asserts the posterior-expected
rate recovers the truth.

Default: Poisson counts (``Var = mean``), 10% mean-relative-error bar.
``--nb R`` switches to Negative Binomial: counts drawn as a gamma-Poisson
mixture with dispersion R (``Var = mean + mean^2/R``, genuinely
overdispersed) and fitted with
:class:`GaussianProcessNegativeBinomialRegression` at the matching
dispersion, 15% bar.

Run: python examples/poisson.py [--n 2000] [--nb 2.0]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend

import argparse

import numpy as np

from spark_gp_tpu import (
    GaussianProcessNegativeBinomialRegression,
    GaussianProcessPoissonRegression,
    RBFKernel,
)


def _configure(gp):
    """The example's count-regression configuration, applied to either
    estimator (Poisson / Negative Binomial share it)."""
    return (
        gp
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setActiveSetSize(100)
        .setMaxIter(25)
    )


def make_poisson_gp():
    """The example's Poisson configuration — SINGLE source for this script
    and the on-chip quality slice that certifies it
    (tests/test_tpu_quality_slice.py)."""
    return _configure(GaussianProcessPoissonRegression())


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=2000)
    parser.add_argument(
        "--nb", type=float, default=None, metavar="R",
        help="Negative Binomial mode with dispersion R (overdispersed "
        "counts; default is Poisson)",
    )
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    rng = np.random.default_rng(42)
    x = np.linspace(0, 4, args.n)[:, None]
    rate = np.exp(1.0 + np.sin(2 * x[:, 0]))

    if args.nb is None:
        y = rng.poisson(rate).astype(np.float64)
        gp = make_poisson_gp()
        bar = 0.1
    else:
        # estimator first: its likelihood validates dispersion > 0 with a
        # clear message before any division by args.nb below
        gp = _configure(
            GaussianProcessNegativeBinomialRegression(dispersion=args.nb)
        )
        lam = rate * rng.gamma(shape=args.nb, scale=1.0 / args.nb, size=args.n)
        y = rng.poisson(lam).astype(np.float64)
        bar = 0.15

    model = gp.fit(x, y)
    rel = float(np.mean(np.abs(model.predict_rate(x) - rate) / rate))
    print("Mean relative rate error: " + str(rel))
    assert rel < bar, rel
    print(f"OK (< {bar})")


if __name__ == "__main__":
    main()
