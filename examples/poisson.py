"""Poisson (count) GP regression example — model family beyond the
reference (akopich/spark-gp ships Gaussian regression and binary
classification only).

Seeded synthetic counts with rate = exp(1 + sin 2x); fits the log-rate GP
via the generic-likelihood Laplace core and asserts the posterior-expected
rate recovers the truth to 10% mean relative error.

Run: python examples/poisson.py [--n 2000]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse

import numpy as np

from spark_gp_tpu import GaussianProcessPoissonRegression, RBFKernel


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=2000)
    args = parser.parse_args()

    rng = np.random.default_rng(42)
    x = np.linspace(0, 4, args.n)[:, None]
    rate = np.exp(1.0 + np.sin(2 * x[:, 0]))
    y = rng.poisson(rate).astype(np.float64)

    model = (
        GaussianProcessPoissonRegression()
        .setKernel(lambda: 1.0 * RBFKernel(0.5, 1e-2, 10.0))
        .setActiveSetSize(100)
        .setMaxIter(25)
        .fit(x, y)
    )
    rel = float(np.mean(np.abs(model.predict_rate(x) - rate) / rate))
    print("Mean relative rate error: " + str(rel))
    assert rel < 0.1, rel
    print("OK (< 0.1)")


if __name__ == "__main__":
    main()
