"""Year-Prediction-MSD 515k regression — BASELINE.json stress config 5.

515345 points, 90 timbre features: the pod-scale inducing-point config
(~5153 experts at the default expert size; shard the expert axis over a mesh
with ``--devices`` to exercise the multi-chip path).  No counterpart example
exists in the reference; the config comes from BASELINE.json.

Run: python examples/year_msd.py [--csv path] [--n N] [--expert 100]
     [--active 1000] [--maxiter 30] [--devices K]
"""

import os as _os
import sys as _sys

# runnable as ``python examples/<name>.py`` from anywhere: put the repo
# root (the spark_gp_tpu package home) ahead of the script's own dir
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

# imported early (cheap); called in main() after argparse so --help and
# bad-args invocations never pay the probe (utils/platform.py)
from spark_gp_tpu.utils.platform import preflight_backend

import argparse
import time

import numpy as np

from spark_gp_tpu import ARDRBFKernel, GaussianProcessRegression, WhiteNoiseKernel
from spark_gp_tpu.data import load_year_msd
from spark_gp_tpu.ops.scaling import fit_scaler
from spark_gp_tpu.utils.validation import rmse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--csv", type=str, default=None, help="YearPredictionMSD csv")
    parser.add_argument("--n", type=int, default=None, help="subsample size")
    parser.add_argument("--expert", type=int, default=100)
    parser.add_argument("--active", type=int, default=1000)
    parser.add_argument("--maxiter", type=int, default=30)
    parser.add_argument("--devices", type=int, default=0,
                        help="shard experts over a K-device mesh (0 = single device)")
    args = parser.parse_args()

    # never wedge on a half-dead accelerator tunnel: probe the default
    # backend in a subprocess and fall back to CPU if it hangs
    preflight_backend()

    x, y = load_year_msd(args.csv, n=args.n)

    if args.csv is not None and args.n is None and x.shape[0] > 463715:
        # UCI mandates a positional split (first 463715 train / last 51630
        # test) so no artist appears on both sides.  Only exact on the full
        # file — a partial file or a subsample cannot preserve the boundary,
        # so those fall through to the random split below.
        cut = 463715
        tr = np.arange(cut)
        te = np.arange(cut, x.shape[0])
    else:
        rng = np.random.default_rng(13)
        perm = rng.permutation(x.shape[0])
        cut = int(0.8 * x.shape[0])
        tr, te = perm[:cut], perm[cut:]

    # Normalization statistics from the training split only — no test
    # leakage into the reported RMSE.
    mean, std = (np.asarray(s) for s in fit_scaler(x[tr]))
    x = (x - mean) / std
    y_mean, y_std = y[tr].mean(), y[tr].std()
    y_scaled = (y - y_mean) / y_std

    gp = (
        GaussianProcessRegression()
        .setKernel(lambda: 1.0 * ARDRBFKernel(x.shape[1], x.shape[1] ** -0.5)
        + WhiteNoiseKernel(0.1, 0.0, 1.0))
        .setDatasetSizeForExpert(args.expert)
        .setActiveSetSize(args.active)
        .setSigma2(1e-3)
        .setMaxIter(args.maxiter)
        .setSeed(13)
    )
    if args.devices:
        import jax

        from spark_gp_tpu.parallel.mesh import expert_mesh

        gp.setMesh(expert_mesh(jax.devices()[: args.devices]))

    start = time.perf_counter()
    model = gp.fit(x[tr], y_scaled[tr])
    fit_s = time.perf_counter() - start
    pred = np.asarray(model.predict(x[te])) * y_std + y_mean
    print(f"TIME: {fit_s * 1000.0:.0f} ms  ({cut} points)")
    print("RMSE: " + str(rmse(y[te], pred)))


if __name__ == "__main__":
    main()
