"""Hyperparameter optimizers (box-constrained L-BFGS)."""

from spark_gp_tpu.optimize.lbfgsb import minimize_lbfgsb

__all__ = ["minimize_lbfgsb"]
