"""Box-constrained L-BFGS-B driving a jitted device objective.

The reference uses Breeze's driver-side ``LBFGSB(lower, upper, maxIter, tol)``
where every objective evaluation is a full Spark cluster round-trip, memoized
so line-search re-evaluations don't re-launch jobs
(GaussianProcessCommons.scala:66-92, util/DiffFunctionMemoized.scala).

Here the objective is one fused XLA ``value_and_grad`` executable: an
evaluation moves (1 + |theta|) floats host<->device — negligible next to the
compute — so SciPy's L-BFGS-B on the host is the right v0 architecture, and
memoization is pointless (value+grad is a single pass).  The on-device
box-LBFGSB (``lbfgs_device.py`` — generalized Cauchy point + subspace
minimization in a ``lax.while_loop``) is the v1 for pod-scale runs where
even the host sync per step matters; both drivers share this interface.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np
import scipy.optimize


@dataclass
class OptimizeResult:
    theta: np.ndarray
    fun: float
    nit: int
    nfev: int
    success: bool
    message: str
    trace: list = field(default_factory=list)


def log_space_applicable(theta0, lower) -> bool:
    """Log-domain optimization needs strictly-positive initial values and
    non-negative lower bounds (every GP scale/length hyperparameter in
    practice)."""
    return bool(np.all(np.asarray(theta0) > 0) and np.all(np.asarray(lower) >= 0))


def minimize_lbfgsb(
    value_and_grad: Callable,
    theta0: np.ndarray,
    lower: np.ndarray,
    upper: np.ndarray,
    max_iter: int = 100,
    tol: float = 1e-6,
    callback: Optional[Callable] = None,
    log_space: bool = False,
) -> OptimizeResult:
    """Minimize ``value_and_grad`` subject to ``lower <= theta <= upper``.

    ``value_and_grad(theta) -> (float, grad)`` may return device arrays; they
    are pulled to host (tiny transfers).  ``tol`` maps to both scipy's
    ``ftol`` and ``gtol`` — the closest match to Breeze LBFGSB's convergence
    ``tolerance`` (GaussianProcessCommons.scala:84-86).

    ``log_space=True`` optimizes u = log(theta) (chain rule applied to the
    gradient, bounds mapped through log).  GP marginal likelihoods are
    notoriously ill-scaled in the linear domain — e.g. with uncentered
    labels the amplitude hyperparameter's gradient dwarfs the length-scales',
    L-BFGS-B inflates the amplitude first, and the fit collapses into the
    constant-kernel local optimum (observed on the airfoil config; the same
    collapse occurs in float64, so it is a landscape problem, not precision).
    Log-domain coordinates equalize the scales and reach the good basin.
    """
    theta0 = np.asarray(theta0, dtype=np.float64)

    if log_space:
        if not log_space_applicable(theta0, lower):
            raise ValueError(
                "log-space optimization requires theta0 > 0 and lower >= 0"
            )
        inner = value_and_grad
        u0 = np.log(theta0)
        with np.errstate(divide="ignore"):
            lo_u = np.where(lower > 0, np.log(np.maximum(lower, 1e-300)), -np.inf)
            hi_u = np.where(np.isposinf(upper), np.inf, np.log(np.maximum(upper, 1e-300)))

        def value_and_grad_u(u):
            theta = np.exp(u)
            value, grad = inner(theta)
            return value, np.asarray(grad, dtype=np.float64) * theta

        # Callbacks (checkpointers) must observe linear-domain theta, not the
        # log-domain iterate the inner solver walks.
        callback_u = None if callback is None else (lambda u: callback(np.exp(u)))
        res = minimize_lbfgsb(
            value_and_grad_u, u0, lo_u, hi_u,
            max_iter=max_iter, tol=tol, callback=callback_u, log_space=False,
        )
        res.theta = np.exp(res.theta)
        return res
    bounds = list(
        zip(
            [None if np.isneginf(lo) else float(lo) for lo in lower],
            [None if np.isposinf(hi) else float(hi) for hi in upper],
        )
    )

    nfev = 0

    def fun(theta):
        nonlocal nfev
        nfev += 1
        value, grad = value_and_grad(theta)
        value = float(np.asarray(value))
        grad = np.asarray(grad, dtype=np.float64)
        if not np.isfinite(value):
            if nfev == 1:
                # A non-finite NLL at theta0 means the kernel matrix is not
                # PD at the *initial* hyperparameters — returning a masked
                # value here would make L-BFGS-B declare instant convergence.
                # Surface it like the reference does (MatrixSingularException
                # -> NotPositiveDefiniteException advice).
                from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException

                raise NotPositiveDefiniteException()
            # Mid-line-search non-PD trial point: return a large finite value
            # with zero gradient so the Wolfe decrease test fails and the
            # search backtracks (never accepted as an iterate).
            return 1e25, np.zeros_like(grad)
        return value, grad

    res = scipy.optimize.minimize(
        fun,
        theta0,
        jac=True,
        method="L-BFGS-B",
        bounds=bounds,
        callback=callback,
        options={"maxiter": max_iter, "ftol": tol, "gtol": tol},
    )
    return OptimizeResult(
        theta=np.asarray(res.x, dtype=np.float64),
        fun=float(res.fun),
        # scipy omits nit when L-BFGS-B exits before its first iteration
        # (e.g. all bounds pinned lower == upper)
        nit=int(getattr(res, "nit", 0)),
        nfev=int(res.nfev),
        success=bool(res.success),
        message=str(res.message),
    )
