"""On-device box-constrained L-BFGS in a single XLA program.

The v1 optimizer promised in ``lbfgsb.py``: the entire hyperparameter
optimization — objective, gradient, line search, history updates — runs
inside one ``lax.while_loop`` under jit, so a fit costs ONE device dispatch
instead of one per L-BFGS evaluation.  On dispatch-latency-heavy runtimes
(remote TPU tunnels, multi-host pods where every host sync stalls the ICI
collective) this is the difference between latency-bound and compute-bound
training.

Algorithm: TRUE box-LBFGSB (Byrd, Lu, Nocedal & Zhu 1995 — the same method
as Breeze's LBFGSB, GPC.scala:84-86): each iteration walks the generalized
Cauchy point of the quadratic model along the projected-gradient path, then
minimizes the model over the free variables with the Cauchy active set held
fixed (dense subspace Newton solve — the hyperparameter count is 1..~100,
so the compact-representation machinery of the large-n original is
unnecessary), backtracks the subspace step into the box, and line-searches
the proposal with weak-Wolfe bracketing over the clipped path.  Curvature
pairs are stored only when s.y > eps.  v1 of this module shipped the
projected-gradient compromise; the GCP + subspace step closed that last
semantic delta from the reference optimizer (VERDICT r3 item 8).

Generic over an auxiliary carry threaded through objective evaluations: GPR
passes none; the Laplace objective carries its latent warm-start stack
(the functional analogue of GPClf.scala:53-60).

All state is fixed-shape: [m_hist, h] circular history buffers with masks —
no dynamic shapes, fully MXU/VPU-friendly, differentiably irrelevant (the
loop is never differentiated through).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


def log_transform_vag(value_and_grad_aux):
    """Chain-rule wrap of an objective for u = log(theta) coordinates."""

    def vag_u(u, aux):
        theta = jnp.exp(u)
        value, grad, aux2 = value_and_grad_aux(theta, aux)
        return value, grad * theta, aux2

    return vag_u


def log_transform_bounds(lower, upper):
    """Box bounds mapped through log (0 lower -> -inf, inf upper -> inf)."""
    lower_u = jnp.where(lower > 0, jnp.log(jnp.maximum(lower, 1e-300)), -jnp.inf)
    upper_u = jnp.where(
        jnp.isposinf(upper), jnp.inf, jnp.log(jnp.maximum(upper, 1e-300))
    )
    return lower_u, upper_u


def log_reparam(value_and_grad_aux, theta0, lower, upper):
    """Map a box-constrained objective to log-domain coordinates u = log(theta).

    Returns ``(vag_u, u0, lower_u, upper_u, from_u)``.  See
    ``optimize.lbfgsb.minimize_lbfgsb(log_space=True)`` for why GP marginal
    likelihoods want this.  Caller guarantees theta0 > 0, lower >= 0.
    """
    lower_u, upper_u = log_transform_bounds(lower, upper)
    return (
        log_transform_vag(value_and_grad_aux),
        jnp.log(theta0),
        lower_u,
        upper_u,
        jnp.exp,
    )


def lbfgs_state_donation(state_argnum: int) -> tuple:
    """``donate_argnums`` for a jitted segment-advance whose
    :class:`_LbfgsState` carry sits at positional ``state_argnum``.

    The segmented checkpoint drivers round-trip the full optimizer state
    — iterate, gradient, the [m_hist, h] curvature history pair, aux —
    through one compiled program per chunk.  The input state is consumed
    exactly once and replaced by the returned state (every family's
    ``run_segmented`` loop rebinds and persists the RETURN value before
    the next dispatch), so donating it lets XLA alias the output into the
    input's HBM instead of double-buffering the carry.  ONE home for the
    argnum-tuple so every family's segment runner declares donation the
    same way and tests can assert the contract (test_precision_policy.py
    asserts the lowered programs carry the donor/aliasing annotations and
    that the live-buffer count stays flat across segments).
    """
    return (int(state_argnum),)


class _LbfgsState(NamedTuple):
    theta: jax.Array  # [h]
    f: jax.Array  # scalar
    grad: jax.Array  # [h]
    aux: object  # pytree carried through objective evals
    s_hist: jax.Array  # [m, h]
    y_hist: jax.Array  # [m, h]
    hist_count: jax.Array  # int32
    hist_head: jax.Array  # int32 (next write slot)
    n_iter: jax.Array  # int32
    n_fev: jax.Array  # int32
    done: jax.Array  # bool
    stalled: jax.Array  # bool: line search exhausted without an acceptable step


def _dense_b_from_history(s_hist, y_hist, count, head, m_hist):
    """The L-BFGS Hessian approximation B as a DENSE [h, h] matrix.

    Classic LBFGSB (Byrd/Lu/Nocedal/Zhu 1995) keeps B in the compact
    ``theta I - W M W^T`` form because n is large; here n = h is the kernel's
    hyperparameter count (1 to ~100), so materializing B and applying the
    stored curvature pairs as dense BFGS updates is both simpler and exact —
    the SAME quasi-Newton matrix the two-loop recursion represents
    implicitly.  Invalid (unfilled) slots are skipped by masking; the
    initial scaling is the standard ``theta = y.y / s.y`` of the newest
    pair.
    """
    h = s_hist.shape[1]
    dtype = s_hist.dtype
    newest = (head - 1) % m_hist
    sy_n = jnp.dot(s_hist[newest], y_hist[newest])
    yy_n = jnp.dot(y_hist[newest], y_hist[newest])
    theta = jnp.where((count > 0) & (sy_n > 0), yy_n / jnp.maximum(sy_n, 1e-30), 1.0)
    b0 = theta * jnp.eye(h, dtype=dtype)

    def upd(i, b_mat):
        # oldest -> newest (BFGS update order matters)
        slot = (head - count + i) % m_hist
        valid = i < count
        s = s_hist[slot]
        y = y_hist[slot]
        sy = jnp.dot(s, y)
        bs = b_mat @ s
        sbs = jnp.dot(s, bs)
        b_new = (
            b_mat
            - jnp.outer(bs, bs) / jnp.maximum(sbs, 1e-30)
            + jnp.outer(y, y) / jnp.maximum(sy, 1e-30)
        )
        return jnp.where(valid, b_new, b_mat)

    return jax.lax.fori_loop(0, m_hist, upd, b0)


def _steps_to_bounds(origin, direction, lower, upper, fill):
    """Per-coordinate step length along ``direction`` until each coordinate
    of ``origin`` hits its box bound; ``fill`` where the direction component
    is zero (no bound ever hit) or the ratio is indeterminate (infinite
    bound).  Shared by the Cauchy breakpoint computation and the subspace
    feasibility backtrack — one home for the guarded-division pattern."""
    pos = direction > 0.0
    neg = direction < 0.0
    steps = jnp.where(
        pos,
        (upper - origin) / jnp.where(pos, direction, 1.0),
        jnp.where(neg, (lower - origin) / jnp.where(neg, direction, 1.0), fill),
    )
    return jnp.where(jnp.isnan(steps), fill, steps)


def _cauchy_point(x, g, lower, upper, b_mat):
    """Generalized Cauchy point of the quadratic model over the box
    (Byrd et al. 1995, CP algorithm): minimize
    ``m(t) = g.z(t) + z(t)^T B z(t) / 2`` along the projected
    steepest-descent path ``z(t) = P(x - t g) - x``, examining the
    piecewise-linear segments between bound breakpoints in sorted order.

    Returns ``(z_c, fixed)``: the step to the Cauchy point and the mask of
    variables that hit their bound before the path minimizer (the active
    set the subspace minimization holds fixed).  h is small, so each
    segment recomputes its directional derivatives against dense B —
    O(h^2) per segment, O(h^3) total.
    """
    dtype = x.dtype
    h = x.shape[0]
    inf = jnp.asarray(jnp.inf, dtype)
    # breakpoint where each coordinate's projected path (direction -g)
    # hits its bound
    t_break = _steps_to_bounds(x, -g, lower, upper, inf)
    order = jnp.argsort(t_break)

    class CP(NamedTuple):
        t_prev: jax.Array
        z: jax.Array  # [h] step so far
        d: jax.Array  # [h] current segment direction
        fixed: jax.Array  # [h] bool
        done: jax.Array  # bool

    def seg(j, cp: CP):
        idx = order[j]
        t_j = t_break[idx]
        bd = b_mat @ cp.d
        f1 = jnp.dot(g, cp.d) + jnp.dot(cp.z, bd)
        f2 = jnp.maximum(jnp.dot(cp.d, bd), 1e-30)
        dt_star = -f1 / f2
        seg_len = t_j - cp.t_prev
        # minimizer inside this segment (or already behind us: f1 >= 0)
        hit = (~cp.done) & ((f1 >= 0.0) | (dt_star <= seg_len))
        dt = jnp.clip(dt_star, 0.0, jnp.minimum(seg_len, jnp.finfo(dtype).max))
        z_min = cp.z + dt * cp.d
        # otherwise advance to the breakpoint and fix variable idx exactly
        # at its bound (exact snap: no fp drift into the infeasible side)
        z_at_break = cp.z + seg_len * cp.d
        z_at_break = z_at_break.at[idx].set(
            jnp.where(g[idx] < 0.0, upper[idx] - x[idx], lower[idx] - x[idx])
        )
        advance = (~cp.done) & ~hit
        return CP(
            t_prev=jnp.where(cp.done | hit, cp.t_prev, t_j),
            z=jnp.where(hit, z_min, jnp.where(advance, z_at_break, cp.z)),
            d=jnp.where(advance, cp.d.at[idx].set(0.0), cp.d),
            fixed=jnp.where(advance, cp.fixed.at[idx].set(True), cp.fixed),
            done=cp.done | hit,
        )

    init = CP(
        t_prev=jnp.zeros((), dtype),
        z=jnp.zeros_like(x),
        d=-g,
        fixed=jnp.zeros((h,), bool),
        done=jnp.zeros((), bool),
    )
    cp = jax.lax.fori_loop(0, h, seg, init)
    # final unbounded segment (every remaining coordinate is bound-free)
    bd = b_mat @ cp.d
    f1 = jnp.dot(g, cp.d) + jnp.dot(cp.z, bd)
    f2 = jnp.maximum(jnp.dot(cp.d, bd), 1e-30)
    dt = jnp.maximum(-f1 / f2, 0.0)
    z_c = jnp.where(cp.done, cp.z, cp.z + dt * cp.d)
    return z_c, cp.fixed


def _lbfgsb_direction(x, g, lower, upper, s_hist, y_hist, count, head, m_hist):
    """True box-LBFGSB step proposal ``x_bar - x`` (Byrd et al. 1995):
    generalized Cauchy point, then minimization of the quadratic model over
    the free variables with the Cauchy active set held fixed, backtracked
    to the box.  Replaces the projected-gradient compromise this module
    shipped first (the one semantic delta from Breeze's LBFGSB,
    GaussianProcessCommons.scala:84-86, VERDICT r3 item 8).

    In the interior with an interior minimizer this reduces exactly to the
    unconstrained quasi-Newton step ``-B^-1 g``; with active bounds it
    walks the Cauchy active set like the reference optimizer instead of
    clipping a free-space step.
    """
    dtype = x.dtype
    b_mat = _dense_b_from_history(s_hist, y_hist, count, head, m_hist)
    z_c, fixed = _cauchy_point(x, g, lower, upper, b_mat)

    # subspace Newton system on the free variables: rows/cols of fixed
    # variables are replaced by identity so the dense solve leaves them 0
    free = ~fixed
    free_f = free.astype(dtype)
    rhs = -(g + b_mat @ z_c) * free_f
    m_free = (
        b_mat * free_f[:, None] * free_f[None, :]
        + jnp.diag(1.0 - free_f)
    )
    d_f = jnp.linalg.solve(m_free, rhs)
    d_f = jnp.where(jnp.all(jnp.isfinite(d_f)), d_f, jnp.zeros_like(d_f))

    # backtrack the subspace step into the box (alpha* in Byrd et al. 5.8)
    x_c = x + z_c
    big = jnp.asarray(jnp.finfo(dtype).max, dtype)
    room = _steps_to_bounds(x_c, d_f, lower, upper, big)
    alpha = jnp.clip(jnp.min(room, initial=big, where=free), 0.0, 1.0)
    return z_c + alpha * d_f


def lbfgs_init_state(value_and_grad_aux, theta0, aux0, m_hist: int = 10):
    """Evaluate the objective once and build the optimizer's carried state.

    The state is a flat-array NamedTuple (plus the aux pytree), so it can be
    pulled to host, persisted, and fed back into ``lbfgs_run_segment`` — the
    checkpoint/resume unit for long fits (SURVEY.md §5: JAX has no lineage;
    the reference leans on Spark recompute).
    """
    theta0 = jnp.asarray(theta0)
    dtype = theta0.dtype
    h = theta0.shape[0]
    f0, g0, aux1 = value_and_grad_aux(theta0, aux0)
    return _LbfgsState(
        theta=theta0,
        f=f0,
        grad=g0,
        aux=aux1,
        s_hist=jnp.zeros((m_hist, h), dtype=dtype),
        y_hist=jnp.zeros((m_hist, h), dtype=dtype),
        hist_count=jnp.zeros((), jnp.int32),
        hist_head=jnp.zeros((), jnp.int32),
        n_iter=jnp.zeros((), jnp.int32),
        n_fev=jnp.ones((), jnp.int32),
        done=jnp.zeros((), jnp.bool_),
        stalled=jnp.zeros((), jnp.bool_),
    )


def lbfgs_run_segment(
    value_and_grad_aux,
    state: _LbfgsState,
    lower,
    upper,
    iter_limit,
    tol: float = 1e-6,
    m_hist: int = 10,
    max_ls: int = 25,
    armijo_c1: float = 1e-4,
):
    """Run L-BFGS iterations until convergence or ``n_iter >= iter_limit``.

    ``iter_limit`` is an absolute iteration count (may be traced), so a host
    loop can advance the same compiled program in K-iteration segments,
    persisting the returned state between dispatches.
    """
    dtype = state.theta.dtype
    lower = jnp.asarray(lower, dtype=dtype)
    upper = jnp.asarray(upper, dtype=dtype)
    body = _make_body(
        value_and_grad_aux, lower, upper, tol, m_hist, max_ls, armijo_c1
    )

    def cond(s: _LbfgsState):
        return jnp.logical_and(~s.done, s.n_iter < iter_limit)

    return jax.lax.while_loop(cond, body, state)


def lbfgs_minimize_device(
    value_and_grad_aux,
    theta0,
    lower,
    upper,
    aux0,
    max_iter: int = 100,
    tol: float = 1e-6,
    m_hist: int = 10,
    max_ls: int = 25,
    armijo_c1: float = 1e-4,
):
    """Minimize on device.  ``value_and_grad_aux(theta, aux) -> (f, g, aux)``
    must be jit-traceable.  Returns ``(theta, f, aux, n_iter, n_fev,
    stalled)``.

    Convergence mirrors the scipy/Breeze pair of tests used by the host
    driver: projected-gradient inf-norm < tol, or relative objective change
    < tol between accepted iterates.  ``stalled`` is True when the loop ended
    because the line search could not find an acceptable step (the analogue
    of scipy's ``success=False`` / ``ABNORMAL_TERMINATION_IN_LNSRCH``) — the
    returned iterate is the best seen, but it is NOT a certified optimum and
    callers should surface the condition (common.py logs a warning).
    """
    state = lbfgs_init_state(value_and_grad_aux, theta0, aux0, m_hist)
    final = lbfgs_run_segment(
        value_and_grad_aux, state, lower, upper, max_iter, tol,
        m_hist, max_ls, armijo_c1,
    )
    return (
        final.theta, final.f, final.aux, final.n_iter, final.n_fev,
        final.stalled,
    )


def _make_body(value_and_grad_aux, lower, upper, tol, m_hist, max_ls, armijo_c1):
    """One L-BFGS iteration (direction, Wolfe line search, history update)."""
    dtype = lower.dtype

    def proj(t):
        return jnp.clip(t, lower, upper)

    def proj_grad_norm(theta, grad):
        # norm of the projected gradient: zero at a KKT point of the box
        step = proj(theta - grad) - theta
        return jnp.max(jnp.abs(step)) if step.size else jnp.zeros((), dtype)

    def body(state: _LbfgsState):
        direction = _lbfgsb_direction(
            state.theta, state.grad, lower, upper,
            state.s_hist, state.y_hist, state.hist_count, state.hist_head,
            m_hist,
        )
        # safeguard: fall back to steepest descent if the model step is not
        # a descent direction (degenerate B / all-fixed Cauchy corner)
        descent = jnp.dot(direction, state.grad) < 0
        direction = jnp.where(descent, direction, -state.grad)

        # Weak-Wolfe bracketing line search along the projected path.
        # Armijo alone stalls L-BFGS: it happily accepts steps far shorter
        # than the local curvature scale, the resulting (s, y) pairs violate
        # s.y > 0, the history freezes, and the direction collapses (observed
        # on Rosenbrock).  Bisection bracketing on the pair
        #   A: f(t) <= f + c1 t g.d       (sufficient decrease)
        #   C: g(t).d >= c2 g.d           (curvature / step-not-too-short)
        # guarantees curvature-consistent pairs on smooth objectives.
        c2 = jnp.asarray(0.9, dtype)
        g_dot_d = jnp.dot(state.grad, direction)

        class LS(NamedTuple):
            t: jax.Array
            low: jax.Array
            high: jax.Array  # inf until an upper bracket is found
            f_new: jax.Array
            g_new: jax.Array
            aux_new: object
            theta_new: jax.Array
            accepted: jax.Array  # full Wolfe pair found
            armijo_seen: jax.Array  # fallback: some Armijo point found
            n_ls: jax.Array
            n_fev: jax.Array

        def ls_cond(ls: LS):
            return jnp.logical_and(~ls.accepted, ls.n_ls < max_ls)

        def ls_body(ls: LS):
            theta_cand = proj(state.theta + ls.t * direction)
            f_cand, g_cand, aux_cand = value_and_grad_aux(theta_cand, state.aux)
            delta = theta_cand - state.theta
            # Non-finite value OR gradient marks the candidate unusable (an
            # overflowed theta can yield a finite plateau f with NaN grad —
            # accepting it would poison the next direction): treat as "too
            # far" so the bracket shrinks.
            finite = jnp.isfinite(f_cand) & jnp.all(jnp.isfinite(g_cand))
            armijo = (
                f_cand <= state.f + armijo_c1 * jnp.dot(state.grad, delta)
            ) & finite
            curv = jnp.dot(g_cand, direction) >= c2 * g_dot_d
            moved = jnp.max(jnp.abs(delta)) > 0
            # Box saturation: if growing t cannot move the projected iterate
            # any further, the curvature test can never pass along this
            # direction — accept the Armijo point instead of doubling t until
            # max_ls (the clipped path is constant from here on).
            saturated = jnp.all(
                proj(state.theta + 2.0 * ls.t * direction) == theta_cand
            )
            accept = armijo & moved & (curv | saturated)
            # keep any Armijo point as the fallback iterate
            keep = accept | (armijo & moved)
            # bracket update: no Armijo -> shrink from above; Armijo but
            # too-short -> grow from below (double until an upper bracket
            # exists, then bisect)
            high = jnp.where(armijo, ls.high, ls.t)
            low = jnp.where(armijo & ~curv, ls.t, ls.low)
            t_next = jnp.where(
                armijo & ~curv,
                jnp.where(jnp.isinf(high), ls.t * 2.0, 0.5 * (low + high)),
                0.5 * (low + high),
            )
            return LS(
                t=jnp.where(accept, ls.t, t_next),
                low=low,
                high=high,
                f_new=jnp.where(keep, f_cand, ls.f_new),
                g_new=jnp.where(keep, g_cand, ls.g_new),
                aux_new=jax.tree.map(
                    lambda new, old: jnp.where(keep, new, old), aux_cand, ls.aux_new
                ),
                theta_new=jnp.where(keep, theta_cand, ls.theta_new),
                accepted=accept,
                armijo_seen=ls.armijo_seen | (armijo & moved),
                n_ls=ls.n_ls + 1,
                n_fev=ls.n_fev + 1,
            )

        # Entry-point KKT check: an iterate already box-stationary has zero
        # projected gradient — NO step can move it, so running the line
        # search would burn max_ls futile objective evaluations before the
        # convergence logic below certifies it (scipy likewise certifies on
        # gtol before attempting a step).  Seeding accepted=True makes the
        # search loop exit immediately with the unchanged iterate.
        already_opt = proj_grad_norm(state.theta, state.grad) <= tol

        # First iteration has no curvature history: the raw steepest-descent
        # direction is unnormalized (its magnitude is the gradient's, which
        # for a summed-over-experts NLL can be ~1e4), so a unit step would
        # overflow log-domain coordinates.  Standard remedy: initial trial
        # step min(1, 1/|d|_inf).  Once history exists, gamma scaling makes
        # t=1 the right trial.
        t_init = jnp.where(
            state.hist_count == 0,
            jnp.minimum(
                jnp.ones((), dtype),
                1.0 / jnp.maximum(jnp.max(jnp.abs(direction)), 1e-30),
            ),
            jnp.ones((), dtype),
        )
        ls0 = LS(
            t=t_init,
            low=jnp.zeros((), dtype),
            high=jnp.asarray(jnp.inf, dtype),
            f_new=state.f,
            g_new=state.grad,
            aux_new=state.aux,
            theta_new=state.theta,
            # already_opt: no step can move a box-stationary iterate.
            # state.done: a frozen lane under vmap (its result is discarded
            # by the freeze guard below) must not burn max_ls batched
            # objective evaluations per outer iteration; standalone, done
            # never reaches the body (the outer cond gates it).
            accepted=already_opt | state.done,
            armijo_seen=jnp.zeros((), jnp.bool_),
            n_ls=jnp.zeros((), jnp.int32),
            n_fev=jnp.zeros((), jnp.int32),
        )
        ls = jax.lax.while_loop(ls_cond, ls_body, ls0)
        ls = ls._replace(accepted=ls.accepted | ls.armijo_seen)

        # curvature pair update (only when accepted and s.y > eps)
        s_vec = ls.theta_new - state.theta
        y_vec = ls.g_new - state.grad
        sy = jnp.dot(s_vec, y_vec)
        store = ls.accepted & (sy > 1e-10)
        slot = state.hist_head
        s_hist = jnp.where(
            store, state.s_hist.at[slot].set(s_vec), state.s_hist
        )
        y_hist = jnp.where(
            store, state.y_hist.at[slot].set(y_vec), state.y_hist
        )
        head = jnp.where(store, (slot + 1) % m_hist, slot)
        count = jnp.where(
            store, jnp.minimum(state.hist_count + 1, m_hist), state.hist_count
        )

        f_change = jnp.abs(state.f - ls.f_new) <= tol * jnp.maximum(
            1.0, jnp.abs(ls.f_new)
        )
        g_small = proj_grad_norm(ls.theta_new, ls.g_new) <= tol
        converged = (ls.accepted & (f_change | g_small)) | already_opt
        stalled = ~ls.accepted & ~already_opt  # line search exhausted

        new_state = _LbfgsState(
            theta=ls.theta_new,
            f=ls.f_new,
            grad=ls.g_new,
            aux=ls.aux_new,
            s_hist=s_hist,
            y_hist=y_hist,
            hist_count=count,
            hist_head=head,
            n_iter=state.n_iter + 1,
            n_fev=state.n_fev + ls.n_fev,
            done=converged | stalled,
            stalled=stalled,
        )
        # Freeze finished lanes.  Standalone, the while_loop exits the moment
        # done is True and this guard is a no-op; under vmap (multistart) the
        # batched loop keeps stepping every lane until ALL are done, and an
        # unguarded body would let a converged lane keep moving — flipping
        # its done/stalled flags (a converged lane whose line search can no
        # longer move would end "stalled") and inflating its n_iter/n_fev to
        # the global loop count.
        return jax.tree.map(
            lambda new, old: jnp.where(state.done, old, new), new_state, state
        )

    return body


def lbfgs_minimize_device_multistart(
    value_and_grad_aux,
    theta0_batch,
    lower,
    upper,
    aux0,
    max_iter: int = 100,
    tol: float = 1e-6,
    m_hist: int = 10,
):
    """ALL restarts of a multi-start minimization as ONE batched device
    program: ``vmap`` over the starting points runs the R optimizers in
    lockstep, so a multi-start fit costs one dispatch and the per-lane
    compute batches onto the MXU instead of R sequential programs.  A lane
    that terminates (converged or stalled) is frozen by the body's done
    guard while the remaining lanes iterate, so its final state — iterate,
    diagnostics, termination flags — is exactly what a standalone run would
    report.

    ``theta0_batch`` is ``[R, h]``; ``aux0`` is shared (broadcast).
    Returns ``(theta_best, f_best, aux_best, n_iter_best, n_fev_best,
    stalled_best, f_all [R], best)`` — ``f_all`` is every lane's final
    objective, ``best`` the winning lane's index (the SINGLE home of the
    winner selection — callers must not re-rank), and the iter/fev
    diagnostics are the winner's (matching the sequential driver's
    winner-only reporting semantics).
    """

    def run_one(t0):
        return lbfgs_minimize_device(
            value_and_grad_aux, t0, lower, upper, aux0,
            max_iter=max_iter, tol=tol, m_hist=m_hist,
        )

    thetas, fs, auxs, iters, fevs, stalls = jax.vmap(run_one)(theta0_batch)
    # non-finite lanes (diverged restarts) must never win
    fs_ranked = jnp.where(jnp.isfinite(fs), fs, jnp.inf)
    best = jnp.argmin(fs_ranked)
    return (
        thetas[best],
        fs[best],
        jax.tree.map(lambda a: a[best], auxs),
        iters[best],
        fevs[best],
        stalls[best],
        fs,
        best,
    )


def multistart_minimize(
    value_and_grad_aux, log_space, theta0_batch, lower, upper, aux0,
    max_iter, tol,
):
    """Shared plumbing of every model family's batched multi-start fit:
    optional log-space reparameterization (elementwise, so the [R, h]
    starting batch maps through unchanged) around
    :func:`lbfgs_minimize_device_multistart`.  Returns
    ``(theta_best, aux_best, nll_best, n_iter, n_fev, stalled,
    f_all [R], best)`` in the original (non-log) coordinates."""
    if log_space:
        value_and_grad_aux, theta0_batch, lower, upper, from_u = log_reparam(
            value_and_grad_aux, theta0_batch, lower, upper
        )
    else:
        from_u = lambda t: t
    theta, f, aux, n_iter, n_fev, stalled, f_all, best = (
        lbfgs_minimize_device_multistart(
            value_and_grad_aux, theta0_batch, lower, upper, aux0,
            max_iter=max_iter, tol=tol,
        )
    )
    return from_u(theta), aux, f, n_iter, n_fev, stalled, f_all, best
