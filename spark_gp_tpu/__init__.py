"""spark_gp_tpu — a TPU-native Gaussian Process framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
Spark/Breeze library (akopich/spark-gp): linear-time Gaussian Process
regression and classification at scale via

* **Bayesian Committee Machine (product-of-experts)** hyperparameter fitting —
  the dataset is split into small "expert" chunks and the approximate negative
  log marginal likelihood is the sum of per-expert NLLs
  (reference: GaussianProcessCommons.scala:66-92), and

* **Projected Process Approximation** prediction — the posterior is projected
  onto an m-point active set so model size and predict cost are independent of
  N (reference: GaussianProcessCommons.scala:40-59, Rasmussen & Williams
  ch. 8.3.4).

The TPU-first design differs deliberately from the reference's architecture:

* experts live on a leading array axis ``[E, s, ...]`` sharded across chips
  (``jax.sharding.Mesh`` + ``shard_map``) instead of Spark RDD partitions;
* cross-device reductions are XLA ``psum`` collectives over ICI instead of
  ``treeAggregate``;
* kernels are pure functions of a flat hyperparameter vector — gradients come
  from autodiff (``jax.value_and_grad``), not hand-written matrix calculus;
* all dense linear algebra is Cholesky-based (no LU + ``dgetri``, no explicit
  inverses, no ``eigSym`` positive-definiteness sweeps).
"""

from spark_gp_tpu.utils.platform import honor_platform_env as _honor_platform_env

_honor_platform_env()

from spark_gp_tpu.utils.compat import install_jax_compat as _install_jax_compat

_install_jax_compat()

from spark_gp_tpu.kernels import (
    ARDMatern32Kernel,
    ARDRationalQuadraticKernel,
    ARDMatern52Kernel,
    ARDRBFKernel,
    Const,
    DotProductKernel,
    EyeKernel,
    Kernel,
    Matern12Kernel,
    Matern32Kernel,
    Matern52Kernel,
    PeriodicKernel,
    ProductKernel,
    PolynomialKernel,
    RationalQuadraticKernel,
    SpectralMixtureKernel,
    RBFKernel,
    Scalar,
    SumKernel,
    WhiteNoiseKernel,
)
from spark_gp_tpu.models.gpr import (
    GaussianProcessRegression,
    GaussianProcessRegressionModel,
)
from spark_gp_tpu.models.gpc import (
    GaussianProcessClassifier,
    GaussianProcessClassificationModel,
)
from spark_gp_tpu.models.gpc_ep import (
    GaussianProcessEPClassificationModel,
    GaussianProcessEPClassifier,
)
from spark_gp_tpu.models.gpc_mc import (
    GaussianProcessMulticlassClassifier,
    GaussianProcessMulticlassModel,
)
from spark_gp_tpu.models.gp_poisson import (
    GaussianProcessNegativeBinomialRegression,
    GaussianProcessPoissonModel,
    GaussianProcessPoissonRegression,
)
from spark_gp_tpu.models.active_set import (
    ActiveSetProvider,
    GreedilyOptimizingActiveSetProvider,
    KMeansActiveSetProvider,
    RandomActiveSetProvider,
)
from spark_gp_tpu.ops.linalg import NotPositiveDefiniteException
from spark_gp_tpu.resilience.quarantine import (
    ExpertQuarantineError,
    NonFiniteFitError,
)
from spark_gp_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    CheckpointMismatchError,
)

__version__ = "0.4.0"

__all__ = [
    "Kernel",
    "RBFKernel",
    "ARDRBFKernel",
    "Matern12Kernel",
    "Matern32Kernel",
    "Matern52Kernel",
    "ARDMatern32Kernel",
    "ARDMatern52Kernel",
    "RationalQuadraticKernel",
    "ARDRationalQuadraticKernel",
    "PeriodicKernel",
    "DotProductKernel",
    "PolynomialKernel",
    "SpectralMixtureKernel",
    "EyeKernel",
    "WhiteNoiseKernel",
    "SumKernel",
    "ProductKernel",
    "Scalar",
    "Const",
    "GaussianProcessRegression",
    "GaussianProcessRegressionModel",
    "GaussianProcessClassifier",
    "GaussianProcessClassificationModel",
    "GaussianProcessMulticlassClassifier",
    "GaussianProcessMulticlassModel",
    "GaussianProcessPoissonRegression",
    "GaussianProcessEPClassifier",
    "GaussianProcessEPClassificationModel",
    "GaussianProcessNegativeBinomialRegression",
    "GaussianProcessPoissonModel",
    "ActiveSetProvider",
    "RandomActiveSetProvider",
    "KMeansActiveSetProvider",
    "GreedilyOptimizingActiveSetProvider",
    "NotPositiveDefiniteException",
    "ExpertQuarantineError",
    "NonFiniteFitError",
    "CheckpointCorruptError",
    "CheckpointMismatchError",
]
