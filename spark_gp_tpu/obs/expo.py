"""OpenMetrics text exposition of the package's metric state.

Renders any :class:`~spark_gp_tpu.utils.instrumentation.Instrumentation`
(phase timings + fit metrics) or
:class:`~spark_gp_tpu.serve.metrics.ServingMetrics` (counters + gauges +
latency histograms), plus the :mod:`spark_gp_tpu.obs.runtime` telemetry,
as one spec-compliant OpenMetrics 1.0 page — the format every Prometheus
scraper (and its whole alerting/dashboards ecosystem) ingests natively.

Mapping rules (docs/OBSERVABILITY.md):

* dotted keys become ``gp_``-prefixed underscore names
  (``queue.shed.deadline`` -> ``gp_queue_shed_deadline_total``); a
  trailing ``_s`` becomes ``_seconds`` with a ``# UNIT`` line;
* catalog patterns with a ``label`` collapse into ONE family with that
  label (``breaker.open.mymodel`` ->
  ``gp_breaker_open{model="mymodel"}``) instead of a family per model;
* :class:`LatencyHistogram` instances render their lifetime-cumulative
  bucket counters (``cumulative()``) — true monotonic ``_bucket`` /
  ``_count`` / ``_sum`` series as Prometheus ``rate()`` and
  ``histogram_quantile()`` require; the recency window feeds only the
  p50/p99 JSON snapshots;
* fit metrics (free-form scalar diagnostics) render as the single
  labeled family ``gp_fit_metric{key="..."}`` (strings as
  ``gp_fit_info{key=...,value=...} 1``) so a new diagnostic never mints
  an unregistered family.

The page ends with ``# EOF`` as the spec requires; the grammar is pinned
by ``tests/test_observability.py``.
"""

from __future__ import annotations

import contextlib
import math
import socket
import threading
from typing import Dict, List, Optional, Tuple

from spark_gp_tpu.obs import names as _names

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: bucket ladders live in the catalog module (histograms pick theirs at
#: creation — obs/names.buckets_for); re-exported here for convenience
LATENCY_BUCKETS = _names.LATENCY_BUCKETS
SIZE_BUCKETS = _names.SIZE_BUCKETS
RATIO_BUCKETS = _names.RATIO_BUCKETS


def _fmt(value: float) -> str:
    value = float(value)
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return format(value, ".10g")


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _family_for(key: str) -> Tuple[str, Optional[str], Dict[str, str]]:
    """``(family_key, unit, labels)`` for one concrete emitted key: the
    catalog's labeled patterns collapse the dynamic part into a label,
    everything else maps 1:1."""
    spec = _names.lookup(key)
    labels: Dict[str, str] = {}
    family_key = key
    if spec is not None and "*" in spec.key and spec.label is not None:
        prefix = spec.key.split("*", 1)[0].rstrip(".")
        family_key = prefix if prefix else key
        if key.startswith(prefix) and len(key) > len(prefix):
            labels[spec.label] = key[len(prefix):].lstrip(".")
    unit = None
    if family_key.endswith("_s"):
        family_key = family_key[:-2] + "_seconds"
        unit = "seconds"
    return "gp_" + family_key.replace(".", "_"), unit, labels


def _help_for(key: str, fallback: str) -> str:
    spec = _names.lookup(key)
    return spec.help if spec is not None else fallback


class _Page:
    """Accumulates families, renders them sorted, one block per family."""

    def __init__(self):
        # family name -> (type, help, unit, [(suffix, labels, value)])
        self._families: Dict[str, list] = {}

    def add(self, family, mtype, help_text, unit, suffix, labels, value):
        entry = self._families.setdefault(family, [mtype, help_text, unit, []])
        entry[3].append((suffix, labels, value))

    def render(self) -> str:
        lines: List[str] = []
        for family in sorted(self._families):
            mtype, help_text, unit, samples = self._families[family]
            lines.append(f"# TYPE {family} {mtype}")
            if unit:
                lines.append(f"# UNIT {family} {unit}")
            if help_text:
                lines.append(f"# HELP {family} {help_text}")
            for suffix, labels, value in samples:
                lines.append(f"{family}{suffix}{_labels(labels)} {_fmt(value)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _add_histogram(page: _Page, key: str, hist) -> None:
    # lifetime-cumulative bucket counters (LatencyHistogram.cumulative),
    # NOT the recency window: Prometheus rate()/histogram_quantile()
    # require _bucket/_count/_sum to be monotonic counters
    family, unit, labels = _family_for(key)
    bounds, counts, count, total = hist.cumulative()
    help_text = _help_for(key, "latency histogram")
    for le, cum in zip(bounds, counts):
        page.add(family, "histogram", help_text, unit, "_bucket",
                 {**labels, "le": _fmt(le)}, cum)
    page.add(family, "histogram", help_text, unit, "_bucket",
             {**labels, "le": "+Inf"}, count)
    page.add(family, "histogram", help_text, unit, "_count",
             dict(labels), count)
    page.add(family, "histogram", help_text, unit, "_sum",
             dict(labels), total)


def render_openmetrics(metrics, runtime_snapshot: Optional[dict] = None) -> str:
    """One OpenMetrics page for an ``Instrumentation``/``ServingMetrics``
    instance (live object — histograms need their sample windows), with
    the runtime telemetry snapshot merged in when given."""
    page = _Page()

    # build/runtime identity on EVERY page (OpenMetrics info type: family
    # gp_build, sample gp_build_info{...} 1) — the satellite that lets a
    # scrape answer "which package/jax/backend produced these series"
    from spark_gp_tpu.obs.runtime import build_info

    page.add(
        "gp_build", "info",
        _help_for("build", "build/runtime identity"), None, "_info",
        {k: str(v) for k, v in build_info().items()}, 1.0,
    )

    # copy ALL instance state under its lock (the snapshot() discipline):
    # emitters insert first-time keys concurrently, and iterating the live
    # dicts would raise "dictionary changed size during iteration" mid-scrape
    instance_lock = getattr(metrics, "_lock", None)
    with instance_lock if instance_lock is not None else contextlib.nullcontext():
        counters = dict(getattr(metrics, "counters", {}) or {})
        gauges = dict(getattr(metrics, "gauges", {}) or {})
        histograms = dict(getattr(metrics, "histograms", {}) or {})
        timings = dict(getattr(metrics, "timings", {}) or {})
        fit_metrics = dict(getattr(metrics, "metrics", {}) or {})

    for key, value in sorted(counters.items()):
        family, unit, labels = _family_for(key)
        page.add(family, "counter", _help_for(key, "counter"), unit,
                 "_total", labels, value)
    for key, value in sorted(gauges.items()):
        family, unit, labels = _family_for(key)
        page.add(family, "gauge", _help_for(key, "gauge"), unit,
                 "", labels, value)
    for key, hist in sorted(histograms.items()):
        _add_histogram(page, key, hist)  # hist.window() takes its own lock

    for key, value in sorted(timings.items()):
        page.add(
            "gp_phase_seconds", "counter",
            "accumulated wall-clock per instrumentation phase",
            "seconds", "_total", {"phase": key}, value,
        )
    for key, value in sorted(fit_metrics.items()):
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            page.add(
                "gp_fit_metric", "gauge",
                "scalar fit diagnostics (see obs/names.py for keys)",
                None, "", {"key": key}, value,
            )
        else:
            page.add(
                "gp_fit_info", "gauge",
                "non-numeric fit diagnostics as key/value info",
                None, "", {"key": key, "value": str(value)}, 1.0,
            )

    if runtime_snapshot:
        for key, value in sorted(runtime_snapshot.get("counters", {}).items()):
            family, unit, labels = _family_for(key)
            page.add(family, "counter", _help_for(key, "runtime counter"),
                     unit, "_total", labels, value)
        for key, by_entry in sorted(
            runtime_snapshot.get("per_entry", {}).items()
        ):
            family, _, _ = _family_for(key)
            for entry, value in sorted(by_entry.items()):
                page.add(
                    family + "_by_entry", "counter",
                    _help_for(key, "runtime counter") + " (by entry point)",
                    None, "_total", {"entry": entry}, value,
                )
        for key, value in sorted(runtime_snapshot.get("gauges", {}).items()):
            family, unit, labels = _family_for(key)
            page.add(family, "gauge", _help_for(key, "runtime gauge"),
                     unit, "", labels, value)

    return page.render()


class ScrapeListener:
    """Minimal plain-text TCP scrape endpoint for the exposition page.

    Answers ANY request on the socket with an HTTP/1.0 200 carrying the
    freshly-rendered page — enough for ``curl`` and a Prometheus
    ``static_config`` target, with none of http.server's surface.  Bound
    to localhost by design: metrics pages leak operational detail, so
    remote scrape topologies should front this with their own proxy."""

    def __init__(self, render, port: int = 0, host: str = "127.0.0.1"):
        self._render = render  # zero-arg callable -> page text
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(8)
        self._sock.settimeout(0.5)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="gp-metrics-scrape", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with conn:
                try:
                    conn.settimeout(2.0)
                    conn.recv(4096)  # drain the request; content is ignored
                    try:
                        body = self._render()
                        status = "200 OK"
                    except Exception as exc:  # noqa: BLE001 — scrape survives
                        body = f"# render failed: {type(exc).__name__}\n"
                        status = "500 Internal Server Error"
                    payload = body.encode("utf-8")
                    head = (
                        f"HTTP/1.0 {status}\r\n"
                        f"Content-Type: {CONTENT_TYPE}\r\n"
                        f"Content-Length: {len(payload)}\r\n"
                        "Connection: close\r\n\r\n"
                    )
                    conn.sendall(head.encode("ascii") + payload)
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)
