"""Flight recorder + incident bundles: the forensics plane.

The span ring evicts, run journals are written only on successful fit
completion, and serve failures leave nothing but counters — so when a
classified failure finally fires, the evidence of *what led up to it* is
gone.  This module keeps that evidence:

* a **flight recorder** — a bounded, lock-cheap ring of structured
  events, fed automatically by every span event
  (:func:`spark_gp_tpu.obs.trace.add_event` relays here even when no
  span is open), erroring spans, classified-failure observations
  (``resilience/fallback.record_failure``), and the serve metric
  watchlist (shed/breaker/watchdog counters —
  ``serve/metrics.ServingMetrics.inc``).  ``GP_RECORDER=0`` (or
  :func:`set_recording`) turns the feed into a no-op; the bench's
  ``observability.recorder`` section prices the on/off difference and
  ``test_bench_contract`` holds it under 2%;
* **incident bundles** — on a *terminal* classified failure (a fit
  raising out of ``models/common._observed_fit``, a predict ladder
  raising its classified error, a hang-watchdog trip) ONE JSON artifact
  (tmp + atomic rename, the checkpoint writers' convention) is dumped
  into ``GP_INCIDENT_DIR`` / the fit's checkpoint dir /
  ``GP_RUN_JOURNAL_DIR``: the failing span tree, the last-N recorder
  events, the degradation-rung history, compile/memory deltas, build
  provenance and the staged chaos environment — everything a post-mortem
  needs, written at the moment of failure.  Bundles ride the existing
  ``GP_ARTIFACT_RETENTION`` pruning (``obs/runtime.prune_artifacts``).

Successfully-degraded work (a fit that completed through a fallback
rung) does NOT bundle — the run journal already carries its
``degradations`` — and :data:`~spark_gp_tpu.resilience.fallback.UNKNOWN`
failures never bundle: the forensics plane documents what the taxonomy
can name.  Exactly-one-bundle-per-terminal-failure is the invariant
``tools/soak.py`` asserts across seeded chaos campaigns.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: schema version of the incident-bundle JSON (docs/OBSERVABILITY.md)
BUNDLE_FORMAT = "spark_gp_tpu.incident_bundle/v1"

#: keys every schema-valid bundle carries (golden-schema test +
#: tools/gpctl validation read this, so the contract lives in one place)
BUNDLE_REQUIRED_KEYS = (
    "format", "reason", "created_unix", "pid", "trace_id", "failure_class",
    "error", "degradations", "spans", "events", "compiles", "memory",
    "build_info", "chaos", "recorder",
)

#: serve-metric keys relayed into the recorder when they increment (the
#: "metric deltas" feed): the admission/failure story of the minutes
#: before an incident, without recording every request counter
METRIC_WATCH_PREFIXES = (
    "shed", "queue.shed", "queue.poisoned", "timeouts", "breaker.trips",
    "exec.hung", "predict.failures", "lifecycle.", "canary.",
    "registry.evictions", "quality.alerts", "drift.alerts",
)

_seq = itertools.count(1)  # CPython-atomic, like trace._ids

_forced: Optional[bool] = None


def recording_enabled() -> bool:
    """ONE definition of the recorder gate, read at call time (the
    ``tracing_enabled`` convention): ``set_recording`` wins, else
    ``GP_RECORDER`` (default on)."""
    if _forced is not None:
        return _forced
    return os.environ.get("GP_RECORDER", "").strip().lower() not in (
        "0", "off", "false",
    )


def set_recording(enabled: Optional[bool]) -> None:
    """Force the recorder on/off for this process (None = back to env)."""
    global _forced
    _forced = enabled


class FlightRecorder:
    """Thread-safe bounded ring of structured events (oldest evicted).

    An event is one small dict — monotonic ``seq``, wall-clock
    ``t_unix``, emitting ``thread``, ``name``, and the emitter's
    attributes.  Appends are one lock + one deque push; the ring never
    allocates past its bound, so the recorder can run always-on in
    production."""

    def __init__(self, capacity: int = 2048):
        self._buf: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self.dropped = 0  # events evicted by the bound (monotonic)

    def record(self, name: str, **attrs) -> None:
        if not recording_enabled():
            return
        event = {
            "seq": next(_seq),
            "t_unix": time.time(),
            "thread": threading.current_thread().name,
            "name": name,
            **attrs,
        }
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(event)

    def note_metric(self, key: str, value: float) -> None:
        """Watchlist relay for metric increments (``ServingMetrics.inc``):
        only the admission/failure keys land in the ring — recording
        every request counter would evict the events that matter."""
        if not recording_enabled():
            return
        if key.startswith(METRIC_WATCH_PREFIXES):
            self.record(f"metric.{key}", value=float(value))

    def snapshot(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            events = list(self._buf)
        if last is not None and last >= 0:
            events = events[-last:]
        return events

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


def _ring_capacity() -> int:
    # lenient like GP_TRACE_RING: a malformed value must not crash import
    try:
        return int(os.environ.get("GP_RECORDER_RING", "") or 2048)
    except ValueError:
        return 2048


#: THE process-global recorder every feed lands in
RECORDER = FlightRecorder(_ring_capacity())

#: events included in a bundle (the ring may be larger)
BUNDLE_LAST_EVENTS = 256

_INCIDENT_MARK = "_gp_incident_path"


def incident_dir(explicit: Optional[str] = None) -> Optional[str]:
    """Where bundles land: ``GP_INCIDENT_DIR`` (operator redirect) wins,
    then the caller's directory (a fit's checkpoint dir), then
    ``GP_RUN_JOURNAL_DIR``; None disables persistence entirely."""
    for candidate in (
        os.environ.get("GP_INCIDENT_DIR", "").strip() or None,
        explicit,
        os.environ.get("GP_RUN_JOURNAL_DIR", "").strip() or None,
    ):
        if candidate:
            return candidate
    return None


def _chaos_environment() -> Dict[str, str]:
    """The staged chaos knobs at failure time: a seeded soak campaign's
    repro recipe rides the bundle."""
    return {
        key: value
        for key, value in sorted(os.environ.items())
        if key.startswith("GP_CHAOS_") or key == "GP_SEED"
    }


def _span_tree_of(root) -> List[dict]:
    """The failing trace's span tree, sourced from the ROOT span's own
    ``trace_spans`` collection — immune to span-ring eviction, so a
    bundle written after a long fit still contains the failure's own
    span path (the ring-eviction test pins this)."""
    from spark_gp_tpu.obs import trace as obs_trace

    if root is None or not getattr(root, "trace_id", 0):
        return []
    spans = obs_trace.spans_of_root(root)
    tree = obs_trace.span_tree(spans)
    if not tree or tree[0].get("name") != getattr(root, "name", None):
        # the root itself is still open (we are inside its except clause):
        # synthesize it at the head so the tree is rooted correctly
        tree = [{**root.to_dict(), "children": tree}]
    return tree


def already_bundled(exc: Optional[BaseException]) -> Optional[str]:
    """Bundle path a propagating exception was already dumped for, or
    None — the debounce that keeps nested trigger points (a predict
    ladder inside a fit, a ladder error crossing ``_observed_fit``) from
    double-dumping one incident."""
    return getattr(exc, _INCIDENT_MARK, None) if exc is not None else None


def dump_incident(
    reason: str,
    exc: Optional[BaseException] = None,
    failure_class: Optional[str] = None,
    root=None,
    instr=None,
    capture=None,
    directory: Optional[str] = None,
    trace_id: Optional[str] = None,
    extra: Optional[dict] = None,
) -> Optional[dict]:
    """Assemble (and persist, when a directory resolves) ONE incident
    bundle; returns the bundle dict or None when debounced.

    Never raises: forensics must not replace the failure it documents —
    an unwritable directory degrades to an in-memory bundle plus an
    ``incident.bundle_failures`` count, and ANY other assembly failure
    (a span attr whose ``str()`` raises while the runtime is wedged, a
    pathological structure ``json.dump`` rejects) is logged and
    swallowed: the callers are exception shells and the hang-watchdog
    verdict, where an escaping error would replace the classified
    failure or leave the hung batch's futures unanswered.
    """
    try:
        return _dump_incident_inner(
            reason, exc, failure_class, root, instr, capture, directory,
            trace_id, extra,
        )
    except Exception:  # noqa: BLE001 — see docstring: never raises
        import logging

        logging.getLogger("spark_gp_tpu").warning(
            "incident bundle assembly failed for %r", reason, exc_info=True
        )
        try:
            from spark_gp_tpu.obs.runtime import telemetry

            telemetry.inc("incident.bundle_failures")
        except Exception:  # noqa: BLE001 — counting is best-effort too
            pass
        return None


def _dump_incident_inner(
    reason, exc, failure_class, root, instr, capture, directory, trace_id,
    extra,
) -> Optional[dict]:
    if already_bundled(exc) is not None:
        return None
    from spark_gp_tpu.obs import runtime as obs_runtime
    from spark_gp_tpu.obs import trace as obs_trace

    if capture is not None:
        capture.finish()  # idempotent: the bundle needs the deltas NOW
    if trace_id is None:
        trace_id = obs_runtime.active_trace_token()
    degradations = []
    for source in (exc, instr):
        got = list(getattr(source, "degradations", []) or [])
        if got:
            degradations = got
            break
    telemetry_snap = obs_runtime.telemetry.snapshot()
    bundle = {
        "format": BUNDLE_FORMAT,
        "reason": reason,
        "created_unix": time.time(),
        "pid": os.getpid(),
        "trace_id": trace_id,
        "failure_class": failure_class,
        "error": (
            None if exc is None
            else f"{type(exc).__name__}: {exc}"[:500]
        ),
        "degradations": degradations,
        # predicted-vs-actual on OOM: the memory planner's decision rows
        # (predicted bytes, budget, chosen config — memplan.py) next to
        # the measured memory gauges below, so a plan that admitted a
        # dispatch the runtime then killed is readable evidence
        "memory_plan": list(getattr(instr, "memory_plan", []) or []),
        "spans": _span_tree_of(root),
        "events": RECORDER.snapshot(last=BUNDLE_LAST_EVENTS),
        "compiles": (
            dict(capture.compiles) if capture is not None
            else dict(telemetry_snap["counters"])
        ),
        "memory": {
            "samples": (
                list(capture.memory_samples) if capture is not None else []
            ),
            "gauges": dict(telemetry_snap["gauges"]),
        },
        "timings": dict(getattr(instr, "timings", {}) or {}),
        "metrics": {
            k: v for k, v in (getattr(instr, "metrics", {}) or {}).items()
            if isinstance(v, (int, float, str, bool))
        },
        "build_info": obs_runtime.build_info(),
        "chaos": _chaos_environment(),
        "recorder": {
            "dropped": RECORDER.dropped,
            "capacity": RECORDER._buf.maxlen,
        },
        "path": None,
        **(extra or {}),
    }
    # one emission: add_event relays into THIS recorder too (trace.py),
    # so a second explicit record would double-log every incident
    obs_trace.add_event(
        "incident.bundle", reason=reason, failure_class=failure_class
    )
    target = incident_dir(directory)
    if target is not None:
        try:
            os.makedirs(target, exist_ok=True)
            tag = f"{int(bundle['created_unix'] * 1000):d}-p{os.getpid()}"
            path = os.path.join(
                target, f"incident_{reason.replace('.', '_')}-{tag}.json"
            )
            from spark_gp_tpu.utils.checkpoint import _fsync_replace

            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(bundle, fh, default=str)
            _fsync_replace(tmp, path)
            bundle["path"] = path
            obs_runtime.prune_artifacts(target, protect=path)
        except OSError:
            obs_runtime.telemetry.inc("incident.bundle_failures")
            import logging

            logging.getLogger("spark_gp_tpu").warning(
                "incident bundle not persisted to %r", target, exc_info=True
            )
    obs_runtime.telemetry.inc("incident.bundles")
    if exc is not None:
        try:
            setattr(exc, _INCIDENT_MARK, bundle["path"] or "<unpersisted>")
        except (AttributeError, TypeError):
            pass  # slotted/frozen exception: worst case is a second bundle
    return bundle


def record_fit_failure(
    exc: BaseException,
    entry: str,
    instr=None,
    root=None,
    capture=None,
    directory: Optional[str] = None,
) -> Optional[dict]:
    """The fit entry points' bundle trigger (``common._observed_fit``):
    dump for terminal CLASSIFIED failures and for
    ``DegradationExhaustedError`` (whose class may be ``unknown`` when a
    rung itself broke — the history is the evidence); anything the
    taxonomy cannot name stays bundle-free."""
    from spark_gp_tpu.resilience import fallback

    cls = fallback.classify_failure(exc)
    if cls == fallback.UNKNOWN and not isinstance(
        exc, fallback.DegradationExhaustedError
    ):
        return None
    return dump_incident(
        reason=entry, exc=exc, failure_class=cls, root=root, instr=instr,
        capture=capture, directory=directory,
    )


def validate_bundle(bundle: dict) -> List[str]:
    """Schema check shared by tests, ``tools/gpctl`` and the soak
    invariant: returns the list of problems (empty = valid)."""
    problems = []
    if bundle.get("format") != BUNDLE_FORMAT:
        problems.append(f"format is {bundle.get('format')!r}")
    for key in BUNDLE_REQUIRED_KEYS:
        if key not in bundle:
            problems.append(f"missing required key {key!r}")
    if not isinstance(bundle.get("events"), list):
        problems.append("events is not a list")
    if not isinstance(bundle.get("spans"), list):
        problems.append("spans is not a list")
    if not isinstance(bundle.get("degradations"), list):
        problems.append("degradations is not a list")
    return problems
