"""Context-var span tracer: one tree per fit or serve request.

The telemetry islands this unifies each see a sliver — ``Instrumentation``
sees phase wall-clocks, ``ServingMetrics`` sees counters — but neither can
answer "what did THIS fit (or THIS batch) actually do, in order, with what
attributes?".  Spans can: a span is a named, timed, attributed interval;
spans nest through a :mod:`contextvars` context variable (thread- and
task-local, so the serve batcher thread and the submit thread each get
their own stack); finished spans land in a process-global ring buffer
from which a whole trace is reassembled by ``trace_id``.

Cost discipline: the tracer must stay out of the hot loop (the bench's
``observability`` section asserts <2% overhead on fit and serve_predict).
Span creation is one object + one contextvar set/reset; there is NO
tracing inside per-request or per-iteration code — only coarse units
(fit phases, micro-batches) open spans.  ``GP_TRACING=0`` (or
:func:`set_tracing`) turns the whole layer into no-ops.

Exports: :func:`export_jsonl` (one span per line) and
:func:`chrome_trace` — the Chrome/Perfetto ``trace_event`` JSON that
``chrome://tracing`` / https://ui.perfetto.dev render as a timeline.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

# the flight recorder is the span events' second sink (obs/recorder.py);
# it has no top-level obs imports, so this cannot cycle
from spark_gp_tpu.obs.recorder import RECORDER as _RECORDER

_ids = itertools.count(1)  # CPython-atomic; no lock needed

_current: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "gp_obs_current_span", default=None
)

_forced: Optional[bool] = None


def tracing_enabled() -> bool:
    """ONE definition of the tracer gate, read at call time (like
    ``GP_SYNC_PHASES``): ``set_tracing`` wins, else ``GP_TRACING`` (any
    value but ``0``/``off``/``false`` — default on)."""
    if _forced is not None:
        return _forced
    return os.environ.get("GP_TRACING", "").strip().lower() not in (
        "0", "off", "false",
    )


def set_tracing(enabled: Optional[bool]) -> None:
    """Force the tracer on/off for this process (None = back to the env)."""
    global _forced
    _forced = enabled


class Span:
    """One finished-or-running interval of a trace tree.

    A slotted plain class, not a dataclass: span creation sits on the
    serve batch path (two spans per micro-batch) and the bench's <2%
    overhead contract prices every microsecond of it."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "root", "start_unix",
        "start", "thread", "attrs", "events", "duration_s", "status",
        # the trace's root Span object; finished spans register themselves
        # on root.trace_spans, so reassembling ONE trace (the run journal)
        # is O(trace) instead of an O(ring) scan
        "root_span", "trace_spans",
    )

    def __init__(
        self, name, trace_id, span_id, parent_id, root, start_unix, start,
        thread, attrs=None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.root = root
        self.start_unix = start_unix
        self.start = start
        self.thread = thread
        self.attrs: Dict[str, Any] = attrs if attrs is not None else {}
        self.events: List[dict] = []
        self.duration_s: Optional[float] = None
        self.status = "ok"
        self.root_span: Optional["Span"] = None
        self.trace_spans: Optional[List["Span"]] = None

    def add_event(self, name: str, **attrs) -> None:
        self.events.append(
            {"name": name, "t_unix": time.time(), **attrs}
        )

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_unix": self.start_unix,
            "duration_s": self.duration_s,
            "thread": self.thread,
            "status": self.status,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class _NoopSpan:
    """What :func:`span` yields when tracing is off: absorbs the span API
    at zero cost, never enters the ring."""

    name = ""
    trace_id = 0
    span_id = 0
    parent_id = None
    root = ""
    events: List[dict] = []
    attrs: Dict[str, Any] = {}

    def add_event(self, name: str, **attrs) -> None:
        pass

    def set_attr(self, key: str, value) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class SpanRing:
    """Thread-safe bounded buffer of finished spans (oldest evicted)."""

    def __init__(self, capacity: int = 4096):
        self._buf: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self.dropped = 0  # spans evicted by the bound (monotonic)

    def append(self, span: Span) -> None:
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(span)

    def snapshot(self, trace_id: Optional[int] = None) -> List[Span]:
        with self._lock:
            spans = list(self._buf)
        if trace_id is None:
            return spans
        return [s for s in spans if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


def _ring_capacity() -> int:
    # lenient like every other env knob (GP_TRACING, GP_SYNC_PHASES): a
    # malformed value must not crash `import spark_gp_tpu`
    try:
        return int(os.environ.get("GP_TRACE_RING", "") or 4096)
    except ValueError:
        return 4096


#: the process-global buffer every finished span lands in
RING = SpanRing(_ring_capacity())


class span:
    """Open a span: child of the context's current span, or a new trace
    root.  ``with span(name, **attrs) as s:`` yields the :class:`Span`
    (a no-op stub when tracing is off); an escaping exception marks
    ``status="error"`` and re-raises.

    A hand-rolled context manager (not ``@contextmanager``): the
    generator protocol costs several microseconds per use, which at two
    spans per serve micro-batch is real money against the <2% bar."""

    __slots__ = ("_name", "_attrs", "_span", "_token")

    def __init__(self, name: str, **attrs):
        self._name = name
        self._attrs = attrs
        self._span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> "Span":
        if not tracing_enabled():
            return NOOP_SPAN
        parent = _current.get()
        if parent is not None:
            s = Span(
                self._name, parent.trace_id, next(_ids), parent.span_id,
                parent.root, time.time(), time.perf_counter(),
                threading.current_thread().name, self._attrs,
            )
            s.root_span = parent.root_span
        else:
            s = Span(
                self._name, next(_ids), next(_ids), None, self._name,
                time.time(), time.perf_counter(),
                threading.current_thread().name, self._attrs,
            )
            s.root_span = s
            s.trace_spans = []
        self._span = s
        self._token = _current.set(s)
        return s

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._span
        if s is None:  # tracing was off at __enter__
            return False
        if exc_type is not None:
            s.status = "error"
            s.add_event("error", type=exc_type.__name__)
            # erroring spans feed the flight recorder: failure-path-only
            # cost, and the incident bundle's event log then shows WHICH
            # unit of work broke even after the span ring evicts
            _RECORDER.record("error", span=s.name, type=exc_type.__name__)
        s.duration_s = time.perf_counter() - s.start
        _current.reset(self._token)
        root_list = s.root_span.trace_spans
        if root_list is not None:
            root_list.append(s)
        RING.append(s)
        return False


def current_span() -> Optional[Span]:
    """The context's active span, or None (tracing off / no span open)."""
    return _current.get()


def add_event(name: str, **attrs) -> bool:
    """Attach a timestamped event to the current span; False (dropped
    from the SPAN) when no span is open — event emitters never need
    their own guard.  Every event is additionally relayed into the
    flight recorder (:mod:`spark_gp_tpu.obs.recorder`) whether or not a
    span is open: the recorder is the incident bundle's event log, and a
    breaker trip on a span-less thread must still leave evidence."""
    _RECORDER.record(name, **attrs)
    s = _current.get()
    if s is None:
        return False
    s.add_event(name, **attrs)
    return True


def current_root_name() -> Optional[str]:
    """Root-span name of the active trace (the compile-attribution entry
    point), or None outside any span."""
    s = _current.get()
    return s.root if s is not None else None


# -- reassembly + export ----------------------------------------------------


def spans_for_trace(trace_id: int) -> List[Span]:
    """Every retained span of one trace, in start order (ring scan — for
    ad-hoc queries; a caller holding the ROOT span should use
    :func:`spans_of_root`, which is O(trace))."""
    return sorted(RING.snapshot(trace_id), key=lambda s: s.start)


def spans_of_root(root: Span) -> List[Span]:
    """The finished spans of ``root``'s trace, in start order — collected
    on the root itself, immune to ring eviction and ring size."""
    if getattr(root, "trace_spans", None) is None:
        return []
    return sorted(root.trace_spans, key=lambda s: s.start)


def span_tree(spans: List[Span]) -> List[dict]:
    """Nest a flat span list into ``[{..., "children": [...]}]`` roots.

    A span whose parent was evicted from the ring becomes a root — the
    tree degrades, it never drops spans silently."""
    nodes = {s.span_id: {**s.to_dict(), "children": []} for s in spans}
    roots = []
    for s in sorted(spans, key=lambda s: s.start):
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id) if s.parent_id is not None else None
        (parent["children"] if parent is not None else roots).append(node)
    return roots


def export_jsonl(path: str, spans: Optional[List[Span]] = None) -> int:
    """Write spans (default: the whole ring) as JSON lines; returns the
    span count.  Attr values that aren't JSON types degrade to ``str``."""
    spans = RING.snapshot() if spans is None else spans
    with open(path, "w", encoding="utf-8") as fh:
        for s in spans:
            fh.write(json.dumps(s.to_dict(), default=str) + "\n")
    return len(spans)


def chrome_trace(spans: Optional[List[Span]] = None) -> dict:
    """Chrome/Perfetto ``trace_event`` document: spans as complete
    (``"ph": "X"``) events, span events as instants (``"ph": "i"``),
    plus ``process_name``/``thread_name`` metadata (``"ph": "M"``) so
    Perfetto renders named lanes — the fit driver, the serve batcher,
    the watchdog — instead of bare tids."""
    spans = RING.snapshot() if spans is None else spans
    pid = os.getpid()
    tids = {}
    events = []
    for s in spans:
        tid = tids.setdefault(s.thread, len(tids) + 1)
        events.append({
            "name": s.name,
            "cat": s.root,
            "ph": "X",
            "ts": s.start_unix * 1e6,
            "dur": (s.duration_s or 0.0) * 1e6,
            "pid": pid,
            "tid": tid,
            "args": {k: str(v) for k, v in s.attrs.items()},
        })
        for e in s.events:
            events.append({
                "name": e["name"],
                "cat": s.root,
                "ph": "i",
                "s": "t",
                "ts": e["t_unix"] * 1e6,
                "pid": pid,
                "tid": tid,
                "args": {
                    k: str(v) for k, v in e.items()
                    if k not in ("name", "t_unix")
                },
            })
    # metadata events FIRST (the trace_event spec allows any position,
    # but naming the lanes up front renders correctly in every viewer):
    # one process_name carrying the pid, one thread_name per lane
    meta = [{
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": f"spark_gp_tpu p{pid}"},
    }]
    for thread_name, tid in sorted(tids.items(), key=lambda kv: kv[1]):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": thread_name},
        })
    return {
        "traceEvents": meta + events,
        "metadata": {
            "threads": {str(v): k for k, v in tids.items()},
            "spans_dropped": RING.dropped,
        },
    }


def export_chrome_trace(path: str, spans: Optional[List[Span]] = None) -> int:
    """``chrome_trace`` straight to a file; returns the event count."""
    doc = chrome_trace(spans)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh)
    return len(doc["traceEvents"])
