"""THE metric-name catalog: every key the package emits, in one place.

Dashboards die by rename: a counter that silently becomes
``queue.shed.deadline_v2`` leaves its panel flatlining at zero while the
alert it fed goes quiet.  The contract here is mechanical: every
metric/counter/gauge/histogram/phase key emitted anywhere in
``spark_gp_tpu`` must (a) be dot-separated lowercase
(``[a-z0-9_]+(\\.[a-z0-9_]+)*``) and (b) appear in :data:`CATALOG` —
``tools/check_metric_names.py`` walks the package AST and fails CI on
any emission that breaks either rule (tier-1 wrapper:
``tests/test_observability.py``).

Dynamic keys register as ``*`` patterns (``restart_*_nll``,
``breaker.open.*``); the wildcard part is runtime data (a restart index,
a model name) and exempt from the lowercase grammar.  A pattern may name
the Prometheus ``label`` the wildcard maps to, which is how
:mod:`spark_gp_tpu.obs.expo` renders ``breaker.open.mymodel`` as
``gp_breaker_open{model="mymodel"}`` instead of minting one metric
family per model.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass
from typing import Optional, Tuple

#: concrete-key grammar: lowercase [a-z0-9_] components, dot-separated
KEY_GRAMMAR = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*$")
#: pattern grammar: same, plus ``*`` wildcards for runtime-data parts
PATTERN_GRAMMAR = re.compile(r"^[a-z0-9_*]+(\.[a-z0-9_*]+)*$")


@dataclass(frozen=True)
class MetricName:
    """One registered key (or ``*`` pattern) and how to expose it."""

    key: str
    #: counter | gauge | histogram | metric (fit scalar) | phase (timing)
    #: | event (span/recorder event name) | info (label-only identity)
    kind: str
    help: str
    #: for patterns: the exposition label the wildcard part becomes
    label: Optional[str] = None
    #: histogram bucket upper bounds override (expo picks a default ladder)
    buckets: Optional[Tuple[float, ...]] = None


CATALOG: Tuple[MetricName, ...] = (
    # -- serve counters (ServingMetrics.inc) ------------------------------
    MetricName("requests", "counter", "predict requests admitted at submit"),
    MetricName("requests_rows", "counter", "input rows across admitted requests"),
    MetricName("batches", "counter", "micro-batches dispatched"),
    MetricName("padded_rows", "counter", "bucket-padding rows dispatched beyond request rows"),
    MetricName("timeouts", "counter", "requests shed for any deadline reason (aggregate)"),
    MetricName("shed", "counter", "submits rejected at the door (aggregate)"),
    MetricName("queue.shed.deadline", "counter", "requests whose deadline expired while queued"),
    MetricName("queue.shed.backpressure", "counter", "submits rejected on a full queue"),
    MetricName("queue.shed.draining", "counter", "submits rejected while the server drains for shutdown"),
    MetricName("queue.shed.memory", "counter", "low-priority submits shed by the memory admission gate"),
    MetricName("queue.poisoned", "counter", "requests isolated as poisoned after a batch failure"),
    MetricName("shed.breaker", "counter", "submits rejected while a model's breaker was open"),
    MetricName("shed.poison", "counter", "submits rejected for non-finite payloads"),
    MetricName("predict.failures", "counter", "raising compiled predicts"),
    MetricName("breaker.trips", "counter", "circuit-breaker open transitions"),
    MetricName("compiles", "counter", "XLA bucket compiles paid at registry warmup"),
    MetricName("models_loaded", "counter", "registry loads"),
    MetricName("models_reloaded", "counter", "registry hot-swap reloads"),
    MetricName("registry.evictions", "counter", "retired model versions unloaded (compiled caches freed)"),
    MetricName("exec.hung", "counter", "dispatches failed by the hang watchdog"),
    # -- serve lifecycle (serve/lifecycle.py) ------------------------------
    MetricName("lifecycle.drains", "counter", "drain sequences begun (SIGTERM/SIGINT or explicit)"),
    MetricName("lifecycle.watchdog_trips", "counter", "hang-watchdog verdicts fired"),
    MetricName("lifecycle.draining", "gauge", "1 while the server is draining"),
    MetricName("lifecycle.memory_pressure", "gauge", "1 while the memory admission gate is shedding"),
    MetricName("lifecycle.drain_s", "histogram", "seconds from drain begin to stopped"),
    MetricName("canary.starts", "counter", "canary rollouts begun"),
    MetricName("canary.routed", "counter", "default-traffic requests routed to a canary candidate"),
    MetricName("canary.shadow_scores", "counter", "candidate answers shadow-scored against the incumbent"),
    MetricName("canary.breaches", "counter", "shadow scores past the guard bar"),
    MetricName("canary.errors", "counter", "raising candidate dispatches during a canary"),
    MetricName("canary.promotions", "counter", "canaries auto-promoted to latest"),
    MetricName("canary.rollbacks", "counter", "canaries auto-rolled-back and quarantined"),
    MetricName("canary.active.*", "gauge", "1 while the model has an active canary", label="model"),
    # -- serve gauges ------------------------------------------------------
    MetricName("queue_depth", "gauge", "requests currently queued"),
    MetricName("breaker.open.*", "gauge", "1 while the model's breaker is open", label="model"),
    # -- serve histograms (ServingMetrics.observe) -------------------------
    MetricName("batch_rows", "histogram", "rows per dispatched micro-batch"),
    MetricName("batch_requests", "histogram", "requests coalesced per micro-batch"),
    MetricName("batch_occupancy", "histogram", "request rows / padded bucket rows"),
    MetricName("batch_predict_s", "histogram", "device predict seconds per batch"),
    MetricName("request_latency_s", "histogram", "submit-to-answer seconds per request"),
    # -- fit metrics (Instrumentation.log_metric) --------------------------
    MetricName("num_experts", "metric", "experts in the grouped stack"),
    MetricName("expert_size", "metric", "rows per expert"),
    MetricName("num_classes", "metric", "classes inferred from training labels"),
    MetricName("final_nll", "metric", "optimizer's final objective value"),
    MetricName("final_nll_renormalized", "metric", "final_nll * bcm_renorm (full-stack comparable)"),
    MetricName("lbfgs_iters", "metric", "L-BFGS iterations"),
    MetricName("lbfgs_nfev", "metric", "objective evaluations"),
    MetricName("lbfgs_stalled", "metric", "1 when the line search exhausted before convergence"),
    MetricName("num_restarts", "metric", "multi-start restarts configured"),
    MetricName("best_restart", "metric", "winning restart index"),
    MetricName("restart_*_nll", "metric", "per-restart final NLL", label="restart"),
    MetricName("resumed_from_iteration", "metric", "checkpoint resume point"),
    MetricName("experts_active_initial", "metric", "active experts before any quarantine"),
    MetricName("experts_quarantined", "metric", "experts dropped by screen/recovery"),
    MetricName("experts_jittered", "metric", "experts repaired by adaptive jitter"),
    MetricName("fit_retries", "metric", "recovery re-dispatches of the fit"),
    MetricName("bcm_renorm", "metric", "E_active / E_kept BCM renormalization factor"),
    MetricName("precision_lane", "metric", "precision lane the fit ran at (strict/mixed/fast)"),
    MetricName("solver_lane", "metric", "solver lane the fit engaged (exact/iterative/matfree — ops/iterative.py, auto resolved)"),
    MetricName("solver.cg_iters", "metric", "iterative lane: max live CG iterations on the post-fit convergence probe"),
    MetricName("solver.precond_rank", "metric", "iterative lane: pivoted-Cholesky preconditioner rank k"),
    MetricName("solver.probes", "metric", "iterative lane: Hutchinson/SLQ probe vectors per log-det estimate"),
    MetricName("solver.residual", "metric", "iterative lane: max relative CG residual at the fitted theta (matfree fits probe through the same injected streamed matvec the fit ran)"),
    MetricName("solver.matfree_engaged", "metric", "1 when the matrix-free streamed-matvec lane executed the fit (0: matfree requested but the kernel carries no matvec — materialized fallback ran)"),
    MetricName("solver.matvec_tiles", "metric", "matfree lane: row panels per streamed gram.vector pass (ceil(s / GP_MATVEC_TILE))"),
    MetricName("gram_cache_engaged", "metric", "1 when the theta-invariant gram cache served the fit hot loop"),
    MetricName("agg.policy", "metric", "expert aggregation policy the fit engaged (poe/gpoe/rbcm/healed — models/aggregation.py)"),
    MetricName("agg.effective_experts", "metric", "participation ratio (sum w)^2 / sum w^2 of the per-expert weights"),
    MetricName("agg.selection_dropped", "metric", "experts masked out by fit-time redundancy selection"),
    MetricName("agg.renorm", "metric", "E_active / sum(w) weighted renormalization factor (quarantine.renorm_factor generalized)"),
    MetricName("mixed_precision_guard.delta_nll_rel", "metric", "guard: relative NLL delta vs strict"),
    MetricName("mixed_precision_guard.delta_grad_rel", "metric", "guard: relative gradient delta vs strict"),
    MetricName("mixed_precision_guard.delta_predict_rel", "metric", "guard: relative predict delta vs strict"),
    MetricName("mixed_precision_guard.breach", "metric", "guard: 1 when a delta exceeded the lane bar"),
    MetricName("*.failed", "metric", "a phase of this name raised", label="phase"),
    # -- memory planning (resilience/memplan.py) ---------------------------
    MetricName("plan.hit", "counter", "plan decisions whose chosen configuration was predicted-safe"),
    MetricName("plan.miss", "counter", "reactive recovery engaged despite (or no config fit) a plan decision"),
    MetricName("plan.shed", "counter", "serve submits shed on predicted-per-request bytes vs headroom"),
    MetricName("plan.margin_breach", "counter", "measured peaks that exceeded the margined prediction"),
    # -- degradation ladder (resilience/fallback.py) -----------------------
    MetricName("fallback.engaged", "metric", "1 when the fit completed through at least one degradation rung"),
    MetricName("fallback.transitions", "counter", "degradation-ladder rung transitions executed"),
    MetricName("fallback.exhausted", "counter", "ladders that ran out of applicable rungs (classified error raised)"),
    MetricName("fallback.rung.*", "counter", "transitions into this rung", label="rung"),
    MetricName("fallback.failures.*", "counter", "classified execution failures observed (closed taxonomy)", label="failure_class"),
    # -- phases (Instrumentation.phase -> timings) -------------------------
    MetricName("group_experts", "phase", "host grouping + pre-fit data screen"),
    MetricName("optimize_hypers", "phase", "hyperparameter optimization"),
    MetricName("active_set", "phase", "active-set provider selection"),
    MetricName("kmn_stats", "phase", "distributed (U1, u2) accumulation"),
    MetricName("magic_solve", "phase", "host f64 PPA magic solve"),
    MetricName("sync_fetch", "phase", "deferred device fetch draining the async pipeline"),
    MetricName("load.*", "phase", "registry model load", label="model"),
    MetricName("warmup.*", "phase", "registry AOT bucket warmup", label="model"),
    # -- runtime telemetry (obs/runtime.py) --------------------------------
    MetricName("compile.traces", "counter", "jaxpr traces observed (each implies a compile dispatch)"),
    MetricName("compile.backend", "counter", "XLA backend compiles (persistent-cache misses)"),
    MetricName("compile.cache_hits", "counter", "persistent compilation cache hits"),
    MetricName("compile.bucket_traces", "counter", "serve bucket executable traces (batcher guard)"),
    MetricName("compile.recompile_guard_trips", "counter", "recompiles caught on a frozen serve surface"),
    MetricName("memory.bytes_in_use", "gauge", "device HBM bytes in use at the last sample"),
    MetricName("memory.peak_bytes_in_use", "gauge", "peak device HBM bytes in use"),
    MetricName("memory.host_peak_rss_bytes", "gauge", "host process peak RSS (CPU fallback proxy)"),
    # -- multi-host coordination (parallel/coord.py) -----------------------
    MetricName("coord.degraded", "counter", "distributed.initialize silently degraded to single-process"),
    MetricName("coord.heartbeats", "counter", "liveness stamps this process published"),
    MetricName("coord.stragglers", "counter", "peers flagged straggling (stale heartbeat)"),
    MetricName("coord.dead_hosts", "counter", "peers declared dead (heartbeat past the dead threshold)"),
    MetricName("coord.barrier_timeouts", "counter", "deadline-guarded coordination steps that timed out"),
    MetricName("coord.checkpoints", "counter", "coordinated checkpoint saves completed"),
    MetricName("coord.elastic_resumes", "counter", "resumes under a different process count than the save"),
    MetricName("coord.preemptions", "counter", "SIGTERM preemption signals observed by the watcher"),
    # -- serving fleet (serve/fleet.py, serve/router.py) -------------------
    MetricName("router.requests", "counter", "logical predict requests entering the fleet router"),
    MetricName("router.failovers", "counter", "re-dispatches onto the next ring replica after a classified replica failure"),
    MetricName("router.hedges", "counter", "hedged duplicate dispatches launched against straggling replicas"),
    MetricName("router.hedge_wins", "counter", "hedged dispatches that answered before the primary"),
    MetricName("router.failed", "counter", "router requests that exhausted failover or their deadline"),
    MetricName("router.rebuilds", "counter", "routing views rebuilt from the KV membership (router start/restart)"),
    MetricName("router.replica_errors.*", "counter", "failover-eligible errors observed per replica", label="replica"),
    MetricName("router.request_latency_s", "histogram", "router submit-to-answer seconds (failover/hedging included)"),
    MetricName("fleet.joins", "counter", "replica registrations recorded in fleet membership"),
    MetricName("fleet.leaves", "counter", "replica deregistrations recorded in fleet membership"),
    MetricName("fleet.replica_stragglers", "counter", "replicas flagged straggling by the fleet heartbeat ledger"),
    MetricName("fleet.replica_deaths", "counter", "replicas declared dead by the fleet heartbeat ledger"),
    MetricName("fleet.canary_promotions", "counter", "fleet-wide canary verdicts that promoted on every replica"),
    MetricName("fleet.canary_rollbacks", "counter", "fleet-wide canary verdicts that rolled back on every replica"),
    MetricName("fleet.replicas_live", "gauge", "serving (non-dead) replicas in the routing view"),
    MetricName("fleet.replicas_draining", "gauge", "replicas draining out of the ring"),
    MetricName("fleet.replicas_dead", "gauge", "replicas evicted by heartbeat verdict"),
    MetricName("fleet.generation", "gauge", "membership generation of the current routing view"),
    MetricName("fleet.scale_up", "gauge", "1 while aggregated queue/memory pressure asks for another replica"),
    MetricName("fleet.queue_pressure.*", "gauge", "per-replica queue depth / capacity", label="replica"),
    MetricName("fleet.memory_shedding.*", "gauge", "1 while the replica's memory admission gate sheds", label="replica"),
    # -- statistical health plane (obs/quality.py) -------------------------
    MetricName("quality.observations", "counter", "ground-truth labels joined to served predictions"),
    MetricName("quality.observe.unknown_request", "counter", "observations naming a request_id with no pending prediction"),
    MetricName("quality.observe.duplicate", "counter", "idempotent re-observations of an already-joined request_id"),
    MetricName("quality.windows", "counter", "calibration verdict windows closed"),
    MetricName("quality.alerts", "counter", "sustained-miscalibration alerts raised"),
    MetricName("drift.windows", "counter", "input-drift verdict windows closed"),
    MetricName("drift.alerts", "counter", "sustained-input-drift alerts raised"),
    MetricName("quality.alert.*", "gauge", "1 while the model has an active miscalibration alert", label="model"),
    MetricName("quality.z_mean.*", "gauge", "lifetime mean standardized residual of graded predictions", label="model"),
    MetricName("quality.z_std.*", "gauge", "lifetime std of standardized residuals (1.0 = calibrated)", label="model"),
    MetricName("quality.nll_mean.*", "gauge", "lifetime mean predictive NLL of graded predictions", label="model"),
    MetricName("quality.coverage_50.*", "gauge", "empirical coverage of the nominal 50% central interval", label="model"),
    MetricName("quality.coverage_90.*", "gauge", "empirical coverage of the nominal 90% central interval", label="model"),
    MetricName("quality.coverage_99.*", "gauge", "empirical coverage of the nominal 99% central interval", label="model"),
    MetricName("quality.pending_depth.*", "gauge", "predictions parked awaiting delayed labels", label="model"),
    MetricName("drift.alert.*", "gauge", "1 while the model has an active input-drift alert", label="model"),
    MetricName("drift.score.*", "gauge", "last drift window's max per-dim mean shift in train-std units", label="model"),
    MetricName("fleet.quality_alert.*", "gauge", "1 while the replica reports any active quality/drift alert", label="replica"),
    MetricName("router.observes", "counter", "observations forwarded to the replica that answered the request"),
    # fit-time per-expert quality telemetry (models/common.py)
    MetricName("expert_quality.nll_spread", "metric", "max - min per-expert NLL at theta* (marginal proxy, active experts)"),
    MetricName("expert_quality.nll_std", "metric", "std of per-expert NLL at theta* across active experts"),
    MetricName("expert_quality.jitter_max", "metric", "largest per-expert adaptive-jitter level the fit settled on"),
    MetricName("expert_quality.weight_min", "metric", "smallest per-expert effective BCM weight (0 = quarantined)"),
    # -- numerical integrity plane (resilience/integrity.py) ---------------
    # each verdict also emits a same-named span/recorder event (integrity.
    # _emit), covered by the integrity.* pattern at the end of this block
    MetricName("integrity.attestation_failures", "counter", "published collective payloads failing digest/identity/replay attestation"),
    MetricName("integrity.bounds_violations", "counter", "finite collective contributions past the GP_INTEGRITY_MAX_ABS magnitude bar"),
    MetricName("integrity.panel_checks", "counter", "replicated Cholesky diagonal panels cross-compared across devices"),
    MetricName("integrity.panel_mismatch", "counter", "checked panels diverging across devices (an SDC verdict with the device named)"),
    MetricName("integrity.spot_checks", "counter", "duplicate-dispatch spot checks executed during DCN-fallback fits"),
    MetricName("integrity.spot_check_disagreements", "counter", "spot checks where a recompute contradicted a host's published claim"),
    MetricName("integrity.host_suspect", "counter", "trust-ledger hosts escalated trusted -> suspect"),
    MetricName("integrity.host_quarantined", "counter", "trust-ledger hosts quarantined (definitive verdict or strikes exhausted)"),
    MetricName("integrity.replica_suspect", "counter", "serve replicas striked suspect by cross-replica answer verification"),
    MetricName("integrity.replica_mismatch", "counter", "verified router answers where two replicas' (mean, var) disagreed"),
    MetricName("integrity.replica_evicted", "counter", "replicas evicted from the routing ring on sustained answer mismatch"),
    MetricName("integrity.artifact_verified", "counter", "model artifacts whose sha256 sidecar verified on load"),
    MetricName("integrity.artifact_corrupt", "counter", "model artifacts refused on sidecar digest mismatch"),
    MetricName("integrity.corrupt_payload", "event", "an allgather payload failed attestation (publishing pid + code attributed)"),
    MetricName("integrity.bounds_violation", "event", "a host's collective contribution breached the magnitude attestation bar"),
    MetricName("integrity.*", "counter", "integrity verdict by kind (counter + span/recorder event twin — resilience/integrity._emit)", label="kind"),
    MetricName("router.verifications", "counter", "answered router requests cross-checked against a second replica"),
    MetricName("fleet.replicas_evicted", "gauge", "replicas currently evicted from the ring by the integrity plane"),
    # -- forensics plane (obs/recorder.py, obs/cost.py) --------------------
    MetricName("incident.bundles", "counter", "incident bundles assembled on terminal classified failures"),
    MetricName("incident.bundle_failures", "counter", "incident bundles that could not be persisted"),
    MetricName("xla.flops.*", "counter", "measured XLA flops executed per entry point (compiled.cost_analysis)", label="entry"),
    MetricName("xla.bytes.*", "counter", "measured XLA bytes accessed per entry point (compiled.cost_analysis)", label="entry"),
    MetricName("xla.cost_failures", "counter", "cost_analysis lowerings that failed (metering skipped)"),
    MetricName("build", "info", "build/runtime identity (package, jax, backend, lane, process count)"),
    # -- span/recorder event names (trace.add_event / RECORDER.record) -----
    # registered so tools/check_metric_names.py pins every emitted event
    # name, exactly like metric keys: a renamed event silently empties the
    # journal/bundle queries that grep for it
    MetricName("error", "event", "span closed with an escaping exception"),
    MetricName("experts.quarantined", "event", "experts dropped by screen/recovery"),
    MetricName("experts.jittered", "event", "experts repaired by adaptive jitter"),
    MetricName("experts.deselected", "event", "redundant experts dropped/down-weighted by aggregation selection"),
    MetricName("fit.retry", "event", "recovery re-dispatch of a fit attempt"),
    MetricName("fallback.failure", "event", "classified execution failure observed"),
    MetricName("plan.decision", "event", "memory-plan admission decision (chosen config, predicted bytes, budget)"),
    MetricName("compile.trace", "event", "jaxpr trace observed on the current span"),
    MetricName("breaker.open", "event", "circuit breaker opened"),
    MetricName("breaker.close", "event", "circuit breaker closed"),
    MetricName("breaker.reject", "event", "dispatch rejected by an open breaker"),
    MetricName("queue.isolation", "event", "poisoned batch re-executed singly"),
    MetricName("canary.start", "event", "canary rollout begun"),
    MetricName("canary.rollback", "event", "canary rolled back and quarantined"),
    MetricName("canary.promote", "event", "canary promoted to latest"),
    MetricName("lifecycle.drain_begin", "event", "graceful drain begun"),
    MetricName("lifecycle.drain_end", "event", "graceful drain finished"),
    MetricName("coord.elastic_resume", "event", "resume under a different process count"),
    MetricName("coord.barrier_timeout", "event", "deadline-guarded coordination step timed out"),
    MetricName("coord.recovered", "event", "straggling peer resumed heartbeating"),
    MetricName("coord.dead_host", "event", "peer declared dead by the heartbeat registry"),
    MetricName("coord.straggler", "event", "peer flagged straggling"),
    MetricName("coord.checkpoint", "event", "coordinated checkpoint save completed"),
    MetricName("coord.preempted", "event", "SIGTERM preemption observed"),
    MetricName("incident.bundle", "event", "incident bundle dumped"),
    MetricName("quality.alert", "event", "sustained-miscalibration alert raised"),
    MetricName("quality.recovered", "event", "miscalibration alert cleared by a clean window"),
    MetricName("drift.alert", "event", "sustained-input-drift alert raised"),
    MetricName("drift.recovered", "event", "input-drift alert cleared by a clean window"),
    MetricName("router.failover", "event", "request re-dispatched onto the next ring replica"),
    MetricName("router.hedge", "event", "hedged duplicate dispatch launched against a straggler"),
    MetricName("fleet.member_joined", "event", "replica registered into fleet membership"),
    MetricName("fleet.member_left", "event", "replica deregistered from fleet membership"),
    MetricName("fleet.replica_straggler", "event", "replica flagged straggling (stale fleet heartbeat)"),
    MetricName("fleet.replica_dead", "event", "replica declared dead by the fleet heartbeat ledger"),
    MetricName("fleet.replica_recovered", "event", "flagged replica resumed heartbeating"),
    MetricName("fleet.canary_promote", "event", "fleet-wide canary promoted on every replica"),
    MetricName("fleet.canary_rollback", "event", "fleet-wide canary rolled back on every replica"),
    MetricName("metric.*", "event", "watchlisted serve-metric increment relayed to the flight recorder", label="key"),
)

_EXACT = {spec.key: spec for spec in CATALOG if "*" not in spec.key}
_PATTERNS = tuple(spec for spec in CATALOG if "*" in spec.key)

#: default cumulative-bucket upper bounds by key shape (histograms pick
#: their ladder at CREATION so the bucket counters can be true monotonic
#: counters — see LatencyHistogram and obs/expo.py)
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0, 1024.0)
RATIO_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)


def buckets_for(key: str) -> Tuple[float, ...]:
    """Bucket upper bounds for a histogram key: the catalog override when
    registered, else a ladder picked by the key's shape."""
    spec = lookup(key)
    if spec is not None and spec.buckets:
        return spec.buckets
    if key.endswith("_s"):
        return LATENCY_BUCKETS
    if "occupancy" in key or "ratio" in key:
        return RATIO_BUCKETS
    return SIZE_BUCKETS


def lookup(key: str) -> Optional[MetricName]:
    """Catalog entry for a CONCRETE emitted key (exact match first, then
    ``*`` patterns), or None when unregistered."""
    spec = _EXACT.get(key)
    if spec is not None:
        return spec
    for spec in _PATTERNS:
        if fnmatch.fnmatchcase(key, spec.key):
            return spec
    return None


def is_registered(key_or_pattern: str) -> bool:
    """True when an emission is covered by the catalog.  A concrete key
    may match a pattern; an emitted PATTERN (an f-string whose dynamic
    parts the linter wildcards) must equal a registered pattern verbatim
    — fuzzy pattern-to-pattern matching would let near-miss renames
    slip through."""
    if "*" in key_or_pattern:
        return any(spec.key == key_or_pattern for spec in _PATTERNS)
    return lookup(key_or_pattern) is not None


def grammar_ok(key_or_pattern: str) -> bool:
    """The naming grammar: dot-separated lowercase components, ``*``
    allowed only in patterns (runtime-data parts)."""
    grammar = PATTERN_GRAMMAR if "*" in key_or_pattern else KEY_GRAMMAR
    return bool(grammar.match(key_or_pattern))
