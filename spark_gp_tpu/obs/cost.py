"""XLA cost attribution: measured flops/bytes per compiled entry point.

The roofline work (docs/ROOFLINE.md) estimates MFU from analytic flop
counts; "Memory Safe Computations with XLA Compiler" argues the unit of
performance truth is the compiled executable.  This module closes the
gap: for each jitted fit/predict entry point, the XLA compiler's own
``compiled.cost_analysis()`` (flops + bytes accessed of the optimized
module) is extracted ONCE per (entry point, operand signature) and then
accumulated per execution into the runtime telemetry —

* ``xla.flops.<entry>`` / ``xla.bytes.<entry>`` counters, exposed as
  ``gp_xla_flops_total{entry=...}`` / ``gp_xla_bytes_total{entry=...}``
  (``obs/expo.py`` pattern-label collapse), where ``<entry>`` is the
  active trace root (``fit.GaussianProcessRegression``,
  ``serve.batch``) or the call site's fallback label
  (``predict.ppa``);
* a per-fit table on the active :class:`~spark_gp_tpu.obs.runtime.
  FitCapture`, from which the run journal stamps measured per-phase MFU
  against :func:`spark_gp_tpu.ops.precision.chip_peaks` — measured, not
  estimated.

Cost: one extra trace+lowering per NEW signature (the backend compile is
cache-served for an already-executed program); the process-wide
signature cache makes every later call a dict lookup.  Off by default —
``GP_XLA_COST=1`` (or :func:`set_cost_metering`) opts in; the bench and
the tier-1 acceptance tests enable it explicitly.  Measurement never
raises into the measured path: a failing lowering counts
``xla.cost_failures`` and the entry point proceeds untouched.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

_forced: Optional[bool] = None

_CACHE: Dict[Tuple, Optional[Dict[str, float]]] = {}
_LOCK = threading.Lock()
#: signature-cache bound: far above any steady-state entry-point count
#: (a serve process has a handful of bucket shapes; a fit a handful of
#: programs), but a hard ceiling so a pathological shape churn cannot
#: grow the dict for the process lifetime.  FIFO eviction — an evicted
#: signature just re-measures.
_CACHE_MAX = 512


def cost_metering_enabled() -> bool:
    """The gate, read at call time: ``set_cost_metering`` wins, else
    ``GP_XLA_COST`` (default OFF — measurement pays one lowering per new
    signature); always off while tracing is disabled so the bench's
    tracer-off baseline stays a true zero."""
    from spark_gp_tpu.obs import trace as obs_trace

    if not obs_trace.tracing_enabled():
        return False
    if _forced is not None:
        return _forced
    return os.environ.get("GP_XLA_COST", "").strip().lower() in (
        "1", "on", "true",
    )


def set_cost_metering(enabled: Optional[bool]) -> None:
    """Force cost metering on/off for this process (None = back to env)."""
    global _forced
    _forced = enabled


def clear_cache() -> None:
    """Drop the signature cache (tests; ``jax.clear_caches`` parity)."""
    with _LOCK:
        _CACHE.clear()


def _sig_of(value: Any):
    """Hashable signature of one operand: arrays by (shape, dtype) —
    cost depends on avals, never on values — containers structurally,
    statics (kernels, meshes) by identity-stable hash."""
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(shape), str(dtype))
    if isinstance(value, (tuple, list)):
        return ("t", tuple(_sig_of(v) for v in value))
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    try:
        return ("h", type(value).__name__, hash(value))
    except TypeError:
        return ("u", type(value).__name__)


def _extract(compiled) -> Optional[Dict[str, float]]:
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    flops = bytes_accessed = 0.0
    if analysis:
        flops = float(analysis.get("flops", 0.0) or 0.0)
        bytes_accessed = float(analysis.get("bytes accessed", 0.0) or 0.0)
    peak = _extract_memory(compiled)
    if flops <= 0.0 and bytes_accessed <= 0.0 and peak is None:
        return None
    cost = {"flops": flops, "bytes": bytes_accessed}
    if peak is not None:
        cost["peak_bytes"] = peak
    return cost


def _extract_memory(compiled) -> Optional[float]:
    """The compiler's own predicted peak bytes of one execution:
    ``compiled.memory_analysis()`` — arguments + outputs + temps +
    generated code, aliased buffers counted once.  The number the memory
    planner (``resilience/memplan.py``) treats as ground truth for an
    already-compiled entry point.  None when the backend offers no
    analysis (then only the planner's analytic model covers the entry)."""
    try:
        stats = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — optional API, absence = no signal
        return None
    if stats is None:
        return None
    try:
        peak = (
            float(stats.argument_size_in_bytes)
            + float(stats.output_size_in_bytes)
            + float(stats.temp_size_in_bytes)
            + float(stats.generated_code_size_in_bytes)
            - float(stats.alias_size_in_bytes)
        )
    except AttributeError:
        return None
    return peak if peak > 0.0 else None


def measure(jitted, args: tuple, kwargs: Optional[dict] = None
            ) -> Optional[Dict[str, float]]:
    """``{"flops", "bytes"}`` of one execution of ``jitted(*args,
    **kwargs)``, from the compiler's cost model; cached per signature;
    None when the backend offers no analysis (then cached as None so the
    lowering is never retried per call)."""
    kwargs = kwargs or {}
    key = (id(jitted), _sig_of(args), _sig_of(tuple(sorted(kwargs.items()))))
    with _LOCK:
        if key in _CACHE:
            return _CACHE[key]
    try:
        cost = _extract(jitted.lower(*args, **kwargs).compile())
    except Exception:  # noqa: BLE001 — metering must never fail the
        # measured entry point (chaos-staged compile failures land here too)
        from spark_gp_tpu.obs.runtime import telemetry

        telemetry.inc("xla.cost_failures")
        cost = None
    with _LOCK:
        while len(_CACHE) >= _CACHE_MAX:
            _CACHE.pop(next(iter(_CACHE)))
        _CACHE[key] = cost
    return cost


def observe_call(
    entry_fallback: str, jitted, args: tuple,
    kwargs: Optional[dict] = None, weight: float = 1.0,
) -> Optional[Dict[str, float]]:
    """Attribute ``weight`` executions of a jitted entry point: measure
    (cached), then accumulate into the telemetry counters under the
    active trace root (the compile-counter attribution convention) and
    into the active fit capture's per-entry table.  A no-op returning
    None when metering is off."""
    if not cost_metering_enabled():
        return None
    cost = measure(jitted, args, kwargs)
    if cost is None:
        return None
    from spark_gp_tpu.obs import runtime as obs_runtime
    from spark_gp_tpu.obs import trace as obs_trace

    entry = obs_trace.current_root_name() or entry_fallback
    obs_runtime.telemetry.inc(
        f"xla.flops.{entry}", entry=entry, n=cost["flops"] * weight
    )
    obs_runtime.telemetry.inc(
        f"xla.bytes.{entry}", entry=entry, n=cost["bytes"] * weight
    )
    obs_runtime.note_xla_cost(entry, cost, weight)
    if cost.get("peak_bytes"):
        # the memory planner's compiled-path prediction source
        # (resilience/memplan.py): the signature-cached lower+compile
        # above IS the extraction, this is just the relay
        from spark_gp_tpu.resilience import memplan

        memplan.note_compiled_peak(entry, cost["peak_bytes"])
    return cost


def observed_call(entry_fallback: str, jitted, *args, **kwargs):
    """Meter AND invoke in one step: ``observed_call(entry, fn, *a,
    **kw)`` returns ``fn(*a, **kw)`` and attributes one execution (when
    metering is on).  THE call-site form — the measured args and the
    executed args are one tuple by construction, so they cannot drift.
    The call runs FIRST: a raising dispatch (an injected OOM, a compile
    failure the degradation ladder will classify) is never counted as an
    executed program, and the measurement's lowering happens against an
    already-warm compile."""
    out = jitted(*args, **kwargs)
    observe_call(entry_fallback, jitted, args, kwargs)
    return out


def measured_flops(entry: str) -> float:
    """Total measured flops attributed to ``entry`` so far (the
    ``gp_xla_flops_total{entry=}`` series, host-side read)."""
    from spark_gp_tpu.obs.runtime import telemetry

    return telemetry.snapshot()["counters"].get(f"xla.flops.{entry}", 0.0)


def mfu_against_peak(flops_total: float, seconds: float
                     ) -> Optional[Dict[str, float]]:
    """Measured MFU of ``flops_total`` executed flops over ``seconds``
    against the running chip's nominal bf16 peak
    (:func:`~spark_gp_tpu.ops.precision.chip_peaks`); None when the
    generation is unknown or the denominator is degenerate."""
    if not flops_total or not seconds or seconds <= 0.0:
        return None
    try:
        import jax

        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no backend, no MFU
        return None
    from spark_gp_tpu.ops.precision import chip_peaks

    peak_tflops, _ = chip_peaks(kind)
    if not peak_tflops:
        return None
    return {
        "device_kind": kind,
        "peak_tflops": peak_tflops,
        "achieved_tflops": flops_total / seconds / 1e12,
        "mfu": flops_total / seconds / (peak_tflops * 1e12),
    }
